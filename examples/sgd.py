"""Mini-batch SGD logistic regression — the BASELINE.md stretch workload:
gradients computed as a map/reduce over the dataset, combined with a device
psum over the mesh.

Two layers demonstrate the same decomposition:
1. DSL map/reduce: per-partition gradient partials via ``partition_map``,
   summed with an associative fold (the reference's only route).
2. ``dampr_tpu.parallel.sgd``: the same math as one jitted shard_map program —
   batch sharded over the mesh, gradients psum'd over ICI.

Usage: python examples/sgd.py [n_samples] [n_features] [steps]
"""

import _pathfix  # noqa: F401  (repo root onto sys.path)

import os
import sys

import numpy as np

from dampr_tpu import Dampr, setup_logging
from dampr_tpu.parallel import sgd
from dampr_tpu.parallel.mesh import data_mesh


def _honor_cpu_request():
    """The environment's TPU plugin can programmatically override
    jax_platforms at interpreter start, clobbering JAX_PLATFORMS=cpu — which
    would point the mesh route at a (possibly unreachable) remote tunnel.
    Re-assert a CPU request the way the plugin can't override."""
    if "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass  # backend already initialized: keep whatever it is


def dsl_gradient(pipe, w, b):
    """One gradient evaluation as a Dampr map/reduce: partials per partition,
    associative vector-sum fold."""
    def partial_grads(rows):
        gw = np.zeros_like(w)
        gb = 0.0
        n = 0
        for x, y in rows:
            logit = float(x @ w + b)
            s = 1.0 / (1.0 + np.exp(-logit))
            gw += (s - y) * x
            gb += s - y
            n += 1
        yield 1, (gw, gb, n)

    def add3(a, c):
        return (a[0] + c[0], a[1] + c[1], a[2] + c[2])

    (_, (gw, gb, n)), = (pipe.partition_map(partial_grads)
                         .fold_by(lambda _x: 1, add3, lambda x: x).read())
    return gw / n, gb / n


def build_gradient_pipeline(X, y, w, b):
    """One gradient evaluation as a handle (nothing runs until read)."""
    def partial_grads(rows):
        gw = np.zeros_like(w)
        gb = 0.0
        n = 0
        for x, yv in rows:
            logit = float(x @ w + b)
            s = 1.0 / (1.0 + np.exp(-logit))
            gw += (s - yv) * x
            gb += s - yv
            n += 1
        yield 1, (gw, gb, n)

    def add3(a, c):
        return (a[0] + c[0], a[1] + c[1], a[2] + c[2])

    return (Dampr.memory(list(zip(X, y)), partitions=8).cached()
            .partition_map(partial_grads)
            .fold_by(lambda _x: 1, add3, lambda x: x))


def lint_pipelines():
    """dampr-tpu-lint discovery hook (docs/analysis.md)."""
    rng = np.random.RandomState(0)
    X = rng.randn(16, 4).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    w = np.zeros(4, dtype=np.float32)
    return [("sgd_gradient", build_gradient_pipeline(X, y, w, 0.0))]


def main(n=4096, f=64, steps=10):
    rng = np.random.RandomState(0)
    X = rng.randn(n, f).astype(np.float32)
    true_w = rng.randn(f).astype(np.float32)
    y = (X @ true_w > 0).astype(np.float32)

    # --- DSL route: map/reduce gradients --------------------------------
    pipe = Dampr.memory(list(zip(X, y)), partitions=8).cached()
    w = np.zeros(f, dtype=np.float32)
    b = 0.0
    for step in range(steps):
        gw, gb = dsl_gradient(pipe, w, b)
        w -= 1.0 * gw
        b -= 1.0 * gb
    acc = float((((X @ w + b) > 0) == (y > 0.5)).mean())
    print("DSL map/reduce SGD:   {} steps, accuracy {:.3f}".format(steps, acc))

    # --- Mesh route: one shard_map program, psum'd grads ----------------
    mesh = data_mesh()
    params, loss = sgd.train(mesh, X, y, n_steps=steps * 4, lr=1.0)
    pred = (X @ params["w"] + params["b"]) > 0
    acc2 = float((pred == (y > 0.5)).mean())
    print("mesh psum SGD:        {} devices, loss {:.4f}, accuracy {:.3f}"
          .format(len(mesh.devices.flat), loss, acc2))


if __name__ == "__main__":
    setup_logging()
    _honor_cpu_request()
    args = [int(a) for a in sys.argv[1:4]]
    main(*args)
