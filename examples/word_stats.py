"""Multi-output word statistics over a shared pipeline root (reference
examples/word-stats.py): four graphs sharing one tokenize+count prefix are
unioned into a single run, so the shared stages compute once.

Usage: python examples/word_stats.py <file-or-dir>
"""

import _pathfix  # noqa: F401  (repo root onto sys.path)

import sys

from dampr_tpu import Dampr, setup_logging


def build(fname):
    """The four result handles over one shared tokenize+count prefix."""
    # Shared root: tokenized words, counted once.
    words = Dampr.text(fname, 1024 ** 2).flat_map(lambda line: line.split())

    top_words = (words.count(lambda x: x)
                 .sort_by(lambda word_count: -word_count[1]))

    total_count = top_words.fold_by(
        key=lambda word: 1,
        value=lambda x: x[1],
        binop=lambda x, y: x + y)

    word_lengths = (top_words
                    .fold_by(lambda tc: len(tc[0]),
                             value=lambda tc: tc[1],
                             binop=lambda x, y: x + y)
                    .sort_by(lambda cl: cl[0]))

    avg_word_lengths = (word_lengths
                        .map(lambda wl: wl[0] * wl[1])
                        .a_group_by(lambda x: 1)
                        .sum()
                        .join(total_count)
                        .reduce(lambda awl, tc:
                                next(awl)[1] / float(next(tc)[1])))

    return total_count, top_words, word_lengths, avg_word_lengths


def lint_pipelines():
    """dampr-tpu-lint discovery hook (docs/analysis.md)."""
    tc, tw, wl, awl = build(__file__)
    return [("total_count", tc), ("top_words", tw),
            ("word_lengths", wl), ("avg_word_lengths", awl)]


def main(fname):
    total_count, top_words, word_lengths, avg_word_lengths = build(fname)

    tc, tw, wl, awl = Dampr.run(total_count, top_words, word_lengths,
                                avg_word_lengths, name="word-stats")

    print("\nWord Stats\n" + "*" * 10)
    print("Total Words Found:", tc.read(1)[0][1])
    print("\nTop 10 words")
    for word, count in tw.read(10):
        print(word, count)
    print("\nCharacter histogram")
    for cl, length in wl.read(20):
        print(cl, length)
    print("\nAverage Word Length:", awl.read(1)[0][1])


if __name__ == "__main__":
    setup_logging()
    main(sys.argv[1])
