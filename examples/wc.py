"""Word count — the canonical end-to-end slice (reference examples/wc.py).

Tokenization streams on host threads; the keyed count compiles to vectorized
hash + device segment-sum with map-side combining before the shuffle.

Usage: python examples/wc.py <file-or-dir> [chunk_size_mb]
"""

import _pathfix  # noqa: F401  (repo root onto sys.path)

import sys

from dampr_tpu import Dampr, setup_logging


def build(path, chunk_mb=16):
    """The word-count pipeline handle (nothing executes until run())."""
    return (Dampr.text(path, chunk_size=chunk_mb * 1024 ** 2)
            .flat_map(lambda line: line.split())
            .fold_by(lambda w: w, binop=lambda x, y: x + y,
                     value=lambda w: 1))


def lint_pipelines():
    """dampr-tpu-lint discovery hook (docs/analysis.md)."""
    return [("wc", build(__file__))]


def main(path, chunk_mb=16):
    wc = build(path, chunk_mb)

    results = wc.run("word-count")
    for word, count in sorted(results, key=lambda wc: wc[1], reverse=True)[:20]:
        print("{}: {}".format(word, count))
    results.delete()


if __name__ == "__main__":
    setup_logging()
    if len(sys.argv) < 2:
        print(__doc__)
        sys.exit(1)
    main(sys.argv[1], int(sys.argv[2]) if len(sys.argv) > 2 else 16)
