"""Make `python examples/<script>.py` work without installing the package:
Python puts the script's own directory (examples/) on sys.path, so the repo
root — where the dampr_tpu package lives — is inserted here once."""

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)
