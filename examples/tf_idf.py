"""TF-IDF — the reference's benchmark workload (benchmarks/tf-idf-dampr.py)
as an example, in both styles:

- *parity form*: pure-DSL lambdas, per-record Python, identical to the
  reference source shape;
- *TPU form*: the vectorized DocFreq block mapper (native tokenize+count),
  which the benchmark uses — same results, orders of magnitude faster.

Usage: python examples/tf_idf.py <file-or-dir> [--parity]
"""

import _pathfix  # noqa: F401  (repo root onto sys.path)

import math
import multiprocessing
import operator
import os
import re
import sys

from dampr_tpu import Dampr, setup_logging
from dampr_tpu.ops.text import DocFreq

RX = re.compile(r"[^\w]+")


def doc_freq_parity(docs):
    """Reference shape (tf-idf-dampr.py:13-15), per-record lambdas."""
    return (docs
            .flat_map(lambda x: set(t for t in RX.split(x.lower()) if t))
            .count())


def doc_freq_vectorized(docs):
    """Native block path: one fused tokenize+dedup+count pass per chunk."""
    return (docs.custom_mapper(DocFreq(mode="word", lower=True))
            .fold_by(lambda kv: kv[0], operator.add, lambda kv: kv[1]))


def build(fname, parity=False, out="/tmp/dampr_tpu_idfs"):
    """The full TF-IDF pipeline handle, sink attached, nothing run."""
    chunk_size = os.path.getsize(fname) // multiprocessing.cpu_count() + 1 \
        if os.path.isfile(fname) else 16 * 1024 ** 2
    docs = Dampr.text(fname, chunk_size)

    df = doc_freq_parity(docs) if parity else doc_freq_vectorized(docs)

    idf = df.cross_right(
        docs.len(),
        lambda d, total: (d[0], d[1], math.log(1 + float(total) / d[1])),
        memory=True)
    return idf.sink_tsv(out)


def lint_pipelines():
    """dampr-tpu-lint discovery hook (docs/analysis.md)."""
    return [("tfidf_vectorized", build(__file__)),
            ("tfidf_parity", build(__file__, parity=True))]


def main(fname, parity=False):
    out = "/tmp/dampr_tpu_idfs"
    build(fname, parity, out).run(name="tf-idf")
    print("wrote idf TSV parts under", out)
    with open(os.path.join(out, sorted(os.listdir(out))[0])) as f:
        for line in list(f)[:5]:
            print(" ", line.rstrip())


if __name__ == "__main__":
    setup_logging()
    if len(sys.argv) < 2:
        print(__doc__)
        sys.exit(1)
    main(sys.argv[1], "--parity" in sys.argv)
