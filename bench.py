"""Driver hook: the TF-IDF headline benchmark.

Thin wrapper over :mod:`dampr_tpu.bench_tfidf` (also installed as the
``dampr-tpu-bench`` console script); prints ONE JSON line.
"""

from dampr_tpu.bench_tfidf import main

if __name__ == "__main__":
    main()
