#!/usr/bin/env python
"""Validate a ``dampr-tpu-lint --json`` report against
docs/lint_schema.json.

Dependency-free (CI and containers without jsonschema): reuses the
JSON-Schema subset checker from tools/validate_trace.py — type,
required, properties, items, enum, minItems — plus lint-specific
semantic rules the schema prose defers here:

- every diagnostic ``code`` matches the stable ``DTA\\d{3}`` taxonomy
  (docs/analysis.md);
- ``counts`` agrees with the diagnostics list per severity;
- ``exit_code`` is consistent: 2 requires a failed/empty target, 1
  requires an error (or, under ``strict``, a warning), 0 requires
  neither.

Usage::

    python tools/validate_lint.py REPORT.json
        [--schema docs/lint_schema.json]
"""

import argparse
import importlib.util
import json
import os
import re
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))

_CODE_RX = re.compile(r"^DTA\d{3}$")


def _load_trace_checker():
    spec = importlib.util.spec_from_file_location(
        "validate_trace", os.path.join(_HERE, "validate_trace.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def validate(report, schema):
    """Return a list of error strings (empty = valid)."""
    vt = _load_trace_checker()
    errors = []
    vt._check(report, schema, "$", errors)

    diags = report.get("diagnostics")
    if isinstance(diags, list):
        got = {"error": 0, "warn": 0, "info": 0}
        for i, d in enumerate(diags):
            if not isinstance(d, dict):
                continue
            code = d.get("code", "")
            if not _CODE_RX.match(str(code)):
                errors.append(
                    "diagnostics[{}]: code {!r} outside the DTA "
                    "taxonomy".format(i, code))
            sev = d.get("severity")
            if sev in got:
                got[sev] += 1
        counts = report.get("counts")
        if isinstance(counts, dict):
            for sev, n in got.items():
                if counts.get(sev) != n:
                    errors.append(
                        "counts.{}: {} != {} diagnostics of that "
                        "severity".format(sev, counts.get(sev), n))

    code = report.get("exit_code")
    targets = report.get("targets") or []
    failed = any(not isinstance(t, dict) or t.get("error") is not None
                 or not t.get("pipelines") for t in targets)
    counts = report.get("counts") or {}
    strict = bool(report.get("strict"))
    if isinstance(code, int) and isinstance(counts, dict):
        want = (2 if failed
                else 1 if (counts.get("error") or
                           (strict and counts.get("warn")))
                else 0)
        if code != want:
            errors.append("exit_code: {} inconsistent with targets/"
                          "counts (want {})".format(code, want))
    return errors


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("report")
    ap.add_argument("--schema",
                    default=os.path.join(_HERE, os.pardir, "docs",
                                         "lint_schema.json"))
    args = ap.parse_args(argv)
    with open(args.report) as f:
        report = json.load(f)
    with open(args.schema) as f:
        schema = json.load(f)
    errors = validate(report, schema)
    if errors:
        for e in errors:
            print("INVALID:", e, file=sys.stderr)
        return 1
    print("lint report OK: {} target(s), {} diagnostic(s)".format(
        len(report.get("targets", [])),
        len(report.get("diagnostics", []))))
    return 0


if __name__ == "__main__":
    sys.exit(main())
