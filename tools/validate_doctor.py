#!/usr/bin/env python
"""Validate a ``dampr-tpu-doctor --json`` report against
docs/doctor_schema.json.

Dependency-free (CI and containers without jsonschema): reuses the
JSON-Schema subset checker from tools/validate_trace.py — type,
required, properties, items, enum, minItems — plus doctor-specific
semantic rules the schema prose defers here:

- findings are ranked 1..N with no gaps and sorted most-severe-impact
  first (``impact_seconds`` non-increasing);
- every suggestion's ``setting`` names an attribute that actually
  exists in :mod:`dampr_tpu.settings` (a suggestion for a knob that's
  gone is worse than no suggestion) — skipped with ``--no-settings``
  for environments without the package importable;
- a ``--diff`` report carries its ``diff`` section.

Usage::

    python tools/validate_doctor.py REPORT.json
        [--schema docs/doctor_schema.json] [--no-settings]
"""

import argparse
import importlib.util
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))


def _load_trace_checker():
    spec = importlib.util.spec_from_file_location(
        "validate_trace", os.path.join(_HERE, "validate_trace.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def validate(report, schema, check_settings=True):
    """Return a list of error strings (empty = valid)."""
    vt = _load_trace_checker()
    errors = []
    vt._check(report, schema, "$", errors)

    findings = report.get("findings")
    if isinstance(findings, list):
        prev_impact = None
        for i, f in enumerate(findings):
            if not isinstance(f, dict):
                continue
            if f.get("rank") != i + 1:
                errors.append(
                    "findings[{}]: rank {} != position {}".format(
                        i, f.get("rank"), i + 1))
            imp = f.get("impact_seconds")
            if isinstance(imp, (int, float)):
                if prev_impact is not None and imp > prev_impact + 1e-9:
                    errors.append(
                        "findings[{}]: impact_seconds not "
                        "non-increasing".format(i))
                prev_impact = imp

    if check_settings and isinstance(findings, list):
        try:
            sys.path.insert(0, os.path.dirname(_HERE))
            from dampr_tpu import settings as _settings
        except Exception as e:  # package not importable here
            errors.append(
                "cannot import dampr_tpu.settings to verify suggestion "
                "knobs ({}); pass --no-settings to skip".format(e))
        else:
            for i, f in enumerate(findings):
                for j, s in enumerate((f or {}).get("suggestions") or ()):
                    knob = (s or {}).get("setting")
                    if knob and not hasattr(_settings, knob):
                        errors.append(
                            "findings[{}].suggestions[{}]: setting {!r} "
                            "does not exist in dampr_tpu.settings".format(
                                i, j, knob))
    return errors


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="validate a dampr-tpu-doctor --json report")
    ap.add_argument("report")
    ap.add_argument("--schema", default=os.path.join(
        os.path.dirname(_HERE), "docs", "doctor_schema.json"))
    ap.add_argument("--no-settings", action="store_true",
                    help="skip verifying suggestion knobs against "
                         "dampr_tpu.settings")
    args = ap.parse_args(argv)

    with open(args.report) as f:
        report = json.load(f)
    with open(args.schema) as f:
        schema = json.load(f)
    errors = validate(report, schema,
                      check_settings=not args.no_settings)
    if errors:
        for e in errors:
            print("INVALID: {}".format(e), file=sys.stderr)
        return 1
    print("OK: {} stage verdict(s), {} finding(s), bottleneck {}".format(
        len(report.get("stages") or ()),
        len(report.get("findings") or ()),
        report.get("bottleneck")))
    return 0


if __name__ == "__main__":
    sys.exit(main())
