#!/usr/bin/env python
"""CI perf-regression gate: compare a fresh bench JSON against baselines.

Dependency-free (runs in any CI container).  Reads the one-line JSON the
benches emit (``bench.py`` / ``dampr-tpu-bench`` / ``benchmarks/
sort_bench.py``) — or a driver-wrapped record with the payload under a
``parsed`` key — extracts the headline ``value`` (MB/s, higher is
better), and checks it against the best usable baseline::

    python tools/check_bench.py fresh.json \\
        --baseline BASELINE.json BENCH_r05.json BENCH_r04.json \\
        --tolerance 0.25 [--strict] [--metric-key value]

- **Baselines** may be a mix: historical bench records (``BENCH_r*.json``,
  wrapped or raw) contribute their ``value``; config-only descriptors
  (the repo's ``BASELINE.json`` carries targets, not measurements) are
  skipped with a note.  The gate compares against the BEST usable
  baseline (past best-of is the honest bar; a lucky run must not ratchet
  the gate above what the code sustains, so pass several historical
  files and the max wins).
- **Tolerance** is the allowed fractional drop below that bar (default
  0.25 — CI boxes are noisy; tighten as variance data accumulates).
- **Exit code**: 0 on pass or when no usable baseline exists (first run,
  config-only baselines); on a regression, 1 with ``--strict``, else 0
  with a loud ``WARN`` line (the warn-only rollout mode).  Malformed
  input is always an error (2) — a gate that can't read its input must
  not report success.

Secondary numeric keys shared by fresh and baseline (io_wait_fraction,
spill MB/s, ...) are reported informationally, never gated.

Autotune session reports (``TUNE_r*.json``, docs/doctor_schema.json's
``autotune`` section) are accepted anywhere a baseline is: the winner
trial's measured throughput is the comparable number.  Under ``--trend``
a fresh record carrying the cost model's own prediction
(``model_predicted_value``, emitted by the benches from the plan
report's ``cost`` section) is also checked against it: a measured value
more than the tolerance below the prediction prints a warn-only
``MODEL WARN`` line (regression vs the learned fit, or a stale corpus).

``--trend`` additionally checks the whole baseline TRAJECTORY (pass the
historical ``BENCH_r*.json`` files oldest-first): a best-of gate only
catches a cliff, while a slow leak — each round a few percent under the
last — stays inside tolerance forever.  The trend check flags a monotone
regression when the newest >= 3 comparable points (fresh included when
its ``metric`` matches) each measure below the previous round.  Always
warn-only: it reports, the best-of gate decides the exit code.
"""

import argparse
import json
import sys


def load_record(path):
    """A bench JSON file -> its payload dict (driver wrappers unwrapped,
    non-dict payloads rejected).  Autotune session reports
    (``TUNE_r*.json``, the doctor schema's ``autotune`` section) are
    accepted as baselines: the winner trial's measured throughput is the
    comparable number."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if not isinstance(doc, dict):
        raise ValueError("{}: bench record is not a JSON object".format(
            path))
    v = doc.get("value")
    if (isinstance(doc.get("autotune"), dict)
            and (not isinstance(v, (int, float)) or isinstance(v, bool))):
        # A session report with no headline value of its own (doctor
        # --autotune output): the winner trial's measured throughput is
        # the comparable number.  A record that already carries a
        # numeric value — a fresh bench run with settings.autotune on,
        # or a TUNE report stamped with one — is returned INTACT so
        # none of its secondary keys (model_predicted_value, io shape)
        # are lost.
        winner = (doc["autotune"].get("winner") or {})
        rec = {"metric": doc.get("metric"), "autotune": doc["autotune"]}
        w = winner.get("mbps")
        if isinstance(w, (int, float)) and not isinstance(w, bool):
            rec["value"] = float(w)
        return rec
    return doc


def headline(rec, key="value"):
    """The gated number, or None when the record has no measurement
    (config-only baselines)."""
    v = rec.get(key)
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    return None


def record_direction(rec, default="higher"):
    """A record's gating direction: ``"higher"`` (throughput-like,
    the historical default) or ``"lower"`` (latency-like: p99, wall
    seconds).  Benches stamp ``direction`` into the record so their
    baselines gate the right way without per-CI-job configuration."""
    d = str(rec.get("direction") or default).lower()
    return "lower" if d == "lower" else "higher"


def compare(fresh, baselines, tolerance, key="value", direction=None):
    """Compare one fresh record against (path, record) baselines.

    ``direction`` "higher" (default) gates a drop below the best (=max)
    baseline; "lower" gates a rise above the best (=min) baseline —
    latency metrics regress UP.  None reads the fresh record's own
    ``direction`` field.  Returns a report dict: ``ok`` (bool),
    ``fresh``, ``best`` (None when no usable baseline), ``best_path``,
    ``drop`` (fractional regression in the metric's bad direction,
    negative = improvement), ``skipped`` (unusable baseline paths),
    ``notes``.
    """
    fresh_v = headline(fresh, key)
    if fresh_v is None:
        raise ValueError(
            "fresh bench record has no numeric {!r} field".format(key))
    if direction is None:
        direction = record_direction(fresh)
    metric = fresh.get("metric")
    best = None
    best_path = None
    skipped = []
    for path, rec in baselines:
        v = headline(rec, key)
        if v is None:
            skipped.append(path)
            continue
        bmetric = rec.get("metric")
        if metric and bmetric and bmetric != metric:
            skipped.append(path)
            continue
        if best is None or ((v < best) if direction == "lower"
                            else (v > best)):
            best, best_path = v, path
    report = {
        "metric": metric, "direction": direction, "fresh": fresh_v,
        "best": best, "best_path": best_path, "skipped": skipped,
        "tolerance": tolerance, "drop": None, "ok": True, "notes": [],
    }
    if best is None:
        report["notes"].append(
            "no usable baseline (no numeric {!r} with a matching metric): "
            "gate passes vacuously".format(key))
        return report
    if best > 0:
        if direction == "lower":
            drop = (fresh_v - best) / best   # fractional rise = regression
        else:
            drop = (best - fresh_v) / best   # fractional drop = regression
    else:
        drop = 0.0
    report["drop"] = drop
    report["ok"] = drop <= tolerance
    return report


def trend(fresh, baselines, key="value", min_rounds=3,
          include_fresh=True, direction=None):
    """Trajectory check over the baselines IN THE ORDER GIVEN (pass them
    oldest-first; the caller's ordering is the round ordering).

    Only records carrying a numeric ``key`` and a ``metric`` compatible
    with fresh's participate; fresh itself joins the sequence when its
    metric matches AND ``include_fresh`` is set — pass False when the
    trajectory comes from a different measurement scale than the fresh
    run (full-size rounds vs a tiny CI smoke), where appending fresh
    would manufacture a fake decline.  Returns a report dict:
    ``points`` (the ordered (label, value) trajectory), ``declining``
    (length of the strictly-declining suffix), ``regressing`` (True
    when that suffix spans >= ``min_rounds`` points), ``note``.
    """
    fresh_v = headline(fresh, key)
    if direction is None:
        direction = record_direction(fresh)
    metric = fresh.get("metric")
    points = []
    for path, rec in baselines:
        v = headline(rec, key)
        if v is None:
            continue
        bmetric = rec.get("metric")
        if metric and bmetric and bmetric != metric:
            continue
        points.append((path, v))
    if fresh_v is not None and include_fresh:
        points.append(("fresh", fresh_v))
    report = {"points": points, "declining": 0, "regressing": False,
              "note": None}
    if len(points) < min_rounds:
        report["note"] = ("{} comparable point(s): a trend needs at "
                          "least {}".format(len(points), min_rounds))
        return report
    decl = 1
    for i in range(len(points) - 1, 0, -1):
        worse = (points[i][1] > points[i - 1][1] if direction == "lower"
                 else points[i][1] < points[i - 1][1])
        if worse:
            decl += 1
        else:
            break
    report["declining"] = decl
    report["regressing"] = decl >= min_rounds
    return report


def _fmt_extra(fresh, baseline_rec):
    """Informational table of shared secondary numeric keys."""
    if baseline_rec is None:
        return []
    lines = []
    skip = {"value"}
    for k in sorted(set(fresh) & set(baseline_rec) - skip):
        a, b = fresh[k], baseline_rec[k]
        if (isinstance(a, (int, float)) and isinstance(b, (int, float))
                and not isinstance(a, bool) and not isinstance(b, bool)):
            lines.append("  {:<32} fresh {:>12.4g}   baseline {:>12.4g}"
                         .format(k, float(a), float(b)))
    return lines


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="compare a bench JSON against baseline bench JSONs")
    ap.add_argument("fresh", help="the just-measured bench JSON")
    ap.add_argument("--baseline", nargs="+", default=[],
                    help="baseline bench JSONs (best usable one gates)")
    ap.add_argument("--tolerance", type=float, default=0.25,
                    help="allowed fractional drop below the best baseline "
                         "(default 0.25)")
    ap.add_argument("--metric-key", default="value",
                    help="record key holding the gated number")
    ap.add_argument("--direction", choices=("auto", "higher", "lower"),
                    default="auto",
                    help="gating direction: 'higher' = throughput-like "
                         "(drop below best baseline regresses, the "
                         "default), 'lower' = latency-like (rise above "
                         "best regresses — p99, wall seconds); 'auto' "
                         "reads the fresh record's own 'direction' field")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on regression (default: warn only)")
    ap.add_argument("--trend", action="store_true",
                    help="also check the baseline trajectory (in the "
                         "order given, oldest first) for a monotone "
                         "decline across >=3 rounds — warn-only")
    ap.add_argument("--trend-baseline", nargs="+", default=[],
                    help="records used ONLY for the --trend trajectory, "
                         "never for the best-of gate (the historical "
                         "full-size BENCH_r*.json files, which must not "
                         "gate a small smoke run); fresh is excluded "
                         "from this trajectory too — different scales "
                         "don't chain")
    args = ap.parse_args(argv)

    direction = None if args.direction == "auto" else args.direction
    try:
        fresh = load_record(args.fresh)
        baselines = [(p, load_record(p)) for p in args.baseline]
        trend_pool = [(p, load_record(p)) for p in args.trend_baseline]
        report = compare(fresh, baselines, args.tolerance,
                         key=args.metric_key, direction=direction)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print("check_bench: ERROR: {}".format(e), file=sys.stderr)
        return 2

    direction = report["direction"]
    metric = report["metric"] or args.metric_key
    print("check_bench: {} fresh={:.4g}{}".format(
        metric, report["fresh"],
        " (lower is better)" if direction == "lower" else ""))
    # Device-execution shape (informational, never gated): where the
    # plan placed stages and what the host moved to feed them.
    if fresh.get("device_stages") is not None:
        print("check_bench: device: {} lowered stage(s), "
              "device_fraction={}, h2d={}, d2h={}".format(
                  fresh.get("device_stages"),
                  fresh.get("device_fraction"),
                  fresh.get("h2d_bytes"), fresh.get("d2h_bytes")))
    for p in report["skipped"]:
        print("check_bench: note: {} has no comparable measurement, "
              "skipped".format(p))
    for n in report["notes"]:
        print("check_bench: note: {}".format(n))
    if args.trend:
        # Model-residual check (docs/tuning.md): when the bench embedded
        # the cost model's own throughput prediction, a measured number
        # far below it means either a regression the corpus has not
        # caught up with or a model gone stale — warn-only either way
        # (a byte-based prediction and a wall-based measurement share a
        # scale only approximately; tolerance absorbs that).
        pred = fresh.get("model_predicted_value")
        if (isinstance(pred, (int, float)) and not isinstance(pred, bool)
                and pred > 0):
            if direction == "lower":
                residual = (report["fresh"] - pred) / pred
            else:
                residual = (pred - report["fresh"]) / pred
            if residual > args.tolerance:
                print("check_bench: MODEL WARN: measured {:.4g} fell "
                      "{:.1%} below the cost model's own prediction "
                      "{:.4g} (tolerance {:.0%}) — regression vs the "
                      "learned fit, or a stale corpus".format(
                          report["fresh"], residual, float(pred),
                          args.tolerance))
            else:
                print("check_bench: model residual {:+.1%} vs predicted "
                      "{:.4g} (within {:.0%})".format(
                          -residual, float(pred), args.tolerance))
        # Before the vacuous-pass early return: the trend check must run
        # even when nothing gates best-of (the BASELINE-only CI config).
        # A dedicated --trend-baseline pool never chains fresh onto it
        # (different measurement scales would fake a decline).
        if trend_pool:
            t = trend(fresh, trend_pool, key=args.metric_key,
                      include_fresh=False, direction=direction)
        else:
            t = trend(fresh, baselines, key=args.metric_key,
                      direction=direction)
        if t["note"]:
            print("check_bench: trend: {}".format(t["note"]))
        elif t["regressing"]:
            tail = t["points"][-t["declining"]:]
            print("check_bench: TREND WARN: {} {} across {} "
                  "consecutive round(s): {}".format(
                      metric,
                      "rose" if direction == "lower" else "declined",
                      t["declining"],
                      " -> ".join("{}={:.4g}".format(p, v)
                                  for p, v in tail)))
        else:
            print("check_bench: trend: no monotone decline "
                  "({} points, newest declining run {})".format(
                      len(t["points"]), t["declining"]))
    if report["best"] is None:
        print("check_bench: PASS (nothing to gate against)")
        return 0
    print("check_bench: best baseline {:.4g} ({})  drop {:+.1%}  "
          "tolerance {:.0%}".format(report["best"], report["best_path"],
                                    report["drop"], report["tolerance"]))
    best_rec = dict(baselines).get(report["best_path"])
    for line in _fmt_extra(fresh, best_rec):
        print(line)
    if report["ok"]:
        print("check_bench: PASS")
        return 0
    msg = ("{} regressed {:.1%} {} the best baseline "
           "({:.4g} -> {:.4g}, tolerance {:.0%})".format(
               metric, report["drop"],
               "above" if direction == "lower" else "below",
               report["best"], report["fresh"], report["tolerance"]))
    if args.strict:
        print("check_bench: FAIL")
        print("check_bench: " + msg, file=sys.stderr)
        return 1
    print("check_bench: WARN (non-strict): " + msg)
    return 0


if __name__ == "__main__":
    sys.exit(main())
