#!/usr/bin/env python
"""Repo self-lint: the cross-cutting invariants that otherwise live
scattered across individual test files, consolidated into one
dependency-free command runnable locally and in CI (exit 0 = clean,
1 = violations, each printed with its location).

Checks:

1. **doctor playbook knobs exist** — every ``(setting, env, ...)``
   entry in :data:`dampr_tpu.obs.doctor._PLAYBOOK` names a real
   attribute of :mod:`dampr_tpu.settings` (a suggestion for a knob
   that's gone is worse than no suggestion).
2. **trace span kinds form a closed set** — every literal category
   passed to ``trace.span(...)`` / ``trace.instant(...)`` in the
   package source is declared in ``docs/trace_schema.json``'s
   ``x-span-kinds``, and every declared kind still appears in the
   source (no dead schema entries).
3. **fault site catalog is documented** — every entry of
   :data:`dampr_tpu.faults.SITES` appears (backtick-quoted) in
   ``docs/robustness.md``.
4. **every env var is documented** — every ``DAMPR_TPU_*`` name used
   in the package source appears somewhere under ``docs/`` or in
   ``README.md``.
5. **structured event codes form a closed set** — every code passed to
   an ``obs.log`` emit site (``_obslog.debug/info/warn/error(...)`` or a
   direct ``stream.emit("level", "code", ...)``) is declared in
   :data:`dampr_tpu.obs.log.EVENT_CODES`, every declared code still has
   an emit site (no dead registry entries), and every code appears
   (backtick-quoted) in ``docs/observability.md``'s event table.

Usage::

    python tools/lint_repo.py [--root REPO_ROOT]
"""

import argparse
import json
import os
import re
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_DEFAULT_ROOT = os.path.abspath(os.path.join(_HERE, os.pardir))


def _package_sources(root):
    """{relpath: source} for every .py under dampr_tpu/."""
    out = {}
    pkg = os.path.join(root, "dampr_tpu")
    for dirpath, _dirs, files in os.walk(pkg):
        if "__pycache__" in dirpath:
            continue
        for fn in files:
            if fn.endswith(".py"):
                path = os.path.join(dirpath, fn)
                with open(path) as f:
                    out[os.path.relpath(path, root)] = f.read()
    return out


def check_playbook_knobs(root, errors):
    from dampr_tpu import settings
    from dampr_tpu.obs import doctor

    for verdict, entries in sorted(doctor._PLAYBOOK.items()):
        for knob, env, _propose, _why in entries:
            if not hasattr(settings, knob):
                errors.append(
                    "playbook[{}]: suggests settings.{} which does not "
                    "exist".format(verdict, knob))
            if env and not env.startswith("DAMPR_TPU_"):
                errors.append(
                    "playbook[{}]: knob {} has malformed env {!r}".format(
                        verdict, knob, env))


_SPAN_RX = re.compile(
    r"""(?:trace|_trace)\.(?:span|instant)\(\s*['"]([a-z_0-9]+)['"]""")


def check_span_kinds(root, sources, errors):
    with open(os.path.join(root, "docs", "trace_schema.json")) as f:
        declared = set(json.load(f)["x-span-kinds"])
    used = {}
    for rel, src in sources.items():
        for m in _SPAN_RX.finditer(src):
            used.setdefault(m.group(1), rel)
    for kind, rel in sorted(used.items()):
        if kind not in declared:
            errors.append(
                "span kind {!r} (used in {}) not declared in "
                "docs/trace_schema.json x-span-kinds".format(kind, rel))
    blob = "\n".join(sources.values())
    for kind in sorted(declared):
        if '"{}"'.format(kind) not in blob \
                and "'{}'".format(kind) not in blob:
            errors.append(
                "x-span-kinds declares {!r} but no package source "
                "mentions it (dead schema entry?)".format(kind))


def check_fault_sites(root, errors):
    from dampr_tpu import faults

    with open(os.path.join(root, "docs", "robustness.md")) as f:
        doc = f.read()
    for site in faults.SITES:
        if "`{}`".format(site) not in doc:
            errors.append(
                "faults.SITES entry {!r} undocumented in "
                "docs/robustness.md".format(site))


_ENV_RX = re.compile(r"DAMPR_TPU_[A-Z0-9_]*[A-Z0-9]")


def check_env_docs(root, sources, errors):
    docs = []
    for fn in os.listdir(os.path.join(root, "docs")):
        if fn.endswith((".md", ".json")):
            with open(os.path.join(root, "docs", fn)) as f:
                docs.append(f.read())
    with open(os.path.join(root, "README.md")) as f:
        docs.append(f.read())
    blob = "\n".join(docs)
    used = {}
    for rel, src in sources.items():
        for m in _ENV_RX.finditer(src):
            used.setdefault(m.group(0), rel)
    for env, rel in sorted(used.items()):
        if env not in blob:
            errors.append(
                "env var {} (used in {}) undocumented under docs/ or "
                "README.md".format(env, rel))


_EVENT_RX = re.compile(
    r"""_obslog\.(?:debug|info|warn|error)\(\s*\n?\s*['"]([a-z0-9-]+)['"]""")
_EMIT_RX = re.compile(
    r"""\.emit\(\s*\n?\s*['"](?:debug|info|warn|error)['"],\s*"""
    r"""\n?\s*['"]([a-z0-9-]+)['"]""")


def check_event_codes(root, sources, errors):
    from dampr_tpu.obs import log as obslog

    declared = set(obslog.EVENT_CODES)
    used = {}
    for rel, src in sources.items():
        if rel.endswith(os.path.join("obs", "log.py")):
            continue  # the registry/module itself, not an emit site
        for rx in (_EVENT_RX, _EMIT_RX):
            for m in rx.finditer(src):
                used.setdefault(m.group(1), rel)
    for code, rel in sorted(used.items()):
        if code not in declared:
            errors.append(
                "event code {!r} (emitted in {}) not declared in "
                "obs.log.EVENT_CODES".format(code, rel))
    for code in sorted(declared - set(used)):
        errors.append(
            "EVENT_CODES declares {!r} but no package source emits it "
            "(dead registry entry?)".format(code))
    with open(os.path.join(root, "docs", "observability.md")) as f:
        doc = f.read()
    for code in sorted(declared):
        if "`{}`".format(code) not in doc:
            errors.append(
                "event code {!r} undocumented in docs/observability.md"
                .format(code))


def run(root):
    sys.path.insert(0, root)
    errors = []
    sources = _package_sources(root)
    check_playbook_knobs(root, errors)
    check_span_kinds(root, sources, errors)
    check_fault_sites(root, errors)
    check_env_docs(root, sources, errors)
    check_event_codes(root, sources, errors)
    return errors


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default=_DEFAULT_ROOT)
    args = ap.parse_args(argv)
    errors = run(os.path.abspath(args.root))
    if errors:
        for e in errors:
            print("LINT:", e, file=sys.stderr)
        print("{} violation(s)".format(len(errors)), file=sys.stderr)
        return 1
    print("repo lint OK (playbook knobs, span kinds, fault sites, "
          "env docs, event codes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
