#!/usr/bin/env python
"""Validate a dampr_tpu trace.json / crashdump.json against
docs/trace_schema.json.

Dependency-free (CI and containers without jsonschema): implements the
JSON-Schema subset the checked-in schema uses — type, required,
properties, items, enum, minItems — plus the trace-event phase rules the
schema prose defers here:

- ``X`` (complete) events carry numeric ``ts`` and ``dur``;
- ``i`` (instant) events carry numeric ``ts`` and a scope ``s``;
- ``C`` (counter) events carry numeric ``ts`` and an ``args`` object of
  numeric series values (the metrics plane's sampled time series);
- ``M`` (metadata) events are ``process_name``/``thread_name`` records;
- at least one ``thread_name`` metadata event exists (lanes are named);
- counter timestamps are non-decreasing per series (the sampler's
  monotonic-clock contract).

Flight-recorder crash dumps are the same document shape (their
``otherData.crash`` block is schema-checked when present), so the one
validator covers both artifacts.

Usage::

    python tools/validate_trace.py TRACE.json [--schema docs/trace_schema.json]
                                   [--require-cats codec,fold,spill]
                                   [--require-counters store.resident_bytes]

``--require-cats`` additionally asserts each listed span category appears
on at least one X/i event (the bench smoke job pins the kinds the traced
workload must produce); ``--require-counters`` does the same for counter
series names on C events.
"""

import argparse
import json
import os
import sys

_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "integer": int,
    "boolean": bool,
}


def _check(instance, schema, path, errors):
    typ = schema.get("type")
    if typ == "number":
        if not isinstance(instance, (int, float)) or isinstance(
                instance, bool):
            errors.append("{}: expected number, got {!r}".format(
                path, type(instance).__name__))
            return
    elif typ is not None:
        py = _TYPES.get(typ)
        if py is None:
            errors.append("{}: unsupported schema type {!r}".format(
                path, typ))
            return
        if not isinstance(instance, py) or (
                typ == "integer" and isinstance(instance, bool)):
            errors.append("{}: expected {}, got {!r}".format(
                path, typ, type(instance).__name__))
            return
    if "enum" in schema and instance not in schema["enum"]:
        errors.append("{}: {!r} not in {}".format(
            path, instance, schema["enum"]))
    if isinstance(instance, dict):
        for req in schema.get("required", ()):
            if req not in instance:
                errors.append("{}: missing required key {!r}".format(
                    path, req))
        props = schema.get("properties", {})
        for key, sub in props.items():
            if key in instance:
                _check(instance[key], sub, "{}.{}".format(path, key),
                       errors)
    if isinstance(instance, list):
        if len(instance) < schema.get("minItems", 0):
            errors.append("{}: fewer than minItems={} items".format(
                path, schema["minItems"]))
        items = schema.get("items")
        if items is not None:
            for i, item in enumerate(instance):
                _check(item, items, "{}[{}]".format(path, i), errors)
                if len(errors) > 50:
                    return  # enough to diagnose; don't drown the output


def _phase_rules(events, errors):
    named_lanes = 0
    last_counter_ts = {}  # series name -> last seen ts (monotonic pin)
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        where = "traceEvents[{}]".format(i)
        if ph == "X":
            if not isinstance(ev.get("ts"), (int, float)):
                errors.append(where + ": X event without numeric ts")
            if not isinstance(ev.get("dur"), (int, float)):
                errors.append(where + ": X event without numeric dur")
        elif ph == "i":
            if not isinstance(ev.get("ts"), (int, float)):
                errors.append(where + ": i event without numeric ts")
            if ev.get("s") not in ("t", "p", "g"):
                errors.append(where + ": i event without scope s")
        elif ph == "C":
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)):
                errors.append(where + ": C event without numeric ts")
            args = ev.get("args")
            if not isinstance(args, dict) or not args:
                errors.append(where + ": C event without args payload")
            elif not all(isinstance(v, (int, float))
                         and not isinstance(v, bool)
                         for v in args.values()):
                errors.append(where + ": C event args must be numeric")
            name = ev.get("name")
            if isinstance(ts, (int, float)) and name is not None:
                prev = last_counter_ts.get(name)
                if prev is not None and ts < prev:
                    errors.append(
                        where + ": counter series {!r} timestamps go "
                        "backwards ({} < {})".format(name, ts, prev))
                last_counter_ts[name] = ts
        elif ph == "M":
            if ev.get("name") == "thread_name":
                named_lanes += 1
        if len(errors) > 50:
            return
    if not named_lanes:
        errors.append("no thread_name metadata: lanes are unnamed")


def validate(doc, schema, require_cats=(), require_counters=()):
    """Return a list of error strings (empty = valid)."""
    errors = []
    _check(doc, schema, "$", errors)
    events = doc.get("traceEvents")
    if isinstance(events, list):
        _phase_rules(events, errors)
        counters = {ev.get("name") for ev in events
                    if ev.get("ph") == "C"}
        for want in require_counters:
            if want not in counters:
                errors.append(
                    "required counter series {!r} absent (have: {})"
                    .format(want,
                            ", ".join(sorted(c for c in counters if c))))
        cats = {ev.get("cat") for ev in events
                if ev.get("ph") in ("X", "i")}
        # Closed category set: every span kind the engine emits is
        # declared in the schema's x-span-kinds — an undeclared category
        # fails validation, so new instrumentation must update the schema
        # (and this keeps docs/trace_schema.json the authoritative list).
        known = schema.get("x-span-kinds")
        if known:
            for cat in sorted(c for c in cats if c):
                if cat not in known:
                    errors.append(
                        "span category {!r} is not declared in the "
                        "schema's x-span-kinds".format(cat))
        for want in require_cats:
            if want not in cats:
                errors.append(
                    "required span category {!r} absent (have: {})".format(
                        want, ", ".join(sorted(c for c in cats if c))))
    return errors


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="validate a dampr_tpu Chrome trace-event JSON")
    ap.add_argument("trace")
    ap.add_argument("--schema", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "trace_schema.json"))
    ap.add_argument("--require-cats", default="",
                    help="comma-separated span categories that must appear")
    ap.add_argument("--require-counters", default="",
                    help="comma-separated counter series (C-event names) "
                         "that must appear")
    args = ap.parse_args(argv)

    with open(args.trace) as f:
        doc = json.load(f)
    with open(args.schema) as f:
        schema = json.load(f)
    cats = [c for c in args.require_cats.split(",") if c]
    counters = [c for c in args.require_counters.split(",") if c]
    errors = validate(doc, schema, cats, counters)
    if errors:
        for e in errors:
            print("INVALID: {}".format(e), file=sys.stderr)
        return 1
    n = len(doc["traceEvents"])
    n_counter_series = len({ev.get("name") for ev in doc["traceEvents"]
                            if ev.get("ph") == "C"})
    crash = (doc.get("otherData") or {}).get("crash")
    tag = " [crashdump: {}]".format(crash.get("reason")) if crash else ""
    print("OK: {} events, {} categories, {} counter series{}".format(
        n, len({ev.get("cat") for ev in doc["traceEvents"]
                if ev.get("cat") and ev.get("ph") in ("X", "i")}),
        n_counter_series, tag))
    return 0


if __name__ == "__main__":
    sys.exit(main())
