"""Render docs/API.md from the package's docstrings (the reference ships a
pdoc-generated API reference, docs/dampr/index.html; this is the equivalent
without a pdoc dependency).

Run: python docs/generate_api.py
"""

import importlib
import inspect
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:  # `python docs/generate_api.py` from any cwd
    sys.path.insert(0, _REPO_ROOT)

MODULES = [
    "dampr_tpu",
    "dampr_tpu.dampr",
    "dampr_tpu.base",
    "dampr_tpu.blocks",
    "dampr_tpu.dataset",
    "dampr_tpu.inputs",
    "dampr_tpu.graph",
    "dampr_tpu.plan",
    "dampr_tpu.plan.ir",
    "dampr_tpu.plan.passes",
    "dampr_tpu.plan.cost",
    "dampr_tpu.plan.model",
    "dampr_tpu.plan.explain",
    "dampr_tpu.plan.lower",
    "dampr_tpu.runner",
    "dampr_tpu.faults",
    "dampr_tpu.storage",
    "dampr_tpu.io",
    "dampr_tpu.io.codecs",
    "dampr_tpu.io.frames",
    "dampr_tpu.io.writer",
    "dampr_tpu.obs",
    "dampr_tpu.obs.trace",
    "dampr_tpu.obs.metrics",
    "dampr_tpu.obs.sampler",
    "dampr_tpu.obs.progress",
    "dampr_tpu.obs.promtext",
    "dampr_tpu.obs.flightrec",
    "dampr_tpu.obs.fleet",
    "dampr_tpu.obs.serve",
    "dampr_tpu.obs.export",
    "dampr_tpu.obs.profile",
    "dampr_tpu.obs.critpath",
    "dampr_tpu.obs.history",
    "dampr_tpu.obs.doctor",
    "dampr_tpu.obs.autotune",
    "dampr_tpu.obs.log",
    "dampr_tpu.obs.timeseries",
    "dampr_tpu.obs.sentry",
    "dampr_tpu.obs.top",
    "dampr_tpu.analyze",
    "dampr_tpu.analyze.props",
    "dampr_tpu.analyze.pickleprobe",
    "dampr_tpu.analyze.assoc",
    "dampr_tpu.analyze.jaxtrace",
    "dampr_tpu.analyze.validate",
    "dampr_tpu.analyze.lint",
    "dampr_tpu.resume",
    "dampr_tpu.serve",
    "dampr_tpu.serve.wire",
    "dampr_tpu.serve.scheduler",
    "dampr_tpu.serve.client",
    "dampr_tpu.serve.daemon",
    "dampr_tpu.serve.worker",
    "dampr_tpu.settings",
    "dampr_tpu.ops.hashing",
    "dampr_tpu.ops.segment",
    "dampr_tpu.ops.text",
    "dampr_tpu.ops.lower",
    "dampr_tpu.parallel",
    "dampr_tpu.parallel.mesh",
    "dampr_tpu.parallel.shuffle",
    "dampr_tpu.parallel.exchange",
    "dampr_tpu.parallel.replan",
    "dampr_tpu.parallel.mitigate",
    "dampr_tpu.parallel.sgd",
    "dampr_tpu.native",
    "dampr_tpu.utils",
    "dampr_tpu.utils.indexer",
    "dampr_tpu.utils.common",
]


import re as _re


def _sig(obj):
    try:
        sig = str(inspect.signature(obj))
    except (TypeError, ValueError):
        return "(...)"
    # strip live object addresses from default reprs so regenerated docs
    # are byte-stable
    return _re.sub(r" at 0x[0-9a-f]+", "", sig)


def _doc(obj, indent=""):
    d = inspect.getdoc(obj)
    if not d:
        return ""
    return "\n".join(indent + line for line in d.splitlines())


def render_module(name, out):
    mod = importlib.import_module(name)
    out.append("\n## `{}`\n".format(name))
    d = _doc(mod)
    if d:
        out.append(d + "\n")

    members = vars(mod)
    for attr, obj in sorted(members.items()):
        if attr.startswith("_"):
            continue
        if getattr(obj, "__module__", None) != name:
            continue
        if inspect.isclass(obj):
            out.append("\n### class `{}.{}`\n".format(name, attr))
            d = _doc(obj)
            if d:
                out.append(d + "\n")
            for mname, meth in sorted(vars(obj).items()):
                if mname.startswith("_") or not callable(meth):
                    continue
                fn = meth.__func__ if isinstance(
                    meth, (classmethod, staticmethod)) else meth
                out.append("- **`{}{}`**".format(mname, _sig(fn)))
                md = inspect.getdoc(fn)
                if md:
                    out.append("  - {}".format(md.splitlines()[0]))
        elif inspect.isfunction(obj):
            out.append("\n### `{}.{}{}`\n".format(name, attr, _sig(obj)))
            d = _doc(obj)
            if d:
                out.append(d + "\n")


def main():
    out = [
        "# dampr_tpu API reference",
        "",
        "*Generated from docstrings by `docs/generate_api.py` — regenerate "
        "after changing public surfaces.*",
    ]
    for name in MODULES:
        render_module(name, out)
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "API.md")
    with open(path, "w") as f:
        f.write("\n".join(out) + "\n")
    print("wrote", path, "({} lines)".format(sum(s.count("\n") + 1
                                                 for s in out)))


if __name__ == "__main__":
    main()
