"""Input taps: how bytes enter the framework.

Parity surface: reference dampr/inputs.py — ``read_paths`` glob/walk with
dotfile filtering (14-30), ``PathInput`` (32-41), ``TextInput`` byte-range
chunking with .gz-as-one-chunk (43-56), ``MemoryInput`` (59-71),
``UrlsInput``/``UrlDataset`` with skip-on-error (74-97).

Taps are host-side by design: IO and decompression happen on CPU threads; the
records they emit are batched into columnar blocks downstream, which is where
the TPU path begins.
"""

import glob
import os
from contextlib import closing

from .dataset import (Chunker, Dataset, GzipLineDataset, MemoryDataset,
                      TextLineDataset)


def read_paths(paths, follow_links=True):
    """Expand globs; walk directories; hide dotfiles."""
    if not isinstance(paths, list):
        paths = [paths]

    def it():
        for path_glob in paths:
            for path in sorted(glob.glob(path_glob)):
                if os.path.isfile(path):
                    yield path
                else:
                    for root, _dirs, files in os.walk(
                            path, followlinks=follow_links):
                        for fname in sorted(files):
                            yield os.path.join(root, fname)

    return (p for p in it() if not os.path.basename(p).startswith("."))


class PathInput(Chunker):
    """File / directory / glob of newline-delimited text."""

    def __init__(self, path, chunk_size=64 * 1024 ** 2, follow_links=True):
        self.path = path
        self.chunk_size = chunk_size
        self.follow_links = follow_links

    def chunks(self):
        for path in read_paths(self.path, self.follow_links):
            for c in TextInput(path, self.chunk_size).chunks():
                yield c


class TextInput(Chunker):
    """One text file split into byte-range chunks; .gz files are a single
    unsplittable chunk (gzip streams have no random access)."""

    def __init__(self, path, chunk_size=64 * 1024 ** 2):
        self.path = path
        self.chunk_size = chunk_size

    def chunks(self):
        if self.path.endswith(".gz"):
            yield GzipLineDataset(self.path)
        else:
            file_size = os.stat(self.path).st_size
            offset = 0
            while offset < file_size:
                yield TextLineDataset(self.path, offset,
                                      offset + self.chunk_size)
                offset += self.chunk_size


class MemoryInput(Chunker):
    """In-memory (k, v) list split into ~`partitions` chunks."""

    def __init__(self, items, partitions=50):
        self.items = items
        self.partitions = min(len(items), partitions)

    def chunks(self):
        if self.partitions == 0:
            yield MemoryDataset(self.items)
        else:
            chunk_size = max(1, int(len(self.items) // float(self.partitions)))
            for start in range(0, len(self.items), chunk_size):
                yield MemoryDataset(self.items[start:start + chunk_size])


class UrlsInput(Chunker):
    """One chunk per URL; HTTP errors optionally skipped."""

    def __init__(self, urls, skip_on_error=True):
        self.urls = urls
        self.skip_on_error = skip_on_error

    def chunks(self):
        for url in self.urls:
            yield UrlDataset(url, self.skip_on_error)


class UrlDataset(Dataset):
    def __init__(self, url, skip_on_error=True):
        self.url = url
        self.skip_on_error = skip_on_error

    def read(self):
        from urllib.error import HTTPError, URLError
        from urllib.request import urlopen

        try:
            with closing(urlopen(self.url)) as h:
                for i, line in enumerate(h):
                    yield i, line.decode("utf-8")
        except (HTTPError, URLError):
            if not self.skip_on_error:
                raise

    def __repr__(self):
        return "Url[{}]".format(self.url)
