"""SQLite inverted index over line files (reference dampr/utils/indexer.py).

``build`` runs a Dampr pipeline that writes a hidden ``.<name>.index`` SQLite
DB per input file mapping keys to byte offsets; ``union``/``intersect`` stream
back the matching lines by seeking.  Offsets here are byte offsets (binary
seek), making lookups exact regardless of encoding.
"""

import logging
import os
import sqlite3

from ..dampr import Dampr
from ..inputs import read_paths

log = logging.getLogger("dampr_tpu.indexer")


class Indexer(object):
    def __init__(self, path, suffix=".index"):
        self.path = path
        self.suffix = suffix

    def get_idx(self, path):
        dirname, base = os.path.split(path)
        return os.path.join(dirname, "." + base + self.suffix)

    def exists(self, path):
        return os.path.isfile(self.get_idx(path))

    def _open_db(self, path, delete=False):
        idx = self.get_idx(path)
        if delete and os.path.isfile(idx):
            os.unlink(idx)
        return sqlite3.connect(idx)

    def _create_db(self, path):
        db = self._open_db(path, delete=True)
        db.cursor().execute(
            "CREATE TABLE key_index (key text, offset integer)")
        return db

    def build(self, key_f, force=False):
        """Index every file under ``path``: ``key_f(line) -> iterable of
        keys``.  Returns total keys indexed."""
        paths = sorted(read_paths(self.path, False))

        def index_file(fname):
            log.debug("Indexing %s", fname)
            db = self._create_db(fname)

            def it():
                offset = 0
                with open(fname, "rb") as f:
                    for raw in f:
                        line = raw.decode("utf-8")
                        for key in key_f(line):
                            yield key, offset
                        offset += len(raw)

            c = db.cursor()
            c.executemany("INSERT INTO key_index values (?, ?)", it())
            db.commit()
            c.execute("create index key_idx on key_index (key)")
            db.commit()
            c.execute("select count(*) from key_index")
            count = c.fetchone()[0]
            db.close()
            return count

        return (Dampr.memory(paths)
                .filter(lambda fname: force or not self.exists(fname))
                .map(index_file)
                .fold_by(key=lambda _x: 1, binop=lambda x, y: x + y)
                .read(name="indexing"))

    def _seek_lines(self, query, params):
        params = tuple(params)

        def read_db(fname):
            db = self._open_db(fname)
            cur = db.cursor()
            cur.execute(query, params)
            with open(fname, "rb") as f:
                for (offset,) in cur:
                    f.seek(offset)
                    yield f.readline().decode("utf-8")
            db.close()

        paths = sorted(read_paths(self.path, False))
        return Dampr.memory(paths).flat_map(read_db)

    def union(self, keys):
        """Lines containing any of the keys."""
        if not isinstance(keys, (list, tuple)):
            keys = [keys]
        query = ("select distinct offset from key_index where key in ({}) "
                 "order by offset asc").format(
                     ",".join("?" for _ in keys))
        return self._seek_lines(query, keys)

    def intersect(self, keys, min_match=None):
        """Lines containing at least ``min_match`` of the keys (all, by
        default; a float is a fraction of the key count)."""
        if not isinstance(keys, (list, tuple)):
            keys = [keys]
        if min_match is None:
            min_match = len(keys)
        if isinstance(min_match, float):
            min_match = int(min_match * len(keys))
        query = ("select offset from (select offset, count(*) as c from "
                 "key_index where key in ({}) group by offset) where c >= ? "
                 "order by offset asc").format(
                     ",".join("?" for _ in keys))
        return self._seek_lines(query, list(keys) + [min_match])
