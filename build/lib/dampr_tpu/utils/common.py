"""Composed-DSL utilities (reference dampr/utils/common.py)."""


def filter_by_count(pipe, key_func, filter_func):
    """Keep items whose key's global count passes ``filter_func`` — the
    canonical count-then-join-back composition (reference utils/common.py:2-15).
    The count compiles to a device segment-sum; the join is co-partitioned
    sort-merge.
    """
    item_count = (pipe.map(key_func)
                  .count()
                  .filter(lambda count: filter_func(count[1])))

    return (item_count.group_by(lambda x: x[0], lambda x: x[1])
            .join(pipe.group_by(key_func))
            .reduce(lambda _lit, rit: rit, many=True)
            .map(lambda x: x[1]))
