"""Console entry points (pyproject [project.scripts]).

The reference installs as a plain library with ``test_suite`` wiring only
(reference setup.py:1-20); these go further: the benchmark and the two
canonical workloads run from an installed package without a repo checkout.

- ``dampr-tpu-bench``  — the TF-IDF headline benchmark (same code path the
  repo-root ``bench.py`` driver hook runs; DAMPR_BENCH_MB sizes the corpus).
- ``dampr-tpu-wc``     — word count over a file/dir, top-20 to stdout.
- ``dampr-tpu-tfidf``  — TF-IDF over a file/dir, TSV parts to --out.
"""

import argparse
import math
import operator
import os


def bench():
    from .bench_tfidf import main
    main()


def wc():
    ap = argparse.ArgumentParser(description="word count (top 20)")
    ap.add_argument("path")
    ap.add_argument("--chunk-mb", type=int, default=16)
    args = ap.parse_args()

    from . import Dampr

    counts = (Dampr.text(args.path, chunk_size=args.chunk_mb * 1024 ** 2)
              .flat_map(lambda line: line.split())
              .fold_by(lambda w: w, binop=operator.add, value=lambda w: 1)
              .run("wc-cli"))
    for word, count in sorted(counts, key=lambda kv: kv[1],
                              reverse=True)[:20]:
        print("{}: {}".format(word, count))
    counts.delete()


def tf_idf():
    ap = argparse.ArgumentParser(description="TF-IDF -> TSV parts")
    ap.add_argument("path")
    ap.add_argument("--out", default="/tmp/dampr_tpu_idfs")
    args = ap.parse_args()

    from . import Dampr
    from .ops.text import DocFreq

    chunk = (os.path.getsize(args.path) + 1
             if os.path.isfile(args.path) else 16 * 1024 ** 2)
    docs = Dampr.text(args.path, chunk)
    df = (docs.custom_mapper(DocFreq(mode="word", lower=True))
          .fold_by(lambda kv: kv[0], operator.add, lambda kv: kv[1]))
    idf = df.cross_right(
        docs.len(),
        lambda d, total: (d[0], d[1], math.log(1 + float(total) / d[1])),
        memory=True)
    idf.sink_tsv(args.out).run("tfidf-cli")
    print("TSV parts in {}".format(args.out))
