"""Distributed execution over a jax.sharding.Mesh.

This is the TPU-native replacement for the reference's "distributed
communication backend" — which is a shared local filesystem plus
multiprocessing queues (reference base.py:416-433 DefaultShuffler,
stagerunner.py:16-38; see SURVEY §2 'Distributed communication backend').
Here the exchange is XLA collectives over ICI/DCN:

- :func:`dampr_tpu.parallel.shuffle.mesh_keyed_fold` — the keyed shuffle:
  per-device local segment fold, fixed-capacity ``lax.all_to_all`` routed by
  ``hash % n_devices``, then a final per-device fold.
- :func:`dampr_tpu.parallel.shuffle.mesh_global_sum` — degenerate-key
  aggregates (len/sum) as a local reduce + ``psum``.
- :mod:`dampr_tpu.parallel.mesh` — mesh construction helpers.

The mesh abstraction is host-count-agnostic: the same program spans one chip,
a v4-8 slice, or multi-host DCN — only the Mesh changes (SURVEY §7 hard
part 5).
"""

from .exchange import mesh_blob_exchange, mesh_shuffle_blocks
from .mesh import data_mesh, default_mesh, init_distributed
from .shuffle import mesh_global_sum, mesh_keyed_fold

__all__ = ["data_mesh", "default_mesh", "init_distributed",
           "mesh_keyed_fold", "mesh_global_sum",
           "mesh_blob_exchange", "mesh_shuffle_blocks"]
