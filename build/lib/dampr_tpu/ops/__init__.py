"""Device kernels for dampr_tpu: hashing, segment reduction, sort-based grouping,
and the mesh shuffle.  Every kernel has a numpy host fallback selected by
``settings.use_device`` / small-batch thresholds."""

from .hashing import hash_keys, encode_str_keys, combine64
