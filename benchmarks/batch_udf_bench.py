"""Opaque-UDF batch execution benchmark (SURVEY §7 hard part 1).

Pipeline: JSON-lines text -> json.loads -> field extract -> filter ->
fold_by(count).  Every op is an opaque Python lambda, so nothing can ride
the vectorized text kernels — this isolates exactly the per-record
generator chain the reference pays (ref stagerunner.py:73-74) against our
batched ``apply_batch`` lowering.

Usage: python benchmarks/batch_udf_bench.py [--size-mb 1024]
"""

import argparse
import json
import os
import random
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from dampr_tpu import Dampr, settings  # noqa: E402


def make_input(path, size_mb):
    rnd = random.Random(7)
    words = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta"]
    target = size_mb * 1024 * 1024
    n = 0
    with open(path, "w") as f:
        while f.tell() < target:
            for _ in range(10000):
                rec = {"user": rnd.randrange(10000),
                       "tag": rnd.choice(words),
                       "n": rnd.randrange(100)}
                f.write(json.dumps(rec))
                f.write("\n")
                n += 1
    return n, os.path.getsize(path)


def pipeline(path):
    return (Dampr.text(path)
            .map(json.loads)
            .map(lambda r: (r["tag"], r["n"]))
            .filter(lambda kv: kv[1] % 100 < 80)
            .fold_by(lambda kv: kv[0], binop=lambda a, b: a + b,
                     value=lambda kv: kv[1]))


def run_once(path, batch):
    old = settings.batch_udf
    settings.batch_udf = batch
    try:
        t0 = time.time()
        out = dict(pipeline(path).run(name="batch_bench").read())
        dt = time.time() - t0
    finally:
        settings.batch_udf = old
    return dt, out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size-mb", type=int, default=1024)
    args = ap.parse_args()

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "data.jsonl")
        print("generating %d MB of JSON lines..." % args.size_mb)
        n, nbytes = make_input(path, args.size_mb)
        print("records=%d bytes=%d" % (n, nbytes))

        results = {}
        for mode, batch in [("generator", False), ("batched", True)]:
            dt, out = run_once(path, batch)
            mbs = nbytes / dt / 1e6
            results[mode] = (dt, mbs, out)
            print("%-9s  %6.1fs  %7.1f MB/s" % (mode, dt, mbs))

        assert results["generator"][2] == results["batched"][2], \
            "outputs differ between lowerings!"
        speedup = results["generator"][0] / results["batched"][0]
        print(json.dumps({
            "metric": "batch_udf_speedup",
            "value": round(speedup, 3),
            "unit": "x",
            "generator_mb_s": round(results["generator"][1], 1),
            "batched_mb_s": round(results["batched"][1], 1),
            "size_mb": args.size_mb,
        }))


if __name__ == "__main__":
    main()
