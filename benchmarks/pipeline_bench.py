"""Pipelined-vs-staged multi-stage benchmark (docs/pipeline.md): a
spill-bound map -> keyed-fold -> sort pipeline runs once fully staged
(``DAMPR_TPU_PIPELINE=0``) and once with streamed edges, asserts the two
outputs byte-identical, and reports the wall-clock ratio plus the
pipeline section's overlap evidence (``overlap_fraction``, published
partitions, early-folded blocks, stall seconds).

The speedup is bounded by the host's parallelism: the early fold only
hides work when a core is free to run it while the map stage streams
(on a single-core container the ratio sits near 1.0 and the bench's
value is the byte-identity pin plus the overlap accounting).

    python benchmarks/pipeline_bench.py --mb 256 --budget-mb 32
"""

import _pathfix  # noqa: F401  (repo root onto sys.path)

import argparse
import json
import operator
import os
import sys
import time

import numpy as np


def make_records(path, mb, keys, seed=11):
    if os.path.exists(path) and os.path.getsize(path) >= mb * 1024 ** 2:
        return
    rng = np.random.RandomState(seed)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    target = mb * 1024 ** 2
    written = 0
    with open(path, "w") as f:
        while written < target:
            ks = rng.randint(0, keys, size=100000)
            chunk = "\n".join(str(k) for k in ks) + "\n"
            f.write(chunk)
            written += len(chunk)


def lint_pipelines():
    """dampr-tpu-lint discovery hook: the pipelined fold shape
    (constructed over this source file; nothing runs)."""
    from dampr_tpu import Dampr
    from dampr_tpu.ops.text import ParseNumbers

    pipe = (Dampr.text(__file__, chunk_size=1024 ** 2)
            .custom_mapper(ParseNumbers())
            .fold_values(operator.add)
            .sort_by(lambda kv: -kv[1]))
    return [("pipeline_bench", pipe)]


def _build(path, chunk_mb):
    from dampr_tpu import Dampr
    from dampr_tpu.ops.text import ParseNumbers

    # map (vectorized numeric parse) -> keyed assoc fold (the streamed
    # early_fold edge) -> sort by folded value (a sort barrier stage, so
    # the plan's decision table carries both verdicts).
    return (Dampr.text(path, chunk_size=chunk_mb * 1024 ** 2)
            .custom_mapper(ParseNumbers())
            .fold_values(operator.add)
            .sort_by(lambda kv: -kv[1]))


def _run_leg(pipe, name):
    t0 = time.time()
    em = pipe.run(name=name)
    out = em.read()
    stats = em.stats()
    em.delete()
    return out, stats, time.time() - t0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=256)
    ap.add_argument("--budget-mb", type=int, default=32)
    ap.add_argument("--chunk-mb", type=int, default=16)
    ap.add_argument("--keys", type=int, default=65536)
    ap.add_argument("--dir", default="/tmp/dampr_tpu_bench")
    args = ap.parse_args()

    from dampr_tpu import settings

    # Host-resident like sort_bench: the parse/fold path wins on host
    # numpy, and the streamed-edge analysis conservatively bars streaming
    # whenever a mesh collective could engage.
    settings.use_device = False
    settings.mesh_fold = "off"
    settings.mesh_exchange = "off"
    settings.max_memory_per_stage = args.budget_mb * 1024 ** 2

    path = os.path.join(args.dir, "pipe_records_{}mb_{}k.txt".format(
        args.mb, args.keys))
    make_records(path, args.mb, args.keys)
    size_mb = os.path.getsize(path) / 1e6

    pipe = _build(path, args.chunk_mb)
    stamp = int(time.time())

    settings.pipeline = "0"
    staged, staged_stats, staged_s = _run_leg(
        pipe, "pipe-bench-staged-{}".format(stamp))
    settings.pipeline = "auto"
    streamed, stream_stats, stream_s = _run_leg(
        pipe, "pipe-bench-streamed-{}".format(stamp))

    if staged != streamed:
        print("BYTE-IDENTITY VIOLATION: pipelined output diverged from "
              "staged ({} vs {} records)".format(
                  len(streamed), len(staged)), file=sys.stderr)
        sys.exit(1)

    ps = stream_stats["pipeline"]
    if not ps["executed"]:
        print("NO STREAMED EDGE EXECUTED (degraded={})".format(
            ps["degraded"]), file=sys.stderr)
        sys.exit(1)

    print(json.dumps({
        "metric": "pipeline_speedup",
        "value": round(staged_s / stream_s, 3),
        "unit": "x",
        "input_mb": round(size_mb, 1),
        "keys": args.keys,
        "budget_mb": args.budget_mb,
        "records_out": len(streamed),
        "wall_staged_seconds": round(staged_s, 3),
        "wall_pipelined_seconds": round(stream_s, 3),
        "edges_streamed": ps["edges_streamed"],
        "executed": ps["executed"],
        "published": ps["published"],
        "early_folded_blocks": ps["early_folded_blocks"],
        "overlap_fraction": ps["overlap_fraction"],
        "fold_seconds": round(ps["fold_seconds"], 3),
        "stall_seconds": round(ps["stall_seconds"], 3),
        "queue_peak_mb": round(ps["queue_peak_bytes"] / 1e6, 2),
        "byte_identical": True,
        "throughput_mbps": round(size_mb / stream_s, 2),
        # Artifact paths from the streamed leg (None untraced) — the
        # trace-smoke CI leg validates the pipeline spans behind these.
        "trace_file": stream_stats.get("trace_file"),
        "stats_file": stream_stats.get("stats_file"),
    }))


if __name__ == "__main__":
    main()
