"""Chaos-exactness smoke: the failure-recovery contract, end to end.

Runs a bench pipeline twice — once clean, once under an injected fault
schedule (transient spill-write/read faults, a transient UDF fault to
force a job retry) against an input carrying ONE deterministically
poisoned record — and asserts:

- results are **byte-identical** (the poisoned record is quarantined,
  every transient fault is absorbed by a retry layer);
- ``stats()["faults"]`` reports ``retries > 0`` and ``quarantined == 1``;
- the traced chaos run's trace.json validates against the checked-in
  schema (fault instants included).

    python benchmarks/chaos_smoke.py --mode sort  --mb 8
    python benchmarks/chaos_smoke.py --mode tfidf --mb 4

The acceptance-scale runs are ``--mode sort --mb 256`` and ``--mode
tfidf --mb 64``.  Exits nonzero on any violated invariant; emits one
JSON line (metric/value keyed for tools/check_bench.py) on success.
See docs/robustness.md.
"""

import _pathfix  # noqa: F401  (repo root onto sys.path)

import argparse
import hashlib
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: The poisoned record: a line no numeric parse survives and the fault
#: plan's ``udf:match=POISON`` keys on.  Appended once to the chaos
#: input; the clean baseline runs WITHOUT it, so byte-identical results
#: prove the quarantine removed exactly that record.
POISON_LINE = "POISON_RECORD_0xDEAD"

#: One rule per site.  The sort pipeline's poison is DATA-level
#: (``int()`` raises on the poison line), so its ``udf`` slot carries a
#: one-shot transient fault to force a job retry; the tfidf pipeline's
#: UDFs digest anything, so its ``udf`` slot carries the content-keyed
#: deterministic poison and the transient rides the fold site instead.
FAULT_PLANS = {
    "sort": ("spill_write:p=0.02;spill_read:p=0.01;"
             "udf:nth=2,kind=transient,times=1;seed=7"),
    "tfidf": ("spill_write:p=0.02;spill_read:p=0.01;"
              "fold:nth=2,kind=transient,times=1;"
              "udf:match=POISON,kind=deterministic;seed=7"),
}


def make_numbers(path, mb, seed=7):
    import numpy as np

    if os.path.exists(path) and os.path.getsize(path) >= mb * 1024 ** 2:
        return
    rng = np.random.RandomState(seed)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    target = mb * 1024 ** 2
    written = 0
    with open(path, "w") as f:
        while written < target:
            ks = rng.randint(0, 1 << 62, size=50000)
            chunk = "\n".join(str(k) for k in ks) + "\n"
            f.write(chunk)
            written += len(chunk)


def make_docs(path, mb, seed=11):
    import numpy as np

    if os.path.exists(path) and os.path.getsize(path) >= mb * 1024 ** 2:
        return
    rng = np.random.RandomState(seed)
    vocab = ["w{:04d}".format(i) for i in range(4096)]
    os.makedirs(os.path.dirname(path), exist_ok=True)
    target = mb * 1024 ** 2
    written = 0
    with open(path, "w") as f:
        while written < target:
            n = int(rng.randint(5, 25))
            words = [vocab[int(i)] for i in rng.randint(0, len(vocab),
                                                        size=n)]
            line = " ".join(words) + "\n"
            f.write(line)
            written += len(line)


def with_poison(clean_path):
    poisoned = clean_path + ".poisoned"
    with open(clean_path, "rb") as src, open(poisoned, "wb") as dst:
        dst.write(src.read())
        dst.write((POISON_LINE + "\n").encode())
    return poisoned


def build_pipe(mode, path):
    from dampr_tpu import Dampr

    if mode == "sort":
        # int() raises ValueError on the poison line — a genuinely
        # poisoned record, not merely an injected one.
        return (Dampr.text(path)
                .map(int)
                .sort_by(lambda x: x))
    assert mode == "tfidf"
    # Word counts over the corpus (the TF side of TF-IDF; the poison
    # line is killed by the injected udf:match rule).
    return (Dampr.text(path)
            .flat_map(lambda line: line.split())
            .count(lambda w: w))


def digest(em):
    """SHA-256 over the emitted value stream, in emission order (the
    DSL's key-sorted read) — byte-identity means identical values in
    identical order."""
    h = hashlib.sha256()
    n = 0
    for v in em.read():
        h.update(repr(v).encode())
        n += 1
    return h.hexdigest(), n


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("sort", "tfidf"), default="sort")
    ap.add_argument("--mb", type=int, default=8)
    ap.add_argument("--budget-mb", type=int, default=8)
    ap.add_argument("--dir", default="/tmp/dampr_tpu_chaos")
    args = ap.parse_args()

    from dampr_tpu import faults, settings

    settings.use_device = False
    settings.max_memory_per_stage = args.budget_mb * 1024 ** 2
    settings.trace = True
    settings.trace_dir = os.path.join(args.dir, "traces")
    settings.scratch_root = os.path.join(args.dir, "scratch")

    clean_path = os.path.join(
        args.dir, "{}_{}mb.txt".format(args.mode, args.mb))
    (make_numbers if args.mode == "sort" else make_docs)(
        clean_path, args.mb)
    poisoned_path = with_poison(clean_path)

    # -- clean baseline: no faults, no poison record -------------------------
    settings.faults = None
    faults.clear()
    settings.job_retries = 0
    settings.max_quarantined = 0
    em = build_pipe(args.mode, clean_path).run(
        name="chaos-{}-clean".format(args.mode))
    clean_digest, clean_n = digest(em)
    em.delete()

    # -- chaos leg: fault schedule + one poisoned record ---------------------
    settings.faults = FAULT_PLANS[args.mode]
    settings.job_retries = 3
    settings.max_quarantined = 1
    t0 = time.time()
    em = build_pipe(args.mode, poisoned_path).run(
        name="chaos-{}-chaos".format(args.mode))
    secs = time.time() - t0
    chaos_digest, chaos_n = digest(em)
    stats = em.stats()
    fa = stats["faults"]
    em.delete()
    settings.faults = None
    faults.clear()
    settings.job_retries = 0
    settings.max_quarantined = 0

    failures = []
    if chaos_digest != clean_digest or chaos_n != clean_n:
        failures.append(
            "results diverged: clean {} ({} records) vs chaos {} ({})"
            .format(clean_digest[:16], clean_n, chaos_digest[:16],
                    chaos_n))
    if fa.get("retries", 0) <= 0:
        failures.append("no retries absorbed under the fault schedule: "
                        "{}".format(fa))
    if fa.get("quarantined") != 1:
        failures.append("expected exactly 1 quarantined record, got "
                        "{}".format(fa.get("quarantined")))

    # Trace schema validity (fault instants included).
    trace_file = stats.get("trace_file")
    trace_valid = None
    if trace_file and os.path.isfile(trace_file):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "validate_trace", os.path.join(ROOT, "tools",
                                           "validate_trace.py"))
        vt = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(vt)
        with open(os.path.join(ROOT, "docs", "trace_schema.json")) as f:
            schema = json.load(f)
        with open(trace_file) as f:
            doc = json.load(f)
        errors = vt.validate(doc, schema)
        trace_valid = not errors
        if errors:
            failures.append("chaos trace failed schema validation: "
                            "{}".format(errors[:5]))

    out = {
        "bench": "chaos_smoke",
        "metric": "chaos_{}_records_per_s".format(args.mode),
        "value": round(chaos_n / secs, 2) if secs > 0 else 0.0,
        "mode": args.mode,
        "mb": args.mb,
        "records": chaos_n,
        "seconds": round(secs, 3),
        "byte_identical": chaos_digest == clean_digest,
        "retries": fa.get("retries"),
        "job_retries": fa.get("job_retries"),
        "io_retries": fa.get("io_retries"),
        "quarantined": fa.get("quarantined"),
        "injected": fa.get("injected"),
        "backoff_seconds": fa.get("backoff_seconds"),
        "trace_valid": trace_valid,
        "fault_plan": FAULT_PLANS[args.mode],
        "ok": not failures,
    }
    print(json.dumps(out))
    if failures:
        for msg in failures:
            print("CHAOS FAILURE: {}".format(msg), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
