"""Cross-stage device-handoff microbenchmark: the lowered map->fold EDGE
in isolation (docs/plan.md "Cross-stage device fusion").

The pipeline is the smallest one that has the edge — a native DocFreq
scanner map feeding a device-lowered associative sum fold — run twice
under forced lowering (``DAMPR_TPU_LOWER=1`` semantics, in-process):

- ``spill`` leg (``DAMPR_TPU_HANDOFF=off``): the lowered map's program
  outputs drain to host, pickle, frame-encode, spill, re-read and h2d
  back into the fold — the pre-handoff edge;
- ``device`` leg (``DAMPR_TPU_HANDOFF=on``): program outputs stay
  HBM-resident in the per-job vocabulary accumulator and the collective
  fold consumes them in place (``ops/handoff.py``).

Both legs must produce byte-identical doc-frequency counts (asserted
against each other AND a host-side oracle); the JSON reports per-leg
walls, throughput, d2h bytes on the edge, and the drain bytes the
device leg never fetched — check_bench-comparable via
``metric``/``value`` (the device-leg MB/s is the headline).

    python benchmarks/device_bench.py [--mb 16] [--trials 2] [--json F]

CI runs the tiny flavor and compares against the checked-in
``DEVICE_r01.json`` trajectory point (warn-only, tools/check_bench.py).
"""

import _pathfix  # noqa: F401  (repo root onto sys.path)

import argparse
import json
import operator
import os
import re
import sys
import time
from collections import Counter


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def oracle(corpus):
    """Host-side doc-frequency oracle (the bench_tfidf baseline shape:
    per line, the set of lowercased ``[^\\w]+``-split tokens)."""
    rx = re.compile(r"[^\w]+")
    counts = Counter()
    with open(corpus, encoding="utf-8") as f:
        for line in f:
            counts.update(set(t for t in rx.split(line.lower()) if t))
    return dict(counts)


def run_leg(corpus, handoff, name, trials):
    """One edge leg: forced lowering, handoff per ``handoff``.  Returns
    (best wall seconds, result dict, device stats of the best run)."""
    from dampr_tpu import Dampr, settings
    from dampr_tpu.ops.text import DocFreq

    old_lower, old_handoff = settings.lower, settings.handoff
    settings.lower = "1"
    settings.handoff = handoff
    try:
        import multiprocessing

        chunk = os.path.getsize(corpus) // multiprocessing.cpu_count() + 1
        best, result, dev = None, None, None
        for t in range(max(1, trials)):
            docs = Dampr.text(corpus, chunk)
            df = (docs.custom_mapper(
                DocFreq(mode="word", lower=True, pair_values=False))
                .fold_values(operator.add))
            t0 = time.time()
            em = df.run(name="{}-t{}".format(name, t))
            wall = time.time() - t0
            got = dict(em.read())
            stats = em.stats()
            em.delete()
            if best is None or wall < best:
                best, result, dev = wall, got, stats["device"]
        return best, result, dev
    finally:
        settings.lower = old_lower
        settings.handoff = old_handoff


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int,
                    default=int(os.environ.get("DAMPR_BENCH_MB", "16")))
    ap.add_argument("--trials", type=int, default=2)
    ap.add_argument("--json", default=None,
                    help="also write the JSON record to this path")
    args = ap.parse_args(argv)

    from dampr_tpu.bench_tfidf import BENCH_DIR, make_corpus
    from dampr_tpu.parallel.mesh import maybe_init_distributed

    maybe_init_distributed()
    corpus = os.path.join(BENCH_DIR, "corpus_{}mb.txt".format(args.mb))
    make_corpus(corpus, args.mb)
    size_mb = os.path.getsize(corpus) / 1e6
    log("corpus: {} ({:.1f} MB)".format(corpus, size_mb))

    spill_wall, spill_got, spill_dev = run_leg(
        corpus, "off", "device-bench-spill", args.trials)
    log("spill leg:  {:.2f}s = {:.1f} MB/s  (d2h {:.1f} MB)".format(
        spill_wall, size_mb / spill_wall,
        spill_dev["d2h_bytes"] / 1e6))

    dev_wall, dev_got, dev_dev = run_leg(
        corpus, "on", "device-bench-handoff", args.trials)
    log("device leg: {:.2f}s = {:.1f} MB/s  (d2h {:.1f} MB, "
        "avoided {:.1f} MB, edges {})".format(
            dev_wall, size_mb / dev_wall, dev_dev["d2h_bytes"] / 1e6,
            dev_dev["d2h_avoided_bytes"] / 1e6,
            dev_dev["handoff_edges"]))

    # Exactness: both legs agree with each other and the host oracle.
    assert spill_got == dev_got, (
        "handoff leg diverged from the spill leg: {} vs {} keys".format(
            len(dev_got), len(spill_got)))
    want = oracle(corpus)
    assert dev_got == want, (
        "device leg diverged from the host oracle: {} vs {} keys".format(
            len(dev_got), len(want)))
    log("verified {} doc-frequency entries exact on both legs".format(
        len(want)))

    assert dev_dev["handoff_edges"] >= 1, dev_dev
    assert dev_dev["d2h_avoided_bytes"] > 0, dev_dev

    rec = {
        "metric": "device_handoff_throughput",
        "unit": "MB/s",
        "corpus_mb": round(size_mb, 1),
        "trials": args.trials,
        "spill_wall_s": round(spill_wall, 3),
        "spill_MBps": round(size_mb / spill_wall, 2),
        "spill_d2h_bytes": spill_dev["d2h_bytes"],
        "device_wall_s": round(dev_wall, 3),
        "device_MBps": round(size_mb / dev_wall, 2),
        "device_d2h_bytes": dev_dev["d2h_bytes"],
        "d2h_avoided_bytes": dev_dev["d2h_avoided_bytes"],
        "d2h_reduction": round(
            1.0 - dev_dev["d2h_bytes"] / float(spill_dev["d2h_bytes"]), 4)
        if spill_dev["d2h_bytes"] else None,
        "handoff_edges": dev_dev["handoff_edges"],
        "handoff_bytes": dev_dev["handoff_bytes"],
        "handoff_degrades": dev_dev["handoff_degrades"],
        "speedup_vs_spill": round(spill_wall / dev_wall, 3),
        "value": round(size_mb / dev_wall, 2),
    }
    line = json.dumps(rec)
    print(line)
    if args.json:
        with open(args.json, "w") as f:
            f.write(line + "\n")
    return rec


if __name__ == "__main__":
    main()
