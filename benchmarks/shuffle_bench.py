"""ICI shuffle microbenchmark (BASELINE.md config: "shuffle all-to-all
bandwidth"): times the full mesh keyed-fold program (local segment fold ->
all_to_all -> final fold) and the ring all-reduce over the visible mesh.

On a single chip the collectives are loopback (upper bound); on a real slice
the same program measures ICI.  Run on the virtual CPU mesh for a
functional check:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/shuffle_bench.py --cpu
"""

import _pathfix  # noqa: F401  (repo root onto sys.path)

import argparse
import json
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--records", type=int, default=1 << 22)
    ap.add_argument("--keys", type=int, default=1 << 16)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--cpu", action="store_true",
                    help="force the virtual CPU mesh")
    args = ap.parse_args()

    import os

    import jax

    # honor --cpu and a JAX_PLATFORMS=cpu request even where the TPU plugin
    # programmatically overrides jax_platforms at interpreter start
    if args.cpu or "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
        jax.config.update("jax_platforms", "cpu")

    from dampr_tpu.ops import hashing
    from dampr_tpu.parallel import mesh_keyed_fold
    from dampr_tpu.parallel.mesh import data_mesh
    from dampr_tpu.parallel.ring import ring_allreduce

    mesh = data_mesh()
    n_dev = len(jax.devices())
    rng = np.random.RandomState(0)
    keys = rng.randint(0, args.keys, size=args.records)
    vals = np.ones(args.records, dtype=np.int32)
    h1, h2 = hashing.hash_keys(keys)
    payload_mb = args.records * 12 / 1e6  # h1 + h2 + v

    # warm (compile)
    mesh_keyed_fold(mesh, h1, h2, vals, "sum")
    t0 = time.time()
    for _ in range(args.iters):
        fh1, _fh2, fv = mesh_keyed_fold(mesh, h1, h2, vals, "sum")
    fold_s = (time.time() - t0) / args.iters
    assert int(fv.sum()) == args.records

    x = rng.randn(n_dev * 1024, 256).astype(np.float32)
    ring_allreduce(mesh, x)  # warm
    t0 = time.time()
    for _ in range(args.iters):
        ring_allreduce(mesh, x)
    ring_s = (time.time() - t0) / args.iters
    ring_mb = x.nbytes / 1e6

    print(json.dumps({
        "devices": n_dev,
        "keyed_fold_MBps": round(payload_mb / fold_s, 1),
        "keyed_fold_records_per_s": round(args.records / fold_s),
        "ring_allreduce_MBps": round(ring_mb / ring_s, 1),
    }))


if __name__ == "__main__":
    main()
