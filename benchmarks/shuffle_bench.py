"""ICI shuffle microbenchmark (BASELINE.md config: "shuffle all-to-all
bandwidth"): times the full mesh keyed-fold program (local segment fold ->
all_to_all -> final fold), the ring all-reduce, and the budget-scheduled
byte exchange over the visible mesh.

On a single chip the collectives are loopback (upper bound); on a real slice
the same program measures ICI.  Run on the virtual CPU mesh for a
functional check:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/shuffle_bench.py --cpu

Multi-process mode spawns N local OS processes that join one
``jax.distributed`` deployment over a localhost coordinator (gloo CPU
collectives — the same code path a TPU pod runs over DCN) and drives the
byte exchange across the process boundary:

    python benchmarks/shuffle_bench.py --cpu --mproc 2

The JSON (one line, ``metric``/``value`` keyed for tools/check_bench.py)
reports ``exchange_bytes``, ``exchange_steps``, ``peak_inflight_bytes``
(the replan cost model's per-step high-water mark — asserted under
``hbm_budget``), and MB/s.
"""

import _pathfix  # noqa: F401  (repo root onto sys.path)

import argparse
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _exchange_blobs(n_dev, mb, seed=0):
    """Synthetic routed payload: every (src, dst) pair carries an uneven
    share of ``mb`` total megabytes (dst-skewed, so schedules see mixed
    piece counts)."""
    total = int(mb * 1e6)
    rng = np.random.RandomState(seed)
    weights = rng.rand(n_dev, n_dev) + 0.1
    weights /= weights.sum()
    blobs = {}
    for s in range(n_dev):
        for d in range(n_dev):
            n = int(total * weights[s, d])
            if n:
                blobs[(s, d)] = rng.randint(
                    0, 256, size=n).astype(np.uint8).tobytes()
    return blobs


def _bench_exchange(mesh, args):
    """Time the scheduled byte exchange; returns the JSON fields."""
    from dampr_tpu import settings
    from dampr_tpu.parallel import exchange as px
    from dampr_tpu.parallel.mesh import mesh_size

    n_dev = mesh_size(mesh)
    budget = (int(args.budget_mb * 1e6) if args.budget_mb
              else settings.exchange_hbm_budget)
    blobs = _exchange_blobs(n_dev, args.exchange_mb)
    payload = sum(len(b) for b in blobs.values())
    px.mesh_blob_exchange(mesh, blobs, budget=budget)  # warm (compile)
    t0 = time.time()
    for _ in range(args.iters):
        out = px.mesh_blob_exchange(mesh, blobs, budget=budget)
    ex_s = (time.time() - t0) / args.iters
    assert sum(len(b) for b in out.values()) == payload, "exchange lost bytes"
    info = px.last_info
    return {
        "exchange_bytes": payload,
        "exchange_steps": info["steps"],
        "peak_inflight_bytes": info["peak_inflight_bytes"],
        "hbm_budget": budget,
        "budget_respected": (info["peak_inflight_bytes"] <= budget
                             and not info["clamped"]),
        "exchange_MBps": round(payload / 1e6 / ex_s, 1),
    }


def _bench_straggler(mesh, args):
    """Injected-straggler scenario (docs/robustness.md "Straggler
    mitigation"): rank ``--slow-rank`` sleeps ``--slow-ms`` at every
    collective exchange step via the fault harness, and the SAME windows
    run twice — a no-mitigation control and a mitigated pass
    (``parallel.mitigate``) — under identical fresh fault plans.  Emits
    ``mitigation_engaged``, ``speculative_wins``, ``stolen_partitions``,
    ``windows_skipped`` / down-weighting, and the mitigated-vs-not wall.
    Only meaningful under ``--mproc`` (the live skew signal crosses the
    process boundary); single-process runs record the control wall and
    an un-engaged mitigation."""
    import jax

    from dampr_tpu import faults, settings
    from dampr_tpu.parallel import exchange as px
    from dampr_tpu.parallel import mitigate
    from dampr_tpu.parallel.mesh import mesh_size

    n_dev = mesh_size(mesh)
    blobs = _exchange_blobs(n_dev, min(args.exchange_mb, 1.0), seed=1)
    payload = sum(len(b) for b in blobs.values())
    px.mesh_blob_exchange(mesh, blobs)  # warm (compile) before faults
    spec = ("exchange_step:rank={},sleep_ms={},every=1,times=100000"
            .format(args.slow_rank, args.slow_ms))
    windows = args.slow_windows

    def drive():
        t0 = time.time()
        for _ in range(windows):
            out = px.mesh_blob_exchange(mesh, blobs)
            assert out == blobs, "exchange window not byte-identical"
        return time.time() - t0

    faults.configure(spec)
    try:
        control_wall = drive()
    finally:
        faults.clear()

    # Degrade-in-place requires the bounded-collective regime: arm the
    # exchange watchdog for the mitigated pass (generous deadline — it
    # exists so a diverged skip could never hang, not to fire here).
    saved_timeout = settings.exchange_timeout_ms
    if settings.exchange_timeout_ms <= 0:
        settings.exchange_timeout_ms = 120000
    ctl = mitigate.MitigationController(run_name=None)
    mitigate.start(ctl)
    faults.configure(spec)  # fresh plan: identical injected schedule
    try:
        mitigated_wall = drive()
    finally:
        faults.clear()
        mitigate.stop(ctl)
        settings.exchange_timeout_ms = saved_timeout
    s = ctl.summary()
    if jax.process_count() <= 1:
        sys.stderr.write(
            "shuffle_bench: --slow-rank without --mproc measures the "
            "control only (the live skew signal needs >= 2 ranks)\n")
    return {
        "slow_rank": args.slow_rank,
        "slow_ms": args.slow_ms,
        "slow_windows": windows,
        "straggler_payload_bytes": payload,
        "control_wall_s": round(control_wall, 3),
        "mitigated_wall_s": round(mitigated_wall, 3),
        "mitigation_speedup": (round(control_wall / mitigated_wall, 2)
                               if mitigated_wall > 1e-9 else None),
        "mitigation_engaged": s["engagements"] >= 1,
        "mitigation_windows_skipped": s["windows_skipped"],
        "speculative_wins": s["speculative_wins"],
        "stolen_partitions": s["stolen_partitions"],
        "downweighted_ranks": s["downweighted_ranks"],
        "straggler_named": s["straggler_rank"],
        "late_ratio": s["last_late_ratio"],
    }


def _obs_export(run_name, tracer, wall_start, wall, rec):
    """Per-rank artifact export for a traced bench run: trace.json +
    a minimal stats.json (schema dampr-tpu-stats/1) under the rank's
    trace dir, carrying the exchange route matrix obs.fleet folds into
    the rank x rank send/recv matrices."""
    from dampr_tpu.obs import export as _export
    from dampr_tpu.parallel import exchange as px

    proc = _export.process_section()
    tdir = _export.run_trace_dir(run_name)
    os.makedirs(tdir, exist_ok=True)
    trace_file = _export.write_trace(
        tracer, os.path.join(tdir, _export.TRACE_FILE))
    info = px.last_info or {}
    summary = {
        "schema": _export.STATS_SCHEMA,
        "run": run_name,
        "process": proc,
        "started_at": round(wall_start, 3),
        "wall_seconds": round(wall, 4),
        "n_partitions": 0,
        "stages": [],
        # records_out stays 0: the exchange bench materializes no record
        # stream (keyed_fold_records_per_s is a RATE and must not leak
        # into a count field the fleet table renders).
        "totals": {"records_out": 0,
                   "bytes_out": rec.get("exchange_bytes", 0),
                   "spill_bytes": 0},
        "mesh": {
            "folds": 0,
            "exchanges": 1,
            "exchange_bytes": rec.get("exchange_bytes", 0),
            "exchange": {
                "bytes": rec.get("exchange_bytes", 0),
                "steps": info.get("steps", 0),
                "peak_inflight_bytes": info.get("peak_inflight_bytes", 0),
                "hbm_budget": info.get("budget", 0),
                "sent_per_device": {
                    str(k): v
                    for k, v in sorted(px.sent_bytes_per_device.items())},
                "received_per_device": {
                    str(k): v for k, v in sorted(
                        px.received_bytes_per_device.items())},
                "routes": [[s, d, n] for (s, d), n in sorted(
                    px.pair_bytes_per_route.items())],
            },
        },
        "spans": tracer.span_summary(),
        "trace_file": trace_file,
    }
    spath = os.path.join(tdir, _export.STATS_FILE)
    summary["stats_file"] = spath
    _export.write_stats(summary, spath)
    return trace_file, spath


def _run_traced(args, run_name="shuffle-bench"):
    """Run the bench under a run-scoped tracer; rank 0 of a
    multi-process deployment then merges the fleet timeline (bounded
    wait for siblings) and reports the merged trace + fleet section in
    its JSON line."""
    import time as _time

    from dampr_tpu import settings
    from dampr_tpu.obs import trace as _trace

    tracer = _trace.Tracer(run_name)
    _trace.start(tracer)
    wall_start = _time.time()
    try:
        rec = _run_single(args)
    finally:
        _trace.stop(tracer)
    wall = _time.time() - wall_start
    trace_file, stats_file = _obs_export(run_name, tracer, wall_start,
                                         wall, rec)
    rec["trace_file"] = trace_file
    rec["stats_file"] = stats_file
    from dampr_tpu.obs import export as _export

    proc = _export.process_section()
    if proc.get("num_processes", 1) > 1 and not proc.get("process_id"):
        from dampr_tpu.obs import fleet as _fleet

        section = _fleet.merge_run(run_name,
                                   wait_ms=settings.fleet_wait_ms)
        if section is not None:
            rec["fleet"] = section
    return rec


def _run_single(args):
    import jax

    from dampr_tpu.ops import hashing
    from dampr_tpu.parallel import mesh_keyed_fold
    from dampr_tpu.parallel.mesh import data_mesh, process_info
    from dampr_tpu.parallel.ring import ring_allreduce

    mesh = data_mesh()
    n_dev = len(jax.devices())
    rng = np.random.RandomState(0)
    keys = rng.randint(0, args.keys, size=args.records)
    vals = np.ones(args.records, dtype=np.int32)
    h1, h2 = hashing.hash_keys(keys)
    payload_mb = args.records * 12 / 1e6  # h1 + h2 + v

    # warm (compile)
    mesh_keyed_fold(mesh, h1, h2, vals, "sum")
    t0 = time.time()
    for _ in range(args.iters):
        fh1, _fh2, fv = mesh_keyed_fold(mesh, h1, h2, vals, "sum")
    fold_s = (time.time() - t0) / args.iters
    assert int(fv.sum()) == args.records

    rec = {
        "metric": "shuffle_exchange_MBps",
        "devices": n_dev,
        "processes": process_info()["process_count"],
        "keyed_fold_MBps": round(payload_mb / fold_s, 1),
        "keyed_fold_records_per_s": round(args.records / fold_s),
    }
    rec.update(_bench_exchange(mesh, args))
    rec["value"] = rec["exchange_MBps"]
    if args.slow_rank >= 0:
        rec.update(_bench_straggler(mesh, args))

    if jax.process_count() == 1:
        x = rng.randn(n_dev * 1024, 256).astype(np.float32)
        ring_allreduce(mesh, x)  # warm
        t0 = time.time()
        for _ in range(args.iters):
            ring_allreduce(mesh, x)
        ring_s = (time.time() - t0) / args.iters
        rec["ring_allreduce_MBps"] = round(x.nbytes / 1e6 / ring_s, 1)
    return rec


def _spawn_mproc(args):
    """Parent side of --mproc: spawn N worker ranks of this same script
    joined through a localhost coordinator; rank 0's JSON line is the
    result."""
    port = _free_port()
    env_base = dict(os.environ)
    env_base.pop("XLA_FLAGS", None)
    procs = []
    for rank in range(args.mproc):
        env = dict(env_base)
        env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=%d"
                            % args.devices_per_proc)
        env["DAMPR_TPU_COORDINATOR"] = "localhost:%d" % port
        env["DAMPR_TPU_NUM_PROCESSES"] = str(args.mproc)
        env["DAMPR_TPU_PROCESS_ID"] = str(rank)
        cmd = [sys.executable, os.path.abspath(__file__), "--worker"]
        cmd += ["--records", str(args.records), "--keys", str(args.keys),
                "--iters", str(args.iters),
                "--exchange-mb", str(args.exchange_mb),
                "--devices-per-proc", str(args.devices_per_proc)]
        if args.slow_rank >= 0:
            cmd += ["--slow-rank", str(args.slow_rank),
                    "--slow-ms", str(args.slow_ms),
                    "--slow-windows", str(args.slow_windows)]
        if args.budget_mb:
            cmd += ["--budget-mb", str(args.budget_mb)]
        if args.cpu:
            cmd.append("--cpu")
        procs.append(subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env))
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=600))
    except subprocess.TimeoutExpired:
        # A dead rank wedges its siblings in the collective — kill the
        # whole deployment rather than leaking orphans until CI times out.
        for q in procs:
            q.kill()
        raise
    failed = any(p.returncode != 0 for p in procs)
    for rank, (p, (out, err)) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            sys.stderr.write("rank %d failed:\n%s\n" % (rank, err[-4000:]))
    if failed:
        raise SystemExit(1)
    # rank 0 prints the deployment's JSON line
    line = [ln for ln in outs[0][0].splitlines() if ln.startswith("{")][-1]
    print(line)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--records", type=int, default=1 << 22)
    ap.add_argument("--keys", type=int, default=1 << 16)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--exchange-mb", type=float, default=8.0,
                    help="total payload MB for the byte-exchange phase")
    ap.add_argument("--budget-mb", type=float, default=0,
                    help="exchange HBM budget override (MB); 0 = "
                         "settings.exchange_hbm_budget")
    ap.add_argument("--slow-rank", type=int, default=-1,
                    help="inject a straggler: this process rank sleeps "
                         "--slow-ms at every collective exchange step "
                         "(fault harness), and the bench reports "
                         "mitigated-vs-not wall (-1 = off)")
    ap.add_argument("--slow-ms", type=int, default=200,
                    help="straggler stall per exchange step (ms)")
    ap.add_argument("--slow-windows", type=int, default=16,
                    help="exchange windows per straggler pass")
    ap.add_argument("--mproc", type=int, default=0,
                    help="spawn N local processes joined via "
                         "jax.distributed (gloo on CPU) and bench the "
                         "exchange across the process boundary")
    ap.add_argument("--devices-per-proc", type=int, default=4,
                    help="virtual CPU devices per spawned process")
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)  # internal: one --mproc rank
    ap.add_argument("--cpu", action="store_true",
                    help="force the virtual CPU mesh")
    args = ap.parse_args()

    if args.mproc and not args.worker:
        _spawn_mproc(args)
        return

    import jax

    # honor --cpu and a JAX_PLATFORMS=cpu request even where the TPU plugin
    # programmatically overrides jax_platforms at interpreter start
    if args.cpu or "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
        jax.config.update("jax_platforms", "cpu")

    from dampr_tpu import settings
    from dampr_tpu.parallel.mesh import maybe_init_distributed

    maybe_init_distributed()  # joins the --mproc deployment when spawned

    if settings.trace:
        rec = _run_traced(args)
    else:
        rec = _run_single(args)
    if jax.process_index() == 0:
        print(json.dumps(rec))


if __name__ == "__main__":
    main()
