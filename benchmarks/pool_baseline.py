"""Second-system TF-IDF baseline: multiprocessing.Pool Counter-merge.

The reference compares against Dask bag (benchmarks/tf-idf-dask.py) and
derives a finding from it (Dask OOMs at the 500x tier).  This offline
image has no dask wheel (VERDICT round 5, item 7), so the second system
is the stdlib's honest multi-core yardstick: split the corpus into
line-aligned byte ranges, run the reference baseline's exact per-line
Counter loop (benchmarks/baseline.py:12-24 shape) in a worker pool, and
merge the per-chunk Counters in the parent.

This is the fairest non-engine comparison on a multi-core host: same
tokenization regex, same per-line set() dedup, C-speed Counter update,
zero spill machinery — its only costs over the 1-core baseline are chunk
scheduling and the Counter merge (vocabulary-sized, 24k keys).  What it
cannot do is bound memory (every worker holds a full vocabulary Counter
and the merge holds all of them) or generalize past this one workload —
which is the point of the comparison.

    python benchmarks/pool_baseline.py --mb 2048

Prints ONE JSON line: {"metric": "tfidf_pool_baseline_throughput", ...}.
Verifies the merged result exactly against the single-core baseline's
cached Counter when one exists for the same corpus (bench_tfidf caches
it next to the corpus file).
"""

import _pathfix  # noqa: F401  (repo root onto sys.path)

import argparse
import json
import math
import multiprocessing
import os
import re
import sys
import time
from collections import Counter

from dampr_tpu.bench_tfidf import make_corpus

RX = re.compile(r"[^\w]+")


def _count_range(args):
    """Reference baseline.py's per-line loop over one byte range of the
    corpus.  Ranges are split on arbitrary byte offsets; a line is owned
    by the range containing its FIRST byte, so the worker seeks to the
    first line start at or after ``begin`` (consuming the partial line
    the previous range owns) and reads through the line straddling
    ``end``.  The loop bound is strict: a line starting exactly at
    ``end`` belongs to the next range, which lands on it via its own
    seek(begin-1)+readline."""
    path, begin, end = args
    counter = Counter()
    lines = 0
    with open(path, "rb") as f:
        if begin:
            f.seek(begin - 1)
            f.readline()  # consume the partial line the previous range owns
        while f.tell() < end:
            line = f.readline()
            if not line:
                break
            lines += 1
            counter.update(
                t for t in set(RX.split(line.decode().lower())) if t)
    return counter, lines


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=2048)
    ap.add_argument("--dir", default=os.environ.get(
        "DAMPR_BENCH_DIR", "/tmp/dampr_tpu_bench"))
    ap.add_argument("--procs", type=int, default=multiprocessing.cpu_count())
    args = ap.parse_args()

    corpus = os.path.join(args.dir, "corpus_{}mb.txt".format(args.mb))
    make_corpus(corpus, args.mb)
    size = os.path.getsize(corpus)
    size_mb = size / 1e6

    # ~4 ranges per worker bounds straggler skew without per-chunk cost
    n_chunks = max(args.procs * 4, 1)
    step = size // n_chunks + 1
    ranges = [(corpus, at, min(at + step, size))
              for at in range(0, size, step)]

    t0 = time.time()
    counter = Counter()
    total = 0
    with multiprocessing.Pool(args.procs) as pool:
        for c, n in pool.imap_unordered(_count_range, ranges):
            counter.update(c)
            total += n
    outdir = os.path.join(args.dir, "pool-idf")
    os.makedirs(outdir, exist_ok=True)
    with open(os.path.join(outdir, "out"), "w") as out:
        for word, count in counter.items():
            print("\t".join((word, str(count),
                             str(math.log(1 + float(total) / count)))),
                  file=out)
    secs = time.time() - t0
    print("pool baseline ({} procs): {:.2f}s = {:.1f} MB/s".format(
        args.procs, secs, size_mb / secs), file=sys.stderr)

    verified = False
    cache = corpus + ".baseline.pkl"
    if os.path.exists(cache):
        import pickle

        with open(cache, "rb") as f:
            _key, _secs, want_counter, want_total = pickle.load(f)
        assert total == want_total, (total, want_total)
        assert counter == want_counter, "pool merge diverged from 1-core"
        verified = True
        print("verified: merged Counter identical to 1-core baseline",
              file=sys.stderr)

    print(json.dumps({
        "metric": "tfidf_pool_baseline_throughput",
        "value": round(size_mb / secs, 2),
        "unit": "MB/s",
        "procs": args.procs,
        "corpus_mb": round(size_mb, 1),
        "verified_vs_1core": verified,
    }))


if __name__ == "__main__":
    main()
