"""External-sort benchmark (BASELINE.md config: "external sort of synthetic
records with HBM<->host spill"): globally sort synthetic records through the
engine under a deliberately tight memory budget, verify order and
completeness, and report sustained throughput plus spill counters.

    python benchmarks/sort_bench.py --mb 512 --budget-mb 64
"""

import _pathfix  # noqa: F401  (repo root onto sys.path)

import argparse
import json
import os
import sys
import time

import numpy as np


def make_records(path, mb, seed=7):
    if os.path.exists(path) and os.path.getsize(path) >= mb * 1024 ** 2:
        return
    rng = np.random.RandomState(seed)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    target = mb * 1024 ** 2
    written = 0
    with open(path, "w") as f:
        while written < target:
            ks = rng.randint(0, 1 << 62, size=50000)
            chunk = "\n".join(str(k) for k in ks) + "\n"
            f.write(chunk)
            written += len(chunk)


def lint_pipelines():
    """dampr-tpu-lint discovery hook: the external-sort pipeline shape
    (constructed over this source file; nothing runs)."""
    from dampr_tpu import Dampr
    from dampr_tpu.ops.text import ParseNumbers

    pipe = (Dampr.text(__file__, chunk_size=1024 ** 2)
            .custom_mapper(ParseNumbers())
            .checkpoint(force=True))
    return [("sort_bench", pipe)]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=256)
    ap.add_argument("--budget-mb", type=int, default=64)
    ap.add_argument("--chunk-mb", type=int, default=32,
                    help="map chunk size (smaller -> more sorted runs; "
                         "combine with DAMPR_TPU_MERGE_FANIN to force "
                         "in-run merge generations)")
    ap.add_argument("--dir", default="/tmp/dampr_tpu_bench")
    ap.add_argument("--out", default=None,
                    help="also write the sorted keys as text to this "
                         "directory (one streaming part file) — the "
                         "byte-exactness witness autotune sessions "
                         "digest between trials")
    ap.add_argument("--progress", action="store_true",
                    help="live status line while the sort runs "
                         "(settings.progress)")
    args = ap.parse_args()

    from dampr_tpu import Dampr, settings
    from dampr_tpu.runner import MTRunner

    if args.progress:
        settings.progress = True

    path = os.path.join(args.dir, "sort_records_{}mb.txt".format(args.mb))
    make_records(path, args.mb)
    size_mb = os.path.getsize(path) / 1e6
    # completeness ground truth: one record per line
    expected = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 24)
            if not chunk:
                break
            expected += chunk.count(b"\n")

    settings.max_memory_per_stage = args.budget_mb * 1024 ** 2
    # This pipeline is host-resident end-to-end (parse -> hash -> spill ->
    # merge), so memory-bound kernels win on host numpy: device dispatch only
    # pays when transfer cost is amortized by compute, which a remote-tunnel
    # TPU attachment never reaches for hashing.  (Measured 3x here.)
    settings.use_device = False

    from dampr_tpu.ops.text import ParseNumbers

    t0 = time.time()
    # Vectorized external sort: parse lines to int64 keys in C, hash-sorted
    # spill runs, bounded merge; records come back in ascending key order.
    pipe = (Dampr.text(path, chunk_size=args.chunk_mb * 1024 ** 2)
            .custom_mapper(ParseNumbers())
            .checkpoint(force=True))
    runner = MTRunner("sort-bench", pipe.pmer.graph)
    out = runner.run([pipe.source])

    # vectorized order + count verification over sorted blocks
    out_f = None
    if args.out:
        os.makedirs(args.out, exist_ok=True)
        out_f = open(os.path.join(args.out, "sorted-part-0.txt"), "w")
    n = 0
    prev = None
    for blk in out[0].sorted_blocks():
        ks = blk.keys
        assert (np.diff(ks) >= 0).all(), "order violation inside block"
        if prev is not None and len(ks) and ks[0] < prev:
            print("ORDER VIOLATION across blocks", file=sys.stderr)
            sys.exit(1)
        if len(ks):
            prev = ks[-1]
        if out_f is not None and len(ks):
            out_f.write("\n".join(map(str, ks)))
            out_f.write("\n")
        n += len(ks)
    if out_f is not None:
        out_f.close()
    secs = time.time() - t0
    if n != expected:
        print("COMPLETENESS VIOLATION: {} != {}".format(n, expected),
              file=sys.stderr)
        sys.exit(1)
    # I/O shape from the store's live counters (not run_summary: the
    # summary freezes when run() returns, and the merge-read loop above —
    # the bench's dominant read side — happens after that).
    sto = runner.store
    io = {
        "spill_write_mbps": (round(sto.spill_disk_bytes / 1e6
                                   / sto.spill_write_seconds, 2)
                             if sto.spill_write_seconds > 1e-9 else 0.0),
        "spill_read_mbps": (round(sto.spill_read_bytes / 1e6
                                  / sto.spill_read_seconds, 2)
                            if sto.spill_read_seconds > 1e-9 else 0.0),
        "io_wait_seconds": round(sto.io_wait_seconds, 4),
        "io_wait_fraction": round(sto.io_wait_seconds / secs, 4),
        "io_wait_write_fraction": round(
            sto.io_wait_write_seconds / secs, 4),
        "writer_threads": settings.spill_write_threads,
    }

    print(json.dumps({
        "metric": "external_sort_throughput",
        "value": round(size_mb / secs, 2),
        "unit": "MB/s",
        "records": n,
        "budget_mb": args.budget_mb,
        "spills": runner.store.spill_count,
        "spilled_mb": round(runner.store.spilled_bytes / 1e6, 1),
        # Spill-lean merge planning evidence: generations == 0 means the
        # final read fed straight from first-level runs (write
        # amplification ~1x); each generation past that re-spills the data
        # once through the streamed file->file merge.
        "merge_generations": runner.store.merge_gens,
        "merge_gen_mb": round(runner.store.merge_gen_bytes / 1e6, 1),
        "sorted_runs": bool(out[0].pset.key_sorted_runs),
        # Cross-check for the per-run summary (dampr_tpu.obs): the
        # per-stage spill-bytes sum must track the store's measured spill
        # volume (they are boundary snapshots of the same counter).
        "stage_spill_mb": round(sum(
            s["spill_bytes"] for s in runner.run_summary["stages"]) / 1e6,
            1) if runner.run_summary else None,
        # Async spill I/O shape (dampr_tpu.io, from RunStats "io"): disk
        # bandwidth on each side and the fold-side stall fraction — the
        # acceptance gauge for the background writer/prefetch subsystem
        # (io_wait_fraction < 0.10 means folds almost never blocked on
        # codec+disk).
        "spill_write_mbps": io.get("spill_write_mbps"),
        "spill_read_mbps": io.get("spill_read_mbps"),
        "io_wait_fraction": io.get("io_wait_fraction"),
        "io_wait_write_fraction": io.get("io_wait_write_fraction"),
        "io_wait_seconds": io.get("io_wait_seconds"),
        "spill_writer_threads": io.get("writer_threads"),
        # Device lowering (dampr_tpu.plan.lower): the external sort has
        # no keyed-fold shape, so device_stages stays 0 — pinned here so
        # the gate notices if a lowering change ever claims a sort stage.
        # (device_fraction/h2d/d2h are run-wide device counters and may be
        # nonzero on accelerator hosts via the HBM tier / sort kernels.)
        "device_fraction": (runner.run_summary or {}).get(
            "device", {}).get("device_fraction"),
        "device_stages": (runner.run_summary or {}).get(
            "device", {}).get("device_stages"),
        "h2d_bytes": (runner.run_summary or {}).get(
            "device", {}).get("h2d_bytes"),
        "d2h_bytes": (runner.run_summary or {}).get(
            "device", {}).get("d2h_bytes"),
        # Live metrics plane (dampr_tpu.obs.metrics): the sampler's
        # self-measured cost when sampling was on (acceptance gauge:
        # <3% at 100 ms cadence), None with the plane off.
        "metrics_interval_ms": settings.effective_metrics_interval_ms(),
        "sampler_overhead": ((runner.run_summary or {}).get(
            "metrics", {}).get("sampler", {}).get("overhead")),
        # Logical plan optimizer (dampr_tpu.plan): constructed vs executed
        # stage counts — fused-vs-unfused evidence for the baselines
        # (stages_before == stages_after under DAMPR_TPU_OPTIMIZE=0).
        "optimize": settings.optimize,
        "plan_stages_before": (runner.plan_report or {}).get(
            "stages_before"),
        "plan_stages_after": (runner.plan_report or {}).get("stages_after"),
        # Learned cost model (dampr_tpu.plan.model): where the sizing
        # decisions came from (model / median-fallback / static) and the
        # model's own throughput prediction — the perf gate's
        # predicted-vs-measured residual check reads these.
        "cost_source": ((runner.plan_report or {}).get("cost")
                        or {}).get("source"),
        "cost_choices_applied": sum(
            1 for c in ((runner.plan_report or {}).get("cost")
                        or {}).get("choices") or ()
            if c.get("applied")),
        "model_predicted_value": (((runner.plan_report or {}).get("cost")
                                   or {}).get("predicted")
                                  or {}).get("mbps"),
        "n_partitions": runner.n_partitions,
        "trace_file": (runner.run_summary or {}).get("trace_file"),
    }))


if __name__ == "__main__":
    main()
