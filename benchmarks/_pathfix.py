"""Make `python benchmarks/<script>.py` work from anywhere: the script's
own directory (benchmarks/) is what Python puts on sys.path, so the repo
root — where the dampr_tpu package lives — is inserted here once, and every
benchmark script just does `import _pathfix  # noqa: F401`."""

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)
