"""Sustained-load benchmark for the serve daemon (docs/serve.md).

N concurrent clients submit overlapping pipelines — V distinct suffix
variants over one shared word-count prefix — against an in-process
:class:`dampr_tpu.serve.ServeDaemon`.  Two measured legs over the SAME
submission schedule:

1. **cold** — every job submits with ``reuse="off"``: no in-flight
   coalescing, no materialization cache; this is "N independent cold
   runs" routed through the daemon's own dispatch machinery (same
   process overhead, so the comparison isolates the reuse win).
2. **served** — the daemon's native mode (``reuse="auto"`` resolves ON
   in workers): identical in-flight submissions coalesce onto one run
   and the shared prefix mounts from the cross-run cache.

Headline ``value`` is the served leg's **requests/s**; the record also
carries ``p50_s`` / ``p99_s`` request latency (lower-is-better — the
CI gate reads them with ``--metric-key p99_s --direction lower``),
the reuse hit count/rate, and ``speedup_vs_cold``.

Correctness is asserted, not sampled: every client's served records
must equal its variant's solo cache-off oracle run, and repeat
submissions of one variant must return **byte-identical** result
payloads (the daemon streams the worker's pickle verbatim).  A
violation exits non-zero — like incremental_bench, this is a
correctness witness first and a perf gate second.

    python benchmarks/serve_bench.py --mb 4 --clients 3
"""

import _pathfix  # noqa: F401  (repo root onto sys.path)

import argparse
import json
import operator
import os
import pickle
import shutil
import sys
import tempfile
import threading
import time


WORDS = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta", "theta",
         "kappa", "lambda", "sigma", "token", "frame", "spill", "merge"]


def make_corpus(d, mb, nfiles=6):
    os.makedirs(d, exist_ok=True)
    per_file = int(mb * 1024 ** 2 / nfiles)
    for i in range(nfiles):
        with open(os.path.join(d, "part-{:04d}.txt".format(i)), "w") as f:
            written, j = 0, i
            while written < per_file:
                row = " ".join(WORDS[(j + k * 3) % len(WORDS)]
                               for k in range(9))
                line = "{} doc{}\n".format(row, j % 257)
                f.write(line)
                written += len(line)
                j += 1


def build_variant(corpus_dir, variant):
    """One tenant's pipeline: the shared word-count prefix (identical
    across variants — the reusable materialization) plus a variant-
    specific suffix.  The suffix lambda's default-arg capture gives each
    variant a distinct plan fingerprint (identical submissions of ONE
    variant still coalesce)."""
    from dampr_tpu import Dampr

    counts = (Dampr.text(corpus_dir)
              .flat_map(lambda line: line.split())
              .map(lambda w: (w, 1))
              .fold_by(lambda kv: kv[0], value=lambda kv: kv[1],
                       binop=operator.add))
    return counts.map(lambda kv, v=variant: (kv[0], (v, kv[1])))


def lint_pipelines():
    """dampr-tpu-lint discovery hook (nothing runs)."""
    return [("serve_bench", build_variant(__file__, 0))]


def solo_oracle(corpus_dir, variant):
    """The variant's cache-off in-process run — the correctness bar."""
    from dampr_tpu import settings

    old = settings.reuse
    settings.reuse = "off"
    try:
        em = build_variant(corpus_dir, variant).run(
            name="serve-bench-oracle-{}".format(variant))
        return sorted(em.dataset.read())
    finally:
        settings.reuse = old


def run_leg(client_cls, url, corpus_dir, schedule, reuse, timeout_s):
    """Execute one submission schedule: ``schedule`` is a list of
    (client_index, variant) pairs per client thread.  Returns
    (wall_seconds, per-request latencies, rows, payload bytes by job)."""
    latencies = []
    rows = {}
    payloads = {}
    errors = []
    lock = threading.Lock()

    def one_client(ci, variants):
        client = client_cls(url)
        for v in variants:
            plan = build_variant(corpus_dir, v)
            t0 = time.time()
            try:
                job = client.submit(plan, tenant="tenant-{}".format(ci),
                                    reuse=reuse)
                row = job.wait(timeout_s=timeout_s)
                body = job.result_bytes(timeout_s=timeout_s)
            except Exception as e:
                with lock:
                    errors.append("client {} variant {}: {}".format(
                        ci, v, e))
                return
            dt = time.time() - t0
            with lock:
                latencies.append(dt)
                rows[job.id] = row
                payloads.setdefault(v, []).append(body)

    threads = [threading.Thread(target=one_client, args=(ci, variants))
               for ci, variants in enumerate(schedule)]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.time() - t0
    if errors:
        raise RuntimeError("; ".join(errors))
    return wall, latencies, rows, payloads


def percentile(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--mb", type=float, default=4.0,
                    help="corpus size in MB (default 4)")
    ap.add_argument("--clients", type=int, default=3,
                    help="concurrent client threads (default 3)")
    ap.add_argument("--jobs-per-client", type=int, default=3,
                    help="submissions per client (default 3)")
    ap.add_argument("--variants", type=int, default=None,
                    help="distinct pipeline suffixes (default: clients)")
    ap.add_argument("--workers", type=int, default=2,
                    help="daemon worker slots (default 2)")
    ap.add_argument("--timeout", type=float, default=300.0,
                    help="per-request wait deadline seconds")
    args = ap.parse_args()

    tmp = tempfile.mkdtemp(prefix="dampr-serve-bench-")
    os.environ["DAMPR_TPU_SCRATCH"] = os.path.join(tmp, "scratch")
    from dampr_tpu import settings

    settings.scratch_root = os.path.join(tmp, "scratch")
    from dampr_tpu.serve.client import ServeClient
    from dampr_tpu.serve.daemon import ServeDaemon

    corpus = os.path.join(tmp, "corpus")
    make_corpus(corpus, args.mb)
    nvariants = args.variants or args.clients
    # Client i's schedule rotates through the variant set, so variants
    # overlap across clients (the service's whole premise) and repeat
    # within the run (coalesce + identical-rerun hits).
    schedule = [[(ci + j) % nvariants for j in range(args.jobs_per_client)]
                for ci in range(args.clients)]
    total_jobs = args.clients * args.jobs_per_client

    oracles = {v: solo_oracle(corpus, v) for v in range(nvariants)}

    daemon = ServeDaemon(port=0, workers=args.workers,
                         state_dir=os.path.join(tmp, "serve"))
    if daemon.start() is None:
        print("serve_bench: daemon bind failed", file=sys.stderr)
        return 2
    url = "http://127.0.0.1:{}".format(daemon.port)
    try:
        cold_wall, cold_lat, _rows, _payloads = run_leg(
            ServeClient, url, corpus, schedule, "off", args.timeout)
        wall, lat, rows, payloads = run_leg(
            ServeClient, url, corpus, schedule, "auto", args.timeout)
    finally:
        daemon.stop()

    # Correctness gate 1: served records match each variant's solo
    # cache-off oracle.  Gate 2: repeat submissions of one variant got
    # byte-identical payloads (verbatim-stream contract).
    for v, bodies in payloads.items():
        got = sorted(pickle.loads(bodies[0]))
        if got != oracles[v]:
            print("serve_bench: FAIL: variant {} served records diverge "
                  "from the solo oracle".format(v), file=sys.stderr)
            return 1
        if any(b != bodies[0] for b in bodies[1:]):
            print("serve_bench: FAIL: variant {} repeat submissions "
                  "returned non-identical payload bytes".format(v),
                  file=sys.stderr)
            return 1

    reuse_hits = sum(r.get("reuse_hits") or 0 for r in rows.values())
    coalesced = sum(1 for r in rows.values()
                    if r.get("state") == "done" and r.get("primary"))
    shutil.rmtree(tmp, ignore_errors=True)
    print(json.dumps({
        "metric": "serve-sustained",
        # Headline: served-leg sustained throughput (higher is better;
        # the same record also gates p99_s with --direction lower).
        "value": round(total_jobs / wall, 4),
        "direction": "higher",
        "requests_per_s": round(total_jobs / wall, 4),
        "p50_s": round(percentile(lat, 0.50), 4),
        "p99_s": round(percentile(lat, 0.99), 4),
        "cold_requests_per_s": round(total_jobs / cold_wall, 4),
        "cold_p50_s": round(percentile(cold_lat, 0.50), 4),
        "cold_p99_s": round(percentile(cold_lat, 0.99), 4),
        "speedup_vs_cold": round(cold_wall / wall, 3),
        "reuse_hits": reuse_hits,
        "reuse_hit_rate": round(reuse_hits / float(total_jobs), 3),
        "coalesced_jobs": coalesced,
        "clients": args.clients,
        "jobs_per_client": args.jobs_per_client,
        "variants": nvariants,
        "workers": args.workers,
        "corpus_mb": args.mb,
        "total_jobs": total_jobs,
        "wall_seconds": round(wall, 3),
        "cold_wall_seconds": round(cold_wall, 3),
        "byte_exact": True,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
