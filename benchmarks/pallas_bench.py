"""Real-TPU benchmark for both Pallas kernels vs their XLA counterparts,
settling the perf claims with measurements (VERDICT r2 task 5):

1. ``ops.pallas_fnv.fnv_pallas`` (VMEM-resident dual-lane FNV byte scan)
   vs the portable ``ops.hashing._fnv_jit`` fori-loop kernel, on a padded
   token matrix generated on-device.
2. ``ops.pallas_segfold.segfold_sorted`` (fused post-sort segmented fold)
   vs the XLA scan chain in ``parallel.shuffle._local_fold`` — both run on
   the same pre-sorted data; the comparison isolates the post-sort chain.

Timing is amortized inside one jitted fori_loop per measurement (the
remote-tunnel dispatch here costs ~65 ms per call), with a checksum
accumulated so nothing is dead code.  Each kernel's outputs are first
verified against the XLA/host reference for the same inputs.

    python benchmarks/pallas_bench.py [--iters 20]
"""

import _pathfix  # noqa: F401  (repo root onto sys.path)

import argparse
import json
import time

import numpy as np


def bench_fnv(iters):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from dampr_tpu.ops.hashing import _fnv_jit
    from dampr_tpu.ops.pallas_fnv import _ROW_TILE, _build, fnv_pallas

    n, L = 1 << 17, 16  # 128k tokens, 16-byte pad bucket (typical words)
    assert n % _ROW_TILE == 0

    def gen(seed):
        key = jax.random.PRNGKey(seed)
        mat = jax.random.randint(key, (n, L), 97, 123, dtype=jnp.int32
                                 ).astype(jnp.uint8)
        lens = jax.random.randint(jax.random.fold_in(key, 1), (n,), 1, L,
                                  dtype=jnp.int32)
        return mat, lens

    # device-side pallas entry: same layout prep as fnv_pallas's host
    # wrapper, but traced, so the timed loop never leaves the chip
    pallas_run = _build(L, False)

    def pallas_dev(m, l):
        h1, h2 = pallas_run(m.T.astype(jnp.int32), l.reshape(1, n))
        return (h1.reshape(n).view(jnp.uint32),
                h2.reshape(n).view(jnp.uint32))

    # verify the host wrapper AND the exact device entry the loop times
    mat, lens = gen(0)
    a1, a2 = _fnv_jit()(mat, lens)
    b1, b2 = fnv_pallas(np.asarray(mat), np.asarray(lens))
    assert (np.asarray(a1) == np.asarray(b1)).all()
    assert (np.asarray(a2) == np.asarray(b2)).all()
    d1, d2 = jax.jit(pallas_dev)(mat, lens)
    assert (np.asarray(a1) == np.asarray(d1)).all()
    assert (np.asarray(a2) == np.asarray(d2)).all()

    results = {}
    checks = {}
    for name, fn in (("xla", lambda m, l: _fnv_jit()(m, l)),
                     ("pallas", pallas_dev)):
        def loop(seed0, fn=fn):
            def body(i, acc):
                m, l = gen(seed0 + i)
                h1, h2 = fn(m, l)
                return acc ^ h1[0] ^ h2[-1]

            return lax.fori_loop(0, iters, body, jnp.uint32(0))

        jl = jax.jit(loop)
        checks[name] = int(jax.device_get(jl(0)))
        t0 = time.time()
        jax.device_get(jl(100))
        results[name] = (time.time() - t0) / iters
    # same seeds, same hash definition: the warmup checksums must agree
    assert checks["xla"] == checks["pallas"], checks
    return {
        "tokens": n,
        "xla_Mtok_s": round(n / results["xla"] / 1e6, 1),
        "pallas_Mtok_s": round(n / results["pallas"] / 1e6, 1),
        "pallas_speedup": round(results["xla"] / results["pallas"], 2),
    }


def bench_segfold(iters, n=1 << 22, interpret=None):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from dampr_tpu.ops import pallas_segfold as SF
    from dampr_tpu.parallel.shuffle import _local_fold

    if interpret is None:
        # Mosaic compiles only for TPU; everywhere else the kernel runs
        # (and is measured) in interpreter mode — a functional number,
        # not a hardware one, but it finally gets the kernel on a
        # measured path (CI runs this tiny).
        interpret = jax.default_backend() != "tpu"

    def gen_sorted(seed):
        key = jax.random.PRNGKey(seed)
        ids = jax.random.randint(key, (n,), 0, 1 << 16, dtype=jnp.int32)
        h1 = jnp.sort(ids.astype(jnp.uint32) * jnp.uint32(0x9E3779B9))
        h2 = h1 ^ jnp.uint32(0x85EBCA6B)
        v = jnp.ones((n,), jnp.int32)
        inv = jnp.zeros((n,), jnp.uint32)
        return h1, h2, v, inv

    # verify parity once (totals per (h1,h2) against the XLA scan chain)
    h1, h2, v, inv = gen_sorted(0)
    oinv, oh1, oh2, ov = _local_fold(inv, h1, h2, v, "sum", nonneg_sum=True)
    tot, live = SF.segfold_sorted(np.asarray(h1), np.asarray(h2),
                                  np.asarray(v), np.asarray(inv),
                                  interpret=interpret)
    want = {}
    m = np.asarray(oinv) == 0
    for a, b, t in zip(np.asarray(oh1)[m], np.asarray(oh2)[m],
                       np.asarray(ov)[m]):
        want[(int(a), int(b))] = int(t)
    got = {}
    lm = np.asarray(live) == 1
    ah1, ah2, at = np.asarray(h1)[lm], np.asarray(h2)[lm], np.asarray(tot)[lm]
    for a, b, t in zip(ah1, ah2, at):
        got[(int(a), int(b))] = int(t)
    assert got == want, "pallas segfold diverged from the XLA scan chain"

    from dampr_tpu.parallel.shuffle import _scan_fold_sorted

    def xla_chain(h1, h2, v, inv):
        # post-sort chain only — inputs are pre-sorted, same as pallas
        return _scan_fold_sorted(inv, h1, h2, v)[3][0]

    te = SF._tile_elems()
    n_tiles = n // te

    def pallas_chain(h1, h2, v, inv):
        shape = (n_tiles * SF._ROWS, SF._LANES)
        tot, live = SF._segfold_call(n_tiles, interpret)(
            h1.reshape(shape), h2.reshape(shape), v.reshape(shape),
            inv.reshape(shape))
        return tot[0, 0]

    results = {}
    checks = {}
    for name, fn in (("xla_scan", xla_chain), ("pallas", pallas_chain)):
        def loop(seed0, fn=fn):
            def body(i, acc):
                h1, h2, v, inv = gen_sorted(seed0 + i)
                return acc + fn(h1, h2, v, inv).astype(jnp.int32)

            return lax.fori_loop(0, iters, body, jnp.int32(0))

        jl = jax.jit(loop)
        checks[name] = int(jax.device_get(jl(0)))
        t0 = time.time()
        jax.device_get(jl(100))
        results[name] = (time.time() - t0) / iters
    # both chains define tot identically (segment totals at end positions),
    # so the warmup checksums over identical seeds must agree
    assert checks["xla_scan"] == checks["pallas"], checks
    return {
        "records": n,
        "interpret": bool(interpret),
        # 3 decimals: interpret-mode runs at tiny --records on slow CI
        # boxes must not round a real (correct) measurement down to 0.0
        "xla_scan_Mrec_s": round(n / results["xla_scan"] / 1e6, 3),
        "pallas_Mrec_s": round(n / results["pallas"] / 1e6, 3),
        "pallas_speedup": round(results["xla_scan"] / results["pallas"], 2),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--records", type=int, default=1 << 22,
                    help="segfold record count (multiple of the tile size)")
    ap.add_argument("--only", choices=["fnv", "segfold"])
    ap.add_argument("--interpret", action="store_true",
                    help="force Pallas interpreter mode (default: auto — "
                    "interpreted everywhere but TPU)")
    args = ap.parse_args()

    import jax

    # One JSON line per section, flushed immediately: a flaky accelerator
    # tunnel can kill the later (bigger) section without losing the first.
    base = {"metric": "pallas_vs_xla", "backend": jax.default_backend()}
    if args.only in (None, "fnv"):
        r = dict(base, kernel="fnv", **bench_fnv(args.iters))
        print(json.dumps(r), flush=True)
    if args.only in (None, "segfold"):
        r = dict(base, kernel="segfold",
                 **bench_segfold(args.iters, args.records,
                                 interpret=args.interpret or None))
        print(json.dumps(r), flush=True)


if __name__ == "__main__":
    main()
