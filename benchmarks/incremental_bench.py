"""Cross-run reuse benchmark (docs/reuse.md): cold run vs identical
re-run vs +10% appended corpus, with byte-identity asserted against a
cache-off oracle at every leg.

Three legs over one word-frequency pipeline (the TF-IDF document-
frequency stage shape):

1. **cold** — empty cache; every stage executes and publishes.
2. **identical** — same corpus; the whole chain should mount from the
   cache (the headline number: ``identical_rerun_speedup``).
3. **append** — ~``--append-fraction`` new files; the scan stage reruns
   only the delta and merges partials with the cached frames
   (associativity certified by ``analyze/assoc``), then is compared
   against a cold cache-off run of the appended corpus.

Byte-identity violations exit non-zero — this bench is a correctness
witness first and a perf gate second.

    python benchmarks/incremental_bench.py --mb 8
"""

import _pathfix  # noqa: F401  (repo root onto sys.path)

import argparse
import hashlib
import json
import operator
import os
import shutil
import sys
import tempfile
import time


WORDS = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta", "theta",
         "kappa", "lambda", "sigma", "token", "frame", "spill", "merge"]


def make_corpus(d, mb, nfiles=8, offset=0):
    """Deterministic text corpus split over ``nfiles`` files; ``offset``
    shifts the word schedule so appended files carry fresh content."""
    os.makedirs(d, exist_ok=True)
    per_file = int(mb * 1024 ** 2 / nfiles)
    paths = []
    for i in range(nfiles):
        path = os.path.join(d, "part-{:04d}.txt".format(offset + i))
        paths.append(path)
        with open(path, "w") as f:
            written = 0
            j = offset * 1000 + i
            while written < per_file:
                row = " ".join(WORDS[(j + k * 3) % len(WORDS)]
                               for k in range(9))
                line = "{} doc{}\n".format(row, j % 257)
                f.write(line)
                written += len(line)
                j += 1
    return paths


def build(corpus_dir):
    from dampr_tpu import Dampr
    from dampr_tpu.ops.text import DocFreq

    return (Dampr.text(corpus_dir)
            .custom_mapper(DocFreq(mode="word", lower=True))
            .fold_by(lambda kv: kv[0], operator.add, lambda kv: kv[1]))


def lint_pipelines():
    """dampr-tpu-lint discovery hook: the bench's pipeline shape
    (constructed over this source file; nothing runs)."""
    return [("incremental_bench", build(__file__))]


def run_leg(corpus_dir, name):
    t0 = time.time()
    out = build(corpus_dir).run(name)
    rows = sorted(out.stream())
    secs = time.time() - t0
    digest = hashlib.sha256(
        "\n".join(repr(r) for r in rows).encode()).hexdigest()
    return secs, digest, (out.stats() or {}).get("reuse") or {}


def oracle(corpus_dir, name):
    """Cache-off cold run: the byte-identity reference."""
    from dampr_tpu import settings

    old = settings.reuse
    settings.reuse = "off"
    try:
        return run_leg(corpus_dir, name)
    finally:
        settings.reuse = old


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=float, default=8.0,
                    help="corpus size in MB (pre-append)")
    ap.add_argument("--append-fraction", type=float, default=0.10)
    ap.add_argument("--dir", default=None,
                    help="working dir (default: fresh tempdir, removed "
                         "on exit)")
    args = ap.parse_args()

    from dampr_tpu import settings

    work = args.dir or tempfile.mkdtemp(prefix="dampr-incr-bench-")
    corpus = os.path.join(work, "corpus")
    shutil.rmtree(corpus, ignore_errors=True)
    # Fresh cache + scratch per invocation: the bench MEASURES the cold
    # leg, so a warm shared cache would corrupt it.  plan_adapt off so
    # the identical leg keys identically to the cold leg (history-driven
    # option changes legitimately shift the reuse key).
    settings.scratch_root = os.path.join(work, "scratch")
    settings.reuse_dir = os.path.join(work, "reuse-cache")
    settings.reuse = "on"
    settings.plan_adapt = False

    nfiles = 10
    make_corpus(corpus, args.mb, nfiles=nfiles)

    cold_s, cold_d, cold_ru = run_leg(corpus, "incr-bench")
    warm_s, warm_d, warm_ru = run_leg(corpus, "incr-bench")
    if warm_d != cold_d:
        print("BYTE-IDENTITY VIOLATION: identical re-run diverged",
              file=sys.stderr)
        sys.exit(1)
    if not warm_ru.get("hits"):
        print("REUSE MISS: identical re-run took no cache hits: {}"
              .format(warm_ru), file=sys.stderr)
        sys.exit(1)

    n_append = max(1, int(round(nfiles * args.append_fraction)))
    make_corpus(corpus, args.mb * args.append_fraction,
                nfiles=n_append, offset=nfiles)
    incr_s, incr_d, incr_ru = run_leg(corpus, "incr-bench")
    oracle_s, oracle_d, _ = oracle(corpus, "incr-bench-oracle")
    if incr_d != oracle_d:
        print("BYTE-IDENTITY VIOLATION: incremental run diverged from "
              "the cold oracle", file=sys.stderr)
        sys.exit(1)

    decided = len(warm_ru.get("decisions") or ()) or 1
    print(json.dumps({
        "metric": "identical_rerun_speedup",
        "value": round(cold_s / warm_s, 2) if warm_s > 1e-9 else 0.0,
        "unit": "x",
        "corpus_mb": args.mb,
        "append_fraction": args.append_fraction,
        "wall_cold_seconds": round(cold_s, 3),
        "wall_identical_seconds": round(warm_s, 3),
        "wall_incremental_seconds": round(incr_s, 3),
        "wall_appended_cold_seconds": round(oracle_s, 3),
        "incremental_vs_cold_fraction": (
            round(incr_s / oracle_s, 3) if oracle_s > 1e-9 else 0.0),
        "reuse_hit_fraction": round(
            (warm_ru.get("hits") or 0) / decided, 3),
        "identical_hits": warm_ru.get("hits"),
        "identical_bytes_mounted": warm_ru.get("bytes_mounted"),
        "incremental_merges": incr_ru.get("incremental_merges"),
        "cold_bytes_published": cold_ru.get("bytes_published"),
        "byte_identical": True,
        "digest": cold_d,
    }))
    if args.dir is None:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
