"""Device-resident keyed-fold microbenchmark: the engine's real local-fold
kernel (`dampr_tpu.parallel.shuffle._local_fold` — dual hash lanes ->
lexsort -> segmented fold) as one jitted program whose inputs are generated
on-device — no host transfer in the timed loop.  This measures what the TPU
compute path sustains when data lives in HBM, separating kernel throughput
from this environment's slow host<->device tunnel (which bench.py's
host-path numbers include).

Two lowerings are timed (see _local_fold):

- ``scan``: the nonneg-sum scan fold (cumsum + cummax carry, no scatter) —
  the count/len/doc-freq hot path;
- ``scatter``: the segment_sum lowering (general sums, min/max).

Timing is amortized: the kernel runs ``--iters`` times inside one jitted
``fori_loop`` (fresh threefry data each iteration, results folded into a
checksum), so the per-dispatch tunnel latency (~65 ms here) is paid once
per measurement, not per iteration.

Verification: one un-looped invocation's folded per-key counts are fetched
and compared exactly against a host-side np.bincount of the identical
(threefry-deterministic) id sequence.

    python benchmarks/device_fold_bench.py [--records 2**22] [--keys 65536]
"""

import _pathfix  # noqa: F401  (repo root onto sys.path)

import argparse
import functools
import json
import time

import numpy as np


def _gen(seed, n, n_keys):
    import jax
    import jax.numpy as jnp

    from dampr_tpu.ops.hashing import _mix_int_jit

    key = jax.random.PRNGKey(seed)
    ids = jax.random.randint(key, (n,), 0, n_keys, dtype=jnp.int32)
    # the engine's own dual-lane integer mix (ops/hashing._mix_int_jit)
    h1, h2 = _mix_int_jit()(ids.astype(jnp.uint32), jnp.zeros((n,),
                                                             jnp.uint32))
    vals = jnp.ones((n,), dtype=jnp.int32)
    return ids, h1, h2, vals


@functools.lru_cache(maxsize=None)
def _build_once(n, n_keys, nonneg):
    """One un-looped fold returning full arrays for exact verification."""
    import jax
    import jax.numpy as jnp

    from dampr_tpu.parallel.shuffle import _local_fold

    def program(seed):
        ids, h1, h2, vals = _gen(seed, n, n_keys)
        inv = jnp.zeros((n,), dtype=jnp.uint32)
        oinv, fh1, fh2, fv = _local_fold(inv, h1, h2, vals, "sum", nonneg)
        return oinv, fh1, fh2, fv

    return jax.jit(program)


@functools.lru_cache(maxsize=None)
def _build_loop(n, n_keys, iters, nonneg):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from dampr_tpu.parallel.shuffle import _local_fold

    def loop(seed0):
        def body(i, acc):
            ids, h1, h2, vals = _gen(seed0 + i, n, n_keys)
            inv = jnp.zeros((n,), dtype=jnp.uint32)
            oinv, fh1, fh2, fv = _local_fold(inv, h1, h2, vals, "sum",
                                             nonneg)
            return acc ^ fh1[0] ^ fv[-1].astype(jnp.uint32)

        return lax.fori_loop(0, iters, body, jnp.uint32(0))

    return jax.jit(loop)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--records", type=int, default=1 << 22)
    ap.add_argument("--keys", type=int, default=1 << 16)
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()

    import jax

    results = {}
    for mode, nonneg in (("scan", True), ("scatter", False)):
        # exact verification of this lowering: fold results map back to
        # ids through the (host-mirrored) hash lanes — each distinct key
        # must appear exactly once with its exact count
        oinv, fh1, fh2, fv = _build_once(args.records, args.keys, nonneg)(0)
        host_ids = np.asarray(jax.device_get(
            _gen(0, args.records, args.keys)[0]))
        want = np.bincount(host_ids, minlength=args.keys)

        # host mirror of the device-side dual-lane mix — the engine's own
        # numpy kernel, so the verification cannot drift from the hash
        from dampr_tpu.ops.hashing import _mix_int_numpy
        kh1, kh2 = _mix_int_numpy(np.arange(args.keys, dtype=np.int64))
        id_of = {(int(a), int(b)): k for k, (a, b) in
                 enumerate(zip(kh1, kh2))}
        live = np.asarray(oinv) == 0
        got = np.zeros(args.keys, dtype=np.int64)
        f = np.asarray(fv)
        a1 = np.asarray(fh1)
        a2 = np.asarray(fh2)
        for i in np.flatnonzero(live):
            got[id_of[(int(a1[i]), int(a2[i]))]] += f[i]
        assert (got == want).all(), (
            "device fold (%s) diverged from host bincount" % mode)

        prog = _build_loop(args.records, args.keys, args.iters, nonneg)
        jax.device_get(prog(0))  # warm: compile + first run
        t0 = time.time()
        jax.device_get(prog(100))
        secs = (time.time() - t0) / args.iters
        results[mode] = secs

    print(json.dumps({
        "metric": "device_keyed_fold",
        "backend": jax.default_backend(),
        "records": args.records,
        "distinct_keys": args.keys,
        "records_per_s_scan": round(args.records / results["scan"]),
        "records_per_s_scatter": round(args.records / results["scatter"]),
        "GBps_payload_scan": round(
            args.records * 8 / results["scan"] / 1e9, 2),
        "speedup_scan_vs_scatter": round(
            results["scatter"] / results["scan"], 2),
        "verified": True,
    }))


if __name__ == "__main__":
    main()
