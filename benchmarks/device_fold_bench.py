"""Device-resident keyed-fold microbenchmark: the engine's core aggregation
shape (dual-lane hash mix -> lexsort by both lanes -> segment fold) as ONE
jitted program whose inputs are generated on-device — no host transfer in the
timed loop.  This measures what the TPU compute path sustains when data lives
in HBM, separating kernel throughput from this environment's slow
host<->device tunnel (which bench.py's host-path numbers include).

Verification: the folded per-key counts for the warm-up seed are fetched once
and compared exactly against a host-side np.bincount of the identical
(threefry-deterministic) id sequence.

    python benchmarks/device_fold_bench.py [--records 2**22] [--keys 65536]
"""

import argparse
import functools
import json
import time

import numpy as np


@functools.lru_cache(maxsize=None)
def _build(n, n_keys):
    import jax
    import jax.numpy as jnp
    from jax import lax

    def fmix(x, y):
        h = x ^ y
        h = (h ^ (h >> 16)) * jnp.uint32(0x85EBCA6B)
        h = (h ^ (h >> 13)) * jnp.uint32(0xC2B2AE35)
        return h ^ (h >> 16)

    def program(seed):
        key = jax.random.PRNGKey(seed)
        ids = jax.random.randint(key, (n,), 0, n_keys, dtype=jnp.int32)
        vals = jnp.ones((n,), dtype=jnp.int32)
        # the engine's dual independent lanes (ops/hashing.py _mix_int_jit)
        lo = ids.astype(jnp.uint32)
        hi = jnp.zeros_like(lo)
        h1 = fmix(lo ^ jnp.uint32(0x9E3779B9), hi)
        h2 = fmix(lo ^ jnp.uint32(0x85EBCA6B), hi ^ jnp.uint32(0xC2B2AE35))
        sh1, sh2, sv, sids = lax.sort((h1, h2, vals, ids), num_keys=2)
        iota = jnp.arange(n, dtype=jnp.int32)
        starts = jnp.where(
            iota == 0, True,
            (sh1 != jnp.roll(sh1, 1)) | (sh2 != jnp.roll(sh2, 1)))
        seg = jnp.cumsum(starts.astype(jnp.int32)) - 1
        # fold counts per segment and remember each segment's original id so
        # the host can verify the grouping, not just a conserved total
        folded = jax.ops.segment_sum(sv, seg, num_segments=n_keys * 2)
        seg_ids = jax.ops.segment_max(sids, seg, num_segments=n_keys * 2,
                                      indices_are_sorted=False)
        live = jax.ops.segment_sum(jnp.ones_like(sv), seg,
                                   num_segments=n_keys * 2) > 0
        return folded, seg_ids, live

    return jax.jit(program)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--records", type=int, default=1 << 22)
    ap.add_argument("--keys", type=int, default=1 << 16)
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    import jax

    prog = _build(args.records, args.keys)

    # warm-up + exact verification against host ground truth
    folded, seg_ids, live = prog(0)
    host_ids = np.asarray(
        jax.device_get(jax.random.randint(
            jax.random.PRNGKey(0), (args.records,), 0, args.keys,
            dtype=np.int32)))
    want = np.bincount(host_ids, minlength=args.keys)
    got = np.zeros(args.keys, dtype=np.int64)
    f = np.asarray(folded)
    s = np.asarray(seg_ids)
    lv = np.asarray(live)
    for i in np.flatnonzero(lv):
        got[s[i]] += f[i]
    assert (got == want).all(), "device fold diverged from host bincount"
    n_distinct = int(lv.sum())

    t0 = time.time()
    out = None
    for i in range(args.iters):
        out = prog(i + 1)
    jax.block_until_ready(out)
    secs = (time.time() - t0) / args.iters

    print(json.dumps({
        "metric": "device_keyed_fold",
        "backend": jax.default_backend(),
        "records": args.records,
        "records_per_s": round(args.records / secs),
        "GBps_payload": round(args.records * 8 / secs / 1e9, 2),  # 4B id + 4B value
        "distinct_keys": n_distinct,
        "verified": True,
    }))


if __name__ == "__main__":
    main()
