"""Live straggler mitigation on a REAL 2-process gloo deployment
(docs/robustness.md "Straggler mitigation"): rank 1 is injected slow at
every collective exchange step for a bounded window
(``exchange_step:rank=1,sleep_ms=...,duration_ms=...`` — the faults
grammar's windowed slowness), and the contract holds end to end:

- the controller ENGAGES after ``speculate_after_steps`` consecutive
  late windows (entry times shared on the piggyback all_gather, aligned
  on the ``mesh.clock_sync`` barrier clock);
- engaged windows are degraded in place (skipped) with probe windows on
  the configured cadence, every delivered window byte-identical to its
  input (the host oracle — the exchange is a placement transport);
- once the slow window expires the probes turn healthy and the
  mitigation DISENGAGES cleanly; collectives resume;
- both ranks' controllers traverse the identical state machine (the
  shared-observation invariant that keeps skip decisions collective-
  safe).
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SLOW_MS = 250
DURATION_MS = 2500

_WORKER = textwrap.dedent("""
    import json, os, sys, time
    pid = int(sys.argv[1]); port = sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, @ROOT@)
    import numpy as np
    from dampr_tpu import settings, faults
    settings.scratch_root = os.path.join(
        os.environ["MIT_SCRATCH"], "rank%d" % pid)
    from dampr_tpu.parallel.mesh import init_distributed, data_mesh
    init_distributed(coordinator_address="localhost:%s" % port,
                     num_processes=2, process_id=pid)
    assert jax.process_count() == 2 and len(jax.devices()) == 8

    from dampr_tpu.parallel import exchange as px
    from dampr_tpu.parallel import mitigate

    mesh = data_mesh()
    rng = np.random.RandomState(0)
    blobs = {(s, d): rng.randint(0, 256, size=2048).astype(
                 np.uint8).tobytes()
             for s in range(8) for d in range(8) if s != d}

    # Warm the collective programs BEFORE arming the slow site, so
    # compile time never counts as lateness.
    out = px.mesh_blob_exchange(mesh, blobs)
    assert out == blobs, "warmup exchange not byte-identical"

    # Window skipping requires the bounded-collective regime: arm the
    # exchange watchdog (generous — nothing should ever hit it here).
    settings.exchange_timeout_ms = 60000
    ctl = mitigate.MitigationController(
        run_name="mitmp", threshold=1.5, after=2, probe_every=2)
    assert ctl.skip_safe
    mitigate.start(ctl)
    faults.configure(
        "exchange_step:rank=1,sleep_ms=@SLOW_MS@,every=1,"
        "duration_ms=@DURATION_MS@,times=1000")

    engaged_seen = False
    skipped_while_slow = 0
    for w in range(60):
        out = px.mesh_blob_exchange(mesh, blobs)
        assert out == blobs, "window %d not byte-identical" % w
        if ctl.engaged:
            engaged_seen = True
        if px.last_info.get("skipped"):
            skipped_while_slow += 1
        # Deterministic early exit: controller state is shared, so both
        # ranks take the same branch (a one-sided exit would wedge the
        # next collective forever).
        if ctl.disengagements >= 1 and w >= 6:
            break
    # Post-recovery: two more windows cross the mesh normally.
    for _ in range(2):
        out = px.mesh_blob_exchange(mesh, blobs)
        assert out == blobs
        assert not px.last_info.get("skipped")

    s = ctl.summary()
    s["engaged_seen"] = engaged_seen
    s["skipped_windows_seen"] = skipped_while_slow
    mitigate.stop(ctl)
    print("MITSUMMARY " + json.dumps(s, sort_keys=True), flush=True)
""").replace("@ROOT@", repr(ROOT)).replace(
    "@SLOW_MS@", str(SLOW_MS)).replace(
    "@DURATION_MS@", str(DURATION_MS))


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestLiveMitigation2Proc:
    def test_engage_skip_probe_disengage_byte_identical(self, tmp_path):
        port = _free_port()
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["MIT_SCRATCH"] = str(tmp_path / "scratch")
        script = str(tmp_path / "worker.py")
        with open(script, "w") as f:
            f.write(_WORKER)
        procs = [
            subprocess.Popen(
                [sys.executable, script, str(i), str(port)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, env=env)
            for i in range(2)]
        outs = []
        try:
            for p in procs:
                outs.append(p.communicate(timeout=240))
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        for rank, (p, (out, err)) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, (rank, out[-2000:], err[-4000:])
        summaries = []
        for rank, (out, _err) in enumerate(outs):
            lines = [ln for ln in out.splitlines()
                     if ln.startswith("MITSUMMARY ")]
            assert lines, (rank, out[-2000:])
            summaries.append(json.loads(lines[-1].split(" ", 1)[1]))
        for rank, s in enumerate(summaries):
            assert s["engaged_seen"], (rank, s)
            assert s["engagements"] >= 1, (rank, s)
            assert s["disengagements"] >= 1, (rank, s)
            assert s["windows_skipped"] >= 1, (rank, s)
            assert s["straggler_rank"] == 1, (rank, s)
            assert not s["engaged"], (rank, s)  # ended disengaged
        # Shared-observation invariant: both ranks' controllers walked
        # the identical state machine.
        keys = ("engagements", "disengagements", "windows_skipped",
                "observations", "straggler_rank")
        assert ({k: summaries[0][k] for k in keys}
                == {k: summaries[1][k] for k in keys}), summaries
