"""Diagnosis layer: critical-path analysis (dampr_tpu.obs.critpath),
run-history corpus (obs.history) + corpus-driven cost adaptation
equivalence pins, and the dampr-tpu-doctor CLI (report shape, schema
validity, suggestion knobs, --diff)."""

import importlib.util
import json
import operator
import os

import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.obs import critpath, doctor, export, history
from dampr_tpu.ops.devtime import union_seconds
from dampr_tpu.plan import cost

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


validate_doctor = _load_tool("validate_doctor")

with open(os.path.join(ROOT, "docs", "doctor_schema.json")) as _f:
    DOCTOR_SCHEMA = json.load(_f)


@pytest.fixture
def diagnosed(tmp_path):
    """Tracing + isolated scratch (history corpus is per scratch root)."""
    old = (settings.trace, settings.trace_dir, settings.scratch_root)
    settings.trace = True
    settings.trace_dir = str(tmp_path / "traces")
    settings.scratch_root = str(tmp_path / "scratch")
    yield tmp_path
    settings.trace, settings.trace_dir, settings.scratch_root = old


def _corpus(tmp_path, lines=6000):
    path = tmp_path / "corpus.txt"
    words = ["alpha", "beta", "gamma", "delta", "tok7", "zz", "mu", "xi"]
    with open(path, "w") as f:
        for i in range(lines):
            f.write(" ".join(words[(i + j) % len(words)]
                             for j in range(9)) + "\n")
    return str(path)


def _tfidf_run(tmp_path, name="doc-tfidf"):
    import math

    docs = Dampr.text(_corpus(tmp_path), 1 << 17)
    from dampr_tpu.ops.text import DocFreq

    df = (docs.custom_mapper(DocFreq(mode="word", lower=True))
          .fold_by(lambda kv: kv[0], operator.add, lambda kv: kv[1]))
    idf = df.cross_right(
        docs.len(),
        lambda d, total: (d[0], d[1], math.log(1 + float(total) / d[1])),
        memory=True)
    return idf.run(name)


class TestUnionSeconds:
    def test_disjoint_overlap_nested(self):
        assert union_seconds([]) == 0.0
        assert union_seconds([(0, 1), (2, 3)]) == 2.0
        assert union_seconds([(0, 2), (1, 3)]) == 3.0
        assert union_seconds([(0, 10), (2, 3), (4, 5)]) == 10.0
        # degenerate/reversed intervals contribute nothing
        assert union_seconds([(1, 1), (3, 2)]) == 0.0

    def test_never_exceeds_span(self):
        import random

        rng = random.Random(7)
        iv = [(a, a + rng.random())
              for a in (rng.random() * 10 for _ in range(50))]
        u = union_seconds(iv)
        lo = min(a for a, _ in iv)
        hi = max(b for _, b in iv)
        assert 0 <= u <= hi - lo + 1e-9


class TestCritpath:
    def test_synthetic_span_verdicts(self):
        """Hand-built events: stage 0 is codec-bound (two concurrent
        codec lanes must union, not sum), stage 1 is spill-queue-bound
        through the io_wait writer-backpressure spans."""
        ev = [
            ("stage", "s0:map", 0.0, 10.0, "stages", None),
            # two overlapping codec lanes: union 8s of 10s wall
            ("codec", "codec-window", 0.0, 6.0, 1, None),
            ("codec", "codec-window", 2.0, 6.0, 2, None),
            ("fold", "partial-fold", 6.0, 1.0, 1, None),
            ("stage", "s1:reduce", 10.0, 5.0, "stages", None),
            ("io_wait", "writer-backpressure", 10.5, 4.0, 3, None),
        ]
        section = critpath.analyze({"wall_seconds": 15.0}, ev)
        assert section["source"] == "spans"
        by = {s["stage"]: s for s in section["stages"]}
        assert by[0]["verdict"] == "codec"
        assert abs(by[0]["fractions"]["codec"] - 0.8) < 0.01
        assert by[1]["verdict"] == "spill-queue"
        assert section["run"]["verdict"] == "codec"

    def test_unattributed_wall_is_host_compute(self):
        ev = [("stage", "s2:map", 0.0, 4.0, "stages", None),
              ("codec", "w", 0.0, 0.5, 1, None)]
        section = critpath.analyze({"wall_seconds": 4.0}, ev)
        st = section["stages"][0]
        assert st["verdict"] == "host-compute"
        assert st["fractions"]["host-compute"] > 0.8

    def test_persisted_trace_events_accepted(self):
        """Chrome-format dict events (microseconds) normalize the same
        as live tuples."""
        ev = [{"ph": "X", "cat": "stage", "name": "s0:map",
               "ts": 0, "dur": 2e6},
              {"ph": "X", "cat": "merge", "name": "gen",
               "ts": 0, "dur": 1.5e6},
              {"ph": "i", "cat": "retry", "name": "x", "ts": 5}]
        section = critpath.analyze({"wall_seconds": 2.0}, ev)
        assert section["stages"][0]["verdict"] == "merge"

    def test_summary_only_degrades(self):
        section = critpath.analyze({
            "wall_seconds": 10.0,
            "devtime": {"codec_wait": 3.0},
            "io": {"io_wait_fraction": 0.1, "io_wait_write_fraction": 0.1},
            "device": {"device_fraction": 0.0},
            "stages": [{"stage": 1, "kind": "map", "seconds": 9.0,
                        "target": "host"}],
        }, events=None)
        assert section["source"] == "summary"
        assert section["run"]["verdict"] == "host-compute"
        assert section["run"]["fractions"]["overlap-stall"] == 0.3

    def test_traced_run_names_verdict_per_stage(self, diagnosed,
                                                tmp_path):
        """Acceptance shape: on a traced TF-IDF run every executed stage
        gets a named verdict and the dominant map stage's attribution
        is span-backed."""
        em = _tfidf_run(tmp_path)
        section = em.stats()["critpath"]
        assert section["source"] == "spans"
        stages = em.stats()["stages"]
        assert len(section["stages"]) == len(stages)
        for s in section["stages"]:
            assert s["verdict"], s
        heavy = max(section["stages"], key=lambda s: s["seconds"])
        if heavy["seconds"] > 0.05:
            # span-backed attribution on a meaningful window (sub-ms
            # stages are all fixed overhead and legitimately read as
            # host-compute)
            assert heavy["attributed_fraction"] > 0.3, heavy
        assert section["run"]["verdict"], section["run"]
        em.delete()


class TestHistoryCorpus:
    def _summary(self, run="h-run", wall=2.0, bytes_in=1 << 20,
                 shapes=None):
        return {
            "run": run, "started_at": 1.0, "wall_seconds": wall,
            "n_partitions": 8,
            "stages": [{"stage": 1, "kind": "map", "target": "host",
                        "jobs": 2, "records_in": 10, "records_out": 100,
                        "bytes_in": bytes_in, "bytes_out": 2 * bytes_in,
                        "spill_bytes": 0, "seconds": wall / 2}],
            "totals": {"records_out": 100, "bytes_out": 2 * bytes_in,
                       "spill_bytes": 0},
            "plan": {"stage_shapes": shapes or [
                {"sid": 1, "shape": "map:DocFreq"}]},
        }

    def test_append_load_roundtrip(self, diagnosed):
        path = history.append(self._summary())
        assert path and os.path.isfile(path)
        recs = history.load("h-run")
        assert len(recs) == 1
        rec = recs[0]
        assert rec["schema"] == history.SCHEMA
        assert rec["stages"][0]["bytes_in"] == 1 << 20
        assert rec["fingerprint"] == history.plan_fingerprint(
            rec["stage_shapes"])
        assert rec["settings"]["partitions"] == settings.partitions

    def test_corrupt_lines_skipped(self, diagnosed):
        path = history.append(self._summary())
        with open(path, "a") as f:
            f.write("{not json\n")
            f.write(json.dumps({"schema": "other/1", "stages": []}) + "\n")
        history.append(self._summary(wall=3.0))
        recs = history.load("h-run")
        assert len(recs) == 2
        assert [r["wall_seconds"] for r in recs] == [2.0, 3.0]

    def test_bounded(self, diagnosed, monkeypatch):
        monkeypatch.setattr(settings, "history_entries", 4)
        for i in range(9):
            history.append(self._summary(wall=float(i)))
        recs = history.load("h-run")
        assert len(recs) == 4
        assert [r["wall_seconds"] for r in recs] == [5.0, 6.0, 7.0, 8.0]

    def test_disabled_by_zero(self, diagnosed, monkeypatch):
        monkeypatch.setattr(settings, "history_entries", 0)
        assert history.append(self._summary()) is None
        assert history.load("h-run") == []

    def test_matching_filters_by_shape(self, diagnosed):
        history.append(self._summary())
        history.append(self._summary(
            shapes=[{"sid": 1, "shape": "map:Other"}]))
        recs = history.load("h-run")
        assert len(recs) == 2
        m = history.matching(recs, [{"sid": 1, "shape": "map:DocFreq"}])
        assert len(m) == 1

    def test_synthesize_single_is_verbatim_median_at_three(self,
                                                           diagnosed):
        """<3 records: newest verbatim (the old single-stats behavior);
        >=3: per-stage medians."""
        r1 = history.compact_record(self._summary(bytes_in=100))
        r2 = history.compact_record(self._summary(bytes_in=900))
        r3 = history.compact_record(self._summary(bytes_in=300))
        one = history.synthesize([r1])
        assert one["stages"][0]["bytes_in"] == 100
        assert one["history_entries"] == 1
        two = history.synthesize([r1, r2])
        assert two["stages"][0]["bytes_in"] == 900  # newest, not mean
        three = history.synthesize([r1, r2, r3])
        assert three["stages"][0]["bytes_in"] == 300  # median of 100/900/300
        assert three["history_entries"] == 3


class TestCorpusDrivenAdaptation:
    def test_single_entry_reproduces_stats_behavior(self, diagnosed,
                                                    tmp_path):
        """Equivalence pin: with exactly one corpus entry, the history
        fed to adaptation carries the same per-stage measurements the
        stats.json path would have provided — decision-for-decision
        identical inputs."""
        em = (Dampr.memory(list(range(4096)))
              .map(lambda x: (x % 7, 1))
              .fold_by(lambda kv: kv[0], binop=operator.add,
                       value=lambda kv: kv[1])
              .run("adapt-pin"))
        em.delete()
        assert len(history.load("adapt-pin")) == 1
        stats_summary, _ = export.load_stats("adapt-pin")
        assert stats_summary is not None

        class G(object):
            stages = []

        # same graph shapes as the recorded run: reuse the recorded ones
        rec_shapes = history.load("adapt-pin")[0]["stage_shapes"]
        from dampr_tpu.plan import ir as plan_ir

        real_shapes = plan_ir.stage_shapes
        try:
            plan_ir.stage_shapes = lambda g: rec_shapes
            hist, reason = cost.corpus_history("adapt-pin", G())
        finally:
            plan_ir.stage_shapes = real_shapes
        assert reason is None and hist is not None
        by_corpus = {s["stage"]: s for s in hist["stages"]}
        by_stats = {s["stage"]: s for s in stats_summary["stages"]}
        assert set(by_corpus) == set(by_stats)
        for sid, st in by_stats.items():
            for field in ("records_in", "records_out", "bytes_in",
                          "bytes_out"):
                assert by_corpus[sid][field] == st[field], (sid, field)

    def test_shape_mismatch_reason(self, diagnosed):
        history.append({
            "run": "adapt-mm", "started_at": 1.0, "wall_seconds": 1.0,
            "n_partitions": 4,
            "stages": [{"stage": 1, "kind": "map", "seconds": 1.0}],
            "totals": {},
            "plan": {"stage_shapes": [{"sid": 1, "shape": "map:X"}]},
        })

        class G(object):
            stages = []

        hist, reason = cost.corpus_history("adapt-mm", G())
        assert hist is None and reason == "shape-mismatch"

    def test_no_history_reason(self, diagnosed):
        class G(object):
            stages = []

        hist, reason = cost.corpus_history("never-ran", G())
        assert hist is None and reason == "no-history"


class TestDoctor:
    def test_playbook_knobs_exist(self):
        """Every suggestion in the taxonomy names a real settings
        attribute (the acceptance bar: suggestions are actionable)."""
        for verdict, entries in doctor._PLAYBOOK.items():
            for knob, _env, _prop, why in entries:
                assert hasattr(settings, knob), (verdict, knob)
                assert why

    def test_exchange_bound_verdict_maps_to_budget_knobs(self):
        """An exchange-bound run (the `mesh` critpath verdict) must point
        at the chunked-schedule knobs: the HBM budget first, then the
        explicit chunk size (docs/parallel.md decision table)."""
        knobs = [k for k, _e, _p, _w in doctor._PLAYBOOK["mesh"]]
        assert knobs[0] == "exchange_hbm_budget"
        assert "exchange_chunk_bytes" in knobs
        sugs = doctor._suggestions_for("mesh", {}, run_settings={
            "exchange_hbm_budget": 64 * 1024 ** 2})
        by_knob = {s["setting"]: s for s in sugs}
        assert by_knob["exchange_hbm_budget"]["suggested"] == 128 * 1024 ** 2
        assert by_knob["exchange_hbm_budget"]["env"] == \
            "DAMPR_TPU_EXCHANGE_HBM"

    def test_diagnose_traced_run_schema_valid(self, diagnosed, tmp_path):
        em = _tfidf_run(tmp_path, name="doc-run")
        em.delete()
        report = doctor.diagnose("doc-run")
        errors = validate_doctor.validate(report, DOCTOR_SCHEMA)
        assert errors == [], errors
        assert report["bottleneck"]
        assert report["stages"]
        # >=1 actionable suggestion whose knob exists (acceptance)
        suggestions = [s for f in report["findings"]
                       for s in f["suggestions"]]
        assert suggestions
        for s in suggestions:
            assert hasattr(settings, s["setting"]), s
        # human rendering never crashes and names the bottleneck
        text = doctor.format_report(report)
        assert report["bottleneck"] in text

    def test_findings_ranked_by_impact(self, diagnosed, tmp_path):
        em = _tfidf_run(tmp_path, name="doc-rank")
        em.delete()
        report = doctor.diagnose("doc-rank")
        impacts = [f["impact_seconds"] for f in report["findings"]]
        assert impacts == sorted(impacts, reverse=True)
        assert [f["rank"] for f in report["findings"]] == list(
            range(1, len(impacts) + 1))

    def test_suggestions_use_run_settings_not_process(self):
        """'current -> suggested' is computed from the DIAGNOSED run's
        recorded knobs, not whatever the doctor process happens to have
        (a doctor on another machine must not advise below the value
        that was already the bottleneck)."""
        rs = {"spill_write_threads": 8}
        sugg = doctor._suggestions_for("spill-queue", {}, run_settings=rs)
        by = {s["setting"]: s for s in sugg}
        assert by["spill_write_threads"]["current"] == 8
        assert by["spill_write_threads"]["suggested"] == 16

    def test_run_settings_sources(self):
        summary = {"io": {"writer_threads": 5, "read_prefetch": 7},
                   "overlap": {"windows": 9},
                   "metrics": {"sampler": {"interval_ms": 250}}}
        hist = [{"settings": {"spill_write_threads": 1, "partitions": 32}}]
        rs = doctor._run_settings(summary, hist)
        # summary-sourced values beat the corpus snapshot
        assert rs["spill_write_threads"] == 5
        assert rs["spill_read_prefetch"] == 7
        assert rs["overlap_windows"] == 9
        assert rs["metrics_interval_ms"] == 250
        assert rs["partitions"] == 32

    def test_threadseconds_impact_clamped_to_wall(self, diagnosed,
                                                  tmp_path):
        """io_wait_write_seconds is thread-seconds and can exceed run
        wall; the run-level finding's impact must stay on the wall
        axis the stage findings rank on."""
        stats = {
            "schema": "dampr-tpu-stats/1", "run": "clamp-run",
            "wall_seconds": 10.0, "stages": [],
            "io": {"io_wait_write_fraction": 1.6,
                   "io_wait_write_seconds": 16.0,
                   "io_wait_fraction": 1.6},
            "devtime": {}, "overlap": {}, "device": {},
        }
        p = tmp_path / "stats.json"
        with open(p, "w") as f:
            json.dump(stats, f)
        rep = doctor.diagnose(str(p))
        f = [x for x in rep["findings"]
             if x["bottleneck"] == "spill-queue"]
        assert f, rep["findings"]
        assert f[0]["impact_seconds"] <= 10.0
        assert "thread-seconds" in f[0]["evidence"]

    def test_no_duplicate_runlevel_findings(self, diagnosed, tmp_path):
        """A per-stage verdict and its run-level mirror are ONE root
        cause: run-level spill-queue/overlap-stall findings are
        suppressed when a stage already names them."""
        em = _tfidf_run(tmp_path, name="doc-dedup")
        em.delete()
        rep = doctor.diagnose("doc-dedup")
        staged = {f["bottleneck"] for f in rep["findings"]
                  if f["stage"] is not None}
        runlevel = [f["bottleneck"] for f in rep["findings"]
                    if f["stage"] is None and f["bottleneck"] !=
                    "host-compute"]
        assert not (staged & set(runlevel)), rep["findings"]

    def test_missing_run_raises(self, diagnosed):
        with pytest.raises(doctor.DoctorError):
            doctor.diagnose("no-such-run")

    def test_cli_exit_codes(self, diagnosed, tmp_path, capsys):
        assert doctor.main(["no-such-run"]) == 2
        em = _tfidf_run(tmp_path, name="doc-cli")
        em.delete()
        assert doctor.main(["doc-cli"]) == 0
        out = capsys.readouterr().out
        assert "bottleneck" in out
        assert doctor.main(["doc-cli", "--json"]) == 0
        out = capsys.readouterr().out
        report = json.loads(out)
        assert report["schema"] == doctor.SCHEMA

    def test_diff(self, diagnosed, tmp_path, capsys):
        em = _tfidf_run(tmp_path, name="diff-a")
        em.delete()
        em = _tfidf_run(tmp_path, name="diff-b")
        em.delete()
        report = doctor.diff("diff-a", "diff-b")
        errors = validate_doctor.validate(report, DOCTOR_SCHEMA)
        assert errors == [], errors
        d = report["diff"]
        assert d["run_a"] == "diff-a" and d["run_b"] == "diff-b"
        assert d["stages"]
        # same settings both runs -> no recorded delta
        assert d["settings_delta"] == {}
        text = doctor.format_report(report)
        assert "diff-a" in text and "diff-b" in text
        assert doctor.main(["--diff", "diff-a", "diff-b"]) == 0
        capsys.readouterr()

    def test_diff_surfaces_settings_change(self, diagnosed, tmp_path,
                                           monkeypatch):
        em = _tfidf_run(tmp_path, name="diff-s1")
        em.delete()
        old = settings.overlap_windows
        monkeypatch.setattr(settings, "overlap_windows", old + 5)
        em = _tfidf_run(tmp_path, name="diff-s2")
        em.delete()
        report = doctor.diff("diff-s1", "diff-s2")
        delta = report["diff"]["settings_delta"]
        assert delta.get("overlap_windows") == {"a": old, "b": old + 5}
