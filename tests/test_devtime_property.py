"""Property tests for devtime's codec_wait interval union (ops/devtime.py).

The bucket is defined as the WALL-CLOCK union of intervals during which
every live overlap slot is simultaneously stalled on its codec.  Under
arbitrary multithreaded enter/stall/unstall/exit churn that definition
implies two machine-checkable invariants:

- **wall bound**: the union of sub-intervals of [t0, t1] can never exceed
  t1 - t0;
- **monotonicity**: the bucket is cumulative, so successive snapshots
  never decrease (snapshot() folds the open interval in).

Plus the pinned ``reset()`` contract: resetting while the all-stalled
interval is OPEN restarts that interval at the reset point — the bucket
afterwards counts only post-reset stall time.
"""

import random
import threading
import time

from dampr_tpu.ops import devtime


def _churn(seed, iters=120):
    """One slot's randomized lifecycle: enter, a random stall/unstall
    dance with tiny sleeps, exit.  All operations correctly paired."""
    rng = random.Random(seed)
    devtime.slot_enter()
    try:
        for _ in range(iters):
            if rng.random() < 0.6:
                devtime.slot_stall()
                if rng.random() < 0.5:
                    time.sleep(rng.random() * 0.002)
                devtime.slot_unstall()
            else:
                time.sleep(rng.random() * 0.001)
    finally:
        devtime.slot_exit()


class TestCodecWaitUnion:
    def test_never_exceeds_wall_and_monotone(self):
        devtime.reset()
        t0 = time.perf_counter()
        threads = [threading.Thread(target=_churn, args=(seed,))
                   for seed in range(6)]
        for t in threads:
            t.start()
        prev = 0.0
        snaps = 0
        while any(t.is_alive() for t in threads):
            cur = devtime.snapshot()["codec_wait"]
            wall = time.perf_counter() - t0
            assert cur <= wall + 1e-3, (cur, wall)
            assert cur >= prev - 1e-9, "codec_wait went backwards"
            prev = cur
            snaps += 1
            time.sleep(0.001)
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        final = devtime.snapshot()["codec_wait"]
        assert final <= wall + 1e-3
        assert final >= prev - 1e-9
        assert snaps > 5, "churn finished before sampling anything"
        devtime.reset()

    def test_all_stalled_interval_accumulates(self):
        """One slot, stalled: the union interval is open and grows."""
        devtime.reset()
        devtime.slot_enter()
        devtime.slot_stall()
        try:
            time.sleep(0.02)
            got = devtime.snapshot()["codec_wait"]
            assert got >= 0.015, got
        finally:
            devtime.slot_unstall()
            devtime.slot_exit()
        closed = devtime.snapshot()["codec_wait"]
        time.sleep(0.005)
        assert devtime.snapshot()["codec_wait"] == closed, (
            "bucket must stop accumulating once the slot unstalls")
        devtime.reset()

    def test_partial_stall_does_not_count(self):
        """Two live slots, one stalled: NOT all-stalled, no accumulation."""
        devtime.reset()
        devtime.slot_enter()
        devtime.slot_enter()
        devtime.slot_stall()
        try:
            time.sleep(0.01)
            assert devtime.snapshot()["codec_wait"] == 0.0
        finally:
            devtime.slot_unstall()
            devtime.slot_exit()
            devtime.slot_exit()
        devtime.reset()

    def test_reset_restarts_open_interval(self):
        """Pinned: reset() during an OPEN all-stalled interval zeroes the
        bucket and restarts the interval at the reset point."""
        devtime.reset()
        devtime.slot_enter()
        devtime.slot_stall()
        try:
            time.sleep(0.02)  # pre-reset stall time, must be discarded
            devtime.reset()
            t0 = time.perf_counter()
            time.sleep(0.02)
            got = devtime.snapshot()["codec_wait"]
            elapsed = time.perf_counter() - t0
            assert got <= elapsed + 1e-3, (got, elapsed)
            assert got >= 0.015, (
                "post-reset stall time must still accumulate: %r" % got)
        finally:
            devtime.slot_unstall()
            devtime.slot_exit()
        devtime.reset()


class TestEpochDelta:
    def test_delta_is_run_scoped(self):
        """epoch()/delta() reads do not require (or perform) a reset, so
        they cannot clobber a concurrent reader's counters."""
        devtime.reset()
        devtime.add("device", 1.0)
        outer = devtime.epoch()
        devtime.add("device", 0.25)
        devtime.add("codec", 0.5)
        inner = devtime.epoch()
        devtime.add("codec", 0.125)
        d_inner = devtime.delta(inner)
        assert abs(d_inner["codec"] - 0.125) < 1e-9
        assert d_inner["device"] == 0.0
        d_outer = devtime.delta(outer)
        assert abs(d_outer["device"] - 0.25) < 1e-9
        assert abs(d_outer["codec"] - 0.625) < 1e-9
        # absolute counters still carry the pre-epoch history
        assert abs(devtime.snapshot()["device"] - 1.25) < 1e-9
        devtime.reset()

    def test_delta_clamps_after_interleaved_reset(self):
        devtime.reset()
        devtime.add("transfer", 2.0)
        ep = devtime.epoch()
        devtime.reset()  # a legacy caller clobbers the counters
        devtime.add("transfer", 0.5)
        d = devtime.delta(ep)
        assert d["transfer"] == 0.0  # clamped, never negative
        devtime.reset()
