"""Pallas FNV kernel: exact equality with the scalar/numpy definition
(interpreter mode on the CPU rig; the compiled kernel runs on real TPUs)."""

import numpy as np

from dampr_tpu.ops import hashing
from dampr_tpu.ops.pallas_fnv import fnv_pallas

from conftest import reference_text


class TestPallasFNV:
    def test_matches_numpy_on_words(self):
        words = (reference_text() * 3).split()
        mat, lens = hashing.encode_str_keys(words)
        w1, w2 = hashing._fnv_numpy(mat, lens)
        p1, p2 = fnv_pallas(mat, lens, interpret=True)
        np.testing.assert_array_equal(w1, p1)
        np.testing.assert_array_equal(w2, p2)

    def test_high_bytes_and_empty(self):
        keys = ["", "é" * 20, "\xff\x80 mixed", "plain"]
        mat, lens = hashing.encode_str_keys(keys)
        w1, w2 = hashing._fnv_numpy(mat, lens)
        p1, p2 = fnv_pallas(mat, lens, interpret=True)
        np.testing.assert_array_equal(w1, p1)
        np.testing.assert_array_equal(w2, p2)

    def test_row_padding_boundaries(self):
        # row counts straddling the tile size
        for n in (1, 511, 512, 513):
            keys = ["k%d" % i for i in range(n)]
            mat, lens = hashing.encode_str_keys(keys)
            w1, w2 = hashing._fnv_numpy(mat, lens)
            p1, p2 = fnv_pallas(mat, lens, interpret=True)
            np.testing.assert_array_equal(w1, p1)
            np.testing.assert_array_equal(w2, p2)
