"""Seeded property tests: randomly composed DSL pipelines vs a pure-Python
oracle evaluating the same semantics (SURVEY §4: deterministic-seed property
tests — pipeline result == pure-Python reference semantics).

Each case builds a random chain of map/filter/flat_map/fold/group/sort ops
over random data, runs it through the real engine (8-device CPU mesh, mesh
paths in auto mode), and compares against a list-based evaluator applying
the documented semantics of each op.
"""

import random

import pytest

from dampr_tpu import Dampr, settings


@pytest.fixture(autouse=True)
def small_partitions():
    old = (settings.partitions, settings.mesh_fold, settings.mesh_exchange)
    settings.partitions = 8
    settings.mesh_fold = "auto"
    settings.mesh_exchange = "auto"  # mesh paths engage on the 8-dev rig
    yield
    (settings.partitions, settings.mesh_fold,
     settings.mesh_exchange) = old


def _gen_data(rng):
    kind = rng.choice(["int", "str", "mixed", "float"])
    n = rng.randrange(0, 400)
    if kind == "int":
        return [rng.randrange(-1000, 1000) for _ in range(n)]
    if kind == "str":
        return ["w%d" % rng.randrange(50) for _ in range(n)]
    if kind == "float":
        return [round(rng.uniform(-10, 10), 3) for _ in range(n)]
    return [rng.choice([rng.randrange(100), "s%d" % rng.randrange(20)])
            for _ in range(n)]


# Each op: (applies_to_kind_check, engine_fn, oracle_fn, terminal?)

def _op_map(rng):
    c = rng.randrange(1, 5)
    return (lambda p: p.map(lambda x, c=c: (x, c)),
            lambda xs: [(x, c) for x in xs], False)


def _op_stringify(rng):
    return (lambda p: p.map(lambda x: str(x)),
            lambda xs: [str(x) for x in xs], False)


def _op_filter(rng):
    m = rng.randrange(2, 5)
    return (lambda p: p.filter(lambda x, m=m: hash(str(x)) % m != 0),
            lambda xs: [x for x in xs if hash(str(x)) % m != 0], False)


def _op_flat_map(rng):
    k = rng.randrange(0, 3)
    return (lambda p: p.flat_map(lambda x, k=k: [x] * k),
            lambda xs: [x for x in xs for _ in range(k)], False)


def _op_count(rng):
    return (lambda p: p.count(lambda x: str(x)[:2]),
            lambda xs: sorted(_count(xs).items()), True)


def _count(xs):
    d = {}
    for x in xs:
        k = str(x)[:2]
        d[k] = d.get(k, 0) + 1
    return d


def _op_fold_min(rng):
    return (lambda p: p.a_group_by(lambda x: str(x)[:1],
                                   lambda x: str(x)).reduce(min),
            lambda xs: sorted(_fold(xs, min).items()), True)


def _fold(xs, op):
    d = {}
    for x in xs:
        k = str(x)[:1]
        v = str(x)
        d[k] = v if k not in d else op(d[k], v)
    return d


def _op_group_reduce(rng):
    return (lambda p: p.group_by(lambda x: str(x)[:1])
            .reduce(lambda k, vs: sorted(str(v) for v in vs)[:3]),
            lambda xs: sorted(_group3(xs).items()), True)


def _group3(xs):
    d = {}
    for x in xs:
        d.setdefault(str(x)[:1], []).append(x)
    # a group reduce's emitted value is (k, reducer_result)
    return {k: sorted(str(v) for v in vs)[:3] for k, vs in d.items()}


def _op_sort(rng):
    return (lambda p: p.map(lambda x: str(x)).sort_by(lambda x: x),
            lambda xs: sorted(str(x) for x in xs), True)


def _op_len(rng):
    return (lambda p: p.len(), lambda xs: [len(xs)], True)


def _op_topk(rng):
    k = rng.randrange(1, 6)
    return (lambda p: p.map(lambda x: len(str(x))).topk(k),
            lambda xs: sorted((len(str(x)) for x in xs), reverse=True)[:k],
            True)


def _op_mean(rng):
    def orc(xs):
        if not xs:
            return []
        vs = [len(str(x)) for x in xs]
        d = {}
        for v in vs:
            s, c = d.get(v % 3, (0, 0))
            d[v % 3] = (s + v, c + 1)
        return sorted((k, s / float(c)) for k, (s, c) in d.items())

    return (lambda p: p.map(lambda x: len(str(x)))
            .mean(lambda v: v % 3, lambda v: v), orc, True)


def _op_join(rng):
    def eng(p):
        left = p.group_by(lambda x: str(x)[:1])
        right = (p.map(lambda x: str(x))
                 .group_by(lambda s: s[:1]))
        return left.join(right).reduce(
            lambda l, r: (len(list(l)), len(list(r))))

    def orc(xs):
        lg, rg = {}, {}
        for x in xs:
            lg.setdefault(str(x)[:1], []).append(x)
        for x in xs:
            rg.setdefault(str(x)[:1], []).append(str(x))
        return sorted((k, (len(lg[k]), len(rg[k])))
                      for k in set(lg) & set(rg))

    return (eng, orc, True)


_CHAIN_OPS = [_op_map, _op_stringify, _op_filter, _op_flat_map]
_TERMINALS = [_op_count, _op_fold_min, _op_group_reduce, _op_sort, _op_len,
              _op_topk, _op_mean, _op_join]


def _run_case(seed, budget=None):
    rng = random.Random(seed)
    data = _gen_data(rng)
    pipe = Dampr.memory(list(data), partitions=rng.choice([2, 5, 8]))
    oracle = list(data)
    for _ in range(rng.randrange(0, 4)):
        eng, orc, _t = rng.choice(_CHAIN_OPS)(rng)
        pipe = eng(pipe)
        oracle = orc(oracle)
    eng, orc, _t = rng.choice(_TERMINALS)(rng)
    pipe = eng(pipe)
    want = orc(oracle)

    kwargs = {} if budget is None else {"memory_budget": budget}
    got = list(pipe.run("prop-%d" % seed, **kwargs).read())
    return got, want


class TestRandomPipelines:
    @pytest.mark.parametrize("seed", range(60))
    def test_random_pipeline_matches_oracle(self, seed):
        got, want = _run_case(seed)
        # terminal outputs: count/fold/group emit (k, v) values keyed by k;
        # sort/len emit plain values.  Compare as sorted collections.
        assert sorted(map(repr, got)) == sorted(map(repr, want)), seed

    @pytest.mark.parametrize("seed", range(0, 60, 2))
    def test_random_pipeline_tiny_budget(self, seed):
        # A 16KB budget forces spills, streamed merges, and windowed
        # exchanges through the same random pipelines; results must not
        # change by a byte.
        got, want = _run_case(seed, budget=1 << 14)
        assert sorted(map(repr, got)) == sorted(map(repr, want)), seed
