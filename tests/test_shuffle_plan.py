"""The plan layer's host-vs-mesh shuffle routing: cost.shuffle_choice
decisions, plan-report/explain() visibility, the runner's target-aware
redistribution dispatch, and the stats()["mesh"]["exchange"] section."""

import uuid

import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.plan import cost, lower as plan_lower
from dampr_tpu.runner import MTRunner, _exchange_mesh_gate


@pytest.fixture(autouse=True)
def shuffle_env():
    old = (settings.partitions, settings.mesh_fold, settings.mesh_exchange,
           settings.exchange_min_bytes)
    settings.partitions = 8
    settings.mesh_fold = "off"
    settings.mesh_exchange = "auto"
    yield
    (settings.partitions, settings.mesh_fold, settings.mesh_exchange,
     settings.exchange_min_bytes) = old


def _salt(prefix):
    return "%s-%s" % (prefix, uuid.uuid4().hex[:8])


class TestShuffleChoice:
    def test_explicit_modes_win(self):
        t, r = cost.shuffle_choice(None, 8, 8, mode="off")
        assert t == "host" and "mesh_exchange" in r
        t, r = cost.shuffle_choice(
            {"bytes_in": 10}, 8, 8, mode="on")
        assert t == "mesh" and "forces" in r

    def test_single_device_stays_host(self):
        t, r = cost.shuffle_choice(None, 1, 8, mode="auto")
        assert t == "host" and "single" in r

    def test_no_history_defaults_mesh(self):
        t, r = cost.shuffle_choice(None, 8, 8, mode="auto")
        assert t == "mesh" and "no shuffle history" in r

    def test_tiny_history_pins_host(self):
        st = {"bytes_in": settings.exchange_min_bytes - 1}
        t, r = cost.shuffle_choice(st, 8, 8, mode="auto")
        assert t == "host" and "exchange_min_bytes" in r

    def test_large_history_rides_mesh_with_evidence(self):
        st = {"bytes_in": 64 * 1024 ** 2, "records_in": 1 << 20}
        t, r = cost.shuffle_choice(st, 8, 32, mode="auto")
        assert t == "mesh"
        # the reason carries the evidence: bytes, record size, partitions
        assert "B/record" in r and "32 partitions" in r
        assert str(settings.exchange_hbm_budget) in r


class TestGateTargets:
    def test_host_target_declines_in_auto(self):
        assert _exchange_mesh_gate(1 << 20, target="host") is None

    def test_mesh_target_engages(self):
        assert _exchange_mesh_gate(1 << 20, target="mesh") is not None

    def test_explicit_off_beats_mesh_target(self):
        settings.mesh_exchange = "off"
        assert _exchange_mesh_gate(1 << 20, target="mesh") is None


class TestPlanReportAndDispatch:
    def _pipe(self, n=3000):
        return (Dampr.memory([(i % 7, i) for i in range(n)], partitions=8)
                .group_by(lambda x: x[0])
                .reduce(lambda k, vs: len(list(vs))))

    def test_report_carries_decisions_and_runner_map(self):
        pipe = self._pipe()
        runner = MTRunner(_salt("shufplan"), pipe.pmer.graph)
        runner.run([pipe.source])
        rep = runner.plan_report["shuffle"]
        assert rep["enabled"] is True
        reduce_rows = [d for d in rep["targets"] if d["kind"] == "reduce"]
        assert reduce_rows and all(d["reason"] for d in reduce_rows)
        assert rep["mesh_stages"] >= 1
        assert set(runner._shuffle_targets.values()) <= {"mesh", "host"}

    def test_history_pins_second_run_to_host(self):
        """End to end: run 1 (no history) exchanges over the mesh; run 2
        under the same name sees the corpus record a tiny shuffle and
        keeps the host path — the cost model's call, visible in the
        report with the evidence."""
        name = _salt("shufpin")
        pipe = self._pipe()
        r1 = MTRunner(name, pipe.pmer.graph)
        out1 = sorted(r1.run([pipe.source])[0].read())
        assert r1.mesh_exchanges >= 1
        pipe2 = self._pipe()
        r2 = MTRunner(name, pipe2.pmer.graph)
        out2 = sorted(r2.run([pipe2.source])[0].read())
        assert out2 == out1  # byte-identical either way
        rows = [d for d in r2.plan_report["shuffle"]["targets"]
                if d["kind"] == "reduce"]
        assert rows and rows[0]["target"] == "host"
        assert "exchange_min_bytes" in rows[0]["reason"]
        assert r2.mesh_exchanges == 0

    def test_forced_on_ignores_tiny_history(self):
        settings.mesh_exchange = "on"
        name = _salt("shufforce")
        for _ in range(2):
            pipe = self._pipe()
            r = MTRunner(name, pipe.pmer.graph)
            r.run([pipe.source])
            assert r.mesh_exchanges >= 1

    def test_stats_exchange_section_and_stage_field(self):
        pipe = self._pipe()
        runner = MTRunner(_salt("shufstats"), pipe.pmer.graph)
        out = runner.run([pipe.source])
        del out
        mesh = runner.run_summary["mesh"]
        ex = mesh["exchange"]
        assert ex["bytes"] == mesh["exchange_bytes"] > 0
        assert ex["steps"] >= 1
        assert 0 < ex["peak_inflight_bytes"] <= ex["hbm_budget"]
        assert ex["mesh_stages"] >= 1
        stages = [st.as_dict() for st in runner.stats]
        assert any(st["shuffle_target"] == "mesh" for st in stages
                   if st["kind"] == "reduce")

    def test_device_lowered_reduce_recorded_not_routed(self):
        """An assoc fold the lowering pass placed on device shows up in
        the shuffle section as target=device (its redistribution rides
        the collective fold, not the byte exchange)."""
        old = settings.lower
        settings.lower = "1"
        try:
            pipe = (Dampr.memory(list(range(5000)), partitions=8)
                    .count(lambda x: x % 5))
            runner = MTRunner(_salt("shufdev"), pipe.pmer.graph)
            runner.run([pipe.source])
            rows = runner.plan_report["shuffle"]["targets"]
            dev = [d for d in rows if d["target"] == "device"]
            assert dev and "collective fold" in dev[0]["reason"]
            assert all(d["sid"] not in runner._shuffle_targets
                       for d in dev)
        finally:
            settings.lower = old

    def test_explain_renders_shuffle_lines(self):
        text = self._pipe().explain()
        assert "shuffle:" in text
        assert "reduce shuffle -> mesh" in text
        settings.mesh_exchange = "off"
        text = self._pipe().explain()
        assert "mesh exchange off" in text

    def test_sort_stage_classified_and_hinted(self):
        nums = [((i * 7919) % 10007) for i in range(20000)]
        pipe = Dampr.memory(nums, partitions=8).sort_by(lambda x: x)
        runner = MTRunner(_salt("shufsort"), pipe.pmer.graph,
                          memory_budget=1 << 16)
        out = runner.run([pipe.source])[0]
        rows = [d for d in runner.plan_report["shuffle"]["targets"]
                if d["kind"] == "sort"]
        assert rows and rows[0]["target"] == "mesh"
        assert out.pset.shuffle_target == "mesh"
        assert [v for _k, v in out.read()] == sorted(nums)
