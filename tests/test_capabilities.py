"""New capabilities: outer join, ring collectives, job retries."""

import numpy as np
import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.parallel.ring import ring_allgather, ring_allreduce


@pytest.fixture(autouse=True)
def small_partitions():
    old = settings.partitions
    settings.partitions = 8
    yield
    settings.partitions = old


class TestOuterJoin:
    def test_outer_reduce(self):
        left = Dampr.memory([("foo", 13), ("bar", 14)]).group_by(
            lambda x: x[0])
        right = Dampr.memory([("bar", "b"), ("baz", "z")]).group_by(
            lambda x: x[0])
        out = left.join(right).outer_reduce(
            lambda lit, rit: (list(lit), list(rit))).read()
        assert out == [
            ("bar", ([("bar", 14)], [("bar", "b")])),
            ("baz", ([], [("baz", "z")])),
            ("foo", ([("foo", 13)], [])),
        ]

    def test_outer_matches_inner_plus_exclusives(self):
        left = Dampr.memory(list(range(0, 10))).group_by(lambda x: x % 7)
        right = Dampr.memory(list(range(5, 15))).group_by(lambda x: x % 7)
        outer = left.join(right).outer_reduce(
            lambda l, r: (sorted(l), sorted(r))).read()
        # every key 0..6 appears exactly once with both sides' members
        assert [k for k, _v in outer] == list(range(7))

    def test_outer_empty_sides(self):
        left = Dampr.memory([]).group_by(lambda x: x)
        right = Dampr.memory([("k", 1)]).group_by(lambda x: x[0])
        out = left.join(right).outer_reduce(
            lambda l, r: (list(l), list(r))).read()
        assert out == [("k", ([], [("k", 1)]))]


class TestRingCollectives:
    def test_ring_allreduce_matches_sum(self, mesh8):
        x = np.arange(8 * 16, dtype=np.float32).reshape(8 * 16)
        out = ring_allreduce(mesh8, x)
        total = x.reshape(8, 16).sum(axis=0)
        for d in range(8):
            np.testing.assert_allclose(out.reshape(8, 16)[d], total,
                                       rtol=1e-6)

    def test_ring_allreduce_max(self, mesh8):
        rng = np.random.RandomState(1)
        x = rng.randn(8 * 32).astype(np.float32)
        out = ring_allreduce(mesh8, x, op="max")
        want = x.reshape(8, 32).max(axis=0)
        for d in range(8):
            np.testing.assert_allclose(out.reshape(8, 32)[d], want)

    def test_ring_allgather(self, mesh8):
        x = np.arange(8 * 4, dtype=np.float32)
        out = ring_allgather(mesh8, x)
        # every device ends with all shards concatenated in device order
        out = out.reshape(8, 32)
        for d in range(8):
            np.testing.assert_allclose(out[d], x)


class TestJobRetries:
    def test_flaky_job_succeeds_with_retry(self):
        attempts = {"n": 0}

        def flaky(x):
            attempts["n"] += 1
            if attempts["n"] == 1:
                raise RuntimeError("transient")
            return x * 2

        old = settings.job_retries
        settings.job_retries = 2
        try:
            out = Dampr.memory([1, 2, 3], partitions=1).map(flaky).read()
            assert out == [2, 4, 6]
        finally:
            settings.job_retries = old

    def test_persistent_failure_still_raises(self):
        def always(x):
            raise RuntimeError("permanent")

        old = settings.job_retries
        settings.job_retries = 1
        try:
            with pytest.raises(RuntimeError, match="permanent"):
                Dampr.memory([1], partitions=1).map(always).read()
        finally:
            settings.job_retries = old


class TestRetryNoLeak:
    def test_failed_attempt_registrations_rolled_back(self):
        from dampr_tpu.runner import MTRunner

        state = {"n": 0}

        def flaky_reducer(k, it):
            vals = sum(it)
            state["n"] += 1
            if state["n"] == 1:
                raise RuntimeError("transient mid-reduce")
            return vals

        old = settings.job_retries
        settings.job_retries = 1
        try:
            pipe = (Dampr.memory(list(range(100)), partitions=4)
                    .group_by(lambda x: x % 3).reduce(flaky_reducer))
            runner = MTRunner("retry-leak", pipe.pmer.graph)
            out = runner.run([pipe.source])
            got = dict(v for _k, v in out[0].read())
            assert got == {i: sum(range(i, 100, 3)) for i in range(3)}
            # no orphaned refs: residency equals live partition contents
            live = sum(r.nbytes for r in out[0].pset.all_refs()
                       if r.resident)
            assert runner.store._resident_bytes <= live + 1024
        finally:
            settings.job_retries = old
