"""Multi-process pins for the fleet observability plane: rank-tagged
artifacts from REAL separate processes.

Three legs, two spawn styles:

- env-rank workers (DAMPR_TPU_PROCESS_ID/_NUM_PROCESSES, no coordinator,
  no jax.distributed): pin the history-corpus rank discipline and the
  crashdump rank attribution — both only need rank *identity*, which by
  design never forces a process group;
- a full gloo 2-process deployment (localhost coordinator, the PR-8
  rig): the clock handshake runs at init, both ranks trace a chunked
  byte exchange with an artificial straggler, rank 0 merges the fleet
  timeline and the skew math must name the sleeping rank.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_env_ranks(tmp_path, sources, extra_env=None, timeout=180):
    """Spawn one process per source with rank env vars set (no process
    group).  Returns [(rc, out, err)] in rank order."""
    scratch = str(tmp_path / "scratch")
    outs = []
    procs = []
    for rank, src in enumerate(sources):
        script = str(tmp_path / "worker{}.py".format(rank))
        with open(script, "w") as f:
            f.write(src)
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "DAMPR_TPU_NUM_PROCESSES": str(len(sources)),
            "DAMPR_TPU_PROCESS_ID": str(rank),
            "DAMPR_TPU_SCRATCH": scratch,
            "DAMPR_TPU_TRACE": "1",
            "DAMPR_TPU_FLEET_WAIT_MS": "2000",
        })
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            [sys.executable, script], stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, env=env))
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    return scratch, outs


_HIST_WORKER = textwrap.dedent("""
    import sys
    sys.path.insert(0, {root!r})
    from dampr_tpu import Dampr
    out = (Dampr.memory(list(range(3000)), partitions=4)
           .count(lambda x: x % 11))
    em = out.run("mp-hist")
    assert sorted(v for _k, v in em.read()) == sorted(
        [273] * 8 + [272] * 3), "results diverged"
    print("HIST_OK")
""").format(root=ROOT)


_CRASH_WORKER_OK = textwrap.dedent("""
    import sys
    sys.path.insert(0, {root!r})
    from dampr_tpu import Dampr
    em = (Dampr.memory(list(range(1000)), partitions=2)
          .count(lambda x: x % 3)).run("mp-crash")
    list(em.read())
    print("CRASH0_OK")
""").format(root=ROOT)


_CRASH_WORKER_DIES = textwrap.dedent("""
    import sys
    sys.path.insert(0, {root!r})
    from dampr_tpu import Dampr

    def boom(x):
        if x == 500:
            raise RuntimeError("rank1 injected failure")
        return (x, 1)

    try:
        em = (Dampr.memory(list(range(1000)), partitions=2)
              .map(boom).run("mp-crash"))
        list(em.read())
    except Exception:
        print("CRASH1_DIED")
        raise SystemExit(7)
    raise SystemExit(0)  # should not be reached
""").format(root=ROOT)


class TestEnvRankProcesses:
    def test_history_corpus_rank_discipline(self, tmp_path):
        """Two ranks of one logical run append to the shared corpus:
        only rank 0's record feeds adaptation; rank 1's is rank-tagged
        and excluded by matching()/synthesize() (the multi-rank
        pollution fix)."""
        scratch, outs = _run_env_ranks(
            tmp_path, [_HIST_WORKER, _HIST_WORKER])
        for rank, (rc, out, err) in enumerate(outs):
            assert rc == 0, (rank, out, err[-2000:])
            assert "HIST_OK" in out
        corpus = os.path.join(scratch, "mp-hist", "history.jsonl")
        assert os.path.isfile(corpus), os.listdir(scratch)
        recs = [json.loads(ln) for ln in open(corpus) if ln.strip()]
        assert len(recs) == 2, recs
        tagged = [r for r in recs if r.get("rank")]
        untagged = [r for r in recs if not r.get("rank")]
        assert len(tagged) == 1 and tagged[0]["rank"] == 1
        assert len(untagged) == 1
        assert untagged[0]["process"]["num_processes"] == 2
        # the adaptation layer sees exactly ONE run, not one per rank
        sys.path.insert(0, ROOT)
        from dampr_tpu.obs import history as H

        shapes = untagged[0]["stage_shapes"]
        matched = H.matching(recs, shapes)
        assert len(matched) == 1 and not matched[0].get("rank")
        assert H.synthesize(matched)["history_entries"] == 1

    def test_per_rank_trace_artifacts_land(self, tmp_path):
        scratch, outs = _run_env_ranks(
            tmp_path, [_HIST_WORKER, _HIST_WORKER])
        base = os.path.join(scratch, "mp-hist", "trace")
        assert os.path.isfile(os.path.join(base, "stats.json"))
        assert os.path.isfile(os.path.join(base, "rank1", "stats.json"))
        with open(os.path.join(base, "rank1", "stats.json")) as f:
            s1 = json.load(f)
        assert s1["process"] == {"process_id": 1, "num_processes": 2}
        # rank 0 merged what it could (env ranks have no clock
        # handshake -> wall alignment; no collectives -> no skew)
        with open(os.path.join(base, "stats.json")) as f:
            s0 = json.load(f)
        fl = s0.get("fleet")
        assert fl is not None, "rank 0 should have merged the fleet"
        assert fl["num_processes"] == 2
        assert fl["alignment"] == "wall"
        assert {e["rank"] for e in fl["per_rank"]} == {0, 1}
        assert os.path.isfile(fl["merged_trace_file"])

    def test_killed_rank_leaves_named_crashdump(self, tmp_path):
        """Satellite pin: rank 1 dies mid-run; the surviving artifacts
        name the dead rank (crashdump.rank1.json + stats exit 3)."""
        scratch, outs = _run_env_ranks(
            tmp_path, [_CRASH_WORKER_OK, _CRASH_WORKER_DIES])
        rc0, out0, err0 = outs[0]
        rc1, out1, err1 = outs[1]
        assert rc0 == 0, (out0, err0[-2000:])
        assert rc1 == 7 and "CRASH1_DIED" in out1, (out1, err1[-2000:])
        base = os.path.join(scratch, "mp-crash", "trace")
        dump = os.path.join(base, "rank1", "crashdump.rank1.json")
        assert os.path.isfile(dump), (
            "dead rank's dump missing; tree: %r"
            % [os.path.join(dp, f) for dp, _d, fs in os.walk(base)
               for f in fs])
        with open(dump) as f:
            doc = json.load(f)
        assert doc["otherData"]["process"]["process_id"] == 1
        assert doc["otherData"]["crash"]["reason"] == "run-failed"
        # rank 0's legacy layout is intact and dump-free
        assert os.path.isfile(os.path.join(base, "stats.json"))
        assert not os.path.isfile(os.path.join(base, "crashdump.json"))

        # the stats CLI scans ALL rank dumps: exit 3 naming rank 1
        sys.path.insert(0, ROOT)
        from dampr_tpu.obs import flightrec

        dumps = flightrec.locate_all_crashdumps(
            os.path.join(scratch, "mp-crash"))
        assert dumps == [dump]
        proc = subprocess.run(
            [sys.executable, "-c",
             "import sys; sys.path.insert(0, {root!r}); "
             "sys.argv = ['dampr-tpu-stats', sys.argv[1]]; "
             "from dampr_tpu.cli import stats; stats()".format(root=ROOT),
             os.path.join(scratch, "mp-crash")],
            capture_output=True, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=120)
        assert proc.returncode == 3, (proc.stdout, proc.stderr)
        assert "rank 1" in proc.stderr
        assert "crashdump.rank1.json" in proc.stderr


_GLOO_WORKER = textwrap.dedent("""
    import os, sys, time
    pid = int(sys.argv[1]); port = sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, "@ROOT@")
    from dampr_tpu import settings
    settings.scratch_root = os.environ["DAMPR_TPU_SCRATCH"]
    from dampr_tpu.parallel import mesh as M
    from dampr_tpu.parallel.mesh import init_distributed, data_mesh
    init_distributed(coordinator_address="localhost:%s" % port,
                     num_processes=2, process_id=pid)
    assert jax.process_count() == 2 and len(jax.devices()) == 8
    # the clock handshake ran at init and anchored this rank
    assert M.clock_sync is not None, "clock handshake did not run"
    assert M.clock_sync["barrier_perf"] > 0
    assert M.rank_info() == (pid, 2)

    import numpy as np
    from dampr_tpu.obs import export, fleet, trace as T
    from dampr_tpu.parallel import exchange as px
    mesh = data_mesh()
    D = 8
    rng = np.random.RandomState(3)
    blobs = {}
    for s in range(D):
        for d in range(D):
            if (s + d) % 3 == 0:
                blobs[(s, d)] = rng.randint(
                    0, 256, size=6000).astype(np.uint8).tobytes()
    budget = 1 << 18
    px.mesh_blob_exchange(mesh, blobs, budget=budget)  # warm/compile

    run = "mp-fleet"
    tracer = T.Tracer(run)
    T.start(tracer)
    w0 = time.time()
    if pid == 1:
        time.sleep(0.4)  # artificial straggler: rank 1 arrives late
    out = px.mesh_blob_exchange(mesh, blobs, budget=budget)
    T.stop(tracer)
    wall = time.time() - w0
    assert out == blobs, "exchange diverged on rank %d" % pid
    info = px.last_info
    assert info["steps"] >= 1

    proc = export.process_section()
    assert proc["process_id"] == pid and proc["num_processes"] == 2
    assert "clock" in proc
    tdir = export.run_trace_dir(run)
    os.makedirs(tdir, exist_ok=True)
    tf = export.write_trace(tracer, os.path.join(tdir, export.TRACE_FILE))
    summary = {
        "schema": export.STATS_SCHEMA,
        "run": run, "process": proc,
        "started_at": round(w0, 3), "wall_seconds": round(wall, 4),
        "stages": [],
        "totals": {"records_out": 0, "bytes_out": info["bytes"],
                   "spill_bytes": 0},
        "mesh": {"exchange": {
            "bytes": info["bytes"], "steps": info["steps"],
            "peak_inflight_bytes": info["peak_inflight_bytes"],
            "hbm_budget": budget,
            "sent_per_device": {str(k): v for k, v in
                                px.sent_bytes_per_device.items()},
            "received_per_device": {str(k): v for k, v in
                                    px.received_bytes_per_device.items()},
            "routes": [[s, d, n] for (s, d), n in
                       sorted(px.pair_bytes_per_route.items())],
        }},
        "spans": tracer.span_summary(),
        "trace_file": tf,
    }
    export.write_stats(summary, os.path.join(tdir, export.STATS_FILE))

    if pid == 0:
        import json
        section = fleet.merge_run(run, wait_ms=20000)
        assert section is not None, "merge produced nothing"
        assert section["alignment"] == "clock", section["alignment"]
        assert section["missing_ranks"] == []
        skew = section.get("skew")
        assert skew, "no skew computed from exchange step spans"
        for st in skew["steps"]:
            assert 0.0 <= st["fraction"] <= 1.0, st
        assert skew["straggler_rank"] == 1, skew
        assert skew["skew_seconds"] >= 0.3, skew
        assert os.path.isfile(section["merged_trace_file"])
        ex = section.get("exchange")
        assert ex and ex["bytes"] > 0
        assert len(ex["rank_sent_matrix"]) == 2
        print("FLEET_JSON=" + json.dumps(
            {"merged": section["merged_trace_file"],
             "straggler": skew["straggler_rank"],
             "mean_fraction": skew["mean_fraction"]}))
    print("FLEETP_%d_OK" % pid, flush=True)
""").replace("@ROOT@", ROOT)


class TestTwoProcessFleet:
    def test_traced_gloo_exchange_merges_with_clock_skew(self, tmp_path):
        """The acceptance path end-to-end: 2 gloo ranks trace a chunked
        exchange, rank 1 is an injected straggler, rank 0's merged
        timeline aligns on the init-time clock handshake and the skew
        math names rank 1.  The merged trace must validate against the
        checked-in schema."""
        port = _free_port()
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["DAMPR_TPU_SCRATCH"] = str(tmp_path / "scratch")
        script = str(tmp_path / "gloo_worker.py")
        with open(script, "w") as f:
            f.write(_GLOO_WORKER)
        procs = [
            subprocess.Popen(
                [sys.executable, script, str(i), str(port)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, env=env)
            for i in range(2)]
        outs = []
        for p in procs:
            try:
                out, err = p.communicate(timeout=240)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            outs.append((p.returncode, out, err))
        for i, (rc, out, err) in enumerate(outs):
            assert rc == 0, (i, out, err[-3000:])
            assert "FLEETP_%d_OK" % i in out, (i, out, err[-2000:])
        line = [ln for ln in outs[0][1].splitlines()
                if ln.startswith("FLEET_JSON=")][0]
        info = json.loads(line.split("=", 1)[1])
        assert info["straggler"] == 1

        # parent-side: the merged artifact is Perfetto-loadable and
        # schema-valid with per-rank process lanes
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "validate_trace", os.path.join(ROOT, "tools",
                                           "validate_trace.py"))
        vt = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(vt)
        with open(info["merged"]) as f:
            doc = json.load(f)
        with open(os.path.join(ROOT, "docs", "trace_schema.json")) as f:
            schema = json.load(f)
        errors = vt.validate(doc, schema, require_cats=("exchange",))
        assert errors == [], errors
        pids = {ev["pid"] for ev in doc["traceEvents"]}
        assert pids == {1, 2}
        lanes = [ev for ev in doc["traceEvents"]
                 if ev.get("ph") == "M" and ev["name"] == "process_name"]
        names = {ev["args"]["name"] for ev in lanes}
        assert {"rank0/2", "rank1/2"} <= names, names


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
