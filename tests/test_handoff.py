"""Cross-stage device-resident handoff (docs/plan.md "Cross-stage device
fusion"): the plan's ``handoff="device"`` edge keeps a lowered map's
program outputs HBM-resident into the consuming device fold.

Exactness contract under test: handoff on / off / forced-fallback are
byte-identical; every degrade (HBM budget, vocabulary overflow, lane
guard) flushes to the classic spill path; a killed job leaves no leaked
device residents; boundary accounting (h2d) is idempotent per block.
"""

import operator
import os
from collections import Counter

import numpy as np
import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.blocks import Block
from dampr_tpu.obs import doctor
from dampr_tpu.ops import handoff as handoff_mod
from dampr_tpu.ops.text import DocFreq
from dampr_tpu.plan import model as plan_model
from dampr_tpu.storage import BlockRef, RunStore


@pytest.fixture(autouse=True)
def handoff_knobs():
    """Force lowering on (so device edges exist on CPU JAX) and restore
    every knob this suite touches.  The optimizer and the analyzer are
    forced ON: the handoff edge only exists on the FUSED map->fold
    shape (the optimizer-off plan interposes an identity stage — a
    structural decline, pinned by its own test below), and certified
    lane chains need the analyze pass."""
    old = (settings.lower, settings.handoff, settings.hbm_budget,
           settings.optimize, settings.analyze, settings.mesh_fold,
           settings.faults)
    settings.lower = "1"
    settings.handoff = "auto"
    settings.optimize = True
    settings.analyze = True
    yield
    (settings.lower, settings.handoff, settings.hbm_budget,
     settings.optimize, settings.analyze, settings.mesh_fold,
     settings.faults) = old


def _corpus(tmp_path, seed=3, n_lines=900, vocab=140):
    rng = np.random.RandomState(seed)
    words = ["w%d" % i for i in range(vocab)] + ["Tok_1", "UPPER", "a"]
    lines = [" ".join(rng.choice(words, size=rng.randint(1, 10)))
             for _ in range(n_lines)]
    path = str(tmp_path / "corpus.txt")
    with open(path, "wb") as f:
        f.write(("\n".join(lines) + "\n").encode())
    return path


def _docfreq(corpus, name):
    docs = Dampr.text(corpus, os.path.getsize(corpus) // 3 + 1)
    pipe = (docs.custom_mapper(
        DocFreq(mode="word", lower=True, pair_values=False))
        .fold_values(operator.add))
    em = pipe.run(name=name)
    got = sorted(em.read())
    stats = em.stats()
    em.delete()
    return got, stats


def _oracle(corpus):
    import re

    rx = re.compile(r"[^\w]+")
    c = Counter()
    with open(corpus, encoding="utf-8") as f:
        for line in f:
            c.update(set(t for t in rx.split(line.lower()) if t))
    return sorted(c.items())


class TestEdgeDecision:
    def test_scanner_edge_marked_device(self, tmp_path):
        corpus = _corpus(tmp_path)
        docs = Dampr.text(corpus, os.path.getsize(corpus) + 1)
        pipe = (docs.custom_mapper(
            DocFreq(mode="word", lower=True, pair_values=False))
            .fold_values(operator.add))
        text = pipe.explain()
        assert "handoff:" in text
        assert "stay HBM-resident" in text

    def test_handoff_off_declines_with_reason(self, tmp_path):
        settings.handoff = "off"
        corpus = _corpus(tmp_path)
        got, stats = _docfreq(corpus, "handoff-off-edge")
        assert stats["device"]["handoff_edges"] == 0
        assert stats["device"]["handoff_bytes"] == 0
        edges = stats["plan"]["lowering"]["handoff"]
        assert edges and all(e["handoff"] == "spill" for e in edges)
        assert any("handoff off" in e["reason"] for e in edges)

    def test_optimizer_off_declines_structurally(self, tmp_path):
        """Without the optimizer's map->fold fusion an identity stage
        sits between producer and fold: the edge declines (the runner
        only threads refs across a DIRECT device->device edge) and the
        whole run rides the spill path, byte-identically."""
        settings.optimize = False
        corpus = _corpus(tmp_path)
        got, stats = _docfreq(corpus, "handoff-noopt")
        assert got == _oracle(corpus)
        assert stats["device"]["handoff_edges"] == 0
        assert stats["device"]["handoff_bytes"] == 0
        edges = stats["plan"]["lowering"]["handoff"]
        assert all(e["handoff"] == "spill" for e in edges)

    def test_pair_values_scanner_declines(self, tmp_path):
        corpus = _corpus(tmp_path)
        docs = Dampr.text(corpus, os.path.getsize(corpus) + 1)
        pipe = (docs.custom_mapper(
            DocFreq(mode="word", lower=True, pair_values=True))
            .fold_by(lambda kv: kv[0], operator.add,
                     lambda kv: kv[1]))
        em = pipe.run(name="handoff-pairvalues")
        stats = em.stats()
        em.delete()
        assert stats["device"]["handoff_bytes"] == 0

    def test_price_handoff_ignores_host_runs(self):
        """Only LOWERED runs vote: a fast host-codec run of the same
        plan says nothing about handoff-vs-spill."""
        mk = lambda wall, frac, edges: {
            "fingerprint": "fp1", "wall_seconds": wall,
            "device_fraction": frac,
            "stages": [{"bytes_in": 64 << 20}],
            "handoff": {"edges": edges, "degrades": 0},
        }
        # host runs (device_fraction 0) are much faster — must not vote
        recs = ([mk(1.0, 0, 0)] * 6
                + [mk(10.0, 0.5, 0)] * 3 + [mk(7.0, 0.5, 1)] * 3)
        decision, why = plan_model.price_handoff(recs, "fp1")
        assert decision == "device", why

    def test_price_handoff_normalizes_by_volume(self):
        """A small spill run and a large resident run compare on s/MB,
        not wall seconds."""
        mk = lambda wall, mb, edges: {
            "fingerprint": "fp1", "wall_seconds": wall,
            "device_fraction": 0.5,
            "stages": [{"bytes_in": mb << 20}],
            "handoff": {"edges": edges, "degrades": 0},
        }
        # spill: 1s for 4MB (0.25 s/MB); resident: 8s for 64MB (0.125)
        recs = [mk(1.0, 4, 0), mk(8.0, 64, 1)]
        decision, why = plan_model.price_handoff(recs, "fp1")
        assert decision == "device", why

    def test_price_handoff_declines_on_slower_evidence(self):
        mk = lambda wall, edges: {
            "fingerprint": "fp1", "wall_seconds": wall,
            "device_fraction": 0.5,
            "stages": [{"bytes_in": 16 << 20}],
            "handoff": {"edges": edges, "degrades": 0},
        }
        recs = [mk(2.0, 0), mk(9.0, 1)]
        decision, why = plan_model.price_handoff(recs, "fp1")
        assert decision == "spill"
        assert "s/MB" in why

    def test_price_handoff_no_variance_reason(self):
        decision, why = plan_model.price_handoff([], "fp1")
        assert decision is None
        assert "variance" in why

    def test_degraded_runs_vote_neither_side(self):
        mk = lambda wall, edges, deg: {
            "fingerprint": "fp1", "wall_seconds": wall,
            "device_fraction": 0.5,
            "stages": [{"bytes_in": 16 << 20}],
            "handoff": {"edges": edges, "degrades": deg},
        }
        recs = [mk(2.0, 1, 3), mk(9.0, 0, 0)]
        decision, _why = plan_model.price_handoff(recs, "fp1")
        assert decision is None  # the degraded run's wall mixes paths


class TestExactness:
    def test_docfreq_byte_identical_on_off_fallback(self, tmp_path):
        corpus = _corpus(tmp_path)
        want = _oracle(corpus)

        settings.handoff = "on"
        on, s_on = _docfreq(corpus, "handoff-on")
        assert s_on["device"]["handoff_edges"] >= 1
        assert s_on["device"]["handoff_bytes"] > 0

        settings.handoff = "off"
        off, s_off = _docfreq(corpus, "handoff-off")
        assert s_off["device"]["handoff_bytes"] == 0

        # forced-fallback: handoff armed, but a starved budget degrades
        # every edge mid-stage back to the spill path
        settings.handoff = "on"
        settings.hbm_budget = 4096
        fb, s_fb = _docfreq(corpus, "handoff-fallback")
        settings.hbm_budget = "auto"
        assert s_fb["device"]["handoff_degrades"] >= 1

        assert on == off == fb == want

    def test_tfidf_shape_byte_identical(self, tmp_path):
        """The bench pipeline shape (DocFreq -> fold -> idf cross) is
        identical with the handoff on and off."""
        import math

        corpus = _corpus(tmp_path, seed=11)

        def tfidf(name):
            docs = Dampr.text(corpus, os.path.getsize(corpus) // 2 + 1)
            df = (docs.custom_mapper(
                DocFreq(mode="word", lower=True, pair_values=False))
                .fold_values(operator.add))
            idf = df.cross_right(
                docs.len(),
                lambda d, total: (d[0], d[1],
                                  math.log(1 + (float(total) / d[1]))),
                memory=True)
            em = idf.run(name=name)
            got = sorted(em.read())
            stats = em.stats()
            em.delete()
            return got, stats

        settings.handoff = "on"
        on, s_on = tfidf("handoff-tfidf-on")
        settings.handoff = "off"
        off, _ = tfidf("handoff-tfidf-off")
        assert on == off
        assert s_on["device"]["handoff_edges"] >= 1

    def test_certified_numeric_chain_byte_identical(self):
        """The first numeric non-text handoff edge: a certified
        ValueMap/Filter/Rekey lane chain feeding a keyed device sum
        fold, byte-identical with the edge resident and spilled."""
        old = settings.device_min_batch
        settings.device_min_batch = 1024
        try:
            N = 60000

            def build():
                return (Dampr.memory(list(range(N)), partitions=2)
                        .map(lambda x: x * 3 + 1)
                        .filter(lambda x: x % 2 == 0)
                        .count(lambda x: x % 97))

            text = build().explain()
            assert "Rekey" in text
            assert "handoff:" in text

            settings.handoff = "on"
            em = build().run(name="lane-handoff-on")
            on = sorted(em.read())
            s_on = em.stats()
            em.delete()

            settings.handoff = "off"
            em = build().run(name="lane-handoff-off")
            off = sorted(em.read())
            em.delete()

            settings.lower = "0"
            em = build().run(name="lane-handoff-host")
            host = sorted(em.read())
            em.delete()

            want = sorted(Counter(
                v % 97 for v in (x * 3 + 1 for x in range(N))
                if v % 2 == 0).items())
            assert on == off == host == want
            assert s_on["device"]["handoff_edges"] >= 1
            # handoff_bytes stays 0 here: lane-program outputs are
            # HOST-authoritative (64-bit host eval), so they enter the
            # HBM tier through a round trip — the chain edge's win is
            # the tier floor (no spill/pickle before the fold), and
            # only scanner vocabularies register without a round trip.
            assert s_on["device"]["handoff_bytes"] == 0
        finally:
            settings.device_min_batch = old

    def test_distinct_rekey_chains_get_distinct_programs(self):
        """Two bare ``count()`` chains have identical (empty) lane ops
        but different key functions: the program cache must key on the
        re-key too, or the second stage runs the first one's compiled
        program.  The key fns AGREE on the smallest values (both bucket
        to 0), so a first-batch differential check alone cannot catch
        the swap — only distinct cache entries can."""
        from dampr_tpu.analyze.jaxtrace import ChainSpec, _chain_key

        ka, kb = (lambda v: v // 10), (lambda v: v // 100)
        assert (_chain_key(ChainSpec([], [], rekey=(ka, None)))
                != _chain_key(ChainSpec([], [], rekey=(kb, None))))

        old = settings.device_min_batch
        settings.device_min_batch = 1024
        try:
            N = 30000
            for div in (10, 100):
                em = (Dampr.memory(list(range(N)), partitions=2)
                      .count(lambda x, d=div: x // d)
                      .run(name="rekey-prog-%d" % div))
                got = sorted(em.read())
                em.delete()
                want = sorted(Counter(x // div
                                      for x in range(N)).items())
                assert got == want, "div=%d" % div
        finally:
            settings.device_min_batch = old

    def test_vocabulary_shift_reverts_and_stays_exact(self, tmp_path):
        """A corpus whose vocabulary turns over mid-stream forces table
        misses past the revert bar; the job re-bootstraps and results
        stay exact."""
        rng = np.random.RandomState(5)
        lines = []
        for phase in range(4):
            words = ["p%d_%d" % (phase, i) for i in range(150)]
            lines += [" ".join(rng.choice(words,
                                          size=rng.randint(1, 10)))
                      for _ in range(400)]
        path = str(tmp_path / "shift.txt")
        with open(path, "wb") as f:
            f.write(("\n".join(lines) + "\n").encode())
        settings.handoff = "on"
        got, stats = _docfreq(path, "handoff-shift")
        assert got == _oracle(path)
        assert stats["device"]["handoff_bytes"] > 0


class TestDegradeAndKill:
    def test_budget_exceeded_mid_stage_degrades_exactly(self, tmp_path):
        corpus = _corpus(tmp_path, vocab=4000, n_lines=2500)
        settings.handoff = "on"
        settings.hbm_budget = 1 << 14  # 16 KB: vocabulary can't fit
        got, stats = _docfreq(corpus, "handoff-degrade")
        assert got == _oracle(corpus)
        assert stats["device"]["handoff_degrades"] >= 1

    def test_drain_failure_loses_no_miss_tokens(self, monkeypatch):
        """A table-mode drain whose miss absorb is REFUSED
        (vocabulary/lane budget) must re-emit the missed tokens through
        the exact host path — the degrade flush only holds the batch's
        hits.  Window 1 bootstraps the vocabulary; window 2 carries NEW
        tokens (guaranteed table misses) and every absorb is forced to
        fail, so its drain takes the degrade path; exactness over the
        emitted blocks proves no token was dropped."""
        from dampr_tpu.ops import lower as ops_lower
        from dampr_tpu.ops.text import DocFreq

        rng = np.random.RandomState(7)
        base = ["w%d" % i for i in range(120)]
        fresh = ["new%d" % i for i in range(80)]
        w1 = ("\n".join(" ".join(rng.choice(base, size=6))
                        for _ in range(300)) + "\n").encode()
        w2 = ("\n".join(" ".join(rng.choice(base + fresh, size=6))
                        for _ in range(300)) + "\n").encode()

        monkeypatch.setattr(
            handoff_mod.HandoffVocab, "_absorb_miss_tokens",
            lambda self, *a, **kw: False)
        settings.handoff = "on"
        store = RunStore("handoff-missdrop", budget=1 << 26)
        store.handoff_active = True
        try:
            sink = ops_lower.device_window_sink(
                DocFreq(mode="word", lower=True, pair_values=False),
                store=store, handoff=True)
            blocks = list(sink.add(w1) or ())
            assert sink._hv.table_mode  # window 1 really bootstrapped
            blocks += list(sink.add(w2) or ())
            assert sink._hv.degraded  # the refused absorb degraded
            fblocks, hmap = sink.finalize_handoff(store, 4)
            assert not hmap  # a degraded job registers no device refs
            blocks += list(fblocks)

            got = Counter()
            for blk in blocks:
                for k, v in zip(blk.keys, blk.values):
                    got[k] += int(v)
            want = Counter()
            for data in (w1, w2):
                for line in data.decode().splitlines():
                    want.update(set(t for t in __import__("re").split(
                        r"[^\w]+", line.lower()) if t))
            assert got == want
        finally:
            store.cleanup()

    def test_kill_mid_handoff_leaks_no_device_residents(self, tmp_path):
        """A fatal fault mid-map (after handoff batches dispatched) must
        not leave device bytes charged against the store budget."""
        from dampr_tpu import runner as runner_mod

        corpus = _corpus(tmp_path, n_lines=1500)
        settings.handoff = "on"
        # nth=1: the first job's window bootstraps (or dispatches) and
        # allocates device residents; the second dispatch-site hit dies
        # fatally with those residents live.
        settings.faults = "device_dispatch:nth=1,kind=fatal"
        stores = []
        orig = RunStore.__init__

        def spy(self, *a, **kw):
            orig(self, *a, **kw)
            stores.append(self)

        RunStore.__init__ = spy
        try:
            with pytest.raises(Exception):
                _docfreq(corpus, "handoff-kill")
        finally:
            RunStore.__init__ = orig
            settings.faults = None
        assert stores
        for store in stores:
            live = [r for r in store._dev_resident if not r._dead]
            assert not live, "leaked device residents"
            assert store._dev_bytes == 0, "device budget not returned"

    def test_long_token_does_not_widen_rows_or_degrade(self):
        """A multi-KB token absorbed into the vocabulary (the
        _long_tokens host path) must not widen every slot's device row —
        probe batches only carry tokens <= _SHORT_TOKEN, so a longer
        row can never verify anyway.  Its bytes truncate; its counts
        stay exact."""
        from dampr_tpu.ops.text import _SHORT_TOKEN

        store = RunStore("handoff-long", budget=1 << 26)
        store.handoff_active = True
        try:
            hv = handoff_mod.HandoffVocab(store, dedup=False)
            long_key = "x" * 5000
            keys = ["a", "b", long_key]
            from dampr_tpu.ops import hashing

            ks = np.empty(3, dtype=object)
            ks[:] = keys
            h1, h2 = hashing.hash_keys(ks)
            ok, _frac = hv.absorb_drain(
                keys, np.array([2, 3, 7], dtype=np.int64), h1, h2, 12)
            assert ok, "long token forced a degrade"
            assert not hv.degraded
            assert hv.Lcap <= 2 * (_SHORT_TOKEN + 1), hv.Lcap
            blk = hv.degrade("test flush")
            got = dict(zip(blk.keys, blk.values))
            assert got == {"a": 2, "b": 3, long_key: 7}
        finally:
            store.cleanup()

    def test_flush_block_returns_budget(self):
        """HandoffVocab.degrade flushes every count into one hash-sorted
        block and resets — no device arrays survive."""
        store = RunStore("handoff-flush", budget=1 << 24)
        store.handoff_active = True
        hv = handoff_mod.HandoffVocab(store, dedup=False)
        keys = ["k%d" % i for i in range(100)]
        from dampr_tpu.ops import hashing

        ks = np.empty(100, dtype=object)
        ks[:] = keys
        h1, h2 = hashing.hash_keys(ks)
        ok, _frac = hv.absorb_drain(keys, np.ones(100, dtype=np.int64),
                                    h1, h2, 100)
        assert ok
        blk = hv.degrade("test degrade")
        assert blk is not None and len(blk) == 100
        assert sorted(blk.keys) == sorted(keys)
        assert hv.acc is None and hv.nslots == 0
        assert store.handoff_degrades == 1
        store.cleanup()


class TestAccounting:
    def _blk(self, n=8192):
        ks = np.arange(n, dtype=np.int64) % 31
        vs = np.arange(n, dtype=np.int64) % 7
        return Block(ks, vs)

    def test_h2d_idempotent_on_reregistration(self):
        """The satellite fix: a device ref re-entered after a fallback
        must not double-count its h2d bytes — the charge is per actual
        transfer, armed where device_put happened."""
        old = settings.hbm_budget, settings.hbm_min_records
        settings.hbm_budget = 64 << 20
        settings.hbm_min_records = 1
        try:
            store = RunStore("handoff-h2d")
            ref = store.register(self._blk(), device=True)
            assert ref.is_device
            once = store.h2d_bytes
            assert once == ref.dev_bytes
            # fallback path re-enters the same (already-resident) ref
            store._enter_ref(ref)
            assert store.h2d_bytes == once, "h2d double-counted"
            store.cleanup()
        finally:
            settings.hbm_budget, settings.hbm_min_records = old

    def test_register_device_charges_hash_lanes_only(self):
        """from_device_lanes: the value lane never crossed the boundary
        (it was born on device), so only the uploaded hash lanes count
        as h2d, and the bytes land in handoff_bytes."""
        import jax

        old = settings.hbm_budget
        settings.hbm_budget = 64 << 20
        try:
            store = RunStore("handoff-dev-reg")
            store.handoff_active = True
            n = 1024
            keys = np.empty(n, dtype=object)
            keys[:] = ["k%d" % i for i in range(n)]
            h1 = np.arange(n, dtype=np.uint32)
            h2 = np.arange(n, dtype=np.uint32)[::-1].copy()
            dev_v = jax.device_put(np.ones(n, dtype=np.int64))
            dev_h1 = jax.device_put(h1)
            dev_h2 = jax.device_put(h2)
            ref = BlockRef.from_device_lanes(
                keys, h1, h2, dev_v, dev_h1, dev_h2, store=store,
                value_dtype=np.int64, lane_abs=n, lane_min=1,
                h2d_bytes=h1.nbytes + h2.nbytes)
            store.register_device(ref)
            assert store.h2d_bytes == h1.nbytes + h2.nbytes
            assert store.handoff_bytes == ref.dev_bytes
            # re-entry after a fallback: still no double count
            store._enter_ref(ref)
            assert store.h2d_bytes == h1.nbytes + h2.nbytes
            got = ref.get()
            assert list(got.keys) == list(keys)
            assert got.values.dtype == np.int64
            store.cleanup()
        finally:
            settings.hbm_budget = old


class TestCompaction:
    def test_compact_partial_preserves_live_rows(self):
        """The mesh-fold refold compaction: live (h1, h2, v) rows survive
        a compaction byte-for-byte; dead pad is dropped to a pow2
        bound."""
        import jax

        from dampr_tpu.parallel.shuffle import compact_partial

        rng = np.random.RandomState(9)
        n = 4096
        h1 = rng.randint(0, 2 ** 32, size=n, dtype=np.uint64).astype(
            np.uint32)
        h2 = rng.randint(0, 2 ** 32, size=n, dtype=np.uint64).astype(
            np.uint32)
        v = rng.randint(0, 100, size=n).astype(np.int32)
        ok = np.zeros(n, dtype=np.uint32)
        live_idx = rng.choice(n, size=300, replace=False)
        ok[live_idx] = 1
        part = tuple(jax.device_put(x) for x in (h1, h2, v, ok))
        ch1, ch2, cv, cok = compact_partial(part)
        assert int(ch1.shape[0]) == 512  # pow2 bound over 300 live
        m = np.asarray(cok) == 1
        assert m.sum() == 300
        got = set(zip(np.asarray(ch1)[m].tolist(),
                      np.asarray(ch2)[m].tolist(),
                      np.asarray(cv)[m].tolist()))
        want = set(zip(h1[live_idx].tolist(), h2[live_idx].tolist(),
                       v[live_idx].tolist()))
        assert got == want

    def test_compact_partial_noop_when_dense(self):
        import jax

        from dampr_tpu.parallel.shuffle import compact_partial

        n = 64
        part = tuple(jax.device_put(x) for x in (
            np.arange(n, dtype=np.uint32),
            np.arange(n, dtype=np.uint32),
            np.ones(n, dtype=np.int32),
            np.ones(n, dtype=np.uint32)))
        out = compact_partial(part)
        assert out is part  # all live: nothing to shrink


class TestDoctor:
    def _summary(self, declined=True, verdict="transfer",
                 kind="settings"):
        edge = {"src": 1, "dst": 2, "handoff": "spill", "kind": kind,
                "reason": "handoff off (settings.handoff='off'; hbm "
                          "budget 0 on this backend)"}
        return {
            "run": "handoff-doc", "wall_seconds": 10.0,
            "stages": [{"stage": 1, "kind": "map", "target": "device",
                        "seconds": 8.0}],
            "plan": {"lowering": {"enabled": True,
                                  "handoff": [edge] if declined else []}},
            "device": {"handoff_edges": 0, "handoff_degrades": 0},
            "critpath": {
                "source": "spans",
                "run": {"verdict": verdict,
                        "fractions": {verdict: 0.6}},
                "stages": [{"stage": 1, "kind": "map",
                            "seconds": 8.0, "verdict": verdict,
                            "fractions": {verdict: 0.7}}],
            },
        }

    def test_declined_edge_maps_to_budget_knobs(self, tmp_path,
                                                monkeypatch):
        import json

        monkeypatch.setattr(settings, "scratch_root", str(tmp_path))
        rundir = tmp_path / "handoff-doc" / "trace"
        rundir.mkdir(parents=True)
        with open(str(rundir / "stats.json"), "w") as f:
            json.dump(self._summary(), f)
        report = doctor.diagnose(str(tmp_path / "handoff-doc"))
        hand = [x for x in report["findings"]
                if x["bottleneck"] == "handoff"]
        assert hand, report["findings"]
        knobs = {s["setting"] for s in hand[0]["suggestions"]}
        assert "handoff" in knobs
        assert "hbm_budget" in knobs
        assert "lower_min_records" in knobs
        assert "declined" in hand[0]["evidence"]

    def test_unactionable_declines_emit_no_finding(self, tmp_path,
                                                   monkeypatch):
        """An object-lane edge has no device tier to buy and a priced
        decline is the cost model already choosing the faster path —
        neither should page the operator at the budget knobs."""
        import json

        monkeypatch.setattr(settings, "scratch_root", str(tmp_path))
        for kind in ("object-lane", "priced"):
            name = "handoff-doc-%s" % kind
            rundir = tmp_path / name / "trace"
            rundir.mkdir(parents=True)
            s = self._summary(kind=kind)
            s["run"] = name
            with open(str(rundir / "stats.json"), "w") as f:
                json.dump(s, f)
            report = doctor.diagnose(str(tmp_path / name))
            assert not [x for x in report["findings"]
                        if x["bottleneck"] == "handoff"], kind

    def test_no_finding_without_transfer_verdict(self, tmp_path,
                                                 monkeypatch):
        import json

        monkeypatch.setattr(settings, "scratch_root", str(tmp_path))
        rundir = tmp_path / "handoff-doc2" / "trace"
        rundir.mkdir(parents=True)
        with open(str(rundir / "stats.json"), "w") as f:
            json.dump(self._summary(verdict="codec"), f)
        report = doctor.diagnose(str(tmp_path / "handoff-doc2"))
        assert not [x for x in report["findings"]
                    if x["bottleneck"] == "handoff"]

    def test_playbook_knobs_exist(self):
        for knob, _env, _prop, why in doctor._PLAYBOOK["handoff"]:
            assert hasattr(settings, knob)
            assert why


class TestObservability:
    def test_stats_trace_and_explain_surfaces(self, tmp_path):
        corpus = _corpus(tmp_path)
        settings.handoff = "on"
        old_trace, old_dir = settings.trace, settings.trace_dir
        settings.trace = True
        settings.trace_dir = str(tmp_path / "traces")
        try:
            got, stats = _docfreq(corpus, "handoff-traced")
        finally:
            settings.trace, settings.trace_dir = old_trace, old_dir
        dev = stats["device"]
        assert dev["handoff_edges"] >= 1
        assert dev["handoff_bytes"] > 0
        assert dev["d2h_avoided_bytes"] > 0
        spans = stats.get("spans") or {}
        assert "handoff" in spans, spans
        # schema-valid trace including the handoff spans
        import subprocess
        import sys

        root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        res = subprocess.run(
            [sys.executable,
             os.path.join(root, "tools", "validate_trace.py"),
             stats["trace_file"],
             "--schema", os.path.join(root, "docs",
                                      "trace_schema.json"),
             "--require-cats", "handoff,stage"],
            capture_output=True, text=True)
        assert res.returncode == 0, res.stdout + res.stderr

    def test_history_records_handoff_evidence(self, tmp_path,
                                              monkeypatch):
        from dampr_tpu.obs import history

        monkeypatch.setattr(settings, "scratch_root", str(tmp_path))
        corpus = _corpus(tmp_path)
        settings.handoff = "on"
        got, stats = _docfreq(corpus, "handoff-hist")
        recs = history.load("handoff-hist")
        assert recs
        h = recs[-1].get("handoff") or {}
        assert h.get("edges", 0) >= 1
        assert h.get("bytes", 0) > 0
        assert "handoff" in (recs[-1].get("settings") or {})
