"""Black-box DSL conformance suite.

Mirrors the reference's engine-agnostic test strategy (SURVEY §4: 35 DSL-level
behaviors, reference tests/test_dampr.py:17-545) rewritten against the new
engine: every test builds a small pipeline with multi-chunk inputs and asserts
on materialized output, so the whole stack — fusion, blocks, hashing, shuffle,
grouped reduction, joins, sinks — is exercised on each assertion.  Runs on the
8-device virtual CPU mesh rig from conftest.py.
"""

import json
import os
import shutil
import tempfile

import pytest

from dampr_tpu import (BlockMapper, BlockReducer, Dampr, Dataset, Map, Reduce,
                       StreamMapper)
from dampr_tpu import settings
from dampr_tpu.utils import filter_by_count

from conftest import reference_text


@pytest.fixture(autouse=True)
def small_partitions(partitions8):
    yield


@pytest.fixture
def items():
    return Dampr.memory(list(range(10, 20)), partitions=2)


class TestMapping:
    def test_identity(self, items):
        assert items.read() == list(range(10, 20))

    def test_map_fusion_chain(self, items):
        # map -> filter -> flat_map fuse into one stage and compose correctly
        out = (items.map(lambda x: x + 1)
               .filter(lambda x: x % 2 == 0)
               .flat_map(lambda x: [x, x])
               .read())
        expected = []
        for x in range(10, 20):
            x += 1
            if x % 2 == 0:
                expected.extend([x, x])
        assert out == expected

    def test_map_values_and_keys(self):
        assert (Dampr.memory([("a", 1), ("b", 2)]).map_values(lambda x: x + 1)
                .read() == [("a", 2), ("b", 3)])
        assert (Dampr.memory([("a", 1), ("bb", 2)]).map_keys(len)
                .read() == [(1, 1), (2, 2)])

    def test_prefix_suffix(self):
        assert Dampr.memory(["a", "bb"]).prefix(len).read() == [
            (1, "a"), (2, "bb")]
        assert Dampr.memory(["a", "bb"]).suffix(len).read() == [
            ("a", 1), ("bb", 2)]

    def test_sample_bounds(self, items):
        everything = items.sample(1.0).read()
        assert everything == list(range(10, 20))
        assert items.sample(0.0).read() == []

    def test_inspect_passthrough(self, items, capsys):
        out = items.inspect("dbg").read()
        assert out == list(range(10, 20))
        assert "dbg: 10" in capsys.readouterr().out


class TestGrouping:
    def test_group_by_reduce(self, items):
        out = items.group_by(lambda x: x % 2).reduce(
            lambda k, it: sum(it)).read()
        assert out == [(0, 10 + 12 + 14 + 16 + 18), (1, 11 + 13 + 15 + 17 + 19)]

    def test_a_group_by_equivalence(self, items):
        general = items.group_by(lambda x: x % 3).reduce(
            lambda k, it: sum(it)).read()
        assoc = items.a_group_by(lambda x: x % 3).reduce(
            lambda x, y: x + y).read()
        assert sorted(general) == sorted(assoc)

    def test_fold_by(self, items):
        out = items.fold_by(lambda x: x % 2, binop=lambda x, y: x + y).read()
        assert out == [(0, 70), (1, 75)]

    def test_sum_and_first(self, items):
        assert items.a_group_by(lambda x: 1).sum().read() == [(1, sum(range(10, 20)))]
        first = dict(items.a_group_by(lambda x: x % 2).first().read())
        assert first == {0: 10, 1: 11}

    def test_count(self, items):
        assert items.count(lambda x: x % 2).read() == [(0, 5), (1, 5)]

    def test_mean(self):
        ages = [("Andrew", 33), ("Alice", 42), ("Andrew", 12), ("Bob", 51)]
        out = Dampr.memory(ages).mean(lambda x: x[0], lambda v: v[1]).read()
        assert out == [("Alice", 42.0), ("Andrew", 22.5), ("Bob", 51.0)]

    def test_len(self, items):
        assert items.len().read() == [10]

    def test_len_empty(self):
        assert Dampr.memory([]).len().read() == [0]

    def test_sort_by(self, items):
        out = items.filter(lambda x: x % 2 == 1).sort_by(lambda x: -x).read()
        assert out == [19, 17, 15, 13, 11]

    def test_unique(self):
        names = [("Andrew", 1), ("Andrew", 1), ("Andrew", 2), ("Becky", 13)]
        out = (Dampr.memory(names)
               .group_by(lambda x: x[0], lambda x: x[1]).unique().read())
        assert out == [("Andrew", [1, 2]), ("Becky", [13])]

    def test_topk(self):
        assert Dampr.memory([1, 3, 2, 4, 2.2]).topk(2).read() == [3, 4]
        assert Dampr.memory([1, 3, 2, 4, 2.2]).topk(2, lambda x: -x).read() == [1, 2]

    def test_mixed_type_keys_group_distinctly(self):
        # 1 and 1.0 and True group together; "1" is distinct
        data = [(1, 1), (1.0, 1), (True, 1), ("1", 1)]
        out = dict(Dampr.memory(data)
                   .fold_by(lambda kv: kv[0], lambda x, y: x + y,
                            lambda kv: kv[1]).read())
        assert out[1] == 3
        assert out["1"] == 1


class TestJoins:
    def test_inner_join(self):
        left = Dampr.memory([("foo", 13), ("bar", 14)]).group_by(lambda x: x[0])
        right = Dampr.memory([("bar", "b"), ("baz", "z")]).group_by(lambda x: x[0])
        out = left.join(right).reduce(
            lambda lit, rit: (list(lit), list(rit))).read()
        assert out == [("bar", ([("bar", 14)], [("bar", "b")]))]

    def test_disjoint_join_is_empty(self):
        left = Dampr.memory(list(range(5))).group_by(lambda x: x)
        right = Dampr.memory(list(range(10, 15))).group_by(lambda x: x)
        assert left.join(right).reduce(lambda l, r: (list(l), list(r))).read() == []

    def test_left_join(self):
        left = Dampr.memory([("foo", 13), ("bar", 14)]).group_by(lambda x: x[0])
        right = Dampr.memory([("bar", "b"), ("baz", "z")]).group_by(lambda x: x[0])
        out = left.join(right).left_reduce(
            lambda lit, rit: (list(lit), list(rit))).read()
        assert out == [("bar", ([("bar", 14)], [("bar", "b")])),
                       ("foo", ([("foo", 13)], []))]

    def test_join_many_flattens(self):
        left = Dampr.memory([("a", 1), ("a", 2)]).group_by(lambda x: x[0])
        right = Dampr.memory([("a", 9)]).group_by(lambda x: x[0])
        out = left.join(right).reduce(
            lambda lit, rit: list(lit) + list(rit), many=True).read()
        assert out == [("a", ("a", 1)), ("a", ("a", 2)), ("a", ("a", 9))]

    def test_join_numeric_keys_int_float_equal(self):
        left = Dampr.memory([(1, "l")]).group_by(lambda x: x[0])
        right = Dampr.memory([(1.0, "r")]).group_by(lambda x: x[0])
        out = left.join(right).reduce(
            lambda lit, rit: (list(lit), list(rit))).read()
        assert len(out) == 1

    def test_pjoin_run_directly(self):
        left = Dampr.memory([("a", 1)]).group_by(lambda x: x[0])
        right = Dampr.memory([("a", 2)]).group_by(lambda x: x[0])
        out = left.join(right).run().read()
        assert out == [("a", ([("a", 1)], [("a", 2)]))]


class TestCrosses:
    def test_cross_left(self):
        left = Dampr.memory([1, 2, 3, 4, 5])
        right = Dampr.memory(["foo", "bar"])
        out = left.cross_left(right, lambda x, y: (x, y)).read()
        assert out == [(1, "foo"), (2, "foo"), (3, "foo"), (4, "foo"),
                       (5, "foo"), (1, "bar"), (2, "bar"), (3, "bar"),
                       (4, "bar"), (5, "bar")]

    def test_cross_right(self):
        left = Dampr.memory([1, 2, 3, 4, 5])
        right = Dampr.memory(["foo", "bar"])
        out = left.cross_right(right, lambda x, y: (x, y)).read()
        assert out == [(1, "foo"), (1, "bar"), (2, "foo"), (2, "bar"),
                       (3, "foo"), (3, "bar"), (4, "foo"), (4, "bar"),
                       (5, "foo"), (5, "bar")]

    def test_cross_left_memory_cached(self):
        left = Dampr.memory([1, 2])
        right = Dampr.memory(["x"])
        out = left.cross_left(right, lambda x, y: (x, y), memory=True).read()
        assert out == [(1, "x"), (2, "x")]

    def test_cross_set(self):
        # Matches the reference's *actual* behavior (verified against the
        # reference implementation; its docstring is wrong): the small set is
        # the iterated side.
        left = Dampr.memory([1, 2, 3, 4, 5])
        right = Dampr.memory([3, 5])
        out = left.cross_set(right, lambda x, y: x in y, agg=set).read()
        assert out == [True, True]


class TestCustomOperators:
    def test_custom_mapper(self, items):
        out = items.custom_mapper(Map(lambda k, x: [(k, x + 1)])).read()
        assert out == list(range(11, 21))

    def test_custom_reducer(self, items):
        out = items.custom_reducer(Reduce(lambda k, it: sum(it))).read()
        assert sorted(out) == list(range(10, 20))

    def test_partition_map(self):
        def plus_one(vals):
            for num in vals:
                yield num, num + 1

        assert Dampr.memory([1, 2, 3, 4, 5]).partition_map(plus_one).read() == [
            2, 3, 4, 5, 6]

    def test_partition_reduce(self):
        def largest_number(it):
            largest = float("-inf")
            found = False
            for _gk, its in it:
                for value in its:
                    found = True
                    largest = max(largest, value)
            if found:
                yield "Largest", largest

        out = Dampr.memory([1, 2, 3, 4, 5]).partition_reduce(
            largest_number).read()
        assert ("Largest", 5) in out

    def test_block_mapper(self, items):
        class Summer(BlockMapper):
            def start(self):
                self.total = 0

            def add(self, k, v):
                self.total += v
                return ()

            def finish(self):
                yield 1, self.total

        out = items.custom_mapper(Summer()).read()
        assert sum(out) == sum(range(10, 20))

    def test_block_reducer(self, items):
        class SumGroups(BlockReducer):
            def start(self):
                self.total = 0

            def add(self, k, it):
                self.total += sum(it)
                return ()

            def finish(self):
                if self.total:
                    yield "total", self.total

        # custom_reducer with a stateful BlockReducer: start/add/finish run
        # per partition; partials sum to the global total.
        out = items.custom_reducer(SumGroups()).read()
        assert sum(out) == sum(range(10, 20))

    def test_stream_reducer_runs_on_empty_partition(self):
        def observe(groups):
            yield "ran", sum(1 for _ in groups)

        out = Dampr.memory([1]).partition_reduce(observe).read()
        # one record -> one non-empty partition; empty partitions still ran
        assert len(out) == 8  # = settings.partitions in this fixture
        assert sum(v[1] for v in out) == 1

    def test_stream_mapper_runs_on_empty(self):
        ran = []

        def streamer(vals):
            ran.append(True)
            return iter(())

        Dampr.memory([]).custom_mapper(StreamMapper(streamer)).read()
        assert ran


class TestPersistence:
    def test_checkpoint_shared_prefix(self, items):
        evens = items.filter(lambda x: x % 2 == 0).checkpoint()
        summed = evens.a_group_by(lambda x: 1).sum()
        prod = evens.a_group_by(lambda x: 1).reduce(lambda x, y: x * y)
        s, p = Dampr.run(summed, prod)
        assert s.read() == [(1, 10 + 12 + 14 + 16 + 18)]
        assert p.read() == [(1, 10 * 12 * 14 * 16 * 18)]

    def test_cached(self):
        out = Dampr.memory([1, 2, 3, 4, 5, 6]).mean(
            lambda x: x % 2).cached().read()
        assert out == [(0, 4.0), (1, 3.0)]

    def test_sink(self, items, tmp_path):
        path = str(tmp_path / "sink_out")
        items.map(str).sink(path).run()
        parts = sorted(os.listdir(path))
        assert parts
        lines = []
        for p in parts:
            with open(os.path.join(path, p)) as f:
                lines.extend(l.strip() for l in f)
        assert sorted(lines) == sorted(str(x) for x in range(10, 20))

    def test_sink_tsv_and_json(self, tmp_path):
        tsv = str(tmp_path / "tsv")
        Dampr.memory([("Hank Aaron", 755)]).sink_tsv(tsv).run()
        content = open(os.path.join(tsv, sorted(os.listdir(tsv))[0])).read()
        assert "Hank Aaron\t755" in content

        js = str(tmp_path / "js")
        Dampr.memory([{"name": "Hank", "hr": 755}]).sink_json(js).run()
        files = [os.path.join(js, p) for p in sorted(os.listdir(js))]
        recs = [json.loads(l) for p in files for l in open(p) if l.strip()]
        assert recs == [{"name": "Hank", "hr": 755}]

    def test_sink_read_back(self, items, tmp_path):
        path = str(tmp_path / "s2")
        emitted = items.map(str).sink(path).run().read()
        assert sorted(emitted) == sorted(str(x) for x in range(10, 20))

    def test_multi_output_run(self):
        foo = Dampr.memory([1, 2, 3, 4, 5])
        bar = Dampr.memory([6, 7, 8, 9, 10])
        left, right = Dampr.run(foo, bar)
        assert left.read() == [1, 2, 3, 4, 5]
        assert right.read() == [6, 7, 8, 9, 10]

    def test_emitter_stream_and_iter(self, items):
        em = items.run()
        assert list(em) == list(range(10, 20))
        assert em.read(3) == [10, 11, 12]
        em.delete()


class TestEmptyInputs:
    def test_empty_map(self):
        assert Dampr.memory([]).map(lambda x: x + 1).read() == []

    def test_empty_group(self):
        assert Dampr.memory([]).group_by(lambda x: x).reduce(
            lambda k, it: sum(it)).read() == []

    def test_filter_all_then_group(self, items):
        out = (items.filter(lambda x: x > 100)
               .group_by(lambda x: x).reduce(lambda k, it: sum(it)).read())
        assert out == []


class TestInputs:
    def test_text_multi_chunk_equals_single_chunk(self, tmp_path):
        p = str(tmp_path / "data.txt")
        lines = ["línea {} — ünïcode".format(i) for i in range(500)]
        with open(p, "w", encoding="utf-8") as f:
            f.write("\n".join(lines) + "\n")

        single = Dampr.text(p, chunk_size=1 << 30).read()
        multi = Dampr.text(p, chunk_size=256).read()  # splits mid-multibyte
        assert single == lines
        assert multi == lines

    def test_text_wordcount_matches_counter(self, tmp_path):
        import collections
        p = str(tmp_path / "corpus.txt")
        text = (reference_text()) * 3
        with open(p, "w") as f:
            f.write(text)
        got = dict(Dampr.text(p, chunk_size=4096)
                   .flat_map(lambda l: l.split())
                   .count().read())
        want = collections.Counter(text.split())
        assert got == dict(want)

    def test_glob_and_directory(self, tmp_path):
        d = tmp_path / "dir"
        d.mkdir()
        for i in range(3):
            (d / "f{}.txt".format(i)).write_text("line{}\n".format(i))
        (d / ".hidden").write_text("secret\n")
        out = Dampr.text(str(d)).read()
        assert sorted(out) == ["line0", "line1", "line2"]
        globbed = Dampr.text(str(d / "f*.txt")).read()
        assert sorted(globbed) == ["line0", "line1", "line2"]

    def test_symlinked_dir(self, tmp_path):
        real = tmp_path / "real"
        real.mkdir()
        (real / "a.txt").write_text("hello\n")
        link = tmp_path / "link"
        os.symlink(str(real), str(link))
        out = Dampr.text(str(link)).read()
        assert out == ["hello"]

    def test_gzip_input(self, tmp_path):
        import gzip as gz
        p = str(tmp_path / "data.txt.gz")
        with gz.open(p, "wt") as f:
            f.write("alpha\nbeta\n")
        assert Dampr.text(p).read() == ["alpha", "beta"]

    def test_json_input(self, tmp_path):
        p = str(tmp_path / "data.json")
        with open(p, "w") as f:
            for i in range(3):
                f.write(json.dumps({"i": i}) + "\n")
        out = Dampr.json(p).map(lambda d: d["i"]).read()
        assert out == [0, 1, 2]

    def test_custom_dataset_subclass(self):
        class RangeDataset(Dataset):
            def __init__(self, n):
                self.n = n

            def read(self):
                for i in range(self.n):
                    yield i, i

        out = Dampr.read_input(RangeDataset(5)).map(lambda x: x * 2).read()
        assert out == [0, 2, 4, 6, 8]

    def test_memory_zero_items(self):
        assert Dampr.memory([]).read() == []


class TestUtils:
    def test_filter_by_count(self):
        data = ["a"] * 5 + ["b"] * 2 + ["c"] * 1
        out = filter_by_count(Dampr.memory(data), lambda x: x,
                              lambda c: c >= 2).read()
        assert sorted(out) == ["a"] * 5 + ["b"] * 2

    def test_indexer(self, tmp_path):
        from dampr_tpu.utils import Indexer
        d = tmp_path / "docs"
        d.mkdir()
        (d / "doc1.txt").write_text("apple banana\nbanana cherry\n")
        (d / "doc2.txt").write_text("apple date\n")
        idx = Indexer(str(d / "*.txt"))
        total = idx.build(lambda line: line.split())
        assert total and total[0][1] == 6
        union = sorted(l.strip() for l in idx.union(["banana"]).read())
        assert union == ["apple banana", "banana cherry"]
        inter = sorted(l.strip() for l in idx.intersect(
            ["apple", "banana"]).read())
        assert inter == ["apple banana"]


class TestReferenceEdgeBehaviors:
    """Edge behaviors ported from the reference suite (test_dampr.py)."""

    def test_count_none_keys(self, items):
        # count(lambda x: None): all records share the None key
        out = items.count(lambda x: None).read()
        assert out == [(None, 10)]

    def test_repartition_disjoint_join_empty(self, items):
        # group_by different key fns -> co-partitioned by hash; disjoint key
        # spaces join to nothing (reference test_repartition)
        items2 = (Dampr.memory(list(range(10)))
                  .group_by(lambda x: -x).reduce(lambda k, vs: sum(vs)))
        out = items.group_by(lambda x: x).join(items2).run().read()
        assert out == []

    def test_cross_join_self(self, items):
        # cross of a source with itself: shared graph prefix dedups
        out = items.cross_left(items, lambda v1, v2: v1 * v2).run().read()
        expected = sorted(i * k for i in range(10, 20) for k in range(10, 20))
        assert sorted(out) == expected

    def test_cross_with_computed_total(self, items):
        item_counts = items.count()
        total = (items.a_group_by(lambda x: 1, lambda x: 1).sum()
                 .map(lambda x: float(x[1])))
        results = item_counts.cross_right(
            total, lambda ic, t: (ic[0], ic[1] / t)).read()
        assert sorted(results) == [(i, 0.1) for i in range(10, 20)]

    def test_group_by_single_key_via_run_iter(self, items):
        res = (items.group_by(lambda x: 1, lambda x: 1)
               .reduce(lambda k, it: sum(it)).run())
        assert next(iter(res))[1] == 10

    def test_urls_input_file_scheme(self, tmp_path):
        p = tmp_path / "u.txt"
        p.write_text("line one\nline two\n")
        out = Dampr.urls(["file://" + str(p)]).read()
        assert [l.strip() for l in out] == ["line one", "line two"]

    def test_urls_skip_on_error(self, tmp_path):
        good = tmp_path / "g.txt"
        good.write_text("ok\n")
        out = Dampr.urls(["file:///nonexistent-xyz",
                          "file://" + str(good)]).read()
        assert [l.strip() for l in out] == ["ok"]

    def test_run_n_partitions_override(self, items):
        out = (items.group_by(lambda x: x % 2)
               .reduce(lambda k, it: sum(it))
               .run(n_partitions=2).read())
        assert out == [(0, 70), (1, 75)]
