"""Run-history maintenance (dampr_tpu.obs.history v3 + the
dampr-tpu-history CLI): the v2 -> v3 upgrade and the health block,
vacuum's on-disk rewrite (invalid lines dropped, old records restamped
at the current schema), GC to the retention cap, and the CLI verbs
(--list, run listing, --fingerprint, --gc, --vacuum, --json).
"""

import json
import os

import pytest

from dampr_tpu import settings
from dampr_tpu.obs import history


def _v2_record(i, fp="cafecafecafecafe"):
    """A minimal valid v2-era corpus line (predates the health block)."""
    return {
        "schema": "dampr-tpu-history/2",
        "run": "old-run", "ts": 1000.0 + i, "fingerprint": fp,
        "wall_seconds": 1.0 + i / 10.0,
        "stages": [{"shape": "scan>map", "spill_bytes": 0}],
        "settings": {}, "throughput": {"mbps": 10.0},
    }


@pytest.fixture
def scratch(tmp_path):
    old = settings.scratch_root
    settings.scratch_root = str(tmp_path)
    yield tmp_path
    settings.scratch_root = old


def _write_corpus(name, records, extra_lines=()):
    path = history.corpus_path(name)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
        for line in extra_lines:
            f.write(line)
    return path


class TestSchemaV3:
    def test_upgrade_v2_adds_health(self):
        rec = history.upgrade(_v2_record(0))
        assert rec["v"] == 2
        assert rec["health"] == {}

    def test_upgrade_v1_chains_through_v3(self):
        rec = {"schema": "dampr-tpu-history/1", "run": "r",
               "stages": [{"shape": "s"}]}
        up = history.upgrade(rec)
        assert up["v"] == 1
        assert up["stages"][0]["shuffle_target"] is None
        assert up["health"] == {} and up["throughput"] == {}

    def test_compact_record_health_block(self):
        summary = {
            "run": "r", "started_at": 1.0, "wall_seconds": 2.0,
            "stages": [{"kind": "map", "jobs": 1, "records_out": 10,
                        "bytes_out": 100, "spill_bytes": 0,
                        "seconds": 1.0}],
            "faults": {"retries": 2, "quarantined": [3]},
            "reuse": {"hits": 3, "misses": 1},
        }
        rec = history.compact_record(summary)
        assert rec["schema"] == history.SCHEMA
        assert rec["health"]["retries"] == 2
        assert rec["health"]["quarantined"] == 1
        assert rec["health"]["reuse_hit_rate"] == pytest.approx(0.75)
        # no skew/mitigation sample -> late_ratio absent, not zero
        assert "late_ratio" not in rec["health"]

    def test_health_section_empty_when_nothing_sampled(self):
        assert history._health_section({"run": "r", "stages": []}) == {}


class TestVacuum:
    def test_vacuum_drops_garbage_and_restamps_on_disk(self, scratch):
        path = _write_corpus(
            "old-run", [_v2_record(i) for i in range(3)],
            extra_lines=["torn {line\n", "\n",
                         json.dumps({"schema": "foreign/9",
                                     "stages": []}) + "\n"])
        kept, dropped = history.vacuum(path)
        assert (kept, dropped) == (3, 3)
        with open(path) as f:
            lines = [json.loads(ln) for ln in f]
        assert len(lines) == 3
        for rec in lines:  # rewritten AT the current version, on disk
            assert rec["schema"] == history.SCHEMA
            assert rec["v"] == history.SCHEMA_VERSION
            assert rec["health"] == {}

    def test_vacuum_respects_cap(self, scratch):
        path = _write_corpus("old-run",
                             [_v2_record(i) for i in range(10)])
        kept, dropped = history.vacuum(path, cap=4)
        assert kept == 4
        recs = history.load("old-run")
        assert [r["ts"] for r in recs] == [1006.0, 1007.0, 1008.0, 1009.0]


class TestCLI:
    def test_missing_run_exits_one(self, scratch, capsys):
        assert history.main(["nonesuch"]) == 1
        assert "no history corpus" in capsys.readouterr().out

    def test_list_all_corpora(self, scratch, capsys):
        _write_corpus("run-a", [_v2_record(0)])
        _write_corpus("run-b", [_v2_record(0), _v2_record(1)])
        assert history.main(["--json"]) == 0
        rows = json.loads(capsys.readouterr().out)
        assert {r["run"] for r in rows} == {"run-a", "run-b"}
        by_run = {r["run"]: r for r in rows}
        assert by_run["run-b"]["records"] == 2
        assert by_run["run-a"]["fingerprints"] == ["cafecafecafecafe"]

    def test_run_listing_and_fingerprint_filter(self, scratch, capsys):
        _write_corpus("old-run",
                      [_v2_record(0), _v2_record(1, fp="deadbeefdeadbeef"),
                       _v2_record(2)])
        assert history.main(["old-run"]) == 0
        out = capsys.readouterr().out
        assert "3 record(s)" in out and "v2" in out
        assert history.main(["old-run", "--fingerprint",
                             "deadbeefdeadbeef", "--json"]) == 0
        recs = json.loads(capsys.readouterr().out)
        assert len(recs) == 1 and recs[0]["ts"] == 1001.0

    def test_gc_compacts_to_retention(self, scratch, capsys):
        _write_corpus("old-run", [_v2_record(i) for i in range(8)])
        old = settings.history_entries
        settings.history_entries = 5
        try:
            assert history.main(["old-run", "--gc", "--json"]) == 0
        finally:
            settings.history_entries = old
        report = json.loads(capsys.readouterr().out)
        assert report[0]["kept"] == 5
        assert len(history.load("old-run")) == 5

    def test_vacuum_verb_over_all_corpora(self, scratch, capsys):
        _write_corpus("run-a", [_v2_record(0)],
                      extra_lines=["garbage\n"])
        _write_corpus("run-b", [_v2_record(0)])
        assert history.main(["--vacuum"]) == 0
        out = capsys.readouterr().out
        assert "run-a: kept 1 record(s), dropped 1" in out
        assert "run-b: kept 1 record(s), dropped 0" in out
        with open(history.corpus_path("run-a")) as f:
            rec = json.loads(f.readline())
        assert rec["schema"] == history.SCHEMA
