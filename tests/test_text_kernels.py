"""Vectorized text kernels: native C++ pass vs numpy fallback vs pure-Python
ground truth, plus the block-protocol integration through the DSL."""

import collections
import operator
import re

import numpy as np
import pytest

from dampr_tpu import Dampr, native, settings
from dampr_tpu.ops import text as T

from conftest import reference_text

SAMPLE = (
    "The quick brown fox jumps over the lazy dog\n"
    "the quick BROWN fox, the dog!\n"
    "\n"
    "edge-case: under_scores and digits 123 mixed42tokens\n"
    "trailing line without newline"
).encode()

RX = re.compile(r"[^\w]+")


def py_word_counts(data):
    return collections.Counter(data.decode().split())


def py_doc_freq(data):
    c = collections.Counter()
    for line in data.decode().split("\n"):
        c.update(t for t in set(RX.split(line.lower())) if t)
    return c


@pytest.fixture(autouse=True)
def small_partitions():
    old = settings.partitions
    settings.partitions = 8
    yield
    settings.partitions = old


class TestChunkKernels:
    def test_token_counts_exact(self):
        got = {k: v[1] for k, v in T.chunk_token_counts(SAMPLE).iter_pairs()}
        assert got == dict(py_word_counts(SAMPLE))

    def test_doc_freq_exact(self):
        got = {k: v[1] for k, v in T.chunk_doc_freq(SAMPLE).iter_pairs()}
        assert got == dict(py_doc_freq(SAMPLE))

    def test_native_and_numpy_agree(self):
        data = reference_text().encode("utf-8") * 7
        import dampr_tpu.native as nat
        blk_native = T.chunk_doc_freq(data)
        old = nat._lib, nat._tried
        nat._lib, nat._tried = None, True  # force numpy fallback
        try:
            blk_numpy = T.chunk_doc_freq(data)
        finally:
            nat._lib, nat._tried = old
        a = {k: v[1] for k, v in blk_native.iter_pairs()}
        b = {k: v[1] for k, v in blk_numpy.iter_pairs()}
        assert a == b

    def test_hashes_match_hash_keys(self):
        # Tokens must group with equal Python-string keys engine-wide.
        from dampr_tpu.ops import hashing
        blk = T.chunk_token_counts(b"alpha beta gamma alpha")
        kh1, kh2 = hashing.hash_keys(blk.keys)
        np.testing.assert_array_equal(blk.h1, kh1)
        np.testing.assert_array_equal(blk.h2, kh2)

    def test_empty_and_separator_only(self):
        assert len(T.chunk_token_counts(b"")) == 0
        assert len(T.chunk_doc_freq(b"...!!!\n\n")) == 0

    def test_vocab_growth_past_table_resize(self):
        # >64k distinct tokens forces the native hash table to grow
        data = " ".join("tok%d" % i for i in range(200000)).encode()
        got = {k: v[1] for k, v in T.chunk_token_counts(data).iter_pairs()}
        assert len(got) == 200000
        assert all(v == 1 for v in got.values())

    def test_adversarial_corpora_vs_python_oracle(self):
        # Shapes that have bitten the native scan: token runs filling whole
        # 64-byte blocks (the SIMD walk's all-ones mask was a ctzll(0)
        # infinite loop), runs ending exactly at block edges, random
        # non-UTF-8 bytes, and case folding at every position.
        fold_tbl = bytes((b + 32) if 65 <= b <= 90 else b
                         for b in range(256))
        rng = np.random.RandomState(7)
        corpora = [
            bytes(rng.randint(0, 256, 20000, dtype=np.uint8)),
            bytes(rng.randint(60, 128, 60000, dtype=np.uint8)),  # dense runs
            b"a" * 64, b"a" * 65, b"Aa " * 21 + b"aA",
            ("A" * 200 + "\n" + "b" * 63 + " " + "Z" * 64 + "\n"
             ).encode() * 50,
        ]
        if native.get_lib() is None:
            pytest.skip("native library unavailable")
        for mode in (0, 1):
            for lower in (0, 1):
                for dedup in (0, 1):
                    for data in corpora:
                        res = native.token_counts(data, mode, lower, dedup)
                        buf = np.frombuffer(data, np.uint8)
                        got = {}
                        for i in range(len(res[0])):
                            key = bytes(buf[res[3][i]:res[3][i] + res[4][i]])
                            if lower:
                                key = key.translate(fold_tbl)
                            assert key not in got
                            got[key] = int(res[2][i])
                        want = collections.Counter()
                        for line in data.split(b"\n"):
                            if mode == 1:
                                toks = re.split(
                                    rb"[^0-9A-Za-z_\x80-\xff]+", line)
                            else:
                                toks = re.split(rb"[ \t\r\v\f]+", line)
                            toks = [t for t in toks if t]
                            if lower:
                                toks = [t.translate(fold_tbl) for t in toks]
                            want.update(set(toks) if dedup else toks)
                        assert got == dict(want), (mode, lower, dedup)


class TestDSLIntegration:
    def test_token_counts_pipeline_multi_chunk(self, tmp_path):
        p = str(tmp_path / "c.txt")
        data = reference_text() * 9
        with open(p, "w") as f:
            f.write(data)
        got = dict(
            Dampr.text(p, chunk_size=8192)
            .custom_mapper(T.TokenCounts())
            .fold_by(lambda kv: kv[0], operator.add, lambda kv: kv[1])
            .read())
        assert got == dict(py_word_counts(data.encode()))

    def test_doc_freq_pipeline_multi_chunk(self, tmp_path):
        p = str(tmp_path / "d.txt")
        data = reference_text() * 9
        with open(p, "w") as f:
            f.write(data)
        got = dict(
            Dampr.text(p, chunk_size=8192)
            .custom_mapper(T.DocFreq())
            .fold_by(lambda kv: kv[0], operator.add, lambda kv: kv[1])
            .read())
        assert got == dict(py_doc_freq(data.encode()))

    def test_len_vectorized_matches_python(self, tmp_path):
        p = str(tmp_path / "l.txt")
        with open(p, "w") as f:
            f.write("a\nb\nc\nd with words\n")
        assert Dampr.text(p, chunk_size=4).len().read() == [4]
        # unterminated final line
        p2 = str(tmp_path / "l2.txt")
        with open(p2, "w") as f:
            f.write("a\nb\nno-newline")
        assert Dampr.text(p2, chunk_size=5).len().read() == [3]
        # after per-record ops the generic Python path runs
        assert (Dampr.memory(list(range(7))).map(lambda x: x).len().read()
                == [7])

    def test_fallback_map_on_memory_input(self):
        # no read_bytes -> per-record fallback, same results
        lines = ["a b a", "b c"]
        got = dict(Dampr.memory(lines)
                   .custom_mapper(T.TokenCounts())
                   .fold_by(lambda kv: kv[0], operator.add,
                            lambda kv: kv[1]).read())
        assert got == {"a": 2, "b": 2, "c": 1}


class TestReviewRegressions:
    def test_long_tokens_numpy_fallback_exact(self):
        # 300-char tokens previously got uninitialized hashes on fallback
        import dampr_tpu.native as nat
        long_tok = "x" * 300
        data = ("a {t} b {t} c".format(t=long_tok)).encode()
        old = nat._lib, nat._tried
        nat._lib, nat._tried = None, True
        try:
            got = {k: v[1] for k, v in T.chunk_token_counts(data).iter_pairs()}
        finally:
            nat._lib, nat._tried = old
        assert got == {"a": 1, "b": 1, "c": 1, long_tok: 2}

    def test_unicode_lower_native_matches_numpy(self):
        data = "ÉCLAIR eclair\nÉCLAIR beta".encode()
        a = {k: v[1] for k, v in T.chunk_doc_freq(data).iter_pairs()}
        import dampr_tpu.native as nat
        old = nat._lib, nat._tried
        nat._lib, nat._tried = None, True
        try:
            b = {k: v[1] for k, v in T.chunk_doc_freq(data).iter_pairs()}
        finally:
            nat._lib, nat._tried = old
        assert a == b
        from dampr_tpu.ops import hashing
        blk = T.chunk_doc_freq(data)
        kh1, _ = hashing.hash_keys(blk.keys)
        np.testing.assert_array_equal(blk.h1, kh1)

    def test_non_utf8_tokens_lanes_match_keys(self):
        # Invalid UTF-8 bytes decode lossily to U+FFFD strings; the cached
        # hash lanes must equal hash_keys(materialized key) in both the
        # native and numpy paths (ADVICE r2 medium finding).
        from dampr_tpu.ops import hashing
        data = b"abc \xff\xfe def\nabc \xff\xfe again\n"
        for fn in (T.chunk_token_counts, T.chunk_doc_freq):
            blk = fn(data)
            assert len(blk)
            kh1, kh2 = hashing.hash_keys(blk.keys)
            np.testing.assert_array_equal(np.asarray(blk.h1), kh1)
            np.testing.assert_array_equal(np.asarray(blk.h2), kh2)

    def test_doc_freq_lossy_tokens_dedup_per_line(self):
        # Two distinct invalid byte tokens on one line decode to the same
        # U+FFFD string; the per-line *set* contract counts that line once.
        data = b"abc \xff \xfe xyz\n"
        got = {k: v[1] for k, v in T.chunk_doc_freq(data).iter_pairs()}
        assert got["�"] == 1
        assert got["abc"] == 1 and got["xyz"] == 1
        # and across lines it still counts per line
        got2 = {k: v[1]
                for k, v in T.chunk_doc_freq(data * 3).iter_pairs()}
        assert got2["�"] == 3

    def test_parse_numbers_no_fromstring(self, tmp_path):
        class _Bytes:
            def __init__(self, data):
                self._data = data

            def read_bytes(self):
                return self._data

        p = T.ParseNumbers()
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # any DeprecationWarning fails
            blocks = list(p.map_blocks(_Bytes(b"3\n1\n2\n")))
        got = sorted(v for _k, v in blocks[0].iter_pairs())
        assert got == [1, 2, 3]
        with pytest.raises(ValueError):
            list(p.map_blocks(_Bytes(b"1\nnope\n")))

    def test_gzip_len_streams(self, tmp_path):
        import gzip as gz
        p = str(tmp_path / "z.gz")
        with gz.open(p, "wt") as f:
            for i in range(1000):
                f.write("line %d\n" % i)
        assert Dampr.text(p).len().read() == [1000]


class TestNativeParse:
    def test_parse_i64_matches_numpy(self):
        import dampr_tpu.native as nat

        assert nat.get_lib() is not None
        data = b"3\n-17\n0\n+9\n9223372036854775807\n-9223372036854775808\n"
        arr = nat.parse_i64(np.frombuffer(data, dtype=np.uint8))
        want = np.array(data.split(), dtype=np.int64)
        np.testing.assert_array_equal(arr, want)

    def test_parse_i64_rejects_junk_and_overflow(self):
        import dampr_tpu.native as nat

        for bad in (b"1\nx\n", b"12a\n", b"9223372036854775808\n",
                    b"-9223372036854775809\n", b"-\n"):
            with pytest.raises(ValueError):
                nat.parse_i64(np.frombuffer(bad, dtype=np.uint8))

    def test_parse_numbers_block_path_exact(self):
        class _Bytes:
            def __init__(self, data):
                self._data = data

            def read_bytes(self):
                return self._data

        p = T.ParseNumbers()
        blocks = list(p.map_blocks(_Bytes(b"5\n-2\n7\n")))
        assert sorted(v for _k, v in blocks[0].iter_pairs()) == [-2, 5, 7]


class TestFoldValues:
    def test_fold_values_matches_fold_by(self, tmp_path):
        p = str(tmp_path / "c.txt")
        data = reference_text() * 9
        open(p, "w").write(data)
        fast = dict(
            Dampr.text(p, chunk_size=8192)
            .custom_mapper(T.DocFreq(pair_values=False))
            .fold_values(operator.add).read())
        slow = dict(
            Dampr.text(p, chunk_size=8192)
            .custom_mapper(T.DocFreq())
            .fold_by(lambda kv: kv[0], operator.add, lambda kv: kv[1])
            .read())
        assert fast == slow

    def test_pair_values_false_block_is_numeric(self):
        blk = T.chunk_doc_freq(SAMPLE, pair_values=False)
        assert blk.values.dtype == np.int64
        from dampr_tpu.ops import hashing
        kh1, _ = hashing.hash_keys(blk.keys)
        np.testing.assert_array_equal(np.asarray(blk.h1), kh1)

    def test_fold_values_per_record_fallback(self):
        lines = ["a b a", "b c"]
        got = dict(Dampr.memory(lines)
                   .custom_mapper(T.TokenCounts(pair_values=False))
                   .fold_values(operator.add).read())
        assert got == {"a": 2, "b": 2, "c": 1}

    def test_fold_values_output_value_shape(self, tmp_path):
        p = str(tmp_path / "v.txt")
        open(p, "w").write("x y x\n")
        vals = (Dampr.text(p)
                .custom_mapper(T.TokenCounts(pair_values=False))
                .fold_values(operator.add).run().read())
        assert sorted(vals) == [("x", 2), ("y", 1)]
