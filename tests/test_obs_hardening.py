"""Obs-plane hardening satellites: Prometheus text-format escaping and
empty expositions (obs.promtext), counter-event epoch clamping
(obs.export), dampr-tpu-stats --series on degenerate runs, and the
check_bench --trend trajectory gate."""

import importlib.util
import json
import os

import pytest

from dampr_tpu import settings
from dampr_tpu.obs import export, promtext
from dampr_tpu.obs.metrics import Metrics

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


validate_trace = _load_tool("validate_trace")
check_bench = _load_tool("check_bench")

with open(os.path.join(ROOT, "docs", "trace_schema.json")) as _f:
    TRACE_SCHEMA = json.load(_f)


class TestPromtextEscaping:
    def test_label_value_escapes(self):
        assert promtext.escape_label_value('a\\b') == 'a\\\\b'
        assert promtext.escape_label_value('a"b') == 'a\\"b'
        assert promtext.escape_label_value('a\nb') == 'a\\nb'
        # order matters: the backslash introduced by the quote escape
        # must not be re-escaped
        assert promtext.escape_label_value('\\"') == '\\\\\\"'

    def test_run_label_with_hostile_name_renders_one_line_per_sample(self):
        out = promtext.render_summary({
            "run": 'bad"run\nname\\x',
            "metrics": {"counters": {"store.records": 5}},
        })
        lines = out.splitlines()
        # exactly TYPE + sample: a raw newline in the label would split
        # the sample line and corrupt the exposition
        assert len(lines) == 2
        assert lines[0] == "# TYPE dampr_tpu_store_records_total counter"
        assert '\\"run\\nname\\\\x' in lines[1]
        assert lines[1].endswith(" 5.0")

    def test_histograms_carry_type_lines(self):
        out = promtext.render_summary({
            "run": "r",
            "metrics": {"histograms": {
                "merge.fanin": {"count": 3, "sum": 12.0,
                                "min": 2, "max": 6}}},
        })
        lines = out.splitlines()
        assert "# TYPE dampr_tpu_merge_fanin summary" in lines
        assert "# TYPE dampr_tpu_merge_fanin_min gauge" in lines
        assert "# TYPE dampr_tpu_merge_fanin_max gauge" in lines
        assert any(l.startswith('dampr_tpu_merge_fanin_count{run="r"} 3')
                   for l in lines)
        # every sample line is preceded (somewhere) by its TYPE
        for l in lines:
            if l.startswith("#"):
                assert l.split()[1] == "TYPE"

    def test_empty_exposition_is_valid_and_empty(self):
        # no metrics section at all
        assert promtext.render_summary({"run": "r"}) == ""
        # a metrics section with nothing in it
        assert promtext.render_summary({"run": "r", "metrics": {}}) == ""
        # a live registry with no samples renders without crashing
        m = Metrics("empty-run")
        out = promtext.render(m)
        assert isinstance(out, str)
        for line in out.splitlines():
            assert line.startswith("#") or " " in line


class TestCounterEpochClamp:
    def test_pre_epoch_samples_clamp_to_zero(self):
        """A sample recorded before the (re-pointed) epoch must not emit
        a negative Chrome ts — clamp to the run origin."""
        m = Metrics("clamp-run")
        # simulate the sampler's first tick landing BEFORE the tracer's
        # run epoch: relative timestamps go negative
        m.series["writer.queue_depth"] = [(-0.25, 3), (-0.1, 4), (0.2, 5)]
        events = export.counter_events(m)
        assert len(events) == 3
        ts = [ev["ts"] for ev in events]
        assert ts == [0.0, 0.0, pytest.approx(0.2e6)]
        assert all(t >= 0 for t in ts)

    def test_clamped_trace_validates(self, tmp_path):
        """The clamped document passes the schema + per-series monotonic
        pin (two clamped samples are non-decreasing at 0)."""
        from dampr_tpu.obs.trace import Tracer

        tracer = Tracer("clamp-run")
        with_span = tracer.span("stage", "s0:map", lane="stages")
        with with_span:
            pass
        m = Metrics("clamp-run")
        m.series["g"] = [(-0.5, 1), (0.1, 2)]
        path = str(tmp_path / "trace.json")
        export.write_trace(tracer, path, metrics=m)
        with open(path) as f:
            doc = json.load(f)
        errors = validate_trace.validate(doc, TRACE_SCHEMA)
        assert errors == [], errors
        cs = [ev for ev in doc["traceEvents"] if ev.get("ph") == "C"]
        assert [ev["ts"] for ev in cs] == [0.0, pytest.approx(1e5)]


class TestSeriesDegenerate:
    def _run_dir_with_trace(self, tmp_path, events, run="deg-run"):
        d = tmp_path / "trace"
        d.mkdir(parents=True)
        trace = {"traceEvents": events, "displayTimeUnit": "ms",
                 "otherData": {"run": run}}
        tp = d / "trace.json"
        with open(tp, "w") as f:
            json.dump(trace, f)
        stats = {"schema": "dampr-tpu-stats/1", "run": run,
                 "wall_seconds": 1.0, "stages": [],
                 "trace_file": str(tp), "stats_file": str(d / "stats.json")}
        with open(d / "stats.json", "w") as f:
            json.dump(stats, f)
        return str(tmp_path)

    def _cli(self, argv, monkeypatch, capsys):
        import sys

        from dampr_tpu import cli

        monkeypatch.setattr(sys, "argv", ["dampr-tpu-stats"] + argv)
        rc = 0
        try:
            cli.stats()
        except SystemExit as e:
            rc = e.code or 0
        out = capsys.readouterr()
        return rc, out.out + out.err

    def test_single_sample_series(self, tmp_path, monkeypatch, capsys):
        run_dir = self._run_dir_with_trace(tmp_path, [
            {"ph": "M", "pid": 1, "tid": 0, "name": "thread_name",
             "args": {"name": "t"}},
            {"ph": "C", "name": "g", "cat": "metric", "pid": 1, "tid": 0,
             "ts": 100.0, "args": {"value": 7}},
        ])
        rc, out = self._cli([run_dir, "--series"], monkeypatch, capsys)
        assert rc == 0
        assert "g" in out and "7" in out

    def test_all_zero_counters(self, tmp_path, monkeypatch, capsys):
        events = [{"ph": "C", "name": "z", "cat": "metric", "pid": 1,
                   "tid": 0, "ts": float(i) * 1000,
                   "args": {"value": 0}} for i in range(5)]
        run_dir = self._run_dir_with_trace(tmp_path, events)
        rc, out = self._cli([run_dir, "--series"], monkeypatch, capsys)
        assert rc == 0
        # a flat zero series renders (flat sparkline), no ZeroDivision
        assert "z" in out

    def test_spans_but_no_counters_reports_no_series(self, tmp_path,
                                                     monkeypatch, capsys):
        run_dir = self._run_dir_with_trace(tmp_path, [
            {"ph": "X", "cat": "stage", "name": "s0:map", "pid": 1,
             "tid": 1, "ts": 0.0, "dur": 1000.0},
        ])
        rc, out = self._cli([run_dir, "--series"], monkeypatch, capsys)
        assert rc == 0
        assert "no counter samples" in out

    def test_format_series_degenerate_units(self):
        assert "no counter samples" in export.format_series({})
        one = export.format_series({"a": [(0.0, 5.0)]})
        assert "a" in one
        flat = export.format_series({"z": [(0.0, 0.0), (1.0, 0.0)]})
        assert "z" in flat


class TestCheckBenchTrend:
    def _rec(self, v, metric="mbps"):
        return {"metric": metric, "value": v}

    def test_monotone_decline_flags(self):
        t = check_bench.trend(
            self._rec(70), [("r1", self._rec(100)), ("r2", self._rec(90)),
                            ("r3", self._rec(80))])
        assert t["regressing"] is True
        assert t["declining"] == 4

    def test_recovery_resets(self):
        t = check_bench.trend(
            self._rec(95), [("r1", self._rec(100)), ("r2", self._rec(80)),
                            ("r3", self._rec(90))])
        # 80 -> 90 -> 95 is improving; only fresh vs r3 comparison counts
        assert t["regressing"] is False

    def test_short_history_notes(self):
        t = check_bench.trend(self._rec(50), [("r1", self._rec(100))])
        assert t["regressing"] is False
        assert "at least 3" in t["note"]

    def test_metric_mismatch_excluded(self):
        t = check_bench.trend(
            self._rec(70, metric="a"),
            [("r1", self._rec(100, metric="b")),
             ("r2", self._rec(90, metric="b")),
             ("r3", self._rec(80, metric="a"))])
        # only r3 + fresh comparable -> too short to trend
        assert t["regressing"] is False
        assert len(t["points"]) == 2

    def test_main_trend_warn_only(self, tmp_path, capsys):
        paths = []
        for name, v in (("r1", 100), ("r2", 90), ("r3", 85),
                        ("fresh", 80)):
            p = tmp_path / (name + ".json")
            with open(p, "w") as f:
                json.dump(self._rec(v), f)
            paths.append(str(p))
        rc = check_bench.main(
            [paths[-1], "--baseline"] + paths[:-1]
            + ["--tolerance", "0.5", "--trend"])
        out = capsys.readouterr().out
        assert rc == 0  # warn-only: trend never changes the exit code
        assert "TREND WARN" in out

    def test_main_trend_quiet_when_healthy(self, tmp_path, capsys):
        paths = []
        for name, v in (("r1", 100), ("r2", 110), ("fresh", 120)):
            p = tmp_path / (name + ".json")
            with open(p, "w") as f:
                json.dump(self._rec(v), f)
            paths.append(str(p))
        rc = check_bench.main(
            [paths[-1], "--baseline"] + paths[:-1] + ["--trend"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "TREND WARN" not in out
        assert "trend:" in out
