"""Ingest planner, readahead, and splittable-gzip (BGZF) taps."""

import gzip
import os
import struct
import zlib

import numpy as np
import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu import inputs as I


def write_bgzf(path, text, block_lines=7):
    """Minimal BGZF writer: one gzip member per `block_lines` lines, each
    carrying the htslib BC extra subfield with its compressed size."""
    lines = text.splitlines(keepends=True)
    with open(path, "wb") as f:
        for at in range(0, len(lines), block_lines):
            payload = "".join(lines[at:at + block_lines]).encode()
            f.write(_bgzf_member(payload))
        f.write(_bgzf_member(b""))  # EOF marker block


def _bgzf_member(payload):
    comp = zlib.compressobj(6, zlib.DEFLATED, -15)
    cdata = comp.compress(payload) + comp.flush()
    bsize = 12 + 6 + len(cdata) + 8  # hdr + extra + deflate + crc/isize
    hdr = struct.pack(
        "<2sBBIBBH2sHH", b"\x1f\x8b", 8, 4, 0, 0, 255, 6, b"BC", 2,
        bsize - 1)
    return hdr + cdata + struct.pack("<II", zlib.crc32(payload) & 0xFFFFFFFF,
                                     len(payload) & 0xFFFFFFFF)


SAMPLE = "".join("line %04d with words\n" % i for i in range(200))


class TestPlanner:
    def test_plan_text_ranges(self, tmp_path):
        p = str(tmp_path / "a.txt")
        open(p, "w").write(SAMPLE)
        size = os.path.getsize(p)
        specs = I.plan_chunks(p, 1000)
        assert all(s.kind == "text" for s in specs)
        assert specs[0].start == 0 and specs[-1].end == size
        assert len(specs) == -(-size // 1000)

    def test_sniff_by_magic_not_extension(self, tmp_path):
        fake_gz = str(tmp_path / "fake.gz")  # plain text, lying name
        open(fake_gz, "w").write(SAMPLE)
        specs = I.plan_chunks(fake_gz, 1000)
        assert all(s.kind == "text" for s in specs)
        assert len(specs) > 1  # splittable, unlike extension-based routing

        real_gz = str(tmp_path / "real.gz")
        with gzip.open(real_gz, "wt") as f:
            f.write(SAMPLE)
        specs = I.plan_chunks(real_gz, 10)
        assert [s.kind for s in specs] == ["gzip"]  # unsplittable

    def test_scandir_walk_sorted_and_hides_dotfiles(self, tmp_path):
        d = tmp_path / "tree"
        (d / "sub").mkdir(parents=True)
        (d / "b.txt").write_text("b\n")
        (d / "a.txt").write_text("a\n")
        (d / ".hidden").write_text("x\n")
        (d / "sub" / "c.txt").write_text("c\n")
        got = list(I.read_paths(str(d)))
        assert got == [str(d / "a.txt"), str(d / "b.txt"),
                       str(d / "sub" / "c.txt")]


class TestBgzf:
    def test_detected_and_split(self, tmp_path):
        p = str(tmp_path / "x.bgzf.gz")
        write_bgzf(p, SAMPLE)
        assert I._sniff(p) == "bgzf"
        specs = I.plan_chunks(p, 300)  # small: several member groups
        assert all(s.kind == "bgzf" for s in specs)
        assert len(specs) > 3

    def test_chunks_cover_every_line_exactly_once(self, tmp_path):
        p = str(tmp_path / "x.gz")
        write_bgzf(p, SAMPLE, block_lines=3)
        for chunk_size in (100, 250, 1000, 10 ** 6):
            specs = I.plan_chunks(p, chunk_size)
            text = b"".join(I._spec_dataset(s).read_bytes()
                            for s in specs).decode()
            assert text == SAMPLE, chunk_size

    def test_read_lines_match(self, tmp_path):
        p = str(tmp_path / "x.gz")
        write_bgzf(p, SAMPLE, block_lines=5)
        specs = I.plan_chunks(p, 200)
        lines = []
        for s in specs:
            lines.extend(v for _k, v in I._spec_dataset(s).read())
        assert lines == [ln for ln in SAMPLE.split("\n") if ln != ""]

    def test_pipeline_matches_plain_text(self, tmp_path):
        plain = str(tmp_path / "plain.txt")
        open(plain, "w").write(SAMPLE)
        bg = str(tmp_path / "blocked.gz")
        write_bgzf(bg, SAMPLE, block_lines=4)
        a = dict(Dampr.text(plain, 500).flat_map(str.split).count().read())
        b = dict(Dampr.text(bg, 300).flat_map(str.split).count().read())
        assert a == b


class TestBgzfEdgeCases:
    def test_trailing_plain_gzip_member_falls_back_whole(self, tmp_path):
        # A legal gzip concatenation whose tail is NOT BGZF must not split
        # (splitting would silently drop the tail): whole-stream fallback.
        p = str(tmp_path / "mixed.gz")
        with open(p, "wb") as f:
            f.write(_bgzf_member(b"a\nb\nc\nd\n"))
            f.write(_bgzf_member(b"e\nf\n"))
            f.write(gzip.compress(b"g\nh\n"))
        specs = I.plan_chunks(p, 10)
        assert [s.kind for s in specs] == ["gzip"]
        got = I._spec_dataset(specs[0]).read_bytes()
        assert got == b"a\nb\nc\nd\ne\nf\ng\nh\n"  # nothing lost

    def test_gzi_index_plans_without_member_walk(self, tmp_path):
        p = str(tmp_path / "x.gz")
        write_bgzf(p, SAMPLE, block_lines=5)
        walk_specs = I.plan_chunks(p, 300)
        # synthesize the .gzi from the walk's member offsets
        offs = []
        size = os.path.getsize(p)
        with open(p, "rb") as f:
            off = 0
            while off < size:
                ms = I._bgzf_member_size(f, off)
                off += ms
                if off < size:
                    offs.append(off)
        with open(p + ".gzi", "wb") as f:
            f.write(len(offs).to_bytes(8, "little"))
            for o in offs:
                f.write(o.to_bytes(8, "little"))
                f.write((0).to_bytes(8, "little"))  # uncompressed: unused
        gzi_specs = I.plan_chunks(p, 300)
        assert [(s.start, s.end) for s in gzi_specs] == [
            (s.start, s.end) for s in walk_specs]
        text = b"".join(I._spec_dataset(s).read_bytes()
                        for s in gzi_specs).decode()
        assert text == SAMPLE

    def test_broken_symlink_ignored(self, tmp_path):
        d = tmp_path / "dir"
        d.mkdir()
        (d / "ok.txt").write_text("fine\n")
        os.symlink(str(tmp_path / "nonexistent"), str(d / "broken.txt"))
        got = list(I.read_paths(str(d) + "/*.txt"))
        assert got == [str(d / "ok.txt")]

    def test_bgzf_keys_are_ints(self, tmp_path):
        p = str(tmp_path / "x.gz")
        write_bgzf(p, SAMPLE, block_lines=5)
        spec = I.plan_chunks(p, 300)[1]
        for k, _v in I._spec_dataset(spec).read():
            assert isinstance(k, int)


class TestReadahead:
    def test_prefetch_matches_direct(self, tmp_path):
        p = str(tmp_path / "a.txt")
        open(p, "w").write(SAMPLE)
        old = settings.readahead_chunks
        settings.readahead_chunks = 2
        try:
            chunks = list(I.PathInput(p, chunk_size=400).chunks())
            assert any(isinstance(c, I.PrefetchedChunk) for c in chunks)
            direct = list(I.TextInput(p, chunk_size=400).chunks())
            for c, d in zip(chunks, direct):
                assert c.read_bytes() == d.read_bytes()
        finally:
            settings.readahead_chunks = old

    def test_out_of_order_take(self):
        loads = [lambda i=i: b"chunk%d" % i for i in range(6)]
        ra = I.Readahead(loads, depth=2)
        assert ra.take(3) == b"chunk3"
        assert ra.take(0) == b"chunk0"
        assert ra.take(5) == b"chunk5"
        assert ra.take(1) == b"chunk1"

    def test_inflight_load_is_waited_not_duplicated(self):
        import threading
        import time

        calls = []
        gate = threading.Event()

        def slow0():
            calls.append(0)
            gate.wait(5)
            return b"zero"

        def fast1():
            calls.append(1)
            return b"one"

        ra = I.Readahead([slow0, fast1], depth=1)
        t = threading.Thread(target=lambda: calls.append(("got", ra.take(0))))
        t.start()
        time.sleep(0.2)  # let the prefetch thread start loading 0
        gate.set()
        t.join(5)
        assert ("got", b"zero") in calls
        assert calls.count(0) == 1  # never loaded twice

    def test_loader_error_propagates(self):
        def boom():
            raise IOError("disk gone")

        ra = I.Readahead([boom], depth=1)
        with pytest.raises(IOError):
            ra.take(0)

    def test_zero_depth_disables(self, tmp_path):
        p = str(tmp_path / "a.txt")
        open(p, "w").write(SAMPLE)
        old = settings.readahead_chunks
        settings.readahead_chunks = 0
        try:
            chunks = list(I.PathInput(p, chunk_size=400).chunks())
            assert not any(isinstance(c, I.PrefetchedChunk) for c in chunks)
        finally:
            settings.readahead_chunks = old
