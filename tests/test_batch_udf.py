"""Batched-UDF execution: ``apply_batch`` must be record-for-record
equivalent to the ``stream`` lowering for every RecordOp, and the runner's
batch path must produce identical pipeline results to the generator path.

SURVEY §7 hard part 1 (batched host execution for opaque lambdas); the loop
being replaced is the reference's per-record generator chain
(ref stagerunner.py:73-74).
"""

import random

import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.base import (Filter, FlatMap, Inspect, MapKeys, MapValues,
                            Prefix, Rekey, Sample, Suffix, ValueMap,
                            record_op_chain)


def _run_both(op, records):
    """Run one op's stream and batch lowerings over the same records."""
    streamed = list(op.stream(iter(records)))
    ks = [k for k, _ in records]
    vs = [v for _, v in records]
    bks, bvs = op.apply_batch(list(ks), list(vs))
    return streamed, list(zip(bks, bvs))


RECORDS = [(i, (i % 5, i * 2)) for i in range(200)]
FLAT_RECORDS = [(i, i) for i in range(200)]


class TestOpEquivalence:
    """batch ≡ stream, op by op, including output order."""

    @pytest.mark.parametrize("op,records", [
        (ValueMap(lambda v: (v[0], v[1] + 1)), RECORDS),
        (MapValues(lambda b: b * 10), RECORDS),
        (MapKeys(lambda a: a - 1), RECORDS),
        (Prefix(lambda v: v[0]), RECORDS),
        (Suffix(lambda v: v[1]), RECORDS),
        (Filter(lambda v: v[1] % 3 == 0), RECORDS),
        (Filter(lambda v: False), RECORDS),
        (Filter(lambda v: True), RECORDS),
        (FlatMap(lambda v: [v, v, v]), FLAT_RECORDS),
        (FlatMap(lambda v: []), FLAT_RECORDS),
        (FlatMap(lambda v: (x for x in range(v % 4))), FLAT_RECORDS),
        (Rekey(lambda v: v[0]), RECORDS),
        (Rekey(lambda v: v[0], lambda v: v[1]), RECORDS),
        (Inspect("t"), FLAT_RECORDS[:3]),
    ])
    def test_batch_equals_stream(self, op, records):
        streamed, batched = _run_both(op, records)
        assert streamed == batched

    def test_sample_rng_sequence_identity(self):
        # Both lowerings must consume the identical random sequence: same
        # seed => same records selected, in the same order.
        def factory():
            return random.Random(1234)

        op = Sample(0.4, factory)
        streamed, batched = _run_both(op, FLAT_RECORDS)
        assert streamed == batched
        assert 30 < len(streamed) < 130  # actually sampled, not all/none

    def test_stateful_filter_sees_stream_order(self):
        # A self-contained stateful UDF (dedupe seen-set) must observe
        # records in the same order under both lowerings.
        def run(lowering):
            seen = set()

            def dedupe(v):
                if v in seen:
                    return False
                seen.add(v)
                return True

            op = Filter(dedupe)
            records = [(i, i % 7) for i in range(50)]
            if lowering == "stream":
                return list(op.stream(iter(records)))
            ks, vs = op.apply_batch([k for k, _ in records],
                                    [v for _, v in records])
            return list(zip(ks, vs))

        assert run("stream") == run("batch")
        assert [v for _, v in run("stream")] == list(range(7))


class TestChainFlattening:
    def test_chain_extracted_from_fused_pipeline(self):
        from dampr_tpu.base import fuse

        ops = [ValueMap(lambda v: v + 1), Filter(lambda v: v % 2 == 0),
               FlatMap(lambda v: [v, -v])]
        fused = fuse(ops)
        chain = record_op_chain(fused)
        assert chain is not None and len(chain) == 3

    def test_opaque_mapper_defeats_chain(self):
        from dampr_tpu.base import Map, fuse

        ops = [ValueMap(lambda v: v + 1), Map(lambda k, v: [(k, v)])]
        assert record_op_chain(fuse(ops)) is None


class TestPipelineEquivalence:
    """End-to-end: batch_udf on/off produce identical results."""

    def _pipeline(self, data):
        return (Dampr.memory(data)
                .map(lambda x: x * 3)
                .filter(lambda x: x % 2 == 0)
                .flat_map(lambda x: [x, x + 1])
                .map(lambda x: x - 1))

    def _fold_pipeline(self, data):
        return (Dampr.memory(data)
                .map(lambda x: x + 1)
                .fold_by(lambda x: x % 10, binop=lambda a, b: a + b))

    @pytest.mark.parametrize("maker", ["_pipeline", "_fold_pipeline"])
    def test_on_off_identical(self, maker):
        data = list(range(3000))
        old = settings.batch_udf
        try:
            settings.batch_udf = True
            on = sorted(getattr(self, maker)(data).run().read())
            settings.batch_udf = False
            off = sorted(getattr(self, maker)(data).run().read())
        finally:
            settings.batch_udf = old
        assert on == off

    def test_batch_path_is_taken(self, monkeypatch):
        # Guard against the path silently unwiring again (round-4 bug):
        # assert apply_batch actually runs during a plain .map pipeline.
        calls = []
        orig = ValueMap.apply_batch

        def spy(self, ks, vs):
            calls.append(len(ks))
            return orig(self, ks, vs)

        monkeypatch.setattr(ValueMap, "apply_batch", spy)
        old = settings.batch_udf
        try:
            settings.batch_udf = True
            out = Dampr.memory(list(range(100))).map(lambda x: x + 1).run()
            assert sorted(out.read()) == list(range(1, 101))
        finally:
            settings.batch_udf = old
        assert calls and sum(calls) == 100

    def test_flatmap_ordering_within_partition(self):
        # FlatMap expansion order must survive the batch path: each key's
        # emitted elements stay contiguous and ordered.
        old = settings.batch_udf
        try:
            settings.batch_udf = True
            out = (Dampr.memory([5])
                   .flat_map(lambda x: list(range(x)))
                   .run())
            assert list(v for v in out.read()) == [0, 1, 2, 3, 4]
        finally:
            settings.batch_udf = old


class TestReadLists:
    """read_lists must yield exactly read()'s records for every chunk
    boundary placement (the chunk-ownership contract)."""

    def test_equivalence_across_boundaries(self, tmp_path):
        from dampr_tpu.dataset import TextLineDataset

        p = tmp_path / "t.txt"
        lines = ["line %d %s" % (i, "x" * (i % 13)) for i in range(500)]
        p.write_text("\n".join(lines) + ("\n" if True else ""))
        size = p.stat().st_size
        # sweep chunk boundaries, including mid-line and exact-newline cuts
        for cut in [0, 1, 7, size // 3, size // 2, size - 2, size]:
            a = TextLineDataset(str(p), 0, cut)
            b = TextLineDataset(str(p), cut, None)
            got = []
            for ds in (a, b):
                for ks, vs in ds.read_lists(64):
                    got.extend(zip(ks, vs))
            want = list(a.read()) + list(b.read())
            assert got == want, "cut=%d" % cut

    def test_no_trailing_newline(self, tmp_path):
        from dampr_tpu.dataset import TextLineDataset

        p = tmp_path / "t.txt"
        p.write_bytes(b"alpha\nbeta\ngamma")  # no trailing newline
        ds = TextLineDataset(str(p))
        got = [kv for ks, vs in ds.read_lists(2) for kv in zip(ks, vs)]
        assert got == list(ds.read())

    def test_empty_file(self, tmp_path):
        from dampr_tpu.dataset import TextLineDataset

        p = tmp_path / "t.txt"
        p.write_bytes(b"")
        assert list(TextLineDataset(str(p)).read_lists(8)) == []


class TestObjectLaneFolds:
    def test_huge_numpy_ints_fold_exactly(self):
        # Object value lanes holding numpy scalars must normalize to Python
        # values before reaching an opaque user binop (np.int64 would wrap).
        import numpy as np

        out = (Dampr.memory([0, 1])
               .map(lambda x: np.int64(2 ** 62))
               .fold_by(lambda v: "k", binop=lambda a, b: a + b))
        assert dict(out.read()) == {"k": 2 ** 63}

    def test_selective_filter_coalesces_blocks(self):
        # 0.4% selectivity over many batches: outputs must still be exact
        # (and internally coalesce, not register thousands of tiny blocks).
        old = settings.batch_udf
        try:
            settings.batch_udf = True
            out = (Dampr.memory(list(range(200_000)), partitions=2)
                   .filter(lambda x: x % 250 == 0)
                   .run())
            assert sorted(out.read()) == list(range(0, 200_000, 250))
        finally:
            settings.batch_udf = old

    def test_high_fanout_flatmap_sliced(self):
        # Fanout ~200 forces the adaptive FlatMap slicing path; results
        # must stay exact and ordered per key.
        old = settings.batch_udf
        try:
            settings.batch_udf = True
            out = (Dampr.memory(list(range(5000)), partitions=1)
                   .flat_map(lambda x: [x] * 200)
                   .fold_by(lambda x: x % 2, binop=lambda a, b: a + b))
            got = dict(out.read())
            want0 = sum(x * 200 for x in range(0, 5000, 2))
            want1 = sum(x * 200 for x in range(1, 5000, 2))
            assert got == {0: want0, 1: want1}
        finally:
            settings.batch_udf = old
