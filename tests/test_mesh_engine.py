"""Engine <-> mesh integration: device-foldable associative reduces route
through the collective shuffle on multi-device meshes, with exact fallbacks."""

import numpy as np
import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.runner import MTRunner


@pytest.fixture(autouse=True)
def small_partitions():
    old = (settings.partitions, settings.mesh_fold)
    settings.partitions = 8
    settings.mesh_fold = "auto"
    yield
    settings.partitions, settings.mesh_fold = old


def _run_counting(pipe):
    pipe = pipe if not pipe.agg else pipe.checkpoint()
    runner = MTRunner("mesh-engine-test", pipe.pmer.graph)
    out = runner.run([pipe.source])
    return out[0], runner


class TestMeshFoldEngagement:
    def test_count_routes_through_mesh(self):
        pipe = Dampr.memory(list(range(5000)), partitions=8).count(
            lambda x: x % 7)
        ds, runner = _run_counting(pipe)
        assert runner.mesh_folds >= 1
        got = dict(v for _k, v in ds.read())
        want = {i: len(range(i, 5000, 7)) for i in range(7)}
        assert got == want

    def test_sum_matches_host_path(self):
        data = list(range(3000))
        mesh_out = (Dampr.memory(data, partitions=8)
                    .a_group_by(lambda x: x % 5).sum().read())
        settings.mesh_fold = "off"
        host_out = (Dampr.memory(data, partitions=8)
                    .a_group_by(lambda x: x % 5).sum().read())
        assert mesh_out == host_out

    def test_min_max_via_mesh(self):
        data = [(i % 4, i) for i in range(2000)]
        mn = dict(Dampr.memory(data, partitions=8)
                  .a_group_by(lambda x: x[0], lambda x: x[1])
                  .reduce(min).read())
        assert mn == {0: 0, 1: 1, 2: 2, 3: 3}
        mx = dict(Dampr.memory(data, partitions=8)
                  .a_group_by(lambda x: x[0], lambda x: x[1])
                  .reduce(max).read())
        assert mx == {0: 1996, 1: 1997, 2: 1998, 3: 1999}

    def test_opaque_binop_stays_on_host(self):
        pipe = (Dampr.memory(list(range(100)), partitions=4)
                .a_group_by(lambda x: x % 3)
                .reduce(lambda a, b: a + b))
        ds, runner = _run_counting(pipe)
        assert runner.mesh_folds == 0  # opaque Python binop: host path
        got = dict(v for _k, v in ds.read())
        assert got == {i: sum(range(i, 100, 3)) for i in range(3)}

    def test_object_values_stay_on_host(self):
        pipe = (Dampr.memory(["a", "bb", "a"], partitions=2)
                .a_group_by(lambda s: s).sum())  # str concat: object lane
        ds, runner = _run_counting(pipe)
        assert runner.mesh_folds == 0
        got = dict(v for _k, v in ds.read())
        assert got == {"a": "aa", "bb": "bb"}

    def test_large_values_fall_back_exactly(self):
        # int64 beyond 32-bit lanes: host path keeps exactness
        data = [("k", 2 ** 40)] * 50
        out = dict(Dampr.memory(data, partitions=4)
                   .a_group_by(lambda x: x[0], lambda x: x[1]).sum().read())
        assert out == {"k": 50 * 2 ** 40}

    def test_string_keys_via_mesh(self):
        words = ["alpha", "beta", "gamma"] * 500
        pipe = Dampr.memory(words, partitions=8).count()
        ds, runner = _run_counting(pipe)
        assert runner.mesh_folds >= 1
        got = dict(v for _k, v in ds.read())
        assert got == {"alpha": 500, "beta": 500, "gamma": 500}


class TestMeshFoldOverBudget:
    """VERDICT r2 task 2: the mesh path must survive over-budget (spilled)
    inputs by streaming windows through the collective, instead of bailing
    to the host exactly when distribution would pay."""

    def test_spilled_count_stays_on_mesh(self):
        # 5000 distinct keys keep map-side combining from shrinking the
        # exchange below the 64KB budget: the reduce input spills, and the
        # mesh fold must stream the spilled runs in windows, not refuse.
        data = [i % 5000 for i in range(60000)]
        pipe = Dampr.memory(data, partitions=8).count()
        pipe = pipe if not pipe.agg else pipe.checkpoint()
        runner = MTRunner("mesh-overbudget", pipe.pmer.graph,
                          memory_budget=1 << 16)
        out = runner.run([pipe.source])[0]
        assert runner.mesh_folds >= 1, "over-budget input left the mesh path"
        assert runner.store.spill_count > 0, "input never spilled"
        got = dict(v for _k, v in out.read())
        want = {k: 12 for k in range(5000)}
        assert got == want

    def test_spilled_string_fold_windows_exact(self):
        words = ["w%d" % (i % 499) for i in range(40000)]
        pipe = Dampr.memory(words, partitions=8).count()
        pipe = pipe if not pipe.agg else pipe.checkpoint()
        runner = MTRunner("mesh-overbudget-str", pipe.pmer.graph,
                          memory_budget=1 << 16)
        out = runner.run([pipe.source])[0]
        assert runner.mesh_folds >= 1
        got = dict(v for _k, v in out.read())
        assert got == {"w%d" % k: len(range(k, 40000, 499)) and
                       len([i for i in range(40000) if i % 499 == k])
                       for k in range(499)}

    def test_cross_window_overflow_falls_back_exact(self):
        # Each window's values fit the 32-bit lanes but the cross-window
        # total does not: the running host-side bound must push the fold to
        # the exact host path instead of wrapping device partials.  7000
        # distinct keys defeat map-side combining, so the reduce input is
        # ~2.7MB >> the 1MB window floor — genuinely multi-window (a
        # single-window regression cannot hide here) — while each window's
        # own abs-sum (~43k records x 3e4 ≈ 1.3e9) stays under 2^31.
        n, k, val = 120000, 7000, 30000  # total 3.6e9 > 2^31
        data = [(i % k, val) for i in range(n)]
        pipe = (Dampr.memory(data, partitions=8)
                .a_group_by(lambda x: x[0], lambda x: x[1]).sum()
                .checkpoint())
        runner = MTRunner("mesh-xwindow", pipe.pmer.graph,
                          memory_budget=1 << 16)
        out = dict(v for _k, v in runner.run([pipe.source])[0].read())
        want = {i: (n // k + (1 if i < n % k else 0)) * val
                for i in range(k)}
        assert out == want

    def test_min_over_budget_matches_host(self):
        data = [(i % 97, (i * 7919) % 100003) for i in range(30000)]

        def build():
            return (Dampr.memory(data, partitions=8)
                    .a_group_by(lambda x: x[0], lambda x: x[1]).reduce(min)
                    .checkpoint())

        p1 = build()
        r1 = MTRunner("mesh-ob-min", p1.pmer.graph, memory_budget=1 << 16)
        mesh_got = sorted(v for _k, v in r1.run([p1.source])[0].read())
        assert r1.mesh_folds >= 1
        settings.mesh_fold = "off"
        p2 = build()
        r2 = MTRunner("host-ob-min", p2.pmer.graph, memory_budget=1 << 16)
        host_got = sorted(v for _k, v in r2.run([p2.source])[0].read())
        assert mesh_got == host_got
