"""Structured log stream (dampr_tpu.obs.log): record shape and level
floor, capacity compaction, tolerant reads, the near-zero disabled-path
pin, the stdlib warn mirror, run integration (events.jsonl + the
stats()["log"] section + byte-identity with logging on), and the
crashdump log tail riding the flight recorder.
"""

import json
import logging
import operator
import os

import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.obs import log as obslog
from dampr_tpu.obs.flightrec import FlightRecorder
from dampr_tpu.obs.log import LogStream

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

with open(os.path.join(ROOT, "docs", "trace_schema.json")) as _f:
    _LOG_ITEM_SCHEMA = (json.load(_f)["properties"]["otherData"]
                        ["properties"]["log"]["items"])


@pytest.fixture
def logged(tmp_path):
    """Structured logging on (debug) with isolated artifacts."""
    old = (settings.log_level, settings.trace_dir, settings.scratch_root)
    settings.log_level = "debug"
    settings.trace_dir = str(tmp_path / "traces")
    settings.scratch_root = str(tmp_path / "scratch")
    yield tmp_path
    (settings.log_level, settings.trace_dir, settings.scratch_root) = old


class TestLogStream:
    def test_record_shape_and_floor(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        s = LogStream("r", rank=1, level="info", path=path)
        assert s.emit("debug", "run-start", "below floor") is None
        rec = s.emit("warn", "codec-fallback", "zstd gone", stage=3,
                     data={"codec": "zstd"})
        for key in ("ts", "level", "rank", "run", "stage", "code", "msg"):
            assert key in rec, key
        assert rec["level"] == "warn" and rec["rank"] == 1
        assert rec["code"] == "codec-fallback" and rec["stage"] == 3
        assert rec["data"] == {"codec": "zstd"}
        assert s.counts == {"warn": 1}
        assert s.summary()["records"] == 1
        assert s.summary()["level"] == "info"
        # one valid JSONL line on disk
        recs = obslog.tail(path)
        assert len(recs) == 1 and recs[0]["code"] == "codec-fallback"

    def test_capacity_compaction_bounds_the_file(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        s = LogStream("r", level="debug", path=path, capacity=16)
        # Compaction checks are amortized (every max(64, cap//8)
        # appends), so overshoot well past one check interval.
        for i in range(200):
            s.emit("info", "run-start", "event %d" % i)
        with open(path) as f:
            lines = f.readlines()
        assert len(lines) <= 16 + 64, len(lines)
        s._compact_if_over()
        with open(path) as f:
            lines = f.readlines()
        assert len(lines) <= 16
        # newest records survive
        assert obslog.tail(path, n=1)[0]["msg"] == "event 199"

    def test_zero_capacity_disables_disk(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        s = LogStream("r", level="debug", path=path, capacity=0)
        s.emit("info", "run-start", "x")
        assert s.path is None and not os.path.exists(path)

    def test_warn_mirrors_into_recorder(self, tmp_path):
        rec = FlightRecorder("r", 64)
        s = LogStream("r", level="info", path=None, recorder=rec)
        s.emit("info", "run-start", "not mirrored")
        s.emit("warn", "writer-pool-stuck", "mirrored")
        s.emit("error", "run-failed", "mirrored too")
        tail = list(rec._log)
        assert [r["code"] for r in tail] == ["writer-pool-stuck",
                                             "run-failed"]

    def test_floor_above_warn_still_mirrors(self, tmp_path):
        """A stream floored at error must still push warns into the
        crash tail (the crashdump is the record of last resort)."""
        rec = FlightRecorder("r", 64)
        s = LogStream("r", level="error", path=None, recorder=rec)
        assert s.emit("warn", "codec-fallback", "dropped on disk") is None
        assert [r["code"] for r in rec._log] == ["codec-fallback"]


class TestTolerantReads:
    def test_valid_line_rejects_garbage(self):
        assert obslog.valid_line("") is None
        assert obslog.valid_line("   \n") is None
        assert obslog.valid_line("not json {") is None
        assert obslog.valid_line('["a", "list"]') is None
        assert obslog.valid_line(json.dumps({"level": "info"})) is None
        assert obslog.valid_line(
            json.dumps({"level": "loud", "code": "x"})) is None
        ok = obslog.valid_line(json.dumps(
            {"ts": 1.0, "level": "info", "rank": 0, "run": "r",
             "code": "run-start", "msg": "m"}))
        assert ok is not None and ok["code"] == "run-start"

    def test_tail_survives_corruption(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        good = {"ts": 1.0, "level": "warn", "rank": 0, "run": "r",
                "code": "codec-fallback", "msg": "m"}
        with open(path, "w") as f:
            f.write(json.dumps(dict(good, msg="first")) + "\n")
            f.write("torn-li")  # crash mid-append
            f.write("\n" + json.dumps(dict(good, msg="last")) + "\n")
        recs = obslog.tail(path)
        assert [r["msg"] for r in recs] == ["first", "last"]
        assert obslog.tail(str(tmp_path / "missing.jsonl")) == []

    def test_tail_level_floor_and_bound(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        s = LogStream("r", level="debug", path=path)
        for i in range(10):
            s.emit("debug", "run-start", "d%d" % i)
        s.emit("warn", "codec-fallback", "w")
        assert len(obslog.tail(path, n=5)) == 5
        warns = obslog.tail(path, min_level="warn")
        assert [r["msg"] for r in warns] == ["w"]

    def test_format_tail(self):
        text = obslog.format_tail([
            {"ts": 0, "level": "warn", "rank": 1, "stage": 2,
             "code": "codec-fallback", "msg": "zstd unavailable"}])
        assert "WARN" in text and "r1 s2" in text
        assert "[codec-fallback]" in text
        assert "DAMPR_TPU_LOG" in obslog.format_tail([])


class TestDisabledPath:
    def test_off_is_one_none_check(self):
        """The off-path pin: no active stream means the leveled helpers
        return before touching codes, rendering, or any file — an
        unregistered code and crashing %-args must both be inert."""
        assert obslog.active() is None and not obslog.enabled()
        obslog.debug("never-a-registered-code", "%d", "not-an-int")
        obslog.info("never-a-registered-code", "x")

    def test_warn_reaches_stdlib_even_when_off(self, caplog):
        assert obslog.active() is None
        with caplog.at_level(logging.WARNING, "dampr_tpu"):
            obslog.warn("codec-fallback", "codec %s gone", "zstd",
                        logger=logging.getLogger("dampr_tpu.io.codecs"))
        assert any("codec zstd gone" in r.getMessage()
                   for r in caplog.records)

    def test_start_stop_scoping(self, tmp_path):
        s = LogStream("r", level="debug",
                      path=str(tmp_path / "e.jsonl"))
        obslog.start(s)
        try:
            assert obslog.active() is s
            obslog.info("run-start", "via module api")
            assert s.counts.get("info") == 1
            # stopping a DIFFERENT stream must not clear the active one
            obslog.stop(LogStream("other"))
            assert obslog.active() is s
        finally:
            obslog.stop(s)
        assert obslog.active() is None


class TestRunIntegration:
    def test_events_jsonl_and_stats_section(self, logged):
        em = (Dampr.memory([(i % 7, i) for i in range(4000)])
              .group_by(lambda kv: kv[0])
              .reduce(lambda k, vs: sum(v[1] for v in vs))
              .run("log-smoke"))
        stats = em.stats()
        sec = stats.get("log")
        assert sec and sec["level"] == "debug", sec
        assert sec["records"] >= 2  # at least run-start + run-finish
        recs = obslog.tail("log-smoke")
        codes = [r["code"] for r in recs]
        assert codes[0] == "run-start" and "run-finish" in codes
        for r in recs:
            assert r["code"] in obslog.EVENT_CODES, r
        # the stream is run-scoped: stopped after finalize
        assert obslog.active() is None
        em.delete()

    def test_results_byte_identical_log_on_vs_off(self, tmp_path):
        def build():
            return (Dampr.memory(list(range(3000)))
                    .map(lambda x: (x % 11, x))
                    .fold_by(lambda kv: kv[0], operator.add,
                             lambda kv: kv[1]))

        old = (settings.log_level, settings.scratch_root,
               settings.trace_dir)
        try:
            settings.scratch_root = str(tmp_path / "off")
            settings.trace_dir = str(tmp_path / "off-traces")
            settings.log_level = ""
            off = sorted(build().run("ident").stream())
            settings.scratch_root = str(tmp_path / "on")
            settings.trace_dir = str(tmp_path / "on-traces")
            settings.log_level = "debug"
            on = sorted(build().run("ident").stream())
        finally:
            (settings.log_level, settings.scratch_root,
             settings.trace_dir) = old
        assert off == on

    def test_crashdump_carries_log_tail(self, logged):
        old = (settings.trace, settings.flight_recorder_events)
        settings.trace = True
        settings.flight_recorder_events = 256

        def boom(x):
            if x == 1234:
                raise RuntimeError("intentional crash")
            return (x, 1)

        try:
            with pytest.raises(Exception):
                Dampr.memory(list(range(4000))).map(boom).run("log-crash")
        finally:
            settings.trace, settings.flight_recorder_events = old
        dump = os.path.join(settings.trace_dir, "log-crash", "trace",
                            "crashdump.json")
        assert os.path.isfile(dump), dump
        with open(dump) as f:
            doc = json.load(f)
        tail = doc["otherData"].get("log")
        assert tail, "crashdump carries no log tail"
        assert any(r["code"] == "run-failed" for r in tail), tail
        for rec in tail:  # every entry matches the checked-in schema
            for key in _LOG_ITEM_SCHEMA["required"]:
                assert key in rec, (key, rec)
            assert rec["level"] in ("debug", "info", "warn", "error")
            assert rec["code"] in obslog.EVENT_CODES
