"""Real multi-process distributed backend: 2 OS processes join via
jax.distributed.initialize (localhost coordinator), form one 8-device
global mesh (4 virtual CPU devices per process), and drive the collective
shuffle across the process boundary — psum and the keyed fold both verified
exact on every process (VERDICT r2 task 7: init_distributed had zero
coverage)."""

import os
import socket
import subprocess
import sys
import textwrap

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = textwrap.dedent("""
    import os, sys
    pid = int(sys.argv[1]); port = sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, "@ROOT@")
    from dampr_tpu.parallel.mesh import init_distributed, data_mesh
    init_distributed(coordinator_address="localhost:%s" % port,
                     num_processes=2, process_id=pid)
    assert jax.process_count() == 2
    assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4
    import numpy as np
    from dampr_tpu import settings
    settings.device_min_batch = 1
    from dampr_tpu.ops import hashing
    from dampr_tpu.parallel import mesh_global_sum, mesh_keyed_fold
    mesh = data_mesh()
    rng = np.random.RandomState(7)
    keys = rng.randint(0, 50, size=4096)
    vals = rng.randint(0, 9, size=4096).astype(np.int64)
    h1, h2 = hashing.hash_keys(keys)
    total = mesh_global_sum(mesh, vals)
    assert total == int(vals.sum()), (total, int(vals.sum()))
    fh1, fh2, fv = mesh_keyed_fold(mesh, h1, h2, vals, "sum")
    import collections
    want = collections.Counter()
    for k, v in zip(keys.tolist(), vals.tolist()):
        want[k] += v
    kh1, kh2 = hashing.hash_keys(np.arange(50))
    lut = {(int(a), int(b)): i
           for i, (a, b) in enumerate(zip(kh1, kh2))}
    got = {lut[(int(a), int(b))]: int(v)
           for a, b, v in zip(fh1, fh2, fv)}
    assert got == dict(want), "keyed fold diverged on process %d" % pid

    # General byte exchange (exchange.py): route object-valued blocks by
    # partition id across the process-spanning mesh and verify every
    # process sees the full delivered set (gather-replicated outputs).
    from dampr_tpu.blocks import Block
    from dampr_tpu.parallel.exchange import mesh_shuffle_blocks
    D = 8
    routed = []
    expect = {}
    seq = 0
    for src in range(D):
        for tpid in (src, (src + 3) % D, src + D):
            ks = np.array(["k%d_%d" % (tpid, src)], dtype=object)
            vs = np.array([("val", tpid, src)], dtype=object)
            bh1, bh2 = hashing.hash_keys(ks)
            routed.append((seq, src, tpid, Block(ks, vs, bh1, bh2)))
            expect.setdefault(tpid, []).append((seq, ks[0]))
            seq += 1
    received, moved = mesh_shuffle_blocks(mesh, routed)
    assert moved > 0
    got_pids = {}
    for rpid, blk in received:
        for k in blk.keys:
            got_pids.setdefault(rpid, []).append(k)
    want_pids = {rpid: [k for _s, k in sorted(entries)]
                 for rpid, entries in expect.items()}
    assert got_pids == want_pids, (
        "general exchange diverged on process %d" % pid)
    print("PROC_%d_OK" % pid, flush=True)
""").replace("@ROOT@", ROOT)


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestTwoProcessBackend:
    def test_keyed_fold_and_psum_across_processes(self, tmp_path):
        port = _free_port()
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # worker sets its own device count
        script = str(tmp_path / "worker.py")
        with open(script, "w") as f:
            f.write(_WORKER)
        procs = [
            subprocess.Popen(
                [sys.executable, script, str(i), str(port)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, env=env)
            for i in range(2)]
        outs = []
        for p in procs:
            try:
                out, err = p.communicate(timeout=240)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            outs.append((p.returncode, out, err))
        for i, (rc, out, err) in enumerate(outs):
            assert rc == 0, (i, out, err[-2000:])
            assert "PROC_%d_OK" % i in out, (i, out, err[-2000:])
