"""Real multi-process distributed backend: 2 OS processes join via
jax.distributed.initialize (localhost coordinator), form one 8-device
global mesh (4 virtual CPU devices per process), and drive the collective
shuffle across the process boundary — psum and the keyed fold both verified
exact on every process (VERDICT r2 task 7: init_distributed had zero
coverage).  The engine leg runs full DSL pipelines (keyed fold, general
group_by exchange, range sort) across the 2-process mesh and pins them
byte-identical to the single-process host path; the replan property tests
pin the chunked-exchange schedule's HBM-budget invariants on random
shapes."""

import os
import random
import socket
import subprocess
import sys
import textwrap

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = textwrap.dedent("""
    import os, sys
    pid = int(sys.argv[1]); port = sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, "@ROOT@")
    from dampr_tpu.parallel.mesh import init_distributed, data_mesh
    init_distributed(coordinator_address="localhost:%s" % port,
                     num_processes=2, process_id=pid)
    assert jax.process_count() == 2
    assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4
    import numpy as np
    from dampr_tpu import settings
    settings.device_min_batch = 1
    from dampr_tpu.ops import hashing
    from dampr_tpu.parallel import mesh_global_sum, mesh_keyed_fold
    mesh = data_mesh()
    rng = np.random.RandomState(7)
    keys = rng.randint(0, 50, size=4096)
    vals = rng.randint(0, 9, size=4096).astype(np.int64)
    h1, h2 = hashing.hash_keys(keys)
    total = mesh_global_sum(mesh, vals)
    assert total == int(vals.sum()), (total, int(vals.sum()))
    fh1, fh2, fv = mesh_keyed_fold(mesh, h1, h2, vals, "sum")
    import collections
    want = collections.Counter()
    for k, v in zip(keys.tolist(), vals.tolist()):
        want[k] += v
    kh1, kh2 = hashing.hash_keys(np.arange(50))
    lut = {(int(a), int(b)): i
           for i, (a, b) in enumerate(zip(kh1, kh2))}
    got = {lut[(int(a), int(b))]: int(v)
           for a, b, v in zip(fh1, fh2, fv)}
    assert got == dict(want), "keyed fold diverged on process %d" % pid

    # General byte exchange (exchange.py): route object-valued blocks by
    # partition id across the process-spanning mesh and verify every
    # process sees the full delivered set (gather-replicated outputs).
    from dampr_tpu.blocks import Block
    from dampr_tpu.parallel.exchange import mesh_shuffle_blocks
    D = 8
    routed = []
    expect = {}
    seq = 0
    for src in range(D):
        for tpid in (src, (src + 3) % D, src + D):
            ks = np.array(["k%d_%d" % (tpid, src)], dtype=object)
            vs = np.array([("val", tpid, src)], dtype=object)
            bh1, bh2 = hashing.hash_keys(ks)
            routed.append((seq, src, tpid, Block(ks, vs, bh1, bh2)))
            expect.setdefault(tpid, []).append((seq, ks[0]))
            seq += 1
    received, moved = mesh_shuffle_blocks(mesh, routed)
    assert moved > 0
    got_pids = {}
    for rpid, blk in received:
        for k in blk.keys:
            got_pids.setdefault(rpid, []).append(k)
    want_pids = {rpid: [k for _s, k in sorted(entries)]
                 for rpid, entries in expect.items()}
    assert got_pids == want_pids, (
        "general exchange diverged on process %d" % pid)

    # Chunked gloo exchange under a tight HBM budget: the same blobs must
    # arrive byte-identical through a multi-step replan schedule whose
    # modeled peak in-flight bytes stay under the budget on every process.
    from dampr_tpu.parallel import exchange as px, replan
    from dampr_tpu.parallel.mesh import data_mesh as _dm
    budget = 1 << 18
    rngb = np.random.RandomState(11)
    blobs = {}
    for s in range(D):
        for d in range(D):
            if (s + d) % 2 == 0:
                n = int(rngb.randint(1, 9000))
                blobs[(s, d)] = rngb.randint(
                    0, 256, size=n).astype(np.uint8).tobytes()
    delivered = px.mesh_blob_exchange(mesh, blobs, budget=budget)
    assert delivered == blobs, (
        "chunked exchange diverged on process %d" % pid)
    info = px.last_info
    assert info["steps"] > 1, info
    assert not info["clamped"], info
    assert info["peak_inflight_bytes"] <= budget, info
    assert info["peak_inflight_bytes"] == replan.plan_exchange(
        D, {sd: len(b) for sd, b in blobs.items()}, budget=budget,
        gather=True).peak_inflight_bytes
    print("PROC_%d_OK" % pid, flush=True)
""").replace("@ROOT@", ROOT)


# Engine pipelines across the 2-process mesh: every process drives the SAME
# DSL runs (input replicated; the collectives span both processes' devices
# and gather-replicate results), and each pins its mesh results
# byte-identical to the host path computed in-process with the mesh off.
_ENGINE_WORKER = textwrap.dedent("""
    import os, sys, tempfile
    pid = int(sys.argv[1]); port = sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, "@ROOT@")
    from dampr_tpu.parallel.mesh import init_distributed, data_mesh
    init_distributed(coordinator_address="localhost:%s" % port,
                     num_processes=2, process_id=pid)
    assert jax.process_count() == 2 and len(jax.devices()) == 8
    from dampr_tpu import Dampr, settings
    from dampr_tpu.runner import MTRunner
    settings.scratch_root = tempfile.mkdtemp(prefix="dampr-mp-%d-" % pid)
    settings.partitions = 8
    settings.device_min_batch = 1

    def run_pipe(pipe, name, budget=None):
        kw = {"memory_budget": budget} if budget else {}
        runner = MTRunner("%s-p%d" % (name, pid), pipe.pmer.graph, **kw)
        out = runner.run([pipe.source])[0]
        got = list(out.read())
        return got, runner

    # 1. keyed fold through the collective fold program (2 processes)
    settings.mesh_fold = "on"; settings.mesh_exchange = "off"
    data = list(range(6000))
    fold_mesh, r = run_pipe(
        Dampr.memory(data, partitions=8).count(lambda x: x % 23),
        "mp-fold-mesh")
    assert r.mesh_folds >= 1, "mesh fold never engaged"
    settings.mesh_fold = "off"
    fold_host, _ = run_pipe(
        Dampr.memory(data, partitions=8).count(lambda x: x % 23),
        "mp-fold-host")
    assert sorted(fold_mesh) == sorted(fold_host), (
        "mesh keyed fold diverged from host on process %d" % pid)

    # 2. non-associative group_by through the general byte exchange
    settings.mesh_fold = "off"; settings.mesh_exchange = "on"
    gdata = [(i % 13, i) for i in range(4000)]
    def build_g():
        return (Dampr.memory(gdata, partitions=8)
                .group_by(lambda x: x[0])
                .reduce(lambda k, vs: sorted(v[1] for v in vs)[:3]))
    g_mesh, r = run_pipe(build_g(), "mp-group-mesh")
    assert r.mesh_exchanges >= 1, "general exchange never engaged"
    assert r.mesh_exchange_steps >= 1
    assert r.mesh_exchange_peak_inflight <= settings.exchange_hbm_budget
    settings.mesh_exchange = "off"
    g_host, _ = run_pipe(build_g(), "mp-group-host")
    assert g_mesh == g_host, (
        "mesh group_by diverged from host on process %d" % pid)

    # 3. range sort: read-time redistribution through the collective
    from dampr_tpu.parallel import exchange as px
    settings.mesh_exchange = "on"
    nums = [((i * 2654435761) % 99991) - 50000 for i in range(5000)]
    before = px.total_exchanges
    s_mesh, _ = run_pipe(
        Dampr.memory(nums, partitions=8).sort_by(lambda x: x),
        "mp-sort-mesh", budget=1 << 16)
    assert px.total_exchanges > before, "range sort never hit the mesh"
    settings.mesh_exchange = "off"
    s_host, _ = run_pipe(
        Dampr.memory(nums, partitions=8).sort_by(lambda x: x),
        "mp-sort-host", budget=1 << 16)
    assert s_mesh == s_host, (
        "mesh range sort diverged from host on process %d" % pid)
    assert [v for _k, v in s_mesh] == sorted(nums)
    print("ENGINE_%d_OK" % pid, flush=True)
""").replace("@ROOT@", ROOT)


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_workers(tmp_path, source, ok_marker, timeout=240):
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    script = str(tmp_path / "worker.py")
    with open(script, "w") as f:
        f.write(source)
    procs = [
        subprocess.Popen(
            [sys.executable, script, str(i), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env)
        for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, (i, out, err[-2000:])
        assert ok_marker % i in out, (i, out, err[-2000:])


class TestTwoProcessBackend:
    def test_keyed_fold_and_psum_across_processes(self, tmp_path):
        _spawn_workers(tmp_path, _WORKER, "PROC_%d_OK")

    def test_engine_pipelines_across_processes(self, tmp_path):
        """Full DSL runs on the 2-process mesh: keyed fold (collective
        fold program), group_by (general byte exchange), and range sort
        (read-time redistribution) — each byte-identical to the host path
        on every process."""
        _spawn_workers(tmp_path, _ENGINE_WORKER, "ENGINE_%d_OK")


class TestExchangeSchedule:
    """Host-side property tests for the replan schedule (no processes
    spawned): for random blob shapes and budgets, the chunked schedule
    must respect the configured HBM budget, cover every byte exactly
    once in piece order, and reassemble to the original blobs."""

    def _random_sizes(self, rng, n_dev):
        sizes = {}
        for s in range(n_dev):
            for d in range(n_dev):
                if rng.random() < 0.6:
                    scale = rng.choice([10, 1000, 100000])
                    sizes[(s, d)] = rng.randrange(0, scale)
        return sizes

    def test_schedule_never_exceeds_budget(self):
        from dampr_tpu.parallel import replan

        rng = random.Random(42)
        for trial in range(200):
            n_dev = rng.choice([2, 4, 8, 16])
            gather = rng.random() < 0.5
            floor = replan.step_inflight_bytes(
                n_dev, replan.MIN_CAPACITY, gather)
            budget = rng.randrange(floor, 64 * floor)
            sizes = self._random_sizes(rng, n_dev)
            sched = replan.plan_exchange(n_dev, sizes, budget=budget,
                                         gather=gather)
            assert not sched.clamped
            assert sched.peak_inflight_bytes <= budget, (
                trial, budget, sched.peak_inflight_bytes)
            for step in sched.steps:
                assert step.inflight_bytes <= budget
                # capacity stays a pow2 at or above the floor
                c = step.capacity
                assert c >= replan.MIN_CAPACITY and (c & (c - 1)) == 0

    def test_schedule_covers_every_byte_in_order(self):
        from dampr_tpu.parallel import replan

        rng = random.Random(7)
        for _trial in range(100):
            n_dev = rng.choice([2, 4, 8])
            sizes = self._random_sizes(rng, n_dev)
            floor = replan.step_inflight_bytes(
                n_dev, replan.MIN_CAPACITY, False)
            sched = replan.plan_exchange(
                n_dev, sizes, budget=rng.randrange(floor, 32 * floor))
            seen = {sd: [] for sd in sizes}
            for step in sched.steps:
                for s, d, start, stop in step.cells:
                    assert stop - start <= step.capacity
                    seen[(s, d)].append((start, stop))
            for sd, n in sizes.items():
                spans = seen[sd]
                # contiguous, in order, exactly covering [0, n)
                at = 0
                for start, stop in spans:
                    assert start == at, (sd, spans)
                    at = stop
                assert at == n, (sd, at, n)
            assert sched.total_bytes == sum(sizes.values())

    def test_tiny_budget_clamps_at_floor(self):
        from dampr_tpu.parallel import replan

        sched = replan.plan_exchange(8, {(0, 1): 4096}, budget=1)
        assert sched.clamped
        # still moves everything, at the capacity floor
        assert sched.total_bytes == 4096
        assert all(s.capacity == replan.MIN_CAPACITY
                   for s in sched.steps)

    def test_explicit_chunk_knob_narrows_pieces(self):
        from dampr_tpu import settings
        from dampr_tpu.parallel import replan

        wide = replan.plan_exchange(4, {(0, 1): 1 << 20},
                                    budget=1 << 26)
        narrow = replan.plan_exchange(4, {(0, 1): 1 << 20},
                                      budget=1 << 26,
                                      chunk_bytes=4096)
        assert narrow.n_steps > wide.n_steps
        assert all(s.capacity <= 4096 for s in narrow.steps)
        # a non-pow2 chunk is an UPPER bound — pieces must round DOWN,
        # never exceed what the memory-pressured operator asked for
        odd = replan.plan_exchange(4, {(0, 1): 1 << 20},
                                   budget=1 << 26, chunk_bytes=5000)
        assert all(s.capacity <= 5000 for s in odd.steps)
        assert max(s.capacity for s in odd.steps) == 4096
        old = settings.exchange_chunk_bytes
        settings.exchange_chunk_bytes = 4096
        try:
            via_setting = replan.plan_exchange(4, {(0, 1): 1 << 20},
                                               budget=1 << 26)
            assert via_setting.n_steps == narrow.n_steps
        finally:
            settings.exchange_chunk_bytes = old

    def test_roundtrip_through_mesh_matches_naive(self, mesh8):
        """Scheduled exchange delivers byte-identical blobs at any
        budget (in-process 8-device mesh)."""
        from dampr_tpu.parallel import exchange as px, replan

        rng = random.Random(3)
        blobs = {}
        for s in range(8):
            for d in range(8):
                if rng.random() < 0.5:
                    n = rng.randrange(0, 30000)
                    blobs[(s, d)] = bytes(
                        rng.getrandbits(8) for _ in range(min(n, 512))
                    ) * max(1, n // 512)
        want = {sd: b for sd, b in blobs.items() if b}
        for budget in (1 << 17, 1 << 20, 1 << 26):
            out = px.mesh_blob_exchange(mesh8, blobs, budget=budget)
            assert out == want, budget
            floor = replan.step_inflight_bytes(8, replan.MIN_CAPACITY,
                                               False)
            if budget >= floor:
                assert px.last_info["peak_inflight_bytes"] <= budget
