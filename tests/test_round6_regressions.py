"""Round-6 advisor regressions: identity-checkpoint alias provenance,
BlockRef.offload publish order, mixed-dtype composite-lane concat."""

import threading

import numpy as np
import pytest

from dampr_tpu import Dampr, settings


@pytest.fixture(autouse=True)
def small_partitions():
    old = settings.partitions
    settings.partitions = 8
    yield
    settings.partitions = old


def _unwrap(v):
    # StreamReducer output records are (k, (k, v)); group values of a
    # SECOND partition_reduce therefore arrive as (k, v) tuples.
    return v[1] if isinstance(v, tuple) else v


def _keyed_sum(groups):
    for k, vs in groups:
        yield k, sum(_unwrap(v) for v in vs)


def _rekey_mod3(groups):
    for k, vs in groups:
        yield k % 3, sum(_unwrap(v) for v in vs)


class TestAliasProvenance:
    def test_partition_reduce_chain_regroups(self):
        # ADVICE round 5 (high): the identity checkpoint between two
        # partition_reduce stages must re-route by hash — the first
        # reducer's output keys are arbitrary and registered under the
        # reduce job's pid, so aliasing it leaves the second reduce
        # grouping each key only within the first's partitions.
        items = list(range(1000))  # keys = positions, values = i
        emitter = (Dampr.memory(items)
                   .partition_reduce(_rekey_mod3)
                   .partition_reduce(_keyed_sum)
                   .run(name="alias-regroup"))
        vals = emitter.read()
        assert len(vals) == 3, (
            "partition_reduce chain regrouped per-partition: "
            "{} records".format(len(vals)))
        got = dict(vals)
        want = {r: sum(i for i in range(1000) if i % 3 == r)
                for r in range(3)}
        assert got == want
        emitter.delete()

    def test_map_checkpoint_still_aliases(self):
        # The benign case keeps the fast path: a forced identity
        # checkpoint over a map output no reduce consumes.
        from dampr_tpu.runner import MTRunner

        items = [i * 2 for i in range(100)]
        pipe = Dampr.memory(items).map(lambda v: v + 1).checkpoint(
            force=True).checkpoint(force=True)
        runner = MTRunner("alias-ok", pipe.pmer.graph)
        out = runner.run([pipe.source])
        assert sorted(v for _k, v in out[0].read()) == sorted(
            v * 2 + 1 for v in range(100))
        assert any(st.kind == "map-alias" for st in runner.stats)
        out[0].delete()

    def test_reduce_output_flags_not_routed(self):
        from dampr_tpu.runner import MTRunner

        items = list(range(50))
        pipe = (Dampr.memory(items).partition_reduce(_keyed_sum))
        runner = MTRunner("flags", pipe.pmer.graph)
        out = runner.run([pipe.source])
        assert not out[0].pset.hash_routed or out[0].pset.hash_sorted is False
        out[0].delete()


class TestOffloadPublishOrder:
    def test_offload_publishes_block_before_clearing_dev(self, monkeypatch):
        # Readers race eviction: after offload() the host block must be
        # visible the moment the device lanes are gone.  Drive offload
        # step-by-step by observing the ref from a second thread at every
        # attribute write.
        jax = pytest.importorskip("jax")
        from dampr_tpu.blocks import Block
        from dampr_tpu.storage import BlockRef

        vals = np.arange(8192, dtype=np.int64)
        blk = Block(vals.copy(), vals.copy())
        prep = BlockRef.lane_prep(blk.values)
        assert prep is not None
        ref = BlockRef(blk, store=None, device_prep=prep)
        assert ref.is_device

        seen = []
        orig_setattr = BlockRef.__setattr__

        def spying_setattr(self, name, value):
            orig_setattr(self, name, value)
            if name in ("_block", "_dev", "_kmeta"):
                # every intermediate state must be readable
                got = self.get()
                seen.append((name, len(got)))

        monkeypatch.setattr(BlockRef, "__setattr__", spying_setattr)
        ref.offload()
        monkeypatch.setattr(BlockRef, "__setattr__", orig_setattr)
        assert seen, "offload never published"
        assert all(n == len(vals) for _attr, n in seen)
        got = ref.get()
        assert np.array_equal(np.asarray(got.values), vals)

    def test_concurrent_get_during_offload_loop(self):
        # Hammer get() from a reader thread while offloading device refs;
        # any publish-order bug shows up as load_block(None) / NoneType
        # unpacking.  (Before the fix this raised within a few hundred
        # iterations.)
        jax = pytest.importorskip("jax")
        from dampr_tpu.blocks import Block
        from dampr_tpu.storage import BlockRef

        vals = np.arange(4096, dtype=np.int64)
        errors = []
        for _ in range(50):
            blk = Block(vals.copy(), vals.copy())
            prep = BlockRef.lane_prep(blk.values)
            ref = BlockRef(blk, store=None, device_prep=prep)
            stop = threading.Event()

            def reader():
                try:
                    while not stop.is_set():
                        got = ref.get()
                        assert len(got) == len(vals)
                except Exception as e:  # pragma: no cover
                    errors.append(e)

            t = threading.Thread(target=reader)
            t.start()
            ref.offload()
            stop.set()
            t.join()
            assert not errors, errors[0]


class TestCompositeLaneConcat:
    def _roundtrip(self, blocks):
        from dampr_tpu.blocks import Block, pylist

        merged = Block.concat(blocks)
        return pylist(merged.values)

    def test_int_then_float_tuples_keep_types(self):
        from dampr_tpu.blocks import Block

        a = Block.from_pairs([("a", (1, 2)), ("b", (3, 4))])
        b = Block.from_pairs([("c", (1.5, 2.5))])
        assert a.values.dtype == np.int64 and a.values.ndim == 2
        assert b.values.dtype == np.float64 and b.values.ndim == 2
        vals = self._roundtrip([a, b])
        assert vals == [(1, 2), (3, 4), (1.5, 2.5)]
        assert [type(x) for t in vals for x in t] == [
            int, int, int, int, float, float]

    def test_float_then_int_tuples_keep_types(self):
        from dampr_tpu.blocks import Block

        a = Block.from_pairs([("a", (1.5, 2.5))])
        b = Block.from_pairs([("b", (1, 2))])
        vals = self._roundtrip([a, b])
        assert vals == [(1.5, 2.5), (1, 2)]
        assert [type(x) for t in vals for x in t] == [
            float, float, int, int]

    def test_same_dtype_composites_stay_vectorized(self):
        from dampr_tpu.blocks import Block

        a = Block.from_pairs([("a", (1, 2))])
        b = Block.from_pairs([("b", (3, 4))])
        merged = Block.concat([a, b])
        assert merged.values.dtype == np.int64 and merged.values.ndim == 2
