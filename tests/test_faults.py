"""Fault-injection harness + end-to-end failure recovery
(dampr_tpu.faults): plan grammar and seeded reproducibility, error
classification, backoff bounds, the classified job retry loop,
poison-record quarantine (exactness, budget, idempotence across
retries), IO-layer transient retries, crash auto-resume
(resume="auto"), SIGTERM crashdumps, exchange-timeout shuffle degrade,
slow-stop thread-leak warnings, the disabled-path pin, and the doctor's
--faults surface."""

import json
import logging
import operator
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from dampr_tpu import Dampr, faults, settings

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    """Every test starts and ends with injection off and fault knobs at
    defaults (the suite runs under one process)."""
    saved = (settings.faults, settings.job_retries, settings.io_retries,
             settings.max_quarantined, settings.retry_backoff_ms,
             settings.retry_backoff_max_ms, settings.run_retries)
    yield
    (settings.faults, settings.job_retries, settings.io_retries,
     settings.max_quarantined, settings.retry_backoff_ms,
     settings.retry_backoff_max_ms, settings.run_retries) = saved
    faults.clear()


class TestPlanGrammar:
    def test_parse_and_describe(self):
        p = faults.FaultPlan(
            "spill_write:p=0.25;udf:match=BAD,kind=deterministic;"
            "exchange_step:nth=3;rank_kill:rank=1,exit=137;seed=42")
        assert p.seed == 42
        assert p.rules["spill_write"].p == 0.25
        assert p.rules["udf"].match == "BAD"
        assert p.rules["udf"].kind == "deterministic"
        assert p.rules["exchange_step"].nth == 3
        assert p.rules["exchange_step"].times == 1  # nth defaults once
        assert p.rules["rank_kill"].exit_code == 137
        d = p.describe()
        assert d["seed"] == 42 and len(d["sites"]) == 4

    def test_seed_position_independent(self):
        a = faults.FaultPlan("seed=9;spill_write:p=0.5")
        b = faults.FaultPlan("spill_write:p=0.5;seed=9")
        seq_a = [a.rules["spill_write"].should_fire() for _ in range(64)]
        seq_b = [b.rules["spill_write"].should_fire() for _ in range(64)]
        assert seq_a == seq_b and any(seq_a) and not all(seq_a)

    def test_p_schedule_reproducible_and_seed_sensitive(self):
        def seq(seed):
            p = faults.FaultPlan("spill_write:p=0.3;seed={}".format(seed))
            return [p.rules["spill_write"].should_fire()
                    for _ in range(128)]

        assert seq(1) == seq(1)
        assert seq(1) != seq(2)

    def test_bad_specs_raise(self):
        for bad in ("spill_write", "udf:p=x", "udf:banana",
                    "udf:kind=weird"):
            with pytest.raises(faults.FaultSpecError):
                faults.FaultPlan(bad)

    def test_unknown_site_tolerated(self):
        p = faults.FaultPlan("not_a_site:nth=1")
        assert "not_a_site" in p.rules  # kept, warned, harmless

    def test_match_rule_fires_every_probe(self):
        """Content-keyed rules must fire deterministically on every
        re-execution — the bisect relies on it."""
        p = faults.FaultPlan("udf:match=POISON,kind=deterministic")
        r = p.rules["udf"]
        for _ in range(5):
            assert r.should_fire(("POISON-x", 1))
        assert not r.should_fire(("clean", 1))

    def test_nth_and_times(self):
        p = faults.FaultPlan("fold:nth=2,times=1")
        r = p.rules["fold"]
        assert [r.should_fire() for _ in range(5)] == [
            False, True, False, False, False]


class TestClassification:
    def test_buckets(self):
        assert faults.classify(OSError("disk")) == "transient"
        assert faults.classify(TimeoutError()) == "transient"
        assert faults.classify(ConnectionError()) == "transient"
        assert faults.classify(
            faults.TransientInjectedFault("x")) == "transient"
        assert faults.classify(ValueError("bad record")) == "deterministic"
        assert faults.classify(RuntimeError()) == "deterministic"
        assert faults.classify(
            faults.DeterministicInjectedFault("x")) == "deterministic"
        assert faults.classify(MemoryError()) == "fatal"
        assert faults.classify(KeyboardInterrupt()) == "fatal"
        assert faults.classify(SystemExit(1)) == "fatal"
        assert faults.classify(
            faults.QuarantineOverflow("full")) == "fatal"
        assert faults.classify(faults.FatalInjectedFault("x")) == "fatal"

    def test_transient_fault_is_oserror(self):
        # code catching real IO errors treats injected ones identically
        assert isinstance(faults.TransientInjectedFault("x"), OSError)

    def test_backoff_bounds(self):
        settings.retry_backoff_ms = 40
        settings.retry_backoff_max_ms = 300
        for attempt in range(10):
            for _ in range(20):
                d = faults.backoff(attempt)
                assert 0.0 <= d <= 0.3 + 1e-9
        # early attempts bounded by base * 2^n
        assert all(faults.backoff(0) <= 0.04 for _ in range(50))


class TestDisabledPath:
    def test_no_plan_no_cost_no_section_noise(self):
        assert faults.active() is None
        faults.check("spill_write")  # inert
        faults.check_records("udf", [1], [2])
        em = Dampr.memory(list(range(500))).map(lambda x: (x, 1)).run()
        fa = em.stats()["faults"]
        assert fa["enabled"] is False
        assert fa["retries"] == 0 and fa["quarantined"] == 0
        assert "plan" not in fa and "injected" not in fa
        em.delete()

    def test_stage_stats_carry_quarantined_field(self):
        em = Dampr.memory(list(range(100))).map(lambda x: (x, 1)).run()
        assert all(s["quarantined"] == 0 for s in em.stats)
        em.delete()


class TestClassifiedRetries:
    def test_transient_retry_backs_off(self):
        settings.job_retries = 2
        settings.retry_backoff_ms = 20
        faults.install(faults.FaultPlan("udf:nth=1,kind=transient"))
        em = Dampr.memory(list(range(2000))).map(
            lambda x: (x, x)).run(name="retry-transient")
        fa = em.stats()["faults"]
        assert fa["job_retries"] >= 1
        assert fa["backoff_seconds"] > 0.0
        assert fa["injected"] == {"udf": 1}
        assert sorted(v for v in em.read())[:3] == [(0, 0), (1, 1), (2, 2)]
        em.delete()

    def test_deterministic_retry_no_backoff(self):
        """Legacy contract: stateful flaky UDFs (deterministic class)
        still retry, immediately."""
        settings.job_retries = 2
        state = {"n": 0}

        def flaky(x):
            state["n"] += 1
            if state["n"] == 1:
                raise RuntimeError("transient-in-behavior")
            return (x, x)

        em = Dampr.memory([1, 2, 3], partitions=1).map(flaky).run(
            name="retry-det")
        fa = em.stats()["faults"]
        assert fa["job_retries"] >= 1
        assert fa["backoff_seconds"] == 0.0
        em.delete()

    def test_fatal_never_retried(self):
        settings.job_retries = 5
        calls = {"n": 0}

        def oom(x):
            calls["n"] += 1
            raise MemoryError("boom")

        with pytest.raises(MemoryError):
            Dampr.memory([1], partitions=1).map(oom).run(name="retry-oom")
        assert calls["n"] == 1  # one attempt, zero retries


class TestQuarantine:
    def _pipe(self, data):
        return Dampr.memory(data).map(lambda s: (int(s), s))

    def test_poison_record_quarantined_exactly(self, tmp_path):
        """The chaos-exactness contract in miniature: results under
        quarantine are byte-identical to a run whose input lacked the
        poison records."""
        settings.max_quarantined = 2
        clean = [str(i) for i in range(5000)]
        poisoned = clean[:1234] + ["POISON-A"] + clean[1234:] + ["POISON-B"]
        got = self._pipe(poisoned).run(name="q-exact").read()
        want = self._pipe(clean).run(name="q-clean").read()
        assert got == want

    def test_counts_and_sink_file(self):
        settings.max_quarantined = 1
        em = self._pipe(["1", "2", "oops", "3"]).run(name="q-counts")
        s = em.stats()
        fa = s["faults"]
        assert fa["quarantined"] == 1
        assert sum(st["quarantined"] for st in s["stages"]) == 1
        recs = faults.load_quarantine("q-counts")
        assert len(recs) == 1
        assert "oops" in recs[0]["value"]
        assert recs[0]["error"] == "ValueError"
        em.delete()

    def test_budget_overflow_fails_fast(self):
        settings.max_quarantined = 1
        settings.job_retries = 3
        with pytest.raises(Exception) as ei:
            self._pipe(["bad1", "bad2", "1"]).run(name="q-overflow")
        # overflow is fatal: the original failure (or the overflow
        # itself) surfaces without burning the retry budget
        assert isinstance(ei.value, (faults.QuarantineOverflow,
                                     ValueError))

    def test_disabled_fails_fast_as_before(self):
        assert settings.max_quarantined == 0
        with pytest.raises(ValueError):
            self._pipe(["1", "nope"]).run(name="q-off")

    def test_duplicate_poison_records_each_count(self):
        """Genuine duplicates are distinct record instances: each
        counts against the budget and each gets a sink line — the
        budget bounds real data loss, not distinct reprs."""
        settings.max_quarantined = 2
        data = ["1", "dup-bad", "2", "dup-bad", "3"]
        em = self._pipe(data).run(name="q-dup")
        fa = em.stats()["faults"]
        assert fa["quarantined"] == 2
        recs = faults.load_quarantine("q-dup")
        assert len(recs) == 2
        assert all("dup-bad" in r["value"] for r in recs)
        assert sorted(em.read()) == [(1, "1"), (2, "2"), (3, "3")]
        em.delete()

    def test_duplicate_poison_overflows_single_budget(self):
        settings.max_quarantined = 1
        with pytest.raises(Exception) as ei:
            self._pipe(["bad", "1", "bad"]).run(name="q-dup-over")
        assert isinstance(ei.value, (faults.QuarantineOverflow,
                                     ValueError))

    def test_idempotent_across_job_retries(self):
        """A transient fault in the same job as a poison record: the
        retried job re-quarantines the same record without burning the
        budget twice."""
        settings.max_quarantined = 1
        settings.job_retries = 3
        faults.install(faults.FaultPlan("fold:nth=1,kind=transient"))
        data = [str(i) for i in range(3000)] + ["POISON"]
        em = (Dampr.memory(data, partitions=4)
              .map(lambda s: (int(s) % 7, 1))
              .fold_by(lambda kv: kv[0], operator.add, lambda kv: kv[1])
              .run(name="q-idem"))
        fa = em.stats()["faults"]
        assert fa["quarantined"] == 1
        assert fa["job_retries"] >= 1
        want = dict(Dampr.memory([str(i) for i in range(3000)],
                                 partitions=4)
                    .map(lambda s: (int(s) % 7, 1))
                    .fold_by(lambda kv: kv[0], operator.add,
                             lambda kv: kv[1])
                    .run(name="q-idem-clean").read())
        got = dict(em.read())
        assert got == want
        em.delete()


class TestIoRetries:
    def test_spill_write_transient_absorbed(self, tmp_path):
        from dampr_tpu.ops.text import ParseNumbers
        from dampr_tpu.runner import MTRunner

        path = tmp_path / "nums.txt"
        with open(path, "w") as f:
            for i in range(60000):
                f.write("{}\n".format((i * 2654435761) % (1 << 40)))
        faults.install(faults.FaultPlan(
            "spill_write:nth=1,kind=transient,times=2"))
        settings.retry_backoff_ms = 5
        old_dev = settings.use_device
        settings.use_device = False
        try:
            pipe = (Dampr.text(str(path), chunk_size=64 * 1024)
                    .custom_mapper(ParseNumbers())
                    .checkpoint(force=True))
            runner = MTRunner("io-retry", pipe.pmer.graph,
                              memory_budget=1 << 18)
            out = runner.run([pipe.source])
            assert sum(len(b) for b in out[0].sorted_blocks()) == 60000
        finally:
            settings.use_device = old_dev
        fa = runner.run_summary["faults"]
        assert fa["io_retries"].get("spill_write", 0) >= 1
        assert fa["retries"] >= 1
        out[0].delete()
        runner.store.cleanup()

    def test_spill_read_transient_absorbed(self, tmp_path):
        from dampr_tpu.io import frames
        from dampr_tpu.io.codecs import resolve
        from dampr_tpu.blocks import Block
        import numpy as np

        path = str(tmp_path / "f.blk")
        arr = np.arange(5000, dtype=np.int64)
        with open(path, "wb") as f:
            frames.write_block_frames(Block(arr, arr.copy()), f,
                                      resolve("zlib", 1), 1000)
        faults.install(faults.FaultPlan(
            "spill_read:nth=1,kind=transient,times=2"))
        settings.retry_backoff_ms = 5
        snap = faults.counters_snapshot()
        r = frames.FrameReader(path)
        payloads = list(r.iter_payloads())
        assert len(payloads) == 5
        _inj, io_r, io_backoff = faults.counters_delta(snap)
        assert io_r.get("spill_read", 0) >= 1
        assert io_backoff >= 0.0

    def test_io_retry_budget_exhausted_raises(self):
        faults.install(faults.FaultPlan("spill_write:p=1.0"))
        settings.io_retries = 1
        settings.retry_backoff_ms = 1
        calls = {"n": 0}

        def always():
            calls["n"] += 1
            faults.check("spill_write")

        with pytest.raises(faults.TransientInjectedFault):
            faults.retry_io(always, "spill_write")
        assert calls["n"] == 2  # initial + one retry

    def test_deterministic_io_error_not_retried(self):
        calls = {"n": 0}

        def corrupt():
            calls["n"] += 1
            raise ValueError("corrupt frame")

        with pytest.raises(ValueError):
            faults.retry_io(corrupt, "spill_read")
        assert calls["n"] == 1


class TestAutoResume:
    def _pipe(self):
        return (Dampr.memory(list(range(4000)))
                .map(lambda x: (x % 13, 1))
                .checkpoint(force=True)
                .fold_by(lambda kv: kv[0], operator.add,
                         lambda kv: kv[1]))

    def test_resume_auto_completes_byte_identical(self):
        faults.install(faults.FaultPlan("fold:nth=1,kind=deterministic"))
        em = self._pipe().run(name="auto-res", resume="auto")
        got = sorted(em.read())
        em.delete()
        faults.clear()
        cold = sorted(self._pipe().run(name="auto-cold").read())
        assert got == cold

    def test_resume_auto_requires_name(self):
        with pytest.raises(ValueError, match="resume"):
            Dampr.memory([1]).map(lambda x: (x, x)).run(resume="auto")

    def test_fatal_never_auto_resumes(self):
        calls = {"n": 0}

        def oom(x):
            calls["n"] += 1
            raise MemoryError("fatal")

        with pytest.raises(MemoryError):
            Dampr.memory([1], partitions=1).map(oom).run(
                name="auto-fatal", resume="auto")
        assert calls["n"] == 1

    def test_quarantine_survives_auto_resume(self):
        """A failed attempt after a checkpointed quarantine: the retry
        restores the stage from its manifest (no re-execution), and the
        quarantine count + audit trail must survive the fresh runner."""
        settings.max_quarantined = 1
        state = {"fails": 1}

        def flaky_reduce(k, vs):
            if state["fails"] > 0:
                state["fails"] -= 1
                raise RuntimeError("dies once after the checkpoint")
            return sum(v[1] for v in vs)

        em = (Dampr.memory([str(i) for i in range(2000)] + ["POISONX"])
              .map(lambda s: (int(s) % 5, 1))
              .checkpoint(force=True)
              .group_by(lambda kv: kv[0])
              .reduce(flaky_reduce)
              .run(name="auto-quar", resume="auto"))
        fa = em.stats()["faults"]
        assert fa["quarantined"] == 1, fa
        recs = faults.load_quarantine("auto-quar")
        assert len(recs) == 1 and "POISONX" in recs[0]["value"]
        assert sum(v for _k, v in em.read()) == 2000
        em.delete()

    def test_settings_cleared_plan_cleared(self):
        """The documented contract: settings.faults=None disables a
        previously settings-installed plan on the next run."""
        settings.faults = "udf:nth=1,kind=transient"
        settings.job_retries = 1
        em = Dampr.memory([1, 2, 3]).map(lambda x: (x, x)).run(
            name="plan-on")
        assert em.stats()["faults"]["enabled"] is True
        em.delete()
        settings.faults = None
        em = Dampr.memory([1, 2, 3]).map(lambda x: (x, x)).run(
            name="plan-off")
        fa = em.stats()["faults"]
        assert fa["enabled"] is False and fa["retries"] == 0
        assert faults.active() is None
        em.delete()

    def test_retry_budget_exhausted_reraises(self):
        settings.run_retries = 1

        def always(x):
            raise RuntimeError("persistent")

        with pytest.raises(RuntimeError, match="persistent"):
            Dampr.memory([1], partitions=1).map(always).run(
                name="auto-exhaust", resume="auto")


class TestExchangeTimeoutPlumbing:
    def test_event_sidecar_roundtrip(self):
        faults.clear_events("ev-run")
        faults.record_event("ev-run", "exchange_timeout", stage=3,
                            step=1, timeout_ms=500)
        faults.record_event("ev-run", "exchange_timeout", stage=None)
        evs = faults.load_events("ev-run")
        assert len(evs) == 2
        assert faults.stages_with_exchange_timeouts("ev-run") == {3}
        faults.clear_events("ev-run")
        assert faults.load_events("ev-run") == []

    def test_events_bounded(self):
        faults.clear_events("ev-cap")
        for i in range(faults.EVENTS_CAP + 50):
            faults.record_event("ev-cap", "exchange_timeout", stage=i)
        evs = faults.load_events("ev-cap")
        assert len(evs) == faults.EVENTS_CAP
        assert evs[-1]["stage"] == faults.EVENTS_CAP + 49
        faults.clear_events("ev-cap")

    def test_shuffle_degrades_after_recorded_timeout(self):
        """A recorded exchange timeout pins that stage's shuffle to the
        host path on the next run, with a fault-history reason in the
        plan report."""
        from dampr_tpu.runner import MTRunner
        from dampr_tpu import plan as _plan

        old = settings.mesh_exchange
        settings.mesh_exchange = "auto"
        name = "degrade-run"

        def build():
            pipe = (Dampr.memory([(i % 5, i) for i in range(3000)],
                                 partitions=4)
                    .group_by(lambda x: x[0])
                    .reduce(lambda k, vs: len(list(vs))))
            return pipe

        try:
            pipe = build()
            runner = MTRunner(name, pipe.pmer.graph)
            _plan.apply_to_runner(runner, [pipe.source])
            targets = (runner.plan_report.get("shuffle") or {}).get(
                "targets") or []
            mesh_sids = [d["sid"] for d in targets
                         if d["target"] == "mesh"]
            if not mesh_sids:
                pytest.skip("no mesh-routed stage on this rig")
            faults.clear_events(name)
            faults.record_event(name, "exchange_timeout",
                                stage=mesh_sids[0])
            pipe2 = build()
            runner2 = MTRunner(name, pipe2.pmer.graph)
            _plan.apply_to_runner(runner2, [pipe2.source])
            dec = {d["sid"]: d for d in
                   runner2.plan_report["shuffle"]["targets"]}
            assert dec[mesh_sids[0]]["target"] == "host"
            assert "fault-history" in dec[mesh_sids[0]]["reason"]
            assert runner2._shuffle_targets.get(mesh_sids[0]) == "host"
        finally:
            settings.mesh_exchange = old
            faults.clear_events(name)

    def test_forced_mesh_wins_over_fault_history(self):
        from dampr_tpu.runner import MTRunner
        from dampr_tpu import plan as _plan

        old = settings.mesh_exchange
        settings.mesh_exchange = "on"
        name = "degrade-forced"
        try:
            pipe = (Dampr.memory([(i % 5, i) for i in range(3000)],
                                 partitions=4)
                    .group_by(lambda x: x[0])
                    .reduce(lambda k, vs: len(list(vs))))
            runner = MTRunner(name, pipe.pmer.graph)
            _plan.apply_to_runner(runner, [pipe.source])
            targets = (runner.plan_report.get("shuffle") or {}).get(
                "targets") or []
            mesh_sids = [d["sid"] for d in targets
                         if d["target"] == "mesh"]
            assert mesh_sids, targets
            faults.clear_events(name)
            faults.record_event(name, "exchange_timeout",
                                stage=mesh_sids[0])
            runner2 = MTRunner(name, pipe.pmer.graph)
            _plan.apply_to_runner(runner2, [pipe.source])
            dec = {d["sid"]: d for d in
                   runner2.plan_report["shuffle"]["targets"]}
            assert dec[mesh_sids[0]]["target"] == "mesh"
        finally:
            settings.mesh_exchange = old
            faults.clear_events(name)


class TestThreadLeakWarnings:
    def test_sampler_slow_stop_warns(self, caplog):
        from dampr_tpu.obs.metrics import Metrics
        from dampr_tpu.obs.sampler import Sampler

        faults.install(faults.FaultPlan(
            "sampler_tick:nth=1,sleep_ms=3500"))
        m = Metrics("slow-stop")
        s = Sampler(m, interval_ms=10)
        with caplog.at_level(logging.WARNING,
                             logger="dampr_tpu.obs.sampler"):
            s.start()
            time.sleep(0.05)  # let the tick enter the injected stall
            s.stop()
        assert any("did not stop" in r.message
                   and "dampr-tpu-sampler" in r.message
                   for r in caplog.records), caplog.records

    def test_overlap_producer_slow_stop_warns_and_drains(self, caplog):
        """Kill-consumer pin: a consumer that dies mid-stream while the
        producer is wedged must still drain every budget reservation
        and name the stuck thread."""
        import numpy as np

        from dampr_tpu.blocks import Block
        from dampr_tpu.runner import _overlap_stream
        from dampr_tpu.storage import RunStore

        faults.install(faults.FaultPlan(
            "overlap_produce:nth=3,sleep_ms=6000"))
        store = RunStore("overlap-kill", budget=1 << 22)
        old = settings.overlap_windows
        settings.overlap_windows = 2

        def codec():
            for i in range(50):
                arr = np.arange(1000, dtype=np.int64)
                yield Block(arr, arr.copy())

        try:
            with caplog.at_level(logging.WARNING,
                                 logger="dampr_tpu.runner"):
                with pytest.raises(RuntimeError):
                    for i, blk in enumerate(_overlap_stream(codec(),
                                                            store)):
                        if i == 1:
                            raise RuntimeError("consumer died")
            assert any("did not stop" in r.message
                       for r in caplog.records), caplog.records
            # reservations reconciled despite the wedged producer: the
            # producer releases its own charge when it observes stop
            deadline = time.time() + 10
            while store.overlap_bytes != 0 and time.time() < deadline:
                time.sleep(0.05)
            assert store.overlap_bytes == 0
        finally:
            settings.overlap_windows = old
            store.cleanup()


class TestSigterm:
    def test_sigterm_leaves_schema_valid_crashdump(self, tmp_path):
        """A SIGTERM'd run must exit nonzero and leave a schema-valid
        crashdump (previously only KeyboardInterrupt and injected
        exceptions were pinned)."""
        script = tmp_path / "victim.py"
        ready = tmp_path / "ready"
        script.write_text(textwrap.dedent("""
            import os, sys, time
            sys.path.insert(0, {root!r})
            os.environ["JAX_PLATFORMS"] = "cpu"
            from dampr_tpu import Dampr, settings
            settings.trace = True
            settings.trace_dir = {tdir!r}
            settings.use_device = False
            settings.max_processes = 1  # serial jobs: the signal lands
            #                             in the main thread's UDF loop

            def slow(x):
                if x == 0:
                    open({ready!r}, "w").write("up")
                time.sleep(0.15)
                return (x, x)

            Dampr.memory(list(range(600)), partitions=2).map(
                slow).run(name="sigterm-victim")
            print("COMPLETED-UNEXPECTEDLY")
        """).format(root=ROOT, tdir=str(tmp_path), ready=str(ready)))
        proc = subprocess.Popen([sys.executable, str(script)],
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)
        deadline = time.time() + 60
        while not ready.exists() and time.time() < deadline:
            if proc.poll() is not None:
                break
            time.sleep(0.05)
        assert ready.exists(), proc.communicate()
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
        assert proc.returncode != 0, (proc.returncode, out, err)
        assert "COMPLETED-UNEXPECTEDLY" not in out
        dump = os.path.join(str(tmp_path), "sigterm-victim", "trace",
                            "crashdump.json")
        assert os.path.isfile(dump), (out, err[-2000:])
        with open(dump) as f:
            doc = json.load(f)
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "validate_trace",
            os.path.join(ROOT, "tools", "validate_trace.py"))
        vt = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(vt)
        with open(os.path.join(ROOT, "docs", "trace_schema.json")) as f:
            schema = json.load(f)
        assert not vt.validate(doc, schema)
        assert doc["otherData"]["crash"]["exception"] == "SystemExit"


class TestDoctorFaults:
    def _diagnosed_run(self, tmp_path):
        settings.max_quarantined = 1
        settings.job_retries = 2
        old = (settings.trace, settings.trace_dir)
        settings.trace = True
        settings.trace_dir = str(tmp_path)
        faults.install(faults.FaultPlan(
            "udf:nth=1,kind=transient,times=1"))
        try:
            em = (Dampr.memory([str(i) for i in range(3000)] + ["BAD"])
                  .map(lambda s: (int(s), 1))
                  .run(name="doc-faults"))
            stats_file = em.stats()["stats_file"]
            em.delete()
        finally:
            (settings.trace, settings.trace_dir) = old
            faults.clear()
        return stats_file

    def test_findings_and_schema(self, tmp_path):
        from dampr_tpu.obs import doctor

        stats_file = self._diagnosed_run(tmp_path)
        report = doctor.diagnose(stats_file)
        bottlenecks = {f["bottleneck"] for f in report["findings"]}
        assert "fault-retry" in bottlenecks
        assert "quarantine" in bottlenecks
        fa = report["faults"]
        assert fa["retries"] >= 1 and fa["quarantined"] == 1
        # machine report validates against the checked-in schema
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "validate_doctor",
            os.path.join(ROOT, "tools", "validate_doctor.py"))
        vd = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(vd)
        with open(os.path.join(ROOT, "docs", "doctor_schema.json")) as f:
            schema = json.load(f)
        errors = vd.validate(report, schema)
        assert not errors, errors
        # human rendering with --faults shows the section
        text = doctor.format_report(report, show_faults=True)
        assert "faults:" in text and "quarantined 1" in text

    def test_exchange_timeout_finding(self, tmp_path):
        from dampr_tpu.obs import doctor

        old = (settings.trace, settings.trace_dir)
        settings.trace = True
        settings.trace_dir = str(tmp_path)
        try:
            em = Dampr.memory(list(range(200))).map(
                lambda x: (x, 1)).run(name="doc-timeout")
            stats_file = em.stats()["stats_file"]
            em.delete()
        finally:
            (settings.trace, settings.trace_dir) = old
        faults.clear_events("doc-timeout")
        faults.record_event("doc-timeout", "exchange_timeout", stage=2,
                            step=0, timeout_ms=500)
        try:
            report = doctor.diagnose(stats_file)
            tof = [f for f in report["findings"]
                   if f["bottleneck"] == "exchange-timeout"]
            assert tof and tof[0]["severity"] == "high"
            assert report["faults"]["exchange_timeouts"] == 1
        finally:
            faults.clear_events("doc-timeout")

    def test_playbook_knobs_exist(self):
        from dampr_tpu.obs.doctor import _PLAYBOOK

        for verdict in ("fault-retry", "quarantine", "exchange-timeout"):
            assert verdict in _PLAYBOOK
            for knob, _env, propose, why in _PLAYBOOK[verdict]:
                assert hasattr(settings, knob), (verdict, knob)
                propose(getattr(settings, knob))  # never raises on current


class TestWatchdog:
    def test_watchdog_aborts_with_crashdump_and_event(self, tmp_path):
        """A wedged collective step: the watchdog flushes a crashdump,
        records the fault event, and exits the process within the
        deadline bound (subprocess — it dies by design)."""
        script = tmp_path / "wedge.py"
        script.write_text(textwrap.dedent("""
            import os, sys, time
            sys.path.insert(0, {root!r})
            os.environ["JAX_PLATFORMS"] = "cpu"
            from dampr_tpu import settings, faults
            settings.trace = True
            settings.trace_dir = {tdir!r}
            settings.scratch_root = {scratch!r}
            from dampr_tpu.obs import flightrec
            rec = flightrec.FlightRecorder("wedged-run", 64)
            flightrec.start(rec)
            faults.set_context(run="wedged-run", stage=4)
            from dampr_tpu.parallel import exchange
            done = exchange._step_watchdog(0, 400)
            time.sleep(30)   # never sets done: the watchdog must kill us
        """).format(root=ROOT, tdir=str(tmp_path),
                    scratch=str(tmp_path / "scratch")))
        t0 = time.time()
        proc = subprocess.run([sys.executable, str(script)],
                              capture_output=True, text=True,
                              timeout=90)
        elapsed = time.time() - t0
        assert proc.returncode == 70, (proc.returncode, proc.stderr)
        # bounded abort: deadline + flush, nowhere near the 30 s sleep
        assert elapsed < 25, elapsed
        dump = os.path.join(str(tmp_path), "wedged-run", "trace",
                            "crashdump.json")
        assert os.path.isfile(dump), proc.stderr[-2000:]
        with open(dump) as f:
            doc = json.load(f)
        assert doc["otherData"]["crash"]["reason"] == "exchange-timeout"
        old_scratch = settings.scratch_root
        settings.scratch_root = str(tmp_path / "scratch")
        try:
            assert faults.stages_with_exchange_timeouts(
                "wedged-run") == {4}
        finally:
            settings.scratch_root = old_scratch


class TestSiteCatalogDocs:
    def test_documented_sites_match_module(self):
        """docs/robustness.md's site table and faults.SITES stay in
        sync."""
        with open(os.path.join(ROOT, "docs", "robustness.md")) as f:
            doc = f.read()
        for site in faults.SITES:
            assert "`{}`".format(site) in doc, site
