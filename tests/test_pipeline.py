"""Barrier-free pipelined execution (docs/pipeline.md): streamed-edge
plan decisions, pipelined-vs-staged byte identity over randomized
pipelines, backpressure and publish-fault cleanliness, the exchange
payload codec, and attempt-scoped frame read timing."""

import operator
import random
import time
import types
import uuid

import pytest

from dampr_tpu import Dampr, faults, settings
from dampr_tpu.io import codecs
from dampr_tpu.plan import pipeline as plan_pipeline


@pytest.fixture(autouse=True)
def pipelined_host():
    """Pipelining on, mesh paths off: the streamed-edge analysis
    conservatively bars streaming whenever a mesh fold/exchange could
    engage, and the 8-device test rig would otherwise bar every edge."""
    saved = (settings.pipeline, settings.pipeline_queue_bytes,
             settings.mesh_fold, settings.mesh_exchange, settings.sort_runs,
             settings.optimize, settings.exchange_codec, settings.faults)
    settings.pipeline = "auto"
    settings.mesh_fold = "off"
    settings.mesh_exchange = "off"
    yield
    (settings.pipeline, settings.pipeline_queue_bytes, settings.mesh_fold,
     settings.mesh_exchange, settings.sort_runs, settings.optimize,
     settings.exchange_codec, settings.faults) = saved
    faults.clear()


def _decisions(pipe, runner=None):
    return plan_pipeline.analyze(pipe.pmer.graph, [pipe.source],
                                 runner=runner)


def _run_both(pipe):
    """(pipelined read, staged read) of the same handle."""
    settings.pipeline = "auto"
    em = pipe.run(name="pipe-on-%s" % uuid.uuid4().hex[:8])
    on, stats = em.read(), em.stats()
    em.delete()
    settings.pipeline = "0"
    em = pipe.run(name="pipe-off-%s" % uuid.uuid4().hex[:8])
    off = em.read()
    em.delete()
    settings.pipeline = "auto"
    return on, off, stats


class TestPlanDecisions:
    def test_assoc_fold_streams_early_fold(self):
        pipe = (Dampr.memory(list(range(100)), partitions=4)
                .map(lambda x: x + 1)
                .fold_by(lambda x: x % 5, operator.add))
        d = _decisions(pipe)
        assert any(e["decision"] == "streamed" and e["mode"] == "early_fold"
                   for e in d), d

    def test_lambda_binop_keeps_barrier(self):
        pipe = (Dampr.memory(list(range(100)), partitions=4)
                .map(lambda x: x + 1)
                .fold_by(lambda x: x % 5, lambda a, b: a + b))
        d = _decisions(pipe)
        assert not any(e["mode"] == "early_fold" for e in d)
        assert any("order-sensitive" in e["reason"] for e in d), d

    def test_checkpoint_keeps_barrier(self):
        pipe = (Dampr.memory(list(range(100)), partitions=4)
                .map(lambda x: x + 1).checkpoint()
                .fold_by(lambda x: x % 5, operator.add))
        d = _decisions(pipe)
        # Both edges touching the checkpoint stage stay barriers; edges
        # strictly downstream of it may still stream.
        ck = [e for e in d if "checkpoint" in e["reason"]]
        assert len(ck) >= 2, d
        assert all(e["decision"] == "barrier" for e in ck)

    def test_multi_consumer_keeps_barrier(self):
        base = Dampr.memory(list(range(100)), partitions=4).map(
            lambda x: x + 1)
        a = base.map(lambda x: x * 2)
        b = base.filter(lambda x: x % 2 == 0)
        merged = a.pmer.graph.union(b.pmer.graph)
        d = plan_pipeline.analyze(merged, [a.source, b.source])
        assert any(e["reason"] == "multi-consumer output" for e in d), d

    def test_mesh_possible_bars_streaming(self):
        settings.mesh_fold = "on"
        pipe = (Dampr.memory(list(range(100)), partitions=4)
                .map(lambda x: x + 1)
                .fold_by(lambda x: x % 5, operator.add))
        d = _decisions(pipe)
        assert not any(e["decision"] == "streamed" and e["dst"] is not None
                       for e in d)
        assert any("mesh" in e["reason"] for e in d), d

    def test_resume_bars_streaming(self):
        pipe = (Dampr.memory(list(range(100)), partitions=4)
                .map(lambda x: x + 1)
                .fold_by(lambda x: x % 5, operator.add))
        fake = types.SimpleNamespace(resume=True, _handoff_sids=set(),
                                     _shuffle_targets={})
        d = _decisions(pipe, runner=fake)
        assert not any(e["decision"] == "streamed" and e["dst"] is not None
                       for e in d)
        assert any("resume" in e["reason"] for e in d), d

    def test_host_shuffle_does_not_bar_mesh_does(self):
        pipe = (Dampr.memory(list(range(100)), partitions=4)
                .map(lambda x: x + 1)
                .fold_by(lambda x: x % 5, operator.add))
        edge = next(e for e in _decisions(pipe)
                    if e["decision"] == "streamed" and e["dst"] is not None)
        host = types.SimpleNamespace(
            resume=False, _handoff_sids=set(),
            _shuffle_targets={edge["dst"]: "host"})
        assert any(e["decision"] == "streamed" and e["dst"] is not None
                   for e in _decisions(pipe, runner=host))
        mesh = types.SimpleNamespace(
            resume=False, _handoff_sids=set(),
            _shuffle_targets={edge["dst"]: "mesh"})
        d = _decisions(pipe, runner=mesh)
        assert not any(e["decision"] == "streamed" and e["dst"] is not None
                       for e in d)

    def test_map_chain_streams_without_sorted_runs(self):
        settings.sort_runs = "off"
        pipe = (Dampr.memory(list(range(100)), partitions=4)
                .map(lambda x: x + 1).map(lambda x: x * 2))
        d = _decisions(pipe)
        assert any(e["decision"] == "streamed" and e["mode"] == "chain"
                   for e in d), d

    def test_sorted_runs_bar_map_chain(self):
        settings.sort_runs = "on"
        pipe = (Dampr.memory(list(range(100)), partitions=4)
                .map(lambda x: x + 1).map(lambda x: x * 2))
        d = _decisions(pipe)
        assert not any(e["mode"] == "chain" for e in d)
        assert any("sorted-run" in e["reason"] for e in d), d

    def test_explain_renders_decision_table(self):
        pipe = (Dampr.memory(list(range(100)), partitions=4)
                .map(lambda x: x + 1)
                .fold_by(lambda x: x % 5, operator.add))
        text = pipe.explain()
        assert "pipeline:" in text
        assert "streamed" in text

    def test_kill_switch_recorded_in_report(self):
        settings.pipeline = "0"
        pipe = (Dampr.memory(list(range(50)), partitions=2)
                .map(lambda x: x + 1)
                .fold_by(lambda x: x % 3, operator.add))
        text = pipe.explain()
        assert "OFF" in text
        # Decisions are still computed so the table stays inspectable.
        assert any(e["decision"] == "streamed" for e in _decisions(pipe))


class TestPipelinedExecution:
    def test_early_fold_byte_identity_and_stats(self):
        rng = random.Random(41)
        data = [rng.randrange(0, 10000) for _ in range(20000)]
        pipe = (Dampr.memory(data, partitions=8)
                .map(lambda x: x * 3 + 1)
                .fold_by(lambda x: x % 101, operator.add))
        on, off, stats = _run_both(pipe)
        assert on == off
        ps = stats["pipeline"]
        assert ps["enabled"] is True
        assert ps["edges_streamed"] >= 1
        assert ps["executed"] >= 1
        assert ps["published"] >= 1
        assert 0.0 <= ps["overlap_fraction"] <= 1.0
        assert stats["plan"]["pipeline"]["streamed"] >= 1
        assert stats["plan"]["pipeline"]["active"] is True

    def test_chain_byte_identity(self):
        settings.sort_runs = "off"
        settings.optimize = False  # the optimizer would fuse the chain
        data = list(range(5000))
        pipe = (Dampr.memory(data, partitions=8)
                .map(lambda x: x * 2)
                .filter(lambda x: x % 3 != 0))
        on, off, stats = _run_both(pipe)
        assert on == off
        assert stats["pipeline"]["executed"] >= 1

    def test_backpressure_bound_respected(self):
        # bound=1: a publish waits for the queue to drain fully, so the
        # peak is one mapping's bytes, strictly below the stage total.
        settings.pipeline_queue_bytes = 1
        rng = random.Random(7)
        data = [rng.randrange(0, 10000) for _ in range(20000)]
        pipe = (Dampr.memory(data, partitions=8)
                .map(lambda x: x + 7)
                .fold_by(lambda x: x % 53, operator.add))
        on, off, stats = _run_both(pipe)
        assert on == off
        ps = stats["pipeline"]
        assert ps["queue_peak_bytes"] <= ps["bytes_in"]
        if ps["published"] > 1:
            assert ps["queue_peak_bytes"] < ps["bytes_in"]

    def test_publish_fault_fails_clean(self):
        data = list(range(8000))
        pipe = (Dampr.memory(data, partitions=8)
                .map(lambda x: x + 1)
                .fold_by(lambda x: x % 7, operator.add))
        faults.install(faults.FaultPlan(
            "stream_publish:nth=1,kind=deterministic"))
        try:
            with pytest.raises(Exception):
                pipe.run(name="pipe-kill-%s" % uuid.uuid4().hex[:8])
            assert faults.injected_counts.get("stream_publish")
        finally:
            faults.clear()
        # The failed streamed run leaves nothing behind that changes a
        # re-run: pipelined and staged reads still agree byte-for-byte.
        on, off, _ = _run_both(pipe)
        assert on == off


class TestPipelinedProperty:
    """Randomized pipelines: pipelined and staged execution are
    byte-identical on every optimizer/sorted-run leg."""

    def _unary(self, rng, pipe):
        roll = rng.randrange(5)
        if roll == 0:
            k = rng.randrange(1, 50)
            return pipe.map(lambda x, k=k: x + k)
        if roll == 1:
            m = rng.randrange(2, 7)
            return pipe.filter(lambda x, m=m: x % m != 0)
        if roll == 2:
            return pipe.flat_map(lambda x: (x, x + 1000000))
        if roll == 3:
            return pipe.sort_by(lambda x: -x)
        return pipe.checkpoint()

    def _build(self, rng, data):
        pipe = Dampr.memory(data, partitions=rng.choice([4, 8, 13]))
        for _ in range(rng.randrange(1, 4)):
            pipe = self._unary(rng, pipe)
        if rng.randrange(2):
            m = rng.randrange(2, 9)
            pipe = (pipe.fold_by(lambda x, m=m: x % m, operator.add)
                    .map_values(lambda v: v * 3))
        return pipe

    @pytest.mark.parametrize("case", range(8))
    def test_pipelined_equals_staged(self, case):
        rng = random.Random(18000 + case)
        settings.optimize = bool(case % 2)
        settings.sort_runs = "off" if case % 4 < 2 else "auto"
        data = [rng.randrange(0, 5000)
                for _ in range(rng.randrange(200, 2000))]
        pipe = self._build(rng, data)
        on, off, _ = _run_both(pipe)
        assert on == off, (
            "case {} diverged: pipelined {} records vs staged {}".format(
                case, len(on), len(off)))


class TestExchangeCodec:
    def test_off_resolves_none(self):
        from dampr_tpu.parallel import exchange
        settings.exchange_codec = "off"
        assert exchange.wire_codec() is None

    def test_unknown_resolves_none(self):
        from dampr_tpu.parallel import exchange
        settings.exchange_codec = "definitely-not-a-codec"
        assert exchange.wire_codec() is None

    def test_auto_never_picks_deflate(self):
        from dampr_tpu.parallel import exchange
        settings.exchange_codec = "auto"
        c = exchange.wire_codec()
        assert c is None or c.name in ("zstd", "lz4")

    def test_roundtrip(self):
        # Explicit selection exercises the wire framing even in builds
        # without zstd/lz4 (where auto deliberately resolves to off).
        from dampr_tpu.parallel import exchange
        settings.exchange_codec = "zlib"
        c = exchange.wire_codec()
        assert c is not None
        data = bytes(range(256)) * 512
        wire = c.compress(data)
        assert len(wire) < len(data)
        assert bytes(codecs.decompress(c.cid, wire)) == data

    def test_blob_exchange_compresses_and_delivers_exactly(self, mesh8):
        from dampr_tpu.parallel import exchange, mesh_blob_exchange
        settings.exchange_codec = "zlib"
        rng = random.Random(5)
        blobs = {}
        for s in range(8):
            for d in range(8):
                if (s + d) % 3 == 0:
                    blobs[(s, d)] = bytes(
                        [rng.randrange(4)] * (1000 + s * 100 + d))
        raw0, wire0 = exchange.codec_raw_bytes, exchange.codec_wire_bytes
        out = mesh_blob_exchange(mesh8, blobs)
        assert out == blobs  # decode restores every route byte-for-byte
        raw_d = exchange.codec_raw_bytes - raw0
        wire_d = exchange.codec_wire_bytes - wire0
        assert raw_d == sum(len(b) for b in blobs.values())
        assert 0 < wire_d < raw_d  # highly repetitive payloads shrink

    def test_blob_exchange_codec_off_is_identity(self, mesh8):
        from dampr_tpu.parallel import exchange, mesh_blob_exchange
        settings.exchange_codec = "off"
        blobs = {(0, 7): bytes(range(256)) * 100, (3, 3): b"x"}
        raw0 = exchange.codec_raw_bytes
        out = mesh_blob_exchange(mesh8, blobs)
        assert out == blobs
        assert exchange.codec_raw_bytes == raw0  # codec never engaged


class TestFrameReadTiming:
    def test_read_seconds_are_attempt_scoped(self, tmp_path, monkeypatch):
        """A transient spill_read retry must not fold the failed attempt
        or its backoff sleep into the per-frame read seconds (the spill
        throughput metric would inflate on every flaky read)."""
        from dampr_tpu.io import frames

        p = str(tmp_path / "t.frames")
        with open(p, "wb") as f:
            w = frames.FrameWriter(f, codecs.resolve("zlib"))
            w.add_frame(b"payload-bytes" * 100, records=1)
            w.close()

        monkeypatch.setattr(settings, "io_retries", 2)
        monkeypatch.setattr(faults, "backoff", lambda attempt, rng=None: 0.25)
        faults.install(faults.FaultPlan("spill_read:nth=1,kind=transient"))
        r = frames.FrameReader(p)
        try:
            t0 = time.perf_counter()
            payload, secs = r._read_frame_timed(0)
            wall = time.perf_counter() - t0
        finally:
            r.close()
            faults.clear()
        assert bytes(payload) == b"payload-bytes" * 100
        assert wall >= 0.25  # the retry really slept the backoff
        assert secs < 0.2, (
            "read seconds {:.3f} include the retry backoff".format(secs))
