"""Packaging surface: pyproject metadata, console entry points, and the
bench driver hook all resolve (reference parity: setup.py:1-20)."""

import os

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestPackaging:
    def test_pyproject_parses_and_lists_packages(self):
        tomllib = pytest.importorskip("tomllib")
        with open(os.path.join(ROOT, "pyproject.toml"), "rb") as f:
            meta = tomllib.load(f)
        assert meta["project"]["name"] == "dampr_tpu"
        pkgs = meta["tool"]["setuptools"]["packages"]
        for pkg in pkgs:
            path = os.path.join(ROOT, pkg.replace(".", os.sep))
            assert os.path.isdir(path), pkg
        scripts = meta["project"]["scripts"]
        assert set(scripts) == {"dampr-tpu-bench", "dampr-tpu-wc",
                                "dampr-tpu-tfidf", "dampr-tpu-stats",
                                "dampr-tpu-doctor", "dampr-tpu-lint",
                                "dampr-tpu-sentry", "dampr-tpu-top",
                                "dampr-tpu-history"}

    def test_console_entry_points_import(self):
        from dampr_tpu import cli

        for fn in (cli.bench, cli.wc, cli.tf_idf, cli.stats, cli.doctor,
                   cli.lint, cli.sentry, cli.top, cli.history_cli):
            assert callable(fn)

    def test_bench_driver_hook_is_thin_wrapper(self):
        import dampr_tpu.bench_tfidf as bt

        assert callable(bt.main)
        src = open(os.path.join(ROOT, "bench.py")).read()
        assert "bench_tfidf" in src  # driver hook delegates to the package

    def test_native_source_ships_with_package(self):
        assert os.path.exists(os.path.join(
            ROOT, "dampr_tpu", "native", "tokenizer.cpp"))
