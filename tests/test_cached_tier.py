"""The cached() tier: pinned blocks live gzip-compressed in RAM (the
reference's MemGZipDataset semantics, dampr/dataset.py:528-547), are charged
against the budget at compressed size, and over-budget pinning fails loudly
instead of silently blowing past the budget."""

import numpy as np
import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.blocks import Block
from dampr_tpu.storage import RunStore


def _big_block(n=20000):
    keys = np.arange(n, dtype=np.int64)
    vals = np.zeros(n, dtype=np.int64)  # compresses well
    return Block(keys, vals)


class TestCompressedPinned:
    def test_pinned_ref_is_compressed_and_round_trips(self):
        store = RunStore("cached-tier", budget=1 << 30)
        blk = _big_block()
        ref = store.register(blk, pin=True)
        assert ref.nbytes < blk.nbytes() // 4  # compressed charge
        got = ref.get()
        np.testing.assert_array_equal(got.keys, blk.keys)
        np.testing.assert_array_equal(got.values, blk.values)
        # windows stream from the packed copy too
        n = sum(len(w) for w in ref.iter_windows())
        assert n == len(blk)

    def test_pinned_never_spills(self, tmp_path):
        # budget holds the (tiny, compressed) pinned block but nothing else
        store = RunStore("cached-nospill", budget=8192)
        ref = store.register(Block.from_pairs([(1, 2)] * 100), pin=True)
        unpinned = store.register(_big_block(), pin=False)
        store.drain_writes()  # spill writes are asynchronous now
        assert not unpinned.resident  # spilled to meet the 1-byte budget
        assert ref.path is None  # pinned stayed in (compressed) RAM
        assert dict(ref.get().iter_pairs()) == {1: 2}

    def test_over_budget_pinning_raises(self):
        store = RunStore("cached-hardfail", budget=1024)
        rng = np.random.RandomState(0)
        incompressible = Block(
            np.arange(50000, dtype=np.int64),
            rng.randint(-2 ** 62, 2 ** 62, size=50000))
        with pytest.raises(MemoryError, match="cached"):
            store.register(incompressible, pin=True)

    def test_cached_pipeline_still_exact(self):
        data = list(range(500))
        pipe = Dampr.memory(data, partitions=4).map(lambda x: x * 2).cached()
        out = sorted(pipe.run().read())
        assert out == [x * 2 for x in data]
