"""Differential conformance: run the SAME pipelines through the actual
reference implementation (subprocess, clean interpreter — its fork-based
runner must not inherit this process's JAX threads) and through dampr_tpu,
and compare materialized results exactly.

This is the strongest parity evidence the suite has: not our reading of the
reference's semantics, but the reference itself as the oracle.
"""

import json
import os
import subprocess
import sys

import pytest

from dampr_tpu import Dampr, settings

REFERENCE = "/root/reference"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REFERENCE),
    reason="reference implementation not mounted at /root/reference")

# Each case: (name, reference_script_body, ours_fn).  Scripts print one JSON
# line; bodies only use the shared DSL surface.  `DATA` is the shared input.
DATA = list(range(30, 50))

_REF_PRELUDE = """
import json, sys
sys.path.insert(0, {ref!r})
from dampr import Dampr
data = {data!r}
""".format(ref=REFERENCE, data=DATA)


def run_reference(body):
    script = _REF_PRELUDE + body
    r = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=120,
        env={"PATH": os.environ.get("PATH", "/usr/bin:/bin"),
             "HOME": "/tmp"})
    assert r.returncode == 0, r.stderr[-2000:]
    return json.loads(r.stdout.strip().splitlines()[-1])


@pytest.fixture(autouse=True)
def small_partitions(partitions8):
    yield


def norm(x):
    """JSON round-trip normalization (tuples->lists) for comparison."""
    return json.loads(json.dumps(x))


class TestDifferential:
    def test_map_filter_flat_map(self):
        ref = run_reference("""
out = Dampr.memory(data).map(lambda x: x + 1).filter(lambda x: x % 3 != 0) \\
    .flat_map(lambda x: [x, -x]).read()
print(json.dumps(out))
""")
        ours = (Dampr.memory(DATA).map(lambda x: x + 1)
                .filter(lambda x: x % 3 != 0)
                .flat_map(lambda x: [x, -x]).read())
        assert norm(ours) == ref

    def test_group_by_reduce(self):
        ref = run_reference("""
out = Dampr.memory(data).group_by(lambda x: x % 4) \\
    .reduce(lambda k, it: sum(it)).read()
print(json.dumps(out))
""")
        ours = (Dampr.memory(DATA).group_by(lambda x: x % 4)
                .reduce(lambda k, it: sum(it)).read())
        assert norm(ours) == ref

    def test_fold_by_and_count(self):
        ref = run_reference("""
a = Dampr.memory(data).fold_by(lambda x: x % 5, lambda x, y: x + y).read()
b = Dampr.memory(data).count(lambda x: x % 2).read()
print(json.dumps([a, b]))
""")
        a = Dampr.memory(DATA).fold_by(lambda x: x % 5,
                                       lambda x, y: x + y).read()
        b = Dampr.memory(DATA).count(lambda x: x % 2).read()
        assert norm([a, b]) == ref

    def test_mean_len_topk(self):
        ref = run_reference("""
m = Dampr.memory(data).mean(lambda x: x % 3).read()
l = Dampr.memory(data).len().read()
t = sorted(Dampr.memory(data).topk(4).read())
print(json.dumps([m, l, t]))
""")
        m = Dampr.memory(DATA).mean(lambda x: x % 3).read()
        ln = Dampr.memory(DATA).len().read()
        t = sorted(Dampr.memory(DATA).topk(4).read())
        assert norm([m, ln, t]) == ref

    def test_inner_and_left_join(self):
        ref = run_reference("""
left = Dampr.memory(data).group_by(lambda x: x % 7)
right = Dampr.memory(list(range(40, 60))).group_by(lambda x: x % 7)
inner = left.join(right).reduce(lambda l, r: [sorted(l), sorted(r)]).read()
left2 = Dampr.memory(data).group_by(lambda x: x)
right2 = Dampr.memory(list(range(45, 55))).group_by(lambda x: x)
lj = left2.join(right2).left_reduce(lambda l, r: [sorted(l), sorted(r)]).read()
print(json.dumps([inner, lj]))
""")
        left = Dampr.memory(DATA).group_by(lambda x: x % 7)
        right = Dampr.memory(list(range(40, 60))).group_by(lambda x: x % 7)
        inner = left.join(right).reduce(
            lambda l, r: [sorted(l), sorted(r)]).read()
        left2 = Dampr.memory(DATA).group_by(lambda x: x)
        right2 = Dampr.memory(list(range(45, 55))).group_by(lambda x: x)
        lj = left2.join(right2).left_reduce(
            lambda l, r: [sorted(l), sorted(r)]).read()
        assert norm([inner, lj]) == ref

    def test_sort_by_and_sample_bounds(self):
        ref = run_reference("""
s = Dampr.memory(data).sort_by(lambda x: -x).read()
print(json.dumps(s))
""")
        ours = Dampr.memory(DATA).sort_by(lambda x: -x).read()
        assert norm(ours) == ref

    def test_cross_left_and_cross_set(self):
        ref = run_reference("""
l = Dampr.memory(data[:4])
r = Dampr.memory(["x", "y"])
c = l.cross_left(r, lambda a, b: [a, b]).read()
cs = l.cross_set(Dampr.memory([31, 33]), lambda a, s: a in s, agg=set).read()
print(json.dumps([c, cs]))
""")
        l = Dampr.memory(DATA[:4])
        r = Dampr.memory(["x", "y"])
        c = l.cross_left(r, lambda a, b: [a, b]).read()
        cs = l.cross_set(Dampr.memory([31, 33]), lambda a, s: a in s,
                         agg=set).read()
        assert norm([c, cs]) == ref

    def test_multi_output_shared_prefix(self):
        ref = run_reference("""
evens = Dampr.memory(data).filter(lambda x: x % 2 == 0).checkpoint()
s = evens.a_group_by(lambda x: 1).sum()
c = evens.count(lambda x: 1)
sv, cv = Dampr.run(s, c)
print(json.dumps([sv.read(), cv.read()]))
""")
        evens = Dampr.memory(DATA).filter(lambda x: x % 2 == 0).checkpoint()
        s = evens.a_group_by(lambda x: 1).sum()
        c = evens.count(lambda x: 1)
        sv, cv = Dampr.run(s, c)
        assert norm([sv.read(), cv.read()]) == ref

    def test_wordcount_text_file(self, tmp_path):
        p = str(tmp_path / "wc.txt")
        text = (open(os.path.join(REFERENCE, "README.md")).read()) * 2
        with open(p, "w") as f:
            f.write(text)
        ref = run_reference("""
out = sorted(Dampr.text({p!r}, 4096).flat_map(lambda l: l.split())
             .count().read())
print(json.dumps(out))
""".replace("{p!r}", repr(p)))
        ours = sorted(Dampr.text(p, 4096)
                      .flat_map(lambda l: l.split()).count().read())
        assert norm(ours) == ref

    def test_unique_matches_as_set(self):
        # The reference's output ORDER here is nondeterministic across runs
        # (PYTHONHASHSEED-salted partitioning + fork completion order —
        # verified by running it repeatedly), so compare contents only.
        # Note: the reference's first() is NOT differentially tested — its
        # implementation keeps the NEWEST value per key (ReducedWriter calls
        # binop(new, cached), dataset.py:100-105), contradicting its own
        # docstring ("first item found"); we implement the documented
        # semantics deterministically.
        ref = run_reference("""
names = [("a", 1), ("a", 1), ("a", 2), ("b", 9)]
u = Dampr.memory(names).group_by(lambda x: x[0], lambda x: x[1]).unique().read()
print(json.dumps(sorted(u, key=str)))
""")
        names = [("a", 1), ("a", 1), ("a", 2), ("b", 9)]
        u = (Dampr.memory(names)
             .group_by(lambda x: x[0], lambda x: x[1]).unique().read())
        assert sorted(norm(u), key=str) == ref

    def test_custom_mapper_and_reducer(self):
        ref = run_reference("""
from dampr.base import Map, Reduce
cm = Dampr.memory(data).custom_mapper(Map(lambda k, x: [(k, x * 3)])).read()
cr = sorted(Dampr.memory(data).custom_reducer(
    Reduce(lambda k, it: sum(it))).read())
print(json.dumps([cm, cr]))
""")
        from dampr_tpu import Map, Reduce
        cm = Dampr.memory(DATA).custom_mapper(
            Map(lambda k, x: [(k, x * 3)])).read()
        cr = sorted(Dampr.memory(DATA).custom_reducer(
            Reduce(lambda k, it: sum(it))).read())
        assert norm([cm, cr]) == ref

    def test_partition_map_reduce(self):
        ref = run_reference("""
def pm(items):
    total = 0
    for v in items:
        total += v
    yield 1, total

def pr(groups):
    s = 0
    seen = False
    for _k, vals in groups:
        for v in vals:
            seen = True
            s += v
    if seen:
        yield "sum", s

out = Dampr.memory(data).partition_map(pm).partition_reduce(pr).read()
print(json.dumps(sorted(v[1] for v in out)))
""")
        def pm(items):
            total = 0
            for v in items:
                total += v
            yield 1, total

        def pr(groups):
            s = 0
            seen = False
            for _k, vals in groups:
                for v in vals:
                    seen = True
                    s += v
            if seen:
                yield "sum", s

        out = Dampr.memory(DATA).partition_map(pm).partition_reduce(pr).read()
        assert sorted(v[1] for v in out) == ref

    def test_sink_tsv_round_trip(self, tmp_path):
        ref_dir = str(tmp_path / "ref_sink")
        ref = run_reference("""
Dampr.memory([(x, x * x) for x in data]).sink_tsv({d!r}).run()
import os
lines = []
for p in sorted(os.listdir({d!r})):
    with open(os.path.join({d!r}, p)) as f:
        lines.extend(l.strip() for l in f if l.strip())
print(json.dumps(sorted(lines)))
""".replace("{d!r}", repr(ref_dir)))
        ours_dir = str(tmp_path / "ours_sink")
        Dampr.memory([(x, x * x) for x in DATA]).sink_tsv(ours_dir).run()
        lines = []
        for p in sorted(os.listdir(ours_dir)):
            with open(os.path.join(ours_dir, p)) as f:
                lines.extend(l.strip() for l in f if l.strip())
        assert sorted(lines) == ref

    def test_filter_by_count_util(self):
        ref = run_reference("""
sys.path.insert(0, {ref!r})
from dampr.utils import filter_by_count
d2 = ["a"] * 5 + ["b"] * 2 + ["c"]
out = sorted(filter_by_count(Dampr.memory(d2), lambda x: x,
                             lambda c: c >= 2).read())
print(json.dumps(out))
""".replace("{ref!r}", repr(REFERENCE)))
        from dampr_tpu.utils import filter_by_count
        d2 = ["a"] * 5 + ["b"] * 2 + ["c"]
        out = sorted(filter_by_count(Dampr.memory(d2), lambda x: x,
                                     lambda c: c >= 2).read())
        assert norm(out) == ref
