"""Fused Pallas segmented fold vs the exact host oracle (interpret mode on
CPU; the Mosaic lowering and real-chip numbers are benchmarks territory —
benchmarks/pallas_bench.py)."""

import numpy as np
import pytest

from dampr_tpu.ops import pallas_segfold as SF


def _sorted_case(rng, n_keys, n, max_v=9, n_invalid=0):
    """Random sorted-by-(inv,h1,h2) arrays + oracle outputs."""
    kh1 = rng.randint(0, 1 << 32, size=n_keys, dtype=np.uint64).astype(
        np.uint32)
    kh2 = rng.randint(0, 1 << 32, size=n_keys, dtype=np.uint64).astype(
        np.uint32)
    ids = np.sort(rng.randint(0, n_keys, size=n - n_invalid))
    h1 = kh1[ids]
    h2 = kh2[ids]
    inv = np.zeros(n, dtype=np.uint32)
    if n_invalid:
        h1 = np.concatenate([h1, np.zeros(n_invalid, np.uint32)])
        h2 = np.concatenate([h2, np.zeros(n_invalid, np.uint32)])
        inv[n - n_invalid:] = 1
    v = rng.randint(0, max_v + 1, size=n).astype(np.int32)
    # sort by (inv, h1, h2) like the engine does
    order = np.lexsort((h2, h1, inv))
    return h1[order], h2[order], v[order], inv[order]


def _pad(h1, h2, v, inv):
    te = SF._tile_elems()
    n = len(h1)
    npad = -(-n // te) * te
    if npad != n:
        pad = npad - n
        h1 = np.concatenate([h1, np.zeros(pad, h1.dtype)])
        h2 = np.concatenate([h2, np.zeros(pad, h2.dtype)])
        v = np.concatenate([v, np.zeros(pad, v.dtype)])
        inv = np.concatenate([inv, np.ones(pad, inv.dtype)])
    return h1, h2, v, inv


def _check(h1, h2, v, inv):
    tot, live = SF.segfold_sorted(h1, h2, v, inv, interpret=True)
    rtot, rlive = SF.segfold_reference(h1, h2, v, inv)
    np.testing.assert_array_equal(np.asarray(live), rlive)
    lt = np.asarray(tot).astype(np.int64) * (np.asarray(live) == 1)
    rt = rtot * (rlive == 1)
    np.testing.assert_array_equal(lt, rt)


class TestSegfoldInterpret:
    def test_single_tile_exact(self):
        rng = np.random.RandomState(0)
        _check(*_pad(*_sorted_case(rng, 50, SF._tile_elems())))

    def test_multi_tile_exact_with_carry(self):
        rng = np.random.RandomState(1)
        _check(*_pad(*_sorted_case(rng, 300, 3 * SF._tile_elems())))

    def test_segment_spanning_tiles(self):
        te = SF._tile_elems()
        n = 2 * te
        h1 = np.zeros(n, dtype=np.uint32)  # one giant segment
        h2 = np.zeros(n, dtype=np.uint32)
        v = np.ones(n, dtype=np.int32)
        inv = np.zeros(n, dtype=np.uint32)
        tot, live = SF.segfold_sorted(h1, h2, v, inv, interpret=True)
        assert int(np.asarray(live).sum()) == 1
        assert int(np.asarray(tot)[np.asarray(live) == 1][0]) == n

    def test_invalid_tail_excluded(self):
        rng = np.random.RandomState(2)
        case = _sorted_case(rng, 40, SF._tile_elems(), n_invalid=500)
        _check(*_pad(*case))

    def test_every_element_distinct(self):
        te = SF._tile_elems()
        h1 = np.arange(te, dtype=np.uint32)
        h2 = np.arange(te, dtype=np.uint32)
        v = np.full(te, 3, dtype=np.int32)
        inv = np.zeros(te, dtype=np.uint32)
        tot, live = SF.segfold_sorted(h1, h2, v, inv, interpret=True)
        assert (np.asarray(live) == 1).all()
        assert (np.asarray(tot) == 3).all()

    def test_matches_local_fold_scan_outputs(self):
        # Oracle parity with the XLA scan lowering in _local_fold.
        import jax.numpy as jnp

        from dampr_tpu.parallel.shuffle import _local_fold

        rng = np.random.RandomState(3)
        h1, h2, v, inv = _pad(*_sorted_case(rng, 100, SF._tile_elems()))
        oinv, oh1, oh2, ov = _local_fold(
            jnp.asarray(inv), jnp.asarray(h1), jnp.asarray(h2),
            jnp.asarray(v), "sum", nonneg_sum=True)
        tot, live = SF.segfold_sorted(h1, h2, v, inv, interpret=True)
        want = {}
        m = np.asarray(oinv) == 0
        for a, b, t in zip(np.asarray(oh1)[m], np.asarray(oh2)[m],
                           np.asarray(ov)[m]):
            want[(int(a), int(b))] = int(t)
        got = {}
        lm = np.asarray(live) == 1
        for a, b, t in zip(h1[lm], h2[lm], np.asarray(tot)[lm]):
            got[(int(a), int(b))] = int(t)
        assert got == want
