"""Run-scoped tracing + stats (dampr_tpu.obs): trace emission at the hot
boundaries, Chrome trace-event schema validity, stats.json structure, the
ValueEmitter.stats() accessor, and per-stage spill attribution."""

import importlib.util
import json
import operator
import os

import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.obs import export, trace

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "validate_trace", os.path.join(ROOT, "tools", "validate_trace.py"))
validate_trace = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(validate_trace)

with open(os.path.join(ROOT, "docs", "trace_schema.json")) as _f:
    TRACE_SCHEMA = json.load(_f)


@pytest.fixture
def traced(tmp_path):
    """Enable tracing for one test, artifacts under tmp_path."""
    old_trace, old_dir = settings.trace, settings.trace_dir
    settings.trace = True
    settings.trace_dir = str(tmp_path)
    yield tmp_path
    settings.trace = old_trace
    settings.trace_dir = old_dir


def _corpus(tmp_path, lines=4000):
    path = tmp_path / "corpus.txt"
    words = ["alpha", "beta", "gamma", "delta", "tok%d" % 7, "zz"]
    with open(path, "w") as f:
        for i in range(lines):
            f.write(" ".join(words[(i + j) % len(words)]
                             for j in range(8)) + "\n")
    return str(path)


def _load_trace(summary):
    assert summary["trace_file"] and os.path.isfile(summary["trace_file"])
    with open(summary["trace_file"]) as f:
        return json.load(f)


def _cats(doc):
    return {ev.get("cat") for ev in doc["traceEvents"]
            if ev.get("ph") in ("X", "i")}


class TestTracedRuns:
    def test_tfidf_shape_kinds_and_schema(self, traced, tmp_path):
        """The bench-shaped workload (block codec -> fold) emits codec,
        fold, stage and job spans on per-slot lanes, and the trace
        validates against the checked-in schema."""
        from dampr_tpu.ops.text import DocFreq

        corpus = _corpus(tmp_path)
        docs = Dampr.text(corpus, chunk_size=16 * 1024)
        em = (docs.custom_mapper(
                  DocFreq(mode="word", lower=True, pair_values=False))
              .fold_values(operator.add)
              .run(name="obs-tfidf"))
        counts = dict(em.read())
        assert counts and all(c > 0 for c in counts.values())
        summary = em.stats()
        doc = _load_trace(summary)
        errors = validate_trace.validate(doc, TRACE_SCHEMA)
        assert not errors, errors
        cats = _cats(doc)
        assert {"codec", "fold", "stage", "job"} <= cats, cats
        # per-slot lanes: more than one named lane (pool workers + codec
        # producer threads), each declared via thread_name metadata
        lanes = [ev for ev in doc["traceEvents"]
                 if ev.get("ph") == "M" and ev["name"] == "thread_name"]
        assert len(lanes) >= 2, lanes
        assert any("codec" in ev["args"]["name"] for ev in lanes), (
            "codec producer threads should appear as their own lanes")
        em.delete()

    def test_mesh_fold_emits_collective_spans(self, traced):
        """On the 8-device test mesh the associative fold rides the
        collective path and records collective spans."""
        em = (Dampr.memory(list(range(20000)))
              .map(lambda x: (x % 31, 1))
              .fold_by(lambda kv: kv[0], operator.add, lambda kv: kv[1])
              .run(name="obs-mesh"))
        out = dict(em.read())
        assert sum(out.values()) == 20000
        doc = _load_trace(em.stats())
        assert "collective" in _cats(doc), _cats(doc)
        em.delete()

    def test_sort_spill_merge_kinds_and_attribution(self, traced, tmp_path):
        """A budget-squeezed external sort emits spill + merge spans, and
        the per-stage spill-bytes sum equals the store's measured spill
        volume (same counter, stage-boundary snapshots)."""
        from dampr_tpu.ops.text import ParseNumbers
        from dampr_tpu.runner import MTRunner

        path = tmp_path / "nums.txt"
        with open(path, "w") as f:
            for i in range(60000):
                f.write("{}\n".format((i * 2654435761) % (1 << 40)))
        old_fanin, old_dev = settings.merge_fanin, settings.use_device
        settings.merge_fanin = 2
        settings.use_device = False
        try:
            pipe = (Dampr.text(str(path), chunk_size=64 * 1024)
                    .custom_mapper(ParseNumbers())
                    .checkpoint(force=True))
            runner = MTRunner("obs-sort", pipe.pmer.graph,
                              memory_budget=1 << 18)
            out = runner.run([pipe.source])
            n = sum(len(b) for b in out[0].sorted_blocks())
            assert n == 60000
        finally:
            settings.merge_fanin = old_fanin
            settings.use_device = old_dev
        summary = runner.run_summary
        assert summary["store"]["spilled_bytes"] > 0
        assert summary["store"]["merge_gens"] > 0
        assert sum(s["spill_bytes"] for s in summary["stages"]) == \
            summary["store"]["spilled_bytes"]
        assert sum(s["merge_gens"] for s in summary["stages"]) == \
            summary["store"]["merge_gens"]
        doc = _load_trace(summary)
        errors = validate_trace.validate(doc, TRACE_SCHEMA)
        assert not errors, errors
        assert {"spill", "merge", "stage", "job"} <= _cats(doc)
        out[0].delete()

    def test_checkpoint_spans_on_resume(self, traced, tmp_path):
        """Durable runs record checkpoint persist spans; reruns record
        restores."""
        src = Dampr.memory(list(range(500))).map(lambda x: x + 1)
        em = src.run(name="obs-ckpt", resume=True)
        assert "checkpoint" in _cats(_load_trace(em.stats()))
        em2 = src.run(name="obs-ckpt", resume=True)
        doc2 = _load_trace(em2.stats())
        restores = [ev for ev in doc2["traceEvents"]
                    if ev.get("cat") == "checkpoint"
                    and ev.get("name") == "restore"]
        assert restores, "rerun should restore from checkpoint"
        em2.delete()


class TestStatsSurface:
    def test_accessor_and_backcompat(self):
        em = Dampr.memory([1, 2, 3]).map(lambda x: x * 2).run()
        # historical shape: a list of per-stage dicts
        assert em.stats and isinstance(em.stats[0], dict)
        assert {"jobs", "records_out", "seconds"} <= set(em.stats[0])
        # extended per-stage fields
        assert {"bytes_in", "bytes_out", "spill_bytes",
                "records_in"} <= set(em.stats[0])
        # the accessor: full run summary
        summary = em.stats()
        assert summary["schema"] == export.STATS_SCHEMA
        assert summary["stages"] == list(em.stats)
        assert summary["wall_seconds"] >= 0
        assert "devtime" in summary and "store" in summary
        # untraced runs persist nothing
        assert summary["trace_file"] is None
        em.delete()

    def test_stats_json_persisted_and_locatable(self, traced):
        em = Dampr.memory(list(range(100))).map(lambda x: x).run(
            name="obs-locate")
        summary = em.stats()
        spath = summary["stats_file"]
        assert spath and os.path.isfile(spath)
        loaded, path = export.load_stats("obs-locate")
        assert path == spath
        assert loaded["run"] == "obs-locate"
        assert loaded["stages"]
        # formatting never raises and mentions the trace
        text = export.format_summary(loaded)
        assert "obs-locate" in text and "trace" in text
        em.delete()

    def test_bytes_in_out_tracked_across_stages(self):
        em = (Dampr.memory(list(range(5000)))
              .map(lambda x: (x % 7, x))
              .checkpoint(force=True)
              .fold_by(lambda kv: kv[0], operator.add, lambda kv: kv[1])
              .run())
        by_kind = {}
        for s in em.stats:
            by_kind.setdefault(s["kind"], []).append(s)
        assert "reduce" in by_kind
        red = by_kind["reduce"][0]
        assert red["records_in"] > 0 and red["bytes_in"] > 0
        assert red["bytes_out"] > 0
        em.delete()


class TestTracerCore:
    def test_disabled_span_is_shared_noop(self):
        assert not trace.enabled()
        s1 = trace.span("x", "a")
        s2 = trace.span("x", "b", arg=1)
        assert s1 is s2  # the shared no-op: no allocation when off
        with s1:
            pass
        assert trace.now() == 0.0
        it = iter([1, 2])
        assert trace.timed_iter(it, "x", "y") is it

    def test_span_collection_and_lanes(self):
        t = trace.Tracer("unit")
        trace.start(t)
        try:
            with trace.span("cat1", "outer", n=3):
                trace.instant("cat2", "mark")
            with trace.span("cat1", "lane-span", lane="custom lane"):
                pass
        finally:
            trace.stop(t)
        assert not trace.enabled()
        cats = {e[0] for e in t.events}
        assert cats == {"cat1", "cat2"}
        assert "custom lane" in t.lane_names.values()
        agg = t.span_summary()
        assert agg["cat1"]["count"] == 2
        # events emitted after stop are dropped (no active tracer)
        before = len(t.events)
        with trace.span("cat1", "late"):
            pass
        assert len(t.events) == before

    def test_chrome_export_round_trip(self, tmp_path):
        t = trace.Tracer("unit2")
        trace.start(t)
        try:
            # declared span kinds: the schema's x-span-kinds is a closed
            # set and validate_trace rejects undeclared categories
            with trace.span("spill", "s", bytes=10):
                pass
            trace.instant("merge", "i")
        finally:
            trace.stop(t)
        path = export.write_trace(t, str(tmp_path / "t.json"))
        with open(path) as f:
            doc = json.load(f)
        errors = validate_trace.validate(doc, TRACE_SCHEMA)
        assert not errors, errors
        phs = [e["ph"] for e in doc["traceEvents"]]
        assert "X" in phs and "i" in phs and "M" in phs
