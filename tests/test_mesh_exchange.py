"""The general shuffle on the mesh: non-associative group_by reduces and
joins route their exchange through the all_to_all byte collective
(parallel/exchange.py), matching the host path exactly."""

import uuid

import numpy as np
import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.runner import MTRunner


@pytest.fixture(autouse=True)
def exchange_on():
    old = (settings.partitions, settings.mesh_fold, settings.mesh_exchange)
    settings.partitions = 8
    settings.mesh_fold = "off"  # keep the assoc fast path out of the way
    settings.mesh_exchange = "auto"
    yield
    (settings.partitions, settings.mesh_fold,
     settings.mesh_exchange) = old


def _run(pipe, **kw):
    # uuid-salted run name: the shuffle cost model reads the run-history
    # corpus by (name, stage shapes) — a shared fixed name would let one
    # test's tiny-run history pin a later same-shaped test's exchange to
    # host (the auto-mode heuristic under exchange_min_bytes).
    runner = MTRunner("mesh-exchange-test-%s" % uuid.uuid4().hex[:8],
                      pipe.pmer.graph, **kw)
    out = runner.run([pipe.source])
    return out[0], runner


class TestBlobExchange:
    def test_blob_routing(self, mesh8):
        from dampr_tpu.parallel import mesh_blob_exchange

        blobs = {(s, d): bytes([s * 16 + d]) * (s + d + 1)
                 for s in range(8) for d in range(8) if (s + d) % 3 == 0}
        out = mesh_blob_exchange(mesh8, blobs)
        assert out == blobs  # delivered intact, keyed by the same (src, dst)

    def test_empty_and_large_blob(self, mesh8):
        from dampr_tpu.parallel import mesh_blob_exchange

        big = bytes(range(256)) * 2000  # 512000 bytes, forces a new bucket
        out = mesh_blob_exchange(mesh8, {(0, 7): big, (3, 3): b"x"})
        assert out[(0, 7)] == big
        assert out[(3, 3)] == b"x"

    def test_shuffle_blocks_order_and_destination(self, mesh8):
        from dampr_tpu.blocks import Block
        from dampr_tpu.parallel import mesh_shuffle_blocks

        routed = []
        seq = 0
        for pid in (0, 3, 11, 3, 8):
            blk = Block.from_pairs([(pid, seq)])
            routed.append((seq, seq % 8, pid, blk))
            seq += 1
        received, moved = mesh_shuffle_blocks(mesh8, routed)
        assert moved > 0
        assert [pid for pid, _ in received] == [0, 3, 11, 3, 8]  # seq order
        assert [list(b.iter_pairs())[0][1] for _, b in received] == [
            0, 1, 2, 3, 4]


class TestEngineExchange:
    def test_nonassoc_group_by_rides_exchange(self):
        data = list(range(4000))
        pipe = (Dampr.memory(data, partitions=8)
                .group_by(lambda x: x % 9)
                .reduce(lambda k, vs: sorted(vs)[:2]))
        ds, runner = _run(pipe)
        assert runner.mesh_exchanges >= 1
        assert runner.mesh_exchange_bytes > 0
        got = dict(v for v in ds.read())
        want = {k: (k, sorted(x for x in data if x % 9 == k)[:2])
                for k in range(9)}
        assert got == want

    def test_group_by_matches_host_path(self):
        data = [(i % 11, i * 3) for i in range(3000)]

        def build():
            return (Dampr.memory(data, partitions=8)
                    .group_by(lambda x: x[0])
                    .reduce(lambda k, vs: sum(v[1] for v in vs)))

        mesh_out, runner = _run(build())
        assert runner.mesh_exchanges >= 1
        settings.mesh_exchange = "off"
        host_out, hrunner = _run(build())
        assert hrunner.mesh_exchanges == 0
        assert sorted(mesh_out.read()) == sorted(host_out.read())

    def test_join_rides_exchange_and_matches_host(self):
        left = [(i % 7, i) for i in range(600)]
        right = [(i % 7, -i) for i in range(200) if i % 7 != 3]

        def build():
            lp = Dampr.memory(left, partitions=8).group_by(lambda x: x[0])
            rp = Dampr.memory(right, partitions=8).group_by(lambda x: x[0])
            return lp.join(rp).reduce(
                lambda l, r: (sorted(v[1] for v in l)[:2],
                              sorted(v[1] for v in r)[:2]))

        mesh_out, runner = _run(build())
        assert runner.mesh_exchanges >= 1
        settings.mesh_exchange = "off"
        host_out, _ = _run(build())
        assert sorted(mesh_out.read()) == sorted(host_out.read())

    def test_left_join_through_exchange(self):
        left = [(i % 5, i) for i in range(100)]
        right = [(0, "z"), (2, "y")]
        lp = Dampr.memory(left, partitions=8).group_by(lambda x: x[0])
        rp = Dampr.memory(right, partitions=8).group_by(lambda x: x[0])
        pipe = lp.join(rp).left_reduce(
            lambda l, r: (len(list(l)), len(list(r))))
        ds, runner = _run(pipe)
        assert runner.mesh_exchanges >= 1
        got = dict(v for v in ds.read())
        assert got == {0: (0, (20, 1)), 1: (1, (20, 0)), 2: (2, (20, 1)),
                       3: (3, (20, 0)), 4: (4, (20, 0))}

    def test_windowed_exchange_small_budget(self):
        # A tiny budget forces many flush windows through the collective;
        # results stay exact and per-group value order is preserved.
        data = [(i % 3, i) for i in range(5000)]
        pipe = (Dampr.memory(data, partitions=8)
                .group_by(lambda x: x[0])
                .reduce(lambda k, vs: [v[1] for v in vs][:5]))
        ds, runner = _run(pipe, memory_budget=1 << 16)
        assert runner.mesh_exchanges >= 1
        got = dict(v for v in ds.read())
        for k in range(3):
            assert got[k] == (k, [x for x in range(5000)
                                  if x % 3 == k][:5])

    def test_over_window_ref_streams_in_pieces(self):
        # One partition holding a block far larger than the exchange window
        # must stream through the collective in bounded pieces, not allocate
        # a D*D amplification of the whole block.
        data = [(0, "x" * 50) for _ in range(20000)]  # one hot key
        pipe = (Dampr.memory(data, partitions=8)
                .group_by(lambda x: x[0])
                .reduce(lambda k, vs: len(list(vs))))
        ds, runner = _run(pipe, memory_budget=1 << 18)
        assert runner.mesh_exchanges >= 1
        got = dict(v for v in ds.read())
        assert got == {0: (0, 20000)}

    def test_empty_input_does_not_count_exchange(self):
        pipe = (Dampr.memory([], partitions=4)
                .group_by(lambda x: x)
                .reduce(lambda k, vs: len(list(vs))))
        ds, runner = _run(pipe)
        assert list(ds.read()) == []
        assert runner.mesh_exchanges == 0  # nothing actually crossed

    def test_sort_by_redistributes_over_mesh(self):
        # Numeric over-budget sort: the sorted read re-partitions by key
        # range through the collective exchange; order must be exact.
        import random

        from dampr_tpu.parallel import exchange as px
        rng = random.Random(5)
        data = [rng.randrange(-10 ** 9, 10 ** 9) for _ in range(30000)]
        pipe = Dampr.memory(data, partitions=8).sort_by(lambda x: x)
        runner = MTRunner("mesh-range-sort", pipe.pmer.graph,
                          memory_budget=1 << 16)  # forces past sorted-concat
        out = runner.run([pipe.source])[0]
        before = px.total_exchanges
        got = [v for _k, v in out.read()]
        assert got == sorted(data)
        assert px.total_exchanges > before, "range sort never hit the mesh"
        # repeated reads reuse the cached bucket runs: no second exchange
        after_first = px.total_exchanges
        got2 = [v for _k, v in out.read()]
        assert got2 == got
        assert px.total_exchanges == after_first
        # partial consumption must not leak; delete releases the cache
        next(iter(out.read()))
        out.delete()
        assert out._range_cache is None

    def test_sort_by_mesh_matches_host_path(self):
        data = [((i * 7919) % 10007) for i in range(20000)]

        def run_it():
            pipe = Dampr.memory(data, partitions=8).sort_by(lambda x: x)
            runner = MTRunner("range-sort-cmp", pipe.pmer.graph,
                              memory_budget=1 << 16)
            return [v for _k, v in runner.run([pipe.source])[0].read()]

        mesh_got = run_it()
        settings.mesh_exchange = "off"
        host_got = run_it()
        assert mesh_got == host_got == sorted(data)

    def test_exchange_off_never_engages(self):
        settings.mesh_exchange = "off"
        pipe = (Dampr.memory(list(range(100)), partitions=4)
                .group_by(lambda x: x % 2)
                .reduce(lambda k, vs: len(list(vs))))
        _ds, runner = _run(pipe)
        assert runner.mesh_exchanges == 0
