"""Live metrics plane (dampr_tpu.obs.metrics/sampler/flightrec/progress/
promtext + tools/check_bench): disabled-path pin, sampler cadence and
monotonic timestamps, flight-recorder crash dumps on stage failure and
kill, ring-buffer bound under span flood, counter events in the trace,
stats surface (writer queue peak, sampler drops, overhead self-metric),
the stats CLI's series/prom/crashdump behaviors, and the CI perf gate.
"""

import importlib.util
import json
import operator
import os
import threading
import time

import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.obs import export, flightrec, metrics, promtext, trace
from dampr_tpu.obs.flightrec import FlightRecorder
from dampr_tpu.obs.metrics import Metrics
from dampr_tpu.obs.progress import ProgressReporter
from dampr_tpu.obs.sampler import Sampler

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


validate_trace = _load_tool("validate_trace")
check_bench = _load_tool("check_bench")

with open(os.path.join(ROOT, "docs", "trace_schema.json")) as _f:
    TRACE_SCHEMA = json.load(_f)


@pytest.fixture
def metered(tmp_path):
    """Metrics plane + tracing on for one test, artifacts under
    tmp_path."""
    old = (settings.trace, settings.trace_dir, settings.metrics_interval_ms)
    settings.trace = True
    settings.trace_dir = str(tmp_path)
    settings.metrics_interval_ms = 10
    yield tmp_path
    (settings.trace, settings.trace_dir,
     settings.metrics_interval_ms) = old


def _obs_threads():
    return [t.name for t in threading.enumerate()
            if t.name in ("dampr-tpu-sampler", "dampr-tpu-progress")]


class TestDisabledPath:
    def test_no_registry_no_sampler_no_cost(self):
        """The default-off pin: no sampler thread, module-level call
        sites are one None-check no-ops, stats carries no metrics
        section."""
        assert settings.effective_metrics_interval_ms() == 0
        assert not metrics.enabled()
        assert metrics.active() is None
        # the instrumentation surface is inert (would raise if it tried
        # to touch a registry)
        metrics.counter_add("x", 5)
        metrics.gauge_set("y", 1.0)
        metrics.observe("z", 2.0)
        metrics.register_gauge("w", lambda: 1)
        em = Dampr.memory(list(range(2000))).map(lambda x: (x, 1)).run()
        assert "metrics" not in em.stats()
        assert not _obs_threads()
        em.delete()

    def test_sampler_thread_scoped_to_run(self, metered):
        em = Dampr.memory(list(range(2000))).map(lambda x: (x, 1)).run(
            name="scoped")
        # sampler stopped and joined at run teardown
        assert not _obs_threads()
        assert em.stats()["metrics"]["sampler"]["samples"] >= 1
        em.delete()


class TestSampler:
    def test_cadence_and_monotonic_timestamps(self):
        m = Metrics("cadence")
        state = {"v": 0}
        m.register_gauge("g", lambda: state["v"])
        s = Sampler(m, interval_ms=10)
        s.start()
        for i in range(10):
            state["v"] = i
            time.sleep(0.02)
        s.stop()
        assert not s.alive
        assert m.sample_count >= 5  # ~20 expected; loaded boxes lag
        series = m.series["g"]
        ts = [t for t, _v in series]
        assert ts == sorted(ts), "sampler timestamps must be monotonic"
        assert all(t >= 0 for t in ts)
        # cadence property: samples are spread out, not a burst — the
        # span of the series covers most of the sampled window
        assert ts[-1] - ts[0] > 0.05
        # the gauge's evolution was captured
        vals = [v for _t, v in series]
        assert vals[-1] >= vals[0]
        # self-accounting present and sane
        assert m.sample_seconds >= 0
        assert 0 <= m.overhead() < 1

    def test_series_cap_and_drop_count(self, monkeypatch):
        monkeypatch.setattr(settings, "metrics_series_cap", 8)
        m = Metrics("cap")
        for i in range(50):
            m.record_sample(float(i), {"g": i}, 0.0)
        assert len(m.series["g"]) == 8
        assert m.series_drops == 42
        # the retained tail is the most recent samples
        assert [v for _t, v in m.series["g"]] == list(range(42, 50))

    def test_broken_gauge_dropped_not_fatal(self):
        m = Metrics("broken")

        def bad():
            raise RuntimeError("gauge exploded")

        m.register_gauge("bad", bad)
        m.register_gauge("good", lambda: 7)
        snap = m.snapshot()
        assert snap["good"] == 7 and "bad" not in snap
        # dead callback evicted: later snapshots don't re-raise
        assert "bad" not in m.gauge_fns
        assert m.snapshot()["good"] == 7


class TestFlightRecorder:
    def test_ring_bound_under_span_flood(self):
        rec = FlightRecorder("flood", capacity=64)
        for i in range(10000):
            rec.record_span("fold", "s{}".format(i), float(i), 0.001,
                            1, "lane", None)
        assert len(rec) <= 64
        assert rec.drops > 0

    def test_flush_is_schema_valid(self, tmp_path, monkeypatch):
        monkeypatch.setattr(settings, "trace_dir", str(tmp_path))
        rec = FlightRecorder("flush-unit", capacity=32)
        rec.record_span("spill", "w", time.perf_counter(), 0.01, 3,
                        "writer-0", {"bytes": 10})
        rec.record_sample(time.perf_counter(),
                          {"writer.queue_depth": 4, "skip": "str"})
        path = rec.flush("unit-test", ValueError("boom"))
        assert path and os.path.isfile(path)
        with open(path) as f:
            doc = json.load(f)
        assert not validate_trace.validate(doc, TRACE_SCHEMA)
        crash = doc["otherData"]["crash"]
        assert crash["reason"] == "unit-test"
        assert crash["exception"] == "ValueError"
        cvals = [ev for ev in doc["traceEvents"] if ev["ph"] == "C"]
        assert cvals and all(isinstance(ev["args"]["value"], (int, float))
                             for ev in cvals)
        # non-numeric sample entries are filtered, not emitted
        assert not any(ev["name"] == "skip" for ev in cvals)

    def test_injected_stage_failure_leaves_crashdump(self, metered):
        def boom(x):
            if x == 333:
                raise RuntimeError("injected")
            return (x, x)

        with pytest.raises(RuntimeError, match="injected"):
            Dampr.memory(list(range(2000))).map(boom).run(name="inj")
        dump = flightrec.locate_crashdump("inj")
        assert dump and os.path.isfile(dump)
        with open(dump) as f:
            doc = json.load(f)
        assert not validate_trace.validate(doc, TRACE_SCHEMA), (
            validate_trace.validate(doc, TRACE_SCHEMA))
        crash = doc["otherData"]["crash"]
        assert crash["exception"] == "RuntimeError"
        # the dump carries recent samples incl. the writer-pool gauges
        cevents = [ev for ev in doc["traceEvents"] if ev["ph"] == "C"]
        cnames = {ev["name"] for ev in cevents}
        assert "writer.queue_depth" in cnames
        assert "writer.inflight_bytes" in cnames
        # sample timestamps share the span clock (the recorder converts
        # absolute perf_counter values against one epoch) — they must
        # not all collapse to 0
        xts = [ev["ts"] for ev in doc["traceEvents"] if ev["ph"] == "X"]
        cts = [ev["ts"] for ev in cevents]
        if xts:
            assert max(cts) > 0
            assert max(cts) <= max(xts) + 10e6  # same order of magnitude

    def test_kill_leaves_crashdump(self, metered):
        def kill(x):
            if x == 999:
                raise KeyboardInterrupt()
            return (x, x)

        with pytest.raises(KeyboardInterrupt):
            Dampr.memory(list(range(3000))).map(kill).run(name="killed")
        dump = flightrec.locate_crashdump("killed")
        assert dump and os.path.isfile(dump)
        with open(dump) as f:
            doc = json.load(f)
        assert not validate_trace.validate(doc, TRACE_SCHEMA)
        assert doc["otherData"]["crash"]["exception"] == (
            "KeyboardInterrupt")

    def test_healthy_run_leaves_no_crashdump(self, metered):
        em = Dampr.memory(list(range(500))).map(lambda x: (x, 1)).run(
            name="healthy")
        em.delete()
        assert flightrec.locate_crashdump("healthy") is None

    def test_successful_rerun_clears_stale_crashdump(self, metered):
        """A crashdump describes the LATEST run under a name: after a
        failed run, a successful rerun must clear it (and the stats
        CLI's exit-3 with it)."""
        def flaky(x):
            if x == 7:
                raise RuntimeError("first attempt dies")
            return (x, x)

        with pytest.raises(RuntimeError):
            Dampr.memory(list(range(100))).map(flaky).run(name="rerun")
        assert flightrec.locate_crashdump("rerun") is not None
        em = Dampr.memory(list(range(100))).map(
            lambda x: (x, x)).run(name="rerun")
        em.delete()
        assert flightrec.locate_crashdump("rerun") is None


class TestTraceCounterEvents:
    def test_counter_tracks_in_trace_and_validator(self, metered):
        em = (Dampr.memory(list(range(60000)))
              .map(lambda x: (x % 101, 1))
              .fold_by(lambda kv: kv[0], operator.add, lambda kv: kv[1])
              .run(name="tracks"))
        summary = em.stats()
        with open(summary["trace_file"]) as f:
            doc = json.load(f)
        errors = validate_trace.validate(
            doc, TRACE_SCHEMA,
            require_counters=("store.resident_bytes",
                              "writer.queue_depth", "run.active_jobs"))
        assert not errors, errors
        cevents = [ev for ev in doc["traceEvents"] if ev["ph"] == "C"]
        assert cevents
        # per-series timestamps non-decreasing (validator also pins this)
        by_name = {}
        for ev in cevents:
            by_name.setdefault(ev["name"], []).append(ev["ts"])
        for name, ts in by_name.items():
            assert ts == sorted(ts), name
        # series round-trip through the CLI loader
        series = export.load_series(summary["trace_file"])
        assert "store.resident_bytes" in series
        text = export.format_series(series)
        assert "store.resident_bytes" in text
        em.delete()

    def test_missing_required_counter_fails_validation(self):
        doc = {"traceEvents": [
            {"ph": "M", "pid": 1, "tid": 0, "name": "thread_name",
             "args": {"name": "main"}},
            {"ph": "C", "pid": 1, "tid": 0, "name": "a", "ts": 1.0,
             "args": {"value": 2}},
        ]}
        errs = validate_trace.validate(doc, TRACE_SCHEMA,
                                       require_counters=("b",))
        assert any("required counter series" in e for e in errs)
        # backwards counter timestamps rejected
        doc["traceEvents"].append(
            {"ph": "C", "pid": 1, "tid": 0, "name": "a", "ts": 0.5,
             "args": {"value": 3}})
        errs = validate_trace.validate(doc, TRACE_SCHEMA)
        assert any("go backwards" in e for e in errs)


class TestStatsSurface:
    def test_summary_metrics_section(self, metered):
        em = (Dampr.memory(list(range(50000)))
              .map(lambda x: (x % 13, 1))
              .fold_by(lambda kv: kv[0], operator.add, lambda kv: kv[1])
              .run(name="surface"))
        s = em.stats()
        m = s["metrics"]
        assert m["counters"]["run.jobs_started"] >= 1
        assert m["counters"]["store.records"] > 0
        sm = m["sampler"]
        assert sm["samples"] >= 1
        assert "series_drops" in sm
        # the overhead self-metric: present, sane, tiny for this run
        assert 0 <= sm["overhead"] < 0.5
        # writer-pool peak queue depth surfaced in the io section
        assert "writer_queue_peak" in s["io"]
        # formatting renders the metrics line
        assert "sampler overhead" in export.format_summary(s)
        em.delete()

    def test_writer_queue_peak_under_spill_pressure(self, metered):
        from dampr_tpu.ops.text import ParseNumbers
        from dampr_tpu.runner import MTRunner

        path = metered / "nums.txt"
        with open(path, "w") as f:
            for i in range(60000):
                f.write("{}\n".format((i * 2654435761) % (1 << 40)))
        old_dev = settings.use_device
        settings.use_device = False
        try:
            pipe = (Dampr.text(str(path), chunk_size=64 * 1024)
                    .custom_mapper(ParseNumbers())
                    .checkpoint(force=True))
            runner = MTRunner("queue-peak", pipe.pmer.graph,
                              memory_budget=1 << 18)
            out = runner.run([pipe.source])
            n = sum(len(b) for b in out[0].sorted_blocks())
            assert n == 60000
        finally:
            settings.use_device = old_dev
        s = runner.run_summary
        if settings.spill_write_threads > 0:
            assert s["io"]["writer_queue_peak"] >= 1
        assert s["store"]["spilled_bytes"] > 0
        # merge fan-in histogram observed under forced merge pressure
        assert "merge.kway_streams" in s["metrics"]["histograms"]
        out[0].delete()

    def test_promtext_render(self, metered):
        em = Dampr.memory(list(range(4000))).map(lambda x: (x, 1)).run(
            name="prom")
        s = em.stats()
        txt = promtext.render_summary(s)
        assert "# TYPE dampr_tpu_store_records_total counter" in txt
        assert 'run="prom"' in txt
        assert "dampr_tpu_sampler_overhead" in txt
        # pre-metrics stats files render to empty, not an error
        assert promtext.render_summary({"run": "old"}) == ""
        em.delete()


class TestStatsCli:
    def _run_cli(self, argv, monkeypatch):
        import sys

        from dampr_tpu import cli

        monkeypatch.setattr(sys, "argv", ["dampr-tpu-stats"] + argv)
        try:
            cli.stats()
        except SystemExit as e:
            return e.code or 0
        return 0

    def test_series_and_prom_flags(self, metered, monkeypatch, capsys):
        em = Dampr.memory(list(range(3000))).map(lambda x: (x, 1)).run(
            name="cliser")
        spath = em.stats()["stats_file"]
        em.delete()
        assert self._run_cli([spath, "--series"], monkeypatch) == 0
        out = capsys.readouterr().out
        assert "store.resident_bytes" in out
        assert self._run_cli([spath, "--prom"], monkeypatch) == 0
        out = capsys.readouterr().out
        assert "dampr_tpu_store_records_total" in out

    def test_crashdump_exit_nonzero(self, metered, monkeypatch, capsys):
        def boom(x):
            raise RuntimeError("cli-crash")

        with pytest.raises(RuntimeError):
            Dampr.memory([1, 2, 3]).map(boom).run(name="clicrash")
        rc = self._run_cli(["clicrash"], monkeypatch)
        assert rc == 3
        assert "CRASHED RUN" in capsys.readouterr().err


class TestCheckBench:
    def _write(self, tmp_path, name, doc):
        p = tmp_path / name
        with open(p, "w") as f:
            json.dump(doc, f)
        return str(p)

    def test_flags_20pct_drop(self, tmp_path):
        fresh = self._write(tmp_path, "fresh.json",
                            {"metric": "m", "value": 80.0})
        base = self._write(tmp_path, "base.json",
                           {"metric": "m", "value": 100.0})
        rc = check_bench.main([fresh, "--baseline", base,
                               "--tolerance", "0.1", "--strict"])
        assert rc == 1
        # warn-only mode reports but passes
        assert check_bench.main([fresh, "--baseline", base,
                                 "--tolerance", "0.1"]) == 0

    def test_passes_within_tolerance_and_improvement(self, tmp_path):
        base = self._write(tmp_path, "base.json",
                           {"metric": "m", "value": 100.0})
        ok = self._write(tmp_path, "ok.json", {"metric": "m", "value": 95.0})
        up = self._write(tmp_path, "up.json",
                         {"metric": "m", "value": 140.0})
        assert check_bench.main([ok, "--baseline", base,
                                 "--tolerance", "0.1", "--strict"]) == 0
        assert check_bench.main([up, "--baseline", base,
                                 "--tolerance", "0.1", "--strict"]) == 0

    def test_best_of_and_wrapped_and_config_only(self, tmp_path):
        fresh = self._write(tmp_path, "fresh.json",
                            {"metric": "m", "value": 90.0})
        wrapped = self._write(
            tmp_path, "wrapped.json",
            {"n": 5, "cmd": "x", "parsed": {"metric": "m", "value": 88.0}})
        config_only = self._write(tmp_path, "cfg.json",
                                  {"metric": "descriptive text only"})
        other_metric = self._write(tmp_path, "other.json",
                                   {"metric": "different", "value": 999.0})
        report = check_bench.compare(
            check_bench.load_record(fresh),
            [(p, check_bench.load_record(p))
             for p in (wrapped, config_only, other_metric)],
            tolerance=0.1)
        assert report["best"] == 88.0  # wrapped counted, others skipped
        assert report["ok"]
        assert config_only in report["skipped"]
        assert other_metric in report["skipped"]

    def test_no_baseline_passes_and_bad_input_errors(self, tmp_path):
        fresh = self._write(tmp_path, "fresh.json",
                            {"metric": "m", "value": 1.0})
        assert check_bench.main([fresh, "--strict"]) == 0
        bad = self._write(tmp_path, "bad.json", {"metric": "m"})
        assert check_bench.main([bad, "--strict"]) == 2


class TestProgress:
    def test_render_line_and_stream_ticks(self):
        import io

        m = Metrics("p")
        m.counter_add("store.records", 1000)
        m.counter_add("store.bytes", 4 * 1024 ** 2)
        buf = io.StringIO()
        rep = ProgressReporter(
            m, lambda: {"sid": 1, "n_stages": 3, "kind": "map",
                        "jobs_total": 8, "jobs_done": 2,
                        "stage_t0": time.time() - 1.0},
            interval_ms=50, stream=buf)
        line = rep.render_line()
        assert "[stage 1/3 map]" in line and "jobs 2/8" in line
        assert "eta" in line
        rep.start()
        time.sleep(0.3)
        rep.stop()
        assert rep.lines >= 2
        assert "[stage 1/3 map]" in buf.getvalue()

    def test_progress_run_end_to_end(self, metered, monkeypatch):
        monkeypatch.setattr(settings, "progress", True)
        monkeypatch.setattr(settings, "progress_interval_ms", 50)
        em = Dampr.memory(list(range(50000))).map(
            lambda x: (x % 7, 1)).run(name="prog-e2e")
        assert not _obs_threads()  # reporter joined at teardown
        em.delete()


class TestRecorderWiring:
    def test_tracer_mirrors_into_ring(self):
        t = trace.Tracer("mirror")
        rec = FlightRecorder("mirror", capacity=8)
        t.recorder = rec
        trace.start(t)
        try:
            for _ in range(20):
                with trace.span("fold", "x"):
                    pass
        finally:
            trace.stop(t)
        assert len(t.events) == 20      # tracer keeps everything
        assert len(rec) <= 8            # ring stays bounded
        assert rec.drops >= 12
