"""Test rig: force an 8-device virtual CPU mesh before JAX initializes.

This is the 'multi-device without a real pod' fake backend from SURVEY.md §4:
XLA_FLAGS=--xla_force_host_platform_device_count=8 + CPU platform, so sharding and
collective paths are exercised on any machine.  Must run before any jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# Small blocks should still exercise the device path in tests.
os.environ.setdefault("DAMPR_TPU_USE_DEVICE", "1")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    import jax
    from jax.sharding import Mesh
    import numpy as np

    devs = np.array(jax.devices())
    assert devs.size == 8, devs
    return Mesh(devs, ("shards",))
