"""Test rig: an 8-device virtual CPU mesh (SURVEY §4's 'multi-device without
a real pod' fake backend).

Two things must happen before JAX initializes a backend:
- XLA_FLAGS gains --xla_force_host_platform_device_count=8 (env, read at
  backend init);
- platform selection must be forced to cpu *via jax.config*, because the
  environment's TPU plugin (axon) programmatically sets
  jax_platforms="axon,cpu" at interpreter start, clobbering any JAX_PLATFORMS
  env var — an env-var setdefault silently loses.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Initialize the CPU backend eagerly: auto-mode mesh decisions
# (settings.device_count_for_auto) deliberately refuse to initialize a
# backend on tunnel-attached hosts, so without this a test that runs first
# in a fresh process would see "1 device" and skip the mesh paths.
jax.devices()

# Small blocks should still exercise the device path in tests: pin the
# dispatch threshold so backend-specific auto-resolution never de-targets
# device-branch regression tests.
os.environ.setdefault("DAMPR_TPU_USE_DEVICE", "1")
from dampr_tpu import settings as _settings  # noqa: E402

_settings.device_min_batch = 4096

# Session-fresh scratch root: the run-history corpus (obs.history) and
# resume checkpoints persist under scratch across pytest SESSIONS, so a
# shared /tmp/dampr_tpu would let a previous session's records steer
# stats-driven adaptation inside this one (fixed run names are reused
# all over the suite).  Within one session behavior is unchanged —
# tests still share one root, which the cross-run resume/adaptive tests
# rely on.
import atexit  # noqa: E402
import shutil  # noqa: E402
import tempfile  # noqa: E402

_settings.scratch_root = tempfile.mkdtemp(prefix="dampr-tpu-tests-")
atexit.register(shutil.rmtree, _settings.scratch_root, True)

import pytest  # noqa: E402

#: The reference repo's README, used by several kernels tests as a natural-
#: text corpus.  Containers without the reference mounted get a
#: deterministic synthetic stand-in with the same character classes the
#: real file exercises (mixed case, punctuation, digits, underscores,
#: blank lines, a little UTF-8).
_REFERENCE_README = "/root/reference/README.md"


def reference_text():
    try:
        with open(_REFERENCE_README) as f:
            return f.read()
    except OSError:
        words = ["Dampr", "map", "reduce", "Stream_Fold", "chunk42",
                 "naïve", "pipeline", "DAG", "a", "the", "of", "tokens",
                 "spill", "merge", "TPU", "block", "codec", "fold"]
        lines = []
        for i in range(120):
            row = [words[(i * 7 + j * 3) % len(words)]
                   for j in range(3 + i % 9)]
            sep = ", " if i % 4 else " -- "
            lines.append(sep.join(row) + (".", "!", "", ":")[i % 4])
            if i % 17 == 0:
                lines.append("")
        return "\n".join(lines) + "\n"


@pytest.fixture(scope="session")
def mesh8():
    import numpy as np
    from jax.sharding import Mesh

    devs = np.array(jax.devices())
    assert devs.size == 8, devs
    return Mesh(devs, ("shards",))


@pytest.fixture
def partitions8():
    """Shared: pin settings.partitions to 8 for a test, restoring after."""
    from dampr_tpu import settings

    old = settings.partitions
    settings.partitions = 8
    yield
    settings.partitions = old
