"""Fleet dashboard + endpoint concurrency (dampr_tpu.obs.top /
obs.serve / obs.promtext): exposition parsing, snapshot rows against a
LIVE MetricsServer, the dead-rank marker and hang bound, the
port-collision fallback (probed above the fleet block, recorded in
stats()["endpoint"]), back-to-back run teardown, and label-value
escaping per the Prometheus text spec.
"""

import json
import time

import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.obs import metrics, promtext, top
from dampr_tpu.obs.metrics import Metrics
from dampr_tpu.obs.serve import MetricsServer


@pytest.fixture
def live_server():
    """A MetricsServer on an OS-assigned port with a live registry."""
    reg = Metrics("top-test")
    reg.gauge_set("run.stage", 2)
    reg.gauge_set("writer.queue_depth", 5)
    reg.gauge_set("store.bytes", 1_000_000)
    reg.counter_add("mitigation.engagements", 3)
    metrics.start(reg)
    srv = MetricsServer(0, run_name="top-test", rank=0, num_processes=1)
    assert srv.start() is not None
    yield srv, reg
    srv.stop()
    metrics.stop(reg)


class TestParseExposition:
    def test_gauges_counters_labels_and_garbage(self):
        text = "\n".join([
            "# HELP dampr_tpu_run_stage current stage",
            "# TYPE dampr_tpu_run_stage gauge",
            'dampr_tpu_run_stage{run="r",rank="0"} 3',
            "dampr_tpu_writer_queue_depth 7.5",
            "dampr_tpu_mitigation_engagements_total 2",
            "malformed-line-without-value",
            "dampr_tpu_bad_value nan-ish-garbage x",
            "",
        ])
        out = top.parse_exposition(text)
        assert out["dampr_tpu_run_stage"] == 3.0
        assert out["dampr_tpu_writer_queue_depth"] == 7.5
        assert out["dampr_tpu_mitigation_engagements_total"] == 2.0
        assert "malformed-line-without-value" not in out

    def test_known_names_cover_real_exposition(self, live_server):
        """Every name the dashboard maps must parse out of a real
        render (catches silent renames of the exposition surface)."""
        _, reg = live_server
        text = promtext.render(reg, rank=0)
        parsed = top.parse_exposition(text)
        assert "dampr_tpu_run_stage" in parsed
        assert "dampr_tpu_writer_queue_depth" in parsed
        assert "dampr_tpu_store_bytes" in parsed
        assert "dampr_tpu_mitigation_engagements_total" in parsed


class TestSnapshot:
    def test_live_rank_row(self, live_server):
        srv, _ = live_server
        rows = top.snapshot([srv.port], timeout=2.0)
        row = rows[0]
        assert row["alive"] is True and row["rank"] == 0
        assert row["run"] == "top-test"
        assert row["stage"] == 2.0
        assert row["queue_depth"] == 5.0
        assert row["mitigation_engagements"] == 3.0

    def test_dead_rank_marker_and_no_hang(self, live_server):
        srv, _ = live_server
        # a port nobody serves: connection refused, not a hang
        dead_port = srv.port + 17
        t0 = time.monotonic()
        rows = top.snapshot([srv.port, dead_port], timeout=1.0)
        elapsed = time.monotonic() - t0
        assert elapsed < 5.0, "snapshot hung on the dead rank"
        assert rows[0]["alive"] is True
        assert rows[1] == {"rank": 1, "port": dead_port, "alive": False}
        text = top.render(rows)
        lines = text.splitlines()
        assert "UP" in lines[1] and "DEAD" in lines[2]
        # dead rows render placeholder cells, not stale numbers
        assert "-" in lines[2]

    def test_mbps_derived_from_store_bytes_delta(self, live_server):
        srv, reg = live_server
        rows = top.snapshot([srv.port], timeout=2.0)
        reg.gauge_set("store.bytes", 5_000_000)
        rows2 = top.snapshot([srv.port], prev_rows=rows, dt=2.0,
                             timeout=2.0)
        assert rows2[0]["mbps"] == pytest.approx(2.0)

    def test_once_json_cli(self, live_server, capsys):
        srv, _ = live_server
        rc = top.main(["--ports", str(srv.port), "--once", "--json"])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["ports"] == [srv.port]
        assert doc["ranks"][0]["alive"] is True

    def test_once_all_dead_exits_one(self, capsys):
        rc = top.main(["--ports", "1", "--once", "--json"])
        assert rc == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["ranks"][0]["alive"] is False

    def test_no_ports_exits_one(self, capsys):
        old = settings.metrics_port
        settings.metrics_port = 0
        try:
            assert top.main(["--once"]) == 1
        finally:
            settings.metrics_port = old


class TestServeConcurrency:
    def test_port_collision_probes_above_fleet_block(self):
        a = MetricsServer(0, rank=0, num_processes=1)
        assert a.start() is not None
        try:
            b = MetricsServer(a.port, rank=0, num_processes=2)
            assert b.start() is not None
            try:
                assert b.fallback is True
                # fallback never steals a sibling rank's expected port:
                # rank 1 of b's fleet would claim a.port + 1
                assert b.port != a.port + 1
                assert b.port >= a.port + 2
            finally:
                b.stop()
        finally:
            a.stop()

    def test_fallback_recorded_in_run_stats(self, tmp_path):
        blocker = MetricsServer(0, rank=0, num_processes=1)
        assert blocker.start() is not None
        old = (settings.metrics_port, settings.scratch_root,
               settings.trace_dir)
        settings.metrics_port = blocker.port
        settings.scratch_root = str(tmp_path / "scratch")
        settings.trace_dir = str(tmp_path / "traces")
        try:
            em = (Dampr.memory(list(range(2000)))
                  .map(lambda x: (x % 5, 1)).run("endpoint-fallback"))
            ep = em.stats().get("endpoint")
            em.delete()
        finally:
            (settings.metrics_port, settings.scratch_root,
             settings.trace_dir) = old
            blocker.stop()
        assert ep, "run recorded no endpoint section"
        assert ep["requested"] == blocker.port
        assert ep["fallback"] is True and ep["port"] != blocker.port

    def test_back_to_back_runs_rebind_cleanly(self, tmp_path):
        """Sequential runs on one configured port: teardown must release
        the socket so the second run binds WITHOUT fallback."""
        probe = MetricsServer(0, rank=0, num_processes=1)
        assert probe.start() is not None
        free_port = probe.port
        probe.stop()
        old = (settings.metrics_port, settings.scratch_root,
               settings.trace_dir)
        settings.metrics_port = free_port
        settings.scratch_root = str(tmp_path / "scratch")
        settings.trace_dir = str(tmp_path / "traces")
        endpoints = []
        try:
            for i in range(2):
                em = (Dampr.memory(list(range(2000)))
                      .map(lambda x: (x % 5, 1)).run("b2b-%d" % i))
                endpoints.append(em.stats().get("endpoint"))
                em.delete()
        finally:
            (settings.metrics_port, settings.scratch_root,
             settings.trace_dir) = old
        for ep in endpoints:
            assert ep and ep["port"] == free_port, endpoints
            assert ep["fallback"] is False, endpoints


class TestPromtextEscaping:
    def test_escape_label_value(self):
        assert promtext.escape_label_value('a"b') == 'a\\"b'
        assert promtext.escape_label_value("a\nb") == "a\\nb"
        assert promtext.escape_label_value("a\\b") == "a\\\\b"
        # backslash escapes first: no double-escaping of the others
        assert promtext.escape_label_value('\\"') == '\\\\\\"'

    def test_hostile_run_name_keeps_exposition_parseable(self):
        reg = Metrics('evil"run\nname\\x')
        reg.gauge_set("run.stage", 1)
        text = promtext.render(reg, rank=0)
        assert "\n\n" not in text.strip()
        for line in text.splitlines():
            assert line.startswith("#") or " " in line, line
        parsed = top.parse_exposition(text)
        assert parsed["dampr_tpu_run_stage"] == 1.0
