"""Mesh collective shuffle tests on the 8-device CPU mesh rig.

Validates the shard_map keyed fold (local combine -> all_to_all -> final
fold) against host-computed ground truth, including the overflow-retry path
and the psum global aggregate.  These are the collectives that carry the
distributed shuffle on real ICI meshes.
"""

import collections

import numpy as np
import pytest

from dampr_tpu.ops import hashing
from dampr_tpu.parallel import mesh_global_sum, mesh_keyed_fold
from dampr_tpu.parallel.mesh import mesh_size

from conftest import reference_text


def _fold_to_dict(keyspace, fh1, fh2, fv):
    kh1, kh2 = hashing.hash_keys(np.asarray(keyspace))
    lookup = {(int(a), int(b)): k
              for k, (a, b) in zip(keyspace, zip(kh1, kh2))}
    return {lookup[(int(a), int(b))]: v
            for a, b, v in zip(fh1, fh2, fv.tolist())}


class TestMeshKeyedFold:
    def test_eight_devices(self, mesh8):
        assert mesh_size(mesh8) == 8

    def test_sum_matches_host(self, mesh8):
        rng = np.random.RandomState(7)
        keys = rng.randint(0, 1000, size=50000)
        vals = rng.randint(0, 50, size=50000).astype(np.int64)
        h1, h2 = hashing.hash_keys(keys)
        got = _fold_to_dict(list(range(1000)),
                            *mesh_keyed_fold(mesh8, h1, h2, vals, "sum"))
        want = {}
        for k, v in zip(keys.tolist(), vals.tolist()):
            want[k] = want.get(k, 0) + v
        assert got == want

    def test_sum_negative_values_scatter_path(self, mesh8):
        # Negative values must miss the nonneg scan lowering and still fold
        # exactly through the scatter path.
        rng = np.random.RandomState(11)
        keys = rng.randint(0, 500, size=30000)
        vals = rng.randint(-50, 50, size=30000).astype(np.int64)
        h1, h2 = hashing.hash_keys(keys)
        got = _fold_to_dict(list(range(500)),
                            *mesh_keyed_fold(mesh8, h1, h2, vals, "sum"))
        want = {}
        for k, v in zip(keys.tolist(), vals.tolist()):
            want[k] = want.get(k, 0) + v
        assert got == want

    def test_scan_and_scatter_lowerings_agree(self, mesh8):
        # Same nonneg data through both static lowerings of the fold program.
        from dampr_tpu.parallel import shuffle as sh
        rng = np.random.RandomState(13)
        keys = rng.randint(0, 777, size=20000)
        vals = rng.randint(0, 9, size=20000).astype(np.int64)
        h1, h2 = hashing.hash_keys(keys)
        a = _fold_to_dict(list(range(777)),
                          *mesh_keyed_fold(mesh8, h1, h2, vals, "sum"))
        # force the scatter lowering by shifting through a negative no-op
        vals2 = np.concatenate([vals, np.array([-1, 1], dtype=np.int64)])
        extra = hashing.hash_keys(np.array([999888, 999888]))
        h1b = np.concatenate([h1, extra[0]])
        h2b = np.concatenate([h2, extra[1]])
        b = _fold_to_dict(list(range(777)) + [999888],
                          *mesh_keyed_fold(mesh8, h1b, h2b, vals2, "sum"))
        assert b.pop(999888) == 0
        assert a == b

    def test_min_max(self, mesh8):
        rng = np.random.RandomState(3)
        keys = rng.randint(0, 64, size=4096)
        vals = rng.randint(-1000, 1000, size=4096).astype(np.int64)
        h1, h2 = hashing.hash_keys(keys)
        gmin = _fold_to_dict(list(range(64)),
                             *mesh_keyed_fold(mesh8, h1, h2, vals, "min"))
        gmax = _fold_to_dict(list(range(64)),
                             *mesh_keyed_fold(mesh8, h1, h2, vals, "max"))
        for k in set(keys.tolist()):
            kv = vals[keys == k]
            assert gmin[k] == kv.min()
            assert gmax[k] == kv.max()

    def test_overflow_retry_is_exact(self, mesh8):
        # Skewed keys + tiny capacity: every record hashes to few devices,
        # forcing the capacity-doubling retry loop.
        keys = np.array([1, 2] * 5000)
        vals = np.ones(10000, dtype=np.int64)
        h1, h2 = hashing.hash_keys(keys)
        got = _fold_to_dict([1, 2], *mesh_keyed_fold(
            mesh8, h1, h2, vals, "sum", capacity_factor=0.02))
        assert got == {1: 5000, 2: 5000}

    def test_string_keys_wordcount(self, mesh8):
        words = (reference_text() * 5).split()
        h1, h2 = hashing.hash_keys(words)
        fh1, fh2, fv = mesh_keyed_fold(
            mesh8, h1, h2, np.ones(len(words), dtype=np.int64), "sum")
        want = collections.Counter(words)
        got = _fold_to_dict(list(want), fh1, fh2, fv)
        assert got == dict(want)

    def test_empty(self, mesh8):
        fh1, fh2, fv = mesh_keyed_fold(
            mesh8, np.empty(0, np.uint32), np.empty(0, np.uint32),
            np.empty(0, np.int64), "sum")
        assert len(fh1) == 0

    def test_float_values(self, mesh8):
        keys = np.arange(100) % 10
        vals = np.linspace(0, 1, 100).astype(np.float32)
        h1, h2 = hashing.hash_keys(keys)
        got = _fold_to_dict(list(range(10)),
                            *mesh_keyed_fold(mesh8, h1, h2, vals, "sum"))
        for k in range(10):
            assert abs(got[k] - vals[keys == k].sum()) < 1e-4


class TestGlobalSum:
    def test_int(self, mesh8):
        vals = np.arange(10001, dtype=np.int64)
        assert mesh_global_sum(mesh8, vals) == int(vals.sum())

    def test_float(self, mesh8):
        vals = np.ones(1000, dtype=np.float32) * 0.5
        assert abs(mesh_global_sum(mesh8, vals) - 500.0) < 1e-3
