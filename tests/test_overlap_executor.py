"""Round-6 tentpole coverage: the stage-overlapped codec->fold executor
(exactness, bounded in-flight memory, kill-mid-window retry) and the
spill-lean sorted-run merge planning for external sorts."""

import operator
import os
import re
from collections import Counter

import numpy as np
import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.ops.text import DocFreq, ParseNumbers
from dampr_tpu.runner import MTRunner, _overlap_stream


@pytest.fixture(autouse=True)
def _settings_guard():
    saved = (settings.partitions, settings.max_memory_per_stage,
             settings.overlap_windows, settings.sort_runs,
             settings.merge_fanin, settings.job_retries)
    settings.partitions = 8
    yield
    (settings.partitions, settings.max_memory_per_stage,
     settings.overlap_windows, settings.sort_runs,
     settings.merge_fanin, settings.job_retries) = saved


def _write_numbers(tmp_path, n, seed=11):
    rng = np.random.RandomState(seed)
    ks = rng.randint(0, 1 << 48, size=n)
    path = str(tmp_path / "nums.txt")
    with open(path, "w") as f:
        f.write("\n".join(str(k) for k in ks) + "\n")
    return path, ks


def _write_corpus(tmp_path, lines, seed=4):
    words = ["alpha", "beta", "Gamma", "delta", "tok7", "x9", "the"]
    rng = np.random.RandomState(seed)
    path = str(tmp_path / "corpus.txt")
    with open(path, "w") as f:
        for _ in range(lines):
            f.write(" ".join(words[j]
                             for j in rng.randint(0, len(words), 9)) + "\n")
    return path


def _doc_freq_truth(path):
    rx = re.compile(r"[^\w]+")
    want = Counter()
    with open(path) as f:
        for line in f:
            want.update(t for t in set(rx.split(line.lower())) if t)
    return dict(want)


def _run_doc_freq(path, chunk_size=1 << 17):
    docs = Dampr.text(path, chunk_size)
    df = (docs.custom_mapper(DocFreq(mode="word", lower=True,
                                     pair_values=False))
          .fold_values(operator.add))
    runner = MTRunner("overlap-tfidf", df.pmer.graph)
    out = runner.run([df.source])
    got = {k: v[1] for k, v in out[0].read()}
    out[0].delete()
    return got, runner


def _run_sort(path, chunk_size=1 << 19):
    pipe = (Dampr.text(path, chunk_size)
            .custom_mapper(ParseNumbers())
            .checkpoint(force=True))
    runner = MTRunner("overlap-sort", pipe.pmer.graph)
    out = runner.run([pipe.source])
    return out[0], runner


class TestOverlapExactness:
    def test_tfidf_overlap_matches_serial(self, tmp_path):
        path = _write_corpus(tmp_path, 12000)
        want = _doc_freq_truth(path)
        results = {}
        for depth in (0, 3):
            settings.overlap_windows = depth
            got, runner = _run_doc_freq(path)
            assert runner.store.overlap_bytes == 0
            results[depth] = got
            runner.store.cleanup()
        assert results[0] == want
        assert results[3] == results[0]

    def test_sort_overlap_matches_serial(self, tmp_path):
        path, ks = _write_numbers(tmp_path, 150000)
        want = sorted(ks.tolist())
        settings.max_memory_per_stage = 1 << 20  # force spilled runs
        for depth in (0, 2):
            settings.overlap_windows = depth
            out, runner = _run_sort(path)
            got = [k for k, _v in out.read()]
            assert got == want, "depth={}".format(depth)
            assert runner.store.overlap_bytes == 0
            out.delete()
            runner.store.cleanup()


class TestOverlapMemory:
    def test_reserve_displaces_resident_blocks(self, tmp_path):
        # The governor invariant: in-flight overlap bytes shrink the
        # residency target, so reserving pushes resident refs to disk
        # instead of raising the ceiling.
        from dampr_tpu.blocks import Block
        from dampr_tpu.storage import RunStore

        store = RunStore("overlap-governor", budget=1 << 20)
        arr = np.arange(40000, dtype=np.int64)
        refs = [store.register(Block(arr.copy(), arr.copy()))]
        assert refs[0].resident
        store.reserve_overlap(1 << 20)  # whole budget in-flight
        store.drain_writes()  # spill writes are asynchronous now
        assert not refs[0].resident, "resident ref not displaced"
        assert refs[0].path is not None
        assert store.spill_count >= 1
        store.release_overlap(1 << 20)
        assert store.overlap_bytes == 0
        assert store.overlap_peak_bytes == 1 << 20
        store.cleanup()

    def test_in_flight_bytes_bounded_by_depth(self, tmp_path):
        # Track the overlap high-water mark during a real run: it must be
        # bounded by (depth + 2) windows' worth per concurrent job — queue
        # slots plus the producer's in-hand block plus the one being
        # folded — never the whole codec output.
        path, ks = _write_numbers(tmp_path, 200000)
        depth = 2
        settings.overlap_windows = depth
        out, runner = _run_sort(path, chunk_size=1 << 18)
        total_out = sum(r.total_bytes for r in out.pset.all_refs())
        peak = runner.store.overlap_peak_bytes
        assert peak > 0, "overlap executor never engaged"
        # per-chunk codec output is ~chunk_size * 1.7 (two int64 lanes for
        # ~11-byte text records); bound with slack for worker concurrency
        per_block = int((1 << 18) * 2)
        assert peak <= (depth + 2) * settings.max_processes * per_block
        assert peak < total_out or total_out <= (depth + 2) * per_block
        assert runner.store.overlap_bytes == 0
        out.delete()
        runner.store.cleanup()


class _FlakyParse(ParseNumbers):
    """ParseNumbers whose codec dies mid-stream on its first invocation:
    the first window block comes out, then the scan raises — simulating a
    killed window inside an overlapped job."""

    attempts = []  # class-level: survives the per-job _clone_op deepcopy

    def window_sink(self):
        inner = ParseNumbers.window_sink(self)

        class _Sink(object):
            def add(_s, win):
                blocks = inner.add(win)
                if not _FlakyParse.attempts:
                    _FlakyParse.attempts.append(1)
                    raise IOError("synthetic codec failure mid-window")
                return blocks

            def finish(_s):
                return inner.finish()

        return _Sink()


class TestOverlapRetry:
    def test_kill_mid_window_retries_without_leaks(self, tmp_path):
        path, ks = _write_numbers(tmp_path, 60000)
        settings.overlap_windows = 2
        settings.job_retries = 1
        _FlakyParse.attempts = []
        pipe = (Dampr.text(path, chunk_size=1 << 18)
                .custom_mapper(_FlakyParse())
                .checkpoint(force=True))
        runner = MTRunner("overlap-retry", pipe.pmer.graph)
        out = runner.run([pipe.source])
        assert _FlakyParse.attempts, "failure never injected"
        got = [k for k, _v in out[0].read()]
        assert got == sorted(ks.tolist())
        # the killed window's reservations and refs were rolled back
        assert runner.store.overlap_bytes == 0
        out[0].delete()
        runner.store.cleanup()

    def test_consumer_abandonment_drains_reservations(self):
        # Unit-level: a consumer that stops mid-stream (exception in the
        # fold) must stop the producer and drain every reservation.
        from dampr_tpu.blocks import Block
        from dampr_tpu.storage import RunStore

        store = RunStore("overlap-drain", budget=1 << 22)
        settings.overlap_windows = 2

        def codec():
            for i in range(50):
                arr = np.arange(1000, dtype=np.int64)
                yield Block(arr, arr.copy())

        with pytest.raises(RuntimeError):
            for i, blk in enumerate(_overlap_stream(codec(), store)):
                if i == 3:
                    raise RuntimeError("fold died")
        assert store.overlap_bytes == 0
        store.cleanup()


class TestSortedRunPlanning:
    def test_direct_feed_under_fanin(self, tmp_path):
        # Fan-in fits: zero merge generations — the read feeds straight
        # from first-level runs and nothing is re-spilled.
        path, ks = _write_numbers(tmp_path, 120000)
        settings.max_memory_per_stage = 64 * 1024 * 1024
        out, runner = _run_sort(path, chunk_size=1 << 18)
        assert out.pset.key_sorted_runs
        assert runner.store.merge_gens == 0
        assert [k for k, _v in out.read()] == sorted(ks.tolist())
        out.delete()
        runner.store.cleanup()

    def test_merge_generations_past_fanin(self, tmp_path):
        path, ks = _write_numbers(tmp_path, 150000)
        settings.merge_fanin = 2
        out, runner = _run_sort(path, chunk_size=1 << 17)
        assert out.pset.key_sorted_runs
        assert runner.store.merge_gens >= 1
        assert len(out.pset.parts.get(0, [])) <= 2
        assert [k for k, _v in out.read()] == sorted(ks.tolist())
        out.delete()
        runner.store.cleanup()

    def test_object_keys_fall_back_to_hash_fanout(self):
        # String keys can't register as numeric sorted runs: jobs fall
        # back to hash fan-out and the pset must not claim the invariant.
        # (sort_by rekeys each record by the sort key, so string values
        # become object-dtype keys.)
        items = ["b", "a", "c", "aa", "z"] * 50
        pipe = Dampr.memory(items).sort_by(lambda v: v)
        runner = MTRunner("runs-fallback", pipe.pmer.graph)
        out = runner.run([pipe.source])
        assert not out[0].pset.key_sorted_runs
        got = [v for _k, v in out[0].read()]
        assert got == sorted(items)
        out[0].delete()
        runner.store.cleanup()

    def test_nan_keys_fall_back_to_hash_fanout(self):
        # NaN float keys have no total order: a NaN-tailed run would
        # poison the k-way merge's bound comparisons, so jobs decline
        # sorted-run registration and take the hash fan-out path.
        items = [3.5, float("nan"), 1.25, 2.0, float("nan"), 0.5] * 40
        pipe = Dampr.memory(items).sort_by(lambda v: v)
        runner = MTRunner("runs-nan", pipe.pmer.graph)
        out = runner.run([pipe.source])
        assert not out[0].pset.key_sorted_runs
        got = [v for _k, v in out[0].read()]
        assert len(got) == len(items)
        finite = [v for v in got if v == v]
        assert finite == sorted(v for v in items if v == v)
        assert sum(1 for v in got if v != v) == sum(
            1 for v in items if v != v)
        out[0].delete()
        runner.store.cleanup()

    def test_checkpoint_then_reduce_regroups(self, tmp_path):
        # A reduce downstream of a forced checkpoint: run-mode planning
        # sees the reduce THROUGH the identity checkpoint, so the map
        # keeps hash fan-out (no sorted runs), the checkpoint aliases
        # instead of paying a re-routing copy pass, and grouping is
        # global and exact.
        path, ks = _write_numbers(tmp_path, 5000, seed=3)
        small = [int(k) % 97 for k in ks]
        spath = str(tmp_path / "small.txt")
        with open(spath, "w") as f:
            f.write("\n".join(str(v) for v in small) + "\n")

        def keyed_sum(groups):
            for k, vs in groups:
                yield k, sum(v[1] if isinstance(v, tuple) else v
                             for v in vs)

        pipe = (Dampr.text(spath, chunk_size=1 << 14)
                .custom_mapper(ParseNumbers())
                .checkpoint(force=True)
                .partition_reduce(keyed_sum))
        runner = MTRunner("runs-reduce", pipe.pmer.graph)
        out = runner.run([pipe.source])
        # StreamReducer records read back as (k, (k, v)): unwrap the value
        got = {k: v[1] for k, v in out[0].read()}
        want = {}
        for v in small:
            want[v] = want.get(v, 0) + v
        assert got == want
        # The efficient plan: no full re-routing copy pass ran — exactly
        # ONE executed map pass touches the data.  The plan optimizer
        # dissolves the checkpoint into the ParseNumbers stage (the fused
        # stage hash-routes because it feeds the reduce); with the
        # optimizer off the surviving identity checkpoint ALIASES the
        # hash-routed map output (jobs == 0) instead of copying.
        real_maps = [st for st in runner.stats
                     if st.kind == "map" and st.n_jobs > 0]
        assert len(real_maps) == 1, [st.as_dict() for st in runner.stats]
        out[0].delete()
        runner.store.cleanup()


class TestMergeTieBuffering:
    """merge_sorted_streams tie handling: extension windows append
    straight to the output (no re-concat), and a giant tie group stops
    extending once the round's extension budget is spent, so
    low-cardinality runs never go whole-RAM-resident."""

    @staticmethod
    def _windows(keys, vals, width):
        from dampr_tpu.blocks import Block

        return [Block(keys[a:a + width], vals[a:a + width], None, None)
                for a in range(0, len(keys), width)]

    def test_low_cardinality_merge_stays_bounded(self):
        from dampr_tpu.blocks import merge_sorted_streams

        old = settings.max_memory_per_stage
        settings.max_memory_per_stage = 1 << 20  # ext budget floor: 1 MB
        try:
            n = 150_000  # per stream; one key spans ~2.4 MB per stream
            streams, want = [], []
            for s in range(2):
                ks = np.full(n, 7, dtype=np.int64)
                vs = np.arange(n, dtype=np.int64) + s * n
                want.append(vs)
                streams.append(self._windows(ks, vs, 16384))
            out = list(merge_sorted_streams(streams))
            total = sum(len(b) for b in out)
            assert total == 2 * n
            assert all((np.diff(b.keys) >= 0).all() for b in out)
            # The giant tie group must straddle rounds instead of
            # buffering both runs whole: no emitted block may hold
            # everything.
            assert max(len(b) for b in out) < 2 * n
            got = np.sort(np.concatenate([np.asarray(b.values)
                                          for b in out]))
            assert np.array_equal(got, np.sort(np.concatenate(want)))
        finally:
            settings.max_memory_per_stage = old

    def test_tie_heavy_merge_exact_and_ordered(self):
        from dampr_tpu.blocks import merge_sorted_streams

        rng = np.random.RandomState(11)
        streams, allk, allv = [], [], []
        for s in range(4):
            ks = np.sort(rng.randint(0, 10, size=5000).astype(np.int64))
            vs = rng.randint(0, 1 << 30, size=5000).astype(np.int64)
            allk.append(ks)
            allv.append(vs)
            streams.append(self._windows(ks, vs, 257))
        out = list(merge_sorted_streams(streams))
        keys = np.concatenate([np.asarray(b.keys) for b in out])
        assert (np.diff(keys) >= 0).all()
        assert np.array_equal(np.sort(keys), np.sort(np.concatenate(allk)))
        got = sorted(zip(keys.tolist(),
                         np.concatenate([np.asarray(b.values)
                                         for b in out]).tolist()))
        want = sorted(zip(np.concatenate(allk).tolist(),
                          np.concatenate(allv).tolist()))
        assert got == want


@pytest.mark.slow
class TestOverlap128MBTier:
    def test_tfidf_exactness_at_tier(self, tmp_path):
        from dampr_tpu.bench_tfidf import make_corpus

        corpus = str(tmp_path / "corpus_128mb.txt")
        make_corpus(corpus, 128)
        want = _doc_freq_truth(corpus)
        settings.overlap_windows = 2
        got, runner = _run_doc_freq(corpus, chunk_size=1 << 26)
        assert got == want
        assert runner.store.overlap_bytes == 0
        runner.store.cleanup()
