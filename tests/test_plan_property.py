"""Optimizer equivalence property tests (plan-optimizer satellite):
randomized pipelines mixing map / filter / flat_map / map_values /
fold_by / sort_by / join run byte-identical with ``settings.optimize``
on and off, and the pass pipeline is idempotent on every generated
graph."""

import operator
import random

import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.plan import graph_signature, passes


@pytest.fixture(autouse=True)
def optimizer_on():
    old = settings.optimize
    settings.optimize = True
    yield
    settings.optimize = old


def _unary_op(rng, pipe):
    """One random per-record op (int values in, int values out)."""
    roll = rng.randrange(5)
    if roll == 0:
        k = rng.randrange(1, 50)
        return pipe.map(lambda x, k=k: x + k)
    if roll == 1:
        m = rng.randrange(2, 7)
        return pipe.filter(lambda x, m=m: x % m != 0)
    if roll == 2:
        return pipe.flat_map(lambda x: (x, x + 1000000))
    if roll == 3:
        return pipe.sort_by(lambda x: -x)
    return pipe.checkpoint()  # explicit barriers mix into the soup too


def _build(rng, data):
    """A random pipeline over ``data``; returns a runnable handle."""
    pipe = Dampr.memory(data, partitions=rng.choice([4, 13, 50]))
    for _ in range(rng.randrange(1, 5)):
        pipe = _unary_op(rng, pipe)
    shape = rng.randrange(4)
    if shape == 0:
        # associative fold: (key, sum) pairs, then map_values rides on top
        m = rng.randrange(2, 9)
        pipe = (pipe.fold_by(lambda x, m=m: x % m, operator.add)
                .map_values(lambda v: v * 3))
    elif shape == 1:
        # general grouping through a non-associative reduce
        m = rng.randrange(2, 6)
        pipe = (pipe.group_by(lambda x, m=m: x % m)
                .reduce(lambda k, it: sorted(it)[:5]))
    elif shape == 2:
        # branch + join: shared prefix (union dedup), co-partitioned join
        left = pipe.map(lambda x: x * 2)
        right = pipe.map(lambda x: x - 1)
        pipe = (left.join(right)
                .reduce(lambda l, r: (sorted(l), sorted(r))))
    # shape 3: map-only pipeline, read back key-sorted
    return pipe


CASES = list(range(12))


@pytest.mark.parametrize("case", CASES)
def test_optimized_equals_unoptimized(case):
    rng = random.Random(9000 + case)
    data = [rng.randrange(0, 5000) for _ in range(rng.randrange(50, 400))]
    pipe = _build(rng, data)
    settings.optimize = True
    opt = pipe.run()
    got_opt = opt.read()
    opt.delete()
    settings.optimize = False
    unopt = pipe.run()
    got_unopt = unopt.read()
    unopt.delete()
    assert got_opt == got_unopt, (
        "case {} diverged: optimized {} records vs {}".format(
            case, len(got_opt), len(got_unopt)))


@pytest.mark.parametrize("case", CASES)
def test_optimize_is_idempotent(case):
    rng = random.Random(7000 + case)
    data = [rng.randrange(0, 1000) for _ in range(60)]
    pipe = _build(rng, data)
    g1, r1 = passes.optimize(pipe.pmer.graph, [pipe.source])
    g2, r2 = passes.optimize(g1, [pipe.source])
    assert g2 is g1, "optimize(optimize(g)) rewrote an optimized graph"
    assert sum(r2["rules"].values()) == 0
    assert graph_signature(g2) == graph_signature(g1)


def test_multi_output_equivalence():
    """Dampr.run with shared prefixes: both emitters identical across
    optimize on/off (requested outputs are fusion-protected)."""
    def build():
        base = Dampr.memory(list(range(200))).map(lambda x: x + 1)
        a = base.filter(lambda x: x % 2 == 0).fold_by(
            lambda x: x % 5, operator.add)
        b = base.map(lambda x: x * 3)
        return a, b

    a, b = build()
    settings.optimize = True
    ra, rb = Dampr.run(a, b)
    opt = (ra.read(), rb.read())
    settings.optimize = False
    ra2, rb2 = Dampr.run(a, b)
    unopt = (ra2.read(), rb2.read())
    assert opt == unopt
