"""Out-of-core joins: over-budget join partitions stream a hash-ordered merge
join whose results match the materialized key-ordered path exactly."""

import numpy as np
import pytest

from dampr_tpu import Dampr, settings


@pytest.fixture(autouse=True)
def tight(tmp_path):
    old = (settings.partitions, settings.streaming_reduce_threshold,
           settings.scratch_root, settings.max_memory_per_stage)
    settings.partitions = 8
    settings.scratch_root = str(tmp_path / "scratch")
    yield
    (settings.partitions, settings.streaming_reduce_threshold,
     settings.scratch_root, settings.max_memory_per_stage) = old


def _both_paths(build):
    settings.streaming_reduce_threshold = None  # default: materialized
    want = build().read()
    settings.streaming_reduce_threshold = 1  # force streaming
    got = build().read()
    return want, got


class TestStreamingJoin:
    def test_inner_join_matches(self):
        rng = np.random.RandomState(0)
        lk = rng.randint(0, 200, size=3000).tolist()
        rk = rng.randint(100, 300, size=3000).tolist()

        def build():
            left = Dampr.memory([(k, "l%d" % i) for i, k in enumerate(lk)]) \
                .group_by(lambda x: x[0], lambda x: x[1])
            right = Dampr.memory([(k, "r%d" % i) for i, k in enumerate(rk)]) \
                .group_by(lambda x: x[0], lambda x: x[1])
            return left.join(right).reduce(
                lambda l, r: (sorted(l), sorted(r)))

        want, got = _both_paths(build)
        assert sorted(want) == sorted(got)
        assert len(got) == len(set(lk) & set(rk))

    def test_inner_join_many_matches(self):
        def build():
            left = Dampr.memory([("a", 1), ("a", 2), ("b", 3)]).group_by(
                lambda x: x[0], lambda x: x[1])
            right = Dampr.memory([("a", 9), ("c", 4)]).group_by(
                lambda x: x[0], lambda x: x[1])
            return left.join(right).reduce(
                lambda l, r: sorted(l) + sorted(r), many=True)

        want, got = _both_paths(build)
        assert sorted(want) == sorted(got)

    def test_left_join_matches(self):
        rng = np.random.RandomState(1)
        lk = rng.randint(0, 100, size=2000).tolist()
        rk = rng.randint(50, 150, size=500).tolist()

        def build():
            left = Dampr.memory(lk).group_by(lambda x: x)
            right = Dampr.memory(rk).group_by(lambda x: x)
            return left.join(right).left_reduce(
                lambda l, r: (len(list(l)), len(list(r))))

        want, got = _both_paths(build)
        assert sorted(want) == sorted(got)
        assert len(got) == len(set(lk))

    def test_outer_join_matches(self):
        def build():
            left = Dampr.memory(list(range(0, 60))).group_by(lambda x: x % 17)
            right = Dampr.memory(list(range(40, 120))).group_by(
                lambda x: x % 23)
            return left.join(right).outer_reduce(
                lambda l, r: (sorted(l), sorted(r)))

        want, got = _both_paths(build)
        assert sorted(want, key=str) == sorted(got, key=str)

    def test_forced_hash_collision_joins_exactly(self):
        from dampr_tpu.base import (KeyedInnerJoin, StreamingGroupedView,
                                    streaming_merge_join)
        from dampr_tpu.blocks import Block
        from dampr_tpu.storage import RunStore

        store = RunStore("collide-join", budget=1 << 30)
        h = np.full(4, 5, dtype=np.uint32)
        lblk = Block(np.array(["a", "b", "a", "b"], dtype=object),
                     np.arange(4), h.copy(), h.copy())
        rblk = Block(np.array(["b", "c"], dtype=object),
                     np.array([10, 20]), h[:2].copy(), h[:2].copy())
        lv = StreamingGroupedView([store.register(lblk)])
        rv = StreamingGroupedView([store.register(rblk)])
        red = KeyedInnerJoin(lambda k, l, r: (sorted(l), sorted(r)))
        out = dict(v for _k, v in streaming_merge_join(lv, rv, red))
        assert out == {"b": ([1, 3], [10])}
