"""Fleet observability (dampr_tpu.obs.fleet / serve): clock-aligned
cross-rank trace merging, skew math, rank-tagged artifacts, the live
metrics endpoint, and the doctor's fleet verdicts — all host-side (no
processes spawned; the 2-process pins live in test_fleet_mp.py)."""

import importlib.util
import json
import os
import random
import urllib.request

import pytest

from dampr_tpu import settings
from dampr_tpu.obs import (critpath, doctor, export, fleet, flightrec,
                           history, metrics as obs_metrics, promtext,
                           serve, trace)
from dampr_tpu.parallel import mesh

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


validate_trace = _load_tool("validate_trace")

with open(os.path.join(ROOT, "docs", "trace_schema.json")) as _f:
    TRACE_SCHEMA = json.load(_f)
with open(os.path.join(ROOT, "docs", "doctor_schema.json")) as _f:
    DOCTOR_SCHEMA = json.load(_f)


@pytest.fixture
def scratch(tmp_path, monkeypatch):
    monkeypatch.setattr(settings, "scratch_root", str(tmp_path / "scratch"))
    monkeypatch.setattr(settings, "trace_dir", None)
    return tmp_path


def _set_rank(monkeypatch, rank, num, barrier_perf=None):
    """Pin the process identity + clock handshake the artifact writers
    read (the production path, not a parallel test-only one)."""
    monkeypatch.setattr(mesh, "rank_info", lambda: (rank, num))
    monkeypatch.setattr(
        mesh, "clock_sync",
        None if barrier_perf is None else {
            "barrier_perf": barrier_perf,
            "barrier_wall": 1000.0 + barrier_perf,
            "method": "test",
        })


def _write_rank_artifacts(monkeypatch, run, rank, num, events,
                          epoch=0.0, barrier=None, wall_start=1000.0,
                          stats_extra=None, counters=()):
    """Per-rank trace.json + stats.json through the real export path.

    ``events`` are tracer tuples (cat, name, t0_seconds, dur, lane,
    args) RELATIVE to this rank's epoch; ``epoch``/``barrier`` are this
    rank's monotonic-clock anchors (barrier None = no handshake -> the
    merge must degrade to wall alignment)."""
    _set_rank(monkeypatch, rank, num, barrier_perf=barrier)
    tracer = trace.Tracer(run)
    tracer.epoch = epoch
    tracer.wall_start = wall_start
    tracer.events = list(events)
    for _cat, _name, _t0, _dur, lane, _args in events:
        if lane is not None and lane not in tracer.lane_names:
            tracer.lane_names[lane] = str(lane)
    tdir = export.run_trace_dir(run, rank=rank)
    os.makedirs(tdir, exist_ok=True)
    tpath = export.write_trace(tracer, os.path.join(tdir,
                                                    export.TRACE_FILE))
    if counters:
        with open(tpath) as f:
            doc = json.load(f)
        doc["traceEvents"].extend(counters)
        with open(tpath, "w") as f:
            json.dump(doc, f)
    summary = {
        "schema": export.STATS_SCHEMA,
        "run": run,
        "process": export.process_section(),
        "started_at": wall_start,
        "wall_seconds": 2.0 + rank,
        "stages": [],
        "totals": {"records_out": 100 * (rank + 1),
                   "bytes_out": 1000 * (rank + 1),
                   "spill_bytes": 10 * rank},
        "trace_file": tpath,
    }
    if stats_extra:
        summary.update(stats_extra)
    spath = os.path.join(tdir, export.STATS_FILE)
    summary["stats_file"] = spath
    export.write_stats(summary, spath)
    return tdir


def _span(cat, name, t0, dur, lane="L"):
    return (cat, name, t0, dur, lane, None)


class TestRankArtifacts:
    def test_rank_dirs_layout(self, scratch, monkeypatch):
        """Rank 0 keeps the legacy path; rank k nests under rank<k>/."""
        d0 = _write_rank_artifacts(monkeypatch, "lay", 0, 2,
                                   [_span("stage", "s0:map", 0.0, 1.0)])
        d1 = _write_rank_artifacts(monkeypatch, "lay", 1, 2,
                                   [_span("stage", "s0:map", 0.0, 1.0)])
        assert d0.endswith(os.path.join("lay", "trace"))
        assert d1.endswith(os.path.join("lay", "trace", "rank1"))
        assert fleet.rank_dirs("lay") == {0: d0, 1: d1}

    def test_artifacts_carry_process_identity(self, scratch, monkeypatch):
        _write_rank_artifacts(monkeypatch, "ident", 1, 3,
                              [_span("codec", "w", 0.0, 0.5)],
                              epoch=5.0, barrier=4.0)
        d = export.run_trace_dir("ident", rank=1)
        with open(os.path.join(d, export.TRACE_FILE)) as f:
            doc = json.load(f)
        proc = doc["otherData"]["process"]
        assert proc["process_id"] == 1 and proc["num_processes"] == 3
        assert proc["epoch_perf"] == 5.0
        assert proc["clock"]["barrier_perf"] == 4.0
        with open(os.path.join(d, export.STATS_FILE)) as f:
            stats = json.load(f)
        assert stats["process"]["process_id"] == 1

    def test_rank_info_env_fallback(self, monkeypatch):
        """rank_info reads the launcher env without touching jax when
        the process group never initialized."""
        monkeypatch.setattr(mesh, "_initialized", False)
        monkeypatch.setenv("DAMPR_TPU_NUM_PROCESSES", "4")
        monkeypatch.setenv("DAMPR_TPU_PROCESS_ID", "2")
        assert mesh.rank_info() == (2, 4)
        monkeypatch.delenv("DAMPR_TPU_NUM_PROCESSES")
        monkeypatch.delenv("DAMPR_TPU_PROCESS_ID")
        assert mesh.rank_info() == (0, 1)


class TestClockAlignment:
    def test_merge_ordering_respects_handshake_offsets(self, scratch,
                                                       monkeypatch):
        """Property: events planted at known fleet-common times, viewed
        through ranks whose monotonic clocks drift wildly, come back in
        true order (and with true pairwise gaps) after the merge."""
        rng = random.Random(17)
        for trial in range(10):
            run = "drift{}".format(trial)
            n = rng.choice([2, 3, 4])
            truth = []  # (true_time, rank, name)
            ranks_events = {r: [] for r in range(n)}
            for i in range(24):
                t = rng.uniform(0.0, 8.0)
                r = rng.randrange(n)
                name = "e{}".format(i)
                truth.append((t, name))
                ranks_events[r].append((t, name))
            for r in range(n):
                # This rank's clock: barrier observed at barrier_r on its
                # own monotonic clock, tracer epoch epoch_r.  An event at
                # fleet-common time t (seconds after the barrier) has
                # absolute perf barrier_r + t, i.e. epoch-relative
                # ts = barrier_r + t - epoch_r.
                barrier_r = rng.uniform(0.0, 200.0)
                epoch_r = barrier_r + rng.uniform(-2.0, 2.0)
                events = [
                    _span("codec", name, barrier_r + t - epoch_r, 0.001)
                    for t, name in ranks_events[r]]
                _write_rank_artifacts(monkeypatch, run, r, n, events,
                                      epoch=epoch_r, barrier=barrier_r,
                                      wall_start=1000.0)
            ranks = fleet.load_ranks(run)
            shifts, mode = fleet.clock_shifts(ranks)
            assert mode == "clock"
            merged, _t0 = fleet.merge_traces(ranks, shifts)
            got = [(ev["ts"], ev["name"]) for ev in merged["traceEvents"]
                   if ev.get("ph") == "X"]
            got.sort()
            want = sorted(truth)
            assert [name for _t, name in got] == [n_ for _t, n_ in want]
            # pairwise gaps survive the alignment (µs tolerance)
            for (gt, _), (wt, _) in zip(got, want):
                pass
            base_g = got[0][0]
            base_w = want[0][0]
            for (gt, _), (wt, _) in zip(got, want):
                assert abs((gt - base_g) / 1e6 - (wt - base_w)) < 1e-3

    def test_wall_fallback_when_handshake_missing(self, scratch,
                                                  monkeypatch):
        run = "nowclock"
        _write_rank_artifacts(monkeypatch, run, 0, 2,
                              [_span("codec", "a", 0.0, 0.1)],
                              barrier=None, wall_start=1000.0)
        _write_rank_artifacts(monkeypatch, run, 1, 2,
                              [_span("codec", "b", 0.0, 0.1)],
                              barrier=None, wall_start=1003.5)
        ranks = fleet.load_ranks(run)
        shifts, mode = fleet.clock_shifts(ranks)
        assert mode == "wall"
        assert shifts[0] == 0.0
        assert abs(shifts[1] - 3.5) < 1e-9

    def test_merged_trace_validates_with_counters(self, scratch,
                                                  monkeypatch):
        """Two ranks sampling the SAME counter series must still pass
        the validator's per-series monotonic pin (rank prefixing)."""
        run = "valid"
        for r in range(2):
            counters = [
                {"ph": "C", "name": "store.resident_bytes",
                 "cat": "metric", "pid": 1, "tid": 0,
                 "ts": float(i * 1000), "args": {"value": i * (r + 1)}}
                for i in range(4)]
            _write_rank_artifacts(
                monkeypatch, run, r, 2,
                [_span("exchange", "step:0", 0.1 * (r + 1), 0.5),
                 _span("stage", "s0:map", 0.0, 1.0)],
                epoch=10.0 * r, barrier=10.0 * r - 0.5 * r,
                counters=counters)
        section = fleet.merge_run(run)
        assert section is not None
        mpath = section["merged_trace_file"]
        with open(mpath) as f:
            doc = json.load(f)
        errors = validate_trace.validate(doc, TRACE_SCHEMA,
                                         require_cats=("exchange",))
        assert errors == [], errors
        names = {ev["name"] for ev in doc["traceEvents"]
                 if ev.get("ph") == "C"}
        assert names == {"rank0/store.resident_bytes",
                         "rank1/store.resident_bytes"}
        pids = {ev["pid"] for ev in doc["traceEvents"]}
        assert pids == {1, 2}  # one Perfetto process lane per rank


class TestSkewMath:
    def _ranks_with_steps(self, monkeypatch, run, entries):
        """entries: {rank: [(step, entry_t, dur)]} at fleet-common
        times (epoch==barrier so shifts are zero)."""
        n = len(entries)
        for r, steps in entries.items():
            events = [_span("exchange", "step:{}".format(s), t, d)
                      for s, t, d in steps]
            _write_rank_artifacts(monkeypatch, run, r, n, events,
                                  epoch=0.0, barrier=0.0)
        return fleet.load_ranks(run)

    def test_skew_fractions_in_unit_interval(self, scratch, monkeypatch):
        rng = random.Random(5)
        for trial in range(20):
            run = "skewp{}".format(trial)
            n = rng.choice([2, 3, 4])
            entries = {}
            for r in range(n):
                entries[r] = [(s, rng.uniform(0, 2), rng.uniform(0.001, 1))
                              for s in range(rng.randrange(1, 5))]
            ranks = self._ranks_with_steps(monkeypatch, run, entries)
            shifts, _mode = fleet.clock_shifts(ranks)
            skew = fleet.step_skew(ranks, shifts)
            if skew is None:
                continue
            for st in skew["steps"]:
                assert 0.0 <= st["fraction"] <= 1.0
                assert st["spread_seconds"] >= 0.0
            assert 0.0 <= skew["mean_fraction"] <= 1.0
            assert 0.0 <= skew["max_fraction"] <= 1.0
            assert skew["late_ratio"] >= 1.0 - 1e-9

    def test_straggler_identified(self, scratch, monkeypatch):
        """Rank 1 enters every step 0.8s late on a 1s collective —
        skew must name it and the spread must dominate."""
        entries = {
            0: [(0, 0.0, 1.0), (1, 2.0, 1.0)],
            1: [(0, 0.8, 0.2), (1, 2.8, 0.2)],
        }
        ranks = self._ranks_with_steps(monkeypatch, "strag", entries)
        shifts, _ = fleet.clock_shifts(ranks)
        skew = fleet.step_skew(ranks, shifts)
        assert skew["straggler_rank"] == 1
        assert skew["max_fraction"] >= 0.7
        assert abs(skew["skew_seconds"] - 1.6) < 1e-6
        assert skew["late_ratio"] > 1.5

    def test_single_rank_steps_yield_no_skew(self, scratch, monkeypatch):
        ranks = self._ranks_with_steps(
            monkeypatch, "solo", {0: [(0, 0.0, 1.0)]})
        shifts, _ = fleet.clock_shifts(ranks)
        assert fleet.step_skew(ranks, shifts) is None


class TestFleetSection:
    def test_single_process_run_has_no_fleet_section(self, scratch,
                                                     monkeypatch):
        """Back-compat pin: the legacy single-process layout merges to
        nothing — no fleet section, no fleet/ dir, stats.json untouched
        and still schema-shaped."""
        run = "single"
        _write_rank_artifacts(monkeypatch, run, 0, 1,
                              [_span("stage", "s0:map", 0.0, 1.0)])
        assert fleet.merge_run(run) is None
        base = export.run_trace_dir(run, rank=0)
        assert not os.path.isdir(os.path.join(base, fleet.FLEET_DIR))
        with open(os.path.join(base, export.STATS_FILE)) as f:
            stats = json.load(f)
        assert "fleet" not in stats
        with open(os.path.join(base, export.TRACE_FILE)) as f:
            doc = json.load(f)
        assert validate_trace.validate(doc, TRACE_SCHEMA) == []

    def test_exchange_matrices_from_routes(self, scratch, monkeypatch):
        """Device routes fold into the rank x rank send/recv matrices
        (8 devices over 2 ranks -> devices 0-3 are rank 0)."""
        routes = [[0, 4, 100], [4, 0, 70], [1, 2, 30], [5, 6, 9]]
        extra = {"mesh": {"exchange": {
            "routes": routes,
            "sent_per_device": {"0": 100, "4": 70, "1": 30, "5": 9},
            "received_per_device": {"4": 100, "0": 70, "2": 30, "6": 9},
        }}}
        run = "matrix"
        for r in range(2):
            _write_rank_artifacts(
                monkeypatch, run, r, 2,
                [_span("exchange", "step:0", 0.1 * r, 0.5)],
                epoch=0.0, barrier=0.0, stats_extra=extra)
        section = fleet.merge_run(run)
        ex = section["exchange"]
        assert ex["devices"] == 7  # max device index + 1
        sent = ex["rank_sent_matrix"]
        recv = ex["rank_received_matrix"]
        # rank_of(dev) with 7 devices / 2 ranks: per=3 -> dev 0-2 rank 0,
        # dev 3-6 rank 1 (clamped)
        assert sent[0][1] == 100  # 0 -> 4
        assert sent[1][0] == 70   # 4 -> 0
        assert sent[0][0] == 30   # 1 -> 2 stays intra-rank-0
        assert sent[1][1] == 9    # 5 -> 6 intra-rank-1
        assert recv[1][0] == 100  # transpose: rank 1 received from 0
        assert ex["bytes"] == 209
        # per-rank traffic is sliced to the rank's OWN devices (0-2),
        # never the fleet-global sum: sent 100 (dev 0) + 30 (dev 1)
        pr = {e["rank"]: e for e in section["per_rank"]}
        assert pr[0]["exchange_sent_bytes"] == 130
        assert pr[1]["exchange_sent_bytes"] == 79
        assert pr[0]["exchange_received_bytes"] == 100
        assert pr[1]["exchange_received_bytes"] == 109

    def test_device_count_prefers_process_block(self, scratch,
                                                 monkeypatch):
        """global_devices from the process block beats route-maxima
        inference: devices that moved nothing must not shift the
        device->rank mapping."""
        routes = [[0, 3, 50], [3, 0, 20]]  # devices 4-7 idle
        extra = {"mesh": {"exchange": {
            "routes": routes,
            "sent_per_device": {"0": 50, "3": 20},
            "received_per_device": {"3": 50, "0": 20},
        }}}
        run = "devcount"
        for r in range(2):
            _write_rank_artifacts(
                monkeypatch, run, r, 2,
                [_span("exchange", "step:0", 0.1 * r, 0.5)],
                epoch=0.0, barrier=0.0, stats_extra=extra)
            # stamp the authoritative device shape into the stats
            d = export.run_trace_dir(run, rank=r)
            with open(os.path.join(d, export.STATS_FILE)) as f:
                s = json.load(f)
            s["process"]["global_devices"] = 8
            with open(os.path.join(d, export.STATS_FILE), "w") as f:
                json.dump(s, f)
        section = fleet.merge_run(run)
        ex = section["exchange"]
        # 8 devices / 2 ranks -> per=4: device 3 belongs to rank 0
        assert ex["devices"] == 8
        assert ex["rank_sent_matrix"][0][0] == 70  # both routes intra-rank-0
        assert ex["rank_sent_matrix"][0][1] == 0

    def test_per_rank_totals_and_straggler_lateness(self, scratch,
                                                    monkeypatch):
        run = "totals"
        for r in range(2):
            _write_rank_artifacts(
                monkeypatch, run, r, 2,
                [_span("exchange", "step:0", 0.5 * r, 1.0 - 0.4 * r)],
                epoch=0.0, barrier=0.0)
        section = fleet.merge_run(run)
        assert section["num_processes"] == 2
        assert section["ranks"] == [0, 1]
        assert section["missing_ranks"] == []
        assert section["alignment"] == "clock"
        pr = {e["rank"]: e for e in section["per_rank"]}
        assert pr[0]["records_out"] == 100
        assert pr[1]["records_out"] == 200
        assert pr[1]["mean_entry_lateness_seconds"] == pytest.approx(0.5)
        assert section["skew"]["straggler_rank"] == 1

    def test_missing_rank_recorded(self, scratch, monkeypatch):
        """A rank that never wrote artifacts (killed) shows up in
        missing_ranks instead of blocking the merge."""
        run = "short"
        _write_rank_artifacts(
            monkeypatch, run, 0, 3,
            [_span("exchange", "step:0", 0.0, 1.0)],
            epoch=0.0, barrier=0.0)
        _write_rank_artifacts(
            monkeypatch, run, 1, 3,
            [_span("exchange", "step:0", 0.2, 0.8)],
            epoch=0.0, barrier=0.0)
        section = fleet.merge_run(run, wait_ms=50)
        assert section["missing_ranks"] == [2]

    def test_fleet_injected_into_rank0_stats(self, scratch, monkeypatch):
        run = "inject"
        for r in range(2):
            _write_rank_artifacts(
                monkeypatch, run, r, 2,
                [_span("exchange", "step:0", 0.3 * r, 0.5)],
                epoch=0.0, barrier=0.0)
        fleet.merge_run(run)
        with open(os.path.join(export.run_trace_dir(run, rank=0),
                               export.STATS_FILE)) as f:
            stats = json.load(f)
        assert stats["fleet"]["num_processes"] == 2
        assert os.path.isfile(stats["fleet"]["merged_trace_file"])


class TestCritpathSkew:
    def test_apply_skew_injects_resource_and_can_flip_verdict(self):
        section = {"run": {"verdict": "mesh",
                           "fractions": {"mesh": 0.3},
                           "attributed_fraction": 0.3,
                           "seconds": 10.0}}
        fl = {"skew": {"skew_seconds": 6.0}}
        out = critpath.apply_skew(section, fl, wall=10.0)
        assert out["run"]["fractions"]["skew"] == pytest.approx(0.6)
        assert out["run"]["verdict"] == "skew"
        assert out["run"]["skew_seconds"] == 6.0

    def test_apply_skew_noop_without_skew(self):
        section = {"run": {"verdict": "codec",
                           "fractions": {"codec": 0.8}}}
        out = critpath.apply_skew(section, {}, wall=10.0)
        assert out["run"]["verdict"] == "codec"
        assert "skew" not in out["run"]["fractions"]

    def test_skew_in_priority_taxonomy(self):
        assert "skew" in critpath._PRIORITY


class TestDoctorFleet:
    def _diagnosable_run(self, monkeypatch, run="doc-fleet",
                         late_ratio=1.8):
        for r in range(2):
            dur = 0.2 if r else 1.0
            events = [_span("exchange", "step:{}".format(s),
                            2.0 * s + (0.8 if r else 0.0), dur)
                      for s in range(3)]
            events.append(_span("stage", "s0:reduce", 0.0, 6.0))
            extra = {"wall_seconds": 6.0,
                     "critpath": {"source": "spans", "stages": [],
                                  "run": {"verdict": "codec",
                                          "fractions": {"codec": 0.5}}}}
            _write_rank_artifacts(monkeypatch, run, r, 2, events,
                                  epoch=0.0, barrier=0.0,
                                  stats_extra=extra)
        fleet.merge_run(run)
        return export.run_trace_dir(run, rank=0)

    def test_doctor_names_straggler_with_real_knob(self, scratch,
                                                   monkeypatch):
        rundir = self._diagnosable_run(monkeypatch)
        report = doctor.diagnose(rundir)
        assert report["fleet"]["straggler_rank"] == 1
        skew_findings = [f for f in report["findings"]
                         if f["bottleneck"] == "skew"]
        assert skew_findings, report["findings"]
        f = skew_findings[0]
        assert "rank 1" in f["evidence"]
        assert f["suggestions"], "skew finding must map to knobs"
        for s in f["suggestions"]:
            assert hasattr(settings, s["setting"])

    def test_doctor_report_schema_valid_with_fleet(self, scratch,
                                                   monkeypatch):
        rundir = self._diagnosable_run(monkeypatch)
        report = doctor.diagnose(rundir)
        validate_doctor = _load_tool("validate_doctor")
        errors = validate_doctor.validate(report, DOCTOR_SCHEMA)
        assert errors == [], errors

    def test_doctor_human_rendering_mentions_fleet(self, scratch,
                                                   monkeypatch):
        rundir = self._diagnosable_run(monkeypatch)
        out = doctor.format_report(doctor.diagnose(rundir))
        assert "straggler: rank 1" in out

    def test_single_process_report_has_no_fleet(self, scratch,
                                                monkeypatch):
        run = "doc-solo"
        _write_rank_artifacts(
            monkeypatch, run, 0, 1,
            [_span("stage", "s0:map", 0.0, 1.0)],
            stats_extra={"critpath": {"source": "spans", "stages": [],
                                      "run": {"verdict": "codec",
                                              "fractions": {}}}})
        report = doctor.diagnose(export.run_trace_dir(run, rank=0))
        assert "fleet" not in report


class TestHistoryRankDiscipline:
    def _summary(self, rank, num):
        return {
            "run": "hist-run",
            "process": {"process_id": rank, "num_processes": num},
            "wall_seconds": 1.0,
            "started_at": 1.0,
            "n_partitions": 4,
            "stages": [{"stage": 0, "kind": "map", "jobs": 1,
                        "records_in": 10, "records_out": 10,
                        "bytes_in": 100, "bytes_out": 100,
                        "spill_bytes": 0, "seconds": 0.5}],
            "totals": {"records_out": 10, "bytes_out": 100},
            "plan": {"stage_shapes": [{"shape": "map"}]},
        }

    def test_nonzero_rank_records_are_tagged_and_excluded(self, scratch):
        rec0 = history.compact_record(self._summary(0, 2))
        rec1 = history.compact_record(self._summary(1, 2))
        assert "rank" not in rec0
        assert rec1["rank"] == 1
        shapes = [{"shape": "map"}]
        assert history.matching([rec0, rec1], shapes) == [rec0]
        synth = history.synthesize(history.matching([rec0, rec1], shapes))
        assert synth["history_entries"] == 1

    def test_corpus_append_roundtrip_keeps_rank(self, scratch):
        path0 = history.append(self._summary(0, 2))
        path1 = history.append(self._summary(1, 2))
        assert path0 == path1
        recs = history.load("hist-run")
        assert len(recs) == 2
        tagged = [r for r in recs if r.get("rank")]
        assert len(tagged) == 1 and tagged[0]["rank"] == 1
        assert len(history.matching(recs, [{"shape": "map"}])) == 1


class TestCrashdumpRankAttribution:
    def test_crashdump_filename_per_rank(self):
        assert flightrec.crashdump_filename(0) == "crashdump.json"
        assert flightrec.crashdump_filename(2) == "crashdump.rank2.json"

    def test_flush_lands_in_rank_dir_and_is_discoverable(self, scratch,
                                                         monkeypatch):
        _set_rank(monkeypatch, 1, 2)
        rec = flightrec.FlightRecorder("crash-run", 16)
        rec.record_span("codec", "w", 0.0, 0.1, 1, "lane", None)
        path = rec.flush("test-kill")
        assert path.endswith(os.path.join("rank1", "crashdump.rank1.json"))
        with open(path) as f:
            doc = json.load(f)
        assert doc["otherData"]["process"]["process_id"] == 1
        assert validate_trace.validate(doc, TRACE_SCHEMA) == []
        # rank 0's legacy dump coexists; the scan finds both
        _set_rank(monkeypatch, 0, 2)
        rec0 = flightrec.FlightRecorder("crash-run", 16)
        rec0.record_span("codec", "w", 0.0, 0.1, 1, "lane", None)
        path0 = rec0.flush("test-kill")
        dumps = flightrec.locate_all_crashdumps(
            export.run_trace_dir("crash-run", rank=0))
        assert path0 in dumps and path in dumps
        assert flightrec.locate_crashdump(
            export.run_trace_dir("crash-run", rank=0)) is not None


class TestPromtextRankLabels:
    def test_multiprocess_summary_gets_rank_label(self):
        out = promtext.render_summary({
            "run": "r", "process": {"process_id": 1, "num_processes": 2},
            "metrics": {"counters": {"store.records": 5}}})
        assert 'rank="1"' in out
        assert 'run="r"' in out

    def test_single_process_summary_stays_unlabeled(self):
        out = promtext.render_summary({
            "run": "r", "process": {"process_id": 0, "num_processes": 1},
            "metrics": {"counters": {"store.records": 5}}})
        assert "rank=" not in out


class TestServeEndpoint:
    def _get(self, port, path):
        with urllib.request.urlopen(
                "http://127.0.0.1:{}{}".format(port, path),
                timeout=5) as resp:
            return resp.status, resp.headers, resp.read().decode("utf-8")

    def test_metrics_and_healthz_from_live_run(self, monkeypatch):
        _set_rank(monkeypatch, 0, 1)
        reg = obs_metrics.Metrics("serve-run")
        reg.counter_add("store.records", 42)
        obs_metrics.start(reg)
        srv = serve.MetricsServer(0, run_name="serve-run").start()
        try:
            status, headers, body = self._get(srv.port, "/metrics")
            assert status == 200
            assert headers["Content-Type"].startswith(
                "text/plain; version=0.0.4")
            assert 'rank="0"' in body
            assert "dampr_tpu_store_records_total" in body
            status, headers, body = self._get(srv.port, "/healthz")
            assert status == 200
            health = json.loads(body)
            assert health["status"] == "ok"
            assert health["run"] == "serve-run"
            assert health["metrics_live"] is True
        finally:
            srv.stop()
            obs_metrics.stop(reg)

    def test_empty_exposition_without_registry(self, monkeypatch):
        _set_rank(monkeypatch, 1, 2)
        srv = serve.MetricsServer(0).start()
        try:
            status, headers, body = self._get(srv.port, "/metrics")
            assert status == 200 and body == ""
            status, _h, body = self._get(srv.port, "/healthz")
            assert json.loads(body)["metrics_live"] is False
            assert json.loads(body)["process_id"] == 1
        finally:
            srv.stop()

    def test_unknown_path_404s(self, monkeypatch):
        _set_rank(monkeypatch, 0, 1)
        srv = serve.MetricsServer(0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(srv.port, "/nope")
            assert ei.value.code == 404
        finally:
            srv.stop()

    def test_per_rank_port_offset(self, monkeypatch):
        """rank k binds metrics_port + k so co-located ranks never
        collide (checked arithmetically — no real bind on fixed ports
        in tests)."""
        _set_rank(monkeypatch, 2, 4)
        srv = serve.MetricsServer(9300)
        assert srv.base_port == 9300 and srv.rank == 2
        # the offset applies at start(); pin the computation via a
        # throwaway ephemeral-port server instead of binding 9302
        srv0 = serve.MetricsServer(0).start()
        try:
            assert srv0.port > 0
        finally:
            srv0.stop()
