"""Regression tests for hashing/grouping correctness fixes (round-2 advice):

- per-item kind dispatch: a key hashes identically no matter which block it
  lands in (mixed-type blocks must route like homogeneous ones);
- arbitrary-precision int keys don't crash the int64 fast path;
- object-lane hashing is deterministic across processes (no PYTHONHASHSEED
  dependence) — required for spill-reload and multi-host partition routing;
- device segment folds respect collision-repaired group bounds;
- bool value columns round-trip exactly.
"""

import subprocess
import sys

import numpy as np
import pytest

from dampr_tpu.blocks import Block
from dampr_tpu.ops import hashing, segment


def _h(keys):
    h1, h2 = hashing.hash_keys(keys)
    return list(zip(h1.tolist(), h2.tolist()))


class TestPerItemDispatch:
    def test_str_key_same_hash_in_mixed_block(self):
        pure = _h(["x", "y"])
        mixed = _h(["x", 3, "y", (1, 2)])
        assert mixed[0] == pure[0]
        assert mixed[2] == pure[1]

    def test_int_key_same_hash_in_mixed_block(self):
        pure = _h([7, 42])
        mixed = _h([7, "a", 42])
        assert mixed[0] == pure[0]
        assert mixed[2] == pure[1]

    def test_python_equality_canonicalization_in_mixed_block(self):
        # 1 == 1.0 == True must share a hash even inside mixed batches.
        hs = _h([1, 1.0, True, "one"])
        assert hs[0] == hs[1] == hs[2]

    def test_tuple_key_same_hash_alone_and_mixed(self):
        pure = _h([(1, "a")])
        mixed = _h([5, (1, "a"), "z"])
        assert mixed[1] == pure[0]

    def test_ndarray_vs_object_list_float(self):
        arr = np.array([1.5, 2.0, -3.25], dtype=np.float64)
        via_arr = _h(arr)
        via_list = _h([1.5, 2.0, -3.25])
        assert via_arr == via_list

    def test_large_integral_float_consistency(self):
        # 2.0**62 is integral and in int64 range: same hash as the int,
        # in every container type.
        f = 2.0 ** 62
        i = 2 ** 62
        assert _h([f]) == _h([i]) == _h(np.array([f]))[0:1]


class TestBigInts:
    def test_big_int_key_does_not_crash(self):
        blk = Block.from_pairs([(2 ** 100, 1), (1, 2)])
        h1, h2 = blk.hashes()
        assert len(h1) == 2

    def test_equal_big_ints_hash_equal(self):
        assert _h([2 ** 100])[0] == _h([2 ** 100, "pad"])[0]

    def test_float_representable_big_int_matches_float(self):
        # Python: 2**200 == float(2**200) exactly, so they must co-group.
        assert _h([2 ** 200])[0] == _h([float(2 ** 200)])[0]


class TestCrossProcessDeterminism:
    def test_tuple_hash_stable_across_processes(self):
        code = (
            "from dampr_tpu.ops import hashing\n"
            "h1, h2 = hashing.hash_keys([('a', 1, 2.5), frozenset({'x', 3}), None])\n"
            "print(h1.tolist(), h2.tolist())\n"
        )
        outs = set()
        for seed in ("0", "12345"):
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, check=True,
                env={"PYTHONHASHSEED": seed, "PATH": "/usr/bin:/bin",
                     "PYTHONPATH": "/root/repo", "JAX_PLATFORMS": "cpu"},
            )
            outs.add(r.stdout.strip())
        assert len(outs) == 1, outs


class TestCollisionRepairFold:
    def test_device_fold_uses_repaired_bounds(self):
        # Force a full 64-bit hash collision between distinct keys by
        # constructing the block with equal (h1, h2) lanes.
        n_a, n_b = 600, 424  # total > device_min_batch to hit the device branch
        keys = np.array(["aa"] * n_a + ["bb"] * n_b, dtype=object)
        vals = np.concatenate([np.ones(n_a, dtype=np.int64),
                               np.full(n_b, 2, dtype=np.int64)])
        h = np.full(n_a + n_b, 77, dtype=np.uint32)
        blk = Block(keys, vals, h.copy(), h.copy())
        out = segment.fold_block(blk, segment.SUM)
        got = dict(out.iter_pairs())
        assert got == {"aa": n_a, "bb": 2 * n_b}


class TestBoolValues:
    def test_bool_values_round_trip(self):
        blk = Block.from_pairs([("k", True), ("j", False)])
        pairs = dict(blk.iter_pairs())
        assert pairs == {"k": True, "j": False}
        assert pairs["k"] is True

    def test_bool_sum_promotes_like_python(self):
        blk = Block.from_pairs([("k", True), ("k", True), ("j", False)])
        out = dict(segment.fold_block(blk, segment.SUM).iter_pairs())
        assert out == {"k": 2, "j": 0}

    def test_mixed_big_int_and_float_keeps_precision(self):
        big = 2 ** 60 + 1
        blk = Block.from_pairs([("a", big), ("b", 0.5)])
        assert dict(blk.iter_pairs())["a"] == big


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))


class TestNativeBatchHash:
    def test_native_matches_numpy_lanes(self):
        import dampr_tpu.native as nat
        from dampr_tpu.ops import hashing

        assert nat.get_lib() is not None, (
            "native library must build on this rig or the parity "
            "comparison is vacuous")
        keys = (["tok%d" % i for i in range(500)]
                + ["", "a", "é", "ÿ" * 300, "x" * 1025]
                + [b"raw\x00bytes", b""])
        with_native = hashing.hash_keys(list(keys))
        old = nat._lib, nat._tried
        nat._lib, nat._tried = None, True
        try:
            without = hashing.hash_keys(list(keys))
        finally:
            nat._lib, nat._tried = old
        import numpy as np
        np.testing.assert_array_equal(with_native[0], without[0])
        np.testing.assert_array_equal(with_native[1], without[1])

    def test_object_lane_native_matches_numpy(self):
        import dampr_tpu.native as nat
        from dampr_tpu.ops import hashing

        keys = [(i, "k%d" % i) for i in range(200)] + [None, frozenset({1})]
        a = hashing.hash_keys(list(keys))
        old = nat._lib, nat._tried
        nat._lib, nat._tried = None, True
        try:
            b = hashing.hash_keys(list(keys))
        finally:
            nat._lib, nat._tried = old
        import numpy as np
        np.testing.assert_array_equal(a[0], b[0])
        np.testing.assert_array_equal(a[1], b[1])
