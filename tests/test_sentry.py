"""Telemetry store + regression sentry (dampr_tpu.obs.timeseries /
obs.sentry): MAD detection math (zero-MAD fallback, one-sidedness,
thin-baseline silence), knob-pointer integrity, store durability
(append/load/compaction/fold), the dampr-tpu-sentry CLI exit-code
contract, and the doctor's schema-valid `regression` finding class over
a real run trajectory with an injected 30% slowdown.
"""

import importlib.util
import json
import os

import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.obs import doctor, history, sentry, timeseries

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


validate_doctor = _load_tool("validate_doctor")

with open(os.path.join(ROOT, "docs", "doctor_schema.json")) as _f:
    DOCTOR_SCHEMA = json.load(_f)


def _point(i, wall=10.0, fp="feedfacecafebeef", **extra):
    p = {"schema": timeseries.SCHEMA, "run": "synth", "ts": 1000.0 + i,
         "fingerprint": fp, "wall_seconds": wall, "mbps": 100.0 / wall}
    p.update(extra)
    return p


HEALTHY = [_point(i, wall) for i, wall in
           enumerate([10.0, 10.2, 9.9, 10.1, 10.0])]


@pytest.fixture
def scratch(tmp_path):
    old = settings.scratch_root
    settings.scratch_root = str(tmp_path / "scratch")
    yield tmp_path
    settings.scratch_root = old


class TestDetect:
    def test_injected_regression_trips(self):
        pts = HEALTHY + [_point(9, wall=13.0)]  # +30%
        findings = sentry.detect(pts, window=8, threshold=3.5)
        metrics = {f["metric"] for f in findings}
        assert "wall_seconds" in metrics, findings
        f = next(f for f in findings if f["metric"] == "wall_seconds")
        assert f["direction"] == "high" and f["z"] > 3.5
        assert f["run"] == "synth" and f["window"] == 5
        assert f["median"] == pytest.approx(10.0)
        # knob pointer rides along
        assert f["setting"] == "max_memory_per_stage"
        assert f["env"] == "DAMPR_TPU_MEMORY_BUDGET"
        # findings sorted most-severe first
        assert [abs(x["z"]) for x in findings] == sorted(
            (abs(x["z"]) for x in findings), reverse=True)

    def test_healthy_newest_is_silent(self):
        pts = HEALTHY + [_point(9, wall=10.05)]
        assert sentry.detect(pts, window=8, threshold=3.5) == []

    def test_one_sided_faster_never_alarms(self):
        pts = HEALTHY + [_point(9, wall=5.0)]  # way FASTER
        findings = sentry.detect(pts, window=8, threshold=3.5)
        assert all(f["metric"] != "wall_seconds" for f in findings)
        # ... and mbps doubled, which is the GOOD direction too
        assert all(f["metric"] != "mbps" for f in findings)

    def test_zero_mad_fallback(self):
        """Flat baseline: identical newest stays silent, a clearly-new
        value trips via the 5%-of-median scale."""
        flat = [_point(i, wall=10.0) for i in range(5)]
        assert sentry.detect(flat + [_point(9, wall=10.0)],
                             window=8, threshold=3.5) == []
        findings = sentry.detect(flat + [_point(9, wall=13.0)],
                                 window=8, threshold=3.5)
        assert any(f["metric"] == "wall_seconds" for f in findings)
        # all-zero counter baseline: a first nonzero value still trips
        zeros = [_point(i, wall=10.0, retries=0) for i in range(5)]
        findings = sentry.detect(zeros + [_point(9, wall=10.0, retries=4)],
                                 window=8, threshold=3.5)
        assert any(f["metric"] == "retries" for f in findings)

    def test_thin_baseline_stays_silent(self):
        pts = HEALTHY[:2] + [_point(9, wall=13.0)]  # 2 < MIN_BASELINE
        assert sentry.detect(pts, window=8, threshold=3.5) == []

    def test_window_bounds_the_baseline(self):
        old = [_point(i, wall=20.0) for i in range(10)]
        recent = [_point(10 + i, wall=10.0 + 0.1 * i) for i in range(5)]
        findings = sentry.detect(old + recent + [_point(99, wall=13.0)],
                                 window=5, threshold=3.5)
        f = next(f for f in findings if f["metric"] == "wall_seconds")
        assert f["window"] == 5 and f["median"] < 11.0

    def test_metric_knobs_point_at_real_settings(self):
        assert set(sentry.METRIC_KNOBS) == set(timeseries.METRICS)
        for metric, (attr, env, why) in sentry.METRIC_KNOBS.items():
            assert hasattr(settings, attr), (metric, attr)
            assert env.startswith("DAMPR_TPU_"), (metric, env)
            assert why


class TestStore:
    def test_point_from_summary(self):
        summary = {
            "run": "r", "started_at": 1234.5, "wall_seconds": 2.0,
            "totals": {"bytes_out": 8_000_000},
            "stages": [{"spill_bytes": 1000}, {"spill_bytes": 2000}],
            "plan": {"stage_shapes": [{"shape": "scan>map"},
                                      {"shape": "fold"}]},
            "faults": {"retries": 3, "quarantined": 1},
            "device": {"device_fraction": 0.5, "handoff_bytes": 4_000_000},
        }
        p = timeseries.point_from_summary(summary)
        assert p["schema"] == timeseries.SCHEMA and p["run"] == "r"
        assert p["fingerprint"] == history.plan_fingerprint(
            summary["plan"]["stage_shapes"])
        assert p["wall_seconds"] == 2.0
        assert p["mbps"] == pytest.approx(4.0)
        assert p["spill_bytes"] == 3000
        assert p["retries"] == 3 and p["quarantined"] == 1
        assert p["device_fraction"] == 0.5
        assert p["handoff_fraction"] == pytest.approx(0.5)
        # a run with nothing trendable folds to None
        assert timeseries.point_from_summary({"run": "r"}) is None

    def test_point_from_history_skips_rank_tagged(self):
        assert timeseries.point_from_history({"rank": 1, "run": "r"}) \
            is None

    def test_append_load_roundtrip_and_tolerance(self, scratch):
        path = timeseries.append_point(_point(0))
        assert path and os.path.isfile(path)
        with open(path, "a") as f:
            f.write("torn {garbage\n")
            f.write(json.dumps({"schema": "other/1", "run": "synth",
                                "fingerprint": "x"}) + "\n")
        timeseries.append_point(_point(1))
        pts = timeseries.load("synth")
        assert [p["ts"] for p in pts] == [1000.0, 1001.0]

    def test_retention_compaction(self, scratch):
        old = settings.history_entries
        settings.history_entries = 1  # cap = 16
        try:
            for i in range(40):
                timeseries.append_point(_point(i))
            pts = timeseries.load("synth")
            assert len(pts) == 16
            assert pts[-1]["ts"] == 1039.0  # newest survive
        finally:
            settings.history_entries = old

    def test_series_groups_by_fingerprint(self):
        pts = [_point(0), _point(1, fp="other"), _point(2)]
        by_fp = timeseries.series(pts)
        assert set(by_fp) == {"feedfacecafebeef", "other"}
        one = timeseries.series(pts, fingerprint="feedfacecafebeef")
        assert [p["ts"] for p in one] == [1000.0, 1002.0]
        assert timeseries.series(pts, fingerprint="missing") == []


class TestCLI:
    def _write_store(self, points):
        path = timeseries.store_path("synth")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            for p in points:
                f.write(json.dumps(p, sort_keys=True) + "\n")

    def test_strict_trips_on_regression(self, scratch, capsys):
        self._write_store(HEALTHY + [_point(9, wall=13.0)])
        assert sentry.main(["synth", "--strict"]) == 2
        out = capsys.readouterr().out
        assert "REGRESSION wall_seconds" in out
        assert "run=synth" in out and "knob:" in out

    def test_warn_only_exits_zero(self, scratch, capsys):
        self._write_store(HEALTHY + [_point(9, wall=13.0)])
        assert sentry.main(["synth"]) == 0
        assert "REGRESSION" in capsys.readouterr().out

    def test_healthy_exits_zero_even_strict(self, scratch, capsys):
        self._write_store(HEALTHY + [_point(9, wall=10.05)])
        assert sentry.main(["synth", "--strict"]) == 0
        assert "no regression" in capsys.readouterr().out

    def test_missing_run_exits_one(self, scratch, capsys):
        assert sentry.main(["nonesuch", "--strict"]) == 1
        assert "no telemetry" in capsys.readouterr().out

    def test_json_output(self, scratch, capsys):
        self._write_store(HEALTHY + [_point(9, wall=13.0)])
        assert sentry.main(["synth", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["run"] == "synth" and doc["points"] == 6
        assert any(f["metric"] == "wall_seconds"
                   for f in doc["findings"])


class TestEndToEnd:
    @pytest.fixture
    def traced(self, tmp_path):
        old = (settings.trace, settings.trace_dir, settings.scratch_root,
               settings.sentry_window)
        settings.trace = True
        settings.trace_dir = str(tmp_path / "traces")
        settings.scratch_root = str(tmp_path / "scratch")
        settings.sentry_window = 8
        yield tmp_path
        (settings.trace, settings.trace_dir, settings.scratch_root,
         settings.sentry_window) = old

    def _run_once(self):
        em = (Dampr.memory([(i % 13, i) for i in range(6000)])
              .group_by(lambda kv: kv[0])
              .reduce(lambda k, vs: sum(v[1] for v in vs))
              .run("sentry-e2e"))
        stats = em.stats()
        em.delete()
        return stats

    def test_trajectory_and_doctor_regression_finding(self, traced):
        """Five same-fingerprint runs build the baseline, an injected
        30%-slower point must produce a schema-valid doctor
        `regression` finding; the healthy trajectory stays clean."""
        for _ in range(5):
            stats = self._run_once()
        pts = timeseries.load("sentry-e2e")
        assert len(pts) >= 5, "runner did not feed the telemetry store"
        assert len({p["fingerprint"] for p in pts}) == 1
        # healthy trajectory: no findings (runs are near-identical)
        assert sentry.check_run("sentry-e2e") == []

        base = [p["wall_seconds"] for p in pts]
        bad = dict(pts[-1], ts=(pts[-1]["ts"] or 0) + 1,
                   wall_seconds=max(base) * 1.3 + 5.0)
        timeseries.append_point(bad)
        findings = sentry.check_run("sentry-e2e")
        assert any(f["metric"] == "wall_seconds" for f in findings)

        report = doctor.diagnose(
            os.path.join(settings.trace_dir, "sentry-e2e", "trace"))
        regress = [f for f in report["findings"]
                   if f.get("bottleneck") == "regression"]
        assert regress, report["findings"]
        f = regress[0]
        assert "wall_seconds" in f["evidence"]
        assert f["severity"] in ("high", "medium")
        assert f["suggestions"], f
        sec = report.get("sentry")
        assert sec and sec["findings"] and sec["window"] == 8, sec
        problems = validate_doctor.validate(report, DOCTOR_SCHEMA)
        assert not problems, problems

    def test_check_run_folds_from_history(self, traced):
        """A corpus that predates the telemetry store gets rebuilt from
        history.jsonl on first check."""
        for _ in range(4):
            self._run_once()
        store = timeseries.store_path("sentry-e2e")
        os.remove(store)
        assert sentry.check_run("sentry-e2e") == []  # fold, then silent
        assert os.path.isfile(store), "check_run did not fold history"
        assert len(timeseries.load("sentry-e2e")) >= 4
