"""HBM residency tier (SURVEY §2 item 6 / §7 sketch 1): numeric value
lanes of reduce-feeding map outputs stay device-resident between map and
reduce; device->host offload is the first spill step, disk the second.

On the test rig "device" is the 8-way virtual CPU backend — the tier
mechanics (budget, offload cascade, zero-copy consumption accounting) are
backend-independent; what the counters claim is what the code did.
"""

import numpy as np
import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.runner import MTRunner
from dampr_tpu.storage import BlockRef, RunStore
from dampr_tpu.blocks import Block


@pytest.fixture(autouse=True)
def hbm_enabled():
    old = (settings.partitions, settings.mesh_fold, settings.hbm_budget,
           settings.hbm_min_records)
    settings.partitions = 8
    settings.mesh_fold = "auto"
    settings.hbm_budget = 64 * 1024 * 1024
    settings.hbm_min_records = 1
    yield
    (settings.partitions, settings.mesh_fold, settings.hbm_budget,
     settings.hbm_min_records) = old


def _mkblock(n, key_mod=17, scale=1):
    ks = np.arange(n, dtype=np.int64) % key_mod
    vs = (np.arange(n, dtype=np.int64) % 100) * scale
    return Block(ks, vs)


class TestDeviceRefs:
    def test_roundtrip_exact(self):
        store = RunStore("hbm-rt")
        blk = _mkblock(8192)
        ref = store.register(blk, device=True)
        assert ref.is_device
        got = ref.get()
        assert np.array_equal(got.keys, blk.keys)
        assert np.array_equal(got.values, blk.values)
        assert got.values.dtype == blk.values.dtype
        assert store.d2h_bytes > 0  # the read was a counted fetch
        store.cleanup()

    def test_host_budget_charges_metadata_only(self):
        store = RunStore("hbm-meta")
        blk = _mkblock(8192)
        ref = store.register(blk, device=True)
        # Host side holds keys + two uint32 hash lanes; the value lane is
        # device bytes.
        h1, _ = blk.hashes()
        assert ref.nbytes == blk.keys.nbytes + 2 * h1.nbytes
        assert ref.dev_bytes > 0
        store.cleanup()

    def test_object_values_stay_host(self):
        store = RunStore("hbm-obj")
        vs = np.empty(100, dtype=object)
        vs[:] = [("t", i) for i in range(100)]
        blk = Block(np.arange(100, dtype=np.int64), vs)
        ref = store.register(blk, device=True)
        assert not ref.is_device
        store.cleanup()

    def test_offload_cascade_below_working_set(self):
        # HBM budget below the working set: oldest device refs offload to
        # host; host budget below that: cascade to disk.  Data stays exact.
        old_hbm = settings.hbm_budget
        settings.hbm_budget = 1 << 16  # 64 KB: far below working set
        try:
            store = RunStore("hbm-cascade", budget=1 << 17)
            blocks = [_mkblock(8192, key_mod=50 + i) for i in range(8)]
            refs = [store.register(b, device=True) for b in blocks]
            store.drain_writes()  # spill writes are asynchronous now
            assert store.hbm_offloads > 0, "nothing offloaded"
            assert store.spill_count > 0, "host pressure never hit disk"
            for b, r in zip(blocks, refs):
                got = r.get()
                assert np.array_equal(got.keys, b.keys)
                assert np.array_equal(got.values, b.values)
            store.cleanup()
        finally:
            settings.hbm_budget = old_hbm


class TestBoundaryZeroCopy:
    def test_fold_consumes_device_refs_without_host_copy(self):
        # TF-IDF-shaped aggregation: map -> count fold.  The reduce must
        # consume the map outputs' value lanes on device: d2h_bytes == 0
        # (the only fetched data is the final distinct-key result, which
        # _emit_keyed_fold materializes from the fold output, not from the
        # map-output blocks).
        pipe = (Dampr.memory(list(range(20000)), partitions=8)
                .count(lambda x: x % 13))
        pipe = pipe.checkpoint() if pipe.agg else pipe
        runner = MTRunner("hbm-boundary", pipe.pmer.graph)
        out = runner.run([pipe.source])
        got = dict(v for _k, v in out[0].read())
        want = {i: len(range(i, 20000, 13)) for i in range(13)}
        assert got == want
        assert runner.store.h2d_bytes > 0, "nothing rode the HBM tier"
        assert runner.mesh_folds >= 1, "fold did not run on device"
        assert runner.store.d2h_bytes == 0, (
            "map->reduce boundary copied %d bytes through host"
            % runner.store.d2h_bytes)

    def test_sum_fold_exact_through_hbm(self):
        data = list(range(30000))
        pipe = (Dampr.memory(data, partitions=8)
                .a_group_by(lambda x: x % 9).sum())
        runner = MTRunner("hbm-sum", pipe.pmer.graph)
        out = runner.run([pipe.source])
        got = dict(v for _k, v in out[0].read())
        want = {k: sum(range(k, 30000, 9)) for k in range(9)}
        assert got == want
        assert runner.store.h2d_bytes > 0

    def test_host_fallback_still_exact_when_tier_disabled(self):
        old = settings.hbm_budget
        settings.hbm_budget = 0
        try:
            pipe = (Dampr.memory(list(range(20000)), partitions=8)
                    .count(lambda x: x % 13))
            pipe = pipe.checkpoint() if pipe.agg else pipe
            runner = MTRunner("hbm-off", pipe.pmer.graph)
            out = runner.run([pipe.source])
            got = dict(v for _k, v in out[0].read())
            assert got == {i: len(range(i, 20000, 13)) for i in range(13)}
            assert runner.store.h2d_bytes == 0
        finally:
            settings.hbm_budget = old


class TestLaneSafety:
    def test_overflowing_values_stay_host(self):
        # Values past the int32 lane (x64 off) must refuse the device tier
        # and still fold exactly on host.
        store = RunStore("hbm-lane")
        big = Block(np.arange(8192, dtype=np.int64),
                    np.full(8192, 2 ** 40, dtype=np.int64))
        ref = store.register(big, device=True)
        import jax

        if not jax.config.jax_enable_x64:
            assert not ref.is_device
        store.cleanup()

    def test_huge_sum_pipeline_exact(self):
        # End-to-end: values whose sum overflows int32 — the engine must
        # deliver the exact total whichever tier/path it picks.
        n = 9000
        pipe = (Dampr.memory([2 ** 30 + i for i in range(n)], partitions=8)
                .a_group_by(lambda x: 0).sum())
        runner = MTRunner("hbm-huge", pipe.pmer.graph)
        out = runner.run([pipe.source])
        got = dict(v for _k, v in out[0].read())
        assert got == {0: sum(2 ** 30 + i for i in range(n))}


class TestIntersections:
    def test_resume_persists_device_refs(self, tmp_path):
        # resume=True must checkpoint HBM-resident stage outputs (their
        # host block is None — persistence goes through get()).
        old_scratch = settings.scratch_root
        settings.scratch_root = str(tmp_path)
        try:
            def keyf(x):
                return x % 7

            pipe = (Dampr.memory(list(range(20000)), partitions=8)
                    .count(keyf))
            pipe = pipe.checkpoint() if pipe.agg else pipe
            runner = MTRunner("hbm-resume", pipe.pmer.graph, resume=True)
            out = runner.run([pipe.source])
            got = dict(v for _k, v in out[0].read())
            assert got == {i: len(range(i, 20000, 7)) for i in range(7)}
            assert runner.store.h2d_bytes > 0
        finally:
            settings.scratch_root = old_scratch

    def test_host_pressure_evicts_device_metadata(self):
        # Device refs' host-side keys+hash metadata must be evictable under
        # host pressure (offload + disk), never a spurious MemoryError.
        old_hbm = settings.hbm_budget
        settings.hbm_budget = 1 << 30  # roomy HBM, tiny host budget
        try:
            store = RunStore("hbm-hostpressure", budget=1 << 14)
            blocks = [_mkblock(4096, key_mod=97 + i) for i in range(10)]
            refs = [store.register(b, device=True) for b in blocks]
            store.drain_writes()  # spill writes are asynchronous now
            # host budget (16 KB) is far below 10 blocks' key+hash bytes
            assert store.spill_count > 0
            for b, r in zip(blocks, refs):
                got = r.get()
                assert np.array_equal(got.keys, b.keys)
                assert np.array_equal(got.values, b.values)
            store.cleanup()
        finally:
            settings.hbm_budget = old_hbm
