"""Runner-level semantics: block-count governor, per-run stats, fail-fast."""

import pytest

from dampr_tpu import Dampr, settings


@pytest.fixture(autouse=True)
def small_partitions():
    old = settings.partitions
    settings.partitions = 4
    yield
    settings.partitions = old


class TestGovernor:
    def test_partition_ref_count_bounded(self):
        # The governor bounds every materialized stage's ref counts
        # (reduce fan-in and read-side file counts alike).
        import numpy as np

        from dampr_tpu.blocks import Block
        from dampr_tpu.runner import MTRunner
        from dampr_tpu.storage import PartitionSet

        old = settings.max_files_per_stage
        settings.max_files_per_stage = 3
        try:
            runner = MTRunner("govern", Dampr.memory([1]).pmer.graph)
            pset = PartitionSet(2)
            for i in range(40):
                blk = Block(np.arange(10, dtype=np.int64) + 10 * i,
                            np.arange(10, dtype=np.int64))
                for pid, sub in blk.split_by_partition(2).items():
                    pset.add(pid, runner.store.register(sub))
            runner._compact_partitions(pset, None, False, feeds_reduce=True)
            assert all(len(refs) <= 3 for refs in pset.parts.values())

            # end-to-end: a REDUNDANT identity checkpoint (input already
            # a materialized PartitionSet) ALIASES instead of copying,
            # and results stay exact
            pipe = (Dampr.memory(list(range(400)), partitions=40)
                    .checkpoint(True)
                    .checkpoint(True))
            r2 = MTRunner("govern2", pipe.pmer.graph)
            out = r2.run([pipe.source])
            assert sorted(v for _k, v in out[0].read()) == list(range(400))
            assert any(s.kind == "map-alias" for s in r2.stats), (
                "identity checkpoint was not aliased")
        finally:
            settings.max_files_per_stage = old

    def test_governor_refolds_combined_stages(self):
        old = settings.max_files_per_stage
        settings.max_files_per_stage = 2
        try:
            out = dict(Dampr.memory(list(range(1000)), partitions=50)
                       .count(lambda x: x % 5).read())
            assert out == {i: 200 for i in range(5)}
        finally:
            settings.max_files_per_stage = old


class TestStats:
    def test_emitter_stats_populated(self):
        em = Dampr.memory([1, 2, 3]).map(lambda x: x + 1).run()
        assert em.stats, "run stats missing"
        kinds = [s["kind"] for s in em.stats]
        assert "map" in kinds
        assert all({"jobs", "records_out", "seconds"} <= set(s)
                   for s in em.stats)

    def test_multi_run_stats(self):
        a, b = Dampr.run(Dampr.memory([1]).map(lambda x: x),
                         Dampr.memory([2]).map(lambda x: x))
        assert a.stats and a.stats == b.stats


class TestFailFast:
    def test_map_exception_propagates(self):
        def boom(x):
            raise RuntimeError("map exploded")

        with pytest.raises(RuntimeError, match="map exploded"):
            Dampr.memory([1, 2, 3]).map(boom).read()

    def test_reduce_exception_propagates(self):
        def boom(k, it):
            raise ValueError("reduce exploded")

        with pytest.raises(ValueError, match="reduce exploded"):
            Dampr.memory([1, 2, 3]).group_by(lambda x: 1).reduce(boom).read()


class TestTinyStageCollapse:
    """The tiny-input collapse must never change results — only job
    granularity.  Chunk-semantic operators (partition_map's StreamMapper,
    even fused inside a ComposedMapper chain) keep per-ref chunks."""

    def _counts_per_chunk(self):
        def per_chunk(it):
            n = sum(1 for _ in it)
            yield 1, n

        return (Dampr.memory(list(range(2000)), partitions=8)
                .checkpoint(force=True)
                .partition_map(per_chunk)
                .map(lambda x: x))

    def test_partition_map_fused_chain_not_collapsed(self):
        from dampr_tpu import settings
        old = settings.small_stage_bytes
        try:
            settings.small_stage_bytes = 0  # collapse off: ground truth
            want = sorted(self._counts_per_chunk().read())
            settings.small_stage_bytes = old  # collapse on (default 4MB)
            got = sorted(self._counts_per_chunk().read())
        finally:
            settings.small_stage_bytes = old
        assert got == want
        assert len(got) > 1  # genuinely per-chunk, not one merged call

    def test_assoc_fold_same_result_with_and_without_collapse(self):
        from dampr_tpu import settings

        def pipe():
            return (Dampr.memory(list(range(300)) * 5, partitions=16)
                    .count(lambda x: x % 97))

        old = settings.small_stage_bytes
        try:
            settings.small_stage_bytes = 0
            want = sorted(v for _k, v in pipe().read())
            settings.small_stage_bytes = old
            got = sorted(v for _k, v in pipe().read())
        finally:
            settings.small_stage_bytes = old
        assert got == want


class TestAliasOwnership:
    def test_requested_input_and_checkpoint_both_readable(self):
        # x and its identity checkpoint y both requested: they must NOT
        # share a PartitionSet (deleting one would empty the other), so
        # the alias fast path must stand down.
        x = Dampr.memory(list(range(50))).map(lambda v: v + 1).checkpoint()
        y = x.checkpoint(True)
        outs = Dampr.run(x, y)
        assert sorted(outs[0].stream()) == list(range(1, 51))
        outs[0].delete()
        assert sorted(outs[1].stream()) == list(range(1, 51))
