"""Static pipeline analysis suite (ISSUE 13): the adversarial UDF
corpus, the pre-flight validator's coded diagnostics, the lint CLI, and
the engine wiring the verdicts drive.

Four pins matter most:

- every adversarial UDF class — impure, nondeterministic, unpicklable,
  non-associative, traceable-numeric — fires its diagnostic with the
  correct evidence, and the shipped examples/benchmarks lint with ZERO
  errors/warnings (the false-positive gate);
- a certified numeric non-text chain executes on the device path with
  the verdict visible in ``explain()``, byte-identical to the
  per-record path (ROADMAP 5a);
- speculation provably declines on a nondeterministic UDF (the
  mitigation controller records why);
- ``DAMPR_TPU_ANALYZE=0`` = byte-identical plans and results.
"""

import functools
import json
import operator
import os
import random
import textwrap
import threading
import time
import uuid

import numpy as np
import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.analyze import (PreflightError, assoc, jaxtrace, lint,
                               pickleprobe, props)
from dampr_tpu.analyze import validate as av
from dampr_tpu.plan import graph_signature, passes

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def analysis_on():
    old = settings.analyze
    settings.analyze = True
    yield
    settings.analyze = old


def _codes(diags):
    return [d.code for d in diags]


# ---------------------------------------------------------------------------
# UDF property classifier (props)
# ---------------------------------------------------------------------------

_COUNTER = {"n": 0}


def _impure_global(x):
    global _G_SINK
    _G_SINK = x
    return x


class TestClassifier:
    def test_local_mutation_is_pure(self):
        """The false-positive guard: building and mutating locals is
        pure in every sense the engine cares about."""
        def f(vals):
            seen = set()
            out = []
            for v in vals:
                if v not in seen:
                    seen.add(v)
                    out.append(v * 2)
            out.sort()
            return out

        v = props.classify_callable(f)
        assert v.pure and v.deterministic, v

    def test_store_global_is_impure(self):
        v = props.classify_callable(_impure_global)
        assert not v.pure
        assert any("global" in e for e in v.impure_evidence)
        assert v.deterministic

    def test_closure_mutator_method_named(self):
        acc = []
        f = lambda x: (acc.append(x), x)[1]  # noqa: E731
        v = props.classify_callable(f)
        assert not v.pure
        assert any("'acc'" in e and "append" in e
                   for e in v.impure_evidence), v.impure_evidence

    def test_module_counter_update_is_impure(self):
        def f(x):
            _COUNTER["n"] += 1
            return x

        v = props.classify_callable(f)
        assert not v.pure, v

    def test_print_open_are_impure(self):
        v = props.classify_callable(lambda x: print(x) or x)
        assert not v.pure and any("print" in e for e in v.impure_evidence)
        v = props.classify_callable(lambda p: open(p).read())
        assert not v.pure and any("open" in e for e in v.impure_evidence)

    def test_os_side_effects_are_impure(self):
        def f(p):
            os.remove(p)
            return p

        v = props.classify_callable(f)
        assert not v.pure
        assert any("os.remove" in e for e in v.impure_evidence)

    def test_attr_write_on_closure_object_impure(self):
        class Box:
            pass

        box = Box()

        def f(x):
            box.last = x
            return x

        v = props.classify_callable(f)
        assert not v.pure
        assert any("'box'" in e for e in v.impure_evidence)

    def test_subscript_write_into_closure_dict_impure(self):
        cache = {}

        def f(x):
            cache[x] = x * 2
            return cache[x]

        v = props.classify_callable(f)
        assert not v.pure
        assert any("'cache'" in e for e in v.impure_evidence)

    def test_nonlocal_value_into_local_container_is_pure(self):
        """Regression: ``d[k] = G`` loads the VALUE before the
        container — the receiver check must look at the container
        position only, or a pure UDF assigning a global/closure value
        into its own local dict flags as impure."""
        def f(v):
            d = {}
            d["k"] = _COUNTER
            return len(d) + v

        cfg = {"scale": 3}

        def g(v):
            out = {}
            out[v] = cfg
            return len(out)

        for fn in (f, g):
            ver = props.classify_callable(fn)
            assert ver.pure, ver.impure_evidence

    def test_self_attr_write_is_exempt(self):
        """Instance state on a method's ``self`` is the per-job-copied
        BlockMapper lifecycle contract, not shared-state impurity."""
        class M:
            def step(self, x):
                self.total = getattr(self, "total", 0) + x
                return self.total

        v = props.classify_callable(M.step)
        assert v.pure, v.impure_evidence

    @pytest.mark.parametrize("f,frag", [
        (lambda x: x + random.random(), "random"),
        (lambda x: x + time.time() * 0, "time.time"),
        (lambda x: (x, uuid.uuid4().hex)[0], "uuid"),
    ])
    def test_nondet_module_reads(self, f, frag):
        v = props.classify_callable(f)
        assert not v.deterministic
        assert any(frag in e for e in v.nondet_evidence), v.nondet_evidence

    def test_datetime_now_nondet(self):
        import datetime

        def f(x):
            return (x, datetime.datetime.now())

        v = props.classify_callable(f)
        assert not v.deterministic, v

    def test_numpy_random_nondet(self):
        def f(x):
            return x + np.random.rand() * 0

        v = props.classify_callable(f)
        assert not v.deterministic
        assert any("numpy.random" in e or "rand" in e
                   for e in v.nondet_evidence)

    def test_closure_rng_instance_nondet(self):
        rng = random.Random()

        def f(x):
            return x + rng.random() * 0

        v = props.classify_callable(f)
        assert not v.deterministic
        assert any("'rng'" in e for e in v.nondet_evidence)

    def test_bound_rng_method_nondet(self):
        v = props.classify_callable(random.Random(7).random)
        assert not v.deterministic

    def test_partial_and_method_unwrap(self):
        acc = []
        f = functools.partial(lambda scale, x: (acc.append(x), x * scale)[1],
                              3)
        v = props.classify_callable(f)
        assert not v.pure

    def test_builtins_are_benign(self):
        for f in (len, str.lower, operator.add, abs):
            v = props.classify_callable(f)
            assert v.pure and v.deterministic, (f, v)

    def test_verdict_cache_returns_fresh_clones(self):
        f = lambda x: x + 1  # noqa: E731
        a = props.classify_callable(f)
        a.name = "renamed"
        a.impure("poisoned")
        b = props.classify_callable(f)
        assert b.pure and b.name != "renamed"


# ---------------------------------------------------------------------------
# Associativity (assoc)
# ---------------------------------------------------------------------------

class TestAssoc:
    def test_recognized_kind_is_yes(self):
        out = assoc.classify_binop(operator.add)
        assert out["assoc"] == "yes" and out["kind"] is not None

    def test_subtraction_proven_non_associative(self):
        out = assoc.classify_binop(lambda a, b: a - b)
        assert out["assoc"] == "no"
        assert "counterexample" in out["evidence"]

    def test_opaque_addlike_is_probably(self):
        out = assoc.classify_binop(lambda a, b: b + a)
        assert out["assoc"] == "probably"

    def test_usertyped_binop_is_unknown(self):
        out = assoc.classify_binop(lambda a, b: a.merge(b))
        assert out["assoc"] == "unknown"

    def test_probe_is_deterministic(self):
        f = lambda a, b: a - b  # noqa: E731
        assert assoc.classify_binop(f) == assoc.classify_binop(f)

    def test_impure_binop_is_never_executed(self):
        """The probe EXECUTES the binop on synthetic operands; a binop
        with detectable side effects must never run under a "static"
        lint — verdict unknown, zero calls."""
        calls = []
        out = assoc.classify_binop(
            lambda a, b: (calls.append((a, b)), a + b)[1])
        assert out["assoc"] == "unknown"
        assert "impure" in out["evidence"]
        assert calls == []


# ---------------------------------------------------------------------------
# Pickle probe (pickleprobe)
# ---------------------------------------------------------------------------

class TestPickleProbe:
    def test_clean_closure_probes_empty(self):
        k = 3
        assert pickleprobe.probe_callable(lambda x: x * k) == []

    def test_lock_closure_names_the_variable(self):
        lock = threading.Lock()
        probs = pickleprobe.probe_callable(lambda x: x if lock else x)
        assert len(probs) == 1
        assert "lock" in probs[0]["variable"]
        assert "pickle" in probs[0]["error"].lower() \
            or "TypeError" in probs[0]["error"]

    def test_partial_kwarg_probed(self):
        bad = functools.partial(lambda x, res=None: x,
                                res=threading.Lock())
        probs = pickleprobe.probe_callable(bad)
        assert any("res" in p["variable"] for p in probs)

    def test_callable_object_attribute_probed(self):
        class Op:
            def __init__(self):
                self.handle = threading.Lock()

            def __call__(self, x):
                return x

        probs = pickleprobe.probe_callable(Op())
        assert any("handle" in p["variable"] for p in probs)


# ---------------------------------------------------------------------------
# Jax-traceability probe (jaxtrace)
# ---------------------------------------------------------------------------

class TestJaxTrace:
    def test_numeric_map_and_filter_certify(self):
        ok, _ = jaxtrace.certify_callable(lambda x: x * 3 + 1, "map")
        assert ok
        ok, _ = jaxtrace.certify_callable(lambda x: x % 2 == 0, "filter")
        assert ok

    def test_data_dependent_branch_rejected(self):
        ok, why = jaxtrace.certify_callable(
            lambda x: x * 2 if x > 0 else -x, "map")
        assert not ok and why

    def test_tuple_and_str_outputs_rejected(self):
        ok, _ = jaxtrace.certify_callable(lambda x: (x, x), "map")
        assert not ok
        ok, _ = jaxtrace.certify_callable(lambda x: str(x), "map")
        assert not ok

    def test_chain_claims_requires_lane_vocabulary(self):
        pipe = Dampr.memory(list(range(10))).flat_map(lambda x: [x, x])
        stage = pipe.pmer.graph.stages[-1]
        spec, why = jaxtrace.chain_claims(stage.mapper)
        assert spec is None and "vocabulary" in why

    def test_chain_claims_rejects_nondet_udf(self):
        pipe = Dampr.memory(list(range(10))).map(
            lambda x: x + random.random() * 0)
        stage = pipe.pmer.graph.stages[-1]
        spec, why = jaxtrace.chain_claims(stage.mapper)
        assert spec is None and "nondeterministic" in why

    def test_chain_program_exactness_with_filter_mask(self):
        pipe = (Dampr.memory(list(range(64)))
                .map(lambda x: x * 3 + 1)
                .filter(lambda x: x % 2 == 0))
        g, _ = passes.optimize(pipe.pmer.graph, [pipe.source])
        stage = [s for s in g.stages if hasattr(s, "mapper")][-1]
        prog = jaxtrace.stage_program(stage)
        assert prog is not None
        ks = list(range(64))
        vs = list(range(64))
        out = prog.run_batch(ks, vs)
        exp = [(k, v * 3 + 1) for k, v in zip(ks, vs)
               if (v * 3 + 1) % 2 == 0]
        assert out is not None
        assert list(zip(out[0], out[1])) == exp

    def test_chain_program_nonnumeric_batch_falls_back(self):
        pipe = Dampr.memory(list(range(8))).map(lambda x: x * 2)
        g, _ = passes.optimize(pipe.pmer.graph, [pipe.source])
        stage = [s for s in g.stages if hasattr(s, "mapper")][-1]
        prog = jaxtrace.stage_program(stage)
        assert prog is not None
        assert prog.run_batch([0, 1], ["a", "b"]) is None
        assert prog.counters["fallback"] >= 1

    def test_zero_divide_batch_falls_back_not_inf(self):
        """numpy turns 1.0/0.0 into inf where per-record Python raises
        ZeroDivisionError; the vectorized host evaluation must fall the
        batch back to the authoritative per-record path, never emit
        the silent inf."""
        pipe = Dampr.memory([1.0, 2.0]).map(lambda v: 1.0 / v)
        g, _ = passes.optimize(pipe.pmer.graph, [pipe.source])
        stage = [s for s in g.stages if hasattr(s, "mapper")][-1]
        prog = jaxtrace.stage_program(stage)
        assert prog is not None
        ks = [0, 1, 2]
        assert prog.run_batch(ks, [4.0, 2.0, 0.0]) is None
        assert prog.counters["fallback"] >= 1
        out = prog.run_batch(ks, [4.0, 2.0, 1.0])
        assert out == (ks, [0.25, 0.5, 1.0])


# ---------------------------------------------------------------------------
# Pre-flight validator: the adversarial corpus end-to-end (PBase.validate)
# ---------------------------------------------------------------------------

class TestValidator:
    def test_non_associative_fold_is_an_error(self):
        pipe = Dampr.memory(list(range(50))).fold_by(
            lambda x: x % 3, lambda a, b: a - b)
        diags = pipe.validate()
        errs = [d for d in diags if d.code == "DTA101"]
        assert len(errs) == 1, _codes(diags)
        assert errs[0].severity == "error"
        assert any("counterexample" in e for e in errs[0].evidence)
        # errors sort first
        assert diags[0].code == "DTA101"

    def test_assume_associative_suppresses(self):
        pipe = Dampr.memory(list(range(50))).fold_by(
            lambda x: x % 3, lambda a, b: a - b, assume_associative=True)
        assert "DTA101" not in _codes(pipe.validate())

    def test_impure_udf_warns_with_evidence(self):
        acc = []
        pipe = Dampr.memory(list(range(50))).map(
            lambda x: (acc.append(x), x)[1])
        diags = [d for d in pipe.validate() if d.code == "DTA201"]
        assert len(diags) == 1
        assert any("'acc'" in e for e in diags[0].evidence)

    def test_nondet_udf_warns(self):
        pipe = Dampr.memory(list(range(50))).map(
            lambda x: x + random.random() * 0)
        diags = [d for d in pipe.validate() if d.code == "DTA301"]
        assert len(diags) == 1
        assert any("random" in e for e in diags[0].evidence)

    def _lock_pipe(self):
        lock = threading.Lock()
        return Dampr.memory(list(range(50))).map(
            lambda x: x if lock else x)

    def test_unpicklable_closure_warns_naming_variable(self):
        diags = [d for d in self._lock_pipe().validate()
                 if d.code == "DTA401"]
        assert len(diags) == 1 and diags[0].severity == "warn"
        assert any("'lock'" in e for e in diags[0].evidence)

    def test_multiprocess_promotes_unpicklable_to_error(self):
        diags = [d for d in self._lock_pipe().validate(num_processes=2)
                 if d.code == "DTA401"]
        assert diags and diags[0].severity == "error"

    def test_resume_flags_volatile_fingerprint(self):
        diags = self._lock_pipe().validate(resume=True)
        assert "DTA402" in _codes(diags)

    def test_probe_false_skips_serialization(self):
        """``validate(probe=False)`` promises the fast bytecode-only
        classification: the pickle probe must not serialize a single
        captured byte (a closure-held broadcast table can be huge)."""
        attempts = []

        class Tattler(object):
            def __reduce__(self):
                attempts.append(1)
                raise TypeError("unpicklable sentinel")

        big = Tattler()
        pipe = Dampr.memory(list(range(50))).map(
            lambda x: x if big else x)
        fast = pipe.validate(probe=False)
        assert "DTA401" not in _codes(fast)
        assert attempts == []
        full = pipe.validate()
        assert "DTA401" in _codes(full)
        assert attempts

    def test_traceable_chain_certified_info(self):
        pipe = (Dampr.memory(list(range(50)))
                .map(lambda x: x * 2)
                .filter(lambda x: x > 5))
        diags = [d for d in pipe.validate() if d.code == "DTA501"]
        assert diags
        assert any("certified" in e for d in diags for e in d.evidence)

    def test_preflight_dispatch_check_names_everything(self):
        pipe = self._lock_pipe()
        with pytest.raises(PreflightError) as ei:
            av.preflight_dispatch_check(pipe.pmer.graph, 2)
        msg = str(ei.value)
        assert "lock" in msg and "ValueMap" in msg and "DTA401" in msg

    def test_preflight_noop_single_process_or_disabled(self):
        pipe = self._lock_pipe()
        av.preflight_dispatch_check(pipe.pmer.graph, 1)
        settings.analyze = False
        av.preflight_dispatch_check(pipe.pmer.graph, 2)

    def test_assume_overrides_suppress_udf_diagnostics(self):
        from dampr_tpu import base

        acc = []

        def f(x):
            acc.append(x)
            return x + random.random() * 0

        pipe = Dampr.memory(list(range(20))).custom_mapper(
            base.ValueMap(f), assume_pure=True,
            assume_deterministic=True)
        codes = _codes(pipe.validate())
        assert "DTA201" not in codes and "DTA301" not in codes


# ---------------------------------------------------------------------------
# Zero false positives over everything we ship + the lint CLI
# ---------------------------------------------------------------------------

SHIPPED = [
    os.path.join(ROOT, "examples", "wc.py"),
    os.path.join(ROOT, "examples", "tf_idf.py"),
    os.path.join(ROOT, "examples", "word_stats.py"),
    os.path.join(ROOT, "examples", "sgd.py"),
    os.path.join(ROOT, "dampr_tpu", "bench_tfidf.py"),
    os.path.join(ROOT, "benchmarks", "sort_bench.py"),
]


class TestLint:
    def test_shipped_pipelines_have_zero_false_positives(self):
        """The acceptance gate: every example and benchmark pipeline
        lints with 0 errors AND 0 warnings (info diagnostics — e.g. a
        probabilistic associativity pass — are fine)."""
        report = lint.run_lint(SHIPPED)
        assert report["exit_code"] == 0, json.dumps(
            report["diagnostics"], indent=2)
        assert report["counts"]["error"] == 0
        assert report["counts"]["warn"] == 0, report["diagnostics"]
        for rec in report["targets"]:
            assert rec["error"] is None and rec["pipelines"], rec

    def test_report_is_schema_valid(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "validate_lint", os.path.join(ROOT, "tools",
                                          "validate_lint.py"))
        vl = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(vl)
        with open(os.path.join(ROOT, "docs", "lint_schema.json")) as f:
            schema = json.load(f)
        report = lint.run_lint([SHIPPED[0]])
        assert vl.validate(report, schema) == []
        # and an erroring report stays schema-valid too
        bad = lint.run_lint([os.path.join(ROOT, "does-not-exist.py")])
        assert bad["exit_code"] == 2
        assert vl.validate(bad, schema) == []

    def _write_module(self, tmp_path, body):
        p = tmp_path / "lintee.py"
        p.write_text(textwrap.dedent(body))
        return str(p)

    def test_cli_exit_codes(self, tmp_path, capsys):
        bad = self._write_module(tmp_path, """
            from dampr_tpu import Dampr

            def lint_pipelines():
                return [("bad", Dampr.memory(list(range(10))).fold_by(
                    lambda x: x % 2, lambda a, b: a - b))]
        """)
        assert lint.main([bad]) == 1
        out = capsys.readouterr().out
        assert "DTA101" in out and "counterexample" in out
        empty = self._write_module(tmp_path, "x = 1\n")
        assert lint.main([empty]) == 2
        clean = self._write_module(tmp_path, """
            from dampr_tpu import Dampr

            def lint_pipelines():
                return [("ok", Dampr.memory(list(range(10)))
                         .map(lambda x: x + 1))]
        """)
        assert lint.main([clean]) == 0
        capsys.readouterr()

    def test_strict_turns_warnings_into_failures(self, tmp_path, capsys):
        warny = self._write_module(tmp_path, """
            import random
            from dampr_tpu import Dampr

            def lint_pipelines():
                return [("nd", Dampr.memory(list(range(10))).map(
                    lambda x: x + random.random() * 0))]
        """)
        assert lint.main([warny]) == 0
        assert lint.main(["--strict", warny]) == 1
        capsys.readouterr()

    def test_json_mode_emits_schema_report(self, tmp_path, capsys):
        clean = self._write_module(tmp_path, """
            from dampr_tpu import Dampr

            def lint_pipelines():
                return [("ok", Dampr.memory(list(range(10)))
                         .map(lambda x: x + 1))]
        """)
        assert lint.main(["--json", clean]) == 0
        report = json.loads(capsys.readouterr().out)
        assert report["schema"] == lint.SCHEMA

    def test_registry_discovery_without_hook(self, tmp_path, capsys):
        """Modules without lint_pipelines(): live-handle discovery finds
        the maximal constructed pipelines."""
        mod = self._write_module(tmp_path, """
            from dampr_tpu import Dampr

            PIPE = (Dampr.memory(list(range(10)))
                    .map(lambda x: x * 2)
                    .filter(lambda x: x > 3))
        """)
        name, diags = lint.lint_target(mod)
        assert name["pipelines"], name
        capsys.readouterr()


# ---------------------------------------------------------------------------
# Engine wiring: fusion, lowering, speculation, and the off-switch
# ---------------------------------------------------------------------------

class TestEngineWiring:
    def test_fusion_declines_across_impure_udf(self):
        acc = []

        def build():
            return (Dampr.memory(list(range(100)))
                    .map(lambda x: (acc.append(x), x)[1])
                    .map(lambda x: x + 1))

        pipe = build()
        g_on, r_on = passes.optimize(pipe.pmer.graph, [pipe.source])
        settings.analyze = False
        g_off, r_off = passes.optimize(pipe.pmer.graph, [pipe.source])
        settings.analyze = True
        assert r_off["rules"]["fuse_maps"] > r_on["rules"]["fuse_maps"]
        # pure chains still fuse with analysis on
        pure = (Dampr.memory(list(range(100)))
                .map(lambda x: x * 2).map(lambda x: x + 1))
        _, rp = passes.optimize(pure.pmer.graph, [pure.source])
        assert rp["rules"]["fuse_maps"] == 1

    def test_analysis_off_plans_identical_for_pure_pipelines(self):
        pipe = (Dampr.memory(list(range(100)))
                .map(lambda x: x * 2).map(lambda x: x + 1)
                .fold_by(lambda x: x % 5, operator.add))
        g_on, _ = passes.optimize(pipe.pmer.graph, [pipe.source])
        settings.analyze = False
        g_off, _ = passes.optimize(pipe.pmer.graph, [pipe.source])
        settings.analyze = True
        assert graph_signature(g_on) == graph_signature(g_off)

    def test_analysis_off_results_byte_identical(self, tmp_path):
        """Even around an impure UDF (where the fusion decision
        differs), results are byte-identical with analysis on vs off —
        and fingerprints never move (analysis rides no stage
        options)."""
        from dampr_tpu import resume as _resume

        def build():
            acc = []
            return (Dampr.memory([(i % 7, i) for i in range(2000)],
                                 partitions=4)
                    .map(lambda kv: (kv[0], kv[1] * 2))
                    .map(lambda kv: (kv[0], kv[1] + 1))
                    .fold_by(lambda kv: kv[0], operator.add,
                             value=lambda kv: kv[1]))

        pipe = build()
        fps_on = _resume.stage_fingerprints(pipe.pmer.graph)
        em = pipe.run(name="analyze-on")
        on = sorted(em.read())
        sec = em.stats()["plan"]["analysis"]
        assert sec["enabled"] and sec["stages"]
        em.delete()
        settings.analyze = False
        pipe2 = build()
        fps_off = _resume.stage_fingerprints(pipe2.pmer.graph)
        em = pipe2.run(name="analyze-off")
        off = sorted(em.read())
        sec_off = em.stats()["plan"]["analysis"]
        assert not sec_off["enabled"] and not sec_off["stages"]
        em.delete()
        settings.analyze = True
        assert on == off
        assert list(fps_on.values()) == list(fps_off.values())

    def test_certified_chain_runs_device_path_exactly(self):
        """The ROADMAP-5a acceptance pin: a numeric non-text chain is
        statically certified, lowers to the device target, dispatches
        through the lane program with per-batch verification, and reads
        back byte-identical to the per-record path — verdict visible in
        explain()."""
        old = (settings.lower, settings.device_min_batch)
        settings.lower = "1"
        settings.device_min_batch = 4096
        try:
            N = 20000

            def build():
                return (Dampr.memory(list(range(N)), partitions=2)
                        .map(lambda x: x * 3 + 1)
                        .filter(lambda x: x % 2 == 0))

            pipe = build()
            text = pipe.explain()
            assert "certified jax-traceable" in text
            assert "DTA501" in text
            em = pipe.run(name="lane-dev")
            got = sorted(em.read())
            st = em.stats()
            assert st["device"]["device_stages"] >= 1
            targets = [s["target"] for s in st["stages"]
                       if s["kind"] == "map"]
            assert "device" in targets
            em.delete()
            prog = jaxtrace.stage_program(
                [s for s in passes.optimize(
                    pipe.pmer.graph, [pipe.source])[0].stages
                 if hasattr(s, "mapper")][-1])
            assert prog.counters["device_dispatched"] >= 1
            assert prog.counters["device_mismatch"] == 0
            assert prog.counters["diff_checked"] >= 1
            assert prog.counters["diff_diverged"] == 0
            settings.lower = "0"
            settings.analyze = False
            em = build().run(name="lane-host")
            host = sorted(em.read())
            em.delete()
            assert got == host
            assert got == sorted(v for v in (x * 3 + 1 for x in range(N))
                                 if v % 2 == 0)
        finally:
            settings.lower, settings.device_min_batch = old
            settings.analyze = True

    def test_stale_device_annotation_cannot_dispatch_opaque_op(self):
        """The runner re-certifies: an exec_target=device annotation on
        a stage whose chain does not certify takes the per-record path
        (stage_program returns None)."""
        pipe = Dampr.memory(list(range(10))).flat_map(lambda x: [x, x])
        stage = pipe.pmer.graph.stages[-1]
        stage.options["exec_target"] = "device"
        assert jaxtrace.stage_program(stage) is None
        em = pipe.run(name="stale-annot")
        assert sorted(em.read()) == sorted(
            [x for x in range(10) for _ in (0, 1)])
        em.delete()

    def test_speculation_declines_on_nondet_udf(self, tmp_path):
        """The acceptance pin: with mitigation armed and a straggling
        map job, the analyzer's nondeterminism verdict vetoes
        first-result-wins — zero speculative attempts, and the
        controller records the decline with evidence."""
        saved = (settings.scratch_root, settings.mitigate,
                 settings.speculate_threshold, settings.faults,
                 settings.max_processes)
        settings.scratch_root = str(tmp_path)
        settings.max_processes = 4
        settings.mitigate = "on"
        settings.speculate_threshold = 1.5
        # exactly the first udf-batch invocation stalls: the straggler
        # job the controller would speculate on
        settings.faults = "udf:nth=1,sleep_ms=1200"
        try:
            data = [(i % 16, i) for i in range(8000)]
            pipe = (Dampr.memory(data, partitions=4)
                    .map(lambda x: (x[0], x[1] + int(time.time() * 0)))
                    .fold_by(lambda x: x[0], operator.add,
                             value=lambda x: x[1]))
            em = pipe.run(name="spec-decline")
            got = sorted(em.read())
            mit = em.stats()["mitigation"]
            em.delete()
            assert mit["speculative_attempts"] == 0, mit
            assert mit["speculation_declined"], mit
            assert any("time" in e
                       for rec in mit["speculation_declined"]
                       for e in rec["evidence"])
            exp = {}
            for k, v in data:
                exp[k] = exp.get(k, 0) + v
            assert got == sorted(exp.items())
        finally:
            (settings.scratch_root, settings.mitigate,
             settings.speculate_threshold, settings.faults,
             settings.max_processes) = saved

    def test_zero_divide_raises_like_analyze_off(self):
        """Engine-level byte-identity pin for the errstate contract: a
        certified chain hitting a zero divisor PAST the first
        diff-tested batch raises the genuine ZeroDivisionError exactly
        as an analyze-off run does — never a silent inf."""
        old = (settings.lower, settings.device_min_batch)
        settings.lower = "1"
        settings.device_min_batch = 1 << 30  # host-vectorized only
        data = [float(i) for i in range(1, 20000)] + [0.0]

        def build():
            return (Dampr.memory(data, partitions=2)
                    .map(lambda v: 1.0 / v))

        try:
            with pytest.raises(ZeroDivisionError):
                build().run(name="zero-div-on")
            settings.lower = "0"
            settings.analyze = False
            with pytest.raises(ZeroDivisionError):
                build().run(name="zero-div-off")
        finally:
            settings.lower, settings.device_min_batch = old
            settings.analyze = True

    def test_explain_renders_analysis_section(self):
        acc = []
        pipe = (Dampr.memory(list(range(30)))
                .map(lambda x: (acc.append(x), x)[1]))
        text = pipe.explain()
        assert "analysis:" in text
        assert "DTA201" in text and "'acc'" in text
        settings.analyze = False
        assert "analysis: off" in pipe.explain()
        settings.analyze = True
