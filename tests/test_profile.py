"""Per-operator profiler (dampr_tpu.obs.profile): disabled-path pin
(no thread, no profile section, inert module surface), per-op
attribution on batched-UDF chains and scanner stages, fusion provenance,
device sub-phase decomposition, and coverage on the fused headline
stage."""

import operator
import os
import threading

import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.obs import profile


@pytest.fixture
def profiled(tmp_path):
    """Profiler + tracing on for one test, artifacts and scratch under
    tmp_path (scratch isolation keeps the history corpus per-test)."""
    old = (settings.trace, settings.trace_dir, settings.profile,
           settings.scratch_root)
    settings.trace = True
    settings.trace_dir = str(tmp_path / "traces")
    settings.profile = True
    settings.scratch_root = str(tmp_path / "scratch")
    yield tmp_path
    (settings.trace, settings.trace_dir, settings.profile,
     settings.scratch_root) = old


def _corpus(tmp_path, lines=6000):
    path = tmp_path / "corpus.txt"
    words = ["alpha", "beta", "gamma", "delta", "tok7", "zz", "mu", "xi"]
    with open(path, "w") as f:
        for i in range(lines):
            f.write(" ".join(words[(i + j) % len(words)]
                             for j in range(9)) + "\n")
    return str(path)


class TestDisabledPath:
    @pytest.mark.skipif(settings.profile,
                        reason="DAMPR_TPU_PROFILE=1 forced (the CI "
                               "profile-on leg): the off-path pin only "
                               "applies at defaults")
    def test_off_by_default_no_thread_no_section(self):
        """The default-off pin (same discipline as test_metrics): module
        surface is inert, no profiler instance, no new threads, and the
        run summary carries no profile section."""
        assert settings.profile is False
        assert profile.active() is None
        assert not profile.enabled()
        # inert module-level calls (would raise if they touched state)
        profile.device_add("build", 0.1, 123)
        before = {t.name for t in threading.enumerate()}
        em = Dampr.memory(list(range(3000))).map(lambda x: (x, 1)).run()
        assert "profile" not in em.stats()
        assert {t.name for t in threading.enumerate()} <= before
        em.delete()

    def test_off_path_no_alloc_in_hot_sites(self):
        """The hot-site contract: with no active profiler the module
        global is None and the (hoisted) site check is one load — pinned
        by asserting active() returns the same object (None) with no
        per-call allocation of noop wrappers (unlike span(), there is no
        wrapper object at all)."""
        assert profile.active() is None
        assert profile.active() is None  # stable, allocation-free


class TestAttribution:
    def test_batch_chain_per_op_and_provenance(self, profiled, tmp_path):
        """A fused map chain attributes per-op seconds/records under
        index-prefixed labels, carries fusion provenance, and covers the
        bulk of the stage's job time."""
        em = (Dampr.memory(list(range(20000)))
              .map(lambda x: (x % 64, x))
              .filter(lambda kv: kv[1] % 2 == 0)
              .fold_by(lambda kv: kv[0], binop=operator.add,
                       value=lambda kv: kv[1])
              .run("prof-chain"))
        prof = em.stats()["profile"]
        assert prof["enabled"] is True
        fused = [s for s in prof["stages"]
                 if any(o["op"].startswith("0:") for o in s["ops"])]
        assert fused, prof["stages"]
        st = fused[0]
        labels = [o["op"] for o in st["ops"]]
        # the chain's ops appear individually, plus the hoisted combiner
        assert any("Filter" in l for l in labels), labels
        assert "combine" in labels, labels
        # records flow through the ops (filter halves them)
        by = {o["op"]: o for o in st["ops"]}
        filt = next(v for k, v in by.items() if "Filter" in k)
        assert filt["records"] > 0
        assert st["provenance"], st
        assert any("Filter" in p for p in st["provenance"])
        assert st["jobs"] >= 1 and st["job_seconds"] > 0
        em.delete()

    def test_scanner_stage_covers_job_time(self, profiled, tmp_path):
        """The fused scanner (map_blocks) stage — the TF-IDF headline
        shape — attributes its codec windows to the scanner op, and on
        a corpus big enough for the codec to dominate per-job fixed
        costs the coverage clears a conservative floor (the acceptance
        bar is 0.9 on the real bench, measured at full size; tiny CI
        corpora leave more registration/clone overhead per second).

        Coverage is attributed-op thread-seconds over job thread-
        seconds: on a loaded 2-core box the scheduler can preempt a job
        thread BETWEEN ops (the full-suite run shares the machine), so
        the denominator inflates with stolen wall the profiled ops
        never saw and a single sample can land under the floor.  The
        assertion is best-of-three: genuinely broken attribution fails
        every attempt, scheduler noise does not repeat three times."""
        from dampr_tpu.ops.text import DocFreq

        corpus = _corpus(tmp_path, lines=40000)
        best = None
        for attempt in range(3):
            docs = Dampr.text(corpus, 1 << 19)
            em = (docs.custom_mapper(DocFreq(mode="word", lower=True))
                  .fold_by(lambda kv: kv[0], operator.add,
                           lambda kv: kv[1])
                  .run("prof-scan-{}".format(attempt)))
            prof = em.stats()["profile"]
            scan = [s for s in prof["stages"]
                    if any("DocFreq" in o["op"]
                           or o["op"].startswith("scan:")
                           for o in s["ops"])]
            assert scan, prof["stages"]
            st = max(scan, key=lambda s: s["job_seconds"])
            em.delete()
            assert st["coverage"] is not None, st
            if best is None or st["coverage"] > best["coverage"]:
                best = st
            if best["coverage"] >= 0.7:
                break
        assert best["coverage"] >= 0.7, best

    def test_stats_profile_reaches_persisted_summary(self, profiled,
                                                     tmp_path):
        """The profile section lands in the persisted stats.json too."""
        import json

        em = (Dampr.memory(list(range(4096)))
              .map(lambda x: (x % 7, 1))
              .fold_by(lambda kv: kv[0], binop=operator.add,
                       value=lambda kv: kv[1])
              .run("prof-persist"))
        path = em.stats()["stats_file"]
        assert path and os.path.isfile(path)
        with open(path) as f:
            on_disk = json.load(f)
        assert on_disk.get("profile", {}).get("enabled") is True
        em.delete()


class TestDeviceSubPhases:
    def test_lowered_stage_decomposes(self, profiled, tmp_path):
        """A device-lowered scanner stage records build/h2d/compute/d2h
        sub-phases with byte counts (the double-buffered dispatch loop's
        brackets)."""
        from dampr_tpu.ops.text import TokenCounts

        old = settings.lower
        old_handoff = settings.handoff
        settings.lower = "1"
        # The classic dispatch loop is what decomposes into these four
        # brackets; the handoff tier's bootstrap/probe path replaces it
        # on this edge and has its own observability pins
        # (test_handoff).
        settings.handoff = "off"
        try:
            # pair_values=False + fold_values is the device-eligible
            # map->fold shape (the bench's): no Rekey between scanner
            # and fold, so the lowering pass claims the map stage.
            em = (Dampr.text(_corpus(tmp_path), 1 << 17)
                  .custom_mapper(TokenCounts(mode="word", lower=True,
                                             pair_values=False))
                  .fold_values(operator.add)
                  .run("prof-device"))
            prof = em.stats()["profile"]
            dev = [s for s in prof["stages"] if s["device"]]
            assert dev, prof["stages"]
            phases = dev[0]["device"]
            for phase in ("build", "h2d", "compute", "d2h"):
                assert phase in phases, phases
                assert phases[phase]["seconds"] >= 0
                assert phases[phase]["calls"] >= 1
            assert phases["h2d"]["bytes"] > 0
            assert phases["d2h"]["bytes"] > 0
            # results are unperturbed by profiling (byte-identity is the
            # lowering contract)
            counts = dict(em.read())
            assert counts and all(v > 0 for v in counts.values())
            assert counts["alpha"] > 1000
            em.delete()
        finally:
            settings.lower = old
            settings.handoff = old_handoff


class TestProfilerUnit:
    def test_op_labels_and_accumulate(self):
        p = profile.Profiler("t")
        p.begin_stage(3, "map", provenance=["map[A]", "map[B]"])
        p.op_add("0:A", 0.5, records=10)
        p.op_add("0:A", 0.25, records=5)
        p.op_add("1:B", 0.1, records=15)
        p.device_add("h2d", 0.05, 1024, sid=3)
        p.job_add(1.0)
        s = p.summary({3: 2.0})
        st = s["stages"][0]
        assert st["stage"] == 3
        assert st["ops"][0] == {"op": "0:A", "seconds": 0.75,
                                "records": 15, "calls": 2}
        assert st["device"]["h2d"]["bytes"] == 1024
        assert st["jobs"] == 1
        assert abs(st["attributed_seconds"] - 0.9) < 1e-9
        assert st["coverage"] == round(min(1.0, 0.9 / 1.0), 4)
        assert st["seconds"] == 2.0
        assert st["provenance"] == ["map[A]", "map[B]"]

    def test_coverage_caps_at_one(self):
        p = profile.Profiler("t")
        p.begin_stage(0, "map")
        p.op_add("x", 5.0)
        p.job_add(1.0)
        assert p.summary()["stages"][0]["coverage"] == 1.0

    def test_timed_iter_attributes_each_next(self):
        p = profile.Profiler("t")
        p.begin_stage(1, "map")
        out = list(p.timed_iter(iter([[1, 2], [3]]), "scan"))
        assert out == [[1, 2], [3]]
        ops = p.summary()["stages"][0]["ops"]
        assert ops[0]["op"] == "scan"
        assert ops[0]["calls"] == 2
        assert ops[0]["records"] == 3

    def test_start_stop_nesting(self):
        a, b = profile.Profiler("a"), profile.Profiler("b")
        profile.start(a)
        profile.start(b)
        assert profile.active() is b
        profile.stop(b)
        assert profile.active() is a
        profile.stop(a)
        assert profile.active() is None
