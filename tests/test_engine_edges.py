"""Engine edge cases from review: chunk-boundary line ownership, value-type
preservation across block concatenation, spill-budget enforcement, streamed
final reads, Splitter/block routing agreement."""

import numpy as np
import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.base import Splitter
from dampr_tpu.blocks import Block, _concat_cols
from dampr_tpu.dataset import TextLineDataset


@pytest.fixture(autouse=True)
def small_partitions():
    old = settings.partitions
    settings.partitions = 8
    yield
    settings.partitions = old


class TestChunkBoundaries:
    def test_line_longer_than_chunk_not_duplicated(self, tmp_path):
        p = str(tmp_path / "long.txt")
        lines = ["short", "x" * 239, "tail"]
        with open(p, "w") as f:
            f.write("\n".join(lines) + "\n")
        for chunk in (100, 50, 17):
            out = Dampr.text(p, chunk_size=chunk).read()
            assert out == lines, (chunk, out)

    def test_every_offset_split_reads_once(self, tmp_path):
        p = str(tmp_path / "u.txt")
        lines = ["aä{}".format(i) for i in range(20)]  # multibyte chars
        data = ("\n".join(lines) + "\n").encode("utf-8")
        with open(p, "wb") as f:
            f.write(data)
        for split in range(1, len(data)):
            got = [v for _k, v in TextLineDataset(p, 0, split).read()]
            got += [v for _k, v in TextLineDataset(p, split, len(data)).read()]
            assert got == lines, split


class TestConcatPreservation:
    def test_bool_survives_cross_block_concat(self):
        out = Dampr.memory([True, False] + [2] * 5, partitions=7).map(
            lambda x: x).read()
        assert out == [True, False, 2, 2, 2, 2, 2]
        assert out[0] is True

    def test_large_int_survives_float_concat(self):
        big = 2 ** 60 + 1
        cols = [np.array([big], dtype=np.int64), np.array([0.5])]
        merged = _concat_cols(cols)
        assert merged[0] == big

    def test_small_int_float_concat_promotes(self):
        merged = _concat_cols([np.array([1, 2]), np.array([0.5])])
        assert merged.dtype == np.float64


class TestSpillBudget:
    def test_map_stage_spills_under_budget(self, tmp_path):
        old_budget = settings.max_memory_per_stage
        old_scratch = settings.scratch_root
        settings.max_memory_per_stage = 64 * 1024  # 64 KB
        settings.scratch_root = str(tmp_path / "scratch")
        try:
            n = 20000
            pipe = Dampr.memory(list(range(n)), partitions=10).checkpoint(True)
            from dampr_tpu.runner import MTRunner
            runner = MTRunner("spill-test", pipe.pmer.graph)
            out = runner.run([pipe.source])
            # budget enforced: blocks actually spilled to disk mid-run
            assert runner.store.spill_count > 0
            got = sorted(v for _k, v in out[0].read())
            assert got == list(range(n))
        finally:
            settings.max_memory_per_stage = old_budget
            settings.scratch_root = old_scratch

    def test_group_by_with_spill_is_exact(self, tmp_path):
        old_budget = settings.max_memory_per_stage
        old_scratch = settings.scratch_root
        settings.max_memory_per_stage = 32 * 1024
        settings.scratch_root = str(tmp_path / "scratch2")
        try:
            n = 30000
            out = dict(Dampr.memory(list(range(n)), partitions=10)
                       .count(lambda x: x % 7).read())
            expect = {}
            for x in range(n):
                expect[x % 7] = expect.get(x % 7, 0) + 1
            assert out == expect
        finally:
            settings.max_memory_per_stage = old_budget
            settings.scratch_root = old_scratch


class TestMixedKeyOutputs:
    def test_mixed_key_final_read_does_not_raise(self):
        out = Dampr.memory([(1, "a"), ("s", "b"), (2.5, "c")]).fold_by(
            lambda kv: kv[0], lambda x, y: x + y, lambda kv: kv[1]).read()
        assert len(out) == 3

    def test_read_k_is_lazy_prefix(self):
        em = Dampr.memory(list(range(1000)), partitions=4).run()
        assert em.read(5) == [0, 1, 2, 3, 4]


class TestSplitterAgreement:
    def test_splitter_matches_block_routing(self):
        keys = ["alpha", 7, (1, "x"), 3.5, b"bytes"]
        blk = Block.from_pairs([(k, 0) for k in keys])
        pids = blk.partition_ids(13)
        sp = Splitter()
        for i, k in enumerate(keys):
            assert sp.partition(k, 13) == int(pids[i])


class TestRetriesWithExchange:
    def test_flaky_reducer_retried_through_mesh_exchange(self):
        from dampr_tpu import Dampr, settings
        from dampr_tpu.runner import MTRunner

        old = (settings.partitions, settings.mesh_exchange,
               settings.mesh_fold, settings.job_retries)
        settings.partitions = 4
        settings.mesh_exchange = "auto"
        settings.mesh_fold = "off"
        settings.job_retries = 1
        fails = {"left": 1}

        def flaky(k, vs):
            if fails["left"] > 0:
                fails["left"] -= 1
                raise RuntimeError("transient")
            return sorted(vs)[:2]

        try:
            pipe = (Dampr.memory(list(range(200)), partitions=4)
                    .group_by(lambda x: x % 3).reduce(flaky))
            runner = MTRunner("flaky-exchange", pipe.pmer.graph)
            out = dict(v for v in runner.run([pipe.source])[0].read())
            assert runner.mesh_exchanges >= 1
            want = {k: (k, sorted(x for x in range(200) if x % 3 == k)[:2])
                    for k in range(3)}
            assert out == want
        finally:
            (settings.partitions, settings.mesh_exchange,
             settings.mesh_fold, settings.job_retries) = old


class TestMultiOutputUnderPressure:
    def test_shared_prefix_multi_output_tiny_budget(self):
        from dampr_tpu import Dampr, settings
        from dampr_tpu.runner import MTRunner

        old = (settings.partitions, settings.mesh_exchange,
               settings.mesh_fold)
        settings.partitions = 8
        settings.mesh_exchange = "auto"
        settings.mesh_fold = "auto"
        try:
            base = Dampr.memory(list(range(4000)), partitions=8).map(
                lambda x: x * 3)
            counts = base.count(lambda x: x % 5)
            total = base.len()
            mx = base.a_group_by(lambda x: x % 7).reduce(max)
            outs = Dampr.run(counts, total, mx, memory_budget=1 << 15)
            got_counts = dict(outs[0].read())
            assert got_counts == {i: 800 for i in range(5)}
            assert list(outs[1].read()) == [4000]
            got_mx = dict(outs[2].read())
            want_mx = {k: max(x * 3 for x in range(4000)
                              if (x * 3) % 7 == k)
                       for k in set((x * 3) % 7 for x in range(4000))}
            assert got_mx == want_mx
        finally:
            (settings.partitions, settings.mesh_exchange,
             settings.mesh_fold) = old


class TestUncopyableUDFs:
    """Per-job operator cloning must share the user callable by reference.

    The reference gets this for free from fork (children inherit the object
    graph); our thread-pool runner deep-copies operators per job, and a
    RecordOp holding a UDF whose closure/attributes include an uncopyable
    resource (open file, socket, model handle) must not crash the run."""

    def test_map_with_open_file_handle(self, tmp_path):
        p = tmp_path / "lookup.txt"
        p.write_text("10\n")
        fh = open(p)

        class Lookup:
            def __init__(self, handle):
                self.handle = handle  # TextIOWrapper: not deepcopy-able
                self.scale = int(handle.read().strip())

            def __call__(self, x):
                # Deliberately no per-call handle use: the shared instance
                # is called from concurrent jobs and must stay thread-safe.
                return x * self.scale

        try:
            out = Dampr.memory(list(range(20))).map(Lookup(fh)).run()
            assert sorted(out.read()) == [i * 10 for i in range(20)]
        finally:
            fh.close()

    def test_every_record_op_shares_udf(self, tmp_path):
        # Callable *objects* with an uncopyable attribute (deepcopy treats
        # plain functions as atomic, so lambdas would not exercise the
        # share-by-reference __deepcopy__; instance attributes do).
        fh = open(tmp_path / "f.txt", "w")

        class Udf:
            def __init__(self, fn):
                self.handle = fh  # TextIOWrapper: not deepcopy-able
                self.fn = fn

            def __call__(self, *a):
                return self.fn(*a)

        try:
            out = (Dampr.memory(list(range(50)))
                   .map(Udf(lambda x: x))
                   .filter(Udf(lambda x: x % 2 == 0))
                   .flat_map(Udf(lambda x: [x, x]))
                   .map(Udf(lambda x: x + 1))
                   .run())
            got = sorted(out.read())
            want = sorted([x + 1 for x in range(0, 50, 2) for _ in (0, 1)])
            assert got == want
        finally:
            fh.close()

    def test_reduce_and_join_share_udf(self, tmp_path):
        # The same share-by-reference policy must cover reducers and joins,
        # not just RecordOps: group_by().reduce, fold_by, and join all hold
        # user callables the runner must never deep-copy.
        fh = open(tmp_path / "f.txt", "w")

        class Udf:
            def __init__(self, fn):
                self.handle = fh
                self.fn = fn

            def __call__(self, *a):
                return self.fn(*a)

        try:
            data = Dampr.memory(list(range(40)))
            grouped = (data
                       .group_by(Udf(lambda x: x % 4))
                       .reduce(Udf(lambda k, it: sum(it))))
            folded = data.fold_by(Udf(lambda x: x % 4),
                                  binop=Udf(lambda a, b: a + b))
            joined = grouped.join(folded).reduce(
                Udf(lambda l, r: (sum(v for _, v in l),
                                  sum(v for _, v in r))))
            outs = Dampr.run(grouped, folded, joined)
            want = {k: sum(x for x in range(40) if x % 4 == k)
                    for k in range(4)}
            assert dict(outs[0].read()) == want
            assert dict(outs[1].read()) == want
            assert dict(outs[2].read()) == {k: (v, v)
                                            for k, v in want.items()}
        finally:
            fh.close()
