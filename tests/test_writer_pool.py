"""Background spill writer pool (dampr_tpu.io.writer): budget-bounded
in-flight bytes, kill-path drain hygiene, publish ordering, checkpoint
consistency through resume, and per-job UDF isolation (the
``_shared_instance_deepcopy`` fix rides this PR)."""

import glob
import os
import threading
import time

import numpy as np
import pytest

from dampr_tpu import settings
from dampr_tpu.blocks import Block
from dampr_tpu.io import codecs
from dampr_tpu.storage import RunStore


def _blk(n=20000, base=0):
    return Block(np.arange(n, dtype=np.int64) + base,
                 np.arange(n, dtype=np.int64) * 2 + base)


@pytest.fixture
def scratch(tmp_path):
    old_scratch = settings.scratch_root
    old_threads = settings.spill_write_threads
    old_inflight = settings.spill_inflight_bytes
    settings.scratch_root = str(tmp_path / "scratch")
    yield tmp_path
    settings.scratch_root = old_scratch
    settings.spill_write_threads = old_threads
    settings.spill_inflight_bytes = old_inflight


class TestInflightBound:
    def test_inflight_bytes_never_exceed_cap(self, scratch):
        """The pool's charge loop admits a job only under the cap, so
        queued-but-unwritten bytes (RAM still held) can never stack an
        unbounded write backlog on top of the stage budget."""
        settings.spill_inflight_bytes = 1 << 18  # 256 KB, ~1.5 blocks
        store = RunStore("pool-bound", budget=1 << 16)
        peaks = []

        class SlowCodec(object):  # force a persistent backlog
            cid = codecs.RAW

            def compress(self, data):
                time.sleep(0.002)
                peaks.append(store.spill_inflight_bytes)
                return data

        import dampr_tpu.storage as storage_mod
        orig = storage_mod._spill_codec
        storage_mod._spill_codec = lambda *a: SlowCodec()
        try:
            refs = [store.register(_blk(base=i)) for i in range(12)]
            store.drain_writes()
        finally:
            storage_mod._spill_codec = orig
        blk_bytes = refs[0].nbytes
        cap = settings.spill_inflight_bytes
        # admission is by current backlog: the bound is cap + one block
        assert store.spill_inflight_peak_bytes <= cap + blk_bytes, (
            store.spill_inflight_peak_bytes, cap)
        assert max(peaks) <= cap + blk_bytes
        for i, r in enumerate(refs):
            got = r.get()
            assert np.array_equal(np.asarray(got.keys),
                                  np.arange(20000, dtype=np.int64) + i)
        store.cleanup()

    def test_inflight_charges_shrink_victim_target(self, scratch):
        """Queued spill bytes count against the budget exactly like
        overlap windows: while a backlog exists, the victim selector's
        target shrinks by the in-flight bytes."""
        store = RunStore("pool-target", budget=1 << 20)
        pool = store.writer_pool()
        assert pool is not None
        with pool._cv:
            pool.inflight_bytes = 1 << 20  # simulate a full backlog
        try:
            ref = store.register(_blk())
            store.drain_writes()
            # the whole budget is charged to in-flight writes, so the
            # fresh ref must have been displaced to disk
            assert not ref.resident and ref.path is not None
        finally:
            with pool._cv:
                pool.inflight_bytes = 0
        store.cleanup()


class TestKillDrain:
    def test_abort_leaves_no_temp_files_and_no_charges(self, scratch):
        settings.spill_inflight_bytes = 1 << 30
        store = RunStore("pool-abort", budget=1)

        gate = threading.Event()

        class BlockingCodec(object):
            cid = codecs.RAW

            def compress(self, data):
                gate.wait(5.0)
                return data

        import dampr_tpu.storage as storage_mod
        orig = storage_mod._spill_codec
        storage_mod._spill_codec = lambda *a: BlockingCodec()
        try:
            refs = [store.register(_blk(base=i)) for i in range(6)]
            assert store.spill_inflight_bytes > 0
            gate.set()
            store.abort_writes()  # the killed-run drain
        finally:
            storage_mod._spill_codec = orig
        assert store.spill_inflight_bytes == 0
        orphans = glob.glob(os.path.join(store.root, "**", "*.tmp"),
                            recursive=True)
        assert orphans == [], orphans
        # aborted refs keep their RAM blocks: nothing lost, all readable
        for i, r in enumerate(refs):
            got = r.get()
            assert np.array_equal(np.asarray(got.keys),
                                  np.arange(20000, dtype=np.int64) + i)
        store.cleanup()

    def test_write_failure_surfaces_on_drain(self, scratch):
        store = RunStore("pool-err", budget=1)

        class BoomCodec(object):
            cid = codecs.RAW

            def compress(self, data):
                raise OSError("disk exploded")

        import dampr_tpu.storage as storage_mod
        orig = storage_mod._spill_codec
        storage_mod._spill_codec = lambda *a: BoomCodec()
        try:
            ref = store.register(_blk())
            with pytest.raises(OSError, match="disk exploded"):
                store.drain_writes()
        finally:
            storage_mod._spill_codec = orig
        # the failed write left the data in RAM and no temp litter
        assert ref.resident
        assert glob.glob(os.path.join(store.root, "**", "*.tmp"),
                         recursive=True) == []
        store.cleanup()


class TestPublishOrder:
    def test_block_readable_until_file_durable(self, scratch):
        """fsync/rename publish order: until the final file exists, the
        ref still answers from RAM; ``path`` never points at a temp or
        half-written file."""
        store = RunStore("pool-pub", budget=1)
        started = threading.Event()
        gate = threading.Event()

        class GatedCodec(object):
            cid = codecs.RAW

            def compress(self, data):
                started.set()
                gate.wait(5.0)
                return data

        import dampr_tpu.storage as storage_mod
        orig = storage_mod._spill_codec
        storage_mod._spill_codec = lambda *a: GatedCodec()
        try:
            ref = store.register(_blk())
            assert started.wait(5.0)
            # mid-write: path unpublished, RAM copy still serving reads
            assert ref.path is None and ref.resident
            assert len(ref.get()) == 20000
        finally:
            storage_mod._spill_codec = orig
            gate.set()
        store.drain_writes()
        assert ref.path is not None and not ref.resident
        assert os.path.exists(ref.path) and not ref.path.endswith(".tmp")
        assert len(ref.get()) == 20000
        store.cleanup()

    def test_dropped_ref_mid_write_leaks_nothing(self, scratch):
        store = RunStore("pool-drop", budget=1)
        gate = threading.Event()

        class GatedCodec(object):
            cid = codecs.RAW

            def compress(self, data):
                gate.wait(5.0)
                return data

        import dampr_tpu.storage as storage_mod
        orig = storage_mod._spill_codec
        storage_mod._spill_codec = lambda *a: GatedCodec()
        try:
            ref = store.register(_blk())
            store.drop_ref(ref)  # delete races the queued write
            gate.set()
            store.drain_writes()
        finally:
            storage_mod._spill_codec = orig
        blks = glob.glob(os.path.join(store.root, "**", "*.blk"),
                         recursive=True)
        assert blks == [], "dropped ref's spill file survived"
        store.cleanup()

    def test_concurrent_register_threads_stay_exact(self, scratch):
        settings.spill_inflight_bytes = 1 << 16
        store = RunStore("pool-conc", budget=1 << 16)
        refs = [[] for _ in range(4)]

        def worker(t):
            for i in range(8):
                refs[t].append(
                    (t * 100 + i, store.register(_blk(4096, t * 100 + i))))

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        store.drain_writes()
        for t in range(4):
            for base, r in refs[t]:
                got = r.get()
                assert np.array_equal(
                    np.asarray(got.keys),
                    np.arange(4096, dtype=np.int64) + base)
        store.cleanup()


class TestSyncPathParity:
    def test_sync_spills_feed_io_counters(self, scratch):
        """DAMPR_TPU_SPILL_WRITERS=0 (the async-off baseline) must still
        report write bandwidth, or the pool can't be compared against it."""
        settings.spill_write_threads = 0
        store = RunStore("sync-io", budget=1)
        ref = store.register(_blk())
        assert not ref.resident  # synchronous: spilled before register returned
        assert store.spill_disk_bytes > 0
        assert store.spill_write_seconds > 0
        store.cleanup()

    def test_unknown_spill_compress_mode_degrades_to_auto(self, scratch,
                                                          tmp_path):
        import dampr_tpu.storage as storage_mod

        old = settings.spill_compress
        settings.spill_compress = "on"  # pre-frame configs accepted this
        try:
            blk = _blk(4096)
            p = str(tmp_path / "mode.blk")
            storage_mod.save_block(blk, p)  # must not raise
            back = storage_mod.load_block(p)
            assert np.array_equal(back.keys, blk.keys)
        finally:
            settings.spill_compress = old


class TestResumeConsistency:
    def _build(self, path, mark):
        from dampr_tpu import Dampr

        return (Dampr.memory(list(range(5000)), partitions=8)
                .map(lambda x: x + mark)
                .checkpoint(force=True))

    def test_checkpoint_persist_through_pool_restores(self, scratch):
        """resume=True persists stage outputs through the writer pool;
        the manifests must reference only durable, loadable files."""
        name = "pool-resume"
        got1 = sorted(self._build(scratch, 0).run(
            name=name, resume=True, memory_budget=1 << 14).read())
        root = os.path.join(settings.scratch_root, name)
        # every manifest-referenced block exists and loads
        import json

        from dampr_tpu.storage import load_block

        mdir = os.path.join(root, "manifest")
        manifests = sorted(os.listdir(mdir))
        assert manifests
        seen_blocks = 0
        for m in manifests:
            with open(os.path.join(mdir, m)) as f:
                man = json.load(f)
            for entry in man.get("blocks", ()):
                p = os.path.join(root, entry[1])
                assert os.path.exists(p), p
                assert len(load_block(p)) == entry[2]
                seen_blocks += 1
        assert seen_blocks > 0
        # a rerun restores from those checkpoints and agrees exactly
        got2 = sorted(self._build(scratch, 0).run(
            name=name, resume=True, memory_budget=1 << 14).read())
        assert got1 == got2

    def test_pre_frame_checkpoint_dir_restores(self, scratch):
        """Back-compat acceptance: a checkpoint written entirely in the
        PRE-frame wire format (what a pre-PR-3 run left on disk) must
        restore and resume correctly with the new loader."""
        import gzip
        import pickle

        name = "pool-oldfmt"
        got1 = sorted(self._build(scratch, 0).run(
            name=name, resume=True, memory_budget=1 << 30).read())
        root = os.path.join(settings.scratch_root, name)
        # Rewrite every checkpoint block into the legacy formats the old
        # engine produced (gzip'd / plain pickle-window streams).
        from dampr_tpu.storage import SPILL_WINDOW, load_block

        rewritten = 0
        for dirpath, _dirs, files in os.walk(root):
            for fname in files:
                if not fname.endswith(".blk"):
                    continue
                p = os.path.join(dirpath, fname)
                blk = load_block(p)
                plain = (blk.keys.dtype != object
                         and blk.values.dtype != object)
                opener = (open if plain
                          else (lambda q, m: gzip.open(q, m,
                                                       compresslevel=1)))
                with opener(p, "wb") as f:
                    n = len(blk)
                    for at in range(0, max(n, 1), SPILL_WINDOW):
                        end = min(at + SPILL_WINDOW, n)
                        pickle.dump(
                            (blk.keys[at:end], blk.values[at:end],
                             None if blk.h1 is None else blk.h1[at:end],
                             None if blk.h2 is None else blk.h2[at:end]),
                            f, protocol=pickle.HIGHEST_PROTOCOL)
                rewritten += 1
        assert rewritten > 0
        got2 = sorted(self._build(scratch, 0).run(
            name=name, resume=True, memory_budget=1 << 30).read())
        assert got1 == got2


class TestStatsSurface:
    def test_run_summary_gains_io_section(self, scratch):
        """Best-of-three (the test_profile.py coverage idiom): the
        spill byte counters depend on the writer pool actually draining
        to disk under the tiny budget, and on a loaded shared box a
        single attempt can serve enough blocks from RAM to leave a zero
        read counter.  A genuinely missing io section (or dead counter
        wiring) fails all three attempts; scheduler luck does not
        repeat."""
        from dampr_tpu import Dampr
        from dampr_tpu.runner import MTRunner

        last_io = None
        for attempt in range(3):
            pipe = (Dampr.memory(list(range(50000)), partitions=8)
                    .checkpoint(force=True))
            runner = MTRunner("pool-stats-{}".format(attempt),
                              pipe.pmer.graph, memory_budget=1 << 14)
            out = runner.run([pipe.source])
            assert (sorted(v for _k, v in out[0].read())
                    == list(range(50000)))
            io = runner.run_summary["io"]
            runner.store.cleanup()
            for key in ("spill_write_bytes", "spill_write_seconds",
                        "spill_write_mbps", "spill_read_bytes",
                        "spill_read_seconds", "spill_read_mbps",
                        "io_wait_seconds", "io_wait_fraction",
                        "writer_threads", "inflight_peak_bytes"):
                assert key in io, key
            last_io = io
            if io["spill_write_bytes"] > 0 and io["spill_read_bytes"] > 0:
                break
        assert last_io["spill_write_bytes"] > 0, last_io
        assert last_io["spill_read_bytes"] > 0, last_io


class TestUdfIsolation:
    """The ``_shared_instance_deepcopy`` fix: stateful callable objects
    get per-job copies; plain functions stay shared; uncopyable state
    degrades to the shared instance with a warning."""

    def test_stateful_callable_object_is_isolated_per_job(self):
        import copy

        from dampr_tpu import base

        class Tagger(object):
            def __init__(self):
                self.seen = []

            def __call__(self, k, v):
                self.seen.append(k)
                yield k, v

        udf = Tagger()
        op = base.Map(udf)
        clone = copy.deepcopy(op)
        assert clone is not op
        assert clone.mapper is not udf
        list(clone.mapper(1, 2))
        assert udf.seen == [] and clone.mapper.seen == [1]

    def test_plain_function_wrapper_stays_shared(self):
        import copy

        from dampr_tpu import base

        def f(k, v):
            yield k, v

        op = base.Map(f)
        assert copy.deepcopy(op) is op
        vm = base.ValueMap(lambda v: v)
        assert copy.deepcopy(vm) is vm

    def test_attributeless_wrapper_stays_shared(self):
        # A shared-deepcopy op with an empty (or absent) __dict__ must
        # share, not crash on the empty-holdings fast path.
        import copy

        from dampr_tpu import base

        class Bare(base.RecordOp):
            def apply_batch(self, ks, vs):
                return ks, vs

        op = Bare()
        assert copy.deepcopy(op) is op

        class Slotted(base.RecordOp):
            __slots__ = ()

            def apply_batch(self, ks, vs):
                return ks, vs

        # __slots__ subclasses of a dict-ful base still expose __dict__;
        # either way the clone path must not raise
        slotted = Slotted()
        assert copy.deepcopy(slotted) is slotted

    def test_uncopyable_stateful_callable_warns_and_shares(self, tmp_path,
                                                           caplog):
        import copy
        import logging

        from dampr_tpu import base

        fh = open(tmp_path / "res.txt", "w")

        class Uncopyable(object):
            def __init__(self):
                self.handle = fh

            def __call__(self, k, v):
                yield k, v

        try:
            op = base.Map(Uncopyable())
            with caplog.at_level(logging.WARNING, "dampr_tpu.base"):
                base._share_warned.discard("Map")
                clone = copy.deepcopy(op)
            assert clone is op  # fell back to sharing
            assert any("SHARED across" in r.message for r in caplog.records)
        finally:
            fh.close()

    def test_bound_method_of_stateful_object_is_isolated(self):
        import copy

        from dampr_tpu import base

        class Dedupe(object):
            def __init__(self):
                self.seen = set()

            def check(self, k, v):
                if k not in self.seen:
                    self.seen.add(k)
                    yield k, v

        d = Dedupe()
        op = base.Map(d.check)
        clone = copy.deepcopy(op)
        assert clone is not op
        list(clone.mapper(1, 2))
        assert d.seen == set(), "bound-method receiver shared across jobs"

    def test_stateful_callable_inside_partial_is_isolated(self):
        import copy
        import functools

        from dampr_tpu import base

        class Acc(object):
            def __init__(self):
                self.seen = []

            def __call__(self, k, v):
                self.seen.append(k)
                yield k, v

        acc = Acc()
        op = base.Map(functools.partial(acc))
        clone = copy.deepcopy(op)
        assert clone is not op
        list(clone.mapper(1, 2))
        assert acc.seen == [], "partial-wrapped stateful callable shared"

    def test_uncopyable_shared_twice_in_one_pass_stays_shared(self,
                                                              tmp_path):
        # The memo must not retain a half-built clone when the copy
        # fails: the SAME op referenced twice in one deepcopy pass must
        # resolve to the shared original both times.
        import copy

        from dampr_tpu import base

        fh = open(tmp_path / "res2.txt", "w")

        class Uncopyable(object):
            def __init__(self):
                self.handle = fh

            def __call__(self, k, v):
                yield k, v

        try:
            op = base.Map(Uncopyable())
            both = copy.deepcopy([op, op])
            assert both[0] is op and both[1] is op
            assert both[1].mapper.handle is fh
        finally:
            fh.close()

    def test_concurrent_jobs_do_not_interleave_stateful_udf(self, scratch):
        """End-to-end: a dedupe-style stateful callable sees only its own
        job's records (pre-fix it observed every chunk's)."""
        from dampr_tpu import Dampr

        class PerJobCounter(object):
            def __init__(self):
                self.n = 0

            def __call__(self, x):
                self.n += 1
                return (x, self.n)

        out = dict(Dampr.memory(list(range(400)), partitions=16)
                   .map(PerJobCounter()).run().read())
        assert sorted(out) == list(range(400))
        # each job's clone counts from 1; with a shared instance the max
        # counter would reach the full record count
        assert max(out.values()) < 400