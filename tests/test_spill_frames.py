"""Chunked-frame spill format (dampr_tpu.io): round-trip fidelity across
codecs, coexistence with the legacy formats in one run directory, the
truncated-footer error path, and parallel-decompress exactness."""

import gzip
import os
import pickle

import numpy as np
import pytest

from dampr_tpu import settings
from dampr_tpu.blocks import Block
from dampr_tpu.io import codecs, frames
from dampr_tpu.io.frames import FrameFormatError, FrameReader
from dampr_tpu.storage import (SPILL_WINDOW, iter_block_windows, load_block,
                               save_block)


def _assert_blocks_equal(a, b):
    assert len(a) == len(b)
    ka, kb = list(a.iter_pairs()), list(b.iter_pairs())
    assert ka == kb


def _object_block(n=SPILL_WINDOW + 777):
    ks = np.empty(n, dtype=object)
    ks[:] = ["key-%d" % (i % 997) for i in range(n)]
    vs = np.empty(n, dtype=object)
    vs[:] = [("v", i) for i in range(n)]
    return Block(ks, vs)


def _numeric_block(n=2 * SPILL_WINDOW + 31):
    blk = Block(np.arange(n, dtype=np.int64),
                np.linspace(0.0, 1.0, n))
    blk.hashes()
    return blk


@pytest.fixture
def fresh_settings():
    old = (settings.spill_compress, settings.spill_codec,
           settings.spill_read_prefetch)
    yield
    (settings.spill_compress, settings.spill_codec,
     settings.spill_read_prefetch) = old


class TestRoundTrip:
    @pytest.mark.parametrize("make", [_numeric_block, _object_block])
    def test_save_load_exact(self, tmp_path, make):
        blk = make()
        p = str(tmp_path / "b.blk")
        save_block(blk, p)
        _assert_blocks_equal(load_block(p), blk)

    def test_windows_are_bounded(self, tmp_path):
        blk = _numeric_block(3 * SPILL_WINDOW + 5)
        p = str(tmp_path / "b.blk")
        save_block(blk, p)
        ws = list(iter_block_windows(p))
        assert len(ws) == 4
        assert all(len(w) <= SPILL_WINDOW for w in ws)
        _assert_blocks_equal(Block.concat(ws), blk)

    def test_empty_block(self, tmp_path):
        blk = Block(np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64))
        p = str(tmp_path / "e.blk")
        save_block(blk, p)
        back = load_block(p)
        assert len(back) == 0
        # the file still parses as a frame file with one (empty) frame
        r = FrameReader(p)
        try:
            assert len(r) == 1 and r.records == 0
        finally:
            r.close()

    def test_hash_lanes_survive(self, tmp_path):
        blk = _numeric_block()
        p = str(tmp_path / "h.blk")
        save_block(blk, p)
        back = load_block(p)
        assert np.array_equal(back.h1, blk.h1)
        assert np.array_equal(back.h2, blk.h2)

    def test_composite_lane_round_trip(self, tmp_path):
        n = SPILL_WINDOW + 9
        blk = Block(np.arange(n, dtype=np.int64),
                    np.stack([np.arange(n), np.arange(n) * 2], axis=1)
                    .astype(np.int64))
        p = str(tmp_path / "c.blk")
        save_block(blk, p)
        back = load_block(p)
        assert np.array_equal(back.values, blk.values)


class TestCodecs:
    @pytest.mark.parametrize("name", ["raw", "zlib", "gzip", "zlib:6"])
    def test_explicit_codec_round_trip(self, tmp_path, fresh_settings, name):
        settings.spill_compress = name
        blk = _object_block(SPILL_WINDOW // 2)
        p = str(tmp_path / "c.blk")
        save_block(blk, p)
        _assert_blocks_equal(load_block(p), blk)

    def test_optional_codecs_round_trip_or_fall_back(self, tmp_path,
                                                     fresh_settings):
        # With lz4/zstd installed this exercises the fast path; without,
        # the graceful fallback — both must produce readable frames.
        for name in ("lz4", "zstd"):
            settings.spill_compress = name
            blk = _object_block(SPILL_WINDOW // 4)
            p = str(tmp_path / (name + ".blk"))
            save_block(blk, p)
            _assert_blocks_equal(load_block(p), blk)
            r = FrameReader(p)
            try:
                cids = {e[1] for e in r.index}
            finally:
                r.close()
            if codecs.available(name):
                assert cids == {codecs._IDS[name]}
            else:
                assert codecs._IDS[name] not in cids  # fell back

    def test_mixed_codecs_coexist_in_one_dir(self, tmp_path, fresh_settings):
        """One run dir holding frames written under different codec
        settings — every file self-describes via per-frame codec ids."""
        blocks, paths = [], []
        for i, mode in enumerate(["raw", "zlib", "gzip", "auto", "never"]):
            settings.spill_compress = mode
            blk = _object_block(SPILL_WINDOW // 8 + i)
            p = str(tmp_path / ("m%d.blk" % i))
            save_block(blk, p)
            blocks.append(blk)
            paths.append(p)
        for blk, p in zip(blocks, paths):
            _assert_blocks_equal(load_block(p), blk)

    def test_missing_codec_decode_raises(self, tmp_path):
        class FutureCodec(object):  # a codec id this build doesn't know
            cid = 99

            def compress(self, data):
                return data

        p = str(tmp_path / "bad.blk")
        with open(p, "wb") as f:
            w = frames.FrameWriter(f, FutureCodec())
            w.add_frame(b"payload", records=1)
            w.close()
        r = FrameReader(p)
        try:
            with pytest.raises(codecs.MissingCodecError):
                r.read_frame(0)
        finally:
            r.close()

    def test_auto_resolves_and_explicit_levels_parse(self):
        c = codecs.resolve("auto")
        assert c.name in ("zstd", "lz4", "zlib")
        assert codecs.resolve("zlib:7").level == 7
        with pytest.raises(ValueError):
            codecs.resolve("nonsense")

    def test_fallback_drops_foreign_level(self):
        # "zstd:19" on a host without zstd must NOT become zlib:19 (zlib
        # stops at 9) — the fallback takes its own default level, and the
        # resolved codec must actually compress.
        c = codecs.resolve("zstd:19")
        if c.name != "zstd":  # fell back
            assert c.name in ("lz4", "zlib")
        data = b"x" * 4096
        assert c.decompress(c.compress(data)) == data


class TestBackCompat:
    """Pre-frame spill dirs (whole-file gzip for object lanes, plain
    pickle-window streams for numeric) must stay readable forever: resume
    manifests written before PR 3 reference them."""

    @staticmethod
    def _legacy_dump(block, f):
        n = len(block)
        for at in range(0, max(n, 1), SPILL_WINDOW):
            end = min(at + SPILL_WINDOW, n)
            pickle.dump(
                (block.keys[at:end], block.values[at:end],
                 None if block.h1 is None else block.h1[at:end],
                 None if block.h2 is None else block.h2[at:end]),
                f, protocol=pickle.HIGHEST_PROTOCOL)

    def test_legacy_gzip_stream_reads(self, tmp_path):
        blk = _object_block()
        p = str(tmp_path / "old.blk")
        with gzip.open(p, "wb", compresslevel=1) as f:
            self._legacy_dump(blk, f)
        _assert_blocks_equal(load_block(p), blk)
        assert sum(len(w) for w in iter_block_windows(p)) == len(blk)

    def test_legacy_plain_stream_reads(self, tmp_path):
        blk = _numeric_block()
        p = str(tmp_path / "old_plain.blk")
        with open(p, "wb") as f:
            self._legacy_dump(blk, f)
        _assert_blocks_equal(load_block(p), blk)

    def test_legacy_and_frame_files_coexist(self, tmp_path):
        old, new = _numeric_block(), _object_block()
        po, pn = str(tmp_path / "o.blk"), str(tmp_path / "n.blk")
        with open(po, "wb") as f:
            self._legacy_dump(old, f)
        save_block(new, pn)
        _assert_blocks_equal(load_block(po), old)
        _assert_blocks_equal(load_block(pn), new)


class TestTruncation:
    def _frame_file(self, tmp_path):
        blk = _numeric_block()
        p = str(tmp_path / "t.blk")
        save_block(blk, p)
        return p

    def test_truncated_footer_raises(self, tmp_path):
        p = self._frame_file(tmp_path)
        size = os.path.getsize(p)
        with open(p, "r+b") as f:
            f.truncate(size - 7)  # clip the trailer mid-struct
        with pytest.raises(FrameFormatError, match="trailer|truncated"):
            list(iter_block_windows(p))

    def test_truncated_mid_frames_raises(self, tmp_path):
        p = self._frame_file(tmp_path)
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) // 2)
        with pytest.raises(FrameFormatError):
            list(iter_block_windows(p))

    def test_corrupt_footer_pickle_raises(self, tmp_path):
        p = self._frame_file(tmp_path)
        r = FrameReader(p)
        r.close()
        size = os.path.getsize(p)
        with open(p, "r+b") as f:
            f.seek(size - 20)
            f.write(b"\xff" * 8)  # stomp the footer bytes
        with pytest.raises(FrameFormatError):
            FrameReader(p)


class TestParallelDecode:
    def test_prefetch_matches_serial(self, tmp_path, fresh_settings):
        """Parallel frame decompress (prefetch on the shared executor)
        must be byte-exact with the serial whole-block inflate."""
        settings.spill_compress = "always"
        blk = _object_block(6 * SPILL_WINDOW + 13)
        p = str(tmp_path / "par.blk")
        save_block(blk, p)

        settings.spill_read_prefetch = 0
        serial = Block.concat(list(iter_block_windows(p)))
        settings.spill_read_prefetch = 4
        parallel = Block.concat(list(iter_block_windows(p)))
        _assert_blocks_equal(serial, parallel)
        _assert_blocks_equal(parallel, blk)

    def test_abandoned_prefetch_iterator_is_safe(self, tmp_path,
                                                 fresh_settings):
        settings.spill_read_prefetch = 4
        blk = _numeric_block(8 * SPILL_WINDOW)
        p = str(tmp_path / "ab.blk")
        save_block(blk, p)
        it = iter_block_windows(p)
        first = next(it)
        assert len(first) == SPILL_WINDOW
        it.close()  # abandon mid-stream: no fd leak, no crash

    def test_random_access_read_frame(self, tmp_path):
        blk = _numeric_block(4 * SPILL_WINDOW)
        p = str(tmp_path / "ra.blk")
        save_block(blk, p)
        r = FrameReader(p)
        try:
            assert len(r) == 4
            # read the LAST frame without touching the first three
            keys, _v, _h1, _h2 = frames.load_window_payload(r.read_frame(3))
            assert np.array_equal(
                keys, blk.keys[3 * SPILL_WINDOW:4 * SPILL_WINDOW])
        finally:
            r.close()
