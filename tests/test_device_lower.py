"""Device-lowering equivalence suite (ISSUE 6 tentpole + satellites).

Pins the lowering contract at three levels:

- **program parity** (property-style): the jitted tokenize+hash+fold
  programs produce key/count/hash-lane output identical to the host
  scanners on randomized corpora, across batch cuts, long tokens,
  multibyte UTF-8, and the explicit fallback edges (invalid UTF-8
  windows, lines wider than a batch, forced hash collisions);
- **pipeline byte-identity**: TF-IDF-shaped pipelines read back
  identical results with lowering on vs off, under BOTH
  ``DAMPR_TPU_OPTIMIZE`` legs, and ineligible (opaque-UDF) stages pin to
  the host fallback with a recorded reason;
- **observability**: device-targeted stages emit ``device`` spans, the
  run summary carries the ``device`` section (fraction, h2d/d2h,
  device_stages), ``explain()`` renders per-stage targets, and the plan
  report gains ``device_stages``.
"""

import math
import operator
import os

import numpy as np
import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.ops import hashing
from dampr_tpu.ops import lower as ops_lower
from dampr_tpu.ops.text import DocFreq, TokenCounts
from dampr_tpu.plan import lower as plan_lower


@pytest.fixture(autouse=True)
def lowering_on():
    """Force the lowering pass on (explicit, so no backend probe) and
    restore every knob after."""
    old = (settings.lower, settings.lower_batch,
           settings.lower_pallas_segfold, settings.optimize)
    settings.lower = "1"
    yield
    (settings.lower, settings.lower_batch,
     settings.lower_pallas_segfold, settings.optimize) = old


def _dict_of(blocks, pair_values):
    d = {}
    for b in blocks:
        for k, v in zip(b.keys, b.values):
            d[k] = d.get(k, 0) + (v[1] if pair_values else int(v))
    return d


def _host_dict(mapper, data):
    sink = mapper.window_sink()
    blks = list(sink.add(data)) + list(sink.finish())
    return _dict_of(blks, mapper.pair_values)


def _device_dict(mapper, data):
    sink = ops_lower.device_window_sink(mapper)
    assert sink is not None
    blks = list(sink.add(data)) + list(sink.finish())
    return _dict_of(blks, mapper.pair_values), sink


def _corpus(seed, n_lines=300, exotic=False):
    rng = np.random.RandomState(seed)
    words = ["w%d" % i for i in range(120)] + ["Tok_1", "UPPER", "a"]
    if exotic:
        words += ["x" * 300, "émoji", "naïve", "日本語", "mixedÉcase"]
    lines = [" ".join(rng.choice(words, size=rng.randint(1, 10)))
             for _ in range(n_lines)]
    return ("\n".join(lines) + "\n").encode()


SCANNERS = [
    TokenCounts(mode="whitespace", lower=False, pair_values=False),
    TokenCounts(mode="word", lower=True, pair_values=True),
    DocFreq(mode="word", lower=True, pair_values=False),
    DocFreq(mode="whitespace", lower=False, pair_values=True),
]


class TestProgramParity:
    @pytest.mark.parametrize("case", range(4))
    def test_counts_match_host_scanner(self, case):
        mapper = SCANNERS[case]
        for seed in (1, 2):
            data = _corpus(10 * case + seed, exotic=(seed == 2))
            dev, sink = _device_dict(mapper, data)
            assert dev == _host_dict(mapper, data)
            assert sink.batches >= 1

    def test_small_batches_cut_at_line_boundaries(self):
        data = _corpus(7, n_lines=400)
        for mapper in SCANNERS[1:3]:
            settings.lower_batch = 64  # floor of 1024 applies
            dev, sink = _device_dict(mapper, data)
            settings.lower_batch = 1 << 18
            assert sink.batches > 1
            assert dev == _host_dict(mapper, data)

    def test_hash_lanes_match_engine_hash(self):
        data = _corpus(3)
        mapper = DocFreq(mode="word", lower=True, pair_values=False)
        sink = ops_lower.device_window_sink(mapper)
        for b in sink.add(data):
            h1, h2 = hashing.hash_keys(b.keys)
            assert np.array_equal(h1, b.h1)
            assert np.array_equal(h2, b.h2)

    def test_invalid_utf8_window_falls_back_whole(self):
        data = b"alpha \xff\xfe beta\nbeta \xff gamma\n"
        mapper = DocFreq(mode="word", lower=True, pair_values=False)
        dev, sink = _device_dict(mapper, data)
        assert sink.fallbacks >= 1
        assert dev == _host_dict(mapper, data)

    def test_line_wider_than_batch_falls_back(self):
        settings.lower_batch = 0  # floor 1024
        wide = (" ".join("t%d" % (i % 5) for i in range(4000)) + "\n").encode()
        mapper = DocFreq(mode="word", lower=True, pair_values=False)
        dev, sink = _device_dict(mapper, wide)
        settings.lower_batch = 1 << 18
        assert sink.fallbacks >= 1
        assert dev == _host_dict(mapper, wide)

    def test_wide_line_with_long_token_counts_once(self):
        """The whole-window fallback recounts long tokens — staged
        long-token partials must be discarded, not double-counted."""
        settings.lower_batch = 0  # floor 1024
        big = "y" * 300
        wide = ((big + " " + " ".join("t%d" % (i % 5) for i in range(3000))
                 + " " + big) + "\n").encode()
        for mapper in (DocFreq(mode="word", lower=True, pair_values=False),
                       TokenCounts(mode="word", lower=True,
                                   pair_values=False)):
            dev, sink = _device_dict(mapper, wide)
            if mapper.__class__ is DocFreq:
                # only per-line dedup needs the whole-window fallback;
                # TokenCounts cuts the line into batches freely
                assert sink.fallbacks >= 1
            assert dev == _host_dict(mapper, wide)
        settings.lower_batch = 1 << 18

    def test_empty_and_blank_windows(self):
        mapper = TokenCounts(mode="word", lower=True, pair_values=False)
        for data in (b"", b"  \t \n \n", b"\n\n"):
            dev, _sink = _device_dict(mapper, data)
            assert dev == _host_dict(mapper, data)

    def test_forced_collision_regroups_exactly(self, monkeypatch):
        """A reported 64-bit collision re-groups the batch on host by
        exact token bytes — results cannot change."""
        real = ops_lower._token_fold_jit

        def lying(n, L, dedup, pallas, interpret):
            fn = real(n, L, dedup, pallas, interpret)

            def wrapped(mat, lens, lines):
                out = list(fn(mat, lens, lines))
                out[-1] = np.int32(1)  # claim a collision happened
                return tuple(out)

            return wrapped

        monkeypatch.setattr(ops_lower, "_token_fold_jit", lying)
        data = _corpus(11)
        for mapper in (TokenCounts(mode="word", lower=True,
                                   pair_values=False),
                       DocFreq(mode="word", lower=True, pair_values=False)):
            dev, sink = _device_dict(mapper, data)
            assert sink.fallbacks >= 1
            assert dev == _host_dict(mapper, data)

    def test_pallas_segfold_path_matches(self):
        settings.lower_pallas_segfold = True
        try:
            data = _corpus(13)
            mapper = TokenCounts(mode="word", lower=True, pair_values=False)
            dev, _sink = _device_dict(mapper, data)
            assert dev == _host_dict(mapper, data)
        finally:
            settings.lower_pallas_segfold = False

    def test_claims_rejects_subclasses_and_unknown(self):
        class Odd(TokenCounts):
            pass

        assert ops_lower.claims(Odd()) is None
        assert ops_lower.claims(object()) is None
        assert ops_lower.claims(TokenCounts()) is not None


def _tfidf(corpus, name):
    docs = Dampr.text(corpus, os.path.getsize(corpus) // 3 + 1)
    doc_freq = (docs.custom_mapper(
        DocFreq(mode="word", lower=True, pair_values=False))
        .fold_values(operator.add))
    idf = doc_freq.cross_right(
        docs.len(),
        lambda df, total: (df[0], df[1],
                           math.log(1 + (float(total) / df[1]))),
        memory=True)
    em = idf.run(name=name)
    got = em.read()
    stats = em.stats()
    em.delete()
    return got, stats


@pytest.fixture
def corpus(tmp_path):
    path = str(tmp_path / "corpus.txt")
    with open(path, "wb") as f:
        f.write(_corpus(21, n_lines=600))
    return path


class TestPipelineEquivalence:
    @pytest.mark.parametrize("optimize", [True, False])
    def test_tfidf_byte_identical_both_legs(self, corpus, optimize):
        settings.optimize = optimize
        settings.lower = "1"
        dev, s_dev = _tfidf(corpus, "lowertest-dev-%d" % optimize)
        settings.lower = "0"
        host, s_host = _tfidf(corpus, "lowertest-host-%d" % optimize)
        assert dev == host
        assert s_dev["device"]["device_stages"] >= 1
        assert s_dev["device"]["device_fraction"] > 0
        assert s_host["device"]["device_stages"] == 0
        targets = {st["stage"]: st["target"] for st in s_dev["stages"]}
        assert "device" in targets.values()
        assert all(st["target"] == "host" for st in s_host["stages"])

    def test_word_count_shape(self, corpus):
        def run():
            em = (Dampr.text(corpus, os.path.getsize(corpus) // 2 + 1)
                  .custom_mapper(TokenCounts(mode="whitespace",
                                             pair_values=False))
                  .fold_values(operator.add)
                  .run(name="lowertest-wc"))
            got = em.read()
            em.delete()
            return got

        settings.lower = "1"
        dev = run()
        settings.lower = "0"
        assert dev == run()

    def test_ineligible_udf_falls_back_with_reason(self, corpus):
        """An opaque per-record UDF after the scanner keeps the whole
        fused stage on host — and the decision records why.  The UDF
        branches on its value, so the widened jax-traceability
        vocabulary (dampr_tpu.analyze.jaxtrace) rejects it too — the
        abstract eval hits the data-dependent ``if``."""
        docs = Dampr.text(corpus, os.path.getsize(corpus))
        pipe = (docs.custom_mapper(
            DocFreq(mode="word", lower=True, pair_values=False))
            .map(lambda c: c * 2 if c > 0 else -c)
            .fold_values(operator.add))
        em = pipe.run(name="lowertest-udf")
        got_dev = em.read()
        stats = em.stats()
        em.delete()
        # the fused scanner+UDF map stage must NOT have lowered
        map_targets = [st["target"] for st in stats["stages"]
                       if st["kind"] == "map"]
        assert "device" not in map_targets
        decisions = stats["plan"]["lowering"]["targets"]
        reasons = [d["reason"] for d in decisions
                   if d["kind"] == "map" and d["target"] == "host"]
        assert any("vocabulary" in r or "opaque" in r for r in reasons)
        settings.lower = "0"
        em = pipe.run(name="lowertest-udf-host")
        assert got_dev == em.read()
        em.delete()

    def test_memory_input_marked_device_still_exact(self):
        """A device-marked scanner over a non-byte input takes the
        per-record fallback inside the job — results unchanged."""
        lines = ["a b c", "b c", "c c a"]
        pipe = (Dampr.memory(lines)
                .custom_mapper(DocFreq(mode="word", lower=True,
                                       pair_values=False))
                .fold_values(operator.add))
        em = pipe.run(name="lowertest-mem")
        dev = em.read()
        em.delete()
        settings.lower = "0"
        em = pipe.run(name="lowertest-mem-host")
        assert dev == em.read()
        em.delete()

    def test_per_stage_kill_switch(self, corpus):
        docs = Dampr.text(corpus, os.path.getsize(corpus))
        pipe = (docs.custom_mapper(
            DocFreq(mode="word", lower=True, pair_values=False),
            lower=False)
            .fold_values(operator.add))
        em = pipe.run(name="lowertest-kill")
        stats = em.stats()
        em.delete()
        decisions = stats["plan"]["lowering"]["targets"]
        killed = [d for d in decisions if "lower=False" in d["reason"]]
        assert killed, decisions


class TestGranularityGuards:
    """Device batching regroups partial counts (batch vs window
    granularity) — only summing consumers are invariant to it, so
    anything else must pin the scanner to host."""

    def test_min_fold_stays_host_and_matches(self, corpus):
        def build():
            return (Dampr.text(corpus, os.path.getsize(corpus))
                    .custom_mapper(DocFreq(mode="word", lower=True,
                                           pair_values=False))
                    .fold_values(min))

        decisions = plan_lower.analyze(build().pmer.graph)
        map_targets = [d for d in decisions if d["kind"] == "map"]
        assert all(d["target"] == "host" for d in map_targets), decisions
        settings.lower = "1"
        em = build().run(name="lowertest-min")
        dev = em.read()
        assert all(st["target"] == "host" for st in em.stats()["stages"]
                   if st["kind"] == "map")
        em.delete()
        settings.lower = "0"
        em = build().run(name="lowertest-min-host")
        assert dev == em.read()
        em.delete()

    def test_branched_consumer_pins_host(self, corpus):
        """A second, non-fold consumer of the scanner output would
        observe the partial grouping — the scanner must not lower."""
        docs = Dampr.text(corpus, os.path.getsize(corpus))
        x = docs.custom_mapper(DocFreq(mode="word", lower=True,
                                       pair_values=False))
        folded = x.fold_values(operator.add)
        branch = x.filter(lambda c: c > 1)
        graph = folded.pmer.graph.union(branch.pmer.graph)
        decisions = plan_lower.analyze(graph)
        scanner = [d for d in decisions if d["kind"] == "map"][0]
        assert scanner["target"] == "host"
        assert "granularity" in scanner["reason"]

    def test_requested_output_pins_host(self, corpus):
        """Reading the scanner output directly exposes the partials."""
        docs = Dampr.text(corpus, os.path.getsize(corpus))
        x = docs.custom_mapper(DocFreq(mode="word", lower=True,
                                       pair_values=False))
        decisions = plan_lower.analyze(x.pmer.graph, outputs=[x.source])
        scanner = [d for d in decisions if d["kind"] == "map"][0]
        assert scanner["target"] == "host"

    def test_sum_combiner_still_lowers(self, corpus):
        """With a hoisted sum combiner the job output is fold-compacted
        identically on both legs — eligibility is unaffected."""
        docs = Dampr.text(corpus, os.path.getsize(corpus))
        pipe = (docs.custom_mapper(DocFreq(mode="word", lower=True,
                                           pair_values=False))
                .fold_values(operator.add))
        from dampr_tpu.plan import passes

        optimized, _report = passes.optimize(pipe.pmer.graph, [pipe.source])
        decisions = plan_lower.analyze(optimized, outputs=[pipe.source])
        assert any(d["target"] == "device" and d["kind"] == "map"
                   for d in decisions), decisions


class TestPlanAnalysis:
    def test_history_pins_tiny_stage_to_host(self, corpus):
        docs = Dampr.text(corpus, os.path.getsize(corpus))
        pipe = (docs.custom_mapper(
            DocFreq(mode="word", lower=True, pair_values=False))
            .fold_values(operator.add))
        graph = pipe.pmer.graph
        base_decisions = plan_lower.analyze(graph)
        dev_sids = [d["sid"] for d in base_decisions
                    if d["target"] == "device" and d["kind"] == "map"]
        assert dev_sids
        history = {"stages": [{"stage": dev_sids[0], "records_out": 3}]}
        pinned = plan_lower.analyze(graph, history)
        got = {d["sid"]: d for d in pinned}[dev_sids[0]]
        assert got["target"] == "host"
        assert "lower_min_records" in got["reason"]

    def test_forced_lowering_ignores_history_floor(self, corpus,
                                                   tmp_path):
        """An explicit DAMPR_TPU_LOWER=1 wins over accumulated run
        history: the stats floor (lower_min_records) is an AUTO-mode
        behavior, so a tiny prior run recorded in the history corpus
        must not silently pin a forced run's eligible stage back to
        host (regression: the corpus — which untraced runs now feed —
        would otherwise flip device_stages to 0 on every rerun of a
        small named pipeline)."""
        from dampr_tpu.obs import history as obs_history

        old_scratch = settings.scratch_root
        settings.scratch_root = str(tmp_path / "scratch")
        settings.lower = "1"
        try:
            name = "lowertest-forced-history"

            def pipe():
                docs = Dampr.text(corpus, os.path.getsize(corpus))
                return (docs.custom_mapper(
                    DocFreq(mode="word", lower=True, pair_values=False))
                    .fold_values(operator.add))

            em1 = pipe().run(name)
            s1 = em1.stats()
            em1.delete()
            assert s1["device"]["device_stages"] >= 1, s1["device"]
            # the finalized run recorded tiny history under this name...
            assert obs_history.load(name)
            # ...and a rerun STILL lowers (forced mode skips the floor)
            em2 = pipe().run(name)
            s2 = em2.stats()
            em2.delete()
            assert s2["device"]["device_stages"] >= 1, s2["device"]
        finally:
            settings.lower = "auto"
            settings._resolved_lower = None
            settings.scratch_root = old_scratch

    def test_explain_renders_targets(self, corpus):
        docs = Dampr.text(corpus, os.path.getsize(corpus))
        pipe = (docs.custom_mapper(
            DocFreq(mode="word", lower=True, pair_values=False))
            .fold_values(operator.add))
        text = pipe.explain()
        assert "targets:" in text
        assert "-> device" in text
        assert "jitted" in text
        settings.lower = "0"
        text = pipe.explain()
        assert "device lowering off" in text

    def test_optimize_off_leg_still_analyzed(self, corpus):
        settings.optimize = False
        docs = Dampr.text(corpus, os.path.getsize(corpus))
        pipe = (docs.custom_mapper(
            DocFreq(mode="word", lower=True, pair_values=False))
            .fold_values(operator.add))
        text = pipe.explain()
        assert "optimizer OFF" in text
        assert "-> device" in text


class TestObservability:
    def test_device_span_and_stats_section(self, corpus, tmp_path):
        old_trace, old_dir = settings.trace, settings.trace_dir
        old_handoff = settings.handoff
        settings.trace = True
        settings.trace_dir = str(tmp_path / "traces")
        # This test pins the CLASSIC device-lowered surface (device
        # spans, boundary bytes); the handoff tier replaces exactly
        # those on its edge and has its own pins (test_handoff).
        settings.handoff = "off"
        try:
            _got, stats = _tfidf(corpus, "lowertest-traced")
        finally:
            settings.trace = old_trace
            settings.trace_dir = old_dir
            settings.handoff = old_handoff
        assert stats["device"]["device_stages"] >= 1
        assert stats["device"]["h2d_bytes"] > 0
        assert stats["device"]["d2h_bytes"] > 0
        spans = stats.get("spans") or {}
        assert "device" in spans, spans
        # the emitted trace validates against the checked-in schema
        import subprocess
        import sys
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        res = subprocess.run(
            [sys.executable, os.path.join(root, "tools",
                                          "validate_trace.py"),
             stats["trace_file"],
             "--schema", os.path.join(root, "docs", "trace_schema.json"),
             "--require-cats", "device,stage,fold"],
            capture_output=True, text=True)
        assert res.returncode == 0, res.stdout + res.stderr
