"""Layer tests for blocks / hashing / segment kernels — coverage the reference
lacks (SURVEY.md §4: 'add the layer-level tests Dampr lacks')."""

import numpy as np
import pytest

from dampr_tpu import settings
from dampr_tpu.blocks import Block, BlockBuilder
from dampr_tpu.ops import hashing, segment


def _mk_pairs(n, n_keys=7):
    return [("key-%d" % (i % n_keys), i) for i in range(n)]


class TestHashing(object):
    def test_str_hash_deterministic(self):
        keys = ["alpha", "beta", "gamma", "alpha", ""]
        h1a, h2a = hashing.hash_keys(keys)
        h1b, h2b = hashing.hash_keys(list(keys))
        assert np.array_equal(h1a, h1b) and np.array_equal(h2a, h2b)
        assert h1a[0] == h1a[3] and h2a[0] == h2a[3]
        assert h1a[0] != h1a[1] or h2a[0] != h2a[1]

    def test_device_matches_numpy(self):
        keys = ["w%d" % (i % 997) for i in range(9000)]
        old = settings.device_min_batch
        try:
            settings.device_min_batch = 1 << 30  # force numpy
            h1n, h2n = hashing.hash_keys(keys)
            settings.device_min_batch = 1  # force device
            h1d, h2d = hashing.hash_keys(keys)
        finally:
            settings.device_min_batch = old
        assert np.array_equal(h1n, h1d)
        assert np.array_equal(h2n, h2d)

    def test_int_float_bool_equivalence(self):
        # Python equality semantics: 1 == 1.0 == True group together
        h1, h2 = hashing.hash_keys([1, 1.0, True, 2])
        assert h1[0] == h1[1] == h1[2]
        assert h2[0] == h2[1] == h2[2]
        assert (h1[3], h2[3]) != (h1[0], h2[0])

    def test_int_array_path(self):
        arr = np.arange(5000, dtype=np.int64)
        h1, h2 = hashing.hash_keys(arr)
        assert len(np.unique(hashing.combine64(h1, h2))) == 5000

    def test_tuple_keys_fallback(self):
        keys = [(1, "a"), (2, "b"), (1, "a")]
        h1, h2 = hashing.hash_keys(keys)
        assert h1[0] == h1[2] and h2[0] == h2[2]


class TestBlock(object):
    def test_from_pairs_numeric(self):
        b = Block.from_pairs([("a", 1), ("b", 2), ("a", 3)])
        assert b.numeric_values and not b.numeric_keys
        assert list(b.iter_pairs()) == [("a", 1), ("b", 2), ("a", 3)]

    def test_from_pairs_object_values(self):
        b = Block.from_pairs([("a", [1, 2]), ("b", {"x": 1})])
        assert not b.numeric_values
        assert list(b.iter_pairs()) == [("a", [1, 2]), ("b", {"x": 1})]

    def test_bigint_values_fall_back_to_object(self):
        b = Block.from_pairs([("a", 2 ** 100), ("b", 1)])
        assert not b.numeric_values
        assert b.values[0] == 2 ** 100

    def test_concat_mixed(self):
        b1 = Block.from_pairs([("a", 1)])
        b2 = Block.from_pairs([("b", [2])])
        b = Block.concat([b1, b2])
        assert len(b) == 2 and not b.numeric_values

    def test_split_by_partition_routes_consistently(self):
        b = Block.from_pairs(_mk_pairs(500))
        parts = b.split_by_partition(8)
        assert sum(len(p) for p in parts.values()) == 500
        # same key always lands in the same partition
        key_part = {}
        for pid, pb in parts.items():
            for k, _ in pb.iter_pairs():
                assert key_part.setdefault(k, pid) == pid

    def test_builder_batches(self):
        bb = BlockBuilder(batch_size=100)
        out = []
        for k, v in _mk_pairs(250):
            blk = bb.add(k, v)
            if blk is not None:
                out.append(blk)
        tail = bb.flush()
        if tail is not None:
            out.append(tail)
        assert sum(len(b) for b in out) == 250
        assert len(out) == 3


class TestSegment(object):
    def test_sort_and_group_exact(self):
        pairs = _mk_pairs(1000, n_keys=13)
        g = segment.sort_and_group(Block.from_pairs(pairs))
        got = dict(g.iter_groups())
        want = {}
        for k, v in pairs:
            want.setdefault(k, []).append(v)
        assert set(got) == set(want)
        for k in want:
            assert sorted(got[k]) == sorted(want[k])

    @pytest.mark.parametrize("op,fn", [
        (segment.SUM, sum), (segment.MIN, min), (segment.MAX, max)])
    def test_fold_matches_python(self, op, fn):
        pairs = _mk_pairs(5000, n_keys=37)
        fb = segment.fold_block(Block.from_pairs(pairs), op)
        got = dict(fb.iter_pairs())
        want = {}
        for k, v in pairs:
            want.setdefault(k, []).append(v)
        want = {k: fn(vs) for k, vs in want.items()}
        assert got == want

    def test_fold_device_matches_host(self):
        pairs = _mk_pairs(8192, n_keys=201)
        old = settings.device_min_batch
        try:
            settings.device_min_batch = 1
            dev = dict(segment.fold_block(Block.from_pairs(pairs), segment.SUM).iter_pairs())
            settings.device_min_batch = 1 << 30
            host = dict(segment.fold_block(Block.from_pairs(pairs), segment.SUM).iter_pairs())
        finally:
            settings.device_min_batch = old
        assert dev == host

    def test_opaque_binop_fold(self):
        pairs = [("k%d" % (i % 3), [i]) for i in range(30)]
        fb = segment.fold_block(Block.from_pairs(pairs), segment.as_assoc_op(
            lambda a, b: a + b))
        got = dict(fb.iter_pairs())
        for k, vs in got.items():
            assert isinstance(vs, list) and len(vs) == 10

    def test_hash_collision_repair(self):
        # Force a collision by monkeypatching two distinct keys to equal hashes
        b = Block.from_pairs([("aa", 1), ("bb", 2), ("aa", 3), ("bb", 4)])
        h1, h2 = b.hashes()
        b.h1 = np.zeros_like(h1)
        b.h2 = np.zeros_like(h2)
        g = segment.sort_and_group(b)
        got = dict((k, sorted(v)) for k, v in g.iter_groups())
        assert got == {"aa": [1, 3], "bb": [2, 4]}

    def test_empty_block(self):
        g = segment.sort_and_group(Block.empty())
        assert list(g.iter_groups()) == []
        fb = segment.fold_block(Block.empty(), segment.SUM)
        assert len(fb) == 0
