"""DP SGD (psum gradients) on the mesh rig + driver-hook smoke tests."""

import numpy as np

from dampr_tpu.parallel import sgd


class TestSGD:
    def test_single_step_gradient_matches_host(self, mesh8):
        # One step on 8 devices == closed-form logistic gradient step.
        rng = np.random.RandomState(1)
        X = rng.randn(128, 16).astype(np.float32)
        w = rng.randn(16).astype(np.float32)
        y = (X @ w > 0).astype(np.float32)
        p0 = sgd.init_params(16)

        p1, loss = sgd.train_step(mesh8, p0, X, y, lr=0.5)

        logits = X @ p0["w"] + p0["b"]
        s = 1.0 / (1.0 + np.exp(-logits))
        gl = (s - y) / len(y)
        np.testing.assert_allclose(
            np.asarray(p1["w"]), p0["w"] - 0.5 * (X.T @ gl),
            rtol=1e-4, atol=1e-6)
        want_loss = np.mean(np.maximum(logits, 0) - logits * y
                            + np.log1p(np.exp(-np.abs(logits))))
        assert abs(float(loss) - want_loss) < 1e-5

    def test_eight_device_trajectory_matches_one_device(self, mesh8):
        # Same f32 program on 8 devices vs 1 device: psum of shard-means must
        # equal the global mean, so trajectories stay together.
        import jax
        from jax.sharding import Mesh

        rng = np.random.RandomState(5)
        X = rng.randn(64, 8).astype(np.float32)
        w = rng.randn(8).astype(np.float32)
        y = (X @ w > 0).astype(np.float32)

        mesh1 = Mesh(np.asarray(jax.devices()[:1]), ("shards",))
        p8, l8 = sgd.train(mesh8, X, y, n_steps=10, lr=0.5)
        p1, l1 = sgd.train(mesh1, X, y, n_steps=10, lr=0.5)
        np.testing.assert_allclose(p8["w"], p1["w"], rtol=1e-3, atol=1e-5)
        assert abs(l8 - l1) < 1e-4

    def test_accuracy_improves(self, mesh8):
        rng = np.random.RandomState(2)
        X = rng.randn(256, 8).astype(np.float32)
        w = rng.randn(8).astype(np.float32)
        y = (X @ w > 0).astype(np.float32)
        params, _ = sgd.train(mesh8, X, y, n_steps=40, lr=1.0)
        pred = (X @ params["w"] + params["b"]) > 0
        assert (pred == (y > 0.5)).mean() > 0.9


class TestGraftEntry:
    def test_entry_jits(self):
        import sys
        sys.path.insert(0, "/root/repo")
        import jax

        import __graft_entry__ as g
        fn, args = g.entry()
        folded, loss = jax.jit(fn)(*args)
        assert folded.shape == (4096,)
        assert np.isfinite(float(loss))

    def test_dryrun_multichip_8(self):
        import sys
        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as g
        g.dryrun_multichip(8)
