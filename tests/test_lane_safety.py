"""32-bit device-lane safety: large int64/float64 folds must be exact (host
fallback) or refuse loudly — never silently truncate."""

import numpy as np
import pytest

from dampr_tpu.blocks import Block
from dampr_tpu.ops import segment
from dampr_tpu.parallel import mesh_global_sum, mesh_keyed_fold
from dampr_tpu.ops import hashing


class TestSegmentFoldLanes:
    def test_large_int64_sum_exact(self):
        n = 5000  # >= device_min_batch, would truncate on 32-bit lanes
        blk = Block.from_pairs([("k", 2 ** 40)] * n)
        out = dict(segment.fold_block(blk, segment.SUM).iter_pairs())
        assert out == {"k": n * 2 ** 40}

    def test_int32_range_sum_overflow_guarded(self):
        n = 5000
        blk = Block.from_pairs([("k", 10 ** 6)] * n)
        out = dict(segment.fold_block(blk, segment.SUM).iter_pairs())
        assert out == {"k": n * 10 ** 6}  # 5e9 > int32 max

    def test_float64_sum_keeps_precision(self):
        n = 5000
        blk = Block.from_pairs([("k", 1.0 + 1e-12)] * n)
        out = dict(segment.fold_block(blk, segment.SUM).iter_pairs())
        assert abs(out["k"] - n * (1.0 + 1e-12)) < 1e-6

    def test_small_ints_still_use_device(self):
        n = 5000
        blk = Block.from_pairs([("a", 1)] * n + [("b", 2)] * n)
        out = dict(segment.fold_block(blk, segment.SUM).iter_pairs())
        assert out == {"a": n, "b": 2 * n}

    def test_min_max_large_values(self):
        n = 5000
        vals = [2 ** 40 + i for i in range(n)]
        blk = Block.from_pairs([("k", v) for v in vals])
        assert dict(segment.fold_block(blk, segment.MIN).iter_pairs()) == {
            "k": 2 ** 40}
        assert dict(segment.fold_block(blk, segment.MAX).iter_pairs()) == {
            "k": 2 ** 40 + n - 1}


class TestInt32Columns:
    """int32 *columns* (e.g. custom map_blocks mappers) must sum exactly —
    narrow lanes promote to int64 before any fold (ADVICE r2)."""

    def test_int32_column_sum_promotes(self):
        n = 5000
        keys = np.zeros(n, dtype=np.int64)
        vals = np.full(n, 10 ** 6, dtype=np.int32)  # sum 5e9 wraps in int32
        blk = Block(keys, vals)
        out = dict(segment.fold_block(blk, segment.SUM).iter_pairs())
        assert out == {0: n * 10 ** 6}

    def test_uint16_column_sum_promotes(self):
        n = 4096
        blk = Block(np.zeros(n, dtype=np.int64),
                    np.full(n, 60000, dtype=np.uint16))
        out = dict(segment.fold_block(blk, segment.SUM).iter_pairs())
        assert out == {0: n * 60000}

    def test_uint64_column_folds_exact(self):
        n = 4096
        blk = Block(np.zeros(n, dtype=np.int64),
                    np.full(n, 2 ** 40, dtype=np.uint64))
        assert dict(segment.fold_block(blk, segment.SUM).iter_pairs()) == {
            0: n * 2 ** 40}
        assert dict(segment.fold_block(blk, segment.MAX).iter_pairs()) == {
            0: 2 ** 40}
        assert dict(segment.fold_block(blk, segment.MIN).iter_pairs()) == {
            0: 2 ** 40}

    def test_uint64_beyond_int64_exact(self):
        big = 2 ** 63 + 5
        blk = Block(np.zeros(3, dtype=np.int64),
                    np.array([big, big, big], dtype=np.uint64))
        assert dict(segment.fold_block(blk, segment.SUM).iter_pairs()) == {
            0: 3 * big}
        assert dict(segment.fold_block(blk, segment.MAX).iter_pairs()) == {
            0: big}

    def test_uint64_aggregate_overflow_exact(self):
        # per-element fits int64 but the sum exceeds it: must not wrap
        blk = Block(np.zeros(4, dtype=np.int64),
                    np.full(4, 2 ** 62, dtype=np.uint64))
        assert dict(segment.fold_block(blk, segment.SUM).iter_pairs()) == {
            0: 4 * 2 ** 62}

    def test_int32_minmax_stay_narrow(self):
        blk = Block(np.zeros(4, dtype=np.int64),
                    np.array([3, -7, 5, 1], dtype=np.int32))
        assert dict(segment.fold_block(blk, segment.MIN).iter_pairs()) == {0: -7}
        assert dict(segment.fold_block(blk, segment.MAX).iter_pairs()) == {0: 5}


class TestMeshLanes:
    def test_keyed_fold_int32_overflow_raises(self, mesh8):
        h1, h2 = hashing.hash_keys(np.array([1] * 10))
        with pytest.raises(ValueError, match="32-bit"):
            mesh_keyed_fold(mesh8, h1, h2,
                            np.full(10, 2 ** 30, dtype=np.int32), "sum")

    def test_keyed_fold_int32_in_range_ok(self, mesh8):
        h1, h2 = hashing.hash_keys(np.array([1] * 10))
        fh1, fh2, fv = mesh_keyed_fold(
            mesh8, h1, h2, np.full(10, 7, dtype=np.int32), "sum")
        assert fv.tolist() == [70]

    def test_keyed_fold_uint64_overflow_raises(self, mesh8):
        h1, h2 = hashing.hash_keys(np.array([1] * 8))
        with pytest.raises(ValueError, match="lanes"):
            mesh_keyed_fold(mesh8, h1, h2,
                            np.full(8, 2 ** 40, dtype=np.uint64), "sum")
        with pytest.raises(ValueError, match="lanes"):
            mesh_keyed_fold(mesh8, h1, h2,
                            np.full(8, 2 ** 40, dtype=np.uint64), "max")

    def test_keyed_fold_uint32_and_uint16_exact(self, mesh8):
        h1, h2 = hashing.hash_keys(np.array([1] * 8))
        _, _, fv = mesh_keyed_fold(
            mesh8, h1, h2, np.full(8, 60000, dtype=np.uint16), "sum")
        assert fv.tolist() == [480000]
        with pytest.raises(ValueError, match="lanes"):
            mesh_keyed_fold(mesh8, h1, h2,
                            np.full(8, 2 ** 30, dtype=np.uint32), "sum")

    def test_keyed_fold_large_int_raises(self, mesh8):
        h1, h2 = hashing.hash_keys(np.array([1] * 10))
        with pytest.raises(ValueError, match="32-bit"):
            mesh_keyed_fold(mesh8, h1, h2,
                            np.full(10, 10 ** 9, dtype=np.int64), "sum")

    def test_keyed_fold_float64_raises(self, mesh8):
        h1, h2 = hashing.hash_keys(np.array([1] * 4))
        with pytest.raises(ValueError, match="float32"):
            mesh_keyed_fold(mesh8, h1, h2, np.ones(4, dtype=np.float64), "sum")

    def test_global_sum_large_int_raises(self, mesh8):
        with pytest.raises(ValueError, match="32-bit"):
            mesh_global_sum(mesh8, np.array([2 ** 40, 5], dtype=np.int64))

    def test_global_sum_near_limit_exact(self, mesh8):
        vals = np.full(1000, 2 ** 20, dtype=np.int64)  # sum ~1e9 < 2**31
        assert mesh_global_sum(mesh8, vals) == 1000 * 2 ** 20


class TestX64ScanGuards:
    def test_x64_scan_lowering_guards(self):
        # Under jax_enable_x64 the _lane_safe_values int32 cast (and its
        # abs-sum proof) is skipped, so the scan lowering must re-check the
        # global-cumsum bound and refuse unsigned dtypes (its -1 sentinel
        # wraps).  Runs in a subprocess because x64 is process-global.
        import os
        import subprocess
        import sys

        code = (
            "import os\n"
            "os.environ['XLA_FLAGS'] = "
            "'--xla_force_host_platform_device_count=8'\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "jax.config.update('jax_enable_x64', True)\n"
            "import numpy as np\n"
            "from dampr_tpu import settings\n"
            "settings.device_min_batch = 1\n"
            "from dampr_tpu.ops import hashing\n"
            "from dampr_tpu.parallel import mesh_keyed_fold\n"
            "from dampr_tpu.parallel.mesh import data_mesh\n"
            "mesh = data_mesh()\n"
            "h1, h2 = hashing.hash_keys(np.arange(3))\n"
            "_, _, fv = mesh_keyed_fold(mesh, h1, h2,\n"
            "    np.array([1500000000] * 3, dtype=np.int32), 'sum')\n"
            "assert sorted(fv.tolist()) == [1500000000] * 3, fv\n"
            "_, _, fv = mesh_keyed_fold(mesh, h1, h2,\n"
            "    np.array([1, 2, 3], dtype=np.uint32), 'sum')\n"
            "assert sorted(fv.tolist()) == [1, 2, 3], fv\n"
            "print('OK')\n")
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            timeout=300)
        assert "OK" in r.stdout, (r.stdout, r.stderr)


class TestIndexerQuoting:
    def test_keys_with_quotes_do_not_crash(self, tmp_path):
        from dampr_tpu.utils import Indexer
        d = tmp_path / "docs"
        d.mkdir()
        (d / "doc.txt").write_text('say "hi" there\nplain line\n')
        idx = Indexer(str(d / "*.txt"))
        idx.build(lambda line: line.split())
        out = [l.strip() for l in idx.union(['"hi"']).read()]
        assert out == ['say "hi" there']
        # injection attempt returns nothing instead of executing
        evil = idx.union(['") ; drop table key_index; --']).read()
        assert evil == []
