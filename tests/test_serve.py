"""The serve daemon (dampr_tpu.serve): wire-form, scheduler fairness,
admission gate, isolation, coalescing, cancellation, drain.

Unit layers (wire/scheduler/check_bench) run in-process; the e2e tests
start a real :class:`ServeDaemon` on an ephemeral port and drive it
through :class:`ServeClient` over HTTP, with each job in its own worker
subprocess — the same shape production runs, scaled down.
"""

import importlib.util
import json
import os
import threading
import time

import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.serve import scheduler as sched_mod
from dampr_tpu.serve import wire
from dampr_tpu.serve.client import ServeClient, SubmitError
from dampr_tpu.serve.daemon import ServeDaemon

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HELPER_SCALE = 10


def _helper(x):
    return x * HELPER_SCALE


@pytest.fixture
def daemon(tmp_path):
    """A live daemon on an ephemeral loopback port (2 worker slots)."""
    d = ServeDaemon(port=0, state_dir=str(tmp_path / "serve"), workers=2)
    assert d.start() is not None
    yield d
    d.stop()


@pytest.fixture
def client(daemon):
    return ServeClient("http://127.0.0.1:{}".format(daemon.port))


def _plan(tag="t", items=20):
    return (Dampr.memory(list(range(items)))
            .map(lambda x: x * 3)
            .map(lambda x, t=tag: (t, x)))


# ---------------------------------------------------------------------------
# wire
# ---------------------------------------------------------------------------

class TestWire:
    def test_roundtrip_executes_identically(self):
        bias = 7

        def suffix(x):
            return _helper(x) + bias   # closure + module-global helper

        p = Dampr.memory(list(range(12))).map(lambda x: x + 1).map(suffix)
        data = wire.encode(p.pmer.graph, p.source)
        graph, source = wire.decode(data)
        from dampr_tpu.dampr import PBase

        rebuilt = PBase(source, Dampr(graph))
        assert (list(rebuilt.run(name="wire-rt-b").dataset.read())
                == list(p.run(name="wire-rt-a").dataset.read()))

    def test_python_version_mismatch_refused(self):
        import pickle

        env = {"wire": wire.WIRE_VERSION, "py": [2, 7],
               "graph": None, "source": None}
        with pytest.raises(wire.WireError, match="version mismatch"):
            wire.decode(pickle.dumps(env))

    def test_malformed_payload_refused(self):
        with pytest.raises(wire.WireError):
            wire.decode(b"not a pickle")
        with pytest.raises(wire.WireError, match="wire version"):
            import pickle

            wire.decode(pickle.dumps({"wire": 99}))

    def test_unserializable_capture_is_coded_wire_error(self):
        lock = threading.Lock()
        p = Dampr.memory([1]).map(lambda x: (lock, x)[1])
        with pytest.raises(wire.WireError, match="cannot be serialized"):
            wire.encode(p.pmer.graph, p.source)

    def test_fingerprint_stable_and_distinct(self):
        a1 = _plan("a")
        a2 = _plan("a")
        b = _plan("b")
        fp = lambda p: wire.plan_fingerprint(p.pmer.graph, p.source)
        assert fp(a1) == fp(a2)          # same logical plan -> same fp
        assert fp(a1) != fp(b)           # default-arg capture differs
        assert not wire.is_volatile(fp(a1))

    def test_estimate_input_bytes(self, tmp_path):
        f = tmp_path / "in.txt"
        f.write_text("x" * 4096)
        p_file = Dampr.text(str(f)).map(lambda s: s)
        est = wire.estimate_input_bytes(p_file.pmer.graph)
        assert est >= 4096
        p_mem = Dampr.memory(list(range(10))).map(lambda x: x)
        assert wire.estimate_input_bytes(p_mem.pmer.graph) == 10 * 128


# ---------------------------------------------------------------------------
# scheduler (pure state machine, no daemon)
# ---------------------------------------------------------------------------

def _job(jid, tenant, cost, fp=None):
    return sched_mod.Job(jid, tenant, fp or ("f" + jid), cost)


class TestScheduler:
    def test_budget_admission_and_release(self):
        s = sched_mod.Scheduler(tenant_budget=100, quantum=10,
                                queue_depth=8)
        j1 = _job("j1", "a", 60)
        j2 = _job("j2", "a", 60)
        s.admit(j1)
        with pytest.raises(sched_mod.AdmissionError) as ei:
            s.admit(j2)
        assert ei.value.reason == "budget"
        # A cancelled job releases its reservation immediately.
        assert s.remove_queued(j1)
        j1.state = "cancelled"
        s.release(j1)
        assert s.tenants["a"].reserved == 0
        s.admit(j2)   # fits now

    def test_queue_depth_rejects(self):
        s = sched_mod.Scheduler(tenant_budget=10**9, quantum=10,
                                queue_depth=2)
        s.admit(_job("j1", "a", 1))
        s.admit(_job("j2", "a", 1))
        with pytest.raises(sched_mod.AdmissionError) as ei:
            s.admit(_job("j3", "a", 1))
        assert ei.value.reason == "queue-full"

    def test_drr_byte_fairness_bounds_queue_wait(self):
        """A tenant flooding small jobs cannot starve a tenant with one
        job: deficit round-robin dispatches B within one round."""
        s = sched_mod.Scheduler(tenant_budget=10**9, quantum=100,
                                queue_depth=64)
        for i in range(10):
            s.admit(_job("a{}".format(i), "flood", 50))
        s.admit(_job("b0", "victim", 100))
        order = [s.next_job().id for _ in range(6)]
        assert "b0" in order[:3], order
        # And byte-fairness the other way: one big job cannot starve
        # small ones — they interleave, it does not go last.
        s2 = sched_mod.Scheduler(tenant_budget=10**9, quantum=100,
                                 queue_depth=64)
        s2.admit(_job("big", "heavy", 300))
        for i in range(3):
            s2.admit(_job("s{}".format(i), "light", 100))
        order2 = [s2.next_job().id for _ in range(4)]
        assert order2.index("big") < 3, order2

    def test_coalesce_target_lifecycle(self):
        s = sched_mod.Scheduler(tenant_budget=10**9, quantum=10,
                                queue_depth=8)
        j1 = _job("j1", "a", 1, fp="same")
        s.admit(j1)
        assert s.coalesce_target("same") is j1
        follower = _job("j2", "b", 1, fp="same")
        s.attach_follower(j1, follower)
        assert follower.state == "coalesced"
        assert follower.primary == "j1"
        assert j1.followers == ["j2"]
        j1.state = "done"
        s.release(j1)
        assert s.coalesce_target("same") is None

    def test_release_is_idempotent(self):
        s = sched_mod.Scheduler(tenant_budget=100, quantum=10,
                                queue_depth=8)
        j = _job("j1", "a", 40)
        s.admit(j)
        j.state = "done"
        s.release(j)
        s.release(j)
        assert s.tenants["a"].reserved == 0


# ---------------------------------------------------------------------------
# check_bench direction support (the p99 gate rides this)
# ---------------------------------------------------------------------------

class TestCheckBenchDirection:
    @pytest.fixture(scope="class")
    def cb(self):
        spec = importlib.util.spec_from_file_location(
            "check_bench", os.path.join(ROOT, "tools", "check_bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_lower_is_better_gates_the_rise(self, cb):
        fresh = {"metric": "p99", "value": 2.0}
        baselines = [("b1", {"metric": "p99", "value": 1.0}),
                     ("b2", {"metric": "p99", "value": 3.0})]
        rep = cb.compare(fresh, baselines, 0.25, direction="lower")
        assert rep["best"] == 1.0        # best = MIN for latency
        assert rep["drop"] == pytest.approx(1.0)
        assert not rep["ok"]
        good = cb.compare({"metric": "p99", "value": 0.9}, baselines,
                          0.25, direction="lower")
        assert good["ok"] and good["drop"] < 0

    def test_direction_read_from_record(self, cb):
        fresh = {"metric": "p99", "value": 2.0, "direction": "lower"}
        rep = cb.compare(fresh, [("b", {"metric": "p99", "value": 1.0})],
                         0.25)
        assert rep["direction"] == "lower" and not rep["ok"]

    def test_trend_lower_direction_flags_rise(self, cb):
        fresh = {"metric": "p99", "value": 4.0}
        pool = [("r1", {"metric": "p99", "value": 1.0}),
                ("r2", {"metric": "p99", "value": 2.0}),
                ("r3", {"metric": "p99", "value": 3.0})]
        t = cb.trend(fresh, pool, direction="lower")
        assert t["regressing"]
        t2 = cb.trend({"metric": "p99", "value": 0.5}, pool,
                      direction="higher")
        assert not t2["regressing"]


# ---------------------------------------------------------------------------
# e2e: daemon + subprocess workers over HTTP
# ---------------------------------------------------------------------------

class TestServeE2E:
    def test_submit_roundtrip_byte_exact(self, daemon, client):
        p = _plan("rt")
        oracle = list(p.run(name="serve-rt-oracle").dataset.read())
        job = client.submit(p, tenant="alice")
        row = job.wait(timeout_s=120)
        assert row["state"] == "done", row
        assert job.result() == oracle
        assert row["records"] == len(oracle)
        doc = client.jobs()
        assert doc["schema"] == "dampr-tpu-serve-jobs/1"
        assert any(r["job"] == job.id and r["tenant"] == "alice"
                   for r in doc["jobs"])

    def test_identical_inflight_submissions_coalesce(self, tmp_path):
        """Two clients submitting the same fingerprint mid-flight
        coalesce onto ONE run; both get the same result bytes."""
        d = ServeDaemon(port=0, state_dir=str(tmp_path / "s"), workers=1)
        assert d.start() is not None
        try:
            c = ServeClient("http://127.0.0.1:{}".format(d.port))

            def slowish(x):
                time.sleep(0.15)
                return x + 1

            p = Dampr.memory(list(range(6))).map(slowish)
            j1 = c.submit(p, tenant="alice")
            j2 = c.submit(p, tenant="bob")
            assert j2.state == "coalesced" and j2.primary == j1.id
            r1 = j1.wait(timeout_s=120)
            r2 = j2.wait(timeout_s=120)
            assert r1["state"] == "done" and r2["state"] == "done"
            assert j1.result_bytes() == j2.result_bytes()
            # one run: only the primary has a job directory
            job_dirs = os.listdir(str(tmp_path / "s" / "jobs"))
            assert job_dirs == [j1.id]
            assert d.counters["serve-coalesce"] == 1
        finally:
            d.stop()

    def test_reuse_off_submissions_never_coalesce(self, daemon, client):
        p = _plan("nc")
        j1 = client.submit(p, tenant="alice", reuse="off")
        j2 = client.submit(p, tenant="bob", reuse="off")
        assert j2.primary is None
        assert j1.wait(120)["state"] == "done"
        assert j2.wait(120)["state"] == "done"

    def test_cancel_running_releases_budget_and_dumps(self, daemon,
                                                      client):
        def very_slow(x):
            time.sleep(30)
            return x

        p = Dampr.memory(list(range(3))).map(very_slow)
        job = client.submit(p, tenant="alice")
        while job.poll()["state"] == "queued":
            time.sleep(0.05)
        # Wait until the worker's run actually starts (its trace dir
        # appears) so SIGTERM lands on the fault layer's handler, not on
        # an interpreter that is still importing.
        trace_dir = os.path.join(daemon.state_dir, "jobs", job.id,
                                 "trace")
        deadline = time.time() + 60
        while time.time() < deadline and not (
                os.path.isdir(trace_dir) and os.listdir(trace_dir)):
            time.sleep(0.05)
        time.sleep(0.3)
        job.cancel()
        row = job.wait(timeout_s=60)
        assert row["state"] == "cancelled"
        assert row["exit_code"] == 143     # SIGTERM -> crashdump path
        # the reservation is back
        stats = client.jobs()["tenants"]["alice"]
        assert stats["reserved_bytes"] == 0
        # and the crashdump is schema-valid
        dump = row["crashdump"]
        assert dump and os.path.isfile(dump)
        spec = importlib.util.spec_from_file_location(
            "validate_trace",
            os.path.join(ROOT, "tools", "validate_trace.py"))
        vt = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(vt)
        with open(os.path.join(ROOT, "docs", "trace_schema.json")) as f:
            schema = json.load(f)
        doc = json.load(open(dump))
        assert not vt.validate(doc, schema)
        assert doc["otherData"]["crash"]["exception"] == "SystemExit"

    def test_cancel_queued_releases_immediately(self, tmp_path):
        d = ServeDaemon(port=0, state_dir=str(tmp_path / "s"), workers=1)
        assert d.start() is not None
        try:
            c = ServeClient("http://127.0.0.1:{}".format(d.port))

            def slowish(x):
                time.sleep(5)
                return x

            blocker = c.submit(
                Dampr.memory([1]).map(slowish), tenant="alice")
            queued = c.submit(_plan("q"), tenant="alice")
            doc = queued.cancel()
            assert doc["state"] == "cancelled"
            assert c.jobs()["tenants"]["alice"]["reserved_bytes"] > 0 \
                or True  # blocker may still hold its own reservation
            blocker.cancel()
            blocker.wait(timeout_s=60)
            assert c.jobs()["tenants"]["alice"]["reserved_bytes"] == 0
        finally:
            d.stop()

    def test_poison_tenant_is_isolated(self, daemon, client):
        """One tenant's poison record fails ITS job (classified, with a
        crashdump) while a concurrent healthy tenant's job completes,
        and the daemon keeps serving."""
        def poison(x):
            if x == 7:
                raise ValueError("poison record {!r}".format(x))
            return x

        bad = client.submit(
            Dampr.memory(list(range(20))).map(poison), tenant="eve")
        good = client.submit(_plan("ok"), tenant="alice")
        bad_row = bad.wait(timeout_s=120)
        good_row = good.wait(timeout_s=120)
        assert bad_row["state"] == "failed"
        assert "poison record" in bad_row["error"]
        assert bad_row["crashdump"] and os.path.isfile(
            bad_row["crashdump"])
        assert good_row["state"] == "done"
        # still serving
        again = client.submit(_plan("again"), tenant="alice")
        assert again.wait(timeout_s=120)["state"] == "done"

    def test_server_side_admission_gate_rejects_dta401(self, daemon,
                                                       client):
        # A capture the wire can ship but the pickle probe flags (a
        # lambda inside a container): must bounce at the daemon's door
        # with the coded diagnostic, not crash a worker.
        def make(fns):
            return lambda x: fns[0](x)

        p = Dampr.memory([1, 2]).map(make([lambda v: v * 2]))
        with pytest.raises(SubmitError) as ei:
            client.submit(p, tenant="eve", validate=False)
        assert ei.value.reason == "invalid"
        assert [d["code"] for d in ei.value.diagnostics] == ["DTA401"]
        assert daemon.counters["serve-reject"] == 1
        # client-side pre-flight reports the same coded diagnostic
        with pytest.raises(SubmitError) as ei2:
            client.submit(p, tenant="eve")
        assert [d["code"] for d in ei2.value.diagnostics] == ["DTA401"]

    def test_drain_finishes_inflight_and_rejects_new(self, tmp_path):
        d = ServeDaemon(port=0, state_dir=str(tmp_path / "s"), workers=1)
        assert d.start() is not None
        try:
            c = ServeClient("http://127.0.0.1:{}".format(d.port))

            def slowish(x):
                time.sleep(0.3)
                return x * 2

            inflight = c.submit(
                Dampr.memory(list(range(4))).map(slowish),
                tenant="alice")
            while inflight.poll()["state"] == "queued":
                time.sleep(0.05)
            stragglers = d.drain(timeout_s=60)
            assert stragglers == 0          # in-flight job finished
            assert inflight.poll()["state"] == "done"
            with pytest.raises(SubmitError) as ei:
                c.submit(_plan("late"), tenant="bob")
            assert ei.value.reason == "draining"
            assert c.health()["status"] == "draining"
            events = [json.loads(line) for line in open(
                os.path.join(str(tmp_path / "s"), "events.jsonl"))]
            codes = [e["code"] for e in events]
            assert "serve-drain" in codes and "serve-reject" in codes
        finally:
            d.stop()

    def test_top_jobs_view(self, daemon, client, capsys):
        job = client.submit(_plan("top"), tenant="alice")
        job.wait(timeout_s=120)
        from dampr_tpu.obs import top as top_mod

        url = "http://127.0.0.1:{}".format(daemon.port)
        rc = top_mod.main(["--jobs", url, "--once", "--json"])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        rows = doc["jobs"]["jobs"]
        assert any(r["job"] == job.id and r["state"] == "done"
                   and r["tenant"] == "alice" for r in rows)
        # the human rendering carries the daemon job table too
        text = top_mod.render_jobs(doc["jobs"])
        assert "TENANT" in text and "alice" in text

    def test_metrics_exposition(self, daemon, client):
        job = client.submit(_plan("m"), tenant="alice")
        job.wait(timeout_s=120)
        text = client.metrics()
        assert ('dampr_tpu_serve_jobs{tenant="alice",state="done"} 1'
                in text)
        assert ('dampr_tpu_serve_events_total{code="serve-admit"} 1'
                in text)
        assert "dampr_tpu_serve_uptime_seconds" in text


class TestSettingsServe:
    def test_reuse_auto_resolves_on_only_under_serve(self, monkeypatch):
        monkeypatch.setattr(settings, "reuse", "auto")
        monkeypatch.setattr(settings, "serve_active", False)
        assert settings.reuse_enabled() is False
        monkeypatch.setattr(settings, "serve_active", True)
        assert settings.reuse_enabled() is True
        # explicit off pins the cache out even inside the daemon
        monkeypatch.setattr(settings, "reuse", "off")
        assert settings.reuse_enabled() is False
        monkeypatch.setattr(settings, "reuse", "on")
        monkeypatch.setattr(settings, "serve_active", False)
        assert settings.reuse_enabled() is True

    def test_dsl_submit_hook_exists(self):
        from dampr_tpu.dampr import PBase

        assert callable(getattr(PBase, "submit", None))
