"""Learned per-operator cost model (dampr_tpu.plan.model) + the closed
tuning loop (dampr_tpu.obs.autotune): feature extraction over clean /
legacy / corrupt / rank-tagged corpus lines, per-class fit recovery,
knob-search bounds properties, the DAMPR_TPU_COST_MODEL=0 kill-switch
equivalence pin, thin-corpus degradation reasons, the in-process
autotune session (winner selection, byte-exactness disqualification,
settings restore, tuned.json write-back), and the check_bench autotune
baseline / model-residual satellites."""

import importlib.util
import json
import operator
import os
import random
import types

import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.obs import autotune, history
from dampr_tpu.plan import cost, ir, model

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


validate_doctor = _load_tool("validate_doctor")
check_bench = _load_tool("check_bench")

with open(os.path.join(ROOT, "docs", "doctor_schema.json")) as _f:
    DOCTOR_SCHEMA = json.load(_f)


@pytest.fixture
def isolated(tmp_path):
    """Per-test scratch root (the corpus lives under it) + model knobs
    restored.  mesh_exchange pinned off: the 8-device test rig would
    otherwise flip the tiny reduce's shuffle routing between runs
    (mesh on the history-less first run, host once recorded bytes land
    under exchange_min_bytes), splitting its measurements across the
    exchange/fold classes and thinning both fits — these tests pin the
    deterministic host-path behavior."""
    old = (settings.scratch_root, settings.cost_model,
           settings.autotune, settings.autotune_trials,
           settings.mesh_exchange, settings.optimize)
    settings.scratch_root = str(tmp_path / "scratch")
    settings.mesh_exchange = "off"
    # These tests pin model-layer behavior on EVERY CI leg: force the
    # model (and the optimizer the cost layer rides) on here; the
    # kill-switch tests set cost_model="0" themselves.
    settings.cost_model = "auto"
    settings.optimize = True
    yield tmp_path
    (settings.scratch_root, settings.cost_model,
     settings.autotune, settings.autotune_trials,
     settings.mesh_exchange, settings.optimize) = old


def _record(run="r", stages=None, mbps=10.0, knobs=None, rank=None,
            wall=1.0, schema=history.SCHEMA, fingerprint="fp0",
            shapes=None):
    rec = {
        "schema": schema,
        "run": run,
        "wall_seconds": wall,
        "n_partitions": 64,
        "stage_shapes": shapes if shapes is not None else [
            {"sid": 1, "shape": "map:DocFreq+c"},
            {"sid": 2, "shape": "reduce:AssocFoldReducer"},
        ],
        "stages": stages if stages is not None else [
            {"stage": 1, "kind": "map", "target": "host", "jobs": 4,
             "records_in": 1000, "records_out": 900,
             "bytes_in": 8_000_000, "bytes_out": 6_000_000,
             "spill_bytes": 0, "seconds": 0.8},
            {"stage": 2, "kind": "reduce", "target": "host",
             "shuffle_target": "host", "jobs": 64, "records_in": 900,
             "records_out": 50, "bytes_in": 6_000_000,
             "bytes_out": 4_000, "spill_bytes": 0, "seconds": 0.2},
        ],
        "throughput": {"records_out": 50, "bytes_out": 4_000,
                       "mbps": mbps},
        "settings": dict({"overlap_windows": 2, "spill_write_threads": 2,
                          "spill_read_prefetch": 2, "merge_fanin": 512,
                          "spill_codec": "auto",
                          "exchange_hbm_budget": 64 * 1024 ** 2},
                         **(knobs or {})),
        "fingerprint": fingerprint,
    }
    if rank is not None:
        rec["rank"] = rank
    return rec


class TestFeatureExtraction:
    def test_clean_record_rows(self):
        rows = model.stage_features(_record())
        assert len(rows) == 2
        scan, fold = rows
        assert scan["op_class"] == "scanner"  # DocFreq provenance
        assert fold["op_class"] == "fold"
        assert scan["mb"] == pytest.approx(8.0)
        assert fold["jobs"] == 64
        assert scan["record_bytes"] == pytest.approx(6_000_000 / 900)

    def test_op_class_matrix(self):
        assert model.op_class({"kind": "map"}, "map:DocFreq+c") \
            == "scanner"
        assert model.op_class({"kind": "map"}, "map:Rekey") == "merge"
        # A combinered re-key chain is fold_by's keyed map, not a sort.
        assert model.op_class({"kind": "map"}, "map:GMap.Rekey+c") \
            == "map"
        assert model.op_class({"kind": "reduce",
                               "shuffle_target": "mesh"}, "reduce:X") \
            == "exchange"
        assert model.op_class({"kind": "reduce"}, "reduce:X") == "fold"
        assert model.op_class({"kind": "sink"}, "sink:TSV") == "sink"
        assert model.op_class({"kind": "map", "target": "device"},
                              "map:DocFreq+c") == "device"

    def test_rank_tagged_records_excluded(self):
        recs = [_record(), _record(rank=1), _record(rank=2)]
        rows = model.features(recs)
        assert len(rows) == 2  # only the run-level record's stages

    def test_corrupt_and_partial_records_degrade(self):
        # Feature extraction over garbage must yield rows for what is
        # readable and never raise.
        assert model.stage_features(None) == []
        assert model.stage_features({"stages": "not-a-list"}) == []
        rows = model.stage_features({
            "stages": [
                {"stage": 0, "kind": "map", "seconds": "NaN-ish"},
                {"stage": 1, "kind": "map", "seconds": 0.5},
                "garbage",
            ]})
        assert len(rows) == 1 and rows[0]["seconds"] == 0.5

    def test_legacy_v1_lines_upgrade_on_load(self, isolated):
        """A v1 corpus (pre-PR-12: no shuffle_target, no v field) loads,
        upgrades in memory, and feeds the model — the tolerant upgrade
        path that lets feature extraction evolve."""
        path = history.corpus_path("legacy")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        v1 = _record(schema="dampr-tpu-history/1")
        for st in v1["stages"]:
            st.pop("shuffle_target", None)
        with open(path, "w") as f:
            f.write(json.dumps(v1) + "\n")
            f.write("not json at all\n")
            f.write(json.dumps({"schema": "other/9", "stages": []}) + "\n")
            f.write(json.dumps({"schema": "dampr-tpu-history/99",
                                "stages": []}) + "\n")
            f.write(json.dumps(_record(rank=1)) + "\n")
        recs = history.load("legacy")
        # v1 record + rank-tagged v2 record survive; corrupt/foreign/
        # future-versioned lines are skipped.
        assert len(recs) == 2
        up = recs[0]
        assert up["v"] == 1
        assert all(st.get("shuffle_target") is None
                   for st in up["stages"])
        rows = model.features(recs)
        assert len(rows) == 2  # the rank-tagged record is excluded
        assert {r["op_class"] for r in rows} == {"scanner", "fold"}

    def test_schema_version(self):
        assert history.schema_version({"schema": history.SCHEMA}) \
            == history.SCHEMA_VERSION
        assert history.schema_version(
            {"schema": "dampr-tpu-history/1"}) == 1
        assert history.schema_version({"schema": "dampr-tpu-history/99"}) \
            is None
        assert history.schema_version({"schema": "bogus"}) is None
        assert history.schema_version({}) is None


class TestFit:
    def test_recovers_slope_and_job_cost(self):
        recs = []
        rng = random.Random(7)
        for i in range(8):
            mb = 2.0 + 4.0 * rng.random()
            jobs = rng.choice([4, 16, 64])
            secs = 0.1 * mb + 0.002 * jobs
            recs.append(_record(stages=[
                {"stage": 2, "kind": "reduce", "target": "host",
                 "jobs": jobs, "bytes_in": int(mb * 1e6), "bytes_out": 10,
                 "records_in": 100, "records_out": 10,
                 "spill_bytes": 0, "seconds": secs}]))
        m = model.build(recs)
        f = m.fit_for("fold")
        assert f is not None
        assert f.secs_per_mb == pytest.approx(0.1, rel=0.05)
        assert f.secs_per_job == pytest.approx(0.002, rel=0.05)
        assert f.r2 > 0.95
        assert f.predict(10, 64) == pytest.approx(1.0 + 0.128, rel=0.1)

    def test_outlier_robustness(self):
        pts = [(mb, 1, 0.5 * mb) for mb in (1, 2, 3, 4, 5)]
        pts.append((3.0, 1, 50.0))  # cold-run spike
        recs = [_record(stages=[
            {"stage": 2, "kind": "reduce", "jobs": j,
             "bytes_in": int(mb * 1e6), "bytes_out": 1, "records_in": 1,
             "records_out": 1, "spill_bytes": 0, "seconds": s}])
            for mb, j, s in pts]
        m = model.build(recs)
        f = m.fit_for("fold")
        assert f.secs_per_mb == pytest.approx(0.5, rel=0.1)

    def test_below_min_points_no_fit(self):
        recs = [_record() for _ in range(2)]
        m = model.build(recs)
        assert m.fit_for("scanner") is None
        ok, why = m.confident_for(["scanner"])
        assert not ok and "scanner" in why or "thin-corpus" in why

    def test_confident_reports_missing_classes(self):
        recs = [_record() for _ in range(4)]
        m = model.build(recs)
        ok, why = m.confident_for(["scanner", "fold", "exchange"])
        assert not ok and "exchange" in why
        ok, why = m.confident_for(["scanner", "fold"])
        assert ok and why is None


class TestSearchBounds:
    """Property pins: no search path ever proposes a value outside the
    documented KNOB_BOUNDS, whatever the corpus says."""

    def _random_records(self, rng, n):
        recs = []
        for i in range(n):
            stages = []
            for sid, kind in ((1, "map"), (2, "reduce")):
                stages.append({
                    "stage": sid, "kind": kind,
                    "target": rng.choice(["host", "host", "device"]),
                    "shuffle_target": rng.choice([None, "host", "mesh"]),
                    "jobs": rng.choice([1, 4, 64, 256]),
                    "bytes_in": rng.randrange(0, 1 << 31),
                    "bytes_out": rng.randrange(0, 1 << 31),
                    "records_in": rng.randrange(0, 1 << 20),
                    "records_out": rng.randrange(0, 1 << 20),
                    "spill_bytes": 0,
                    "seconds": rng.random() * 100,
                })
            recs.append(_record(
                stages=stages, mbps=rng.random() * 500,
                knobs={
                    "overlap_windows": rng.choice([0, 2, 4, 8]),
                    "spill_write_threads": rng.choice([0, 2, 8]),
                    "merge_fanin": rng.choice([4, 64, 512, 4096]),
                    "spill_codec": rng.choice(["auto", "zstd", "zlib"]),
                    "exchange_hbm_budget": rng.choice(
                        [1 << 20, 1 << 26, 1 << 30]),
                }))
        return recs

    def test_partition_search_stays_in_bounds(self):
        rng = random.Random(1234)
        for trial in range(40):
            recs = self._random_records(rng, rng.randrange(3, 9))
            m = model.build(recs)
            rows = cost._hist_stage_rows(
                {"stages": recs[-1]["stages"]},
                types.SimpleNamespace(stages=[]))
            # op_class comes from the record fields when shapes are
            # unavailable (the graph is empty here).
            for r in rows:
                r["op_class"] = model.op_class(r, None)
            ch = model.search_partitions(m, rows,
                                         rng.choice([4, 64, 256]))
            if ch is not None:
                lo, hi = model.KNOB_BOUNDS["n_partitions"]
                assert lo <= ch["chosen"] <= hi, ch
                assert ch["chosen"] != ch["static"]

    def test_variance_search_stays_in_bounds(self):
        rng = random.Random(99)
        for trial in range(40):
            recs = self._random_records(rng, rng.randrange(2, 10))
            m = model.build(recs, fingerprint="fp0")
            current = {k: getattr(settings, k, None)
                       for k in model.VARIANCE_KNOBS}
            for ch in model.search_variance_knobs(m, current):
                if ch["chosen"] == ch["static"]:
                    continue
                assert model.in_bounds(ch["knob"], ch["chosen"]), ch

    def test_candidate_vectors_stay_in_bounds(self, isolated):
        rng = random.Random(5)
        path = history.corpus_path("bounds-run")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            for rec in self._random_records(rng, 6):
                rec["critpath"] = {"run": rng.choice(
                    ["codec", "merge", "spill-queue", "io-read"])}
                f.write(json.dumps(rec, default=str) + "\n")
        for cand in autotune.candidate_vectors("bounds-run", 8):
            for knob, val in cand["knobs"].items():
                assert model.in_bounds(knob, val), (knob, val)

    def test_clamp_and_in_bounds(self):
        assert model.clamp("merge_fanin", 1 << 30) == 4096
        assert model.clamp("overlap_windows", -3) == 0
        assert model.in_bounds("spill_codec", "zstd")
        assert not model.in_bounds("spill_codec", "brotli")
        assert not model.in_bounds("n_partitions", True)
        assert not model.in_bounds("nonexistent_knob", 1)


def _fold_pipeline():
    return (Dampr.memory([(i % 50, 1) for i in range(30000)])
            .fold_by(lambda kv: kv[0], operator.add, lambda kv: kv[1]))


def _run(name):
    em = _fold_pipeline().run(name)
    s = em.stats()
    em.delete()
    return s


class TestKillSwitchAndDegradation:
    def test_kill_switch_reproduces_median_path(self, isolated):
        """DAMPR_TPU_COST_MODEL=0: the adaptive decisions must be
        exactly the median path's — n_partitions from
        _clamped_partitions over the synthesized history, tiny-reduce
        collapse — with the kill switch recorded in the cost section
        and NOTHING model-applied."""
        settings.cost_model = "0"
        for i in range(4):
            s = _run("kill-switch")
        plan = s["plan"]
        assert plan["cost"]["enabled"] is False
        assert "disabled" in plan["cost"]["reason"]
        assert plan["cost"]["choices"] == []
        # The median path's exact decisions, recomputed from the corpus
        # the run accumulated (the pre-model behavior pin).
        recs = history.load("kill-switch")
        matched = history.matching(
            recs, recs[-1]["stage_shapes"])
        hist = history.synthesize(
            matched[-max(1, settings.history_window):])
        reduce_bytes = max(st.get("bytes_in") or 0
                           for st in hist["stages"]
                           if st["kind"] == "reduce")
        want = cost._clamped_partitions(reduce_bytes)
        ad = plan["adaptive"]
        changes = {c["what"]: c for c in ad["changes"]}
        assert changes["n_partitions"]["to"] == want
        assert s["n_partitions"] == want

    def test_kill_switch_apply_model_touches_nothing(self, isolated):
        settings.cost_model = "off"
        runner = types.SimpleNamespace(
            graph=None, name="whatever", n_partitions=64,
            _explicit_partitions=False, resume=False)
        report = {}
        cost.apply_model(runner, types.SimpleNamespace(stages=[]),
                         report)
        assert report["cost"]["enabled"] is False
        assert runner.n_partitions == 64

    def test_empty_corpus_degrades_to_static_with_reason(self, isolated):
        s = _run("cold-start")
        c = s["plan"]["cost"]
        assert c["enabled"] is False
        assert c["source"] == "static"
        assert "no-history" in c["reason"]

    def test_thin_corpus_degrades_to_median_with_reason(self, isolated):
        _run("thin")
        s = _run("thin")  # corpus holds 1 record at adapt time
        c = s["plan"]["cost"]
        assert c["enabled"] is False
        assert c["source"] == "median-fallback"
        assert "thin-corpus" in c["reason"] or "unfit" in c["reason"]
        # The median path still adapted (the pre-model behavior).
        assert s["plan"]["adaptive"]["applied"] is True

    def test_confident_corpus_engages_model(self, isolated):
        for i in range(4):
            s = _run("warm")
        c = s["plan"]["cost"]
        assert c["enabled"] is True
        assert c["source"] == "model"
        assert c["model"]["classes"]
        assert isinstance(c["choices"], list)
        # Every no-variance knob records the honest measure-me reason.
        untouched = [ch for ch in c["choices"]
                     if ch["chosen"] == ch["static"]]
        assert any("no-variance" in (ch.get("reason") or "")
                   for ch in untouched)


class TestAutotuneSession:
    def _measure_factory(self, walls_by_overlap):
        calls = []

        def measure():
            w = walls_by_overlap.get(settings.overlap_windows, 1.0)
            calls.append(settings.overlap_windows)
            return w, "result-token"

        return measure, calls

    def test_winner_and_restore(self, isolated):
        settings.autotune_trials = 3
        old_overlap = settings.overlap_windows
        # The exploration schedule tries the opposite regime first
        # (overlap 0 from the default 2): make that the fast config so
        # a non-baseline trial wins.
        measure, calls = self._measure_factory({old_overlap: 1.0,
                                                0: 0.4, 4: 0.4, 8: 0.4})
        best, report = autotune.tune_settings_session(
            measure, "tune-unit", digest_of=lambda r: "d0",
            out=lambda m: None)
        a = report["autotune"]
        assert settings.overlap_windows == old_overlap  # restored
        assert a["byte_identical"] is True
        assert a["winner"]["trial"] != 0
        assert a["improvement"] >= 2.0
        assert best == "result-token"
        # Winner persisted for the next fit.
        tuned = cost.load_tuned("tune-unit")
        assert tuned and tuned["knobs"]
        errors = validate_doctor.validate(report, DOCTOR_SCHEMA,
                                          check_settings=False)
        assert errors == [], errors

    def test_divergent_output_disqualifies(self, isolated):
        settings.autotune_trials = 3
        digests = iter(["base", "DIFFERENT", "base2"])

        def measure():
            return 0.1 if settings.overlap_windows != 2 else 1.0, None

        _best, report = autotune.tune_settings_session(
            measure, "tune-div", digest_of=lambda r: next(digests),
            out=lambda m: None)
        a = report["autotune"]
        assert a["byte_identical"] is False
        disq = [t for t in a["trials"]
                if t.get("byte_identical") is False]
        assert disq
        assert all(a["winner"]["trial"] != t["trial"] for t in disq)
        assert cost.load_tuned("tune-div") is None or \
            a["winner"]["trial"] != 0  # never persisted FROM a disq trial

    def test_tuned_winner_applies_next_run(self, isolated):
        """The closed loop: a tuned.json winner's n_partitions is
        applied by the next run's cost layer with the autotune
        provenance in the decision trace."""
        for i in range(4):
            _run("loop")
        os.makedirs(os.path.join(settings.scratch_root, "loop"),
                    exist_ok=True)
        with open(os.path.join(settings.scratch_root, "loop",
                               "tuned.json"), "w") as f:
            json.dump({"schema": "dampr-tpu-tuned/1",
                       "session": "s1", "run": "loop",
                       "knobs": {"n_partitions": 8},
                       "wall_seconds": 0.01}, f)
        s = _run("loop")
        c = s["plan"]["cost"]
        applied = {ch["knob"]: ch for ch in c["choices"]
                   if ch.get("applied")}
        assert "n_partitions" in applied, c["choices"]
        assert applied["n_partitions"]["chosen"] == 8
        assert "autotuned winner" in applied["n_partitions"]["reason"]
        assert s["n_partitions"] == 8

    def test_stale_fingerprint_tuned_never_applies(self, isolated):
        """A tuned.json winner measured on a DIFFERENT plan shape under
        the same run name is ignored (recorded as tuned_stale), never
        force-applied."""
        for i in range(4):
            _run("stale")
        os.makedirs(os.path.join(settings.scratch_root, "stale"),
                    exist_ok=True)
        with open(os.path.join(settings.scratch_root, "stale",
                               "tuned.json"), "w") as f:
            json.dump({"schema": "dampr-tpu-tuned/1", "session": "sX",
                       "run": "stale", "fingerprint": "deadbeef" * 2,
                       "knobs": {"n_partitions": 8}}, f)
        s = _run("stale")
        c = s["plan"]["cost"]
        assert c.get("tuned_stale", {}).get("session") == "sX", c
        for ch in c["choices"]:
            assert not (ch["knob"] == "n_partitions"
                        and ch.get("chosen") == 8
                        and "autotuned" in (ch.get("reason") or "")), ch
        assert s["n_partitions"] != 8

    def test_as_env_maps_only_env_knobs(self):
        env = autotune.as_env({"overlap_windows": 4, "n_partitions": 8,
                               "spill_codec": "zstd"})
        assert env == {"DAMPR_TPU_OVERLAP_WINDOWS": "4",
                       "DAMPR_TPU_SPILL_CODEC": "zstd"}

    def test_dir_digest_orders_and_content(self, tmp_path):
        d = tmp_path / "out"
        d.mkdir()
        (d / "a.txt").write_text("alpha\nbeta\n")
        one = autotune.dir_digest(str(d))
        tree_one = autotune.dir_digest(str(d), mode="tree")
        (d / "a.txt").write_text("alpha\nbeta!\n")
        assert autotune.dir_digest(str(d)) != one
        assert autotune.dir_digest(str(tmp_path / "missing")) is None
        # Layout invariance (default mode): the same line multiset split
        # across a different number of part files — a partition-count
        # choice — digests identically; tree mode distinguishes it.
        (d / "a.txt").write_text("beta\n")
        (d / "b.txt").write_text("alpha\n")
        assert autotune.dir_digest(str(d)) == one
        assert autotune.dir_digest(str(d), mode="tree") != tree_one


class TestCheckBenchSatellites:
    def _tune_report(self, tmp_path, mbps=120.0):
        report = {
            "schema": "dampr-tpu-doctor/1", "run": "bench-tfidf",
            "wall_seconds": 1.0, "stages": [], "findings": [],
            "metric": "tfidf_docfreq_throughput",
            "autotune": {
                "session": "s", "trials": [
                    {"trial": 0, "knobs": {}, "wall_seconds": 1.4},
                    {"trial": 1, "knobs": {"overlap_windows": 4},
                     "wall_seconds": 1.0, "mbps": mbps,
                     "byte_identical": True},
                ],
                "winner": {"trial": 1,
                           "knobs": {"overlap_windows": 4},
                           "wall_seconds": 1.0, "mbps": mbps},
                "baseline_wall_seconds": 1.4, "improvement": 1.4,
                "byte_identical": True,
            },
        }
        path = tmp_path / "TUNE_test.json"
        path.write_text(json.dumps(report))
        return str(path)

    def test_autotune_report_as_baseline(self, tmp_path, capsys):
        tune = self._tune_report(tmp_path, mbps=120.0)
        rec = check_bench.load_record(tune)
        assert rec["value"] == 120.0
        assert rec["metric"] == "tfidf_docfreq_throughput"
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps({
            "metric": "tfidf_docfreq_throughput", "value": 60.0}))
        rc = check_bench.main([str(fresh), "--baseline", tune,
                               "--tolerance", "0.25"])
        out = capsys.readouterr().out
        assert rc == 0  # warn-only default
        assert "WARN" in out and "120" in out

    def test_autotune_report_without_toplevel_value(self, tmp_path):
        tune = self._tune_report(tmp_path, mbps=80.0)
        doc = json.loads(open(tune).read())
        doc.pop("metric", None)
        with open(tune, "w") as f:
            json.dump(doc, f)
        rec = check_bench.load_record(tune)
        assert rec["value"] == 80.0

    def test_model_residual_warns_under_trend(self, tmp_path, capsys):
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps({
            "metric": "m", "value": 50.0,
            "model_predicted_value": 100.0}))
        rc = check_bench.main([str(fresh), "--trend",
                               "--tolerance", "0.25"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "MODEL WARN" in out

    def test_model_residual_quiet_within_tolerance(self, tmp_path,
                                                   capsys):
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps({
            "metric": "m", "value": 95.0,
            "model_predicted_value": 100.0}))
        rc = check_bench.main([str(fresh), "--trend",
                               "--tolerance", "0.25"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "MODEL WARN" not in out
        assert "model residual" in out

    def test_no_prediction_no_model_line(self, tmp_path, capsys):
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps({"metric": "m", "value": 95.0}))
        rc = check_bench.main([str(fresh), "--trend"])
        out = capsys.readouterr().out
        assert rc == 0 and "MODEL" not in out


class TestTrajectoryFeedstock:
    def test_load_trajectory_mixed(self, tmp_path):
        (tmp_path / "BENCH_r01.json").write_text(json.dumps({
            "parsed": {"metric": "tfidf", "value": 100.0,
                       "overlap_windows": 2}}))
        (tmp_path / "TUNE_r01.json").write_text(json.dumps({
            "metric": "tfidf",
            "autotune": {"winner": {"mbps": 140.0,
                                    "knobs": {"overlap_windows": 4}}}}))
        (tmp_path / "broken.json").write_text("{nope")
        recs = model.load_trajectory([
            str(tmp_path / "BENCH_r01.json"),
            str(tmp_path / "TUNE_r01.json"),
            str(tmp_path / "broken.json"),
            str(tmp_path / "missing.json")])
        assert len(recs) == 2
        assert recs[0]["mbps"] == 100.0
        assert recs[0]["knobs"] == {"overlap_windows": 2}
        assert recs[1]["mbps"] == 140.0
        assert recs[1]["knobs"] == {"overlap_windows": 4}
