"""Rank-death chaos leg (docs/robustness.md): a REAL 2-process gloo
deployment loses rank 1 to an injected kill mid-exchange, and the
contract holds end to end —

- rank 1 dies hard (``rank_kill`` fault, exit 137) but still leaves a
  schema-valid crashdump (the kill action flushes the flight recorder);
- rank 0's exchange watchdog (``settings.exchange_timeout_ms``) aborts
  the hung gloo collective within the bounded deadline (measured from
  rank 1's death: <= 2x the deadline), leaves its own crashdump, and
  records the ``exchange_timeout`` fault event;
- a follow-up single-process ``run(resume="auto")`` under the same name
  restores the checkpointed prefix from rank 0's manifests, completes
  byte-identical to a cold run, and its plan report shows the affected
  stage's shuffle degraded to the host path with a fault-history
  reason."""

import json
import os
import socket
import subprocess
import sys
import textwrap
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TIMEOUT_MS = 6000  # exchange watchdog deadline for the chaos leg

#: The pipeline under test, exec'd VERBATIM by the workers and by the
#: recovery/cold runs in this process — identical source means identical
#: resume fingerprints (lambda bytecode included), so the recovery run
#: genuinely restores the dead deployment's checkpoints.
PIPELINE_SRC = textwrap.dedent("""
    def build_pipe():
        from dampr_tpu import Dampr
        data = [(i % 13, (i * 2654435761) % 99991) for i in range(4000)]
        return (Dampr.memory(data, partitions=8)
                .map(lambda x: (x[0], x[1] * 2))
                .checkpoint(force=True)
                .group_by(lambda x: x[0])
                .reduce(lambda k, vs: sorted(v[1] for v in vs)[:5]))
""")

_WORKER = textwrap.dedent("""
    import os, sys, time
    pid = int(sys.argv[1]); port = sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, @ROOT@)
    from dampr_tpu import settings, faults
    settings.scratch_root = os.path.join(
        os.environ["CHAOS_SCRATCH"], "rank%d" % pid)
    settings.partitions = 8
    settings.trace = True
    settings.mesh_fold = "off"
    settings.mesh_exchange = "on"
    settings.exchange_timeout_ms = @TIMEOUT_MS@
    from dampr_tpu.parallel.mesh import init_distributed
    init_distributed(coordinator_address="localhost:%s" % port,
                     num_processes=2, process_id=pid)
    assert jax.process_count() == 2 and len(jax.devices()) == 8

    # Rank 1 dies at its first collective exchange step — exactly where
    # a real dead rank strands its peers.
    settings.faults = "rank_kill:rank=1,nth=1,exit=137"

    exec(@PIPELINE_SRC@)
    from dampr_tpu.runner import MTRunner
    pipe = build_pipe()
    print("RUN_START_%d" % pid, flush=True)
    runner = MTRunner("rankdeath", pipe.pmer.graph, resume=True)
    runner.run([pipe.source])
    print("UNEXPECTED_COMPLETE_%d" % pid, flush=True)
""").replace("@ROOT@", repr(ROOT)).replace(
    "@TIMEOUT_MS@", str(TIMEOUT_MS)).replace(
    "@PIPELINE_SRC@", repr(PIPELINE_SRC))


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _validate_crashdump(path):
    import importlib.util

    with open(path) as f:
        doc = json.load(f)
    spec = importlib.util.spec_from_file_location(
        "validate_trace", os.path.join(ROOT, "tools",
                                       "validate_trace.py"))
    vt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(vt)
    with open(os.path.join(ROOT, "docs", "trace_schema.json")) as f:
        schema = json.load(f)
    errors = vt.validate(doc, schema)
    assert not errors, (path, errors)
    return doc


class TestRankDeath:
    def test_kill_rank1_bounded_abort_and_auto_resume(self, tmp_path):
        from dampr_tpu import faults, settings

        port = _free_port()
        scratch_base = str(tmp_path / "chaos")
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["CHAOS_SCRATCH"] = scratch_base
        script = str(tmp_path / "worker.py")
        with open(script, "w") as f:
            f.write(_WORKER)
        procs = [
            subprocess.Popen(
                [sys.executable, script, str(i), str(port)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, env=env)
            for i in range(2)]

        # Rank 1 dies first (the injected kill).
        out1, err1 = procs[1].communicate(timeout=240)
        t_rank1_dead = time.time()
        assert procs[1].returncode == 137, (
            procs[1].returncode, out1, err1[-2000:])
        assert "UNEXPECTED_COMPLETE_1" not in out1

        # Rank-death bound: the survivor aborts within 2x the exchange
        # deadline of rank 1's death — no hung gloo collective.
        bound = 2 * TIMEOUT_MS / 1000.0
        try:
            out0, err0 = procs[0].communicate(timeout=bound + 30)
        except subprocess.TimeoutExpired:
            procs[0].kill()
            raise AssertionError(
                "rank 0 hung past the watchdog bound — the abort "
                "path never fired")
        t_rank0_dead = time.time()
        assert procs[0].returncode == 70, (
            procs[0].returncode, out0, err0[-2000:])
        assert "UNEXPECTED_COMPLETE_0" not in out0
        assert t_rank0_dead - t_rank1_dead <= bound, (
            "abort took %.1fs, bound %.1fs"
            % (t_rank0_dead - t_rank1_dead, bound))

        # Schema-valid crashdumps on BOTH ranks, each naming its death.
        dump0 = os.path.join(scratch_base, "rank0", "rankdeath",
                             "trace", "crashdump.json")
        dump1 = os.path.join(scratch_base, "rank1", "rankdeath",
                             "trace", "rank1", "crashdump.rank1.json")
        assert os.path.isfile(dump0), err0[-2000:]
        assert os.path.isfile(dump1), err1[-2000:]
        doc0 = _validate_crashdump(dump0)
        doc1 = _validate_crashdump(dump1)
        assert doc0["otherData"]["crash"]["reason"] == "exchange-timeout"
        assert doc1["otherData"]["crash"]["reason"] == (
            "fault-injected-kill")

        # The watchdog recorded the timeout in rank 0's fault sidecar.
        saved = (settings.scratch_root, settings.partitions,
                 settings.mesh_fold)
        settings.scratch_root = os.path.join(scratch_base, "rank0")
        settings.partitions = 8
        settings.mesh_fold = "off"
        try:
            evs = faults.load_events("rankdeath")
            assert any(ev["kind"] == "exchange_timeout" for ev in evs), (
                evs)

            # Recovery: resume="auto" restores the checkpointed prefix
            # from rank 0's manifests and completes on the host path
            # (the fault-history degrade) — byte-identical to a cold
            # single-process run.
            g = {}
            exec(PIPELINE_SRC, g)
            em = g["build_pipe"]().run(name="rankdeath", resume="auto")
            got = sorted(map(repr, em.read()))
            kinds = [s["kind"] for s in em.stats]
            assert any(k.startswith("resumed-") for k in kinds), kinds
            shuffle = (em.stats().get("plan") or {}).get("shuffle") or {}
            degraded = [d for d in shuffle.get("targets") or ()
                        if "fault-history" in (d.get("reason") or "")]
            assert degraded, shuffle
            assert all(d["target"] == "host" for d in degraded)
            em.delete()
        finally:
            (settings.scratch_root, settings.partitions,
             settings.mesh_fold) = saved

        # Cold single-process baseline in a fresh scratch root.
        saved = (settings.scratch_root, settings.partitions,
                 settings.mesh_fold)
        settings.scratch_root = str(tmp_path / "cold")
        settings.partitions = 8
        settings.mesh_fold = "off"
        try:
            g = {}
            exec(PIPELINE_SRC, g)
            cold = sorted(map(repr,
                              g["build_pipe"]().run(name="cold").read()))
        finally:
            (settings.scratch_root, settings.partitions,
             settings.mesh_fold) = saved
        assert got == cold, "auto-resume diverged from the cold run"
