"""Out-of-core reduce: over-budget partitions stream a k-way merge over
hash-sorted runs with one window resident per run, and results stay exact."""

import numpy as np
import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.base import StreamingGroupedView
from dampr_tpu.blocks import Block
from dampr_tpu.storage import SPILL_WINDOW, RunStore, save_block, load_block


@pytest.fixture(autouse=True)
def tight_memory(tmp_path):
    old = (settings.partitions, settings.max_memory_per_stage,
           settings.scratch_root, settings.streaming_reduce_threshold)
    settings.partitions = 4
    settings.max_memory_per_stage = 32 * 1024
    settings.scratch_root = str(tmp_path / "scratch")
    settings.streaming_reduce_threshold = 16 * 1024
    yield
    (settings.partitions, settings.max_memory_per_stage,
     settings.scratch_root, settings.streaming_reduce_threshold) = old


class TestWindowedSpill:
    def test_round_trip(self, tmp_path):
        n = SPILL_WINDOW * 2 + 37
        blk = Block.from_pairs([("k%d" % (i % 100), i) for i in range(n)])
        blk.hashes()
        p = str(tmp_path / "b.blk")
        save_block(blk, p)
        back = load_block(p)
        assert list(back.iter_pairs()) == list(blk.iter_pairs())

    def test_iter_windows_bounded(self, tmp_path):
        store = RunStore("wintest", budget=1)  # everything spills
        n = SPILL_WINDOW + 123
        ref = store.register(Block.from_pairs([(i, i) for i in range(n)]))
        store.drain_writes()  # spill writes are asynchronous now
        assert not ref.resident
        windows = list(ref.iter_windows())
        assert len(windows) == 2
        assert sum(len(w) for w in windows) == n


class TestStreamingGroupedView:
    def test_matches_materialized_grouping(self):
        store = RunStore("sgv", budget=1 << 30)
        rng = np.random.RandomState(0)
        refs = []
        for _run in range(5):
            keys = rng.randint(0, 50, size=2000)
            blk = Block.from_pairs(
                [(int(k), int(k) * 10 + 1) for k in keys]).sort_by_hash()
            refs.append(store.register(blk))
        view = StreamingGroupedView(refs)
        got = {k: sorted(vs) for k, vs in view.grouped_read()}
        want = {}
        for ref in refs:
            for k, v in ref.get().iter_pairs():
                want.setdefault(k, []).append(v)
        want = {k: sorted(vs) for k, vs in want.items()}
        assert got == want

    def test_forced_hash_collision_subgroups_exactly(self):
        store = RunStore("sgvc", budget=1 << 30)
        h = np.full(6, 9, dtype=np.uint32)
        blk = Block(np.array(["a", "b", "a", "b", "a", "b"], dtype=object),
                    np.arange(6), h.copy(), h.copy())
        view = StreamingGroupedView([store.register(blk)])
        got = {k: list(vs) for k, vs in view.grouped_read()}
        assert got == {"a": [0, 2, 4], "b": [1, 3, 5]}


class TestEndToEnd:
    def test_group_by_streams_over_budget_exactly(self):
        n = 40000
        out = dict(Dampr.memory(list(range(n)), partitions=16)
                   .group_by(lambda x: x % 9)
                   .reduce(lambda k, it: sum(it)).read())
        want = {}
        for x in range(n):
            want[x % 9] = want.get(x % 9, 0) + x
        assert out == want

    def test_assoc_fold_over_budget(self):
        n = 50000
        out = dict(Dampr.memory(list(range(n)), partitions=16)
                   .count(lambda x: x % 11).read())
        want = {i: len(range(i, n, 11)) for i in range(11)}
        assert out == want

    def test_unique_values_order_preserved_within_runs(self):
        # equal keys keep arrival order within a run after hash sorting
        data = [("k", i) for i in range(30000)]
        out = (Dampr.memory(data, partitions=4)
               .group_by(lambda x: x[0], lambda x: x[1])
               .reduce(lambda k, it: list(it)).read())
        (_k, vals), = out
        # exact arrival order: sequential chunks, stable hash sort, merge
        # stable by run index
        assert vals == list(range(30000))

    def test_hot_key_streams_lazily(self):
        # one key dominating an over-budget partition: values stream through
        # the reducer without being buffered into a list first
        n = 200000
        out = dict(Dampr.memory([("hot", 1)] * n + [("cold", 2)] * 5,
                                partitions=8)
                   .group_by(lambda x: x[0], lambda x: x[1])
                   .reduce(lambda k, it: sum(it)).read())
        assert out == {"hot": n, "cold": 10}

    def test_over_budget_assoc_fold_uses_vectorized_accumulator(self):
        from dampr_tpu.runner import MTRunner

        old_mesh = settings.mesh_fold
        old_opt = settings.optimize
        settings.mesh_fold = "off"  # isolate the accumulator path
        # Pin the fused plan: this test asserts WHICH engine path the
        # reduce takes, and that depends on the map-side combine staying
        # per-chunk (under DAMPR_TPU_OPTIMIZE=0 the separate combiner
        # stage collapses to one tiny-input job, shrinking the reduce
        # input below the streaming threshold — correct, different path).
        settings.optimize = True
        try:
            # many chunks x modest key cardinality: per-chunk combined
            # outputs stack up past the threshold per partition, while the
            # distinct-key accumulator stays under it — the shape the
            # vectorized streaming fold exists for
            n_keys, repeats = 2000, 40  # 500 keys/partition ~ 12KB < 16KB threshold
            pipe = (Dampr.memory(list(range(n_keys)) * repeats,
                                 partitions=repeats)
                    .count(lambda x: x).checkpoint())
            runner = MTRunner("assoc-stream", pipe.pmer.graph)
            out = runner.run([pipe.source])
            got = dict(v for _k, v in out[0].read())
            assert got == {i: repeats for i in range(n_keys)}
            assert runner.streamed_assoc_folds >= 1
        finally:
            settings.mesh_fold = old_mesh
            settings.optimize = old_opt


class TestVectorMerge:
    def test_matches_record_merge_exactly(self):
        from dampr_tpu.runner import MTRunner, OutputDataset
        settings.streaming_reduce_threshold = None
        settings.max_memory_per_stage = 1  # force the merge paths
        rng = np.random.RandomState(3)
        data = rng.randint(0, 500, size=20000).tolist()
        pipe = (Dampr.memory([(k, i) for i, k in enumerate(data)],
                             partitions=8)
                .map_keys(lambda k: k).checkpoint(True))
        runner = MTRunner("vmerge", pipe.pmer.graph)
        out = runner.run([pipe.source])
        ds = out[0]
        vec = list(ds.read())
        rec = list(ds._merge_partitions(sorted(ds.pset.parts)))
        assert vec == rec
        keys = [k for k, _v in vec]
        assert keys == sorted(keys)

    def test_sorted_blocks_vector_path(self):
        from dampr_tpu.runner import MTRunner
        settings.max_memory_per_stage = 1
        n = 30000
        pipe = (Dampr.memory(list(range(n, 0, -1)), partitions=8)
                .checkpoint(True))
        runner = MTRunner("vmerge2", pipe.pmer.graph)
        out = runner.run([pipe.source])
        got = []
        prev = None
        for blk in out[0].sorted_blocks():
            ks = blk.keys
            assert (np.diff(ks) >= 0).all()
            if prev is not None and len(ks):
                assert ks[0] >= prev
            if len(ks):
                prev = ks[-1]
            got.extend(blk.values.tolist())
        assert len(got) == n

    def test_object_keys_fall_back(self):
        from dampr_tpu.runner import MTRunner
        settings.max_memory_per_stage = 1
        pipe = (Dampr.memory(["b", "a", "c"] * 100, partitions=4)
                .checkpoint(True))
        runner = MTRunner("vmerge3", pipe.pmer.graph)
        out = runner.run([pipe.source])
        vals = [v for _k, v in out[0].read()]
        assert sorted(vals) == sorted(["b", "a", "c"] * 100)

    def test_hot_key_duplicates_stream_bounded(self):
        from dampr_tpu.runner import MTRunner
        settings.max_memory_per_stage = 1
        # one dominant key with many duplicates across partitions
        data = [(7, i) for i in range(50000)] + [(j, -j) for j in range(50)]
        pipe = Dampr.memory(data, partitions=8).checkpoint(True)
        runner = MTRunner("vmerge-hot", pipe.pmer.graph)
        out = runner.run([pipe.source])
        vec = list(out[0].read())
        rec = list(out[0]._merge_partitions(sorted(out[0].pset.parts)))
        assert vec == rec
        max_block = max((len(b) for b in out[0].sorted_blocks()), default=0)
        assert max_block <= (1 << 16) * 9  # bounded, never whole-output
