"""The logical plan optimizer (dampr_tpu.plan): fusion rules, barriers,
dead-stage elimination, combiner hoisting, adaptive sizing, explain(),
and the plan observability surface."""

import operator
import os

import pytest

from dampr_tpu import Dampr, Mapper, settings
from dampr_tpu.dampr import Dampr as _D, PMap
from dampr_tpu.graph import GInput, GMap, GReduce, GSink
from dampr_tpu.plan import graph_signature, ir, passes


def _executed(graph):
    return [s for s in graph.stages if not isinstance(s, GInput)]


@pytest.fixture(autouse=True)
def optimizer_on():
    old = (settings.optimize, settings.plan_fuse, settings.plan_hoist,
           settings.plan_fuse_sinks, settings.plan_dead, settings.plan_adapt)
    settings.optimize = True
    settings.plan_fuse = settings.plan_hoist = True
    settings.plan_fuse_sinks = settings.plan_dead = True
    settings.plan_adapt = True
    yield
    (settings.optimize, settings.plan_fuse, settings.plan_hoist,
     settings.plan_fuse_sinks, settings.plan_dead,
     settings.plan_adapt) = old


class TestFusion:
    def test_four_op_chain_executes_as_two_stages(self):
        """The acceptance pipeline: map.map_values.filter.fold_by is ~6
        constructed stages and must execute as <= 2."""
        pipe = (Dampr.memory(list(range(500)))
                .map(lambda x: (x % 7, x))
                .map_values(lambda v: v * 2)
                .filter(lambda kv: kv[1] % 4 == 0)
                .fold_by(lambda kv: kv[0], operator.add, lambda kv: kv[1]))
        em = pipe.run()
        plan = em.stats()["plan"]
        assert plan["enabled"] is True
        assert plan["stages_before"] >= 5
        assert plan["stages_after"] <= 2
        assert len(em.stats) <= 2  # executed StageStats
        assert {s["kind"] for s in em.stats} == {"map", "reduce"}
        # explain() shows the same collapse without executing
        text = pipe.explain()
        assert "optimized plan (2 executed)" in text
        assert "hoist_combiners" in text
        # and the result is right
        want = {}
        for x in range(500):
            v = x * 2
            if v % 4 == 0:
                want[x % 7] = want.get(x % 7, 0) + v
        assert dict(em.read()) == want
        em.delete()

    def test_optimized_matches_unoptimized(self):
        pipe = (Dampr.memory(list(range(300)))
                .map(lambda x: x + 1)
                .filter(lambda x: x % 3 != 0)
                .flat_map(lambda x: (x, -x))
                .sort_by(lambda x: x))
        opt = pipe.read()
        settings.optimize = False
        unopt = pipe.read()
        settings.optimize = True
        assert opt == unopt

    def test_identity_tail_dissolves_into_block_mapper(self):
        """An internal identity stage over a non-record-chain producer is
        eliminated without touching the producer's mapper (its vectorized
        paths survive)."""
        from dampr_tpu.base import Map, _identity
        from dampr_tpu.ops.text import CountRecords

        pipe = Dampr.memory(list(range(100))).custom_mapper(CountRecords())
        # join-style internal materialization: force an identity stage
        # over the block mapper through the graph API
        src, pmer = pipe.pmer._add_mapper([pipe.source], Map(_identity))
        g, report = passes.optimize(pmer.graph, [src])
        assert report["rules"]["fuse_maps"] == 1
        ex = _executed(g)
        assert len(ex) == 1 and isinstance(ex[0].mapper, CountRecords)

    def test_combiner_hoists_into_custom_mapper(self):
        class PairEmit(Mapper):
            def map(self, *datasets):
                for _k, v in datasets[0].read():
                    yield v % 5, 1

        pipe = (Dampr.memory(list(range(200)))
                .custom_mapper(PairEmit())
                .fold_values(operator.add))
        g, report = passes.optimize(pipe.pmer.graph, [pipe.source])
        assert report["rules"]["hoist_combiners"] == 1
        ex = _executed(g)
        assert len(ex) == 2
        assert isinstance(ex[0], GMap) and isinstance(ex[0].mapper, PairEmit)
        assert ex[0].combiner is not None  # the hoisted map-side fold
        assert isinstance(ex[1], GReduce)
        got = dict(pipe.read())
        assert got == {i: 40 for i in range(5)}

    def test_sink_fusion_composes_record_chain_into_sinker(self, tmp_path):
        out = str(tmp_path / "parts")
        pipe = (Dampr.memory(list(range(20)))
                .map(lambda x: x * 10)
                .filter(lambda x: x < 100)
                .sink(out))
        g, report = passes.optimize(pipe.pmer.graph, [pipe.source])
        assert report["rules"]["fuse_sinks"] >= 1
        assert all(not isinstance(s, GMap) for s in _executed(g))
        em = pipe.run()
        lines = sorted(int(line) for p in sorted(os.listdir(out))
                       for line in open(os.path.join(out, p)))
        assert lines == [x * 10 for x in range(10)]
        assert em.stats()["plan"]["rules"]["fuse_sinks"] >= 1

    def test_idempotent(self):
        pipe = (Dampr.memory(list(range(50)))
                .map(lambda x: x + 1)
                .map(lambda x: x * 2)
                .fold_by(lambda x: x % 3, operator.add))
        g1, r1 = passes.optimize(pipe.pmer.graph, [pipe.source])
        assert sum(r1["rules"].values()) > 0
        g2, r2 = passes.optimize(g1, [pipe.source])
        assert g2 is g1, "second optimize must be a no-op"
        assert sum(r2["rules"].values()) == 0
        assert graph_signature(g2) == graph_signature(g1)


class TestBarriers:
    """Fusion must not cross checkpoint(), inspect(), sample(), or
    multi-consumer Sources (branch + union shared-prefix reuse)."""

    def _mapper_stages(self, g):
        return [s for s in g.stages if isinstance(s, GMap)]

    def test_checkpoint_is_a_barrier(self):
        pipe = (Dampr.memory(list(range(40)))
                .map(lambda x: x + 1)
                .checkpoint()
                .map(lambda x: x * 2))
        g, report = passes.optimize(pipe.pmer.graph, [pipe.source])
        maps = self._mapper_stages(g)
        # the checkpoint's materialization boundary survives: the stage
        # after it is NOT fused with the stage carrying the barrier (the
        # checkpoint may absorb its private producer — that removes the
        # producer's boundary, never its own)
        barriers = [s for s in maps if (s.options or {}).get("barrier")]
        assert len(barriers) == 1
        assert len(maps) == 2  # [f + checkpoint], [g]
        tail = [s for s in maps if s is not barriers[0]]
        assert tail[0].inputs == [barriers[0].output]
        assert pipe.read() == sorted((x + 1) * 2 for x in range(40))

    def test_cached_pin_survives_and_absorbs_producer(self):
        pipe = (Dampr.memory(list(range(30)))
                .map(lambda x: x + 1)
                .cached()
                .map(lambda x: x * 2))
        g, report = passes.optimize(pipe.pmer.graph, [pipe.source])
        maps = self._mapper_stages(g)
        pinned = [s for s in maps if (s.options or {}).get("memory")]
        assert len(pinned) == 1 and len(maps) == 2
        # the pin's consumer is not fused into it
        assert maps[1].inputs == [pinned[0].output]
        assert pipe.read() == sorted((x + 1) * 2 for x in range(30))

    def test_inspect_is_a_barrier(self, capsys):
        pipe = (Dampr.memory([1, 2])
                .map(lambda x: x + 1)
                .inspect("dbg")
                .map(lambda x: x * 2))
        g, report = passes.optimize(pipe.pmer.graph, [pipe.source])
        assert report["rules"]["fuse_maps"] == 0
        assert len(self._mapper_stages(g)) == 3
        assert sorted(pipe.read()) == [4, 6]
        assert "dbg" in capsys.readouterr().out

    def test_sample_is_a_barrier(self):
        pipe = (Dampr.memory(list(range(100)))
                .map(lambda x: x + 1)
                .sample(0.5)
                .map(lambda x: x * 2))
        g, report = passes.optimize(pipe.pmer.graph, [pipe.source])
        assert report["rules"]["fuse_maps"] == 0
        assert len(self._mapper_stages(g)) == 3

    def test_multi_consumer_source_not_fused(self):
        """A branched prefix (union shared-prefix dedup) computes once and
        is never duplicated into its consumers."""
        base = Dampr.memory(list(range(60))).map(lambda x: x + 1)
        left = base.map(lambda x: x * 2)
        right = base.map(lambda x: -x)
        joined = left.join(right)  # joins on the shared position keys
        out = joined.reduce(lambda l, r: (sorted(l), sorted(r)))
        g, report = passes.optimize(out.pmer.graph, [out.source])
        cons = ir.consumer_counts(g.stages, [out.source])
        multi = [src for src, n in cons.items() if n > 1]
        assert multi, "expected a shared multi-consumer Source to survive"
        # the shared prefix appears exactly once (union dedup preserved,
        # not duplicated into both branches by fusion)
        producers = [s for s in g.stages if s.output in multi]
        assert len(producers) == len(multi)
        got = dict(out.read())
        want = {k: ([(k + 1) * 2], [-(k + 1)]) for k in range(60)}
        assert got == want

    def test_requested_output_never_fused_away(self):
        x = Dampr.memory(list(range(30))).map(lambda v: v + 1)
        y = x.map(lambda v: v * 2)
        # both requested: x's stage must survive even though y is its
        # only graph consumer
        outs = _D.run(x, y)
        assert sorted(outs[0].stream()) == list(range(1, 31))
        assert sorted(outs[1].stream()) == [2 * v for v in range(1, 31)]


class TestDeadStages:
    def test_unreachable_branch_eliminated(self):
        a = Dampr.memory(list(range(25)))
        b = a.map(lambda x: x + 1)
        c = a.map(lambda x: x * 1000)  # never read
        joined = b.join(c)  # union graph holds both branches
        only_b = PMap(b.source, _D(joined.pmer.graph))
        g, report = passes.optimize(only_b.pmer.graph, [only_b.source])
        assert report["rules"]["dead_stages"] >= 1
        em = only_b.run()
        assert sorted(em.read()) == list(range(1, 26))
        assert em.stats()["plan"]["rules"]["dead_stages"] >= 1
        em.delete()

    def test_sinks_always_kept(self, tmp_path):
        out = str(tmp_path / "kept")
        sunk = Dampr.memory([1, 2, 3]).map(str).sink(out)
        # request something unrelated in the same graph: the sink still runs
        g, report = passes.optimize(sunk.pmer.graph, [])
        assert any(isinstance(s, GSink) for s in g.stages)


class TestKillSwitches:
    def _pipe(self):
        return (Dampr.memory(list(range(40)))
                .map(lambda x: x + 1)
                .map(lambda x: x * 2)
                .fold_by(lambda x: x % 5, operator.add))

    def test_optimize_off_runs_constructed_graph(self):
        settings.optimize = False
        em = self._pipe().run()
        plan = em.stats()["plan"]
        assert plan["enabled"] is False
        assert plan["stages_before"] == plan["stages_after"]
        assert len(em.stats) == plan["stages_before"]
        em.delete()

    def test_plan_fuse_off(self):
        settings.plan_fuse = False
        settings.plan_hoist = False
        g, report = passes.optimize(self._pipe().pmer.graph,
                                    [self._pipe().source])
        assert report["rules"]["fuse_maps"] == 0
        assert report["rules"]["hoist_combiners"] == 0

    def test_plan_dead_off(self):
        settings.plan_dead = False
        a = Dampr.memory([1])
        b = a.map(lambda x: x)
        c = a.map(lambda x: -x)
        j = b.join(c)
        only_b = PMap(b.source, _D(j.pmer.graph))
        g, report = passes.optimize(only_b.pmer.graph, [only_b.source])
        assert report["rules"]["dead_stages"] == 0


class TestAdaptive:
    def test_history_drives_sizing_and_results_stable(self, tmp_path):
        # Session-unique name: the history corpus persists under the
        # scratch root across pytest sessions, so a fixed name would
        # make em1's adaptation depend on a PREVIOUS session's records
        # (and, past three sessions, engage the median path on stale
        # measurements from older code).
        import uuid

        name = "plan-adapt-{}".format(uuid.uuid4().hex)
        old_trace, old_dir = settings.trace, settings.trace_dir
        settings.trace = True
        settings.trace_dir = str(tmp_path)
        try:
            def pipe():
                return (Dampr.memory(list(range(2000)))
                        .map(lambda x: (x % 5, x))
                        .fold_by(lambda kv: kv[0], operator.add,
                                 lambda kv: kv[1]))

            em1 = pipe().run(name=name)
            r1 = sorted(em1.read())
            em2 = pipe().run(name=name)
            r2 = sorted(em2.read())
            ad = em2.stats()["plan"]["adaptive"]
            assert ad["applied"] is True
            assert any(c["what"] == "n_partitions" for c in ad["changes"])
            assert r1 == r2
            em2.delete()
        finally:
            settings.trace, settings.trace_dir = old_trace, old_dir

    def test_no_history_static_defaults(self):
        # Unique per invocation: every finalized run now appends to the
        # persistent history corpus under scratch, so a reused name
        # (even pid-salted, across sessions) could find prior history.
        import uuid

        em = (Dampr.memory([1, 2, 3]).map(lambda x: x)
              .run(name="plan-no-history-{}".format(uuid.uuid4().hex)))
        ad = em.stats()["plan"]["adaptive"]
        assert ad["applied"] is False
        assert ad["reason"] in ("no-history", "disabled")
        em.delete()

    def test_explicit_partitions_pinned(self, tmp_path):
        from dampr_tpu.runner import MTRunner

        old_trace, old_dir = settings.trace, settings.trace_dir
        settings.trace = True
        settings.trace_dir = str(tmp_path)
        try:
            pipe = (Dampr.memory(list(range(500)))
                    .map(lambda x: (x % 3, x))
                    .fold_by(lambda kv: kv[0], operator.add,
                             lambda kv: kv[1]))
            import uuid

            name = "plan-pin-{}".format(uuid.uuid4().hex)
            r1 = MTRunner(name, pipe.pmer.graph, n_partitions=7)
            r1.run([pipe.source])
            r2 = MTRunner(name, pipe.pmer.graph, n_partitions=7)
            r2.run([pipe.source])
            assert r2.n_partitions == 7, "explicit partition count retuned"
        finally:
            settings.trace, settings.trace_dir = old_trace, old_dir


class TestSeededSample:
    def test_seeded_sample_reproducible_serial(self):
        old_seed, old_procs = settings.seed, settings.max_processes
        settings.seed, settings.max_processes = 1234, 1
        try:
            def pipe():
                return (Dampr.memory(list(range(400)))
                        .sample(0.5)
                        .map(lambda x: x * 2))

            a = pipe().read()
            b = pipe().read()
            assert a == b, "seeded serial sample must reproduce"
            # and optimized-vs-unoptimized equivalence holds for sampled
            # pipelines (sample at the head: its input chunking is the
            # tap's either way)
            settings.optimize = False
            c = pipe().read()
            settings.optimize = True
            assert a == c
            assert 0 < len(a) < 800
        finally:
            settings.seed, settings.max_processes = old_seed, old_procs

    def test_unseeded_sample_varies(self):
        assert settings.seed is None
        pipe = Dampr.memory(list(range(2000))).sample(0.5)
        a, b = pipe.read(), pipe.read()
        # astronomically unlikely to collide across 2000 coin flips
        assert a != b


class TestObservabilitySurface:
    def test_plan_span_in_trace(self, tmp_path):
        old_trace, old_dir = settings.trace, settings.trace_dir
        settings.trace = True
        settings.trace_dir = str(tmp_path)
        try:
            em = (Dampr.memory(list(range(100))).map(lambda x: x + 1)
                  .run(name="plan-span-test"))
            import json

            with open(em.stats()["trace_file"]) as f:
                doc = json.load(f)
            cats = {ev.get("cat") for ev in doc["traceEvents"]
                    if ev.get("ph") in ("X", "i")}
            assert "plan" in cats
            em.delete()
        finally:
            settings.trace, settings.trace_dir = old_trace, old_dir

    def test_explain_does_not_execute_or_mutate(self):
        pipe = (Dampr.memory([1, 2, 3]).map(lambda x: x + 1)
                .map(lambda x: x * 2))
        before = graph_signature(pipe.pmer.graph)
        text = pipe.explain()
        assert "optimized plan" in text
        assert graph_signature(pipe.pmer.graph) == before

    def test_stats_plan_section_always_present(self):
        em = Dampr.memory([1]).map(lambda x: x).run()
        assert "plan" in em.stats()
        em.delete()
        settings.optimize = False
        em2 = Dampr.memory([1]).map(lambda x: x).run()
        assert em2.stats()["plan"]["enabled"] is False
        em2.delete()
