"""Adversarial feature-INTERSECTION tests: the remaining bugs live where
subsystems compose, so every case here runs several at once — random
pipelines x tiny memory budget x forced mesh exchange/fold x HBM tier x
resume interrupt/rerun — asserting byte-exactness against the pure-Python
oracle (the same generators as test_property_random).
"""

import random

import pytest

from dampr_tpu import Dampr, settings
from test_property_random import _CHAIN_OPS, _TERMINALS, _gen_data


@pytest.fixture(autouse=True)
def crank_everything(tmp_path):
    old = (settings.partitions, settings.mesh_fold, settings.mesh_exchange,
           settings.hbm_budget, settings.hbm_min_records,
           settings.scratch_root)
    settings.partitions = 8
    settings.mesh_fold = "on"
    settings.mesh_exchange = "on"
    settings.hbm_budget = 1 << 20
    settings.hbm_min_records = 1
    settings.scratch_root = str(tmp_path)
    yield
    (settings.partitions, settings.mesh_fold, settings.mesh_exchange,
     settings.hbm_budget, settings.hbm_min_records,
     settings.scratch_root) = old


def _build_case(seed):
    rng = random.Random(seed)
    data = _gen_data(rng)
    oracle = list(data)
    chain = [rng.choice(_CHAIN_OPS)(rng) for _ in range(rng.randrange(0, 4))]
    terminal = rng.choice(_TERMINALS)(rng)
    for _eng, orc, _t in chain:
        oracle = orc(oracle)
    want = terminal[1](oracle)

    def build(extra=None):
        pipe = Dampr.memory(list(data), partitions=rng.choice([2, 5, 8]))
        for eng, _orc, _t in chain:
            pipe = eng(pipe)
        if extra is not None:
            pipe = extra(pipe)
        return terminal[0](pipe)

    return build, want


class TestPressureMeshHBM:
    """Tiny budget x forced mesh paths x HBM tier, random pipelines."""

    @pytest.mark.parametrize("seed", range(0, 40, 2))
    def test_exact_under_all_pressure(self, seed):
        build, want = _build_case(seed)
        got = list(build().run("adv-%d" % seed,
                               memory_budget=1 << 14).read())
        assert sorted(map(repr, got)) == sorted(map(repr, want)), seed


class TestResumeInterruptions:
    """Crash mid-run, then rerun under the same name: completed stages
    resume, the crashed stage recomputes, results stay exact."""

    @pytest.mark.parametrize("seed", range(0, 30, 3))
    def test_bomb_then_rerun(self, seed):
        build, want = _build_case(seed)
        bomb = {"armed": True}

        def fuse_stage(pipe, bomb=bomb):
            def maybe_explode(x):
                if bomb["armed"]:
                    raise RuntimeError("injected failure")
                return x

            return pipe.map(maybe_explode)

        name = "adv-resume-%d" % seed
        with pytest.raises(Exception):
            build(extra=fuse_stage).run(name, resume=True,
                                        memory_budget=1 << 14).read()
        bomb["armed"] = False
        got = list(build(extra=fuse_stage).run(
            name, resume=True, memory_budget=1 << 14).read())
        assert sorted(map(repr, got)) == sorted(map(repr, want)), seed

    @pytest.mark.parametrize("seed", range(1, 30, 3))
    def test_rerun_resumes_exactly(self, seed):
        build, want = _build_case(seed)
        name = "adv-rerun-%d" % seed
        first = list(build().run(name, resume=True,
                                 memory_budget=1 << 14).read())
        second = list(build().run(name, resume=True,
                                  memory_budget=1 << 14).read())
        assert sorted(map(repr, first)) == sorted(map(repr, want)), seed
        assert sorted(map(repr, second)) == sorted(map(repr, first)), seed
