"""Cross-run materialization cache (dampr_tpu/plan/reuse.py): shared-prefix
reuse across runs and incremental recompute over appended corpora.

The exactness contract under test: cached, incremental, and cold
executions of the same pipeline over the same inputs produce identical
results; volatile stages never publish; a corrupted or truncated cache
entry (and an injected ``cache_read`` fault) degrades to recompute —
never to wrong output; concurrent publishers of one key resolve to
exactly one on-disk entry.  See docs/reuse.md.
"""

import json
import operator
import os
import shutil
import tempfile
import threading

import numpy as np
import pytest

from dampr_tpu import Dampr, faults, settings
from dampr_tpu.plan import reuse


@pytest.fixture
def reuse_on(partitions8):
    """Reuse enabled over an isolated cache dir + scratch root, adaptive
    feedback pinned off so the second run keys identically to the first
    (history-driven option changes legitimately shift the key)."""
    old = (settings.reuse, settings.reuse_dir, settings.reuse_budget_bytes,
           settings.scratch_root, settings.plan_adapt)
    settings.reuse = "on"
    settings.reuse_dir = tempfile.mkdtemp(prefix="dampr-reuse-cache-")
    settings.scratch_root = tempfile.mkdtemp(prefix="dampr-reuse-scratch-")
    settings.plan_adapt = False
    yield settings.reuse_dir
    shutil.rmtree(settings.reuse_dir, ignore_errors=True)
    shutil.rmtree(settings.scratch_root, ignore_errors=True)
    (settings.reuse, settings.reuse_dir, settings.reuse_budget_bytes,
     settings.scratch_root, settings.plan_adapt) = old


def _corpus(d, nfiles=3, lines=300, stamp="w"):
    os.makedirs(d, exist_ok=True)
    for i in range(nfiles):
        with open(os.path.join(d, "f{}.txt".format(i)), "w") as f:
            for j in range(lines):
                f.write("{}{} alpha beta gamma\n".format(stamp, j % 11))


def _wordcount(d, binop=operator.add):
    return (Dampr.text(d)
            .flat_map(lambda line: line.split())
            .map(lambda w: (w, 1))
            .fold_by(lambda kv: kv[0], value=lambda kv: kv[1],
                     binop=binop))


def _cold(build):
    """Oracle: the same pipeline with the cache off entirely."""
    old = settings.reuse
    settings.reuse = "off"
    try:
        return sorted(build().run(name="reuse-cold-oracle").stream())
    finally:
        settings.reuse = old


class TestIdenticalRerun:
    def test_second_run_mounts_and_is_identical(self, reuse_on, tmp_path):
        d = str(tmp_path / "data")
        _corpus(d)
        first = _wordcount(d).run(name="reuse-id")
        r1 = sorted(first.stream())
        ru1 = first.stats()["reuse"]
        assert ru1["enabled"] and ru1["bytes_published"] > 0

        second = _wordcount(d).run(name="reuse-id")
        r2 = sorted(second.stream())
        ru2 = second.stats()["reuse"]
        assert r1 == r2
        assert ru2["hits"] >= 1 and ru2["stages_skipped"] >= 1
        kinds = [s["kind"] for s in second.stats]
        assert any(k.startswith("reused-") for k in kinds)
        assert r1 == _cold(lambda: _wordcount(d))

    def test_reuse_off_env_produces_identical_bytes(self, reuse_on,
                                                    tmp_path):
        d = str(tmp_path / "data")
        _corpus(d)
        r_on = sorted(_wordcount(d).run(name="reuse-on-leg").stream())
        settings.reuse = "off"
        off = _wordcount(d).run(name="reuse-off-leg")
        assert sorted(off.stream()) == r_on
        assert "reuse" not in off.stats()

    def test_volatile_stage_never_cached(self, reuse_on, tmp_path):
        class Opaque:
            __slots__ = ()

            def __reduce__(self):
                raise TypeError("nope")

            def __call__(self, x):
                return (x % 3, 1)

        def build():
            return (Dampr.memory(list(range(30)), partitions=4)
                    .map(Opaque())
                    .fold_by(lambda kv: kv[0], value=lambda kv: kv[1],
                             binop=operator.add))

        got1 = dict(build().run(name="reuse-volatile").stream())
        second = build().run(name="reuse-volatile")
        got2 = dict(second.stream())
        assert got1 == got2 == {0: 10, 1: 10, 2: 10}
        ru = second.stats()["reuse"]
        assert ru["hits"] == 0
        assert any(d["decision"] == "volatile" for d in ru["decisions"])
        # Nothing from the volatile chain may have landed on disk.
        entries = os.path.join(reuse_on, "entries")
        assert not os.path.isdir(entries) or not os.listdir(entries)


class TestDegrade:
    def _seed(self, tmp_path):
        d = str(tmp_path / "data")
        _corpus(d)
        out = _wordcount(d).run(name="reuse-degrade")
        return d, sorted(out.stream())

    def _entry_dirs(self, cache_root):
        ed = os.path.join(cache_root, "entries")
        return [os.path.join(ed, n) for n in sorted(os.listdir(ed))
                if not n.startswith(".tmp-")]

    def test_corrupt_manifest_recomputes(self, reuse_on, tmp_path):
        d, r1 = self._seed(tmp_path)
        for e in self._entry_dirs(reuse_on):
            with open(os.path.join(e, "manifest.json"), "w") as f:
                f.write("{ not json !!")
        out = _wordcount(d).run(name="reuse-degrade")
        ru = out.stats()["reuse"]
        assert sorted(out.stream()) == r1
        assert ru["recompute_fallbacks"] >= 1 and ru["stages_skipped"] == 0

    def test_truncated_block_recomputes(self, reuse_on, tmp_path):
        d, r1 = self._seed(tmp_path)
        truncated = 0
        for e in self._entry_dirs(reuse_on):
            for fn in os.listdir(e):
                if fn.endswith(".frames"):
                    p = os.path.join(e, fn)
                    with open(p, "r+b") as f:
                        f.truncate(max(0, os.path.getsize(p) // 2))
                    truncated += 1
        assert truncated
        out = _wordcount(d).run(name="reuse-degrade")
        ru = out.stats()["reuse"]
        assert sorted(out.stream()) == r1
        assert ru["recompute_fallbacks"] >= 1

    def test_cache_read_fault_site_degrades(self, reuse_on, tmp_path):
        d, r1 = self._seed(tmp_path)
        faults.install(faults.FaultPlan("cache_read:p=1.0"))
        try:
            out = _wordcount(d).run(name="reuse-degrade")
            ru = out.stats()["reuse"]
            assert sorted(out.stream()) == r1
            assert ru["recompute_fallbacks"] >= 1
            # Chaos runs consume but never seed the shared cache.
            assert ru["bytes_published"] == 0
        finally:
            faults.clear()


class TestEviction:
    def test_tight_budget_evicts_lru_whole_entries(self, reuse_on,
                                                   tmp_path):
        d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
        _corpus(d1, stamp="aa")
        _corpus(d2, stamp="bb")
        first = _wordcount(d1).run(name="reuse-evict")
        published = first.stats()["reuse"]["bytes_published"]
        assert published > 0
        # Room for roughly one run's worth of entries, not two.
        settings.reuse_budget_bytes = int(published * 1.25)
        second = _wordcount(d2).run(name="reuse-evict")
        ru = second.stats()["reuse"]
        assert ru["evictions"] >= 1
        store = reuse.CacheStore()
        assert store.total_bytes() <= settings.reuse_budget_bytes
        # Evicted prefix for d1 is gone -> a d1 rerun recomputes, exactly.
        r1 = sorted(_wordcount(d1).run(name="reuse-evict").stream())
        assert r1 == sorted(first.stream())

    def test_single_entry_over_budget_is_declined(self, reuse_on,
                                                  tmp_path):
        d = str(tmp_path / "data")
        _corpus(d)
        settings.reuse_budget_bytes = 64  # smaller than any real entry
        out = _wordcount(d).run(name="reuse-declined")
        assert out.stats()["reuse"]["bytes_published"] == 0
        entries = os.path.join(reuse_on, "entries")
        names = (os.listdir(entries) if os.path.isdir(entries) else [])
        assert not [n for n in names if not n.startswith(".tmp-")]


class TestIncremental:
    def test_append_only_growth_merges_partials(self, reuse_on, tmp_path):
        d = str(tmp_path / "data")
        _corpus(d, nfiles=3)
        _wordcount(d).run(name="reuse-incr")
        with open(os.path.join(d, "f3.txt"), "w") as f:
            for j in range(80):
                f.write("new{} appended tokens\n".format(j % 5))
        out = _wordcount(d).run(name="reuse-incr")
        ru = out.stats()["reuse"]
        assert ru["incremental_merges"] >= 1
        assert any(d_["decision"].startswith("incremental:")
                   for d_ in ru["decisions"])
        assert sorted(out.stream()) == _cold(lambda: _wordcount(d))

    def test_grown_file_forces_full_recompute(self, reuse_on, tmp_path):
        d = str(tmp_path / "data")
        _corpus(d)
        _wordcount(d).run(name="reuse-grown")
        with open(os.path.join(d, "f0.txt"), "a") as f:
            f.write("tail grew beyond the signed chunks\n")
        out = _wordcount(d).run(name="reuse-grown")
        ru = out.stats()["reuse"]
        assert ru["incremental_merges"] == 0
        assert sorted(out.stream()) == _cold(lambda: _wordcount(d))

    def test_uncertified_fold_is_ineligible(self, reuse_on, tmp_path):
        d = str(tmp_path / "data")
        _corpus(d)
        binop = lambda a, b: a + b  # noqa: E731 — no assoc certificate
        _wordcount(d, binop).run(name="reuse-lam")
        with open(os.path.join(d, "f3.txt"), "w") as f:
            for j in range(50):
                f.write("more{} appended tokens\n".format(j % 5))
        out = _wordcount(d, binop).run(name="reuse-lam")
        ru = out.stats()["reuse"]
        assert ru["incremental_merges"] == 0
        assert any(x["decision"].startswith("incremental-ineligible")
                   for x in ru["decisions"])
        assert sorted(out.stream()) == _cold(
            lambda: _wordcount(d, binop))


class TestConcurrentPublish:
    def test_race_resolves_to_one_winner(self, reuse_on):
        from dampr_tpu.blocks import Block
        from dampr_tpu.runner import MTRunner
        from dampr_tpu.storage import PartitionSet

        runner = MTRunner("reuse-race", Dampr.memory([1]).pmer.graph)
        try:
            def mk_pset():
                pset = PartitionSet(2)
                blk = Block(np.arange(20, dtype=np.int64),
                            np.arange(20, dtype=np.int64) * 3)
                for pid, sub in blk.split_by_partition(2).items():
                    pset.add(pid, runner.store.register(sub))
                return pset

            key = reuse._resume._h("race-key")
            struct = reuse._resume._h("race-struct")
            cache = reuse.CacheStore()
            barrier = threading.Barrier(2)
            landed = []

            def publish():
                pset = mk_pset()
                barrier.wait()
                landed.append(cache.publish(
                    key, struct, pset, 20, None, runner.store))

            ts = [threading.Thread(target=publish) for _ in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert any(b > 0 for b in landed)
            entries = [n for n in os.listdir(
                os.path.join(reuse_on, "entries"))
                if not n.startswith(".tmp-")]
            assert len(entries) == 1
            m = cache.lookup(key)  # winner validates end-to-end
            pset, nrec, _ = cache.mount(m, runner.store)
            got = sorted(
                (int(k), int(v))
                for refs in pset.parts.values() for ref in refs
                for k, v in ref.get().iter_pairs())
            assert got == [(i, i * 3) for i in range(20)]
        finally:
            runner.store.cleanup()


class TestSurfaces:
    def test_stats_renderer_shows_reuse_section(self):
        from dampr_tpu.obs import export

        text = export.format_summary({
            "run": "r", "wall_seconds": 1.0, "stages": [],
            "reuse": {"enabled": True, "hits": 2, "misses": 1,
                      "stages_skipped": 2, "bytes_mounted": 1024,
                      "bytes_published": 0, "incremental_merges": 1,
                      "recompute_fallbacks": 0, "evictions": 0,
                      "decisions": [{"stage": 1, "decision": "hit"}]},
        })
        assert "reuse: 2 hit(s)" in text
        assert "s1=hit" in text

    def test_explain_has_reuse_preview(self, reuse_on, tmp_path):
        d = str(tmp_path / "data")
        _corpus(d)
        _wordcount(d).run(name="reuse-explain")
        text = _wordcount(d).explain()
        assert "reuse: cache" in text
        assert "would mount" in text

    def test_explain_reuse_off_one_liner(self):
        old = settings.reuse
        settings.reuse = "off"
        try:
            text = Dampr.memory([1, 2, 3]).map(lambda x: x).explain()
            assert "reuse: off" in text
        finally:
            settings.reuse = old

    def test_trace_carries_reuse_spans(self, reuse_on, tmp_path):
        old_tr, old_td = settings.trace, settings.trace_dir
        settings.trace = True
        settings.trace_dir = str(tmp_path / "traces")
        try:
            d = str(tmp_path / "data")
            _corpus(d)
            _wordcount(d).run(name="reuse-traced")
            out = _wordcount(d).run(name="reuse-traced")
            tf = out.stats().get("trace_file")
            assert tf and os.path.isfile(tf)
            with open(tf) as f:
                cats = {e.get("cat") for e in
                        json.load(f)["traceEvents"]}
            assert "reuse" in cats
        finally:
            settings.trace, settings.trace_dir = old_tr, old_td

    def test_doctor_thrash_finding(self, tmp_path):
        from dampr_tpu.obs import doctor

        stats = {
            "schema": "dampr-tpu-stats/1", "run": "thrash-run",
            "wall_seconds": 5.0, "stages": [],
            "reuse": {"enabled": True, "hits": 0, "misses": 4,
                      "evictions": 6, "bytes_published": 123456},
        }
        p = tmp_path / "stats.json"
        with open(p, "w") as f:
            json.dump(stats, f)
        rep = doctor.diagnose(str(p))
        f = [x for x in rep["findings"]
             if x["bottleneck"] == "reuse-thrash"]
        assert f, rep["findings"]
        assert any(s["setting"] == "reuse_budget_bytes"
                   for s in f[0]["suggestions"])
        assert rep["reuse"]["evictions"] == 6

    def test_doctor_playbook_reuse_knobs_exist(self):
        from dampr_tpu.obs.doctor import _PLAYBOOK

        for verdict in ("reuse-thrash", "reuse-off"):
            assert verdict in _PLAYBOOK
            for knob, _env, _fn, _why in _PLAYBOOK[verdict]:
                assert hasattr(settings, knob), knob
