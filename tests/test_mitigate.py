"""Straggler mitigation (dampr_tpu.parallel.mitigate): the controller
state machine (engage after N pathological windows, probe cadence,
clean disengage, sticky down-weight + deterministic weighted routing),
first-result-wins exactly-once commits under racing duplicate attempts
(including a loser completing AFTER the winner committed), work-stealing
dispatch, end-to-end engine exactness with mitigation on, speculative
re-execution of an injected straggler job, the CAMR coded-exchange
exactness pin, the faults ``duration_ms`` windowed-slowness grammar,
the zero-overhead disabled-path pin, and the doctor/history/schema
surfaces."""

import json
import operator
import os
import threading
import time

import numpy as np
import pytest

from dampr_tpu import Dampr, faults, settings
from dampr_tpu.parallel import mitigate

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_mitigate():
    saved = (settings.mitigate, settings.speculate_threshold,
             settings.speculate_after_steps,
             settings.mitigate_probe_windows, settings.exchange_coding,
             settings.mesh_fold, settings.mesh_exchange,
             settings.small_stage_bytes, settings.max_processes,
             settings.faults, settings.job_retries)
    yield
    (settings.mitigate, settings.speculate_threshold,
     settings.speculate_after_steps, settings.mitigate_probe_windows,
     settings.exchange_coding, settings.mesh_fold,
     settings.mesh_exchange, settings.small_stage_bytes,
     settings.max_processes, settings.faults,
     settings.job_retries) = saved
    faults.clear()
    mitigate._active = None


def _ctl(threshold=1.5, after=2, probe=3, run=None, skip_safe=True):
    # skip_safe=True: unit tests exercise the degrade path directly;
    # production resolves it from settings.exchange_timeout_ms (window
    # skipping is only enabled under an armed exchange watchdog).
    return mitigate.MitigationController(
        run_name=run, threshold=threshold, after=after,
        probe_every=probe, skip_safe=skip_safe)


def _late(rank, seconds, healthy_rank=0):
    """A 2-rank window observation: ``rank`` enters ``seconds`` late."""
    out = {healthy_rank: 0.0, rank: seconds}
    return out


class TestControllerStateMachine:
    def test_engages_after_consecutive_pathological_windows(self):
        ctl = _ctl(after=3)
        for i in range(2):
            ctl.observe_window(_late(1, 0.4))
            assert not ctl.engaged, i
        ctl.observe_window(_late(1, 0.4))
        assert ctl.engaged
        assert ctl.engagements == 1
        assert ctl.straggler == 1
        assert ctl.last_late_ratio == pytest.approx(2.0)

    def test_jitter_below_spread_floor_never_engages(self):
        ctl = _ctl(after=1)
        for _ in range(10):
            # ratio is huge but the absolute spread is sub-floor noise
            ctl.observe_window(_late(1, mitigate.MIN_SPREAD_S / 4))
        assert not ctl.engaged and ctl.engagements == 0

    def test_interrupted_streak_resets(self):
        ctl = _ctl(after=3)
        ctl.observe_window(_late(1, 0.4))
        ctl.observe_window(_late(1, 0.4))
        ctl.observe_window({0: 0.0, 1: 0.0})  # healthy window
        ctl.observe_window(_late(1, 0.4))
        ctl.observe_window(_late(1, 0.4))
        assert not ctl.engaged

    def test_probe_cadence_and_clean_disengage(self):
        ctl = _ctl(after=2, probe=3)
        for _ in range(2):
            ctl.observe_window(_late(1, 0.4))
        assert ctl.engaged
        # While engaged: two skips then a probe, deterministic cadence.
        decisions = [ctl.use_collective() for _ in range(6)]
        assert decisions == [False, False, True, False, False, True]
        assert ctl.windows_skipped == 4
        # Healthy probes disengage after `after` consecutive ones.
        ctl.observe_window({0: 0.0, 1: 0.0})
        assert ctl.engaged
        ctl.observe_window({0: 0.0, 1: 0.0})
        assert not ctl.engaged
        assert ctl.disengagements == 1
        # Disengaged: every window crosses the mesh again.
        assert all(ctl.use_collective() for _ in range(4))

    def test_pathological_probe_keeps_it_engaged(self):
        ctl = _ctl(after=2, probe=2)
        for _ in range(2):
            ctl.observe_window(_late(1, 0.4))
        assert ctl.engaged
        ctl.observe_window({0: 0.0, 1: 0.0})   # healthy probe #1
        ctl.observe_window(_late(1, 0.4))      # still slow: streak resets
        ctl.observe_window({0: 0.0, 1: 0.0})
        assert ctl.engaged
        ctl.observe_window({0: 0.0, 1: 0.0})
        assert not ctl.engaged

    def test_sticky_downweight_after_double_streak(self):
        ctl = _ctl(after=2)
        for _ in range(4):
            ctl.observe_window(_late(1, 0.4))
        assert ctl.engaged
        assert ctl.downweights.get(1) is not None
        w = ctl.downweights[1]
        assert 0.25 <= w <= 0.75
        # Sticky: recovery disengages but never removes the down-weight.
        for _ in range(4):
            ctl.observe_window({0: 0.0, 1: 0.0})
        assert not ctl.engaged
        assert ctl.downweights.get(1) == w
        actions = [e["action"] for e in ctl.events]
        assert actions.count("engage") == 1
        assert actions.count("downweight") == 1
        assert actions.count("disengage") == 1

    def test_fault_rate_triggers_downweight_without_lateness(self):
        ctl = _ctl(after=2)
        bar = mitigate._FAULT_FACTOR
        # Counts are CUMULATIVE; the controller differences them — a
        # rank still absorbing >= _FAULT_FACTOR new retries per window
        # stays pathological.
        for w in range(1, 5):
            ctl.observe_window({0: 0.0, 1: 0.0},
                               fault_counts={0: 0, 1: bar * w})
        assert 1 in ctl.downweights

    def test_fault_burst_that_ends_goes_healthy_again(self):
        """An old retry burst must not pin a recovered rank bad forever
        — the cumulative count stops moving, the delta goes to zero,
        and an engaged mitigation disengages."""
        ctl = _ctl(after=2)
        bar = mitigate._FAULT_FACTOR
        ctl.observe_window({0: 0.0, 1: 0.0}, fault_counts={1: bar})
        ctl.observe_window({0: 0.0, 1: 0.0}, fault_counts={1: 2 * bar})
        assert ctl.engaged
        # Burst over: the cumulative count freezes; deltas are 0.
        ctl.observe_window({0: 0.0, 1: 0.0}, fault_counts={1: 2 * bar})
        ctl.observe_window({0: 0.0, 1: 0.0}, fault_counts={1: 2 * bar})
        assert not ctl.engaged
        assert ctl.disengagements == 1

    def test_route_table_weighted_and_deterministic(self):
        ctl = _ctl(after=1)
        assert ctl.route_table(8, 2) is None  # no down-weights yet
        for _ in range(2):
            ctl.observe_window(_late(1, 0.4))
        table = ctl.route_table(8, 2)
        assert table is not None
        assert table == ctl.route_table(8, 2)  # cached + deterministic
        counts = {d: table.count(d) for d in set(table)}
        # rank 1 owns devices 4..7: down-weighted share is strictly
        # smaller per device than rank 0's.
        assert max(counts.get(d, 0) for d in (4, 5, 6, 7)) < counts[0]
        assert set(table) == set(range(8))  # every device still serves

    def test_skip_requires_armed_watchdog(self):
        """Degrade-in-place is gated on the exchange watchdog: without
        exchange_timeout_ms armed, an engaged controller never skips a
        collective (a diverged skip would hang gloo unboundedly) —
        stealing/speculation/down-weighting stay active."""
        assert settings.exchange_timeout_ms == 0
        ctl = mitigate.MitigationController(threshold=1.5, after=1)
        assert ctl.skip_safe is False
        for _ in range(4):
            ctl.observe_window(_late(1, 0.4))
        assert ctl.engaged
        assert all(ctl.use_collective() for _ in range(6))
        assert ctl.windows_skipped == 0
        assert ctl.collective_fold_ok()  # fold declines only when safe
        assert 1 in ctl.downweights     # down-weighting still engages
        saved = settings.exchange_timeout_ms
        settings.exchange_timeout_ms = 5000
        try:
            armed = mitigate.MitigationController(threshold=1.5, after=1)
            assert armed.skip_safe is True
        finally:
            settings.exchange_timeout_ms = saved

    def test_summary_shape(self):
        ctl = _ctl(after=1)
        ctl.observe_window(_late(1, 0.4))
        ctl.note_steal()
        ctl.note_speculation(win=True)
        ctl.note_speculation(win=False)
        s = ctl.summary()
        assert s["enabled"] and s["engaged"]
        assert s["stolen_partitions"] == 1
        assert s["speculative_attempts"] == 2
        assert s["speculative_wins"] == 1
        assert s["straggler_rank"] == 1
        assert json.dumps(s)  # JSON-safe

    def test_events_land_in_faults_sidecar(self, tmp_path):
        saved = settings.scratch_root
        settings.scratch_root = str(tmp_path)
        try:
            ctl = _ctl(after=1, run="mitrun")
            for _ in range(2):
                ctl.observe_window(_late(1, 0.5))
            evs = faults.load_events("mitrun")
            kinds = [(e["kind"], e.get("action")) for e in evs]
            assert ("mitigation", "engage") in kinds
            assert ("mitigation", "downweight") in kinds
        finally:
            settings.scratch_root = saved


class TestFirstResultWinsExactlyOnce:
    """The attempt-scoped-commit contract under racing duplicates: of N
    attempts exactly one lands its registrations; every loser — even one
    completing after the winner committed — rolls back."""

    def _store(self, name):
        from dampr_tpu import storage

        return storage.RunStore(name)

    def test_loser_completing_after_winner_rolls_back(self, tmp_path):
        from dampr_tpu.blocks import Block

        saved = settings.scratch_root
        settings.scratch_root = str(tmp_path)
        try:
            store = self._store("frw")
            ctl = _ctl()
            release = threading.Event()
            calls = {"n": 0}
            lock = threading.Lock()

            def fn(job):
                with lock:
                    calls["n"] += 1
                    attempt = calls["n"]
                if attempt == 1:
                    # Primary: wedged until AFTER the speculative
                    # duplicate has committed.
                    release.wait(timeout=30)
                blk = Block.from_lists(list(range(64)), [1] * 64)
                ref = store.register(blk)
                return [ref]

            results = {}

            def primary():
                results["out"] = mitigate.pool_dispatch(
                    ctl, fn, [0], 1, store=store, speculative=False)

            # Drive the two attempts by hand through the same claim
            # machinery pool_dispatch uses: attempt A (slow) and
            # attempt B (fast) race on one job.
            committed = [False]
            winner_refs, loser_rolled = [], []

            def attempt(slow):
                try:
                    with store.attempt() as refs:
                        if slow:
                            release.wait(timeout=30)
                        blk = Block.from_lists(list(range(64)), [1] * 64)
                        store.register(blk)
                        with lock:
                            if committed[0]:
                                raise mitigate._SpeculationLost()
                            committed[0] = True
                            winner_refs.extend(refs)
                except mitigate._SpeculationLost:
                    loser_rolled.append(True)

            t_slow = threading.Thread(target=attempt, args=(True,))
            t_fast = threading.Thread(target=attempt, args=(False,))
            t_slow.start()
            t_fast.start()
            t_fast.join(timeout=30)
            assert committed[0]
            release.set()  # loser now completes, after the commit
            t_slow.join(timeout=30)
            assert loser_rolled == [True]
            assert len(winner_refs) == 1
            # Exactly the winner's block is store-resident: the loser's
            # registration was rolled back without leaking budget.
            assert len(store._resident) == 1
            assert store._resident_bytes == winner_refs[0].nbytes
        finally:
            settings.scratch_root = saved

    def test_speculative_dispatch_exactly_once_end_to_end(self, tmp_path):
        from dampr_tpu.blocks import Block

        saved = settings.scratch_root
        settings.scratch_root = str(tmp_path)
        try:
            store = self._store("frw2")
            ctl = _ctl(threshold=1.5)
            attempts = {"n": 0}
            lock = threading.Lock()

            def fn(job):
                with lock:
                    attempts["n"] += 1
                if job == 7:
                    with lock:
                        first = attempts["n"] <= 8
                    if first and not fn_fast[0]:
                        time.sleep(1.0)  # the straggler's first attempt
                blk = Block.from_lists([job] * 32, [1] * 32)
                store.register(blk)
                return job * 10

            fn_fast = [False]
            out = mitigate.pool_dispatch(ctl, fn, list(range(8)), 4,
                                         store=store, speculative=True)
            assert out == [j * 10 for j in range(8)]
            # One committed registration per JOB regardless of how many
            # attempts ran (speculation may or may not have fired on
            # this box; the invariant is exactly-once either way).
            assert len(store._resident) == 8
        finally:
            settings.scratch_root = saved

    def test_randomized_exactly_once_property(self, tmp_path):
        from dampr_tpu.blocks import Block

        saved = settings.scratch_root
        settings.scratch_root = str(tmp_path)
        rng = np.random.RandomState(7)
        try:
            for round_i in range(5):
                store = self._store("frwp{}".format(round_i))
                ctl = _ctl(threshold=1.2)
                delays = rng.uniform(0.0, 0.08, size=10)
                delays[rng.randint(0, 10)] = 0.4  # one straggler

                def fn(job, _d=delays):
                    time.sleep(float(_d[job]))
                    store.register(
                        Block.from_lists([job] * 16, [1] * 16))
                    return job

                out = mitigate.pool_dispatch(
                    ctl, fn, list(range(10)), 4, store=store,
                    speculative=True)
                assert out == list(range(10)), round_i
                assert len(store._resident) == 10, (
                    round_i, ctl.summary())
        finally:
            settings.scratch_root = saved

    def test_primary_failure_with_winning_duplicate_succeeds(
            self, tmp_path):
        """A failure only counts once no attempt of the job can land a
        result: the straggler's primary attempt dies while its
        speculative duplicate is still running — the duplicate's commit
        makes the dispatch succeed."""
        saved = settings.scratch_root
        settings.scratch_root = str(tmp_path)
        try:
            store = self._store("frwpf")
            ctl = _ctl(threshold=1.2)
            attempts = {0: 0}
            lock = threading.Lock()

            def fn(job):
                if job != 0:
                    time.sleep(0.02)
                    return job
                with lock:
                    attempts[0] += 1
                    first = attempts[0] == 1
                if first:
                    time.sleep(0.4)       # straggle until the spec
                    raise OSError("primary died late")
                time.sleep(0.5)           # duplicate outlives the death
                return 0

            out = mitigate.pool_dispatch(ctl, fn, list(range(6)), 3,
                                         store=store, speculative=True)
            assert out == list(range(6))
            assert ctl.speculative_wins >= 1, ctl.summary()
        finally:
            settings.scratch_root = saved

    def test_job_failure_still_fails_dispatch(self, tmp_path):
        saved = settings.scratch_root
        settings.scratch_root = str(tmp_path)
        try:
            store = self._store("frwf")
            ctl = _ctl()

            def fn(job):
                if job == 3:
                    raise ValueError("boom")
                return job

            with pytest.raises(ValueError):
                mitigate.pool_dispatch(ctl, fn, list(range(6)), 3,
                                       store=store, speculative=True)
        finally:
            settings.scratch_root = saved


class TestWorkStealing:
    def test_idle_workers_steal_from_backlogged_queue(self):
        ctl = _ctl()
        slow_worker_jobs = {0, 2, 4, 6}  # dealt to worker 0 of 2

        def fn(job):
            if job in slow_worker_jobs:
                time.sleep(0.15)
            return job

        t0 = time.perf_counter()
        out = mitigate.pool_dispatch(ctl, fn, list(range(8)), 2,
                                     store=None, speculative=False)
        wall = time.perf_counter() - t0
        assert out == list(range(8))
        assert ctl.stolen_partitions >= 1
        # 4 slow jobs x 0.15s serial on one worker = 0.6s; stealing
        # spreads them over 2 workers (generous bound for slow CI).
        assert wall < 0.6


class TestEngineEndToEnd:
    def test_disabled_path_pin(self, tmp_path):
        saved = settings.scratch_root
        settings.scratch_root = str(tmp_path)
        try:
            assert not settings.mitigate_enabled()
            em = (Dampr.memory([(i % 5, i) for i in range(500)],
                               partitions=4)
                  .group_by(lambda x: x[0])
                  .reduce(lambda k, vs: len(list(vs)))
                  .run(name="mit-off"))
            assert mitigate.active() is None
            assert "mitigation" not in em.stats()
            em.delete()
        finally:
            settings.scratch_root = saved

    def test_mitigated_run_byte_identical(self, tmp_path):
        saved = settings.scratch_root
        settings.scratch_root = str(tmp_path)
        settings.max_processes = 4
        try:
            data = [((i * 7919) % 101, i) for i in range(4000)]

            def pipe():
                return (Dampr.memory(data, partitions=8)
                        .map(lambda x: (x[0], x[1] * 3))
                        .group_by(lambda x: x[0])
                        .reduce(lambda k, vs: sorted(
                            v[1] for v in vs)[:3]))

            base = sorted(map(repr, pipe().run(name="mit-base").read()))
            settings.mitigate = "on"
            em = pipe().run(name="mit-on")
            got = sorted(map(repr, em.read()))
            s = em.stats()
            assert got == base
            assert s["mitigation"]["enabled"]
            assert s["plan"]["mitigation"]["engagements"] == 0
            em.delete()
        finally:
            settings.scratch_root = saved

    def test_speculative_win_on_injected_straggler_job(self, tmp_path):
        """One map job stalls 1.2s via the fault harness; with three
        fast siblings done, an idle worker speculatively re-executes it
        (the re-run's fault invocation has moved past the window) and
        wins — results byte-identical to an uninjected run."""
        saved = settings.scratch_root
        settings.scratch_root = str(tmp_path)
        settings.max_processes = 4
        try:
            data = [(i % 16, i) for i in range(8000)]

            def pipe():
                return (Dampr.memory(data, partitions=4)
                        .map(lambda x: (x[0], x[1] + 1))
                        .group_by(lambda x: x[0])
                        .reduce(lambda k, vs: sum(v[1] for v in vs)))

            base = sorted(pipe().run(name="spec-base").read())
            settings.mitigate = "on"
            settings.speculate_threshold = 1.5
            # nth=1: exactly the first udf-batch invocation stalls —
            # one straggler job; every other attempt runs clean.
            settings.faults = "udf:nth=1,sleep_ms=1200"
            em = pipe().run(name="spec-on")
            got = sorted(em.read())
            s = em.stats()
            assert got == base
            mit = s["mitigation"]
            assert mit["speculative_attempts"] >= 1, mit
            assert mit["speculative_wins"] >= 1, mit
            em.delete()
        finally:
            settings.scratch_root = saved

    def test_coded_exchange_byte_exact_and_fewer_bytes(self, tmp_path):
        saved = settings.scratch_root
        settings.scratch_root = str(tmp_path)
        settings.mesh_fold = "off"
        settings.mesh_exchange = "on"
        settings.small_stage_bytes = 1024  # past the tiny-fold path
        try:
            data = [(i % 50, 1) for i in range(20000)]

            def pipe():
                return (Dampr.memory(data, partitions=8)
                        .fold_by(lambda x: x[0], operator.add,
                                 value=lambda x: x[1]))

            base = sorted(pipe().run(name="coded-off").read())
            settings.exchange_coding = "camr"
            em = pipe().run(name="coded-on")
            got = sorted(em.read())
            s = em.stats()
            assert got == base
            cod = s["mesh"]["exchange"].get("coding")
            assert cod is not None, s["mesh"]["exchange"]
            assert cod["windows"] >= 1
            assert cod["coded_bytes"] < cod["raw_bytes"]
            assert 0.0 < cod["savings_fraction"] <= 1.0
            # The plan report marks the armed mode.
            assert (s["plan"].get("shuffle") or {}).get(
                "coding") == "camr"
            em.delete()
        finally:
            settings.scratch_root = saved

    def test_coded_exchange_float_sum_ships_raw(self, tmp_path):
        """Float sums are excluded from the pre-fold (summation order
        would drift ulps): results still exact, no coded savings."""
        saved = settings.scratch_root
        settings.scratch_root = str(tmp_path)
        settings.mesh_fold = "off"
        settings.mesh_exchange = "on"
        settings.small_stage_bytes = 1024
        settings.exchange_coding = "camr"
        try:
            data = [(i % 10, 0.5) for i in range(20000)]
            em = (Dampr.memory(data, partitions=4)
                  .fold_by(lambda x: x[0], operator.add,
                           value=lambda x: x[1])
                  .run(name="coded-float"))
            got = sorted(map(repr, em.read()))
            assert got  # results materialized exactly
            cod = em.stats()["mesh"]["exchange"].get("coding")
            if cod is not None:
                # windows may still count, but floats never fold:
                assert cod["coded_bytes"] == cod["raw_bytes"]
            em.delete()
        finally:
            settings.scratch_root = saved


class TestFaultsDurationWindow:
    def test_duration_window_expires(self):
        rule = faults.SiteRule("exchange_step", sleep_ms=1,
                               duration_ms=150, times=None)
        assert rule.should_fire()        # inside the window
        assert rule.should_fire()
        time.sleep(0.2)
        assert not rule.should_fire()    # window over: recovered
        assert not rule.should_fire()

    def test_duration_parses_and_describes(self):
        p = faults.FaultPlan(
            "exchange_step:rank=1,sleep_ms=400,every=2,duration_ms=5000")
        r = p.rules["exchange_step"]
        assert r.duration_ms == 5000 and r.sleep_ms == 400
        assert r.describe()["duration_ms"] == 5000

    def test_windowed_slow_site_end_to_end(self):
        plan = faults.FaultPlan(
            "fold:sleep_ms=30,duration_ms=120;seed=3")
        faults.install(plan)
        try:
            t0 = time.perf_counter()
            faults.check("fold")
            first = time.perf_counter() - t0
            assert first >= 0.025
            time.sleep(0.15)
            t0 = time.perf_counter()
            faults.check("fold")
            assert time.perf_counter() - t0 < 0.02
        finally:
            faults.clear()


class TestSurfaces:
    def test_new_knobs_exist_and_snapshot(self):
        from dampr_tpu.obs import history

        for knob in ("mitigate", "speculate_threshold",
                     "speculate_after_steps", "mitigate_probe_windows",
                     "exchange_coding"):
            assert hasattr(settings, knob)
            assert knob in history._KNOBS
        snap = history._settings_snapshot()
        assert snap["speculate_threshold"] == settings.speculate_threshold

    def test_skew_playbook_names_mitigation_knobs(self):
        from dampr_tpu.obs import doctor

        knobs = [k for k, _e, _p, _w in doctor._PLAYBOOK["skew"]]
        for knob in ("mitigate", "speculate_threshold",
                     "speculate_after_steps", "exchange_coding"):
            assert knob in knobs
            assert hasattr(settings, knob)

    def _mit_summary(self, engaged=True):
        return {
            "enabled": True, "engaged": False, "observations": 9,
            "engagements": 1 if engaged else 0, "disengagements": 1,
            "windows_skipped": 4, "speculative_attempts": 2,
            "speculative_wins": 1, "stolen_partitions": 3,
            "straggler_rank": 1, "last_late_ratio": 2.4,
            "downweighted_ranks": {"1": 0.42}, "events": [],
        }

    def _fleet_summary(self, tmp_path, mitigation):
        from dampr_tpu.obs import export

        run = "mitdoc"
        summary = {
            "schema": export.STATS_SCHEMA, "run": run,
            "process": {"process_id": 0, "num_processes": 2},
            "started_at": 0.0, "wall_seconds": 10.0,
            "n_partitions": 4, "stages": [
                {"stage": 1, "kind": "reduce", "jobs": 2, "seconds": 9.0,
                 "records_in": 10, "records_out": 5, "bytes_in": 100,
                 "bytes_out": 50, "spill_count": 0, "spill_bytes": 0,
                 "merge_gens": 0, "merge_gen_bytes": 0, "retries": 0,
                 "quarantined": 0, "target": "host",
                 "shuffle_target": None}],
            "totals": {"records_out": 5, "bytes_out": 50,
                       "spill_bytes": 0},
            "fleet": {
                "num_processes": 2, "ranks": [0, 1], "missing_ranks": [],
                "alignment": "clock",
                "per_rank": [{"rank": 0, "wall_seconds": 5.0},
                             {"rank": 1, "wall_seconds": 10.0}],
                "skew": {"steps": [{"step": 0}], "skew_seconds": 4.0,
                         "max_fraction": 0.8, "mean_fraction": 0.6,
                         "straggler_rank": 1,
                         "mean_entry_lateness": {"0": 0.0, "1": 2.0},
                         "late_ratio": 2.0},
                "mitigation": mitigation,
            },
            "mitigation": mitigation,
        }
        tdir = os.path.join(str(tmp_path), run, "trace")
        os.makedirs(tdir, exist_ok=True)
        path = os.path.join(tdir, "stats.json")
        with open(path, "w") as f:
            json.dump(summary, f)
        return path

    def test_doctor_names_mitigation_in_skew_finding(self, tmp_path):
        from dampr_tpu.obs import doctor

        path = self._fleet_summary(tmp_path, self._mit_summary())
        report = doctor.diagnose(path)
        skews = [f for f in report["findings"]
                 if f["bottleneck"] == "skew"]
        assert skews, report["findings"]
        assert "mitigation ACTED" in skews[0]["evidence"]
        assert report["fleet"]["mitigation"]["engagements"] == 1
        assert report["mitigation"]["stolen_partitions"] == 3
        sugg = {s["setting"] for s in skews[0]["suggestions"]}
        assert {"mitigate", "speculate_threshold",
                "exchange_coding"} <= sugg
        # Schema-valid report (mitigation shapes included).
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "validate_doctor",
            os.path.join(ROOT, "tools", "validate_doctor.py"))
        vd = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(vd)
        with open(os.path.join(ROOT, "docs",
                               "doctor_schema.json")) as f:
            schema = json.load(f)
        errors = vd.validate(report, schema)
        assert not errors, errors
        # Human rendering names the mitigation.
        text = doctor.format_report(report)
        assert "mitigation" in text

    def test_doctor_notes_armed_but_idle_mitigation(self, tmp_path):
        from dampr_tpu.obs import doctor

        path = self._fleet_summary(
            tmp_path, self._mit_summary(engaged=False))
        report = doctor.diagnose(path)
        skews = [f for f in report["findings"]
                 if f["bottleneck"] == "skew"]
        assert skews and "never engaged" in skews[0]["evidence"]

    def test_fleet_section_carries_mitigation(self):
        from dampr_tpu.obs import fleet

        mit = self._mit_summary()
        ranks = {
            0: {"dir": "/x", "trace": None,
                "stats": {"process": {"num_processes": 2},
                          "wall_seconds": 1.0, "mitigation": mit}},
            1: {"dir": "/y", "trace": None,
                "stats": {"process": {"num_processes": 2},
                          "wall_seconds": 2.0}},
        }
        section = fleet.fleet_section(ranks, shifts={0: 0.0, 1: 0.0},
                                      alignment="clock")
        assert section["mitigation"] == mit

    def test_straggler_of_matches_step_skew_definition(self):
        from dampr_tpu.obs import fleet

        r, ratio = fleet.straggler_of({0: 0.0, 1: 0.4})
        assert r == 1 and ratio == pytest.approx(2.0)
        r, ratio = fleet.straggler_of({})
        assert r is None and ratio == 1.0

    def test_replan_schedule_carries_coding(self):
        from dampr_tpu.parallel import replan

        coding = {"mode": "camr", "raw_bytes": 100, "coded_bytes": 40}
        sched = replan.plan_exchange(
            4, {(0, 1): 1000}, budget=1 << 20, coding=coding)
        assert sched.coding == coding
        assert replan.plan_exchange(4, {(0, 1): 10}).coding is None

    def test_trace_schema_knows_mitigation_kind(self):
        with open(os.path.join(ROOT, "docs",
                               "trace_schema.json")) as f:
            schema = json.load(f)
        assert "mitigation" in schema["x-span-kinds"]
