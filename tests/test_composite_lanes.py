"""Composite (2D) numeric value lanes: type-uniform numeric tuples ride
the segment kernels — mean's (sum, count) pair is the canonical user —
with strict read-back fidelity for everything that doesn't qualify.
"""

import numpy as np
import pytest

from dampr_tpu import Dampr, settings
from dampr_tpu.blocks import Block, _column_from_list, pylist


class TestTupleColumn:
    def test_key_columns_never_composite(self):
        # tuple KEYS stay on the object lane (hash/sort machinery is
        # lane-shaped); grouping by tuple keys must work end-to-end
        out = dict(Dampr.memory(list(range(20)))
                   .fold_by(key=lambda x: (x % 2, x % 3),
                            binop=lambda a, b: a + b).read())
        want = {}
        for x in range(20):
            k = (x % 2, x % 3)
            want[k] = want.get(k, 0) + x
        assert out == want

    def test_lexicographic_min_over_tuple_values(self):
        # a recognized binop (min) over tuple values means LEXICOGRAPHIC
        # comparison, never elementwise 2D folding
        data = [("k", (1, 5)), ("k", (2, 0)), ("j", (3, 3))]
        out = dict(Dampr.memory(data)
                   .fold_by(key=lambda kv: kv[0], binop=min,
                            value=lambda kv: kv[1]).read())
        assert out == {"k": (1, 5), "j": (3, 3)}

    def test_topk_zero(self):
        assert list(Dampr.memory(list(range(100))).topk(0).read()) == []


    def test_int_pairs_build_2d(self):
        col = _column_from_list([(1, 2), (3, 4), (5, 6)], composite=True)
        assert col.ndim == 2 and col.dtype == np.int64
        assert pylist(col) == [(1, 2), (3, 4), (5, 6)]

    def test_float_triples_build_2d(self):
        col = _column_from_list([(1.0, 2.0, 3.0), (4.0, 5.5, 6.0)],
                                composite=True)
        assert col.ndim == 2 and col.dtype == np.float64
        assert pylist(col) == [(1.0, 2.0, 3.0), (4.0, 5.5, 6.0)]

    @pytest.mark.parametrize("rows", [
        [(0, 6.0), (1, 5.0)],          # mixed types: fidelity forbids 2D
        [(True, 1), (False, 2)],       # bools can't ride numeric lanes
        [(1, 2), (3, 4, 5)],           # ragged
        [(2 ** 64, 1), (1, 2)],        # out of int64
        [("a", 1), ("b", 2)],          # non-numeric
        [(1,), (2,)],                  # width 1: plain tuples, not pairs
    ])
    def test_fidelity_cases_stay_object(self, rows):
        col = _column_from_list(list(rows), composite=True)
        assert col.dtype == object
        assert pylist(col) == rows

    def test_block_ops_on_composite(self):
        ks = np.arange(100, dtype=np.int64) % 5
        vs = np.stack([np.arange(100, dtype=np.int64),
                       np.ones(100, dtype=np.int64)], axis=1)
        blk = Block(ks, vs)
        srt = blk.sort_by_hash()
        assert srt.values.ndim == 2
        parts = blk.split_by_partition(4)
        back = Block.concat(list(parts.values()))
        assert sorted(pylist(back.values)) == sorted(pylist(vs))


class TestMean:
    def test_int_mean_exact(self):
        data = list(range(50000))
        out = dict(Dampr.memory(data, partitions=8)
                   .mean(key=lambda x: x % 7).read())
        want = {k: sum(range(k, 50000, 7)) / float(len(range(k, 50000, 7)))
                for k in range(7)}
        assert out == want

    def test_float_mean(self):
        data = [x * 0.5 for x in range(20000)]
        out = dict(Dampr.memory(data, partitions=8)
                   .mean(key=lambda x: int(x) % 3).read())
        for k, v in out.items():
            vals = [x for x in data if int(x) % 3 == k]
            assert v == pytest.approx(sum(vals) / len(vals), rel=1e-12)

    def test_mean_pairs_ride_composite_lane(self):
        # The (sum, count) pair must build a 2D lane, not per-record
        # Python tuples on the object lane.
        col = _column_from_list([(x, 1) for x in range(10)],
                                composite=True)
        assert col.ndim == 2

    def test_huge_int_mean_falls_back_exactly(self):
        # Values past int64 keep exact arithmetic via the object lane.
        base = 2 ** 63
        data = [base + i for i in range(100)]
        out = dict(Dampr.memory(data).mean().read())
        assert out == {1: sum(data) / float(len(data))}

    def test_mean_under_tiny_budget(self):
        from dampr_tpu.runner import MTRunner

        data = list(range(30000))
        pipe = Dampr.memory(data, partitions=8).mean(key=lambda x: x % 4)
        pipe = pipe.checkpoint() if pipe.agg else pipe
        runner = MTRunner("mean-tiny", pipe.pmer.graph,
                          memory_budget=1 << 15)
        out = runner.run([pipe.source])
        got = dict(v for _k, v in out[0].read())
        want = {k: sum(range(k, 30000, 4)) / float(len(range(k, 30000, 4)))
                for k in range(4)}
        assert got == want


class TestTopkLen:
    def test_topk_block_path_matches_oracle(self):
        data = [((x * 7919) % 100003) for x in range(30000)]
        got = list(Dampr.memory(data, partitions=8).topk(25).read())
        # results read back key-sorted ascending (conformance-pinned:
        # topk(2) of [1,3,2,4] is [3, 4])
        want = sorted(sorted(data, reverse=True)[:25])
        assert got == want

    def test_topk_with_value_fn(self):
        data = [("w%d" % i, i % 97) for i in range(5000)]
        got = list(Dampr.memory(data, partitions=4)
                   .topk(10, value=lambda kv: kv[1]).read())
        assert [kv[1] for kv in got] == [96] * 10

    def test_topk_strings(self):
        data = ["s%05d" % ((x * 131) % 9001) for x in range(3000)]
        got = list(Dampr.memory(data).topk(5).read())
        assert got == sorted(sorted(data, reverse=True)[:5])

    def test_len_block_and_stream_paths(self):
        data = list(range(12345))
        assert list(Dampr.memory(data).len().read()) == [12345]
        assert list(Dampr.memory(data)
                    .flat_map(lambda x: [x, x]).len().read()) == [24690]
        # an empty collection still counts to [0] (one (1, 0) record —
        # matches the reference's always-emitting map_count)
        assert list(Dampr.memory([]).len().read()) == [0]
