"""Checkpoint/resume: crash recovery for named runs (dampr_tpu/resume.py).

The reference cannot recover anything — a failed run restarts from zero
(and a crashed worker deadlocks it, reference stagerunner.py:35-38).  These
tests prove the new capability end-to-end: a run that dies mid-pipeline
reruns under the same name and skips every stage it already completed,
while any change to the pipeline's code, parameters, or input files
invalidates exactly the affected suffix.
"""

import functools
import os
import shutil
import tempfile

import pytest

from dampr_tpu import Dampr, settings


def _inc(v):
    return v + 1


def _dec(v):
    return v - 1


def _scaled(kv, factor):
    return (kv[0], kv[1] * factor)


@pytest.fixture(autouse=True)
def small_partitions(partitions8):
    yield


@pytest.fixture
def workdir():
    d = tempfile.mkdtemp(prefix="dampr-resume-")
    yield d
    shutil.rmtree(d, ignore_errors=True)


def _run_root(name):
    return os.path.join(settings.scratch_root, name.replace("/", "_"))


def _fresh(name):
    shutil.rmtree(_run_root(name), ignore_errors=True)


def _trace_mapper(trace_path):
    """Per-record side effect through a captured PATH (a stable constant:
    file contents are not fingerprinted, so recording executions does not
    invalidate the stage the way a captured accumulator list would)."""
    def fn(x):
        with open(trace_path, "a") as f:
            f.write("m\n")
        return (x % 5, 1)
    return fn


def _count(path):
    if not os.path.exists(path):
        return 0
    with open(path) as f:
        return sum(1 for _ in f)


def _boom_if(flag_path):
    def fn(kv):
        if os.path.exists(flag_path):
            raise RuntimeError("injected failure")
        return (kv[0], kv[1] * 10)
    return fn


class TestResume:
    def test_requires_name(self):
        with pytest.raises(ValueError):
            Dampr.memory([1, 2, 3]).run(resume=True)
        with pytest.raises(ValueError):
            Dampr.run(Dampr.memory([1, 2]), resume=True)

    def test_crash_then_resume_skips_completed_stages(self, workdir):
        name = "resume-crash"
        _fresh(name)
        trace = os.path.join(workdir, "trace")
        flag = os.path.join(workdir, "boom")

        def build():
            counted = (Dampr.memory(list(range(40)), partitions=4)
                       .map(_trace_mapper(trace))
                       .fold_by(lambda kv: kv[0],
                                value=lambda kv: kv[1],
                                binop=lambda a, b: a + b))
            return counted.map(_boom_if(flag)).group_by(
                lambda kv: kv[0]).reduce(
                    lambda k, vs: (k, sum(v[1] for v in vs)))

        open(flag, "w").close()
        with pytest.raises(RuntimeError):
            build().run(name=name, resume=True)
        first_pass = _count(trace)
        assert first_pass == 40  # the fold stage completed before the crash

        os.unlink(flag)
        out = build().run(name=name, resume=True)
        got = dict(out.stream())
        assert got == {k: (k, 80) for k in range(5)}
        # The tokenize/fold stages were restored, not re-executed:
        assert _count(trace) == first_pass
        kinds = [s["kind"] for s in out.stats]
        assert any(k.startswith("resumed-") for k in kinds)
        assert kinds.index("reduce") > 0  # the crashed suffix really ran

    def test_rerun_after_success_serves_outputs(self, workdir):
        name = "resume-rerun"
        _fresh(name)
        trace = os.path.join(workdir, "trace")

        def build():
            return (Dampr.memory(list(range(30)), partitions=3)
                    .map(_trace_mapper(trace))
                    .fold_by(lambda kv: kv[0], value=lambda kv: kv[1],
                             binop=lambda a, b: a + b))

        first = dict(build().run(name=name, resume=True).stream())
        n1 = _count(trace)
        second = build().run(name=name, resume=True)
        assert dict(second.stream()) == first
        assert _count(trace) == n1  # nothing re-executed
        assert all(s["kind"].startswith("resumed-") or s["jobs"] == 0
                   for s in second.stats)

    def test_changed_lambda_invalidates_only_downstream(self, workdir):
        name = "resume-invalidate"
        _fresh(name)
        trace = os.path.join(workdir, "trace")

        def build(scale):
            base = (Dampr.memory(list(range(20)), partitions=2)
                    .map(_trace_mapper(trace))
                    .fold_by(lambda kv: kv[0], value=lambda kv: kv[1],
                             binop=lambda a, b: a + b))
            return base.map(lambda kv: (kv[0], kv[1] * scale))

        a = dict(build(2).run(name=name, resume=True).stream())
        n1 = _count(trace)
        # Different captured constant -> downstream map re-executes with the
        # new code, upstream fold is restored (the tracer never reruns).
        b = dict(build(3).run(name=name, resume=True).stream())
        assert _count(trace) == n1
        assert b == {k: v * 3 // 2 for k, v in a.items()}

    def test_switching_global_helper_invalidates(self, workdir):
        # Two lambdas calling different MODULE-LEVEL helpers compile to
        # identical bytecode/consts; only co_names (and the helpers' own
        # fingerprints) tell them apart — a stale checkpoint here would be
        # silently wrong results.
        name = "resume-conames"
        _fresh(name)

        a = dict((Dampr.memory(list(range(10)), partitions=2)
                  .map(lambda x: (x % 2, _inc(x)))
                  .fold_by(lambda kv: kv[0], value=lambda kv: kv[1],
                           binop=lambda a, b: a + b))
                 .run(name=name, resume=True).stream())
        b = dict((Dampr.memory(list(range(10)), partitions=2)
                  .map(lambda x: (x % 2, _dec(x)))
                  .fold_by(lambda kv: kv[0], value=lambda kv: kv[1],
                           binop=lambda a, b: a + b))
                 .run(name=name, resume=True).stream())
        assert a == {0: sum(x + 1 for x in range(0, 10, 2)),
                     1: sum(x + 1 for x in range(1, 10, 2))}
        assert b == {0: sum(x - 1 for x in range(0, 10, 2)),
                     1: sum(x - 1 for x in range(1, 10, 2))}

    def test_changed_partial_invalidates(self, workdir):
        # functools.partial hides its state from an attribute walk; its
        # func/args/keywords must still drive the fingerprint.
        name = "resume-partial"
        _fresh(name)

        def run(factor):
            return dict(
                (Dampr.memory(list(range(12)), partitions=2)
                 .map(lambda x: (x % 3, 1))
                 .fold_by(lambda kv: kv[0], value=lambda kv: kv[1],
                          binop=lambda a, b: a + b)
                 .map(functools.partial(_scaled, factor=factor)))
                .run(name=name, resume=True).stream())

        assert run(2) == {k: 8 for k in range(3)}
        assert run(5) == {k: 20 for k in range(3)}

    def test_edit_rerun_cycles_prune_superseded_blocks(self, workdir):
        # N edit/rerun cycles must not accumulate N copies of the stage
        # output in the checkpoint tree.
        name = "resume-prune"
        _fresh(name)

        def run(factor):
            return dict(
                (Dampr.memory(list(range(50)), partitions=2)
                 .map(lambda x: (x % 5, 1))
                 .fold_by(lambda kv: kv[0], value=lambda kv: kv[1],
                          binop=lambda a, b: a + b)
                 .map(functools.partial(_scaled, factor=factor)))
                .run(name=name, resume=True).stream())

        counts = []
        for factor in (1, 2, 3, 4):
            run(factor)
            nblk = sum(len(fs) for _d, _s, fs in os.walk(
                os.path.join(_run_root(name), "ckpt")))
            counts.append(nblk)
        assert counts[-1] == counts[0], counts

    def test_changed_input_file_invalidates(self, workdir):
        name = "resume-input"
        _fresh(name)
        path = os.path.join(workdir, "data.txt")
        with open(path, "w") as f:
            f.write("a b\nb c\n")

        def build():
            return (Dampr.text(path)
                    .flat_map(lambda line: line.split())
                    .fold_by(lambda t: t, value=lambda t: 1,
                             binop=lambda a, b: a + b))

        a = dict(build().run(name=name, resume=True).stream())
        assert a == {"a": 1, "b": 2, "c": 1}
        with open(path, "w") as f:
            f.write("c c\n")
        os.utime(path, (1, 1))  # force a visible mtime change regardless
        b = dict(build().run(name=name, resume=True).stream())
        assert b == {"c": 2}

    def test_sink_and_multi_output_resume(self, workdir):
        name = "resume-sink"
        _fresh(name)
        trace = os.path.join(workdir, "trace")
        sinkdir = os.path.join(workdir, "out")

        def build():
            base = (Dampr.memory(list(range(24)), partitions=2)
                    .map(_trace_mapper(trace)))
            counts = base.fold_by(lambda kv: kv[0], value=lambda kv: kv[1],
                                  binop=lambda a, b: a + b)
            sunk = counts.map(lambda kv: "{}\t{}".format(*kv)).sink(sinkdir)
            return counts, sunk

        c1, s1 = build()
        r1 = Dampr.run(c1, s1, name=name, resume=True)
        n1 = _count(trace)
        want = dict(r1[0].stream())
        assert want == {k: len(range(k, 24, 5)) for k in range(5)}

        c2, s2 = build()
        r2 = Dampr.run(c2, s2, name=name, resume=True)
        assert dict(r2[0].stream()) == want
        assert _count(trace) == n1
        parts = sorted(os.listdir(sinkdir))
        assert parts and all(p.startswith("part-") for p in parts)

    def test_resume_under_tiny_budget_spills(self, workdir):
        # Checkpointed blocks double as spill files: the run stays exact
        # under a budget far below the data size, and the rerun restores.
        name = "resume-budget"
        _fresh(name)

        def build():
            return (Dampr.memory(list(range(5000)), partitions=4)
                    .map(lambda x: (x % 97, 1))
                    .fold_by(lambda kv: kv[0], value=lambda kv: kv[1],
                             binop=lambda a, b: a + b))

        a = dict(build().run(name=name, resume=True,
                             memory_budget=1 << 14).stream())
        assert a == {k: len(range(k, 5000, 97)) for k in range(97)}
        b = dict(build().run(name=name, resume=True,
                             memory_budget=1 << 14).stream())
        assert b == a

    def test_partition_count_change_invalidates(self, workdir):
        # A restored partition set must co-partition with anything computed
        # fresh: changing n_partitions invalidates prior checkpoints.
        name = "resume-parts"
        _fresh(name)

        def build():
            left = Dampr.memory(
                [("k%d" % (i % 4), i) for i in range(20)],
                partitions=3).group_by(lambda x: x[0])
            right = Dampr.memory(
                [("k%d" % (i % 4), 100 + i) for i in range(8)],
                partitions=2).group_by(lambda x: x[0])
            return left.join(right).reduce(
                lambda lit, rit: (len(list(lit)), len(list(rit))))

        a = dict(build().run(name=name, resume=True,
                             n_partitions=4).stream())
        b = dict(build().run(name=name, resume=True,
                             n_partitions=7).stream())
        want = {"k%d" % k: (5, 2) for k in range(4)}
        assert a == want and b == want

    def test_resume_with_scan_shared_branches(self, workdir):
        # Two branches over one text tap fuse into a scan-share group; both
        # persist, and a rerun restores both without re-reading the tap.
        name = "resume-scanshare"
        _fresh(name)
        path = os.path.join(workdir, "data.txt")
        with open(path, "w") as f:
            for i in range(50):
                f.write("a b c\n" if i % 2 else "a\n")

        def build():
            docs = Dampr.text(path)
            wc = (docs.flat_map(lambda line: line.split())
                  .fold_by(lambda t: t, value=lambda t: 1,
                           binop=lambda a, b: a + b))
            nlines = docs.len()
            return wc, nlines

        w1, n1 = build()
        r1 = Dampr.run(w1, n1, name=name, resume=True)
        want_wc = dict(r1[0].stream())
        want_n = list(r1[1].stream())
        assert want_wc == {"a": 50, "b": 25, "c": 25}
        assert want_n == [50]

        w2, n2 = build()
        r2 = Dampr.run(w2, n2, name=name, resume=True)
        assert dict(r2[0].stream()) == want_wc
        assert list(r2[1].stream()) == want_n
        assert all(s["kind"].startswith("resumed-") or s["jobs"] == 0
                   for s in r2[0].stats)

    def test_resume_off_is_default_and_untouched(self, workdir):
        name = "resume-off"
        _fresh(name)
        out = (Dampr.memory(list(range(10)))
               .map(lambda x: (x % 2, 1))
               .fold_by(lambda kv: kv[0], value=lambda kv: kv[1],
                        binop=lambda a, b: a + b)
               .run(name=name))
        assert dict(out.stream()) == {0: 5, 1: 5}
        assert not os.path.isdir(os.path.join(_run_root(name), "manifest"))


class TestFingerprintSharpness:
    """Regression tests for the round-3 advisor findings: fingerprints must
    never collide across semantically different captured state (stale reuse
    is the one unforgivable failure mode)."""

    def test_big_array_content_change_invalidates(self):
        import numpy as np
        from dampr_tpu import resume
        a = np.zeros(1 << 18, dtype=np.float64)  # 2MB: above the old 1MB cap
        b = a.copy()
        assert resume._fp(a) == resume._fp(b)
        b[12345] = 1.0  # same shape, same dtype, different CONTENTS
        assert resume._fp(a) != resume._fp(b)

    def test_noncontiguous_array_fingerprints_by_content(self):
        import numpy as np
        from dampr_tpu import resume
        base = np.arange(64).reshape(8, 8)
        view = base[:, ::2]  # non-contiguous
        assert resume._fp(view) == resume._fp(view.copy())

    def test_depth_cap_is_volatile(self):
        from dampr_tpu import resume
        deep = "leaf"
        for _ in range(resume._MAX_DEPTH + 2):
            deep = [deep]
        fp1, fp2 = resume._fp(deep), resume._fp(deep)
        # State buried past the cap is invisible — must never produce a
        # stable (reusable) fingerprint.
        assert resume.is_volatile(fp1) and resume.is_volatile(fp2)
        assert fp1 != fp2

    def test_same_size_same_mtime_edit_detected(self, workdir):
        from dampr_tpu import resume
        path = os.path.join(workdir, "data.txt")
        with open(path, "w") as f:
            f.write("aaaa\nbbbb\n")
        st = os.stat(path)
        fp1 = resume._stat_fp(path)
        with open(path, "w") as f:
            f.write("aaaa\ncccc\n")  # same size
        os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns))  # restore mtime
        fp2 = resume._stat_fp(path)
        assert fp1 != fp2  # the 64KB content probe catches it

    def test_volatile_stage_blocks_are_pruned(self, workdir):
        """A stage capturing unfingerprintable state persists no manifest;
        its spilled blocks must be deleted at cleanup, not retained forever
        in the named scratch root."""
        name = "resume-volatile-prune"
        _fresh(name)

        class Opaque:
            # No __dict__ attrs, not picklable -> _fp returns volatile.
            __slots__ = ()

            def __reduce__(self):
                raise TypeError("nope")

            def __call__(self, x):
                return (x % 3, 1)

        def build():
            return (Dampr.memory(list(range(30)), partitions=4)
                    .map(Opaque())
                    .fold_by(lambda kv: kv[0], value=lambda kv: kv[1],
                             binop=lambda a, b: a + b))

        def blk_files():
            root = _run_root(name)
            out = []
            for d, _dirs, fs in os.walk(root):
                out.extend(os.path.join(d, f) for f in fs
                           if f.endswith(".blk"))
            return out

        # memory_budget=1 forces every block to disk
        got1 = dict(build().run(name=name, resume=True,
                                memory_budget=1).stream())
        n1 = len(blk_files())
        got2 = dict(build().run(name=name, resume=True,
                                memory_budget=1).stream())
        n2 = len(blk_files())
        assert got1 == got2 == {0: 10, 1: 10, 2: 10}
        # Volatile stages can never be resumed; reruns must not accumulate
        # their spill files.
        assert n2 <= n1


class TestChainDepth:
    def test_long_op_chains_stay_resumable(self):
        # >= 6 chained per-record ops must NOT fingerprint volatile: fused
        # Composed chains flatten before the depth budget applies.
        from dampr_tpu import resume
        from dampr_tpu.base import Filter, ValueMap, fuse

        ops = [ValueMap(lambda x, i=i: x + i) for i in range(10)]
        ops.insert(5, Filter(lambda x: x % 2 == 0))
        fused = fuse(ops)
        fp1 = resume._fp(fused)
        assert not resume.is_volatile(fp1), "11-op chain went volatile"
        # determinism + sensitivity: same chain again matches, an edited
        # link does not
        assert resume._fp(fuse([ValueMap(lambda x, i=i: x + i)
                                for i in range(10)]
                               + [Filter(lambda x: x % 2 == 0)])) != fp1
