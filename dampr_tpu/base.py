"""Operator kernels: the Mapper/Reducer interfaces and concrete operators.

Parity surface: reference dampr/base.py — ``Mapper``/``Streamable`` (10-16),
``Map`` (18-40), composition (42-60), ``BlockMapper``/``StreamMapper``
(62-124), map-side joins ``MapCrossJoin``/``MapAllJoin`` (139-178),
``Reducer``/``Reduce``/``BlockReducer``/``StreamReducer``/``KeyedReduce``
(180-257), sort-merge ``InnerJoin``/``LeftJoin`` + keyed variants (259-320),
combiners (373-402), ``Splitter`` (6-8).

Execution model differences from the reference: operators are *logical* here.
The runner streams records through fused mapper chains into columnar blocks and
hands reducers key-sorted grouped views built by vectorized hash-sort
(ops/segment.py) instead of pickled sorted spills + heapq merges.  Reducers
receive dataset-like objects exposing ``grouped_read()`` — the same contract
the reference's ``yield_groups`` provides — so user subclasses transfer.

The reference's ``OuterJoin``/``CrossJoin`` reducers are dead code with latent
bugs (base.py:355, 366) and are not part of the public DSL; we implement the
two exposed joins (inner/left) plus the map-side crosses.
"""

import copy
import functools
import itertools
import logging
import threading
import types

import numpy as np

from .ops import hashing, segment

log = logging.getLogger("dampr_tpu.base")


class Splitter(object):
    """Partition routing (reference base.py:6-8).  Uses the deterministic
    vectorized hash lanes, so routing agrees with block-level
    ``Block.partition_ids`` everywhere."""

    def partition(self, key, n_partitions):
        h1, _ = hashing.hash_keys([key])
        return int(h1[0] % np.uint32(n_partitions))


# ---------------------------------------------------------------------------
# Mappers
# ---------------------------------------------------------------------------

#: Callable types that are always safe to share by reference: plain
#: functions/builtins are atomic to deepcopy, and a closure's captured
#: state is the user's explicit choice (same as under the fork-based
#: reference's exec model).  Bound methods are NOT here — deepcopy
#: copies their ``__self__``, and a bound method of a stateful object is
#: exactly the shared-mutable-UDF hazard this machinery isolates.
_ATOMIC_CALLABLE_TYPES = (types.FunctionType, types.BuiltinFunctionType,
                          types.BuiltinMethodType, type)

_share_warned = set()
_share_warned_lock = threading.Lock()


def _stateful_callable(v, _depth=0):
    """A callable *object* carrying per-instance state (nonempty
    ``__dict__``): shared across concurrent jobs it would observe every
    partition's records interleaved and must be thread-safe — so the
    per-job clone isolates it instead (the thread-pool analog of the
    fork-based reference's copy-on-write worker isolation).  Detected
    when held directly, inside ``functools.partial``, or one or two
    levels down a plain list/tuple/dict (deepcopy then clones the whole
    holding structure); state buried deeper than that stays shared —
    the documented must-be-thread-safe contract."""
    if _depth > 2:
        return False
    if isinstance(v, functools.partial):
        return (_stateful_callable(v.func, _depth + 1)
                or any(_stateful_callable(a, _depth + 1) for a in v.args)
                or any(_stateful_callable(a, _depth + 1)
                       for a in (v.keywords or {}).values()))
    if isinstance(v, (list, tuple)):
        return any(_stateful_callable(x, _depth + 1) for x in v)
    if isinstance(v, dict):
        return any(_stateful_callable(x, _depth + 1) for x in v.values())
    if isinstance(v, types.MethodType):
        # A bound method mutates its receiver: stateful iff the receiver
        # carries instance state (deepcopy of the method clones
        # ``__self__``, so isolation works the same way).
        recv = v.__self__
        if isinstance(recv, type):
            return False  # classmethod: class-level state, always shared
        return bool(getattr(recv, "__dict__", None))
    if not callable(v) or isinstance(v, _ATOMIC_CALLABLE_TYPES):
        return False
    return bool(getattr(v, "__dict__", None))


def _shared_instance_deepcopy(self, memo):
    """``__deepcopy__`` body for the stateless wrapper operators: the
    runner's per-job clone (runner._clone_op) shares the instance when
    everything it holds is safely shareable — plain functions, closures,
    builtins, bound methods (deepcopy treats them as atomic; they were
    always shared).

    A held callable *object* with a nonempty ``__dict__`` is different:
    it has per-instance state, and sharing one across concurrent jobs
    silently interleaves every partition's records through it (the fork-
    based reference gave such UDFs copy-on-write isolation per worker).
    So the wrapper deep-copies itself — reaching the stateful callable —
    and each job gets its own instance.  Callables whose state resists
    deepcopy (open files, sockets, loaded models) fall back to the shared
    instance with a once-per-type warning: they must then be thread-safe,
    the documented pre-fix contract.  Truly per-chunk mutable state still
    belongs in the BlockMapper/BlockReducer lifecycle, which is always
    deep-copied."""
    held = getattr(self, "__dict__", None) or {}
    if not any(_stateful_callable(v) for v in held.values()):
        return self
    pre_keys = set(memo)
    try:
        cls = self.__class__
        clone = cls.__new__(cls)
        memo[id(self)] = clone
        for k, v in held.items():
            object.__setattr__(clone, k, copy.deepcopy(v, memo))
        return clone
    except Exception as e:
        # Un-poison the memo: it was seeded with the half-built clone
        # before the child copies ran (required for cycles), and children
        # copied before the failure may hold back-references to that
        # discarded clone — drop every entry this attempt added (except
        # deepcopy's own id(memo) keep-alive list), then map self to the
        # shared original so later references resolve consistently.
        for k in set(memo) - pre_keys:
            if k != id(memo):
                memo.pop(k, None)
        memo[id(self)] = self
        key = type(self).__name__
        with _share_warned_lock:
            seen = key in _share_warned
            _share_warned.add(key)
        if not seen:
            from .obs import log as _obslog

            _obslog.warn(
                "shared-state-udf",
                "%s holds a stateful callable object whose state cannot "
                "be deep-copied (%s); the instance is SHARED across "
                "concurrent jobs and must be thread-safe", key, e,
                logger=log, type=key)
        return self


class Mapper(object):
    """Lowest-level map interface: consume whole datasets, yield (k, v)."""

    #: Declares that map_blocks prefers the bounded iter_byte_blocks scan
    #: over materializing chunk bytes.  The runner's scan-sharing pass runs
    #: byte-materializing members first so streaming members can serve from
    #: the already-read bytes.
    streams_bytes = False

    def map(self, *datasets):
        raise NotImplementedError()


class Streamable(object):
    """Per-record transform that can fuse with neighbors into one pass."""

    def stream(self, kvs):
        raise NotImplementedError()


def _identity(k, v):
    """The no-op record map (checkpoint/sink stages with no queued aggs).
    Lives here so the runner can recognize ``Map(_identity)`` stages and
    pass whole blocks through instead of iterating records."""
    yield k, v


class Map(Mapper, Streamable):
    """Wraps a generator function ``f(k, v) -> iterable[(k, v)]``."""

    __deepcopy__ = _shared_instance_deepcopy

    def __init__(self, mapper):
        assert not isinstance(mapper, Mapper)
        self.mapper = mapper

    def map(self, *datasets):
        assert len(datasets) == 1
        return self.stream(datasets[0].read())

    def stream(self, kvs):
        mapper = self.mapper
        for key, value in kvs:
            for nkv in mapper(key, value):
                yield nkv

    def __repr__(self):
        name = getattr(self.mapper, "__name__", str(type(self.mapper)))
        return "Map[{}]".format(name)

    __str__ = __repr__


class ComposedStreamable(Streamable):
    def __init__(self, left, right):
        assert isinstance(left, Streamable)
        assert isinstance(right, Streamable)
        self.left = left
        self.right = right

    def stream(self, kvs):
        return self.right.stream(self.left.stream(kvs))


class ComposedMapper(Mapper):
    def __init__(self, left, right):
        assert isinstance(left, Mapper)
        assert isinstance(right, Streamable)
        self.left = left
        self.right = right

    def map(self, *datasets):
        return self.right.stream(self.left.map(*datasets))


def fuse(aggs):
    """Compose a queue of Streamables into one Mapper (map fusion — chained
    map/filter/flat_map cost one pass; reference dampr.py:959-967)."""
    if len(aggs) == 1:
        return aggs[0]
    s = aggs[1]
    for i in range(2, len(aggs)):
        s = ComposedStreamable(s, aggs[i])
    return ComposedMapper(aggs[0], s)


def is_pure_record_stream(m):
    """True when a (possibly fused) mapper chains only plain ``Map`` /
    ``RecordOp`` steps, so records transform independently and chunk
    granularity is mechanical.  False for anything carrying per-chunk
    semantics (StreamMapper observes whole-partition iterators, BlockMapper
    has a per-chunk lifecycle) — the runner's tiny-input collapse must not
    merge those chunks."""
    if type(m) is Map or isinstance(m, RecordOp):
        return True
    if type(m) in (ComposedMapper, ComposedStreamable):
        return is_pure_record_stream(m.left) and is_pure_record_stream(m.right)
    return False


# ---------------------------------------------------------------------------
# Typed record ops: the DSL's per-record transforms with a BATCH lowering
# ---------------------------------------------------------------------------

class RecordOp(Mapper, Streamable):
    """A typed per-record transform the engine can execute over whole
    batches: ``apply_batch(keys, values) -> (keys, values)`` transforms
    parallel Python lists in tight list-comprehension loops (one C-level
    loop per op per batch) instead of threading every record through a
    chain of nested generator frames.  ``stream`` remains as the record-
    at-a-time lowering for paths that need a generator.

    Equivalence note: a fused generator chain interleaves ops per record
    (op2 sees record 1 before op1 sees record 2); the batch lowering runs
    op1 over the whole batch first.  For per-record-pure functions — the
    DSL contract — the outputs are identical, and each op still sees
    records in stream order, so self-contained stateful UDFs (a dedupe
    filter's seen-set) behave the same within one stream.  Only state
    shared ACROSS two ops of one chain could observe the difference; batch
    size bounds it.  UDF sharing across concurrent jobs (see
    ``_shared_instance_deepcopy``): plain functions/closures are shared;
    a stateful callable *object* is deep-copied per job where possible,
    and only falls back to the shared instance — which must then be
    thread-safe — when its state defies deepcopy."""

    def map(self, *datasets):
        assert len(datasets) == 1
        return self.stream(datasets[0].read())

    def apply_batch(self, ks, vs):
        raise NotImplementedError()

    # No per-chunk state of its own (Sample re-derives its RNG per
    # stream): clones share the wrapper unless a held stateful callable
    # object needs per-job isolation (_shared_instance_deepcopy).
    __deepcopy__ = _shared_instance_deepcopy


class ValueMap(RecordOp):
    """value -> f(value)  (PMap.map)."""

    def __init__(self, f):
        self.f = f

    def apply_batch(self, ks, vs):
        f = self.f
        return ks, [f(v) for v in vs]

    def stream(self, kvs):
        f = self.f
        for k, v in kvs:
            yield k, f(v)

    def __repr__(self):
        return "ValueMap[{}]".format(getattr(self.f, "__name__", self.f))


class MapValues(RecordOp):
    """(a, b) -> (a, f(b))  (PMap.map_values)."""

    def __init__(self, f):
        self.f = f

    def apply_batch(self, ks, vs):
        f = self.f
        return ks, [(v[0], f(v[1])) for v in vs]

    def stream(self, kvs):
        f = self.f
        for k, v in kvs:
            yield k, (v[0], f(v[1]))


class MapKeys(RecordOp):
    """(a, b) -> (f(a), b)  (PMap.map_keys)."""

    def __init__(self, f):
        self.f = f

    def apply_batch(self, ks, vs):
        f = self.f
        return ks, [(f(v[0]), v[1]) for v in vs]

    def stream(self, kvs):
        f = self.f
        for k, v in kvs:
            yield k, (f(v[0]), v[1])


class Prefix(RecordOp):
    """value -> (f(value), value)."""

    def __init__(self, f):
        self.f = f

    def apply_batch(self, ks, vs):
        f = self.f
        return ks, [(f(v), v) for v in vs]

    def stream(self, kvs):
        f = self.f
        for k, v in kvs:
            yield k, (f(v), v)


class Suffix(RecordOp):
    """value -> (value, f(value))."""

    def __init__(self, f):
        self.f = f

    def apply_batch(self, ks, vs):
        f = self.f
        return ks, [(v, f(v)) for v in vs]

    def stream(self, kvs):
        f = self.f
        for k, v in kvs:
            yield k, (v, f(v))


class Filter(RecordOp):
    """Keep records whose value satisfies the predicate."""

    def __init__(self, f):
        self.f = f

    def apply_batch(self, ks, vs):
        sel = list(map(self.f, vs))
        if all(sel):
            return ks, vs
        return (list(itertools.compress(ks, sel)),
                list(itertools.compress(vs, sel)))

    def stream(self, kvs):
        f = self.f
        for k, v in kvs:
            if f(v):
                yield k, v

    def __repr__(self):
        return "Filter[{}]".format(getattr(self.f, "__name__", self.f))


class FlatMap(RecordOp):
    """value -> iterable, flattened; the key repeats per emitted element."""

    def __init__(self, f):
        self.f = f

    def apply_batch(self, ks, vs):
        repeat = itertools.repeat
        f = self.f
        nks, nvs = [], []
        ext_k, ext_v = nks.extend, nvs.extend
        for k, v in zip(ks, vs):
            out = f(v)
            if not isinstance(out, (list, tuple)):
                out = list(out)
            ext_v(out)
            ext_k(repeat(k, len(out)))
        return nks, nvs

    def stream(self, kvs):
        f = self.f
        for k, v in kvs:
            for vi in f(v):
                yield k, vi

    def __repr__(self):
        return "FlatMap[{}]".format(getattr(self.f, "__name__", self.f))


class Rekey(RecordOp):
    """(k, v) -> (key_f(v), value_f(v)) — the shuffle re-key every
    group_by / a_group_by / sort_by plants.  Splitting key and value
    extraction into two tight loops keeps each a single-call batch pass."""

    def __init__(self, key_f, value_f=None):
        self.key_f = key_f
        self.value_f = value_f

    def apply_batch(self, ks, vs):
        key_f, value_f = self.key_f, self.value_f
        nks = [key_f(v) for v in vs]
        return nks, (vs if value_f is None else [value_f(v) for v in vs])

    def stream(self, kvs):
        key_f, value_f = self.key_f, self.value_f
        if value_f is None:
            for _k, v in kvs:
                yield key_f(v), v
        else:
            for _k, v in kvs:
                yield key_f(v), value_f(v)

    def __repr__(self):
        return "Rekey[{}]".format(getattr(self.key_f, "__name__", self.key_f))


class Sample(RecordOp):
    """Keep each record with probability ``prob``; draws come from the
    injected thread-local RNG factory in stream order, so batch and
    per-record lowerings consume the identical random sequence."""

    def __init__(self, prob, rand_factory):
        self.prob = prob
        self.rand_factory = rand_factory

    def apply_batch(self, ks, vs):
        rnd = self.rand_factory().random
        prob = self.prob
        sel = [rnd() < prob for _ in vs]
        return ([k for k, s in zip(ks, sel) if s],
                [v for v, s in zip(vs, sel) if s])

    def stream(self, kvs):
        rnd = self.rand_factory().random
        prob = self.prob
        for k, v in kvs:
            if rnd() < prob:
                yield k, v


class Inspect(RecordOp):
    """Debug passthrough: print each value as it streams."""

    def __init__(self, prefix=""):
        self.prefix = prefix

    def apply_batch(self, ks, vs):
        for v in vs:
            print("{}: {}".format(self.prefix, v))
        return ks, vs

    def stream(self, kvs):
        for k, v in kvs:
            print("{}: {}".format(self.prefix, v))
            yield k, v


def record_op_chain(m):
    """Flatten a (possibly fused) mapper into an ordered [RecordOp] list, or
    None when any link lacks a batch lowering.  ``Map(_identity)`` links
    contribute nothing and drop out."""
    out = []

    def walk(node):
        if isinstance(node, RecordOp):
            out.append(node)
            return True
        if type(node) is Map and node.mapper is _identity:
            return True
        if type(node) in (ComposedMapper, ComposedStreamable):
            return walk(node.left) and walk(node.right)
        return False

    return out if walk(m) else None


class BlockMapper(Mapper, Streamable):
    """start/add/finish lifecycle mapper for user aggregation logic.

    Stateful across one chunk — the runner deep-copies instances per job, so
    concurrent jobs never share state (the reference got isolation from
    process forks; we make it explicit).
    """

    def start(self):
        pass

    def add(self, key, value):
        raise NotImplementedError()

    def finish(self):
        return ()

    def map(self, *datasets):
        assert len(datasets) == 1
        return self.stream(datasets[0].read())

    def stream(self, kvs):
        self.start()
        for key, value in kvs:
            for out in self.add(key, value):
                yield out
        for out in self.finish():
            yield out


class StreamMapper(Mapper, Streamable):
    """Whole-partition generator mapper: ``f(value_iter) -> iterable[(k, v)]``."""

    __deepcopy__ = _shared_instance_deepcopy

    def __init__(self, streamer_f):
        self.streamer_f = streamer_f

    def map(self, *datasets):
        assert len(datasets) == 1
        return self.stream(datasets[0].read())

    def stream(self, kvs):
        it = (v for _k, v in kvs)
        return self.streamer_f(it)

    def __repr__(self):
        name = getattr(self.streamer_f, "__name__", str(type(self.streamer_f)))
        return "StreamMapper[{}]".format(name)

    __str__ = __repr__


def group_datasets(dataset):
    """Normalize a chunker / dataset list to one readable dataset."""
    from .dataset import CatDataset, Chunker, EmptyDataset

    if isinstance(dataset, Chunker) and not hasattr(dataset, "read"):
        dataset = list(dataset.chunks())
    if isinstance(dataset, (list, tuple)):
        if len(dataset) > 1:
            return CatDataset(dataset)
        if len(dataset) == 1:
            return dataset[0]
        return EmptyDataset()
    return dataset


class MapCrossJoin(Mapper):
    """Map-side cross product; with ``cache`` the right side is pinned in RAM
    (broadcast join — reference base.py:139-163)."""

    __deepcopy__ = _shared_instance_deepcopy

    def __init__(self, crosser, cache=False):
        self.crosser = crosser
        self.cache = cache

    def map(self, *datasets):
        assert len(datasets) == 2
        left, right = [group_datasets(d) for d in datasets]

        if self.cache:
            cached = list(right.read())
            read_right = lambda: iter(cached)  # noqa: E731
        else:
            read_right = right.read

        crosser = self.crosser
        for key, value in left.read():
            for key2, value2 in read_right():
                for kv in crosser(key, value, key2, value2):
                    yield kv


class MapAllJoin(Mapper):
    """Loads the whole right side through an aggregate fn, passes it to every
    left record (reference base.py:165-178)."""

    __deepcopy__ = _shared_instance_deepcopy

    def __init__(self, crosser, load_f=lambda d: [v for _k, v in d]):
        self.crosser = crosser
        self.load_f = load_f

    def map(self, *datasets):
        assert len(datasets) == 2
        left, right = [group_datasets(d) for d in datasets]
        loaded = self.load_f(right.read())
        crosser = self.crosser
        for key, value in left.read():
            for kv in crosser(key, value, loaded):
                yield kv


# ---------------------------------------------------------------------------
# Grouped partition views (what reducers consume)
# ---------------------------------------------------------------------------

class StreamingGroupedView(object):
    """Out-of-core grouped view: a k-way merge over hash-sorted runs, holding
    one bounded window per run instead of the whole partition (the reference's
    ``MergeDataset`` heap merge over sorted spill files, dataset.py:567-588,
    restated over columnar runs).

    Groups stream in **hash order**, not key order — the documented contract
    when a partition exceeds the memory budget (key order would require
    materializing everything; the reference pays sorted-spill cost up front
    instead).  Within one 64-bit hash, records sub-group exactly by real key.
    """

    def __init__(self, refs):
        self.refs = refs

    def _run_stream(self, ref, run_idx):
        from .blocks import pylist

        for window in ref.iter_windows():
            keys = pylist(window.keys)
            vals = pylist(window.values)
            h1, h2 = window.hashes()
            for i in range(len(keys)):
                yield (int(h1[i]), int(h2[i]), run_idx, keys[i], vals[i])

    def grouped_read(self):
        """Yield (key, value_iter) per group, groupby-style: advancing to the
        next group drains the previous iterator.  The common (no-collision)
        case streams a hash-group's values lazily — a hot key never buffers —
        and only records of *other* keys colliding in the same 64-bit hash
        (astronomically rare, tiny) are set aside and re-grouped exactly."""
        import heapq

        streams = [self._run_stream(ref, i) for i, ref in enumerate(self.refs)]
        merged = heapq.merge(*streams, key=lambda r: (r[0], r[1], r[2]))
        rec = next(merged, None)
        holder = [None]
        while rec is not None:
            h = (rec[0], rec[1])
            key = rec[3]
            pending = []  # same-hash records of OTHER keys (collisions)

            def values(first=rec, h=h, key=key):
                yield first[4]
                while True:
                    r = next(merged, None)
                    if r is None or (r[0], r[1]) != h:
                        holder[0] = r
                        return
                    if r[3] == key:
                        yield r[4]
                    else:
                        pending.append(r)

            gen = values()
            holder[0] = None
            yield key, gen
            # groupby contract: drain whatever the caller left unconsumed so
            # the merge advances past this group (values are dropped, not
            # stored — memory stays bounded).
            for _ in gen:
                pass
            for k2, vs2 in _group_small(pending):
                yield k2, iter(vs2)
            rec = holder[0]

    def read(self):
        for k, vs in self.grouped_read():
            for v in vs:
                yield k, v


def _group_small(records):
    """Exact first-seen-order grouping of a handful of collision records."""
    by_key = []
    for rec in records:
        for entry in by_key:
            if entry[0] == rec[3]:
                entry[1].append(rec[4])
                break
        else:
            by_key.append((rec[3], [rec[4]]))
    return by_key


def _hash_bundles(view):
    """Walk a StreamingGroupedView's merged record stream yielding
    ``(h64pair, [(key, [values])])`` per distinct hash, in hash order.  Values
    materialize per *hash group* (not per partition) — the streaming join's
    memory bound is the largest single join-key group."""
    import heapq
    import itertools

    streams = [view._run_stream(ref, i) for i, ref in enumerate(view.refs)]
    merged = heapq.merge(*streams, key=lambda r: (r[0], r[1], r[2]))
    for h, group in itertools.groupby(merged, key=lambda r: (r[0], r[1])):
        yield h, _group_small(group)


def streaming_merge_join(lview, rview, reducer):
    """Out-of-core sort-merge join over two hash-ordered streaming views —
    the runner's over-budget path for co-partitioned joins.  Walks both
    sides by 64-bit hash, matching real keys inside each hash (so collisions
    join exactly); inner/left/outer semantics and ``many`` flattening come
    from the reducer instance.  Yields the same (k, (k, v)) records the
    Keyed* join reducers produce."""
    left_only = isinstance(reducer, (LeftJoin, OuterJoin))
    right_only = isinstance(reducer, OuterJoin)
    inner_many = getattr(reducer, "many", False)
    joiner = reducer.joiner_f
    default = getattr(reducer, "default", lambda: iter(()))

    def emit(k, result, flatten):
        if flatten:
            for v in result:
                yield k, (k, v)
        else:
            yield k, (k, result)

    def left_emit(groups):
        if left_only:
            for k, vals in groups:
                for out in emit(k, joiner(k, iter(vals), default()), False):
                    yield out

    def right_emit(groups):
        if right_only:
            for k, vals in groups:
                for out in emit(k, joiner(k, default(), iter(vals)), False):
                    yield out

    lgen = _hash_bundles(lview)
    rgen = _hash_bundles(rview)
    lcur = next(lgen, None)
    rcur = next(rgen, None)
    while lcur is not None and rcur is not None:
        if lcur[0] < rcur[0]:
            for out in left_emit(lcur[1]):
                yield out
            lcur = next(lgen, None)
        elif lcur[0] > rcur[0]:
            for out in right_emit(rcur[1]):
                yield out
            rcur = next(rgen, None)
        else:
            # Same 64-bit hash: match by real key (collision-exact).
            rgroups = rcur[1]  # already a materialized list (_group_small)
            matched_r = [False] * len(rgroups)
            for k, lvals in lcur[1]:
                hit = None
                for j, (rk, rvals) in enumerate(rgroups):
                    if rk == k:
                        hit = j
                        break
                if hit is not None:
                    matched_r[hit] = True
                    result = joiner(k, iter(lvals), iter(rgroups[hit][1]))
                    for out in emit(k, result, inner_many):
                        yield out
                else:
                    for out in left_emit([(k, lvals)]):
                        yield out
            for j, (rk, rvals) in enumerate(rgroups):
                if not matched_r[j]:
                    for out in right_emit([(rk, rvals)]):
                        yield out
            lcur = next(lgen, None)
            rcur = next(rgen, None)
    while lcur is not None:
        for out in left_emit(lcur[1]):
            yield out
        lcur = next(lgen, None)
    while rcur is not None:
        for out in right_emit(rcur[1]):
            yield out
        rcur = next(rgen, None)


class GroupedView(object):
    """Key-sorted grouped view over one input's blocks within a partition.

    Built once per (reduce job, input) by vectorized hash-sort + collision
    repair + a final order-by-real-key of the group starts.  Provides the same
    contract as the reference's merged sorted runs (``yield_groups``,
    base.py:184-195): ``grouped_read()`` yields (key, value_iter) in ascending
    key order; ``read()`` yields (k, v) records in the same order.
    """

    def __init__(self, blocks):
        from .blocks import Block

        blk = Block.concat(blocks)
        self._groups = segment.sort_and_group(blk)
        starts, ends = self._groups.bounds()
        keys = self._groups.block.keys
        ng = len(starts)
        if ng:
            gkeys = keys.take(starts)
            try:
                order = np.argsort(gkeys, kind="stable")
            except TypeError:
                # Uncomparable mixed keys — keep hash order (the reference
                # would raise inside heapq.merge; we stay permissive).
                order = np.arange(ng)
            self._order = order
        else:
            self._order = np.arange(0)
        self._starts = starts
        self._ends = ends

    @property
    def n_groups(self):
        return len(self._starts)

    def grouped_read(self):
        from .blocks import pylist

        sb = self._groups.block
        keys = sb.keys
        vals = sb.values

        def group_values(s, e, _W=8192):
            # windowed C-level conversion: a near-budget partition never
            # boxes its whole lane at once, a hot key never boxes its
            # whole group
            for w0 in range(s, e, _W):
                for v in pylist(vals[w0:min(e, w0 + _W)]):
                    yield v

        for gi in self._order:
            s, e = self._starts[gi], self._ends[gi]
            k = keys[s]
            yield (
                k.item() if isinstance(k, np.generic) else k,
                group_values(s, e),
            )

    def read(self):
        for k, vs in self.grouped_read():
            for v in vs:
                yield k, v

    # Device-path accessors (AssocFoldReducer) -----------------------------
    def sorted_groups(self):
        return self._groups

    def key_order(self):
        return self._order


# ---------------------------------------------------------------------------
# Reducers
# ---------------------------------------------------------------------------

class Reducer(object):
    """Consumes one grouped view per input; yields (k, v) records."""

    def reduce(self, *datasets):
        raise NotImplementedError()

    def yield_groups(self, dataset):
        return dataset.grouped_read()


class Reduce(Reducer):
    """``f(key, value_iter) -> value`` per group (reference base.py:197-207)."""

    __deepcopy__ = _shared_instance_deepcopy

    def __init__(self, reducer):
        self.reducer = reducer

    def reduce(self, *datasets):
        assert len(datasets) == 1
        reducer = self.reducer
        for k, vs in self.yield_groups(datasets[0]):
            yield k, reducer(k, vs)


class KeyedReduce(Reduce):
    """Reduce whose emitted value is the (k, v) tuple itself, so downstream
    reads see the pairs (reference base.py:254-257)."""

    def reduce(self, *datasets):
        for k, v in super(KeyedReduce, self).reduce(*datasets):
            yield k, (k, v)


class BlockReducer(Reducer):
    """start/add/finish lifecycle over groups (reference base.py:209-231).
    Deep-copied per partition job for state isolation."""

    def start(self):
        pass

    def add(self, k, it):
        raise NotImplementedError()

    def finish(self):
        return ()

    def reduce(self, *datasets):
        assert len(datasets) == 1
        self.start()
        for k, vs in self.yield_groups(datasets[0]):
            for nkv in self.add(k, vs):
                yield nkv
        for nkv in self.finish():
            yield nkv


class StreamReducer(Reducer):
    """``f(group_iter) -> iterable[(k, v)]`` over the whole partition; output
    values are wrapped as (k, v) pairs (reference base.py:233-251).  Runs on
    empty partitions too — documented reference behavior."""

    __deepcopy__ = _shared_instance_deepcopy

    def __init__(self, stream_f):
        self.stream_f = stream_f

    def reduce(self, *datasets):
        assert len(datasets) == 1
        for nk, nv in self.stream_f(self.yield_groups(datasets[0])):
            yield nk, (nk, nv)

    def __repr__(self):
        name = getattr(self.stream_f, "__name__", str(type(self.stream_f)))
        return "StreamReducer[{}]".format(name)

    __str__ = __repr__


class AssocFoldReducer(Reducer):
    """Final fold for ``a_group_by`` pipelines — the reduce-side half of the
    local-combine → shuffle → final-combine decomposition (reference pairs
    ``PartialReduceCombiner`` with a plain ``Reduce``; dampr.py:661-691).

    Recognized ops (sum/min/max/first) fold on device via segment kernels;
    opaque binops fold on host over the sorted groups.  Output value is the
    (k, acc) pair, matching KeyedReduce semantics.
    """

    __deepcopy__ = _shared_instance_deepcopy

    def __init__(self, op):
        self.op = segment.as_assoc_op(op)

    def reduce(self, *datasets):
        assert len(datasets) == 1
        view = datasets[0]
        if isinstance(view, GroupedView):
            from .blocks import pylist

            groups = view.sorted_groups()
            folded = segment.fold_sorted(groups, self.op)
            order = view.key_order()
            keys = pylist(folded.keys)
            vals = pylist(folded.values)
            for gi in order:
                k = keys[gi]
                yield k, (k, vals[gi])
        else:
            fn = self.op.fn
            for k, vs in view.grouped_read():
                acc = None
                first = True
                for v in vs:
                    acc = v if first else fn(acc, v)
                    first = False
                yield k, (k, acc)


def _sort_merge_walk(g1, g2):
    """The one sort-merge walk all joins share: yields
    ``('both', k, lvals, rvals)`` on matched keys, ``('left', k, lvals)`` /
    ``('right', k, rvals)`` on exclusives, in ascending key order (reference
    base.py:259-315, deduplicated)."""
    left, right = next(g1, None), next(g2, None)
    while left is not None and right is not None:
        if left[0] < right[0]:
            yield ("left", left[0], left[1])
            left = next(g1, None)
        elif left[0] > right[0]:
            yield ("right", right[0], right[1])
            right = next(g2, None)
        else:
            yield ("both", left[0], left[1], right[1])
            left, right = next(g1, None), next(g2, None)
    while left is not None:
        yield ("left", left[0], left[1])
        left = next(g1, None)
    while right is not None:
        yield ("right", right[0], right[1])
        right = next(g2, None)


class InnerJoin(Reducer):
    """Sort-merge inner join over two co-partitioned grouped views
    (reference base.py:259-283)."""

    __deepcopy__ = _shared_instance_deepcopy

    def __init__(self, joiner_f, many=False):
        self.joiner_f = joiner_f
        self.many = many

    def reduce(self, *datasets):
        assert len(datasets) == 2
        walk = _sort_merge_walk(self.yield_groups(datasets[0]),
                                self.yield_groups(datasets[1]))
        for side, k, *vals in walk:
            if side != "both":
                continue
            it = self.joiner_f(k, vals[0], vals[1])
            if not self.many:
                it = [it]
            for nv in it:
                yield k, nv


class KeyedInnerJoin(InnerJoin):
    def reduce(self, *datasets):
        for k, v in super(KeyedInnerJoin, self).reduce(*datasets):
            yield k, (k, v)


class LeftJoin(Reducer):
    """Sort-merge left join; missing right groups get ``default()``
    (reference base.py:290-315)."""

    __deepcopy__ = _shared_instance_deepcopy

    def __init__(self, joiner_f, default=lambda: iter(())):
        self.joiner_f = joiner_f
        self.default = default

    def reduce(self, *datasets):
        assert len(datasets) == 2
        walk = _sort_merge_walk(self.yield_groups(datasets[0]),
                                self.yield_groups(datasets[1]))
        for side, k, *vals in walk:
            if side == "both":
                yield k, self.joiner_f(k, vals[0], vals[1])
            elif side == "left":
                yield k, self.joiner_f(k, vals[0], self.default())


class KeyedLeftJoin(LeftJoin):
    def reduce(self, *datasets):
        for k, v in super(KeyedLeftJoin, self).reduce(*datasets):
            yield k, (k, v)


class OuterJoin(Reducer):
    """Sort-merge full outer join; either side may be missing and sees
    ``default()``.  The reference's OuterJoin is dead code with undefined-
    variable bugs (reference base.py:355, 366 — never exposed by its DSL);
    this is the corrected behavior, exposed as a new capability
    (PJoin.outer_reduce)."""

    __deepcopy__ = _shared_instance_deepcopy

    def __init__(self, joiner_f, default=lambda: iter(())):
        self.joiner_f = joiner_f
        self.default = default

    def reduce(self, *datasets):
        assert len(datasets) == 2
        walk = _sort_merge_walk(self.yield_groups(datasets[0]),
                                self.yield_groups(datasets[1]))
        for side, k, *vals in walk:
            if side == "both":
                yield k, self.joiner_f(k, vals[0], vals[1])
            elif side == "left":
                yield k, self.joiner_f(k, vals[0], self.default())
            else:
                yield k, self.joiner_f(k, self.default(), vals[0])


class KeyedOuterJoin(OuterJoin):
    def reduce(self, *datasets):
        for k, v in super(KeyedOuterJoin, self).reduce(*datasets):
            yield k, (k, v)


# ---------------------------------------------------------------------------
# Combiners (map-side pre-aggregation descriptors)
# ---------------------------------------------------------------------------

class Combiner(object):
    """Map-side combine marker.  In this engine combining is block-native
    (segment folds over sorted hash lanes), so combiners describe *what* to
    fold rather than how to merge spill files (reference base.py:373-402)."""


class NoopCombiner(Combiner):
    pass


class UnorderedCombiner(Combiner):
    pass


class PartialReduceCombiner(Combiner):
    """Fold records sharing a key with an associative op during the map stage
    — the communication-avoidance step before the shuffle (reference
    base.py:393-402 + ReducedWriter dataset.py:84-117)."""

    def __init__(self, op):
        self.op = segment.as_assoc_op(op)
