"""Inverted index built ON the engine's columnar substrate.

Capability parity with the reference's indexer utility (reference
dampr/utils/indexer.py: per-file hidden SQLite DB, ``build``/``union``/
``intersect`` surface), engine-native construction and querying:

- **build**: each file's (token, byte-offset) postings accumulate as
  columnar Blocks and group through the vectorized hash-sort kernels
  (ops/segment.sort_and_group) — no per-posting SQL rows, no B-tree
  insert churn.  Each token stores ONE row: its offsets as a packed
  int64 array (ascending — stable sort preserves scan order).
- **union / intersect**: the matching tokens' offset arrays combine with
  vectorized set ops (np.unique over the concatenation); ``intersect``
  counts matched postings per offset, reproducing the reference's
  occurrence-counting semantics (a key appearing twice on a line counts
  twice toward ``min_match``).
- Lookups stream the matching lines back through a Dampr pipeline, one
  seek per offset, exactly like the reference.

The on-disk container stays a hidden per-file SQLite DB (one row per
token), so index files remain single ordinary files; all queries are
parameterized (hostile keys select nothing — they can never execute).
"""

import logging
import os
import sqlite3

import numpy as np

from ..blocks import Block
from ..dampr import Dampr
from ..inputs import read_paths
from ..ops import segment

log = logging.getLogger("dampr_tpu.indexer")

#: Postings batch: (token, offset) pairs accumulate into blocks of this
#: many records before grouping.
_BATCH = 1 << 16


class Indexer(object):
    def __init__(self, path, suffix=".index"):
        self.path = path
        self.suffix = suffix

    def get_idx(self, path):
        dirname, base = os.path.split(path)
        return os.path.join(dirname, "." + base + self.suffix)

    def exists(self, path):
        return os.path.isfile(self.get_idx(path))

    # -- build -------------------------------------------------------------
    def _index_one(self, fname, key_f):
        """Group one file's postings through the segment kernels and store
        one packed row per token.  Returns the posting count."""
        ks, vs, blocks = [], [], []
        off = 0
        with open(fname, "rb") as f:
            for raw in f:
                # key_f sees the line WITH its terminator — reference
                # parity (its indexer never stripped the newline).
                for tok in key_f(raw.decode("utf-8")):
                    ks.append(tok)
                    vs.append(off)
                off += len(raw)
                if len(ks) >= _BATCH:
                    blocks.append(Block.from_lists(ks, vs))
                    ks, vs = [], []
        if ks:
            blocks.append(Block.from_lists(ks, vs))

        idx = self.get_idx(fname)
        if os.path.isfile(idx):
            os.unlink(idx)
        db = sqlite3.connect(idx)
        db.execute("CREATE TABLE postings (key TEXT, offs BLOB)")
        total = 0
        if blocks:
            blk = Block.concat(blocks)
            total = len(blk)
            groups = segment.sort_and_group(blk)
            sb = groups.block
            starts, ends = groups.bounds()

            def rows():
                for i in range(len(starts)):
                    k = sb.keys[starts[i]]
                    offs = np.asarray(
                        sb.values[starts[i]:ends[i]], dtype=np.int64)
                    yield (k.item() if isinstance(k, np.generic) else k,
                           offs.tobytes())

            db.executemany("INSERT INTO postings VALUES (?, ?)", rows())
            db.execute("CREATE INDEX postings_key ON postings (key)")
        db.commit()
        db.close()
        return total

    def build(self, key_f, force=False):
        """Index every file under ``path``: ``key_f(line) -> iterable of
        keys``.  Returns the total postings indexed (same shape as the
        reference: ``[(1, total)]``)."""
        paths = sorted(read_paths(self.path, False))
        return (Dampr.memory(paths)
                .filter(lambda fname: force or not self.exists(fname))
                .map(lambda fname: self._index_one(fname, key_f))
                .fold_by(key=lambda _x: 1, binop=lambda x, y: x + y)
                .read(name="indexing"))

    # -- query -------------------------------------------------------------
    def _offsets_for(self, fname, keys):
        """Concatenated (with multiplicity) offset arrays of the matching
        tokens — the vectorized analog of the reference's per-row scan."""
        db = sqlite3.connect(self.get_idx(fname))
        try:
            marks = ",".join("?" for _ in keys)
            rows = db.execute(
                "SELECT offs FROM postings WHERE key IN ({})".format(marks),
                tuple(keys)).fetchall()
        finally:
            db.close()
        if not rows:
            return np.empty(0, dtype=np.int64)
        return np.concatenate(
            [np.frombuffer(blob, dtype=np.int64) for (blob,) in rows])

    def _seek_lines(self, select_offsets, keys):
        keys = list(keys)

        def read_matches(fname):
            offs = select_offsets(self._offsets_for(fname, keys))
            with open(fname, "rb") as f:
                for off in offs.tolist():
                    f.seek(off)
                    yield f.readline().decode("utf-8")

        paths = sorted(read_paths(self.path, False))
        return Dampr.memory(paths).flat_map(read_matches)

    def union(self, keys):
        """Lines containing any of the keys."""
        if not isinstance(keys, (list, tuple)):
            keys = [keys]
        return self._seek_lines(np.unique, keys)

    def intersect(self, keys, min_match=None):
        """Lines containing at least ``min_match`` of the keys (all, by
        default; a float is a fraction of the key count)."""
        if not isinstance(keys, (list, tuple)):
            keys = [keys]
        if min_match is None:
            min_match = len(keys)
        if isinstance(min_match, float):
            min_match = int(min_match * len(keys))

        def at_least(offs, m=min_match):
            uniq, counts = np.unique(offs, return_counts=True)
            return uniq[counts >= m]

        return self._seek_lines(at_least, keys)
