"""Global configuration for dampr_tpu.

Parity surface: the reference exposes mutable module globals in dampr/settings.py:1-37
(max_processes, compress_level, partitions, max_files_per_stage, batch_size,
memory_checker_type, max_memory_per_worker).  We keep the same "assign a module
attribute" ergonomics so reference users can switch without relearning config, and add
TPU-specific knobs (mesh shape, device batch size, spill tiers) that have no reference
analog.

Unlike the reference, per-op overrides still ride graph-node ``options`` dicts
(reference: runner.py:285/331, stagerunner.py:58-95), threaded through unchanged.
"""

import os

import multiprocessing

# ---------------------------------------------------------------------------
# Parity knobs (same names/meaning as reference dampr/settings.py)
# ---------------------------------------------------------------------------

#: Max host-side worker threads for input IO / opaque-UDF map stages.  The
#: reference forks this many processes (settings.py:5); we use threads because the
#: heavy lifting happens on-device and numpy/IO release the GIL.
max_processes = multiprocessing.cpu_count()

#: gzip compression level for spilled blocks (reference settings.py:8).
compress_level = 1

#: Number of shuffle partitions (reference settings.py:11 uses 91).  We default to a
#: multiple of typical mesh sizes so partitions map evenly onto devices.
partitions = 64

#: Upper bound on materialized block files per stage before a merge pass runs
#: (reference settings.py:16 `max_files_per_stage`).
max_files_per_stage = 50

#: Records per host block flushed to the device path (reference settings.py:20 uses
#: 1000 for pickle batches; device batches want to be much larger to amortize
#: dispatch).
batch_size = 65536

#: Execute pure per-record op chains (RecordOps) batch-at-a-time via
#: ``apply_batch`` — one tight C-level loop per op per batch — instead of
#: threading every record through nested generator frames (the reference's
#: hot loop, stagerunner.py:73-74).  Off = the record-at-a-time generator
#: lowering; outputs are identical (tests pin it), this is purely the
#: execution strategy.
batch_udf = os.environ.get("DAMPR_TPU_BATCH_UDF", "1") not in ("0", "false")

#: Byte budget per stage for in-memory blocks before spilling to the next tier
#: (replaces the reference's RSS-watermark `max_memory_per_worker`=512MB,
#: settings.py:27 + memory.py — our block sizes are known, so accounting is
#: deterministic, no /proc sampling).  Env-settable so deployment configs
#: (and autotune cold-config sessions) can pin it without code.
max_memory_per_stage = int(os.environ.get(
    "DAMPR_TPU_MEMORY_BUDGET", str(512 * 1024 * 1024)))

# ---------------------------------------------------------------------------
# TPU-native knobs (no reference analog)
# ---------------------------------------------------------------------------

#: Mesh axis name used for data-parallel sharding of record batches.
mesh_axis = "shards"

#: Stages whose materialized input is at most this many bytes skip per-
#: partition fan-out: plain record maps and sinks run as one job over the
#: concatenated refs, and associative folds reduce every partition in one
#: vectorized pass before re-splitting by hash.  Partition *identity* is
#: unchanged (outputs re-split by the same hash % P), only job granularity
#: collapses — per-partition numpy fixed costs dominate tiny stages
#: (measured: 64 partitions x ~1 ms on a 24k-record fold).
small_stage_bytes = 4 * 1024 * 1024

#: Scan sharing: map stages that read the SAME input tap (shared pipeline
#: prefixes — word_stats' four branches, TF-IDF's doc-freq + len) execute
#: fused in one pass over the chunks.  Members on the vectorized block
#: path (read_bytes / iter_byte_blocks) are served from one shared read
#: per chunk; per-record members still read their input independently.
#: Purely a scheduling change — per-stage outputs, partitioning, and
#: cleanup are unchanged.
scan_sharing = True

#: Byte-scanning block mappers (ops.text TokenCounts/DocFreq/ParseNumbers)
#: process chunks in line-aligned windows of this size instead of one
#: buffer: on this platform materializing a multi-GB contiguous bytes
#: object is pathological (measured 10.7 GB: one-shot read 196 s vs
#: windowed reads at 1.6 GB/s), and windows also bound mapper RSS by the
#: window, not the chunk.  256 MB measures within noise of a whole-buffer
#: scan at the 128 MB bench tier while keeping the 10 GB tier bounded.
scan_window_bytes = 256 * 1024 ** 2

#: When True, keyed kernels (hash/sort/segment-reduce) run through JAX on the default
#: backend; when False everything uses the numpy host fallback (useful for debugging).
use_device = os.environ.get("DAMPR_TPU_USE_DEVICE", "1") not in ("0", "false")

#: Minimum records in a block before device dispatch is worth it; smaller
#: blocks take the numpy path to dodge dispatch overhead.  None = resolve by
#: transport: in-process backends (cpu) dispatch cheaply at 4096; a
#: locally-attached accelerator needs larger batches to amortize transfer;
#: a remote-tunnel attachment (detected via the tunnel env) only pays off
#: for multi-million-record batches.  Set an int to pin it.
device_min_batch = (int(os.environ["DAMPR_TPU_DEVICE_MIN_BATCH"])
                    if os.environ.get("DAMPR_TPU_DEVICE_MIN_BATCH") else None)

#: Every auto-resolved threshold is at least this, so batches below it decide
#: "host" without touching (or initializing) any JAX backend.
_MIN_BATCH_FLOOR = 4096

_resolved_min_batch = None


def effective_device_min_batch():
    global _resolved_min_batch
    if device_min_batch is not None:
        return device_min_batch
    if _resolved_min_batch is None:
        # The remote-tunnel check comes FIRST and reads only the
        # environment: resolving via jax.default_backend() would
        # initialize the backend, which on a tunnel-attached host is a
        # network round-trip that can block indefinitely when the tunnel
        # is unhealthy — the engine must never hang just to decide that
        # host numpy is the right place for a batch.
        if os.environ.get("PALLAS_AXON_REMOTE_COMPILE"):
            _resolved_min_batch = 1 << 22
        else:
            import jax

            if jax.default_backend() == "cpu":
                _resolved_min_batch = 4096
            else:
                _resolved_min_batch = 1 << 16
    return _resolved_min_batch


def device_count_for_auto():
    """Visible-device count for auto-mode mesh decisions, without forcing a
    backend init through a (possibly unhealthy) remote tunnel: when no jax
    backend is initialized yet on a tunnel-attached host, report 1 — the
    mesh paths stay off, which is the correct call for a single tunneled
    chip anyway.  Anywhere else (or once a backend exists) this is just
    len(jax.devices())."""
    import jax

    if os.environ.get("PALLAS_AXON_REMOTE_COMPILE"):
        try:
            from jax._src import xla_bridge

            if not xla_bridge._backends:
                return 1
        except Exception:
            pass  # private attr moved: fall through to the real probe
    return len(jax.devices())


def use_device_for(n):
    """Device-dispatch decision for an n-record batch.  Small batches answer
    without resolving the backend (no accidental JAX initialization)."""
    if not use_device or n < _MIN_BATCH_FLOOR:
        return False
    return n >= effective_device_min_batch()

#: HBM residency tier budget, in bytes (SURVEY §2 item 6 / §7 sketch 1):
#: numeric VALUE lanes of reduce-feeding stage outputs stay device-resident
#: between map and reduce — the reduce's collective fold consumes them
#: without a host round-trip.  Over budget, the oldest device refs offload
#: device->host (the FIRST spill step; host RAM pressure then cascades to
#: disk as usual).  0 disables the tier.  "auto" resolves by transport the
#: same way device_min_batch does: off on cpu backends (device RAM is host
#: RAM) and on tunnel-attached hosts (a hung tunnel must never wedge the
#: engine), 1 GB on a locally-attached accelerator.
hbm_budget = os.environ.get("DAMPR_TPU_HBM_BUDGET", "auto")

#: Minimum records in a block before HBM residency is worth the put
#: overhead; smaller reduce-feeding blocks stay host (the local fold is
#: cheaper than a device round-trip at that size).
hbm_min_records = int(os.environ.get("DAMPR_TPU_HBM_MIN_RECORDS", "4096"))

_resolved_hbm = None


def effective_hbm_budget():
    global _resolved_hbm
    if isinstance(hbm_budget, int):
        return hbm_budget
    s = str(hbm_budget).lower()
    if s != "auto":
        return int(s)
    if _resolved_hbm is None:
        if os.environ.get("PALLAS_AXON_REMOTE_COMPILE"):
            _resolved_hbm = 0
        else:
            import jax

            _resolved_hbm = 0 if jax.default_backend() == "cpu" else 1 << 30
    return _resolved_hbm


#: Capacity slack factor for the fixed-shape all_to_all shuffle exchange
#: (MoE-style capacity: per-(src,dst) buffer = ceil(N/D) * factor).
shuffle_capacity_factor = 1.5

#: Route device-foldable associative reduces through the mesh collective
#: shuffle (local fold -> all_to_all -> final fold) instead of per-partition
#: host jobs: "auto" = when more than one device is visible, "on", "off".
#: Falls back to the host path whenever exactness can't be guaranteed
#: (object values, 32-bit lane overflow, 64-bit key collisions).
mesh_fold = os.environ.get("DAMPR_TPU_MESH_FOLD", "auto")

#: Route the *general* shuffle — non-associative group_by reduces, joins —
#: through the mesh byte exchange (parallel/exchange.py): every input
#: partition's blocks cross a fixed-shape all_to_all, windowed under the run
#: budget, with partition pid resident on device pid % D (co-partitioning
#: preserved for joins by construction).  "auto" = when more than one device
#: is visible, "on", "off".  The associative-numeric fast path (mesh_fold)
#: takes precedence where it applies.
mesh_exchange = os.environ.get("DAMPR_TPU_MESH_EXCHANGE", "auto")

#: Peak in-flight device bytes one exchange collective step may occupy
#: (send + delivered buffers, tripled by the multi-process gather
#: replication — the deterministic model in
#: :func:`dampr_tpu.parallel.replan.step_inflight_bytes`).  The byte
#: exchange decomposes every window into a schedule of chunked
#: all_to_all steps that each fit this budget, so the shuffle's device
#: working set is bounded by configuration, never by the data (the
#: memory-efficient redistribution recipe, arXiv 2112.01075).
exchange_hbm_budget = int(os.environ.get(
    "DAMPR_TPU_EXCHANGE_HBM", str(64 * 1024 ** 2)))

#: Optional explicit per-piece chunk cap (bytes) for the exchange
#: schedule, below what the budget alone allows.  0 (default) derives the
#: chunk size from ``exchange_hbm_budget``; set it when a device is
#: memory-pressured beyond what the in-flight model captures (the doctor
#: playbook's second exchange knob).
exchange_chunk_bytes = int(os.environ.get("DAMPR_TPU_EXCHANGE_CHUNK", "0"))

#: Cost-model floor for routing a redistribution over the mesh: in auto
#: mode, a stage whose recorded shuffle input (run-history corpus) is
#: under this many bytes keeps the host shuffle — collective windows pay
#: D*D pack/unpack fixed costs that dominate tiny exchanges.  Explicit
#: ``mesh_exchange="on"``/``"off"`` always wins over this heuristic.
exchange_min_bytes = int(os.environ.get(
    "DAMPR_TPU_EXCHANGE_MIN_BYTES", str(4 * 1024 ** 2)))

#: Ingest readahead window (chunks): a background thread prefetches the next
#: chunks' bytes (file IO + gzip inflate release the GIL) while the current
#: chunk computes.  0 disables.  See inputs.Readahead.
readahead_chunks = int(os.environ.get("DAMPR_TPU_READAHEAD", "2"))

#: Codec->fold overlap depth (the stage-overlapped streaming executor):
#: each map job runs its codec — decompress + tokenize/parse, the
#: ``map_blocks`` window scan — on a dedicated thread that stays this many
#: produced blocks ahead of the fold/register consumer, extending the raw-
#: byte readahead (``readahead_chunks``) up through the codec.  In-flight
#: codec output is charged byte-for-byte against the stage memory budget
#: (storage.RunStore.reserve_overlap), so overlapping displaces resident
#: blocks instead of raising the memory ceiling.  0 = serial (codec and
#: fold interleave on the job thread, the pre-round-6 behavior).
overlap_windows = int(os.environ.get("DAMPR_TPU_OVERLAP_WINDOWS", "2"))

#: Barrier-free pipelined execution (docs/pipeline.md): the plan's
#: ``pipeline`` pass marks producer->consumer stage edges ``streamed``
#: wherever byte-identity is provable (map->keyed-fold via early partial
#: folds, unfused map->map chains, sorted-run merge -> final read), and
#: the runner dissolves the stage barrier on those edges — completed
#: partitions publish into a bounded backpressure queue the consumer
#: works from while the producer is still running.  "auto"/"on" enable
#: it; "off"/"0" (the kill switch) reproduces staged execution
#: byte-identically.  Every edge decision — streamed or barrier, with
#: its reason — lands in the plan report and ``explain()`` regardless.
pipeline = os.environ.get("DAMPR_TPU_PIPELINE", "auto")


def pipeline_enabled():
    return str(pipeline).lower() not in ("off", "0", "false", "no")


#: Byte bound for the pipelined publish queue (the backpressure
#: contract): at most this many bytes of completed-but-unconsumed
#: partition output sit between a streamed edge's producer and consumer;
#: past it the publisher blocks (a ``pipe-wait`` stall span) until the
#: consumer drains.  Queued bytes are charged against the run budget
#: through ``RunStore.reserve_overlap``, so spill admission sees the
#: pressure.  0 (default) resolves to a quarter of the stage memory
#: budget at run time.
pipeline_queue_bytes = int(os.environ.get("DAMPR_TPU_PIPELINE_QUEUE", "0"))

#: Spill-lean sorted-run mode for map outputs no reduce ever consumes
#: (external sorts: ``ParseNumbers -> checkpoint``): each map job registers
#: its chunk's output as ONE key-sorted run instead of hash-fanning it into
#: ``partitions`` sub-blocks, the block-count compaction rewrite is skipped,
#: and the final read streams a k-way merge over the runs.  "auto"/"on"
#: enable it (jobs fall back to hash fan-out per chunk when keys are
#: non-numeric); "off" restores hash fan-out everywhere.  Reduce-consumed
#: outputs are never eligible — they keep hash routing, and the identity-
#: checkpoint alias gate forces a re-routing copy stage if a sorted-run
#: set ever flows toward a reduce.
sort_runs = os.environ.get("DAMPR_TPU_SORT_RUNS", "auto")

#: Maximum first-level sorted runs the final read merges directly.  At or
#: under this fan-in the output streams straight from first-level runs —
#: zero re-spill generations, each run file read once, sequentially.  Past
#: it, runs merge in generations of ``merge_fanin`` through a streamed
#: file->file pass (storage.register_stream) until the count fits.  The
#: effective cap also respects the memory budget: a merge holds one spill
#: window per run, so the planner clamps fan-in to
#: ``budget // per-run-window-bytes`` (floor 4).
merge_fanin = int(os.environ.get("DAMPR_TPU_MERGE_FANIN", "512"))


def sort_runs_enabled():
    return str(sort_runs).lower() not in ("off", "0", "false")

# ---------------------------------------------------------------------------
# Logical plan optimizer (dampr_tpu.plan — see docs/plan.md)
# ---------------------------------------------------------------------------

#: Master switch for the logical plan optimizer: every run's stage list is
#: rewritten (map fusion, combiner hoisting, sink fusion, dead-stage
#: elimination, stats-driven sizing) before execution.  Off, the graph
#: executes exactly as constructed — one stage per chained DSL call — the
#: reference's literal schedule.  Results are identical either way (the
#: optimizer-equivalence property tests pin it); this only changes how
#: many materialize boundaries the run pays.
def _env_flag(name):
    """Shared on/off env parsing: 0/false/no/off (any case) disable."""
    return os.environ.get(name, "1").lower() not in (
        "0", "false", "no", "off")


optimize = _env_flag("DAMPR_TPU_OPTIMIZE")

#: Static pipeline analysis (dampr_tpu.analyze, docs/analysis.md): UDF
#: purity/determinism classification, dispatch-safety (pickle) probes,
#: fold associativity recognition, and the jax-traceability probe that
#: widens device lowering to certified numeric UDF chains.  On (the
#: default), every run's plan report carries an ``analysis`` section,
#: fusion declines to fuse across evidence-impure UDFs, speculation
#: declines on nondeterministic UDFs, multi-process dispatch of
#: unpicklable closures fails pre-flight with a named diagnostic, and
#: certified numeric chains become device-lowerable.  Off
#: (``DAMPR_TPU_ANALYZE=0``), every hook is one flag check and plans,
#: fingerprints, and results are byte-identical to the pre-analysis
#: engine (CI pins it).
analyze = _env_flag("DAMPR_TPU_ANALYZE")

#: Per-rule kill switches (all default on; only consulted when
#: ``optimize`` is on).  plan_fuse: compose chains of pure per-record map
#: stages; plan_hoist: dissolve identity+combiner stages into their
#: producer (the map-side fold runs inside the producer's jobs);
#: plan_fuse_sinks: compose record chains into sink stages; plan_dead:
#: drop stages unreachable from any requested output or sink;
#: plan_adapt: size partitions/batches from the prior run's stats.json.
plan_fuse = _env_flag("DAMPR_TPU_PLAN_FUSE")
plan_hoist = _env_flag("DAMPR_TPU_PLAN_HOIST")
plan_fuse_sinks = _env_flag("DAMPR_TPU_PLAN_FUSE_SINKS")
plan_dead = _env_flag("DAMPR_TPU_PLAN_DEAD")
plan_adapt = _env_flag("DAMPR_TPU_PLAN_ADAPT")

#: Adaptive sizing targets (dampr_tpu.plan.cost): bytes of reduce input
#: one partition should carry (drives the adapted partition count), and
#: the byte size a map-stage output block should target when history
#: shows fat records (drives per-stage ``batch_size`` options).
plan_partition_bytes = int(os.environ.get(
    "DAMPR_TPU_PLAN_PARTITION_BYTES", str(32 * 1024 ** 2)))
plan_block_bytes = int(os.environ.get(
    "DAMPR_TPU_PLAN_BLOCK_BYTES", str(8 * 1024 ** 2)))

#: Learned per-operator cost model (dampr_tpu.plan.model, docs/tuning.md):
#: "auto" (default) fits per-operator-class throughput regressors over the
#: run-history corpus and uses them to SEARCH the knob space (partition
#: count, per-stage batch sizes, merge fan-in, overlap windows, spill
#: codec/threads, exchange budgets, shuffle placement) instead of replaying
#: medians — every choice lands in the plan report's ``cost`` section with
#: its predicted-vs-static delta.  "0"/"off" is the kill switch: the
#: adaptation layer reproduces the pre-model median-path decisions
#: byte-identically (pinned by tests).  Below the fit-confidence floor the
#: model abstains and the median path stands, with the reason recorded.
cost_model = os.environ.get("DAMPR_TPU_COST_MODEL", "auto")


def cost_model_enabled():
    return str(cost_model).lower() not in ("0", "false", "no", "off")


#: Fit-confidence floor for the cost model: an operator class needs at
#: least this many corpus measurements before its regressor participates,
#: and the whole model abstains (median fallback, reason recorded) until
#: the classes covering the plan's stages are all fit.
cost_model_min_points = int(os.environ.get(
    "DAMPR_TPU_COST_MODEL_MIN_POINTS", "3"))

#: Minimum predicted improvement (fractional) before a model choice
#: overrides the median/static decision — hysteresis so a noisy fit never
#: flips knobs for sub-noise gains.
cost_model_margin = float(os.environ.get(
    "DAMPR_TPU_COST_MODEL_MARGIN", "0.02"))

#: Closed-loop autotuning for bench drivers (dampr_tpu.obs.autotune):
#: when "on", benches that honor it (bench_tfidf) re-run their measured
#: pipeline under model-suggested knob vectors, keep the fastest
#: byte-identical configuration, and persist the winner (tuned.json +
#: the winner run's own corpus record) so the next fit sees it.  The
#: unattended CLI form is ``dampr-tpu-doctor --autotune``.  "off"
#: (default) = single-configuration runs, exactly as before.
autotune = os.environ.get("DAMPR_TPU_AUTOTUNE", "off")


def autotune_enabled():
    return str(autotune).lower() in ("on", "1", "true", "yes")


#: Trial budget for one autotune session (trial 0 is always the incoming
#: baseline configuration; the remaining trials come from the model's
#: knob search and the doctor playbook).  Bounded by construction: a
#: session never runs more than this many measured executions.
autotune_trials = int(os.environ.get("DAMPR_TPU_AUTOTUNE_TRIALS", "4"))

#: Cross-run materialization cache (dampr_tpu.plan.reuse): "on"/"1"
#: consults (and publishes to) the shared content-addressed stage cache
#: under ``reuse_dir`` so identical pipeline prefixes — across runs,
#: run NAMES, and processes — mount cached partition frames instead of
#: recomputing, and append-only input growth re-runs only the new
#: chunks.  "auto" (default) resolves OFF in ordinary processes and ON
#: inside serve-daemon workers (``serve_active``): served submissions
#: share materializations across tenants by default, exactly the
#: amortization the service exists for.  "0"/"off" pins the cache
#: fully out of the path — including inside the daemon, so the
#: reuse-off CI leg stays byte-identical end to end (plans,
#: fingerprints, and results are byte-identical either way).
reuse = os.environ.get("DAMPR_TPU_REUSE", "auto")


def reuse_enabled():
    v = str(reuse).lower()
    if v in ("on", "1", "true", "yes"):
        return True
    if v in ("off", "0", "false", "no"):
        return False
    return bool(serve_active)  # "auto": ON inside serve-daemon workers


#: Byte budget for the shared reuse cache directory.  Publishing past
#: the budget evicts least-recently-consumed entries (whole entries,
#: never single blocks) under the store's exclusive flock; mounted runs
#: are immune — consumers hardlink cached frames into their own scratch
#: before reading.
reuse_budget_bytes = int(os.environ.get("DAMPR_TPU_REUSE_BUDGET",
                                        str(2 * 1024 ** 3)))

#: Shared reuse-cache directory.  Empty (default) resolves to
#: ``<scratch_root>/reuse-cache`` at use time, so tests that repoint
#: scratch_root isolate their cache with it; co-located runs that
#: should SHARE materializations point this at one common directory.
reuse_dir = os.environ.get("DAMPR_TPU_REUSE_DIR", "")

#: Content-signature chunk granularity (bytes): input files are
#: fingerprinted in windows of this size, and append-only growth is
#: detected as a signature whose chunk list extends a cached prefix.
reuse_chunk_bytes = int(os.environ.get("DAMPR_TPU_REUSE_CHUNK",
                                       str(16 * 1024 ** 2)))

#: Deterministic seeding for ``sample(prob)``: None (default) keeps the
#: historical behavior — each worker thread draws from a time-seeded RNG,
#: so sampled pipelines are NOT reproducible run to run.  An int seeds
#: every per-thread RNG deterministically (re-derived at each run start),
#: making sampled pipelines reproducible whenever job->thread assignment
#: is deterministic — serial runs (``max_processes=1`` or single-job
#: stages) exactly, parallel runs per-thread-stream.  This is what lets
#: the optimizer-equivalence tests pin sampled pipelines.
seed = (int(os.environ["DAMPR_TPU_SEED"])
        if os.environ.get("DAMPR_TPU_SEED") else None)

# ---------------------------------------------------------------------------
# Device lowering (dampr_tpu.plan.lower + dampr_tpu.ops.lower)
# ---------------------------------------------------------------------------

#: Master switch for the device-lowering pass: fused map->fold stages
#: whose operators come from the native vocabulary (ops.text scanners
#: feeding keyed associative folds) compile into ONE jitted JAX program —
#: tokenize bounds on host, hash + dedup + segment fold on the default
#: JAX backend — instead of the host codec path.  "on"/"1" force it,
#: "off"/"0" disable it, "auto" (default) enables it whenever a
#: non-CPU accelerator backend is attached (on CPU-only hosts the native
#: C codec measures faster, so auto keeps the host path; CI forces the
#: CPU-JAX jit leg with DAMPR_TPU_LOWER=1).  Results are byte-identical
#: either way — ineligible stages and non-byte inputs always keep the
#: host path, and the program's collision check falls back per batch.
lower = os.environ.get("DAMPR_TPU_LOWER", "auto")

_resolved_lower = None


def lower_forced():
    """Was lowering EXPLICITLY forced on ("1"/"on")?  A forced switch
    wins over the stats-driven placement floor (``lower_min_records``):
    the operator asked for device execution, so accumulated history must
    not silently pin eligible stages back to host — only ``auto`` mode
    is cost-driven."""
    return str(lower).lower() in ("on", "1", "true", "yes")


def lower_enabled():
    """Is device lowering in force?  Auto resolves by backend the same
    way the HBM tier does (never through a possibly-unhealthy remote
    tunnel)."""
    global _resolved_lower
    s = str(lower).lower()
    if s in ("on", "1", "true", "yes"):
        return True
    if s in ("off", "0", "false", "no"):
        return False
    if _resolved_lower is None:
        if os.environ.get("PALLAS_AXON_REMOTE_COMPILE"):
            _resolved_lower = False
        else:
            import jax

            _resolved_lower = jax.default_backend() != "cpu"
    return _resolved_lower


#: Tokens per device program dispatch: each line-aligned scan window is
#: fed to the jitted program in batches of at most this many tokens
#: (padded to a power of two so compilations stay bounded).  Bounds the
#: padded token matrix the h2d feed stages while the previous batch's
#: program runs (the double-buffered overlap).
lower_batch = int(os.environ.get("DAMPR_TPU_LOWER_BATCH", str(1 << 18)))

#: Stats-driven placement floor: when a prior run's stats history shows
#: a lowered stage emitted fewer records than this, the cost layer
#: places it back on host — program dispatch overhead dominates tiny
#: stages (the tf.data-service argument: recorded stats pick host vs
#: device per stage).
lower_min_records = int(os.environ.get(
    "DAMPR_TPU_LOWER_MIN_RECORDS", "4096"))

#: Cross-stage device-resident handoff (docs/plan.md "Cross-stage device
#: fusion"): when the plan lowers an adjacent map producer AND its
#: consuming associative fold to the device, the producer's program
#: outputs stay HBM-resident and the fold consumes them in place —
#: skipping the d2h fetch, pickle, frame encode/decode, spill, and h2d
#: re-upload the host spill path would pay on that edge.  "auto"
#: (default) engages whenever lowering is in force AND either the HBM
#: tier has budget (a real accelerator) or lowering was explicitly
#: forced (the CPU-JAX jit leg: device memory IS host memory there, so
#: residency is free) — but an explicit ``hbm_budget=0`` declines auto
#: ("no device residency" wins); "on"/"1" force it, "off"/"0" disable
#: it.  Every
#: fallback (HBM budget exceeded, vocabulary overflow, 64-bit hash
#: collision, non-lowered consumer at run time) degrades that edge — or
#: just that batch — to the existing spill path byte-identically.
handoff = os.environ.get("DAMPR_TPU_HANDOFF", "auto")


def handoff_forced():
    return str(handoff).lower() in ("on", "1", "true", "yes")


def handoff_enabled():
    """Is the cross-stage device handoff tier in force?  Auto follows the
    lowering decision: enabled when stages lower AND device residency is
    either budgeted (HBM budget > 0) or free (the forced CPU-JAX leg) —
    but an EXPLICIT ``hbm_budget=0`` ("no device residency") always
    declines auto; only a forced ``handoff=on`` overrides it."""
    s = str(handoff).lower()
    if s in ("off", "0", "false", "no"):
        return False
    if s in ("on", "1", "true", "yes"):
        return True
    if str(hbm_budget).lower() != "auto" and effective_hbm_budget() == 0:
        return False
    return lower_enabled() and (effective_hbm_budget() > 0
                                or lower_forced())


def effective_handoff_budget():
    """Device bytes the handoff tier may keep resident: the HBM budget
    when the tier is funded, else (forced / forced-lowering CPU legs,
    where device RAM is host RAM) the run's stage memory budget."""
    b = effective_hbm_budget()
    if b > 0:
        return b
    if handoff_enabled():
        return max_memory_per_stage
    return 0


#: Route the lowered program's segment-count step through the Pallas
#: fused segfold kernel (ops/pallas_segfold.py) instead of the XLA scan
#: lowering.  Off by default until benchmarks/pallas_bench.py measures a
#: win on real hardware (the FNV Pallas kernel measured 0.58x and is NOT
#: dispatched; same discipline here).
lower_pallas_segfold = os.environ.get(
    "DAMPR_TPU_LOWER_PALLAS", "0").lower() not in ("0", "false", "no", "off")

#: Spill compression policy: "auto" (default) compresses object-lane
#: blocks and writes fully-numeric blocks raw (high-entropy lanes don't
#: compress and the codec pass is core-bound both ways); "always"/"never"
#: force it.  A codec name ("gzip", "zlib", "zlib:6", "lz4", "zstd") is
#: also accepted and means "always, with that codec".
spill_compress = os.environ.get("DAMPR_TPU_SPILL_COMPRESS", "auto")

#: Frame codec used when the policy above says compress: "auto" picks the
#: best available (zstd > lz4 > zlib); explicit names take an optional
#: ":level" suffix ("zlib:6").  Unavailable optional codecs (lz4/zstd not
#: installed) fall back down the same ladder with a one-time warning;
#: gzip remains readable forever via per-frame codec ids and whole-file
#: magic sniffing (see dampr_tpu.io and docs/spill_format.md).
spill_codec = os.environ.get("DAMPR_TPU_SPILL_CODEC", "auto")

#: Background spill writer threads (dampr_tpu.io.writer.SpillWriterPool):
#: spill writes enqueue onto this many writer threads so folds never
#: block on codec+disk unless the queue is full; queued blocks'
#: in-flight bytes are charged against the stage memory budget like
#: overlap windows.  0 = synchronous spills on the evicting thread (the
#: pre-PR-3 behavior).
spill_write_threads = int(os.environ.get("DAMPR_TPU_SPILL_WRITERS", "2"))

#: Byte cap on queued-but-unwritten spill blocks (the writer pool's
#: double-buffering bound; admission is by current backlog, so in-flight
#: bytes peak at this cap plus one block).  None or 0 = half the stage
#: memory budget.  Queued bytes are budget-charged either way — they
#: displace resident blocks, never stack on top of the stage ceiling.
spill_inflight_bytes = (int(os.environ["DAMPR_TPU_SPILL_INFLIGHT"])
                        if os.environ.get("DAMPR_TPU_SPILL_INFLIGHT")
                        else None)

#: Readahead depth (frames) per spilled-run stream: merge readers and
#: final reads keep this many frames in flight on the shared read
#: executor, so decompression overlaps consumption and sibling runs'
#: frames decode in parallel.  0 = strictly serial reads.
spill_read_prefetch = int(os.environ.get("DAMPR_TPU_SPILL_PREFETCH", "2"))

#: Threads on the shared frame-read executor (process-wide; a k-way merge
#: over hundreds of runs multiplexes its prefetch onto these).
spill_read_threads = int(os.environ.get(
    "DAMPR_TPU_SPILL_READ_THREADS",
    str(min(4, multiprocessing.cpu_count()))))

#: Spill directory for host-RAM overflow (the reference's /tmp/<job> scratch tree,
#: base.py:435-469).
scratch_root = os.environ.get("DAMPR_TPU_SCRATCH", "/tmp/dampr_tpu")

#: Per-job retry budget for transient failures (flaky IO/UDF): a failing map/
#: reduce/sink job re-executes up to this many times before the run fails
#: fast with the original traceback.  The reference deadlocks on a dead
#: worker (stagerunner.py:35-38); 0 keeps plain fail-fast.  Retries are
#: CLASSIFIED (dampr_tpu.faults.classify): transient failures (flaky IO)
#: back off exponentially with jitter between attempts; deterministic
#: failures retry immediately (legacy behavior — a stateful UDF may
#: recover); fatal failures (MemoryError, kills) never retry.
job_retries = int(os.environ.get("DAMPR_TPU_JOB_RETRIES", "0"))

#: In-place retry budget for transient spill IO (background/sync frame
#: writes, frame reads, checkpoint persistence).  These retries are
#: absorbed inside the IO layer — a flaky disk never surfaces as a job
#: failure unless the budget is exhausted.  Counted in
#: ``stats()["faults"]``.
io_retries = int(os.environ.get("DAMPR_TPU_IO_RETRIES", "2"))

#: Exponential-backoff base and cap (milliseconds) for classified
#: transient retries (full jitter: each delay is uniform over
#: [0, min(cap, base * 2^attempt)]).
retry_backoff_ms = int(os.environ.get("DAMPR_TPU_RETRY_BACKOFF_MS", "50"))
retry_backoff_max_ms = int(os.environ.get(
    "DAMPR_TPU_RETRY_BACKOFF_MAX_MS", "5000"))

#: Poison-record quarantine budget: when > 0, a deterministically-failing
#: record batch on the batched-UDF map path is bisected and up to this
#: many offending records land in the run's quarantine sink
#: (``<scratch_root>/<run>/quarantine.jsonl``) instead of failing the
#: run; the stage completes with the skip count in
#: ``stats()["faults"]["quarantined"]`` and per-stage ``quarantined``
#: counters.  0 (default) = fail fast as before.
max_quarantined = int(os.environ.get("DAMPR_TPU_MAX_QUARANTINED", "0"))

#: Bounded deadline (milliseconds) for each collective exchange step
#: (``parallel.exchange.mesh_blob_exchange``).  0 (default) = no
#: watchdog.  When set, a step that has not completed within the
#: deadline — a dead rank wedging the gloo collective — makes every
#: SURVIVING rank abort cleanly: the flight recorder flushes a
#: crashdump, the timeout is recorded in the run's fault-event sidecar
#: (so the next run's shuffle routing degrades that stage to the host
#: path), and the process exits nonzero instead of hanging forever.
exchange_timeout_ms = int(os.environ.get(
    "DAMPR_TPU_EXCHANGE_TIMEOUT_MS", "0"))

#: Straggler mitigation (dampr_tpu.parallel.mitigate): when "on", every
#: run starts a per-run mitigation controller that turns the live skew
#: signal into action — work stealing from backlogged job queues,
#: speculative re-execution of straggler jobs (first-result-wins under
#: attempt-scoped commits), collective degrade-in-place when a rank is
#: persistently late at exchange steps, and sticky partition-share
#: down-weighting for pathological ranks.  "off" (the default) costs
#: zero overhead: every mitigation site is one module-global None-check,
#: the same contract as tracing/profiling.
mitigate = os.environ.get("DAMPR_TPU_MITIGATE", "off")


def mitigate_enabled():
    return str(mitigate).lower() in ("on", "1", "true", "yes")


#: Engagement threshold for the mitigation controller, two roles with
#: one meaning ("this worker is this many times slower than its peers"):
#: (a) a rank whose collective-step entry lateness is >= this multiple
#: of the OTHER ranks' mean lateness plus the 20 ms jitter floor counts
#: as pathological (deliberately not the reported ``late_ratio``, which
#: saturates at the rank count — see mitigate.observe_window); (b) a
#: host job whose elapsed time exceeds this multiple of the median
#: completed job duration becomes a speculation candidate.
speculate_threshold = float(os.environ.get(
    "DAMPR_TPU_SPECULATE_THRESHOLD", "1.5"))

#: Consecutive pathological observations before the mitigation engages
#: (and consecutive healthy probe observations before it disengages).
#: Twice this count of consecutive pathological observations escalates
#: to the sticky down-weight (the rank's partition share is reduced for
#: the remainder of the run).
speculate_after_steps = int(os.environ.get(
    "DAMPR_TPU_SPECULATE_AFTER", "3"))

#: While the collective path is degraded, every this-many skipped
#: windows one window runs through the mesh as a PROBE to re-measure
#: skew — how a mitigation engaged for a transient slow spell
#: (faults.py's windowed ``duration_ms`` slowness) disengages cleanly
#: once the rank recovers.  0 disables probing (degrade becomes sticky
#: for the run).
mitigate_probe_windows = int(os.environ.get(
    "DAMPR_TPU_MITIGATE_PROBE", "4"))

#: CAMR-style coded aggregation for keyed folds routed over the byte
#: exchange (arXiv 1901.07418): "camr" pre-folds each exchange window's
#: blocks per destination partition under the stage's associative op —
#: replicated map-side fold work traded for strictly fewer shuffle
#: bytes (duplicate keys collapse before they cross the mesh).  Applies
#: only where exactness is free: integer/bool lanes for sums (float
#: summation order would change), any numeric lane for min/max.  "off"
#: (default) ships every window's raw partials.  Byte-exactness against
#: the uncoded path is pinned by tests.
exchange_coding = os.environ.get("DAMPR_TPU_EXCHANGE_CODING", "off")


def exchange_coding_enabled():
    return str(exchange_coding).lower() in ("camr", "on", "1", "true")


#: Per-route exchange payload compression: each (src, dst) blob is
#: compressed before the chunked all_to_all schedule is planned, so the
#: schedule's HBM-budget packing and the gloo wire both see compressed
#: bytes.  "auto" (default) picks the best codec available in the
#: environment (zstd > lz4 > off — io/codecs.py ladder); a codec name
#: pins it; "off" ships raw bytes.  Wire-vs-raw byte counts land in
#: ``stats()["mesh"]["exchange"]``; byte-exactness against the uncoded
#: path is pinned by tests (decompression restores the exact payload).
exchange_codec = os.environ.get("DAMPR_TPU_EXCHANGE_CODEC", "auto")


#: Whole-run retry budget for ``run(resume="auto")``: a failed run
#: re-executes from its last durable checkpoint manifest up to this
#: many times (transient-backoff between attempts; fatal failures and
#: explicit kills never auto-resume).
run_retries = int(os.environ.get("DAMPR_TPU_RUN_RETRIES", "1"))

#: Deterministic fault-injection plan (dampr_tpu.faults): a seeded,
#: schedule-based spec naming fault sites and firing rules, e.g.
#: ``"spill_write:p=0.01;exchange_step:nth=3;seed=7"``.  Empty/None
#: (default) = injection fully disabled — every site is one
#: module-global None-check.  See docs/robustness.md for the grammar
#: and site catalog.
faults = os.environ.get("DAMPR_TPU_FAULTS") or None

#: When set, every run is wrapped in a jax.profiler trace written under this
#: directory (view with TensorBoard / xprof).  Structured per-stage metrics
#: are always available via ValueEmitter.stats regardless.
profile_dir = os.environ.get("DAMPR_TPU_PROFILE_DIR") or None

#: Run-scoped engine tracing (dampr_tpu.obs): when True every run records
#: spans at the hot engine boundaries — codec/fold in the overlapped map
#: driver, spill writes and k-way merge generations, mesh collectives and
#: byte exchanges, checkpoint persist/restore, HBM tier moves — and
#: persists a Chrome trace-event JSON (loadable in Perfetto /
#: chrome://tracing) plus a ``stats.json`` summary under
#: ``<scratch_root>/<run>/trace/``.  Off (the default) the span sites are
#: a single None-check each, so the engine's hot loops pay near-zero cost.
#: This is the engine-boundary timeline; ``profile_dir`` above remains the
#: escape hatch for a profiler-grade XLA kernel timeline.
trace = os.environ.get("DAMPR_TPU_TRACE", "0").lower() not in (
    "0", "false", "no", "off", "")

#: Override directory for trace/stats artifacts.  None (default) puts them
#: under the run's scratch root, next to its durable spill/checkpoint
#: outputs; a path pins every run's artifacts under <trace_dir>/<run>/.
trace_dir = os.environ.get("DAMPR_TPU_TRACE_DIR") or None

#: Per-operator profiler (dampr_tpu.obs.profile): when True every run
#: attributes wall time and record counts to the INDIVIDUAL user ops a
#: fused stage was built from — each composed ``apply_batch`` step, each
#: codec window per scanner, map-side partial/final folds, and the
#: device programs' build/h2d/compute/d2h sub-phases — and ships the
#: result as ``stats()["profile"]`` (plus the run-history corpus).  Off
#: (the default) every instrumentation site is one module-global
#: None-check, same contract as ``trace``/``metrics_interval_ms``; the
#: timers are per-batch/per-window, never per-record, so the on-path
#: overhead stays within the ≤3% bench gate.
profile = os.environ.get("DAMPR_TPU_PROFILE", "0").lower() not in (
    "0", "false", "no", "off", "")

#: Run-history corpus (dampr_tpu.obs.history): every finalized run
#: appends one compact summary record (plan fingerprint + stage shapes,
#: per-stage IO, critical-path verdicts, per-op profile, throughput,
#: settings snapshot) to ``<scratch_root>/<run>/history.jsonl``, bounded
#: to this many entries (oldest rewritten away past it).  The corpus
#: feeds ``plan/cost.py`` adaptation (median over matching runs instead
#: of one stats.json) and ``dampr-tpu-doctor --diff``.  0 disables
#: corpus writes entirely.
history_entries = int(os.environ.get("DAMPR_TPU_HISTORY_ENTRIES", "64"))

#: Recency bound for corpus-driven adaptation: only the most recent this-
#: many shape-matching records feed the per-stage medians (old runs under
#: different data volumes should age out of the estimate).
history_window = int(os.environ.get("DAMPR_TPU_HISTORY_WINDOW", "8"))

#: Live metrics plane (dampr_tpu.obs.metrics): sampling cadence in
#: milliseconds for the background gauge sampler.  0 (the default)
#: disables the metrics registry entirely — every instrumentation site
#: is one module-global None-check, no sampler thread is spawned, same
#: contract as ``trace``.  >0 starts a run-scoped registry + sampler:
#: gauges (budget occupancy, writer-pool queue depth, overlap windows,
#: HBM residency, records/bytes throughput) snapshot on this cadence
#: into an in-memory time series that lands in the Perfetto trace as
#: counter tracks, feeds the live progress reporter, and rides the
#: flight recorder into ``crashdump.json`` on failure.  Traced runs
#: (``trace=True``) sample at 100 ms even when this is 0, so a killed
#: traced run always leaves a crash timeline with recent samples.
metrics_interval_ms = int(os.environ.get("DAMPR_TPU_METRICS_MS", "0"))


def effective_metrics_interval_ms():
    """The sampling cadence actually in force: the explicit setting, or
    the 100 ms traced-run default (a traced run's crashdump must carry
    recent gauge samples), or 0 = metrics plane off.  A live metrics
    endpoint (``metrics_port``) also implies sampling — a scraper
    polling ``/metrics`` must see moving gauges, not a dead registry."""
    if metrics_interval_ms > 0:
        return metrics_interval_ms
    if trace or progress or metrics_port > 0:
        return 100
    return 0


#: Live metrics endpoint (dampr_tpu.obs.serve): when > 0 every run
#: starts a stdlib-only HTTP thread on this port exposing ``/metrics``
#: (Prometheus text exposition of the live registry, rank-labeled) and
#: ``/healthz``.  Multi-process deployments bind ``metrics_port +
#: process_id`` per rank so co-located ranks never collide.  0 (the
#: default) serves nothing — the run pays only the usual metrics-plane
#: cost, and with the plane off too, nothing at all.
metrics_port = int(os.environ.get("DAMPR_TPU_METRICS_PORT", "0"))

#: How long (milliseconds) rank 0 waits at finalize for its sibling
#: ranks' per-rank trace/stats artifacts before building the merged
#: fleet timeline (dampr_tpu.obs.fleet).  Ranks in a collective pipeline
#: finish near-lockstep, so the wait is normally milliseconds; a killed
#: sibling must not wedge the survivor, so past the deadline rank 0
#: merges what landed and records the missing ranks.  0 disables the
#: finalize-time merge entirely (``dampr-tpu-stats --fleet`` still
#: merges post-hoc).
fleet_wait_ms = int(os.environ.get("DAMPR_TPU_FLEET_WAIT_MS", "10000"))


#: Live in-run progress reporter (dampr_tpu.obs.progress): when True,
#: runs print a single updating console line per stage — records/s,
#: MB/s, spill backlog, ETA — to stderr on ``progress_interval_ms``
#: cadence.  Implies the metrics plane (the reporter reads its gauges),
#: so a progress-enabled run samples even with metrics_interval_ms=0.
progress = os.environ.get("DAMPR_TPU_PROGRESS", "0").lower() not in (
    "0", "false", "no", "off", "")

#: Progress reporter refresh cadence (milliseconds).
progress_interval_ms = int(os.environ.get("DAMPR_TPU_PROGRESS_MS", "500"))

#: Flight recorder ring capacity (events): the bounded tail of recent
#: spans + metric samples flushed to ``crashdump.json`` when a run dies
#: (see dampr_tpu.obs.flightrec).  Bounds the crash artifact regardless
#: of run size.  0 disables the recorder.
flight_recorder_events = int(os.environ.get(
    "DAMPR_TPU_FLIGHTREC_EVENTS", "1024"))

#: Cap on retained samples per time series (oldest samples drop past it;
#: the registry counts drops so ``stats()`` reports them).
metrics_series_cap = int(os.environ.get(
    "DAMPR_TPU_METRICS_SERIES_CAP", "4096"))

#: Structured log stream (dampr_tpu.obs.log): minimum level persisted to
#: the run-scoped ``<run>/trace/events.jsonl`` event log — one of
#: ``debug`` / ``info`` / ``warn`` / ``error``.  Empty (the default)
#: writes no event file; traced runs still stream at ``info`` (see
#: :func:`effective_log_level`) so every traced artifact set carries its
#: event tail.  With the stream inactive every emit site is one
#: module-global None-check (same contract as ``trace``/``profile``);
#: WARN+ events always reach the stdlib logger regardless.
log_level = os.environ.get("DAMPR_TPU_LOG", "").strip().lower()

#: Bound on the structured event log: past this many lines
#: ``events.jsonl`` is compacted to the newest entries (tmp + atomic
#: rename, the ``history.jsonl`` durability contract).  0 disables the
#: on-disk stream entirely (WARN+ still mirrors into the flight
#: recorder ring).
log_events_max = int(os.environ.get("DAMPR_TPU_LOG_EVENTS_MAX", "4096"))


def effective_log_level():
    """The structured-log level actually in force: the explicit
    ``log_level``, or ``info`` for traced runs (a traced artifact set
    should include its event tail), or "" = no on-disk event stream."""
    if log_level:
        return log_level
    if trace:
        return "info"
    return ""


#: Regression sentry (dampr_tpu.obs.sentry): trailing-window size for
#: the MAD anomaly check over the per-fingerprint telemetry series —
#: the newest point is judged against up to this many prior points of
#: the same plan fingerprint (at least 3 required).  0 disables the
#: finalize-time sentry check entirely (``dampr-tpu-sentry`` still
#: works post-hoc with an explicit ``--window``).
sentry_window = int(os.environ.get("DAMPR_TPU_SENTRY_WINDOW", "8"))

#: Robust z-score threshold for the sentry: a metric whose deviation
#: from the baseline window's median exceeds this many scaled MADs (in
#: the metric's bad direction) is flagged as a regression.
sentry_mad_threshold = float(os.environ.get("DAMPR_TPU_SENTRY_MAD", "3.5"))

#: Live fleet dashboard (dampr_tpu.obs.top / ``dampr-tpu-top``): refresh
#: cadence in milliseconds between endpoint polls.
top_refresh_ms = int(os.environ.get("DAMPR_TPU_TOP_REFRESH_MS", "1000"))

# ---------------------------------------------------------------------------
# Pipeline service daemon (dampr_tpu.serve / ``dampr-tpu-serve``)
# ---------------------------------------------------------------------------

#: Daemon HTTP port (``dampr-tpu-serve``).  A busy port probes upward
#: (same degradation contract as the metrics endpoint); 0 asks the OS
#: for an ephemeral port (tests).
serve_port = int(os.environ.get("DAMPR_TPU_SERVE_PORT", "9400"))

#: Daemon bind address.  Loopback by default: the wire is pickle, so
#: the protocol is trusted-client (docs/serve.md) — exposing it wider
#: is an explicit operator decision.
serve_host = os.environ.get("DAMPR_TPU_SERVE_HOST", "127.0.0.1")

#: Concurrent job slots: how many per-job worker subprocesses the
#: daemon runs at once.  Queued jobs dispatch deficit-round-robin
#: across tenants as slots free.
serve_workers = int(os.environ.get("DAMPR_TPU_SERVE_WORKERS", "2"))

#: Per-tenant admission byte budget: the sum of estimated input bytes a
#: tenant's queued + running jobs may reserve.  A submission past it is
#: rejected with the coded ``serve-reject`` event (reason ``budget``)
#: instead of queueing unboundedly; a finished or cancelled job
#: releases its reservation immediately.
serve_tenant_budget = int(os.environ.get("DAMPR_TPU_SERVE_BUDGET",
                                         str(2 * 1024 ** 3)))

#: Deficit-round-robin quantum (bytes): the byte allowance each tenant's
#: deficit counter earns per scheduling round.  Smaller = finer-grained
#: fairness between tenants with very different job sizes.
serve_quantum = int(os.environ.get("DAMPR_TPU_SERVE_QUANTUM",
                                   str(64 * 1024 ** 2)))

#: Per-tenant queue depth: submissions past this many queued jobs are
#: rejected (reason ``queue-full``) — backpressure at the door, not an
#: unbounded queue.
serve_queue_depth = int(os.environ.get("DAMPR_TPU_SERVE_QUEUE_DEPTH", "16"))

#: Per-job wall-clock timeout (milliseconds): past it the daemon
#: SIGTERMs the job's worker (which walks the crashdump path, so the
#: tenant still gets a schema-valid artifact), then SIGKILLs a
#: straggler.  0 = no timeout.  A client may pass a tighter per-job
#: ``timeout_s`` at submit.
serve_job_timeout_ms = int(os.environ.get("DAMPR_TPU_SERVE_JOB_TIMEOUT_MS",
                                          "600000"))

#: Graceful-drain deadline (milliseconds): on SIGTERM (or POST /drain)
#: the daemon stops admitting, finishes everything already admitted,
#: and terminates whatever is still running when this deadline fires.
serve_drain_ms = int(os.environ.get("DAMPR_TPU_SERVE_DRAIN_MS", "30000"))

#: Whether serve workers run traced (DAMPR_TPU_TRACE=1 in the job
#: environment).  On (default) so a killed or crashed tenant job always
#: leaves a schema-valid ``crashdump.json`` under its job directory —
#: the isolation contract's evidence trail.  Turn off only to shave the
#: trace plane's overhead from high-rate serving.
serve_trace = os.environ.get("DAMPR_TPU_SERVE_TRACE", "1").lower() not in (
    "0", "false", "no", "off", "")

#: How many terminal job records (and their job directories) the daemon
#: retains; older ones are evicted with a coded ``serve-evict`` event.
serve_jobs_keep = int(os.environ.get("DAMPR_TPU_SERVE_JOBS_KEEP", "256"))

#: Daemon state directory (job payloads, results, event log).  Empty
#: (default) resolves to ``<scratch_root>/serve`` at daemon start.
serve_dir = os.environ.get("DAMPR_TPU_SERVE_DIR", "")

#: Set (to 1) by the daemon in every worker's environment — this is how
#: ``reuse_enabled()`` resolves the "auto" reuse mode ON inside served
#: jobs and OFF everywhere else.  Not an operator knob.
serve_active = os.environ.get("DAMPR_TPU_SERVE_ACTIVE", "0").lower() not in (
    "0", "false", "no", "off", "")

#: Partition-size threshold (bytes) above which a single-input reduce streams
#: a k-way merge over hash-sorted runs instead of materializing the partition
#: (groups then arrive in hash order, not key order).  None = use
#: max_memory_per_stage.
streaming_reduce_threshold = None
