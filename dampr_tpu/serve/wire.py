"""Plan wire-form: how a composed pipeline travels to the daemon.

The engine runs UDFs on threads, so nothing in a normal run ever
pickles a function — pipelines are full of lambdas and closures, which
plain pickle rejects.  Shipping a plan to the service therefore needs
the ``analyze.pickleprobe`` exemption ("plain functions ship by code")
made real: :class:`_PlanPickler` serializes every plain Python function
*by value* — marshalled code object, closure cell contents, defaults,
and the subset of module globals the code references (recursively, so
a lambda calling a module-level helper carries the helper along).
Everything else (captured arrays, configs, taps) must pickle normally;
a capture that cannot is exactly the ``DTA401`` diagnostic, and the
admission gate rejects it with that code instead of crashing a worker.

Deliberate limits, documented in docs/serve.md:

- client and server must run the same Python minor version (marshal
  bytecode is version-specific); :func:`decode` checks and refuses
  mismatches with a :class:`WireError` rather than crashing later;
- classes defined in unimportable modules (``__main__``, a test file)
  cannot ship — pickle's by-reference class lookup fails server-side
  and the submission is rejected at the door;
- the wire is pickle: the daemon executes what clients send.  This is
  a *trusted-client* protocol (the daemon binds loopback by default).

Fingerprints reuse :mod:`dampr_tpu.resume` verbatim: the submission
fingerprint is the chained stage fingerprint of the requested output,
so two clients composing the same logical plan over the same input
files produce the same fingerprint — the scheduler's coalesce key and
the reuse cache's shared-prefix key agree by construction.
"""

import glob
import importlib
import io
import marshal
import os
import pickle
import sys
import types

WIRE_VERSION = 1


class WireError(ValueError):
    """A submission that cannot travel: version/python mismatch, an
    unserializable capture, or a malformed envelope."""


# -- by-value function serialization -----------------------------------------

def _collect_names(code, out):
    """Every global name the code object (or a nested one) references."""
    out.update(code.co_names)
    for const in code.co_consts:
        if isinstance(const, types.CodeType):
            _collect_names(const, out)


def _fn_skeleton(code_bytes, n_cells):
    """Rebuild an empty function shell first so reference cycles through
    ``__globals__`` (recursive lambdas, mutually-recursive helpers) can
    memoize it before its state pickles."""
    code = marshal.loads(code_bytes)
    cells = tuple(types.CellType() for _ in range(n_cells))
    return types.FunctionType(code, {}, code.co_name, None, cells or None)


def _fn_setstate(fn, state):
    import builtins

    fn.__globals__.update(state["globals"])
    fn.__globals__.setdefault("__builtins__", builtins)
    if state["defaults"] is not None:
        fn.__defaults__ = tuple(state["defaults"])
    if state["kwdefaults"]:
        fn.__kwdefaults__ = dict(state["kwdefaults"])
    fn.__name__ = state["name"]
    fn.__qualname__ = state["qualname"]
    fn.__module__ = state["module"]
    if state["dict"]:
        fn.__dict__.update(state["dict"])
    for cell, boxed in zip(fn.__closure__ or (), state["cells"]):
        if boxed is not None:
            cell.cell_contents = boxed[0]
    return fn


#: Top-level packages whose functions travel **by reference** (normal
#: pickle): they are importable server-side by construction — the
#: engine itself, the stdlib, and the numeric stack the engine already
#: requires.  Everything else (client scripts, ``__main__``, test
#: modules, notebooks) ships by value: the daemon's worker cannot be
#: assumed to import it.  Without this split, serializing ONE lambda
#: that references an engine helper would chase the engine's entire
#: module-level function graph by value (and blow the recursion limit).
_BY_REF_PACKAGES = set(getattr(sys, "stdlib_module_names", ())) | {
    "dampr_tpu", "numpy", "jax", "jaxlib"}


def _ships_by_reference(fn):
    module = getattr(fn, "__module__", None)
    qualname = getattr(fn, "__qualname__", None)
    if not module or not qualname or "<" in qualname:
        return False  # lambdas, <locals>, dynamically-built functions
    if module.split(".")[0] not in _BY_REF_PACKAGES:
        return False
    mod = sys.modules.get(module)
    obj = mod
    for part in qualname.split("."):
        obj = getattr(obj, part, None)
    return obj is fn


class _PlanPickler(pickle.Pickler):
    """Pickler that ships plain functions by code and modules by name."""

    def reducer_override(self, obj):
        if isinstance(obj, types.FunctionType):
            if _ships_by_reference(obj):
                return NotImplemented
            return self._reduce_function(obj)
        if isinstance(obj, types.ModuleType):
            return (importlib.import_module, (obj.__name__,))
        return NotImplemented

    def _reduce_function(self, fn):
        code = fn.__code__
        cells = []
        for cell in fn.__closure__ or ():
            try:
                cells.append((cell.cell_contents,))
            except ValueError:  # genuinely empty cell
                cells.append(None)
        names = set()
        _collect_names(code, names)
        globs = {}
        for name in sorted(names):
            if name in fn.__globals__:
                globs[name] = fn.__globals__[name]
        state = {
            "globals": globs,
            "defaults": fn.__defaults__,
            "kwdefaults": fn.__kwdefaults__,
            "name": fn.__name__,
            "qualname": fn.__qualname__,
            "module": getattr(fn, "__module__", None) or "dampr_tpu.wire",
            "dict": fn.__dict__ or None,
            "cells": cells,
        }
        return (_fn_skeleton,
                (marshal.dumps(code), len(cells)),
                state, None, None, _fn_setstate)


# -- envelope ----------------------------------------------------------------

def encode(graph, source):
    """Serialize ``(graph, output source)`` to wire bytes.  Raises
    :class:`WireError` naming the offending capture when something in
    the plan cannot travel."""
    buf = io.BytesIO()
    pickler = _PlanPickler(buf, protocol=pickle.HIGHEST_PROTOCOL)
    try:
        pickler.dump({
            "wire": WIRE_VERSION,
            "py": list(sys.version_info[:2]),
            "graph": graph,
            "source": source,
        })
    except WireError:
        raise
    except Exception as e:
        raise WireError(
            "plan cannot be serialized for submission: {}: {}".format(
                type(e).__name__, e))
    return buf.getvalue()


def decode(data):
    """Wire bytes -> ``(graph, source)``.  Raises :class:`WireError` on
    a malformed envelope or a client/server version mismatch."""
    try:
        env = pickle.loads(data)
    except Exception as e:
        raise WireError(
            "submission payload does not decode: {}: {}".format(
                type(e).__name__, e))
    if not isinstance(env, dict) or env.get("wire") != WIRE_VERSION:
        raise WireError("unsupported wire version: {!r}".format(
            env.get("wire") if isinstance(env, dict) else None))
    py = tuple(env.get("py") or ())
    if py != sys.version_info[:2]:
        raise WireError(
            "python version mismatch: client {} vs server {}.{} "
            "(marshalled code is version-specific)".format(
                ".".join(str(v) for v in py), *sys.version_info[:2]))
    return env["graph"], env["source"]


# -- submission fingerprint --------------------------------------------------

def plan_fingerprint(graph, source):
    """The submission fingerprint: the chained stage fingerprint of the
    requested output (``resume.stage_fingerprints``), or the salted tap
    fingerprint when the output IS an input tap.  Volatile fingerprints
    (unfingerprintable captures) never coalesce — check with
    :func:`dampr_tpu.resume.is_volatile`."""
    from .. import resume
    from ..graph import GInput

    fps = resume.stage_fingerprints(graph)
    for sid, stage in enumerate(graph.stages):
        if stage.output == source:
            if sid in fps:
                return fps[sid]
            if isinstance(stage, GInput):
                return resume._h("tap-salted", "", resume._fp_tap(stage.tap))
    return resume._volatile()


def is_volatile(fp):
    from .. import resume

    return resume.is_volatile(fp)


# -- admission cost estimate -------------------------------------------------

def estimate_input_bytes(graph, default=1 << 20):
    """Rough input volume of a plan — what the scheduler reserves
    against the tenant's byte budget.  Path taps stat their files
    (mirroring ``resume._fp_tap``'s file discovery); memory taps charge
    a flat per-record figure; anything opaque charges ``default``.
    Deliberately cheap and conservative: admission control needs a
    consistent ordering of job sizes, not an exact byte count."""
    from ..graph import GInput

    total = 0
    for stage in graph.stages:
        if not isinstance(stage, GInput):
            continue
        tap = stage.tap
        path = getattr(tap, "path", None)
        if isinstance(path, str):
            files = [p for p in glob.glob(path) or [path]
                     if os.path.isfile(p)]
            if not files and os.path.isdir(path):
                files = [os.path.join(d, f)
                         for d, _dirs, fs in os.walk(path) for f in fs]
            try:
                total += sum(os.path.getsize(p) for p in files)
            except OSError:
                total += default
            continue
        items = getattr(tap, "items", None)
        if items is not None:
            try:
                total += max(1, len(items)) * 128
            except TypeError:
                total += default
            continue
        total += default
    return max(total, 1)
