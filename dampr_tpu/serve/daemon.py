"""The pipeline service daemon: HTTP plane + scheduler + job lifecycle.

One :class:`ServeDaemon` owns four things:

- the **HTTP endpoints** (stdlib ``ThreadingHTTPServer``, same shape as
  ``obs/serve.py``): ``POST /submit`` (wire envelope in, job id out),
  ``GET /jobs[/<id>]`` (the job table), ``GET /result/<id>`` (the
  worker's result bytes, streamed verbatim), ``POST /cancel/<id>``,
  ``POST /drain``, plus the telemetry pair ``GET /metrics`` (Prometheus
  text, per-tenant labels) and ``GET /healthz``;
- the **admission gate**: decode (:class:`~.wire.WireError` -> coded
  reject), ``analyze.validate`` pre-flight with multi-process promotion
  (an unpicklable capture is ``DTA401`` *error* here — it is about to
  cross a process boundary), fingerprinting, in-flight coalesce, and
  the scheduler's budget/queue-depth charge;
- the **dispatch loop**: a worker-slot pump draining the deficit-
  round-robin scheduler into per-job subprocesses (:mod:`.worker`),
  each watched by a waiter thread that enforces the per-job timeout
  (SIGTERM first — the child's crashdump path — then SIGKILL);
- the **drain protocol**: ``drain()`` (wired to SIGTERM by ``main``)
  stops admitting with a coded event, finishes everything already
  admitted, and terminates stragglers at the deadline.

Every lifecycle transition emits a coded structured event
(``serve-submit/admit/reject/coalesce/evict/drain`` — registered in
``obs.log.EVENT_CODES``, enforced by the repo self-lint) into the
daemon's own ``events.jsonl``, and each finished job appends a
per-tenant telemetry point (run ``serve-<tenant>``) so the regression
sentry trends served tenants like any other run series.
"""

import base64
import collections
import json
import logging
import os
import shutil
import subprocess
import sys
import threading
import time

from .. import settings
from ..obs import log as _obslog
from ..obs import timeseries as _timeseries
from ..obs.serve import METRICS_CONTENT_TYPE
from . import scheduler as _scheduler
from . import wire as _wire

log = logging.getLogger("dampr_tpu.serve")


def _state_dir():
    return settings.serve_dir or os.path.join(settings.scratch_root, "serve")


class ServeDaemon(object):
    def __init__(self, port=None, host=None, workers=None,
                 tenant_budget=None, quantum=None, queue_depth=None,
                 state_dir=None, name="serve"):
        self.name = name
        self.host = settings.serve_host if host is None else host
        self.base_port = settings.serve_port if port is None else int(port)
        self.port = None
        self.workers = max(1, settings.serve_workers if workers is None
                           else int(workers))
        self.state_dir = state_dir or _state_dir()
        self.sched = _scheduler.Scheduler(
            settings.serve_tenant_budget if tenant_budget is None
            else tenant_budget,
            settings.serve_quantum if quantum is None else quantum,
            settings.serve_queue_depth if queue_depth is None
            else queue_depth)
        self.jobs = collections.OrderedDict()
        self.draining = False
        self.started_at = time.time()
        self.counters = collections.Counter()
        self._seq = 0
        self._running = {}      # job id -> subprocess.Popen
        self._lock = threading.RLock()
        self._wake = threading.Condition(self._lock)
        self._stopped = False
        self._httpd = None
        self._http_thread = None
        self._dispatcher = None
        self._waiters = []
        os.makedirs(os.path.join(self.state_dir, "jobs"), exist_ok=True)
        # The daemon's own structured event stream (NOT the run-scoped
        # module-global one: served runs start/stop their own streams in
        # worker processes, and tests host several daemons in-process).
        self._stream = _obslog.LogStream(
            "serve", level="info",
            path=os.path.join(self.state_dir, "events.jsonl"))

    # -- coded events --------------------------------------------------------
    def emit(self, level, code, msg, **data):
        self.counters[code] += 1
        try:
            self._stream.emit(level, code, msg, data=data or None)
        except Exception:
            pass
        (log.warning if level in ("warn", "error") else log.info)(
            "%s: %s", code, msg)

    # -- submission ----------------------------------------------------------
    def submit(self, request):
        """One submission request (the parsed /submit JSON body) ->
        ``(http_status, response_dict)``."""
        tenant = str(request.get("tenant") or "default")
        self.emit("info", "serve-submit",
                    "submission from tenant {!r}".format(tenant),
                    tenant=tenant)
        try:
            payload = base64.b64decode(request["plan"])
        except Exception:
            return self._reject(tenant, "wire", 400,
                                "submission carries no decodable plan")
        try:
            graph, source = _wire.decode(payload)
        except _wire.WireError as e:
            return self._reject(tenant, "wire", 400, str(e))

        # Pre-flight admission gate: the submission is about to cross a
        # process boundary, so unpicklable captures are errors (DTA401),
        # exactly as validate's num_processes>1 promotion defines.  The
        # jax-traceability probe is advisory-only and expensive — skip.
        from ..analyze import validate as _validate

        try:
            diags = _validate.validate_graph(
                graph, num_processes=2, probe_traceable=False,
                probe_assoc=True, probe_pickle=True)
        except Exception as e:
            return self._reject(tenant, "invalid", 422,
                                "pre-flight validation crashed: {}".format(e))
        errors = [d for d in diags if d.severity == "error"]
        if errors:
            return self._reject(
                tenant, "invalid", 422,
                "; ".join("{}: {}".format(d.code, d.message)
                          for d in errors),
                diagnostics=[d.to_dict() for d in errors])

        fingerprint = _wire.plan_fingerprint(graph, source)
        volatile = _wire.is_volatile(fingerprint)
        cost = _wire.estimate_input_bytes(graph)
        options = {
            "reuse": str(request.get("reuse") or "auto"),
            "timeout_s": request.get("timeout_s"),
            "label": request.get("label"),
        }
        with self._wake:
            if self.draining or self._stopped:
                return self._reject(tenant, "draining", 503,
                                    "daemon is draining; not accepting "
                                    "new submissions")
            self._seq += 1
            job = _scheduler.Job("j%04d" % self._seq, tenant, fingerprint,
                                 cost, payload=payload, options=options)
            job.diagnostics = [d.to_dict() for d in diags]
            primary = None
            if not volatile and options["reuse"] != "off":
                primary = self.sched.coalesce_target(fingerprint)
            if primary is not None:
                self.sched.attach_follower(primary, job)
                self.jobs[job.id] = job
                self.emit(
                    "info", "serve-coalesce",
                    "job {} (tenant {!r}) coalesced onto in-flight {} — "
                    "identical fingerprint {}".format(
                        job.id, tenant, primary.id, fingerprint[:16]),
                    job=job.id, tenant=tenant, primary=primary.id,
                    fingerprint=fingerprint[:16])
                return 200, {"job": job.id, "state": job.state,
                             "primary": primary.id,
                             "fingerprint": fingerprint}
            try:
                self.sched.admit(job)
            except _scheduler.AdmissionError as e:
                return self._reject(tenant, e.reason, 429, str(e))
            self.jobs[job.id] = job
            self.emit(
                "info", "serve-admit",
                "job {} admitted for tenant {!r}: {} byte(s) reserved, "
                "fingerprint {}".format(job.id, tenant, cost,
                                        fingerprint[:16]),
                job=job.id, tenant=tenant, cost_bytes=cost,
                fingerprint=fingerprint[:16])
            self._wake.notify_all()
        return 200, {"job": job.id, "state": job.state, "primary": None,
                     "fingerprint": fingerprint}

    def _reject(self, tenant, reason, status, message, diagnostics=None):
        self.sched.tenant(tenant).counts["rejected"] += 1
        self.emit("warn", "serve-reject",
                    "submission from tenant {!r} rejected ({}): {}".format(
                        tenant, reason, message),
                    tenant=tenant, reason=reason)
        doc = {"error": message, "reason": reason}
        if diagnostics:
            doc["diagnostics"] = diagnostics
        return status, doc

    # -- cancellation --------------------------------------------------------
    def cancel(self, job_id):
        with self._wake:
            job = self.jobs.get(job_id)
            if job is None:
                return 404, {"error": "no such job", "reason": "unknown"}
            if job.state in _scheduler.TERMINAL:
                return 200, {"job": job.id, "state": job.state}
            job.cancel_requested = True
            if job.state == "queued" and self.sched.remove_queued(job):
                job.state = "cancelled"
                job.finished_at = time.time()
                # The whole point of reservation-until-terminal: a
                # cancelled job's bytes return to the tenant NOW.
                self.sched.release(job)
                self._wake.notify_all()
            elif job.state == "running":
                proc = self._running.get(job.id)
                if proc is not None:
                    try:
                        proc.terminate()  # SIGTERM -> child crashdump path
                    except OSError:
                        pass
            elif job.state == "coalesced":
                # The primary keeps running — its other clients still
                # want the result; only this follower is abandoned.
                job.state = "cancelled"
                job.finished_at = time.time()
            return 200, {"job": job.id, "state": job.state}

    # -- dispatch ------------------------------------------------------------
    def _dispatch_loop(self):
        while True:
            with self._wake:
                while not self._stopped:
                    if len(self._running) < self.workers:
                        job = self.sched.next_job()
                        if job is not None:
                            break
                    self._wake.wait(timeout=0.5)
                else:
                    return
                self._spawn(job)

    def _spawn(self, job):
        job_dir = os.path.join(self.state_dir, "jobs", job.id)
        os.makedirs(job_dir, exist_ok=True)
        job.job_dir = job_dir
        job.run_name = "serve-{}-{}".format(job.tenant, job.id)
        with open(os.path.join(job_dir, "payload.bin"), "wb") as f:
            f.write(job.payload)
        job.payload = None  # the file is the source of truth now
        with open(os.path.join(job_dir, "job.json"), "w") as f:
            json.dump({"run_name": job.run_name, "tenant": job.tenant,
                       "resume": "auto", "options": job.options}, f)

        env = dict(os.environ)
        # The worker inherits the daemon's *live* settings, not just its
        # env: tests repoint scratch_root at runtime.
        env["DAMPR_TPU_SERVE_ACTIVE"] = "1"   # resolves reuse "auto" ON
        env["DAMPR_TPU_SCRATCH"] = settings.scratch_root
        env["DAMPR_TPU_TRACE"] = "1" if settings.serve_trace else "0"
        env["DAMPR_TPU_TRACE_DIR"] = os.path.join(job_dir, "trace")
        if settings.reuse_dir:
            env["DAMPR_TPU_REUSE_DIR"] = settings.reuse_dir
        if job.options.get("reuse") == "off":
            env["DAMPR_TPU_REUSE"] = "0"
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")

        child_log = open(os.path.join(job_dir, "child.log"), "wb")
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "dampr_tpu.serve.worker", job_dir],
                env=env, stdout=child_log, stderr=subprocess.STDOUT)
        except OSError as e:
            child_log.close()
            job.state = "failed"
            job.error = "worker spawn failed: {}".format(e)
            job.finished_at = time.time()
            self._finish(job)
            return
        child_log.close()
        job.state = "running"
        job.started_at = time.time()
        self._running[job.id] = proc
        waiter = threading.Thread(
            target=self._wait_for, args=(job, proc),
            name="dampr-tpu-serve-wait-{}".format(job.id), daemon=True)
        self._waiters.append(waiter)
        waiter.start()

    def _wait_for(self, job, proc):
        timeout = job.options.get("timeout_s")
        if not timeout:
            ms = settings.serve_job_timeout_ms
            timeout = (ms / 1000.0) if ms > 0 else None
        timed_out = False
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            timed_out = True
            try:
                proc.terminate()  # SIGTERM: schema-valid crashdump, 143
                proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        self._reap(job, proc, timed_out)

    def _reap(self, job, proc, timed_out):
        meta, error = {}, None
        try:
            with open(os.path.join(job.job_dir, "result.json")) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            pass
        try:
            with open(os.path.join(job.job_dir, "error.json")) as f:
                error = json.load(f)
        except (OSError, ValueError):
            pass
        dump = os.path.join(job.job_dir, "trace", job.run_name, "trace",
                            "crashdump.json")
        with self._wake:
            self._running.pop(job.id, None)
            job.exit_code = proc.returncode
            job.finished_at = time.time()
            job.result_meta = meta
            if os.path.isfile(dump):
                job.crashdump = dump
            result_ok = (proc.returncode == 0 and os.path.isfile(
                os.path.join(job.job_dir, "result.pkl")))
            if job.cancel_requested and not result_ok:
                job.state = "cancelled"
                job.error = "cancelled by client"
            elif timed_out:
                job.state = "failed"
                job.error = "killed: exceeded job timeout"
            elif result_ok:
                job.state = "done"
            else:
                job.state = "failed"
                job.error = ((error or {}).get("message")
                             or "worker exited {}".format(proc.returncode))
            self._finish(job)
            self._wake.notify_all()

    def _finish(self, job):
        """Terminal bookkeeping (lock held): release the reservation,
        resolve followers, emit telemetry, prune old records."""
        self.sched.release(job)
        for fid in job.followers:
            follower = self.jobs.get(fid)
            if follower is not None and follower.state == "coalesced":
                follower.state = job.state
                follower.finished_at = job.finished_at
                follower.error = job.error
                follower.result_meta = job.result_meta
        # Per-tenant sentry point: served tenants trend like any run
        # series (run name serve-<tenant>, keyed by plan fingerprint).
        wall = (job.result_meta or {}).get("wall_seconds")
        if job.state == "done" and isinstance(wall, (int, float)):
            point = {"schema": _timeseries.SCHEMA,
                     "run": "serve-" + job.tenant, "ts": time.time(),
                     "fingerprint": (job.fingerprint or "")[:32],
                     "wall_seconds": round(float(wall), 6)}
            hits = ((job.result_meta.get("reuse") or {}).get("hits"))
            if isinstance(hits, int):
                point["reuse_hit_rate"] = float(min(1, hits))
            _timeseries.append_point(point)
        self._prune()

    def _prune(self):
        keep = max(1, settings.serve_jobs_keep)
        terminal = [j for j in self.jobs.values()
                    if j.state in _scheduler.TERMINAL]
        excess = len(terminal) - keep
        if excess <= 0:
            return
        evicted = []
        for job in terminal[:excess]:
            del self.jobs[job.id]
            evicted.append(job.id)
            if job.job_dir:
                shutil.rmtree(job.job_dir, ignore_errors=True)
        self.emit(
            "info", "serve-evict",
            "evicted {} retired job record(s) past the retention bound "
            "({} kept): {}".format(len(evicted), keep,
                                   ", ".join(evicted)),
            evicted=len(evicted), keep=keep)

    # -- drain / lifecycle ---------------------------------------------------
    def drain(self, timeout_s=None):
        """Stop admitting, finish everything already admitted, terminate
        stragglers at the deadline.  Returns the number of jobs still
        running when the deadline fired (0 = clean drain)."""
        with self._wake:
            already = self.draining
            self.draining = True
        if not already:
            self.emit(
                "warn", "serve-drain",
                "drain initiated: finishing admitted jobs, rejecting new "
                "submissions", inflight=len(self._running))
        if timeout_s is None:
            timeout_s = settings.serve_drain_ms / 1000.0
        deadline = time.time() + timeout_s
        with self._wake:
            while time.time() < deadline:
                busy = len(self._running) + sum(
                    1 for j in self.jobs.values() if j.state == "queued")
                if not busy:
                    break
                self._wake.wait(timeout=min(0.5, max(
                    0.01, deadline - time.time())))
            stragglers = list(self._running.values())
        for proc in stragglers:
            try:
                proc.terminate()
            except OSError:
                pass
        return len(stragglers)

    def start(self):
        """Bind the HTTP plane and start the dispatcher.  Returns self,
        or None when every bind candidate is taken (mirrors
        ``obs.serve``: a busy port degrades, never crashes)."""
        import http.server

        handler = self._make_handler()
        candidates = [self.base_port]
        if self.base_port > 0:
            candidates += list(range(self.base_port + 1,
                                     self.base_port + 17))
        err = None
        for port in candidates:
            try:
                self._httpd = http.server.ThreadingHTTPServer(
                    (self.host, port), handler)
                break
            except OSError as e:
                err = e
        if self._httpd is None:
            log.error("serve daemon bind failed on port %d (+%d probes): "
                      "%s", self.base_port, len(candidates) - 1, err)
            return None
        self.port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dampr-tpu-serve-http")
        self._http_thread.start()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name="dampr-tpu-serve-dispatch")
        self._dispatcher.start()
        log.info("serve daemon up on %s:%d (%d worker slot(s), state %s)",
                 self.host, self.port, self.workers, self.state_dir)
        return self

    def stop(self):
        with self._wake:
            self._stopped = True
            self.draining = True
            self._wake.notify_all()
        for proc in list(self._running.values()):
            try:
                proc.terminate()
            except OSError:
                pass
        for waiter in self._waiters:
            waiter.join(timeout=30)
        if self._dispatcher is not None:
            self._dispatcher.join(timeout=5)
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            try:
                httpd.shutdown()
                httpd.server_close()
            except Exception:
                log.debug("serve daemon shutdown failed", exc_info=True)
        if self._http_thread is not None:
            self._http_thread.join(timeout=2)

    # -- telemetry plane -----------------------------------------------------
    def jobs_doc(self):
        with self._lock:
            rows = [j.to_row() for j in self.jobs.values()]
            tenants = self.sched.stats()
        return {"schema": "dampr-tpu-serve-jobs/1", "daemon": self.name,
                "draining": self.draining,
                "uptime_s": round(time.time() - self.started_at, 3),
                "workers": self.workers, "jobs": rows, "tenants": tenants}

    def health(self):
        with self._lock:
            states = collections.Counter(
                j.state for j in self.jobs.values())
            return {"status": "draining" if self.draining else "ok",
                    "role": "serve", "daemon": self.name,
                    "uptime_s": round(time.time() - self.started_at, 3),
                    "workers": self.workers,
                    "running": len(self._running),
                    "jobs": dict(states)}

    def metrics_text(self):
        from ..obs.promtext import escape_label_value as esc

        lines = ["# Serve daemon exposition (dampr_tpu.serve)"]
        with self._lock:
            states = collections.Counter()
            reuse_hits = collections.Counter()
            for j in self.jobs.values():
                states[(j.tenant, j.state)] += 1
                hits = (j.result_meta or {}).get("reuse") or {}
                if isinstance(hits.get("hits"), int):
                    reuse_hits[j.tenant] += hits["hits"]
            for (tenant, state), n in sorted(states.items()):
                lines.append(
                    'dampr_tpu_serve_jobs{{tenant="{}",state="{}"}} {}'
                    .format(esc(tenant), esc(state), n))
            for tenant, stats in sorted(self.sched.stats().items()):
                t = esc(tenant)
                lines.append(
                    'dampr_tpu_serve_queue_depth{tenant="%s"} %d'
                    % (t, stats["queued"]))
                lines.append(
                    'dampr_tpu_serve_reserved_bytes{tenant="%s"} %d'
                    % (t, stats["reserved_bytes"]))
                lines.append(
                    'dampr_tpu_serve_budget_bytes{tenant="%s"} %d'
                    % (t, stats["budget_bytes"]))
            for tenant, hits in sorted(reuse_hits.items()):
                lines.append(
                    'dampr_tpu_serve_reuse_hits_total{tenant="%s"} %d'
                    % (esc(tenant), hits))
            for code in ("serve-submit", "serve-admit", "serve-reject",
                         "serve-coalesce", "serve-evict", "serve-drain"):
                lines.append(
                    'dampr_tpu_serve_events_total{code="%s"} %d'
                    % (esc(code), self.counters.get(code, 0)))
            lines.append("dampr_tpu_serve_running %d" % len(self._running))
            lines.append("dampr_tpu_serve_draining %d"
                         % (1 if self.draining else 0))
            lines.append("dampr_tpu_serve_uptime_seconds %.3f"
                         % (time.time() - self.started_at))
        return "\n".join(lines) + "\n"

    # -- HTTP ----------------------------------------------------------------
    def _make_handler(self):
        import http.server

        daemon = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, status, body, ctype="application/json"):
                try:
                    self.send_response(status)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def _send_json(self, status, doc):
                self._send(status, json.dumps(doc, default=str,
                                              sort_keys=True).encode())

            def do_GET(self):  # noqa: N802 - http.server API
                path = self.path.split("?")[0].rstrip("/") or "/"
                try:
                    if path == "/jobs":
                        self._send_json(200, daemon.jobs_doc())
                    elif path.startswith("/jobs/"):
                        job = daemon.jobs.get(path[len("/jobs/"):])
                        if job is None:
                            self._send_json(404, {"error": "no such job"})
                        else:
                            self._send_json(200, job.to_row())
                    elif path.startswith("/result/"):
                        self._result(path[len("/result/"):])
                    elif path == "/metrics":
                        self._send(200, daemon.metrics_text().encode(),
                                   METRICS_CONTENT_TYPE)
                    elif path == "/healthz":
                        self._send_json(200, daemon.health())
                    else:
                        self.send_error(404)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def _result(self, job_id):
                job = daemon.jobs.get(job_id)
                if job is not None and job.primary:
                    job = daemon.jobs.get(job.primary) or job
                if job is None:
                    self._send_json(404, {"error": "no such job"})
                    return
                if job.state == "done":
                    path = os.path.join(job.job_dir, "result.pkl")
                    try:
                        with open(path, "rb") as f:
                            body = f.read()
                    except OSError:
                        self._send_json(
                            410, {"error": "result evicted",
                                  "reason": "evicted"})
                        return
                    self._send(200, body, "application/octet-stream")
                elif job.state in _scheduler.TERMINAL:
                    self._send_json(410, {
                        "error": job.error or "job did not complete",
                        "state": job.state, "crashdump": job.crashdump})
                else:
                    self._send_json(409, {"error": "not finished",
                                          "state": job.state})

            def do_POST(self):  # noqa: N802 - http.server API
                path = self.path.split("?")[0].rstrip("/")
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    body = self.rfile.read(length) if length else b""
                    if path == "/submit":
                        try:
                            request = json.loads(body.decode("utf-8"))
                        except (ValueError, UnicodeDecodeError):
                            self._send_json(400, {
                                "error": "submission body is not JSON",
                                "reason": "wire"})
                            return
                        status, doc = daemon.submit(request)
                        self._send_json(status, doc)
                    elif path.startswith("/cancel/"):
                        status, doc = daemon.cancel(path[len("/cancel/"):])
                        self._send_json(status, doc)
                    elif path == "/drain":
                        threading.Thread(target=daemon.drain,
                                         daemon=True).start()
                        self._send_json(200, {"draining": True})
                    else:
                        self.send_error(404)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def log_message(self, fmt, *args):
                log.debug("serve http: " + fmt, *args)

        return Handler


def main(argv=None):
    """``dampr-tpu-serve``: run the daemon until SIGTERM/SIGINT, then
    drain gracefully (finish admitted jobs, reject new ones) and exit."""
    import argparse
    import signal

    p = argparse.ArgumentParser(
        prog="dampr-tpu-serve",
        description="multi-tenant pipeline service daemon (docs/serve.md)")
    p.add_argument("--port", type=int, default=None,
                   help="HTTP port (default: settings.serve_port = "
                        "DAMPR_TPU_SERVE_PORT)")
    p.add_argument("--host", default=None,
                   help="bind address (default: settings.serve_host, "
                        "loopback)")
    p.add_argument("--workers", type=int, default=None,
                   help="concurrent job slots (default: "
                        "settings.serve_workers)")
    p.add_argument("--state-dir", default=None,
                   help="job/state directory (default: "
                        "<scratch_root>/serve)")
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    daemon = ServeDaemon(port=args.port, host=args.host,
                         workers=args.workers, state_dir=args.state_dir)
    if daemon.start() is None:
        return 1
    stop_evt = threading.Event()

    def _on_signal(signum, frame):
        stop_evt.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    print("dampr-tpu-serve listening on http://{}:{} ({} worker "
          "slot(s))".format(daemon.host, daemon.port, daemon.workers),
          flush=True)
    while not stop_evt.is_set():
        stop_evt.wait(0.2)
    daemon.drain()
    daemon.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
